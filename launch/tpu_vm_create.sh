#!/usr/bin/env bash
# Provision a TPU pod slice (the reference's "#PBS -l select=..." block,
# torchrun_multigpu_pbs.sh:7-16, re-expressed as a queued resource).
# Copy, edit the variables, run. Requires: gcloud auth + quota.
set -euo pipefail

# ---- edit these -------------------------------------------------------------
TPU_NAME="${TPU_NAME:-tpu-hpc-dev}"
ZONE="${ZONE:-us-central2-b}"
ACCELERATOR_TYPE="${ACCELERATOR_TYPE:-v4-32}"   # v4-8 | v4-32 | v5litepod-16 ...
RUNTIME_VERSION="${RUNTIME_VERSION:-tpu-ubuntu2204-base}"
SPOT="${SPOT:-false}"                           # preemptible capacity
# -----------------------------------------------------------------------------

extra=()
[[ "${SPOT}" == "true" ]] && extra+=(--spot)

echo ">> creating ${ACCELERATOR_TYPE} slice '${TPU_NAME}' in ${ZONE}"
gcloud compute tpus queued-resources create "${TPU_NAME}-qr" \
    --node-id "${TPU_NAME}" \
    --zone "${ZONE}" \
    --accelerator-type "${ACCELERATOR_TYPE}" \
    --runtime-version "${RUNTIME_VERSION}" \
    "${extra[@]}"

echo ">> waiting for the slice to become ACTIVE"
gcloud compute tpus queued-resources describe "${TPU_NAME}-qr" \
    --zone "${ZONE}" --format='value(state.state)'

cat <<EOF
Next steps:
  ./tpu_vm_setup.sh     # install the framework on every worker
  ./tpu_vm_run.sh examples/06_hybrid_parallelism/train_llama_hybrid.py
EOF
