#!/usr/bin/env bash
# Explicit-env launch mode, as a runnable script: spawn N local
# processes with JAX_PROCESS_ID / JAX_NUM_PROCESSES /
# JAX_COORDINATOR_ADDRESS exported by hand -- the third of this
# repo's three launch modes (docs/guide/12_tpu_operations.md:36-57;
# parity role: any reference launcher that exports RANK/WORLD_SIZE/
# MASTER_ADDR itself, e.g. torchrun_multigpu_ddp.sh:59-76).
#
# On a real deployment each process runs on its own TPU host and N
# comes from the slice shape; locally this is the smoke-test mode
# (processes share the machine, each on a CPU-sim backend unless
# TPU_HPC_LOCAL_DEVICES says otherwise).
#
# Usage:
#   ./local_multiprocess.sh 2 examples/...py [args...]
#   NPROC via $1; coordinator on 127.0.0.1:${COORD_PORT:-12355}.
set -euo pipefail

NPROC="${1:?usage: local_multiprocess.sh <nproc> <script.py> [args...]}"
shift
SCRIPT="${1:?usage: local_multiprocess.sh <nproc> <script.py> [args...]}"
shift || true
COORD_PORT="${COORD_PORT:-12355}"
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
PY="${PYTHON:-$(command -v python3 || command -v python)}"

pids=()
for ((i = 0; i < NPROC; i++)); do
    JAX_PROCESS_ID="${i}" \
    JAX_NUM_PROCESSES="${NPROC}" \
    JAX_COORDINATOR_ADDRESS="127.0.0.1:${COORD_PORT}" \
    PYTHONPATH="${REPO_ROOT}${PYTHONPATH:+:$PYTHONPATH}" \
        "${PY}" "${SCRIPT}" "$@" &
    pids+=($!)
done
rc=0
for pid in "${pids[@]}"; do
    wait "${pid}" || rc=$?
done
exit "${rc}"
