#!/usr/bin/env bash
# Explicit-env launch mode, as a runnable script: spawn N local
# processes with JAX_PROCESS_ID / JAX_NUM_PROCESSES /
# JAX_COORDINATOR_ADDRESS exported by hand -- the third of this
# repo's three launch modes (docs/guide/12_tpu_operations.md:36-57;
# parity role: any reference launcher that exports RANK/WORLD_SIZE/
# MASTER_ADDR itself, e.g. torchrun_multigpu_ddp.sh:59-76).
#
# On a real deployment each process runs on its own TPU host and N
# comes from the slice shape; locally this is the smoke-test mode
# (processes share the machine, each on a CPU-sim backend unless
# TPU_HPC_LOCAL_DEVICES says otherwise).
#
# Usage:
#   ./local_multiprocess.sh 2 examples/...py [args...]
#   NPROC via $1; coordinator on 127.0.0.1:${COORD_PORT:-12355}.
set -euo pipefail

NPROC="${1:?usage: local_multiprocess.sh <nproc> <script.py> [args...]}"
shift
SCRIPT="${1:?usage: local_multiprocess.sh <nproc> <script.py> [args...]}"
shift || true
COORD_PORT="${COORD_PORT:-12355}"
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
PY="${PYTHON:-$(command -v python3 || command -v python)}"

pids=()
for ((i = 0; i < NPROC; i++)); do
    JAX_PROCESS_ID="${i}" \
    JAX_NUM_PROCESSES="${NPROC}" \
    JAX_COORDINATOR_ADDRESS="127.0.0.1:${COORD_PORT}" \
    PYTHONPATH="${REPO_ROOT}${PYTHONPATH:+:$PYTHONPATH}" \
        "${PY}" "${SCRIPT}" "$@" &
    pids+=($!)
done

# Fail fast (torchrun process-group semantics): the moment ANY rank
# exits nonzero, kill the survivors instead of letting them block on
# the JAX coordinator's connection timeout. `wait -n` reaps ranks in
# completion order; the final plain `wait` reaps the killed ones.
kill_survivors() {
    for pid in "${pids[@]}"; do
        kill "${pid}" 2>/dev/null || true
    done
}
# Forwarded preemption (the supervisor sends TERM here): pass the
# signal to the ranks, then propagate THEIR verdict -- if any rank
# took its snapshot and exited 75 (EXIT_RESUMABLE), this launcher
# reports 75 too, keeping the resumable contract intact through the
# process-group layer. A blanket exit 130 would relabel a clean
# preemption as a crash.
on_signal() {
    trap - INT TERM
    kill_survivors
    local final=0 code
    for pid in "${pids[@]}"; do
        code=0
        wait "${pid}" 2>/dev/null || code=$?
        # 127 = already reaped by the main loop's `wait -n` (its exit
        # code was folded in there); not a rank verdict, skip it.
        if ((code == 127)); then
            continue
        fi
        if ((code == 75)); then
            final=75
        elif ((code != 0 && final != 75)); then
            final="${code}"
        fi
    done
    exit "${final}"
}
trap on_signal INT TERM
rc=0
for ((n = 0; n < NPROC; n++)); do
    code=0
    wait -n || code=$?
    if ((code != 0)); then
        rc="${code}"
        echo "local_multiprocess: a rank exited rc=${rc}; killing survivors" >&2
        kill_survivors
        break
    fi
done
wait || true
exit "${rc}"
