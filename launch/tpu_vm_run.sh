#!/usr/bin/env bash
# Run a training script on every worker of a slice -- the mpiexec
# equivalent (reference: `mpiexec -n $TOTAL --ppn 4 --cpu-bind none
# python <example>.py`, run_fsdp.sh:63-70). One process per host, 4
# chips each; jax.distributed.initialize() inside the framework does
# the rendezvous the reference needed MASTER_ADDR/MPI broadcasts for
# (utils/distributed.py:103-121).
#
# Usage:
#   ./tpu_vm_run.sh examples/02_fully_sharded_fsdp/train_unet_fsdp.py --epochs 3
#   LOG_DIR=logs ./tpu_vm_run.sh bench.py
set -euo pipefail

TPU_NAME="${TPU_NAME:-tpu-hpc-dev}"
# Overridable for smoke tests (tests/test_launch.py substitutes a
# stub that captures the assembled remote command).
GCLOUD="${GCLOUD:-gcloud}"
ZONE="${ZONE:-us-central2-b}"
LOG_DIR="${LOG_DIR:-}"
# XLA/libtpu performance preset exported before the program starts --
# the role of the reference launchers' NCCL/FI/MPICH env block
# (torchrun_multigpu_ddp.sh:59-76). "default" = no flags; see
# tpu_hpc/runtime/tuning.py for profiles.
TUNING="${TUNING:-collective-overlap}"
# SUPERVISE=N runs the remote program under the in-framework run
# supervisor (tpu_hpc.resilience.supervisor) with N bounded
# restarts-with-resume per worker -- preempted/crashed runs relaunch
# themselves and auto-resume from the newest checkpoint, replacing
# the ad-hoc shell watchdog pattern. 0 (default) = run bare.
SUPERVISE="${SUPERVISE:-0}"

SCRIPT="${1:?usage: tpu_vm_run.sh <script.py> [args...]}"
shift || true
ARGS="$*"

# Fail fast on a typo'd profile HERE when possible -- best-effort: the
# operator's workstation may have only gcloud (no python/venv), and
# the remote side enforces regardless (set -e below).
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
PY="$(command -v python3 || command -v python || true)"
if [[ -n "${PY}" ]]; then
    PYTHONPATH="${REPO_ROOT}${PYTHONPATH:+:$PYTHONPATH}" \
        "${PY}" -m tpu_hpc.runtime.tuning --profile "${TUNING}" >/dev/null
else
    echo ">> note: no local python; profile '${TUNING}' validated remotely"
fi

# Per-worker output capture (parity: the per-rank redirect
# utils/redirect.py -- here stdout tee'd per worker by gcloud).
REDIRECT=""
if [[ -n "${LOG_DIR}" ]]; then
    REDIRECT="mkdir -p ~/tpu_hpc_logs && exec > >(tee ~/tpu_hpc_logs/\$(hostname).out) 2>&1;"
fi

# The runnable leg: bare, or wrapped in the bounded-restart
# supervisor (attempt logs + heartbeat land next to the worker logs).
RUNNER="python ${SCRIPT} ${ARGS}"
if [[ "${SUPERVISE}" != "0" ]]; then
    RUNNER="python -m tpu_hpc.resilience.supervisor \
--max-restarts ${SUPERVISE} --log-dir ~/tpu_hpc_logs/supervisor \
--heartbeat ~/tpu_hpc_logs/supervisor/heartbeat.json \
-- python ${SCRIPT} ${ARGS}"
fi

echo ">> launching ${SCRIPT} ${ARGS} on all workers of ${TPU_NAME}"
"${GCLOUD}" compute tpus tpu-vm ssh "${TPU_NAME}" --zone "${ZONE}" --worker=all \
    --command "
        set -e
        ${REDIRECT}
        source ~/tpu-hpc-venv/bin/activate
        cd ~/tpu_hpc_repo
        TUNING_VARS=\"\$(python -m tpu_hpc.runtime.tuning --profile ${TUNING} --shell)\"
        eval \"\${TUNING_VARS}\"
        ${RUNNER}
    "

if [[ -n "${LOG_DIR}" ]]; then
    mkdir -p "${LOG_DIR}"
    "${GCLOUD}" compute tpus tpu-vm scp --recurse \
        "${TPU_NAME}:~/tpu_hpc_logs/*" "${LOG_DIR}/" \
        --zone "${ZONE}" --worker=all || true
fi
