#!/usr/bin/env bash
# Smoke-run every parallelism strategy end-to-end -- the per-strategy
# PBS runners collapsed into one script (parity:
# run_tensor_parallel.sh:64-78 runs all TP examples,
# run_pipeline_parallel.sh:73-92 runs both schedules, etc.).
#
# Local / simulated: SIM=8 ./run_all_examples.sh
# On a slice:        via ./tpu_vm_run.sh launch/run_all_examples.sh
set -euo pipefail
cd "$(dirname "$0")/.."

SIM="${SIM:-}"
if [[ -n "${SIM}" ]]; then
    export TPU_HPC_SIM_DEVICES="${SIM}"
    echo ">> simulated ${SIM}-device CPU mesh"
fi
FAST="--epochs 1 --steps-per-epoch 3 --global-batch-size 8"

run() { echo; echo "=== $* ==="; python "$@"; }

run examples/01_data_parallel_dp/train_unet_dp.py       ${FAST}
run examples/01_data_parallel_dp/input_pipeline.py       ${FAST} --global-batch-size 16
run examples/02_fully_sharded_fsdp/train_unet_fsdp.py   ${FAST}
run examples/02_fully_sharded_fsdp/train_resnet_fsdp.py ${FAST} --global-batch-size 16 --strategy grad-op
run examples/02_fully_sharded_fsdp/train_resnet_fsdp.py ${FAST} --global-batch-size 16 --strategy hybrid
run examples/03_tensor_parallel_tp/mesh_basics.py
run examples/03_tensor_parallel_tp/train_llama_tp.py    ${FAST}
run examples/03_tensor_parallel_tp/train_vit_tp.py      ${FAST} --global-batch-size 4
run examples/04_pipeline_parallel_pp/train_pipeline.py  ${FAST} --global-batch-size 16 --schedule gpipe
run examples/04_pipeline_parallel_pp/train_pipeline.py  ${FAST} --global-batch-size 16 --schedule 1f1b
run examples/05_sequence_parallel/train_llama_sp.py     ${FAST} --global-batch-size 4 --attn ring --seq-len 128
run examples/05_sequence_parallel/train_llama_sp.py     ${FAST} --global-batch-size 4 --attn ulysses --seq-len 128
run examples/05_sequence_parallel/train_llama_sp.py     ${FAST} --global-batch-size 4 --attn zigzag --seq-len 128
run examples/06_hybrid_parallelism/train_llama_hybrid.py ${FAST}
run examples/07_domain_parallel/train_domain_parallel.py --demo
run examples/07_domain_parallel/train_domain_parallel.py ${FAST} --global-batch-size 4 --lat 32 --lon 64 --hidden 16

echo; echo "ALL EXAMPLES COMPLETED"
