#!/usr/bin/env bash
# Install the framework on every worker of a slice (the reference's
# "module load conda; conda activate" block, run_fsdp.sh:18-22 -- here a
# one-time rsync + pip install instead of a shared filesystem module).
set -euo pipefail

TPU_NAME="${TPU_NAME:-tpu-hpc-dev}"
ZONE="${ZONE:-us-central2-b}"
REPO_DIR="$(cd "$(dirname "$0")/.." && pwd)"

echo ">> copying the repo to all workers"
gcloud compute tpus tpu-vm scp --recurse "${REPO_DIR}" "${TPU_NAME}:~/tpu_hpc_repo" \
    --zone "${ZONE}" --worker=all

echo ">> installing on all workers"
gcloud compute tpus tpu-vm ssh "${TPU_NAME}" --zone "${ZONE}" --worker=all \
    --command "
        set -e
        python3 -m venv ~/tpu-hpc-venv 2>/dev/null || true
        source ~/tpu-hpc-venv/bin/activate
        pip -q install -U pip
        # constraints.txt pins the exact stack the recorded benchmarks
        # were measured on (BENCH_*/REPORT_* reproducibility) -- a pod
        # launched months later must not silently resolve newer wheels.
        pip -q install -c ~/tpu_hpc_repo/constraints.txt 'jax[tpu]' \
            -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
        pip -q install -c ~/tpu_hpc_repo/constraints.txt -e ~/tpu_hpc_repo
        python -c 'import tpu_hpc, jax; print(jax.devices())'
    "
echo ">> done; use ./tpu_vm_run.sh to launch training"
