"""Headline benchmark: prints ONE JSON line for the driver.

Flagship metric: Llama-2 training throughput in tokens/sec/chip with
MFU accounting -- the BASELINE.md north-star metric (Llama-2 hybrid
FSDPxTP at >=40% MFU; the reference itself publishes no measured
throughput, so ``vs_baseline`` reports achieved-MFU / 0.40 against
that stated target). Runs whatever chips are visible: 1 chip = pure
compute path (TP/FSDP add nothing on one device), N chips = hybrid
recipe via the same code path as examples/06.

The model is sized to the single-chip HBM (v5e ~16 GiB): a ~170M-param
Llama with head_dim 128 (MXU-native), seq 2048, bf16 compute, per-block
remat, and the Pallas flash-attention kernel.

Secondary workload: ``--workload unet`` keeps the reference's own
instrumented DP U-Net throughput (multinode_ddp_unet.py:348-397).
"""
import argparse
import json
import os
import sys

def peak_flops_per_chip(device) -> float:
    """Peak dense bf16 FLOP/s by device kind, from the single spec
    table in checks/roofline.py; conservative v5e-class default for
    unknown kinds so MFU never silently flatters."""
    from tpu_hpc.checks.roofline import peak_flops_for_device

    return peak_flops_for_device(device, default=197e12)


def resolve_batch_accum(batch, accum, microbatch: int):
    """One policy for every llama-family workload's batch/accum CLI
    defaults: with no --batch, run the family's measured-best
    microbatch accumulated 8x (batch = microbatch x accum, so an
    explicit --grad-accum-steps alone sweeps the accum lever at
    CONSTANT microbatch -- the lever-table protocol in
    docs/guide/xla_performance_notes.md, ceiling-budget subsection of
    the measured case study); with an explicit
    --batch and no --grad-accum-steps, run it unaccumulated (--batch 4
    reproduces the round-2 headline unchanged). ``0`` is passed
    through to the Trainer's own validation rather than silently
    replaced."""
    if batch is None:
        accum = 8 if accum is None else accum
        return microbatch * max(accum, 1), accum
    return batch, 1 if accum is None else accum


def flash_blocks_record(attn, block_q, block_k, block_q_bwd, block_k_bwd):
    """The effective flash-attention tiling as artifact fields, bwd
    defaults resolved -- so a JSON row always says which kernel shape
    produced it (the CLI and function defaults drifted once, ADVICE
    r5; now every artifact is self-describing)."""
    if attn != "flash":
        return {}
    return {
        "flash_blocks": {
            "q": block_q,
            "k": block_k,
            "q_bwd": block_q_bwd if block_q_bwd is not None else block_q,
            "k_bwd": block_k_bwd if block_k_bwd is not None else block_k,
        }
    }


def comm_mode_mesh(comm_mode: str, n_dev: int, n_slices: int = 1):
    """Mesh spec for a manual comm-mode run: ``(mesh_spec, batch_axes,
    dp_extent)``.

    Manual gradient-sync modes are DDP-family (replicated params), so
    the whole mesh is data parallelism. ``hierarchical`` needs the two
    fabric tiers as separate axes -- the shared construction policy
    (dcn resolution, validity, slice-aligned ``dcn_axes`` routing on
    real multi-slice hardware) lives in ``runtime.mesh.two_tier_spec``;
    the rejection here just names the CLI lever, because a record
    claiming "hierarchical" while silently measuring something else
    would poison the sweep."""
    from tpu_hpc.runtime import MeshSpec, two_tier_spec

    if comm_mode == "hierarchical":
        try:
            spec = two_tier_spec(n_dev, n_slices, inner_axis="data")
        except ValueError as e:
            raise ValueError(
                f"--comm-mode hierarchical: {e} -- use "
                "bucketed_overlap or flat on this topology"
            ) from None
        return spec, ("dcn", "data"), n_dev
    return MeshSpec(axes={"data": n_dev}), ("data",), n_dev


def bench_model_cfg(seq_len: int = 2048, remat: bool = False):
    """THE bench architecture: the ~170M-param Llama every llama-family
    workload runs, sized to single-chip v5e HBM. One factory so the
    DP headline, the SP rows, and the flagship pp row can never drift
    onto different architectures while claiming comparability."""
    from tpu_hpc.models import llama2

    return llama2.LlamaConfig(
        dim=1024, n_layers=8, n_heads=8, vocab_size=32000,
        multiple_of=256, max_seq_len=seq_len, remat=remat,
    )


def resolve_comm_auto(
    model_cfg,
    comm_table: "str | None" = None,
    bucket_cap_bytes: "int | None" = None,
):
    """Resolve --comm-mode auto for a llama-family workload: the
    collective planner's grad-sync decision (comm.planner) for the
    EXACT gradient payload of ``model_cfg`` on the visible topology.
    Runs before any array exists (eval_shape), because the resolved
    mode decides which mesh family the bench builds.
    ``bucket_cap_bytes`` defaults to the config's comm_bucket_mb --
    the same ladder cap the Trainer's own resolution would apply."""
    import math

    import jax
    import numpy as np

    from tpu_hpc.comm import planner as comm_planner
    from tpu_hpc.config import TrainingConfig
    from tpu_hpc.models import llama2
    from tpu_hpc.runtime.mesh import slice_groups, two_tier_spec

    if bucket_cap_bytes is None:
        bucket_cap_bytes = TrainingConfig().comm_bucket_mb * 2 ** 20

    abstract = jax.eval_shape(
        lambda k: llama2.init_llama(k, model_cfg),
        jax.random.key(0),
    )
    payload = sum(
        int(math.prod(l.shape)) * np.dtype(l.dtype).itemsize
        for l in jax.tree.leaves(abstract)
    )
    n_dev = jax.device_count()
    n_slices = len(slice_groups(jax.devices()))
    try:
        two_tier_spec(n_dev, n_slices)
        two_tier_ok = True
    except ValueError:
        two_tier_ok = False
    table = (
        comm_planner.load_table(comm_table) if comm_table else None
    )
    return comm_planner.Planner.for_devices(
        table=table
    ).plan_grad_sync(
        payload, two_tier=two_tier_ok,
        bucket_cap_bytes=bucket_cap_bytes,
    )


def bench_llama(
    steps: int = 20, remat: bool = False, batch_per_dp: int = 4,
    attn: str = "flash", block_q: int = 512, block_k: int = 1024,
    seq_len: int = 2048, grad_accum_steps: int = 1,
    moments_dtype: str = "float32",
    block_q_bwd: "int | None" = None, block_k_bwd: "int | None" = None,
    comm_mode: str = "flat",
    guard_mode: str = "off",
    comm_table: "str | None" = None,
) -> dict:
    """Best measured single-chip config (v5e) -- what the CLI runs by
    default (the *function* defaults are the unaccumulated round-2
    config; main() resolves the CLI policy via resolve_batch_accum):
    no remat (model fits HBM comfortably; remat costs ~14%), Pallas
    flash attention with 512/1024 q/k blocks (+8 MFU points over the
    XLA einsum path; the round-5 hardware confirmation moved block_k
    512 -> 1024: 124,171 tokens/s/chip 57.6% MFU vs 121,361 56.3% --
    HW_QUEUE_r05/bench_bk1024.log -- and the function default now
    matches the CLI so both entry points measure the same tiling;
    every record also carries the effective blocks),
    microbatch 4 (microbatch 8 loses ~6 points to memory pressure, 2
    ~3 to underfill), and grad-accum 8 over a batch of 32 --
    amortizing the fp32 AdamW state traffic (~6 ms/update) across 8x
    the tokens. Measured lever curve (v5e, 20 steps, microbatch 4):
    accum 1 50.2% MFU, accum 4 55.0%, accum 8 56.3%, accum 16 56.9%;
    bf16 moments add only +0.1-0.6 points once accum amortizes the
    same traffic, so the fp32-numerics default stays. At 32 DP chips
    the default is a 2M-token global step -- the production band for
    a 7B run (REPORT_70b_128chip_2M.md analogue). Round-2 additions
    retained: gather-forward/matmul-backward embedding (+1.9 points
    over forward one-hot), contiguous-pair RoPE (+1.2)."""
    import jax

    from tpu_hpc.config import TrainingConfig
    from tpu_hpc.models import datasets, llama2
    from tpu_hpc.parallel import fsdp, hybrid, tp
    from tpu_hpc.runtime import MeshSpec, build_mesh, init_distributed
    from tpu_hpc.train import Trainer

    init_distributed(verbose=False)
    n_dev = jax.device_count()
    model_cfg = bench_model_cfg(seq_len, remat)

    # comm_mode="auto": resolve the gradient-sync strategy through the
    # collective planner BEFORE the mesh is built -- the resolved mode
    # decides the mesh family (manual modes are pure-DP; hierarchical
    # needs the two-tier axes), so the resolution cannot live inside
    # the Trainer here. Payload is the exact gradient byte count from
    # an eval_shape (no arrays materialize); the record carries the
    # "auto" label, the resolved mode, and the full decision so a
    # sweep can attribute the row to the planner's reasoning.
    comm_mode_requested = comm_mode
    comm_decision = None
    if comm_mode == "auto":
        comm_decision = resolve_comm_auto(model_cfg, comm_table)
        comm_mode = comm_decision.mode
        print(
            f"llama bench | comm_mode auto -> {comm_mode} "
            f"[{comm_decision.source}] "
            f"pred {comm_decision.predicted_cost_s * 1e3:.3f} ms/sync",
            file=sys.stderr,
        )

    def make_attn_fn(mesh, tp_size):
        if attn == "xla":
            return None  # the model's einsum path (XLA-fused)
        # Pallas flash (GQA in-kernel, no repeated KV); multi-chip
        # runs it under shard_map with heads on the TP axis. Manual
        # comm modes run the WHOLE forward per-shard inside one
        # shard_map (comm.overlap), so they take the bare batch-local
        # closure (wrap=False): nesting a second shard_map over the
        # same mesh would fail to trace (the same batch-local idiom
        # bench_llama_pp's stages use), and the shared factory keeps
        # comm-mode rows on the identical kernel config as flat rows.
        return tp.make_tp_flash_attn_fn(
            mesh, "data", "model" if tp_size > 1 else None,
            block_q=block_q, block_k=block_k,
            block_q_bwd=block_q_bwd, block_k_bwd=block_k_bwd,
            wrap=(comm_mode == "flat"),
        )

    from jax.sharding import PartitionSpec as P

    batch_pspec = P("data")
    if comm_mode != "flat":
        # Manual gradient-sync modes (tpu_hpc.comm.overlap) are
        # DDP-family: replicated params, batch over the whole data
        # axis (both tiers of it in hierarchical mode). FSDP/TP
        # layouts keep GSPMD's fused collectives
        # (fsdp.validate_grad_sync_mode rejects them loudly), so the
        # comm-mode rows measure pure-DP sync strategy, attributable
        # via the record's comm_mode field.
        from tpu_hpc.runtime.mesh import slice_groups

        mesh_spec, batch_axes, dp_size = comm_mode_mesh(
            comm_mode, n_dev, len(slice_groups(jax.devices()))
        )
        batch_pspec = P(batch_axes)
        axes = mesh_spec.resolved_sizes(n_dev)
    else:
        axes = tp.auto_mesh_axes(
            n_dev, model_cfg.n_heads, model_cfg.kv_heads, cap=4
        )
        dp_size = axes["data"]
        mesh_spec = MeshSpec(axes=axes)
    tp_size = axes.get("model", 1)
    mesh = build_mesh(mesh_spec)

    params = llama2.init_llama(jax.random.key(0), model_cfg)
    if tp_size > 1:
        specs = hybrid.hybrid_pspecs(
            params, tp.llama_rules(), data_size=dp_size
        )
        constrain = tp.sp_constrain(mesh, dp_axis="data", sp_axis="model")
    elif dp_size > 1 and comm_mode == "flat":
        specs = fsdp.param_pspecs(params, axis="data", axis_size=dp_size)
        constrain = lambda x: x  # noqa: E731
    else:
        specs = None
        constrain = lambda x: x  # noqa: E731

    cfg = TrainingConfig(
        epochs=2,  # epoch 0 absorbs compilation; epoch 1 is measured
        steps_per_epoch=steps,
        global_batch_size=batch_per_dp * dp_size,
        learning_rate=3e-4,
        weight_decay=0.1,
        grad_accum_steps=grad_accum_steps,
        adam_moments_dtype=moments_dtype,
        # The REQUESTED mode: under "auto" the trainer consumes the
        # pre-resolved decision below (bench had to resolve it first
        # -- the mode picks the mesh family), so the planner's exact
        # bucket choice is honored, not re-derived.
        comm_mode=comm_mode_requested,
        guard_mode=guard_mode,
    )
    ds = datasets.TokenStream(
        vocab_size=model_cfg.vocab_size, seq_len=model_cfg.max_seq_len
    )
    trainer = Trainer(
        cfg, mesh,
        llama2.make_forward(
            model_cfg, constrain, make_attn_fn(mesh, tp_size)
        ),
        params, param_pspecs=specs, batch_pspec=batch_pspec,
        comm_plan=comm_decision,
    )
    result = trainer.fit(ds)
    summary = result["epochs"][-1]
    tokens_per_s = summary["items_per_s"] * model_cfg.max_seq_len
    flops_per_token = model_cfg.flops_per_token(model_cfg.max_seq_len)
    peak = peak_flops_per_chip(jax.devices()[0])
    mfu = tokens_per_s * flops_per_token / (peak * n_dev)
    print(
        f"llama bench | mesh {axes} | {tokens_per_s:.0f} tokens/s | "
        f"{tokens_per_s / n_dev:.0f} tokens/s/chip | MFU {mfu:.1%} "
        f"(peak {peak / 1e12:.0f} TF/chip, "
        f"{flops_per_token / 1e6:.0f} MFLOP/token)",
        file=sys.stderr,
    )
    return {
        "metric": "llama2_train_tokens_per_s_per_chip",
        "value": round(tokens_per_s / n_dev, 1),
        "unit": "tokens/s/chip",
        # Reference publishes no measured numbers (BASELINE.md);
        # compare against its stated >=40%-MFU target instead.
        "vs_baseline": round(mfu / 0.40, 3),
        # Effective attention config: rows from the CLI and from
        # programmatic callers must be distinguishable (ADVICE r5).
        "attn": attn,
        # Gradient-sync strategy: BENCH JSONLs must be able to
        # attribute a step-time delta to the comm layer, not guess it.
        # Under "auto" the row carries the label AND the resolution:
        # a sweep must be able to tell "the planner picked flat" from
        # "the operator picked flat".
        "comm_mode": comm_mode_requested,
        **(
            {
                "comm_mode_resolved": comm_mode,
                "comm_plan": comm_decision.summary(),
            }
            if comm_decision is not None else {}
        ),
        # Numeric-health guard: the health vector rides the jitted
        # step, so a guarded row quantifies exactly what the guard
        # costs (the zero-recompile claim's measured counterpart).
        "guard_mode": guard_mode,
        **flash_blocks_record(
            attn, block_q, block_k, block_q_bwd, block_k_bwd
        ),
    }


def bench_llama_sp(
    steps: int = 20, batch_per_dp: int = 4, sp_mode: str = "zigzag",
    grad_accum_steps: int = 1, moments_dtype: str = "float32",
) -> dict:
    """Sequence-parallel Llama throughput: the ring / zigzag / Ulysses
    code paths under the real training loop (VERDICT r1: these paths
    had no recorded BENCH artifact). Context axis = all visible chips
    (1 chip: degenerate ring, still the kernel-under-shard_map path
    that otherwise only runs in tests). Takes the same grad-accum
    amortization as the headline (the AdamW-traffic lever is
    layout-independent)."""
    import jax

    from tpu_hpc.config import TrainingConfig
    from tpu_hpc.models import datasets, llama2
    from tpu_hpc.parallel import ring_attention as ra
    from tpu_hpc.parallel import sp_ulysses
    from tpu_hpc.runtime import MeshSpec, build_mesh, init_distributed
    from tpu_hpc.train import Trainer

    init_distributed(verbose=False)
    n_dev = jax.device_count()
    model_cfg = bench_model_cfg()
    mesh = build_mesh(MeshSpec(axes={"data": 1, "context": n_dev}))
    zigzag_ring = None
    if sp_mode == "zigzag":
        # Production layout: loader emits zigzag order once per batch,
        # the balanced ring runs with zero per-layer permutes, RoPE
        # reads the slots' global positions.
        zigzag_ring = n_dev
        attn_fn = ra.make_zigzag_ring_attn_fn(
            mesh, "data", "context", data_layout="zigzag"
        )
    elif sp_mode == "ring":
        attn_fn = ra.make_ring_attn_fn(mesh, "data", "context")
    elif sp_mode == "ulysses":
        attn_fn = sp_ulysses.make_ulysses_attn_fn(
            mesh, "data", "context"
        )
    else:
        raise ValueError(
            f"unknown sp_mode {sp_mode!r} (ring|zigzag|ulysses)"
        )
    constrain = ra.cp_constrain(mesh, "data", "context")

    cfg = TrainingConfig(
        epochs=2,  # epoch 0 absorbs compilation; epoch 1 is measured
        steps_per_epoch=steps,
        global_batch_size=batch_per_dp,
        learning_rate=3e-4,
        weight_decay=0.1,
        grad_accum_steps=grad_accum_steps,
        adam_moments_dtype=moments_dtype,
    )
    ds = datasets.TokenStream(
        vocab_size=model_cfg.vocab_size, seq_len=model_cfg.max_seq_len,
        zigzag_ring=zigzag_ring,
    )
    params = llama2.init_llama(jax.random.key(0), model_cfg)
    trainer = Trainer(
        cfg, mesh,
        llama2.make_forward(
            model_cfg, constrain, attn_fn, ds.positions()
        ),
        params,
    )
    result = trainer.fit(ds)
    summary = result["epochs"][-1]
    tokens_per_s = summary["items_per_s"] * model_cfg.max_seq_len
    flops_per_token = model_cfg.flops_per_token(model_cfg.max_seq_len)
    peak = peak_flops_per_chip(jax.devices()[0])
    mfu = tokens_per_s * flops_per_token / (peak * n_dev)
    print(
        f"llama-sp[{sp_mode}] | context={n_dev} | "
        f"{tokens_per_s:.0f} tokens/s | MFU {mfu:.1%}",
        file=sys.stderr,
    )
    return {
        "metric": f"llama2_sp_{sp_mode}_tokens_per_s_per_chip",
        "value": round(tokens_per_s / n_dev, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 3),
    }


def bench_llama_long(
    steps: int = 20, seq_len: int = 8192, batch: int = 1,
    remat: bool = False, grad_accum_steps: int = 1,
    moments_dtype: str = "float32",
    block_q: int = 512, block_k: int = 1024,
    block_q_bwd: "int | None" = None, block_k_bwd: "int | None" = None,
    comm_mode: str = "flat",
    guard_mode: str = "off",
    comm_table: "str | None" = None,
) -> dict:
    """Long-context Llama: seq 8192 (4x the headline bench) -- the
    long-sequence regime the SP family exists for. Same harness as
    bench_llama (so multi-chip sharding, flash/xla selection and
    block tuning stay in one place), at microbatch 1/chip (the CLI
    default resolves to batch 8 x accum 8; the function defaults are
    the unaccumulated batch-1 config). The bench model
    still fits HBM unrematerialized at batch 1, and remat costs ~24%
    here (45.3% vs 34.4% MFU measured on v5e), so remat stays opt-in
    (--remat); at 7B scale the fit analysis (checks/fit.py) shows
    where it becomes mandatory."""
    rec = bench_llama(
        steps, remat, batch, "flash", block_q, block_k,
        seq_len=seq_len, grad_accum_steps=grad_accum_steps,
        moments_dtype=moments_dtype,
        block_q_bwd=block_q_bwd, block_k_bwd=block_k_bwd,
        comm_mode=comm_mode, guard_mode=guard_mode,
        comm_table=comm_table,
    )
    rec["metric"] = f"llama2_seq{seq_len}_tokens_per_s_per_chip"
    return rec


def bench_llama_pp(
    steps: int = 20, schedule: str = "1f1b", microbatches: int = 8,
    microbatch_size: int = 4, attn: str = "flash",
    block_q: int = 512, block_k: int = 1024,
    block_q_bwd: "int | None" = None, block_k_bwd: "int | None" = None,
    grad_accum_steps: int = 1, backward: str = "remat",
    remat_stage: "bool | None" = None,
    model: str = "stack",
) -> dict:
    """Pipeline-parallel throughput (VERDICT r1: the PP path had no
    BENCH artifact). Stages fill the visible chips (1 chip: one stage
    through the same pipelined program -- degenerate ring, real code
    path); reports tokens/s, MFU, plus the analytic bubble fraction.

    ``model="llama"`` pipelines the FLAGSHIP model itself
    (models/llama_pp.py stage-splits the same 8-layer dim-1024 Llama
    the DP headline trains -- bench_model_cfg, one factory -- so the
    row is directly comparable to the 121k tok/s/chip headline). All
    four schedules: the interleaved ones stack the stages in the
    Megatron round-robin layout via split_params_interleaved (v=2
    when the depth divides).

    Round-4 parity with the headline bench (VERDICT r3 weak #2: PP
    ran at 42% of the DP path): bf16 compute (PipeConfig's fp32
    default forfeited the MXU bf16 rate), microbatch SIZE 4 (was 1 --
    batch-1 matmuls underfill), the Pallas flash kernel in the stage
    (called batch-locally inside pp's shard_map), and grad-accum.
    What remains vs DP is the schedule itself: the 1f1b schedules'
    custom-vjp backward costs extra stage forwards (remat 5/3 of
    ideal FLOPs, --pp-backward stash 4/3), and
    bubbles at S>1 -- both reported, neither counted into MFU's
    denominator."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpu_hpc.config import TrainingConfig
    from tpu_hpc.kernels.attention import blockwise_attention
    from tpu_hpc.models import datasets, losses
    from tpu_hpc.models import pipeline_transformer as ptx
    from tpu_hpc.parallel import pp
    from tpu_hpc.runtime import MeshSpec, build_mesh, init_distributed
    from tpu_hpc.train import Trainer

    if grad_accum_steps > 1 and microbatch_size % grad_accum_steps:
        # Each accum microstep carries batch/accum rows, which must
        # still split into `microbatches` pipeline microbatches --
        # otherwise pp.microbatch raises deep inside tracing.
        raise ValueError(
            f"--grad-accum-steps {grad_accum_steps} must divide the "
            f"pipeline microbatch size {microbatch_size} (PP already "
            "amortizes the optimizer over its microbatches; accum on "
            "top only makes sense when it divides evenly)"
        )
    if model not in ("stack", "llama"):
        raise ValueError(f"unknown pp model {model!r} (stack|llama)")
    init_distributed(verbose=False)
    n_dev = jax.device_count()
    n_stages = n_dev
    mesh = build_mesh(MeshSpec(axes={"pipe": n_stages}))
    # v=2 only while the total depth (8 layers) still divides over
    # v*S stages -- otherwise the interleaved model would have MORE
    # layers than the gpipe/1f1b baselines and tokens/s would compare
    # apples to oranges.
    v = (
        2
        if schedule in ("interleaved", "interleaved-1f1b")
        and 1 < n_stages and 8 % (2 * n_stages) == 0
        else 1
    )
    model_cfg = ptx.PipeConfig(
        vocab_size=32000, dim=1024, n_heads=8, n_stages=n_stages * v,
        layers_per_stage=max(8 // (n_stages * v), 1), max_seq_len=2048,
        dtype=jnp.bfloat16,
    )
    attn_fn = None
    if attn == "flash":
        # Batch-local call (each stage owns its microbatch inside pp's
        # shard_map) -- no nested shard_map; auto falls back to the
        # XLA path on CPU-simulated meshes.
        def attn_fn(q, k, v_):
            out, _ = blockwise_attention(
                q, k, v_, causal=True,
                block_q=block_q, block_k=block_k,
                block_q_bwd=block_q_bwd, block_k_bwd=block_k_bwd,
            )
            return out
    # No coercion: --pp-backward stash with a non-1f1b schedule gets
    # pp.pipelined's clear ValueError instead of silently benchmarking
    # a different backward than the artifact claims.
    if remat_stage is None:
        # The autodiff schedules' backward saves EVERY tick
        # intermediate without this -- measured 51.9G (3.3x HBM) at
        # the re-levered mb 8x4 bf16 config on v5e. remat_stage puts
        # gpipe/interleaved at the same save-stage-inputs memory point
        # the 1f1b custom backward has by construction, which is the
        # comparable configuration.
        remat_stage = schedule in ("gpipe", "interleaved")
    if model == "llama":
        # The flagship itself, stage-split: SAME architecture as the
        # DP headline bench (bench_model_cfg), so this row is
        # directly comparable to it.
        from tpu_hpc.models import llama2, llama_pp

        lcfg = bench_model_cfg()
        if lcfg.n_layers % (n_stages * v):
            raise ValueError(
                f"llama pp needs n_layers {lcfg.n_layers} divisible "
                f"by stages {n_stages} x chunks {v}"
            )
        full = llama2.init_llama(jax.random.key(0), lcfg)
        params = (
            llama_pp.split_params_interleaved(full, lcfg, n_stages, v)
            if v > 1 else
            llama_pp.split_params(full, lcfg, n_stages)
        )
        specs = llama_pp.pp_pspecs(params)
        forward = llama_pp.make_forward(
            lcfg, mesh, n_microbatches=microbatches,
            schedule=schedule, backward=backward, batch_spec=P(),
            attn_fn=attn_fn, remat_stage=remat_stage, n_chunks=v,
        )
        model_cfg = lcfg  # flops_per_token/max_seq_len/vocab source
    else:
        params = ptx.init_pipeline_transformer(
            jax.random.key(0), model_cfg
        )
        if v > 1:
            params = dict(
                params,
                stages=pp.interleave_stacked(params["stages"], n_stages),
            )
        specs = {
            "embed": jax.tree.map(lambda _: P(), params["embed"]),
            "stages": pp.stage_pspecs(params["stages"], axis="pipe"),
            "head": jax.tree.map(lambda _: P(), params["head"]),
        }
        pipe = pp.pipelined(
            ptx.make_stage_fn(model_cfg, attn_fn), mesh, axis="pipe",
            schedule=schedule, batch_spec=P(), n_chunks=v,
            backward=backward, remat_stage=remat_stage,
        )

        def forward(params, model_state, batch, step_rng):
            inputs, targets = batch
            xs = ptx.embed(
                params, pp.microbatch(inputs, microbatches), model_cfg
            )
            ys = pipe(params["stages"], xs)
            logits = ptx.head(params, ys, model_cfg)
            loss = losses.cross_entropy(
                logits, pp.microbatch(targets, microbatches)
            )
            return loss, model_state, {}

    cfg = TrainingConfig(
        epochs=2, steps_per_epoch=steps,
        global_batch_size=microbatches * microbatch_size,
        learning_rate=3e-4, weight_decay=0.1,
        grad_accum_steps=grad_accum_steps,
    )
    ds = datasets.TokenStream(
        vocab_size=model_cfg.vocab_size, seq_len=model_cfg.max_seq_len
    )
    trainer = Trainer(
        cfg, mesh, forward, params, param_pspecs=specs, batch_pspec=P(),
    )
    result = trainer.fit(ds)
    summary = result["epochs"][-1]
    tokens_per_s = summary["items_per_s"] * model_cfg.max_seq_len
    bubble = pp.bubble_fraction(n_stages, microbatches, n_chunks=v)
    flops_per_token = model_cfg.flops_per_token()
    peak = peak_flops_per_chip(jax.devices()[0])
    mfu = tokens_per_s * flops_per_token / (peak * n_dev)
    tag = (
        f"-{backward}"
        if schedule in ("1f1b", "interleaved-1f1b")
        and backward != "remat" else ""
    ) + ("-llama" if model == "llama" else "")
    print(
        f"llama-pp[{schedule}{tag}] | stages={n_stages} "
        f"mb={microbatches}x{microbatch_size} bubble {bubble:.1%} | "
        f"{tokens_per_s:.0f} tokens/s | MFU {mfu:.1%}",
        file=sys.stderr,
    )
    return {
        "metric": f"pp_{schedule}{tag}_tokens_per_s_per_chip",
        "value": round(tokens_per_s / n_dev, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 3),
        # Self-describing: the interleaved schedules degenerate to
        # v=1 when the 8-layer bench model cannot split into 2*S
        # chunks (e.g. 8 stages) -- a record without this field would
        # present a duplicate of the 1f1b row as interleaved evidence.
        "n_chunks": v,
        "bubble_fraction": round(bubble, 4),
        "attn": attn,
        **flash_blocks_record(
            attn, block_q, block_k, block_q_bwd, block_k_bwd
        ),
    }


def bench_llama_pp_mpmd(
    steps: int, microbatches: int, microbatch_size: int = 4,
    attn: str = "flash",
    block_q: int = 512, block_k: int = 1024,
    block_q_bwd: "int | None" = None, block_k_bwd: "int | None" = None,
    model: str = "stack",
) -> dict:
    """The MPMD pipeline runtime row (``--pp-runtime mpmd``):
    per-stage AOT programs dispatched per stage worker
    (tpu_hpc.parallel.mpmd) instead of one SPMD shard_map tick loop.
    One stage per visible device (disjoint fault domains); reports
    tokens/s plus the runtime's MEASURED bubble fraction and -- when
    ``TPU_HPC_FAULTS`` arms a stage fault -- the recovery MTTR and
    per-stage restart/rollback counts, so the banked ``pp_mpmd_*``
    family carries the robustness evidence next to the throughput
    headline. Zero steady-state recompiles is part of the record
    (``recompiles``), pinned like every serving row's."""
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_hpc.kernels.attention import blockwise_attention
    from tpu_hpc.models import datasets
    from tpu_hpc.models import pipeline_transformer as ptx
    from tpu_hpc.parallel import mpmd
    from tpu_hpc.runtime import init_distributed

    if model not in ("stack", "llama"):
        raise ValueError(f"unknown pp model {model!r} (stack|llama)")
    init_distributed(verbose=False)
    n_dev = jax.device_count()
    n_stages = n_dev
    attn_fn = None
    if attn == "flash":
        def attn_fn(q, k, v_):
            out, _ = blockwise_attention(
                q, k, v_, causal=True,
                block_q=block_q, block_k=block_k,
                block_q_bwd=block_q_bwd, block_k_bwd=block_k_bwd,
            )
            return out
    if model == "llama":
        from tpu_hpc.models import llama2, llama_pp

        lcfg = bench_model_cfg()
        if lcfg.n_layers % n_stages:
            raise ValueError(
                f"llama mpmd needs n_layers {lcfg.n_layers} "
                f"divisible by {n_stages} stages"
            )
        full = llama2.init_llama(jax.random.key(0), lcfg)
        split = llama_pp.split_params(full, lcfg, n_stages)
        bundle = llama_pp.mpmd_bundle(split, lcfg, attn_fn=attn_fn)
        model_cfg = lcfg
    else:
        model_cfg = ptx.PipeConfig(
            vocab_size=32000, dim=1024, n_heads=8,
            n_stages=n_stages,
            layers_per_stage=max(8 // n_stages, 1),
            max_seq_len=2048, dtype=jnp.bfloat16,
        )
        params = ptx.init_pipeline_transformer(
            jax.random.key(0), model_cfg
        )
        bundle = ptx.mpmd_bundle(params, model_cfg, attn_fn=attn_fn)
    cfg = mpmd.MpmdConfig(
        n_microbatches=microbatches, learning_rate=3e-4,
    )
    ds = datasets.TokenStream(
        vocab_size=model_cfg.vocab_size, seq_len=model_cfg.max_seq_len
    )
    batch = microbatches * microbatch_size
    batches = [
        tuple(np.asarray(a) for a in ds.batch_at(i, batch))
        for i in range(steps + 1)
    ]
    pipe = mpmd.MpmdPipeline(bundle, cfg).build(batches[0][0])
    warm_counts = list(pipe.compile_counts)
    pipe.run_step(0, *batches[0])  # warm dispatch outside the timing
    t0 = _time.perf_counter()
    for step, (tokens, targets) in enumerate(batches[1:], start=1):
        pipe.run_step(step, tokens, targets)
    wall = _time.perf_counter() - t0
    res = {
        "bubble_fraction": (
            float(np.mean(pipe.bubble_fractions))
            if pipe.bubble_fractions else 0.0
        ),
        "recovery_mttr_s": (
            float(np.mean([r["mttr_s"] for r in pipe.recoveries]))
            if pipe.recoveries else 0.0
        ),
    }
    recompiles = sum(pipe.compile_counts) - sum(warm_counts)
    tokens_per_s = steps * batch * model_cfg.max_seq_len / wall
    flops_per_token = model_cfg.flops_per_token()
    peak = peak_flops_per_chip(jax.devices()[0])
    mfu = tokens_per_s * flops_per_token / (peak * n_dev)
    tag = "-llama" if model == "llama" else ""
    # A chaos-armed run banks under its OWN pp_mpmd*-chaos family:
    # its recovery MTTR / redispatch counts are that family's judged
    # baseline (robustness drift at the same chaos schedule fails
    # --bank), and they must never pollute the clean family's
    # mttr==0 high-water mark.
    armed = (
        pipe.fault_plan.stage_fault_keys()
        if pipe.fault_plan is not None else []
    )
    if armed:
        tag += "-chaos"
    print(
        f"llama-pp[mpmd{tag}] | stages={n_stages} "
        f"mb={microbatches}x{microbatch_size} "
        f"bubble {res['bubble_fraction']:.1%} | "
        f"{tokens_per_s:.0f} tokens/s | MFU {mfu:.1%} | "
        f"restarts {dict(pipe.supervisor.restarts)} "
        f"rollbacks {dict(pipe.supervisor.rollbacks)} "
        f"mttr {res['recovery_mttr_s']:.2f}s",
        file=sys.stderr,
    )
    return {
        "metric": f"pp_mpmd{tag}_tokens_per_s_per_chip",
        "value": round(tokens_per_s / n_dev, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 3),
        "pp_runtime": "mpmd",
        **({"faults": ",".join(armed)} if armed else {}),
        "bubble_fraction": round(res["bubble_fraction"], 4),
        "recovery_mttr_s": round(res["recovery_mttr_s"], 3),
        "stage_restarts": sum(pipe.supervisor.restarts.values()),
        "stage_rollbacks": sum(pipe.supervisor.rollbacks.values()),
        "redispatched": pipe.redispatched,
        "recompiles": recompiles,
        "wire_mb": round(pipe.wire_bytes / 2**20, 2),
        "attn": attn,
        **flash_blocks_record(
            attn, block_q, block_k, block_q_bwd, block_k_bwd
        ),
    }


def bench_elastic(
    steps: int, shrink_at: int = 2, grow_at: int = 4,
) -> dict:
    """The preemption-storm acceptance row (tpu_hpc.elastic): one
    training run driven through shrink -> train -> grow -> train by
    the topology coordinator, ZERO process restarts, judged against a
    fixed-topology reference on the final layout. The banked
    ``elastic_morph_*`` family carries the transition costs -- mean
    stall seconds per morph as the headline, morph count and wire
    bytes as side keys (all lower-is-better) -- so a coordinator
    change that starts moving more bytes or stalling longer at the
    same chaos schedule fails ``--bank``. ``loss_parity`` records
    whether the morphing run's loss stream stayed bit-identical to
    the fixed run (the data-extent-preserving layout policy's whole
    point)."""
    import tempfile
    import time as _time

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpu_hpc.config import TrainingConfig
    from tpu_hpc.elastic import TopologyCoordinator, choose_layout
    from tpu_hpc.runtime import MeshSpec, build_mesh, init_distributed
    from tpu_hpc.train.trainer import Trainer

    init_distributed(verbose=False)
    n_dev = jax.device_count()
    # The storm must actually change the topology: shrink keeps half
    # the pool, so the data axis is pinned to the extent both halves
    # can carry.
    extent = max(n_dev // 2, 1)
    batch = extent * 4

    def init_params():
        k1, k2 = jax.random.split(jax.random.key(7))
        return {
            "w1": jax.random.normal(k1, (64, 128), jnp.float32) * 0.1,
            "w2": jax.random.normal(k2, (128, 16), jnp.float32) * 0.1,
        }

    def forward(params, model_state, b, rng):
        pred = jnp.tanh(b["x"] @ params["w1"]) @ params["w2"]
        return jnp.mean((pred - b["y"]) ** 2), model_state, {}

    class _DS:
        def batch_at(self, step, gbs):
            k = jax.random.key(1000 + int(step))
            kx, ky = jax.random.split(k)
            return {
                "x": jax.random.normal(kx, (gbs, 64), jnp.float32),
                "y": jax.random.normal(ky, (gbs, 16), jnp.float32),
            }

    def cfg_for(path):
        return TrainingConfig(
            epochs=steps, steps_per_epoch=1, global_batch_size=batch,
            learning_rate=1e-2, weight_decay=0.01, metrics_path=path,
        )

    def factory_for(cfg):
        def factory(mesh):
            params = init_params()
            return Trainer(
                cfg, mesh, forward, params,
                param_pspecs=jax.tree.map(lambda _: P(), params),
                batch_pspec=P("data"),
            )
        return factory

    def losses_from(path):
        out = []
        with open(path) as f:
            for line in f:
                r = json.loads(line)
                if r.get("event") == "epoch":
                    out.append((r["step"], r["loss"]))
        return out

    tmp = tempfile.mkdtemp(prefix="bench_elastic_")
    # Fixed-topology reference on the FINAL layout (the full pool,
    # same layout policy) -- built before the chaos schedule is
    # armed, or the un-managed Trainer would rightly refuse it.
    fixed_path = os.path.join(tmp, "fixed.jsonl")
    decision = choose_layout(
        jax.devices(), global_batch=batch, current_data_extent=extent
    )
    fixed_mesh = build_mesh(
        MeshSpec(axes=dict(decision.axes)), devices=jax.devices()
    )
    fixed_tr = factory_for(cfg_for(fixed_path))(fixed_mesh)
    fixed_tr.fit(_DS())

    morph_path = os.path.join(tmp, "morph.jsonl")
    prev = os.environ.get("TPU_HPC_FAULTS")
    os.environ["TPU_HPC_FAULTS"] = (
        f"slice_down_at_step={shrink_at},slice_up_at_step={grow_at}"
    )
    t0 = _time.perf_counter()
    try:
        coord = TopologyCoordinator(
            factory_for(cfg_for(morph_path)),
            global_batch=batch, data_extent=extent,
        )
        summary = coord.run(_DS())
    finally:
        if prev is None:
            os.environ.pop("TPU_HPC_FAULTS", None)
        else:
            os.environ["TPU_HPC_FAULTS"] = prev
    wall = _time.perf_counter() - t0
    parity = losses_from(fixed_path) == losses_from(morph_path)
    morphs = summary["morph_count"]
    print(
        f"elastic | {n_dev} devices, shrink@{shrink_at} "
        f"grow@{grow_at} | {morphs} morphs, "
        f"{summary['wire_bytes']} wire bytes, "
        f"{summary['stall_s']:.3f}s stall | restarts "
        f"{summary['restarts']} | loss parity {parity} | "
        f"{wall:.1f}s wall",
        file=sys.stderr,
    )
    return {
        "metric": "elastic_morph_stall_s",
        "value": round(summary["stall_s"] / max(morphs, 1), 6),
        "unit": "s",
        "vs_baseline": None,
        "faults": (
            f"slice_down_at_step={shrink_at},"
            f"slice_up_at_step={grow_at}"
        ),
        "morphs": morphs,
        "morph_wire_bytes": summary["wire_bytes"],
        "stall_s": summary["stall_s"],
        "restarts": summary["restarts"],
        "segments": len(summary["segments"]),
        "loss_parity": parity,
        "n_devices": n_dev,
    }


def _kv_metric_tag(summary: dict) -> str:
    """Metric-family suffix for the paged read path
    (tpu_hpc.kernels.paged_attention): '' for the default gather/fp
    pool -- pre-existing banked histories continue untouched --
    '_pallas', '_q8', or '_pallas_q8' otherwise, so each read-path
    trajectory banks against its own high-water marks."""
    tag = ""
    if summary.get("kv_kernel", "gather") == "pallas":
        tag += "_pallas"
    if summary.get("kv_quant", "none") == "int8":
        tag += "_q8"
    return tag


def serve_record(summary: dict, disagg: bool = False) -> dict:
    """Serving summary -> the training-bench record schema
    (metric/value/unit/vs_baseline), with the serving-native latency
    quantiles riding along. vs_baseline = serving MFU (forward-only
    2N accounting, train.metrics.mfu mode="inference") against the
    same 40% north-star target the training rows use; None on
    backends with no published peak (CPU sim). The KV-cache layout
    (slab|paged, block size, prefix-hit rate) is part of the record's
    identity -- a paged row must never be diffed against a slab one
    unlabeled."""
    mfu = summary.get("serve_mfu")
    rec_serve = {
        "requests": summary["requests"],
        "slots": summary["slots"],
        "prefill_buckets": summary["prefill_buckets"],
        "recompiles": summary["recompiles"],
        "kv_layout": summary.get("kv_layout", "slab"),
    }
    kv_tag = ""
    if summary.get("kv_layout") == "paged":
        rec_serve.update(
            kv_block_size=summary.get("kv_block_size"),
            kv_blocks=summary.get("kv_blocks"),
            kv_kernel=summary.get("kv_kernel", "gather"),
            kv_quant=summary.get("kv_quant", "none"),
            prefix_hit_rate=round(
                summary.get("prefix_hit_rate", 0.0), 4
            ),
            prefix_hit_blocks=summary.get("prefix_hit_blocks", 0),
            block_stalls=summary.get("batcher", {}).get(
                "block_stalls", 0
            ),
        )
        # Read path + storage dtype are part of the metric FAMILY
        # (the kv_layout discipline): a pallas or int8 row banked
        # under the gather/fp family would set high-water marks the
        # next default row gets judged against. Default gather/none
        # contributes no tag, so pre-ISSUE-20 histories continue.
        kv_tag = _kv_metric_tag(summary)
    spec_mode = summary.get("spec_mode")
    acceptance = round(summary.get("acceptance_rate", 0.0), 4)
    if spec_mode:
        # Speculative identity + the two judged signals: a
        # speculative row must never be diffed against a greedy one
        # unlabeled (the kv_layout discipline).
        rec_serve.update(
            spec_mode=spec_mode,
            spec_k=summary.get("spec_k"),
            acceptance_rate=acceptance,
            verify_steps=summary.get("verify_steps"),
            draft_ms=summary.get("draft_ms"),
        )
    if disagg:
        d = summary.get("disagg", {})
        rec_serve["disagg"] = {
            "prefill_mesh": d.get("prefill_mesh"),
            "decode_mesh": d.get("decode_mesh"),
            "kv_transfers": d.get("kv_transfers"),
            "kv_transfer_bytes": d.get("kv_transfer_bytes"),
            "kv_transfer_ms_p95": d.get("kv_transfer_ms_p95"),
        }
    if spec_mode:
        # The speculative mode is part of the METRIC family, not just
        # a sub-dict label: the --bank reduction reads only the
        # top-level value + side keys, so a spec row banked under the
        # greedy family would set itl/ttft high-water marks the next
        # greedy row gets judged against (and draft-vs-ngram
        # trajectories would cross the same way) -- the
        # loadgen_record separation, applied here too.
        metric = f"serve_spec_{spec_mode}{kv_tag}_tokens_per_s_per_chip"
    elif disagg:
        metric = f"serve_disagg{kv_tag}_tokens_per_s_per_chip"
    else:
        metric = f"serve{kv_tag}_tokens_per_s_per_chip"
    rec = {
        "metric": metric,
        "value": round(summary["tokens_per_s_per_chip"], 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 3) if mfu is not None else None,
        "ttft_ms_p50": round(summary["ttft_ms_p50"], 2),
        "ttft_ms_p95": round(summary["ttft_ms_p95"], 2),
        "itl_ms_p50": round(summary["itl_ms_p50"], 2),
        "itl_ms_p95": round(summary["itl_ms_p95"], 2),
        "serve": rec_serve,
    }
    if spec_mode:
        # Top level, where the bank reduction can see it: the
        # mechanism metric rides every spec row (higher-is-better in
        # the gate -- a stale draft fails --bank even when the
        # latency outcome still rides within tolerance).
        rec["acceptance_rate"] = acceptance
    return rec


def _bench_paged_cfg(
    paged: bool, slots: int, max_seq: int, buckets,
    block_size=None, kv_blocks=None, prefill_chunk=None,
    host_blocks=None, kernel=None, kv_quant=None,
):
    """(PagedConfig | None, page-aligned max_seq) for the serve/
    loadgen rows. ONE derivation shared with server.py's CLI
    (paging.derive_paged_config), so the bench rows and the serving
    CLI can never silently diverge on a default; invalid sizing is a
    clean CLI error, not a ValueError traceback after model init."""
    if not paged:
        return None, max_seq
    from tpu_hpc.serve.paging import derive_paged_config

    try:
        return derive_paged_config(
            slots, max_seq, buckets,
            block_size=block_size, num_blocks=kv_blocks,
            prefill_chunk=prefill_chunk, align_capacity=True,
            host_blocks=host_blocks or 0,
            kernel=kernel, kv_quant=kv_quant,
        )
    except ValueError as e:
        raise SystemExit(f"bench.py: {e}")


def _bench_spec_cfg(spec: str, spec_k):
    """(SpecConfig | None) from the CLI spec flags -- invalid
    combinations fail as clean CLI errors like _bench_paged_cfg."""
    if spec == "off":
        return None
    from tpu_hpc.serve.spec import SpecConfig

    try:
        return SpecConfig(mode=spec, k=spec_k if spec_k is not None
                          else 4)
    except ValueError as e:
        raise SystemExit(f"bench.py: {e}")


def bench_serve(
    requests: int = 32, slots: int = 8, max_new: int = 64,
    prompt_lens=(96, 192, 384), buckets=(128, 256, 512),
    model_cfg=None, disagg: bool = False, paged: bool = False,
    block_size=None, kv_blocks=None, prefill_chunk=None,
    host_blocks=None, kernel=None, kv_quant=None,
    spec: str = "off", spec_k=None, draft_ckpt=None,
) -> dict:
    """Batched-inference throughput: the SAME ~170M bench architecture
    as the training headline (bench_model_cfg -- one factory, so
    train and serve rows describe one model), run through the serving
    engine's continuous batcher. Emits TTFT/ITL quantiles and
    tokens/s/chip in the training-record schema; ``recompiles`` in the
    record must read 0 -- the engine warms up every program shape
    before the replay clock starts."""
    import jax

    from tpu_hpc.runtime import init_distributed
    from tpu_hpc.serve.engine import ServeConfig
    from tpu_hpc.serve.server import run_replay

    init_distributed(verbose=False)
    if disagg and jax.device_count() < 2:
        # The server.py guard's twin: a tier split needs a chip per
        # tier -- fail as a CLI error, not a mid-bring-up traceback.
        raise SystemExit(
            "bench.py: --serve-disagg needs >= 2 devices (one per "
            f"tier); only {jax.device_count()} visible"
        )
    model_cfg = model_cfg or bench_model_cfg()
    paged_cfg, max_seq = _bench_paged_cfg(
        paged, slots, max(buckets) + max_new, buckets,
        block_size, kv_blocks, prefill_chunk, host_blocks,
        kernel, kv_quant,
    )
    spec_cfg = _bench_spec_cfg(spec, spec_k)
    serve_cfg = ServeConfig(
        slots=slots,
        max_seq_len=max_seq,
        prefill_buckets=tuple(buckets),
    )
    summary = run_replay(
        model_cfg, serve_cfg, requests, prompt_lens, max_new,
        disagg=disagg, paged=paged_cfg,
        spec=spec_cfg, spec_draft_ckpt=draft_ckpt,
    )
    rec = serve_record(summary, disagg=disagg)
    _attach_logit_rmse(rec, model_cfg, paged_cfg)
    print(
        f"serve{'-disagg' if disagg else ''}"
        f"{'-paged' if paged else ''}"
        f"{f'-{kernel}' if kernel == 'pallas' else ''}"
        f"{f'-{kv_quant}' if kv_quant == 'int8' else ''}"
        f"{f'-spec:{spec}' if spec != 'off' else ''} | "
        f"{summary['mesh']} slots={slots} | "
        f"{summary['tokens_per_s']:.0f} tokens/s | "
        f"TTFT p50 {summary['ttft_ms_p50']:.0f} ms | "
        f"ITL p50 {summary['itl_ms_p50']:.1f} ms",
        file=sys.stderr,
    )
    return rec


def _attach_logit_rmse(rec: dict, model_cfg, paged_cfg) -> None:
    """Pin the quantization error onto every int8 row, top level
    where the --bank reduction judges it (obs/regress
    _BANKED_SIDE_KEYS, lower-is-better via the rmse token): the
    deterministic pre-softmax score RMSE of per-page int8 K against
    fp at THIS model's head geometry and page size. A quantizer
    regression fails the gate even while the latency headline still
    rides within tolerance."""
    if paged_cfg is None or paged_cfg.kv_quant != "int8":
        return
    from tpu_hpc.kernels.paged_attention import int8_logit_rmse

    rec["logit_rmse"] = round(
        int8_logit_rmse(
            head_dim=model_cfg.dim // model_cfg.n_heads,
            kv_heads=model_cfg.n_kv_heads or model_cfg.n_heads,
            n_heads=model_cfg.n_heads,
            block_size=paged_cfg.block_size,
        ),
        6,
    )


def loadgen_record(summary: dict) -> dict:
    """Load-harness summary -> the bench record schema. The headline
    value is the interactive-visible p95 TTFT in VIRTUAL ms (the
    harness's deterministic clock -- scheduling behavior, not machine
    noise; wall-clock throughput remains the serve row's job), with
    the per-tenant shed/queued breakdown riding along so the regress
    gate can hold admission control to its history."""
    tenants = summary.get("tenants", {})
    lg = {
        "scenario": summary["scenario"],
        "seed": summary["seed"],
        "shed": summary["shed"],
        "queued": summary["queued"],
        "occupancy_mean": round(summary["occupancy_mean"], 4),
        "stall_events": summary["stall_events"],
        "slo_violations": summary["slo_violations"],
        "recompiles": summary["recompiles"],
        "kv_layout": summary.get("kv_layout", "slab"),
        "tenants": {
            name: {
                "shed": t["shed"], "queued": t["queued"],
                "ttft_ms_p95": round(t["ttft_ms_p95"], 3),
            }
            for name, t in tenants.items()
        },
    }
    metric = f"loadgen_{summary['scenario']}_ttft_ms_p95"
    kv_tag = ""
    if summary.get("kv_layout") == "paged":
        lg.update(
            kv_block_size=summary.get("kv_block_size"),
            kv_blocks=summary.get("kv_blocks"),
            kv_kernel=summary.get("kv_kernel", "gather"),
            kv_quant=summary.get("kv_quant", "none"),
            prefix_hit_rate=round(
                summary.get("prefix_hit_rate", 0.0), 4
            ),
            block_stalls=summary.get("batcher", {}).get(
                "block_stalls", 0
            ),
        )
        # The cache layout is part of the metric's identity: the
        # --bank gate must track paged and slab trajectories
        # separately (at equal traffic they are different systems).
        # So are the read path and the page storage dtype (the cost
        # model charges them differently); gather/fp contributes no
        # tag so pre-ISSUE-20 histories continue.
        kv_tag = _kv_metric_tag(summary)
        metric = f"loadgen_{summary['scenario']}_paged{kv_tag}_ttft_ms_p95"
    tiered = bool(summary.get("kv_host_blocks"))
    if tiered:
        # A host page tier changes what the same traffic measures
        # (returns prefetch instead of re-prefilling, spill/refill
        # hops ride the cost model), so tiered rows bank under their
        # own family -- an HBM-only trajectory and a tiered one must
        # never cross in the --bank history.
        lg.update(
            kv_host_blocks=summary.get("kv_host_blocks"),
            kv_host_used=summary.get("kv_host_used"),
            kv_host_drops=summary.get("kv_host_drops", 0),
            kv_spill_pages=summary.get("kv_spill_pages", 0),
            kv_refill_pages=summary.get("kv_refill_pages", 0),
        )
        metric = (
            f"loadgen_{summary['scenario']}_paged{kv_tag}"
            "_tiered_ttft_ms_p95"
        )
    spec_mode = summary.get("spec_mode")
    acceptance = round(summary.get("acceptance_rate", 0.0), 4)
    if spec_mode:
        # Speculative rows bank under their own per-MODE metric
        # family (draft and ngram trajectories must not cross) for
        # the same reason, and carry acceptance + modeled draft cost.
        lg.update(
            spec_mode=spec_mode,
            spec_k=summary.get("spec_k"),
            acceptance_rate=acceptance,
            verify_steps=summary.get("verify_steps"),
            draft_ms=summary.get("draft_ms"),
        )
        metric = (
            f"loadgen_{summary['scenario']}_paged{kv_tag}_spec_"
            f"{spec_mode}_ttft_ms_p95"
        )
    fleet = summary.get("fleet")
    if fleet:
        # Fleet rows bank under their own metric family: a
        # multi-replica quantile at the same traffic is a different
        # system from a single-engine one (failure handling, routing
        # and autoscale all in the loop), and the robustness counters
        # ride along so the --bank gate fails on redispatch/
        # replica-loss/swap-rollback drift (regress direction
        # tokens).
        lg.update(
            fleet={
                k: fleet[k]
                for k in (
                    "replicas", "live_min", "live_max", "router",
                    "weights_version", "redispatched",
                    "replica_down", "restarts", "swapped_replicas",
                    "swap_rollbacks", "scale_ups", "scale_downs",
                )
            },
            prefix_affinity_hit_rate=round(
                fleet["prefix_affinity_hit_rate"], 4
            ),
            lost_requests=summary.get("lost_requests", 0),
            block_stalls=summary.get("block_stalls", 0),
        )
        metric = (
            f"loadgen_{summary['scenario']}_fleet{kv_tag}_ttft_ms_p95"
        )
    rec = {
        "metric": metric,
        "value": round(summary["ttft_ms_p95"], 3),
        "unit": "virtual_ms",
        "vs_baseline": None,
        "ttft_ms_p50": round(summary["ttft_ms_p50"], 3),
        "ttft_ms_p99": round(summary["ttft_ms_p99"], 3),
        "itl_ms_p50": round(summary["itl_ms_p50"], 3),
        "itl_ms_p95": round(summary["itl_ms_p95"], 3),
        "loadgen": lg,
    }
    if fleet:
        # Top level so the --bank reduction judges the MECHANISMS
        # (obs/regress._BANKED_SIDE_KEYS -- the reduction reads only
        # the record's top level, sub-dicts are never walked): the
        # router's affinity outcome (higher-is-better by token
        # absence) and the robustness counters (lower via the
        # redispatch/replica_down/swap/lost_requests direction
        # tokens) fail the gate on drift even while the latency
        # headline still rides within tolerance.
        rec["prefix_affinity_hit_rate"] = round(
            fleet["prefix_affinity_hit_rate"], 4
        )
        rec["redispatched"] = fleet["redispatched"]
        rec["replica_down"] = fleet["replica_down"]
        rec["swap_rollbacks"] = fleet["swap_rollbacks"]
        rec["lost_requests"] = summary.get("lost_requests", 0)
    if spec_mode:
        # Top level so the --bank reduction judges the MECHANISM, not
        # just the latency outcome: acceptance_rate is one of the
        # banked side keys (obs/regress._BANKED_SIDE_KEYS,
        # higher-is-better) -- a draft source going stale fails the
        # gate even while ttft/itl still ride within tolerance.
        rec["acceptance_rate"] = acceptance
    ret = tenants.get("return")
    if ret is not None:
        # Top level for the same reason: the return-visit experience
        # is the tier's whole thesis, so the banked side keys judge
        # it directly -- TTFT-on-return quantiles (lower via the
        # ttft token), returns shed at the door (lower via shed),
        # and resident sessions = returns whose KV prefix was still
        # seated or refilled (prefix hits; higher-is-better by token
        # absence). An HBM-only row banks the same keys, so the
        # contrast is in the history, not just this run's stderr.
        rec["ttft_on_return_ms_p50"] = round(ret["ttft_ms_p50"], 3)
        rec["ttft_on_return_ms_p95"] = round(ret["ttft_ms_p95"], 3)
        rec["shed_on_return"] = ret["shed"]
        rec["resident_sessions"] = summary.get("prefix_hits", 0)
    if tiered:
        # Wire volume over the host hop, top level so the --bank
        # reduction catches a spill/refill storm (regress direction
        # tokens: spill/refill + wire_bytes, lower-is-better) even
        # while the latency headline rides within tolerance.
        rec["kv_spill_wire_bytes"] = summary.get(
            "kv_spill_wire_bytes", 0
        )
        rec["kv_refill_wire_bytes"] = summary.get(
            "kv_refill_wire_bytes", 0
        )
    return rec


def bench_loadgen(
    scenario: str = "multi_tenant", requests: int = 64,
    slots: int = 8, max_new: int = 32, seed: int = 0,
    paged: bool = False, block_size=None, kv_blocks=None,
    prefill_chunk=None, host_blocks=None, kernel=None,
    kv_quant=None, model: str = "bench",
    spec: str = "off", spec_k=None, draft_ckpt=None,
    fleet: int = 0, fleet_min: int = 1, fleet_swap_at=None,
    fleet_router: str = "affinity",
) -> dict:
    """Scenario-diverse load row: the SAME ~170M bench architecture as
    the serve row, driven by the tpu_hpc.loadgen harness. ``recompiles``
    must read 0 like the serve row -- a scenario mix that recompiled
    would be measuring the compiler.

    ``model="tiny"`` swaps in the 8-device-sim dev model
    (serve/server.tiny_config). This is legal for THIS workload only:
    loadgen latencies run on the virtual clock, a pure function of
    (scenario, seed, serve shape, cost model) -- the model provides
    the real programs but contributes zero virtual time, so the
    banked quantiles are identical across models. The record still
    carries ``model`` so no row masquerades as a bench-architecture
    measurement. Caveat: ``spec`` weakens model-independence to
    model-DETERMINISM -- acceptance depends on the actual token
    streams, so speculative quantiles are a pure function of
    (scenario, seed, serve shape, cost model, MODEL); the ``model``
    label in the record is part of a speculative row's identity."""
    import dataclasses as _dc

    from tpu_hpc.runtime import init_distributed
    from tpu_hpc.serve.engine import ServeConfig
    from tpu_hpc.serve.server import (
        run_fleet_loadgen,
        run_loadgen,
        tiny_config,
    )

    init_distributed(verbose=False)
    if model == "tiny":
        # The dev model's capacity must still hold bucket + max_new.
        model_cfg = _dc.replace(tiny_config(), max_seq_len=1024)
    else:
        model_cfg = bench_model_cfg()
    buckets = (128, 256, 512)
    paged_cfg, max_seq = _bench_paged_cfg(
        paged, slots, max(buckets) + max_new, buckets,
        block_size, kv_blocks, prefill_chunk, host_blocks,
        kernel, kv_quant,
    )
    spec_cfg = _bench_spec_cfg(spec, spec_k)
    serve_cfg = ServeConfig(
        slots=slots,
        max_seq_len=max_seq,
        prefill_buckets=buckets,
    )
    if fleet:
        summary = run_fleet_loadgen(
            model_cfg, serve_cfg, scenario, requests, max_new,
            paged_cfg, n_replicas=fleet, min_replicas=fleet_min,
            router=fleet_router, swap_at=fleet_swap_at, seed=seed,
        )
    else:
        summary = run_loadgen(
            model_cfg, serve_cfg, scenario, requests, max_new,
            seed=seed, paged=paged_cfg,
            spec=spec_cfg, spec_draft_ckpt=draft_ckpt,
        )
    rec = loadgen_record(summary)
    rec["loadgen"]["model"] = model
    _attach_logit_rmse(rec, model_cfg, paged_cfg)
    print(
        f"loadgen {scenario}{' paged' if paged else ''}"
        f"{f' {kernel}' if kernel == 'pallas' else ''}"
        f"{f' {kv_quant}' if kv_quant == 'int8' else ''}"
        f"{' tiered' if host_blocks else ''}"
        f"{f' fleet:{fleet}' if fleet else ''}"
        f"{f' spec:{spec}' if spec != 'off' else ''} | "
        f"shed {summary['shed']} "
        f"queued {summary['queued']} | TTFT p95 "
        f"{summary['ttft_ms_p95']:.1f} virtual-ms | ITL p50 "
        f"{summary['itl_ms_p50']:.1f} | occupancy "
        f"{summary['occupancy_mean']:.0%}"
        + (
            f" | affinity "
            f"{summary.get('prefix_affinity_hit_rate', 0):.0%} "
            f"redisp {summary['fleet']['redispatched']} "
            f"lost {summary.get('lost_requests', 0)}"
            if fleet else ""
        )
        + (
            f" | acceptance {summary.get('acceptance_rate', 0):.0%}"
            if spec != "off" else ""
        ),
        file=sys.stderr,
    )
    return rec


def bench_unet(steps: int = 20) -> dict:
    import jax
    import jax.numpy as jnp

    from tpu_hpc.config import TrainingConfig
    from tpu_hpc.models import datasets, losses
    from tpu_hpc.models.unet import UNetConfig, apply_unet, init_unet
    from tpu_hpc.parallel import dp
    from tpu_hpc.runtime import MeshSpec, build_mesh, init_distributed
    from tpu_hpc.train import Trainer

    init_distributed(verbose=False)
    cfg = TrainingConfig(
        epochs=2,
        steps_per_epoch=steps,
        global_batch_size=8 * jax.device_count(),
        learning_rate=1e-3,
    )
    mesh = build_mesh(MeshSpec(axes={"data": -1}))
    ds = datasets.ERA5Synthetic()
    model_cfg = UNetConfig(
        in_channels=ds.channels, out_channels=ds.channels,
        dtype=jnp.bfloat16,
    )
    params, model_state = init_unet(
        jax.random.key(0), model_cfg, ds.sample_shape
    )

    def forward(p, ms, batch, step_rng):
        x, y = batch
        pred, new_ms = apply_unet(p, ms, x, model_cfg, train=True)
        return losses.lat_weighted_mse(pred, y), new_ms, {}

    trainer = Trainer(
        cfg, mesh, forward, params, model_state,
        param_pspecs=dp.param_pspecs(params),
    )
    result = trainer.fit(ds)
    summary = result["epochs"][-1]
    return {
        "metric": "unet_dp_train_throughput",
        "value": round(summary["items_per_s"], 2),
        "unit": "samples/s",
        "vs_baseline": 1.0,
    }


def probe_backend(timeout_s: int = 180, window_s: int = None):
    """Bounded check that the accelerator backend comes up before
    committing to a (long-compiling) workload. A down tunnel otherwise
    hangs jax initialization for ~30 min per attempt (observed during
    a mid-round pool outage) -- fail with a clear message so the
    caller records an actionable error instead of a stall.

    Transient outages are the common failure (two straight rounds of
    driver benches lost to them), so failed probes RETRY with backoff
    across a window -- default 30 min, override via
    ``TPU_HPC_PROBE_WINDOW_S`` (0 = single attempt) -- instead of
    giving up after two tries.

    Returns ``(device_count, device_kind)`` on success (so callers
    never need a second, unbounded jax.devices() of their own), else
    None."""
    import subprocess
    import time

    if window_s is None:
        window_s = int(os.environ.get("TPU_HPC_PROBE_WINDOW_S", "1800"))
    code = (
        "import jax; d = jax.devices(); "
        "print('PROBE_OK', len(d), '|', d[0].device_kind)"
    )
    deadline = time.monotonic() + window_s
    backoff, attempt = 30, 0
    while True:
        attempt += 1
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=timeout_s,
            )
            out = proc.stdout.strip()
            if proc.returncode == 0 and "PROBE_OK" in out:
                line = [
                    l for l in out.splitlines() if l.startswith("PROBE_OK")
                ][-1]
                head, kind = line.split("|", 1)
                return int(head.split()[1]), kind.strip()
            err = proc.stderr.strip().splitlines()
            msg = err[-1] if err else f"rc={proc.returncode}"
        except subprocess.TimeoutExpired:
            msg = f"no backend after {timeout_s}s"
        remaining = deadline - time.monotonic()
        print(
            f"backend probe attempt {attempt} failed: {msg} "
            f"({max(remaining, 0):.0f}s left in retry window)",
            file=sys.stderr,
        )
        if remaining <= backoff:
            return None
        time.sleep(backoff)
        backoff = min(backoff * 2, 240)


def run_all(out_path: str, steps: int, devinfo=None) -> int:
    """Record every workload family into one artifact (markdown table
    + raw JSONL next to it): the recorded-evidence pass VERDICT r1
    asked for -- each parallelism family gets a measured number on
    whatever hardware is visible. Each workload runs in a fresh
    subprocess so one family's failure (or HBM state) cannot poison
    the next."""
    import subprocess

    jobs = [
        ("llama (hybrid/dp)", ["--workload", "llama"]),
        ("llama-sp zigzag ring", ["--workload", "llama-sp", "--sp-mode", "zigzag"]),
        ("llama-sp ulysses", ["--workload", "llama-sp", "--sp-mode", "ulysses"]),
        ("llama-pp 1f1b", ["--workload", "llama-pp", "--pp-schedule", "1f1b"]),
        ("llama-pp 1f1b flagship",
         ["--workload", "llama-pp", "--pp-schedule", "1f1b",
          "--pp-model", "llama"]),
        ("llama-pp 1f1b-stash",
         ["--workload", "llama-pp", "--pp-schedule", "1f1b",
          "--pp-backward", "stash"]),
        ("llama-pp gpipe",
         ["--workload", "llama-pp", "--pp-schedule", "gpipe"]),
        ("llama-pp interleaved-1f1b",
         ["--workload", "llama-pp", "--pp-schedule", "interleaved-1f1b"]),
        ("llama dp bucketed-overlap sync",
         ["--workload", "llama", "--comm-mode", "bucketed_overlap"]),
        ("llama-long seq 8192", ["--workload", "llama-long"]),
        ("serve (continuous batching)", ["--workload", "serve"]),
        ("loadgen multi-tenant mix", ["--workload", "loadgen"]),
        ("unet ddp", ["--workload", "unet"]),
    ]
    rows, raw = [], []
    child_env = dict(os.environ, TPU_HPC_BENCH_NO_PROBE="1")
    for name, argv in jobs:
        print(f"--- {name} ---", file=sys.stderr)
        try:
            proc = subprocess.run(
                [sys.executable, __file__, *argv, "--steps", str(steps)],
                capture_output=True, text=True, timeout=1800,
                env=child_env,
            )
            sys.stderr.write(proc.stderr[-500:])
            out, err = proc.stdout.strip(), proc.stderr
        except subprocess.TimeoutExpired as e:
            # One hung family must not poison the sweep: record it
            # failed and keep going.
            out = ""
            err = f"timed out after {e.timeout}s"
        line = out.splitlines()[-1] if out else ""
        try:
            rec = json.loads(line)
            # A child whose last stdout line is valid JSON but not a
            # bench record (or lacks value/unit) must not abort the
            # sweep and lose every already-collected row.
            if not isinstance(rec, dict) or "value" not in rec \
                    or "unit" not in rec:
                raise ValueError(f"not a bench record: {line[:120]!r}")
        except (ValueError, IndexError):
            from tpu_hpc.obs import stamp

            # Failure rows keep the bench schema too: the sweep JSONL
            # must validate end to end even when a family died.
            rec = stamp({
                "event": "bench", "metric": name, "value": None,
                "unit": "FAILED", "vs_baseline": None,
                "error": err[-300:],
            })
        rec["workload"] = name
        raw.append(rec)
        rows.append(
            f"| {name} | {rec['value']} | {rec['unit']} | "
            f"{rec.get('vs_baseline')} |"
        )
    # Device identity from the parent's bounded probe -- a direct
    # jax.devices() here would hang unboundedly if the backend died
    # mid-sweep, losing every already-collected row.
    n_dev, kind = devinfo if devinfo else ("?", "unknown")
    md = "\n".join([
        "# Recorded benchmark sweep",
        "",
        f"One row per parallelism family (`python bench.py --all`), "
        f"run on {n_dev}x {kind}. vs_baseline for llama "
        "workloads = achieved MFU / the 40% north-star target "
        "(BASELINE.md; the reference publishes no measured numbers).",
        "",
        "| workload | value | unit | vs_baseline |",
        "|---|---|---|---|",
        *rows,
        "",
    ])
    with open(out_path, "w") as f:
        f.write(md)
    with open(os.path.splitext(out_path)[0] + ".jsonl", "w") as f:
        f.write("\n".join(json.dumps(r) for r in raw) + "\n")
    print(md)
    return 0 if all(r.get("value") is not None for r in raw) else 1


def main(argv=None) -> int:
    # allow_abbrev=False: --supervise is stripped from argv by exact
    # name before re-exec; an accepted abbreviation ("--superv 2")
    # would survive the strip and recurse supervisors forever.
    ap = argparse.ArgumentParser(allow_abbrev=False)
    ap.add_argument(
        "--workload",
        choices=(
            "llama", "llama-sp", "llama-pp", "pp", "llama-long",
            "unet", "serve", "loadgen", "elastic",
        ),
        default=None,  # resolved after --serve alias handling
        help="'pp' is an alias for 'llama-pp' (the pipeline workload "
        "family; --pp-runtime selects the SPMD tick loop or the MPMD "
        "per-stage runtime)",
    )
    ap.add_argument(
        "--serve", action="store_true",
        help="alias for --workload serve: batched-inference "
        "throughput (TTFT/ITL/tokens-per-s) on the bench model via "
        "tpu_hpc.serve",
    )
    ap.add_argument("--serve-requests", type=int, default=32)
    ap.add_argument("--serve-slots", type=int, default=8)
    ap.add_argument("--serve-max-new", type=int, default=64)
    ap.add_argument(
        "--serve-disagg", action="store_true",
        help="disaggregated serving row: prefill/decode on disjoint "
        "mesh tiers, KV blocks moved by tpu_hpc.reshard plans; the "
        "record carries the per-tier meshes and kv-transfer load "
        "(--workload serve only)",
    )
    ap.add_argument(
        "--loadgen-scenario", type=str, default=None,
        help="tpu_hpc.loadgen catalog scenario for --workload loadgen "
        "(default multi_tenant; sized by --serve-requests/"
        "--serve-slots; virtual-clock latencies, the regress gate's "
        "input)",
    )
    ap.add_argument(
        "--serve-fleet", type=int, default=None, metavar="N",
        help="run the loadgen scenario over a fleet of N paged "
        "replicas on disjoint mesh slices (serve/fleet.py): "
        "affinity routing, heartbeat failure handling, autoscale; "
        "the record banks under its own loadgen_<scenario>_fleet_* "
        "family with the robustness counters riding along "
        "(--workload loadgen with --serve-paged "
        "--serve-prefill-chunk only)",
    )
    ap.add_argument(
        "--fleet-swap-at", type=int, default=None, metavar="TICK",
        help="publish a live weight update mid-run at this fleet "
        "tick (dev mode: a fresh random init at seed+1) rolled out "
        "drain-and-swap behind the content-checksum gate; requires "
        "--serve-fleet",
    )
    ap.add_argument(
        "--fleet-router", choices=("affinity", "round_robin"),
        default=None,
        help="fleet request placement (default affinity; round_robin "
        "is the documented degraded control); requires --serve-fleet",
    )
    ap.add_argument(
        "--fleet-min", type=int, default=None, metavar="N",
        help="autoscaler's minimum live replicas (default 1; initial "
        "live set = max(min, ceil(N/2))); requires --serve-fleet",
    )
    ap.add_argument(
        "--serve-paged", action="store_true",
        help="paged KV cache (tpu_hpc/serve/paging.py): block-table "
        "pool with prefix reuse + chunked prefill; the record carries "
        "kv_layout/kv_block_size/prefix-hit rate (--workload serve "
        "or loadgen)",
    )
    ap.add_argument(
        "--serve-block-size", type=int, default=None, metavar="TOK",
        help="tokens per KV page for --serve-paged (default 16)",
    )
    ap.add_argument(
        "--serve-kv-blocks", type=int, default=None, metavar="N",
        help="physical pages in the paged pool incl. scratch "
        "(default: slab-equivalent capacity) for --serve-paged",
    )
    ap.add_argument(
        "--serve-host-blocks", type=int, default=None, metavar="N",
        help="host-DRAM KV page tier (serve/tier.py) slots incl. "
        "scratch for --serve-paged: parked prefixes spill to host "
        "under pool pressure and prefetch back before the return "
        "visit seats; tiered rows bank under their own "
        "_paged_tiered_ metric family; size with "
        "tpu_hpc.checks.fit --kv-host-tier",
    )
    ap.add_argument(
        "--serve-prefill-chunk", type=int, default=None, metavar="TOK",
        help="chunked-prefill stride for --serve-paged (0/omitted = "
        "whole-prompt prefill)",
    )
    ap.add_argument(
        "--serve-kernel", choices=("gather", "pallas"), default=None,
        help="paged attention read path for --serve-paged "
        "(tpu_hpc.kernels.paged_attention): 'gather' materializes "
        "pages before a dense flash call (the oracle), 'pallas' "
        "walks the block table in-kernel -- one HBM read per page; "
        "pallas rows bank under their own _pallas metric family",
    )
    ap.add_argument(
        "--serve-kv-quant", choices=("none", "int8"), default=None,
        help="KV page storage for --serve-paged: 'int8' quantizes "
        "pages per page with fp32 scales -- half the bytes per "
        "token, ~2x resident context at equal HBM; int8 rows bank "
        "under their own _q8 family and carry logit_rmse",
    )
    ap.add_argument(
        "--serve-spec", choices=("off", "draft", "ngram"),
        default="off",
        help="speculative decoding (tpu_hpc/serve/spec.py; requires "
        "--serve-paged): 'draft' = small-model drafting "
        "(--serve-draft-ckpt, else a dev random init), 'ngram' = "
        "prompt-lookup self-speculation; records carry "
        "spec_mode/acceptance_rate (--workload serve or loadgen)",
    )
    ap.add_argument(
        "--serve-draft-ckpt", type=str, default=None, metavar="DIR",
        help="draft-model checkpoint dir for --serve-spec draft",
    )
    ap.add_argument(
        "--spec-k", type=int, default=None, metavar="K",
        help="drafted tokens per verify step for --serve-spec "
        "(default 4)",
    )
    ap.add_argument(
        "--serve-model", choices=("bench", "tiny"), default="bench",
        help="model for --workload loadgen ONLY: 'tiny' runs the "
        "8-device-sim dev model -- legal because loadgen quantiles "
        "are virtual-clock (model-independent); the record carries "
        "the model label",
    )
    ap.add_argument(
        "--all", action="store_true",
        help="run every workload family, write BENCH_EXTRA.md/.jsonl",
    )
    ap.add_argument("--out", type=str, default="BENCH_EXTRA.md")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--remat", action="store_true")
    # Per-dp-shard batch. Default: the family's measured-best
    # microbatch (4; 1 for llama-long at seq 8192) x accum 8 — see
    # resolve_batch_accum. Explicit --batch runs unaccumulated unless
    # --grad-accum-steps is also given.
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--attn", choices=("flash", "xla"), default="flash")
    # 512/1024 q/k tiling: the autotuner's pick (AUTOTUNE_v5e.md),
    # confirmed end-to-end on the chip in round 5 -- 124,171
    # tokens/s/chip 57.6% MFU vs 121,361 56.3% at 512/512
    # (HW_QUEUE_r05/bench_bk1024.log vs bench_headline.log). The
    # bench_* function defaults MATCH these (reconciled, ADVICE r5),
    # and every record carries its effective flash_blocks.
    ap.add_argument("--block-q", type=int, default=512)
    ap.add_argument("--block-k", type=int, default=1024)
    ap.add_argument("--block-q-bwd", type=int, default=None,
                    help="backward-kernel q tiling (default: --block-q)")
    ap.add_argument("--block-k-bwd", type=int, default=None,
                    help="backward-kernel k tiling (default: --block-k)")
    ap.add_argument(
        "--sp-mode", choices=("ring", "zigzag", "ulysses"),
        default="zigzag",
    )
    ap.add_argument(
        "--pp-schedule",
        choices=("gpipe", "1f1b", "interleaved", "interleaved-1f1b"),
        default="1f1b"
    )
    ap.add_argument("--pp-microbatches", type=int, default=8)
    ap.add_argument(
        "--pp-microbatch-size", type=int, default=4,
        help="examples per microbatch (the DP headline's measured-best "
        "microbatch; total batch = microbatches x this)",
    )
    ap.add_argument(
        "--pp-model", choices=("stack", "llama"), default="stack",
        help="stack: the homogeneous PipelineTransformer; llama: the "
        "flagship model itself stage-split via models/llama_pp.py "
        "(same architecture as the DP headline -- directly "
        "comparable; all four schedules)",
    )
    ap.add_argument(
        "--pp-runtime", choices=("spmd", "mpmd"), default="spmd",
        help="pipeline runtime: spmd = the single shard_map tick "
        "loop (parallel/pp.py, all four schedules); mpmd = per-stage "
        "AOT programs on disjoint devices with per-stage fault "
        "domains (parallel/mpmd.py) -- the record carries the "
        "measured bubble fraction + recovery MTTR and banks under "
        "the pp_mpmd_* family; stage faults (TPU_HPC_FAULTS "
        "stage_kill_at/stage_nan_at/stage_straggler) are consumed "
        "ONLY here",
    )
    ap.add_argument(
        "--pp-backward", choices=("remat", "stash"), default="remat",
        help="1f1b backward: remat saves only stage inputs and "
        "recomputes the forward (5/3 of ideal FLOPs); stash saves the "
        "vjp residuals (4/3, Megatron-style, O(S) microbatches of "
        "residual HBM)",
    )
    ap.add_argument("--seq-len", type=int, default=None,
                help="sequence length (default: 2048 for llama, 8192 for llama-long)")
    ap.add_argument(
        "--grad-accum-steps", type=int, default=None,
        help="microbatch the per-step batch this many times inside the "
        "jitted step (amortizes optimizer/AdamW-state HBM traffic over "
        "more tokens per optimizer step). llama-family default: 8, "
        "with batch scaled to hold the measured-best microbatch when "
        "--batch is omitted; explicit --batch without this flag runs "
        "unaccumulated",
    )
    ap.add_argument(
        "--comm-mode",
        choices=("flat", "hierarchical", "bucketed_overlap", "auto"),
        default="flat",
        help="gradient-sync strategy (config.comm_mode): flat = "
        "GSPMD's fused collectives; bucketed_overlap = explicit "
        "DDP-style size-capped bucket reductions inside shard_map; "
        "hierarchical = bucketed + two-phase ICI/DCN decomposition; "
        "auto = the collective planner (tpu_hpc.comm.planner) picks "
        "mode and bucket from this topology's cost table (alpha-beta "
        "fallback without one). "
        "Manual modes run the pure-DP replicated-params recipe; the "
        "record carries comm_mode so BENCH JSONLs can attribute "
        "step-time deltas (llama/llama-long workloads)",
    )
    ap.add_argument(
        "--comm-table", type=str, default=None, metavar="PATH",
        help="explicit planner cost-table file for --comm-mode auto "
        "(default: the cache-dir entry for the live topology, "
        "$TPU_HPC_COMM_TABLES); requires --comm-mode auto",
    )
    ap.add_argument(
        "--guard-mode", choices=("off", "skip"), default="off",
        help="numeric-health guard (config.guard_mode): 'skip' arms "
        "the in-step health vector + on-device nonfinite-update skip "
        "so the row measures the guard's steady-state cost "
        "('rollback' needs a checkpoint manager the bench does not "
        "run; llama/llama-long workloads)",
    )
    ap.add_argument(
        "--moments-dtype", choices=("float32", "bfloat16"),
        default="float32",
        help="AdamW moment storage dtype (bfloat16 halves optimizer-"
        "state HBM bytes read+written per step)",
    )
    ap.add_argument(
        "--elastic-shrink-at", type=int, default=None, metavar="N",
        help="topology coordinator chaos: lose half the device pool "
        "at step N (live shrink, no restart; --workload elastic "
        "only; default 2)",
    )
    ap.add_argument(
        "--elastic-grow-at", type=int, default=None, metavar="N",
        help="topology coordinator chaos: the lost slice returns at "
        "step N (live grow back to the full pool; --workload "
        "elastic only; default 4)",
    )
    ap.add_argument(
        "--supervise", type=int, default=0, metavar="N",
        help="re-launch this bench under the resilience supervisor "
        "with N bounded restarts (attempt-unique logs in "
        "bench_logs/; a preempted/crashed run restarts instead of "
        "losing the allocation -- the shell-watchdog replacement)",
    )
    args = ap.parse_args(argv)
    if args.serve:
        if args.workload not in (None, "serve"):
            # The alias must never silently replace an explicit
            # conflicting request -- the record's metric name would
            # not be the one the caller's pipeline expects.
            ap.error(
                f"--serve conflicts with --workload {args.workload}"
            )
        args.workload = "serve"
    elif args.workload is None:
        args.workload = "llama"
    if args.workload == "pp":
        args.workload = "llama-pp"  # documented alias
    if args.pp_runtime == "mpmd":
        # The misplaced-flag discipline: the MPMD runtime only exists
        # on the pipeline workload, runs its own gpipe-ordered
        # dispatch (the schedule flags parameterize the SPMD tick
        # programs), and has its own backward (per-stage vjp).
        if args.workload != "llama-pp":
            ap.error(
                "--pp-runtime mpmd is only consumed by --workload "
                f"llama-pp/pp; --workload {args.workload} would "
                "silently run without it"
            )
        if args.pp_schedule != "gpipe":
            ap.error(
                f"--pp-runtime mpmd dispatches its own gpipe-ordered "
                "schedule; pass --pp-schedule gpipe explicitly "
                f"(got {args.pp_schedule!r} -- a 1f1b/interleaved "
                "row label would misdescribe what ran)"
            )
        if args.pp_backward != "remat":
            ap.error(
                "--pp-runtime mpmd does not consume --pp-backward "
                "(its per-stage backward is an explicit vjp program)"
            )
    if args.loadgen_scenario is not None and args.workload != "loadgen":
        # Same discipline as the --comm-mode guard below: a scenario
        # flag the selected workload never consumes must be a CLI
        # error, not a silently-plain run recorded as the scenario.
        ap.error(
            f"--loadgen-scenario {args.loadgen_scenario} is only "
            f"consumed by --workload loadgen; --workload "
            f"{args.workload} would silently ignore it"
        )
    if args.serve_disagg and args.workload != "serve":
        # The --comm-mode guard discipline: a tier-split flag on a
        # workload that never consumes it must be a CLI error, not a
        # silently single-tier row labeled disaggregated.
        ap.error(
            "--serve-disagg is only consumed by --workload serve; "
            f"--workload {args.workload} would silently run "
            "single-tier"
        )
    if args.serve_paged and args.workload not in ("serve", "loadgen"):
        # Same discipline: a cache-layout flag the workload never
        # consumes must be a CLI error, not a slab row labeled paged.
        ap.error(
            "--serve-paged is only consumed by --workload "
            f"serve/loadgen; --workload {args.workload} would "
            "silently run the slab cache"
        )
    if not args.serve_paged:
        for flag, val in (
            ("--serve-block-size", args.serve_block_size),
            ("--serve-kv-blocks", args.serve_kv_blocks),
            ("--serve-host-blocks", args.serve_host_blocks),
            ("--serve-prefill-chunk", args.serve_prefill_chunk),
            ("--serve-kernel", args.serve_kernel),
            ("--serve-kv-quant", args.serve_kv_quant),
        ):
            if val is not None:
                ap.error(
                    f"{flag} is only consumed together with "
                    "--serve-paged"
                )
    if args.serve_host_blocks is not None and args.serve_host_blocks < 2:
        # server.py's guard, mirrored: the tier reserves host slot 0
        # as scratch, so 1 slot would be a tier that can never hold a
        # page -- a parse error, not a row labeled tiered that never
        # spilled.
        ap.error(
            f"--serve-host-blocks {args.serve_host_blocks} must be "
            ">= 2 (one scratch slot plus at least one page)"
        )
    if args.serve_fleet is not None:
        # The misplaced-flag discipline, fleet edition: a fleet flag
        # on a workload/layout that cannot consume it must be a CLI
        # error, not a single-engine row banked under a fleet label.
        if args.serve_fleet < 1:
            ap.error(f"--serve-fleet {args.serve_fleet} must be >= 1")
        if args.workload != "loadgen":
            ap.error(
                "--serve-fleet is only consumed by --workload "
                f"loadgen; --workload {args.workload} would silently "
                "run a single engine"
            )
        if not args.serve_paged or not args.serve_prefill_chunk:
            ap.error(
                "--serve-fleet needs --serve-paged "
                "--serve-prefill-chunk N (replicas are paged "
                "engines; redispatch replays prompt + committed "
                "tokens, which can exceed any single bucket)"
            )
        if args.serve_spec != "off":
            ap.error(
                "--serve-fleet does not consume --serve-spec"
            )
        if args.fleet_min is not None and not \
                1 <= args.fleet_min <= args.serve_fleet:
            ap.error(
                f"--fleet-min {args.fleet_min} must be in "
                f"[1, --serve-fleet {args.serve_fleet}]"
            )
    else:
        for flag, val in (
            ("--fleet-swap-at", args.fleet_swap_at),
            ("--fleet-router", args.fleet_router),
            ("--fleet-min", args.fleet_min),
        ):
            if val is not None:
                ap.error(
                    f"{flag} is only consumed together with "
                    "--serve-fleet"
                )
    if args.serve_spec != "off":
        # The misplaced-flag discipline, speculative edition: a spec
        # flag on a workload (or cache layout) that cannot consume it
        # is a parse error, not a greedy row wearing a spec label.
        if args.workload not in ("serve", "loadgen"):
            ap.error(
                "--serve-spec is only consumed by --workload "
                f"serve/loadgen; --workload {args.workload} would "
                "silently run greedy"
            )
        if not args.serve_paged:
            ap.error(
                "--serve-spec rides the paged engine; add "
                "--serve-paged"
            )
        if args.serve_disagg:
            ap.error(
                "--serve-spec is not consumed by --serve-disagg "
                "(the verify program is a single-mesh paged program)"
            )
        if args.serve_kv_quant == "int8":
            # server.py's guard, mirrored: verify would replay
            # drafted positions against requantized pages and drift
            # from the greedy oracle.
            ap.error(
                "--serve-spec is not consumed with --serve-kv-quant "
                "int8 (verify replays positions the draft loop "
                "already requantized)"
            )
        if args.spec_k is not None and args.spec_k < 1:
            # server.py's guard, mirrored: `or`-defaulting would
            # silently coerce 0 to 4 and bank a row labeled spec_k=4.
            ap.error(f"--spec-k {args.spec_k} must be >= 1")
    else:
        for flag, val in (
            ("--spec-k", args.spec_k),
            ("--serve-draft-ckpt", args.serve_draft_ckpt),
        ):
            if val is not None:
                ap.error(
                    f"{flag} is only consumed together with "
                    "--serve-spec"
                )
    if args.serve_draft_ckpt is not None \
            and args.serve_spec != "draft":
        ap.error(
            "--serve-draft-ckpt is only consumed together with "
            "--serve-spec draft"
        )
    if args.serve_model != "bench" and args.workload != "loadgen":
        # The dev model is ONLY legal where the virtual clock makes
        # the row model-independent; a tiny-model wall-clock serve row
        # would be an incomparable number wearing the bench label.
        ap.error(
            "--serve-model tiny is only consumed by --workload "
            f"loadgen (virtual-clock rows); --workload "
            f"{args.workload} measures wall clock on the bench model"
        )
    if args.guard_mode != "off" and (
        args.all or args.workload not in ("llama", "llama-long")
    ):
        # The --comm-mode guard discipline: a guard flag on a workload
        # that never consumes it must be a CLI error, not a row
        # labeled guarded that silently ran unguarded.
        ap.error(
            f"--guard-mode {args.guard_mode} is only consumed by the "
            "llama/llama-long workloads; "
            + ("--all runs fixed rows"
               if args.all else
               f"--workload {args.workload} would silently run "
               "unguarded")
        )
    if args.comm_mode != "flat" and (
        args.all or args.workload not in ("llama", "llama-long")
    ):
        # Only the llama/llama-long workloads consume the gradient-sync
        # knob; running any other with it silently flat would emit rows
        # a comm-mode sweep cannot tell apart from the real thing.
        # (comm_mode="auto" without a gradient-sync-consuming workload
        # is the same lie one indirection later: there is no sync for
        # the planner to plan.)
        ap.error(
            f"--comm-mode {args.comm_mode} is only consumed by the "
            "llama/llama-long workloads; "
            + ("--all runs its own fixed comm-mode row"
               if args.all else
               f"--workload {args.workload} would silently run flat")
        )
    if args.workload != "elastic":
        # The misplaced-flag discipline, elastic edition: a morph
        # schedule on a workload that never morphs must be a CLI
        # error, not a fixed-topology row wearing a storm label.
        for flag, val in (
            ("--elastic-shrink-at", args.elastic_shrink_at),
            ("--elastic-grow-at", args.elastic_grow_at),
        ):
            if val is not None:
                ap.error(
                    f"{flag} is only consumed by --workload elastic; "
                    f"--workload {args.workload} would silently run "
                    "fixed-topology"
                )
    else:
        shrink = (
            args.elastic_shrink_at
            if args.elastic_shrink_at is not None else 2
        )
        grow = (
            args.elastic_grow_at
            if args.elastic_grow_at is not None else 4
        )
        if not 0 < shrink < grow:
            ap.error(
                f"--elastic-shrink-at {shrink} must be > 0 and < "
                f"--elastic-grow-at {grow} (the storm is shrink -> "
                "train -> grow -> train)"
            )
        if grow >= args.steps:
            ap.error(
                f"--elastic-grow-at {grow} needs --steps > {grow}: "
                "the grow morph would never fire and the chaos "
                "schedule would fail its vacuous-pass guard"
            )
        args.elastic_shrink_at, args.elastic_grow_at = shrink, grow
    if args.comm_table is not None and args.comm_mode != "auto":
        # Planner flags on non-auto modes: the --comm-mode guard
        # discipline. A table nothing consults must be a CLI error,
        # not a row that silently ignored the measurements it names.
        ap.error(
            f"--comm-table {args.comm_table} is only consumed by "
            f"--comm-mode auto; --comm-mode {args.comm_mode} never "
            "consults the planner"
        )
    if args.supervise:
        from tpu_hpc.resilience.supervisor import (
            run_supervised,
            strip_flag,
        )

        # Strip the flag (both "--supervise N" and "--supervise=N"):
        # the supervised child must run the bench itself.
        child_args = strip_flag(
            list(sys.argv[1:] if argv is None else argv), "--supervise"
        )
        return run_supervised(
            [sys.executable, os.path.abspath(__file__), *child_args],
            max_restarts=args.supervise,
            log_dir=os.environ.get("TPU_HPC_SUPERVISE_LOGS", "bench_logs"),
        )
    devinfo = None
    if os.environ.get("TPU_HPC_BENCH_NO_PROBE") != "1":
        # Children of --all skip this: the parent already probed, and
        # each probe is a full (discarded) backend bring-up.
        devinfo = probe_backend()
        if devinfo is None:
            print(
                "bench: accelerator backend unavailable (tunnel/pool "
                "outage?) -- aborting instead of hanging",
                file=sys.stderr,
            )
            return 3
    if args.all:
        return run_all(args.out, args.steps, devinfo=devinfo)
    if args.workload == "llama":
        batch, accum = resolve_batch_accum(
            args.batch, args.grad_accum_steps, microbatch=4
        )
        rec = bench_llama(
            args.steps, args.remat, batch, args.attn,
            args.block_q, args.block_k, seq_len=args.seq_len or 2048,
            grad_accum_steps=accum,
            moments_dtype=args.moments_dtype,
            block_q_bwd=args.block_q_bwd, block_k_bwd=args.block_k_bwd,
            comm_mode=args.comm_mode,
            guard_mode=args.guard_mode,
            comm_table=args.comm_table,
        )
    elif args.workload == "llama-sp":
        batch, accum = resolve_batch_accum(
            args.batch, args.grad_accum_steps, microbatch=4
        )
        rec = bench_llama_sp(
            args.steps, batch, args.sp_mode,
            grad_accum_steps=accum, moments_dtype=args.moments_dtype,
        )
    elif args.workload == "llama-pp" and args.pp_runtime == "mpmd":
        rec = bench_llama_pp_mpmd(
            args.steps, args.pp_microbatches,
            microbatch_size=args.pp_microbatch_size, attn=args.attn,
            block_q=args.block_q, block_k=args.block_k,
            block_q_bwd=args.block_q_bwd, block_k_bwd=args.block_k_bwd,
            model=args.pp_model,
        )
    elif args.workload == "llama-pp":
        rec = bench_llama_pp(
            args.steps, args.pp_schedule, args.pp_microbatches,
            microbatch_size=args.pp_microbatch_size, attn=args.attn,
            block_q=args.block_q, block_k=args.block_k,
            block_q_bwd=args.block_q_bwd, block_k_bwd=args.block_k_bwd,
            grad_accum_steps=args.grad_accum_steps or 1,
            backward=args.pp_backward,
            model=args.pp_model,
        )
    elif args.workload == "llama-long":
        batch, accum = resolve_batch_accum(
            args.batch, args.grad_accum_steps, microbatch=1
        )
        rec = bench_llama_long(
            args.steps, seq_len=args.seq_len or 8192,
            batch=batch, remat=args.remat,
            grad_accum_steps=accum,
            moments_dtype=args.moments_dtype,
            block_q=args.block_q, block_k=args.block_k,
            block_q_bwd=args.block_q_bwd, block_k_bwd=args.block_k_bwd,
            comm_mode=args.comm_mode,
            guard_mode=args.guard_mode,
            comm_table=args.comm_table,
        )
    elif args.workload == "serve":
        rec = bench_serve(
            requests=args.serve_requests, slots=args.serve_slots,
            max_new=args.serve_max_new, disagg=args.serve_disagg,
            paged=args.serve_paged,
            block_size=args.serve_block_size,
            kv_blocks=args.serve_kv_blocks,
            prefill_chunk=args.serve_prefill_chunk,
            host_blocks=args.serve_host_blocks,
            kernel=args.serve_kernel,
            kv_quant=args.serve_kv_quant,
            spec=args.serve_spec, spec_k=args.spec_k,
            draft_ckpt=args.serve_draft_ckpt,
        )
    elif args.workload == "loadgen":
        rec = bench_loadgen(
            scenario=args.loadgen_scenario or "multi_tenant",
            requests=args.serve_requests * 2,
            slots=args.serve_slots,
            max_new=args.serve_max_new,
            paged=args.serve_paged,
            block_size=args.serve_block_size,
            kv_blocks=args.serve_kv_blocks,
            prefill_chunk=args.serve_prefill_chunk,
            host_blocks=args.serve_host_blocks,
            kernel=args.serve_kernel,
            kv_quant=args.serve_kv_quant,
            model=args.serve_model,
            spec=args.serve_spec, spec_k=args.spec_k,
            draft_ckpt=args.serve_draft_ckpt,
            fleet=args.serve_fleet or 0,
            fleet_min=args.fleet_min or 1,
            fleet_swap_at=args.fleet_swap_at,
            fleet_router=args.fleet_router or "affinity",
        )
    elif args.workload == "elastic":
        rec = bench_elastic(
            args.steps, shrink_at=args.elastic_shrink_at,
            grow_at=args.elastic_grow_at,
        )
    else:
        rec = bench_unet(args.steps)
    # Every bench line is a schema-stamped ``bench`` event -- the same
    # record discipline the train/serve JSONL sinks follow, so one
    # validator (tpu_hpc.obs.schema) and one report cover all three.
    from tpu_hpc.obs import get_bus

    print(json.dumps(get_bus().emit_record({"event": "bench", **rec})))
    return 0


if __name__ == "__main__":
    sys.exit(main())
