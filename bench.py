"""Headline benchmark: prints ONE JSON line for the driver.

Current flagship metric (round 1): SimpleUNet DP training throughput
(samples/s) on the available chip(s) -- the reference's own
instrumented workload (multinode_ddp_unet.py:348-397). Will move to
Llama-2 tokens/sec/chip + MFU once the hybrid recipe lands.

vs_baseline: the reference publishes no measured throughput
(BASELINE.md), so vs_baseline is reported as 1.0 by convention when no
comparable number exists.
"""
import json
import sys


def main() -> int:
    import jax

    from tpu_hpc.config import TrainingConfig
    from tpu_hpc.models import datasets, losses
    from tpu_hpc.models.unet import UNetConfig, apply_unet, init_unet
    from tpu_hpc.parallel import dp
    from tpu_hpc.runtime import MeshSpec, build_mesh, init_distributed
    from tpu_hpc.train import Trainer

    import jax.numpy as jnp

    init_distributed(verbose=False)
    # epochs=2: epoch 0 absorbs compilation, epoch 1 is the measurement
    # (same reason the reference skips the first batch in its
    # throughput accounting, multinode_ddp_unet.py:363).
    cfg = TrainingConfig(
        epochs=2,
        steps_per_epoch=20,
        global_batch_size=8 * jax.device_count(),
        learning_rate=1e-3,
    )
    mesh = build_mesh(MeshSpec(axes={"data": -1}))
    ds = datasets.ERA5Synthetic()
    model_cfg = UNetConfig(
        in_channels=ds.channels, out_channels=ds.channels,
        dtype=jnp.bfloat16,
    )
    params, model_state = init_unet(
        jax.random.key(0), model_cfg, ds.sample_shape
    )

    def forward(p, ms, batch, step_rng):
        x, y = batch
        pred, new_ms = apply_unet(p, ms, x, model_cfg, train=True)
        return losses.lat_weighted_mse(pred, y), new_ms, {}

    trainer = Trainer(
        cfg, mesh, forward, params, model_state,
        param_pspecs=dp.param_pspecs(params),
    )
    result = trainer.fit(ds)
    summary = result["epochs"][-1]
    print(
        json.dumps(
            {
                "metric": "unet_dp_train_throughput",
                "value": round(summary["items_per_s"], 2),
                "unit": "samples/s",
                "vs_baseline": 1.0,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
