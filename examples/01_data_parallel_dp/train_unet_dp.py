"""Data-parallel U-Net training on ERA5-like synthetic weather data.

Parity with /root/reference/scripts/01_data_parallel_ddp/
multinode_ddp_unet.py: same workload (synthetic ERA5 grids, SimpleUNet,
latitude-weighted MSE), same instrumentation (per-epoch global and
per-device samples/s), same config surface -- but the DDP wrapper +
DistributedSampler + gradient-bucket machinery is replaced by one
sharding plan: batch split over the ``data`` mesh axis, params
replicated; XLA emits the fused gradient all-reduce.

Run (single host, all chips):   python train_unet_dp.py --epochs 3
Multi-host TPU pod:             see launch/ for the pod launcher.
"""
import os as _os
import sys as _sys

# Run directly from a source checkout without installing: put the repo
# root on sys.path (the reference uses the same pattern, e.g.
# resnet_fsdp_training.py:27).
_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
)

import sys

from tpu_hpc.config import TrainingConfig
from tpu_hpc.logging_ import get_logger
from tpu_hpc.models import datasets, losses
from tpu_hpc.models.unet import (
    UNetConfig, apply_unet, init_unet, make_eval_forward,
)
from tpu_hpc.parallel import dp
from tpu_hpc.runtime import MeshSpec, build_mesh, init_distributed
from tpu_hpc.train import Trainer

import jax


def main(argv=None) -> int:
    cfg = TrainingConfig.from_args(argv)
    logger = get_logger()
    init_distributed()
    mesh = build_mesh(MeshSpec(axes={"data": cfg.data_parallel}))
    logger.info("mesh: %s", dict(mesh.shape))

    ds = datasets.ERA5Synthetic()
    param_dtype, compute_dtype = cfg.jax_dtypes()
    model_cfg = UNetConfig(
        in_channels=ds.channels, out_channels=ds.channels,
        dtype=compute_dtype, param_dtype=param_dtype,
    )
    params, model_state = init_unet(
        jax.random.key(cfg.seed), model_cfg, ds.sample_shape
    )

    def forward(p, ms, batch, step_rng):
        x, y = batch
        pred, new_ms = apply_unet(p, ms, x, model_cfg, train=True)
        return losses.lat_weighted_mse(pred, y), new_ms, {}

    ckpt_mgr = None
    if cfg.save_every:
        from tpu_hpc.ckpt import CheckpointManager

        ckpt_mgr = CheckpointManager(cfg.checkpoint_dir)

    trainer = Trainer(
        cfg, mesh, forward, params, model_state,
        param_pspecs=dp.param_pspecs(params),
        batch_pspec=dp.batch_pspec(),
        checkpoint_manager=ckpt_mgr,
        # Inference-mode eval (stored BatchNorm stats), so evaluate()
        # reports true test loss -- and the stateful-model warning
        # stays out of the logs.
        eval_forward=make_eval_forward(model_cfg),
    )
    result = trainer.fit(ds)
    if ckpt_mgr is not None:
        ckpt_mgr.wait()
    if not result["epochs"]:
        logger.info("nothing to do: checkpoint already at %d epochs", cfg.epochs)
        return 0
    summary = result["epochs"][-1]
    logger.info(
        "run summary | final loss %.5f | %.1f samples/s global | "
        "%.1f samples/s/device",
        result["final_loss"],
        summary["items_per_s"],
        summary["items_per_s_per_device"],
    )
    # Exit-code contract (docs/guide/resilience.md): a preemption
    # snapshot exits EXIT_RESUMABLE so the supervisor/launcher knows
    # to relaunch-and-resume rather than count a failure.
    from tpu_hpc.resilience import exit_code_for

    return exit_code_for(result.get("preempted", False))


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
