"""Teaching example: distributed input pipelines on TPU.

Parity with /root/reference/scripts/01_data_parallel_ddp/
distributed_dataloader.py (302 LoC): that script teaches the GPU input
stack -- DistributedSampler restricting each rank to an exclusive
subset, DataLoader(num_workers=4), sampler.set_epoch(epoch) for
per-epoch reshuffling, and the "do NOT pass shuffle=True with a
sampler" footgun. This example teaches the same concerns the TPU way,
where *there is no sampler object*: data placement is a sharding, and
shard exclusivity is arithmetic on (step, host) indices.

The three lessons:

1. **DistributedSampler -> NamedSharding.** A "global batch" is one
   jax.Array sharded over the ``data`` mesh axis. Each device holds
   batch_size/n_devices rows; handing the model a globally-sharded
   array IS the exclusive-subset guarantee the sampler provided.

2. **set_epoch(epoch) -> fold_in(seed, step).** The reference reshuffles
   by reseeding a stateful sampler each epoch. Here batches are pure
   functions of (seed, step): ``batch_at(step)`` folds the step into
   the RNG key, so every epoch sees fresh data, every host computes the
   same global batch definition with no coordination, and resume from a
   checkpoint replays the exact stream from the stored step.

3. **DataLoader(num_workers=4) -> three feeding modes.**
   a. *On-device traced generation* (synthetic/benchmark data): the
      generator is jit-traceable, so the whole epoch fuses into one
      lax.scan dispatch -- zero host involvement (models/datasets.py).
   b. *Host feed*: each process builds only its LOCAL shard as numpy
      and assembles the global array with
      ``jax.make_array_from_process_local_data`` -- the multi-host
      equivalent of "each rank loads its subset".
   c. *Native prefetch* (tpu_hpc/native): C++ worker threads keep
      batches ahead of the loop, the DataLoader(num_workers=N) role.

Run (any chip count, or CPU-sim):
    python input_pipeline.py --epochs 2
"""
import os as _os
import sys as _sys

# Run directly from a source checkout without installing: put the repo
# root on sys.path (the reference uses the same pattern, e.g.
# resnet_fsdp_training.py:27).
_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
)

import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_hpc.config import TrainingConfig
from tpu_hpc.logging_ import get_logger
from tpu_hpc.runtime import MeshSpec, build_mesh, init_distributed


# ---------------------------------------------------------------------------
# Mode (b): the host-feed dataset. Each process materializes ONLY its
# local rows -- the DistributedSampler exclusive-subset contract.
# ---------------------------------------------------------------------------

class HostFedToyDataset:
    """Toy classification pairs (parity: SimpleDataset,
    distributed_dataloader.py:143-156), fed from host numpy.

    Deterministic in (seed, step): the permutation that the reference
    derives from ``sampler.set_epoch`` is here a hash of the step --
    no state, no epoch bookkeeping, no cross-host coordination.
    """

    def __init__(self, mesh, input_dim=10, n_classes=2, seed=0):
        self.mesh = mesh
        self.input_dim = input_dim
        self.n_classes = n_classes
        self.seed = seed
        self.sharding = NamedSharding(mesh, P("data"))

    def _local_rows(self, step: int, global_batch: int):
        """Rows [lo, hi) of global batch ``step`` owned by this host."""
        n_proc = jax.process_count()
        per_host = global_batch // n_proc
        lo = jax.process_index() * per_host
        # Row r of step s is generated from an independent stream --
        # any host could build any row; each builds only its own.
        rng = np.random.default_rng(
            [self.seed, step, jax.process_index()]
        )
        x = rng.standard_normal((per_host, self.input_dim), np.float32)
        w_true = np.linspace(-1, 1, self.input_dim, dtype=np.float32)
        y = (x @ w_true > 0).astype(np.int32)
        return x, y

    def batch_at(self, step: int, global_batch: int):
        x_loc, y_loc = self._local_rows(step, global_batch)
        # Assemble the global sharded array from per-process shards:
        # the TPU equivalent of "each rank's DataLoader yields its
        # subset". On one process this is just a sharded device_put.
        x = jax.make_array_from_process_local_data(self.sharding, x_loc)
        y = jax.make_array_from_process_local_data(self.sharding, y_loc)
        return x, y


# ---------------------------------------------------------------------------
# Mode (a): the same data as an on-device traced generator -- the fast
# path for synthetic data (the Trainer scans the whole epoch on-device).
# ---------------------------------------------------------------------------

class TracedToyDataset:
    def __init__(self, input_dim=10, seed=0):
        self.input_dim = input_dim
        self.seed = seed

    def traced_batch(self, step, global_batch: int):
        key = jax.random.fold_in(jax.random.key(self.seed), step)
        x = jax.random.normal(key, (global_batch, self.input_dim))
        w_true = jnp.linspace(-1, 1, self.input_dim)
        y = (x @ w_true > 0).astype(jnp.int32)
        return x, y

    def batch_at(self, step, global_batch: int):
        return self.traced_batch(jnp.asarray(step), global_batch)


def main(argv=None) -> int:
    cfg = TrainingConfig.from_args(argv)
    logger = get_logger()
    init_distributed()
    mesh = build_mesh(MeshSpec(axes={"data": -1}))
    n_dev = mesh.size
    gb = cfg.global_batch_size

    if jax.process_index() == 0:
        logger.info("mesh: %s over %d process(es)", dict(mesh.shape),
                    jax.process_count())
        logger.info("global batch %d -> %d rows/device", gb, gb // n_dev)

    ds = HostFedToyDataset(mesh, seed=cfg.seed)

    # A global batch is ONE array; its sharding is the "sampler".
    x0, y0 = ds.batch_at(0, gb)
    assert x0.shape == (gb, ds.input_dim)  # global view
    local = x0.addressable_shards
    if jax.process_index() == 0:
        logger.info(
            "lesson 1: x is globally [%d, %d]; this host holds %d "
            "shard(s) of %s rows each (exclusive subsets, no sampler)",
            *x0.shape, len(local), local[0].data.shape[0],
        )

    # Reshuffling: different step -> different rows, deterministically.
    x1, _ = ds.batch_at(1, gb)
    assert not np.allclose(np.asarray(x0), np.asarray(x1))
    xr, _ = ds.batch_at(0, gb)
    np.testing.assert_array_equal(np.asarray(x0), np.asarray(xr))
    if jax.process_index() == 0:
        logger.info(
            "lesson 2: batch_at(step) is pure -- step 0 replayed "
            "byte-identically (resume), step 1 fresh (reshuffle)"
        )

    # Train a toy MLP both ways and compare the loops.
    from tpu_hpc.parallel import dp
    from tpu_hpc.train import Trainer

    k0, k1 = jax.random.split(jax.random.key(cfg.seed))
    params = {
        "w1": jax.random.normal(k0, (ds.input_dim, 64)) * 0.1,
        "w2": jax.random.normal(k1, (64, ds.n_classes)) * 0.1,
    }

    def forward(p, ms, batch, rng):
        x, y = batch
        logits = jax.nn.relu(x @ p["w1"]) @ p["w2"]
        loss = jnp.mean(
            -jax.nn.log_softmax(logits)[jnp.arange(x.shape[0]), y]
        )
        acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
        return loss, ms, {"accuracy": acc}

    # Host-fed loop: one device_put + one step dispatch per batch.
    tr = Trainer(cfg, mesh, forward, params,
                 param_pspecs=dp.param_pspecs(params))
    host_fed = tr.fit(ds)

    # Traced loop: whole epoch is one dispatch (mode (a)).
    tr2 = Trainer(cfg, mesh, forward, params,
                  param_pspecs=dp.param_pspecs(params))
    traced = tr2.fit(TracedToyDataset(seed=cfg.seed))

    if jax.process_index() == 0:
        logger.info(
            "lesson 3: host-fed %.0f items/s vs on-device traced "
            "%.0f items/s (same model, same arithmetic -- the input "
            "path is the difference; use mode (b/c) only when the "
            "host must produce the data)",
            host_fed["epochs"][-1]["items_per_s"],
            traced["epochs"][-1]["items_per_s"],
        )
        logger.info("done: final losses %.4f / %.4f",
                    host_fed["final_loss"], traced["final_loss"])
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
