"""Domain-parallel training: spatially-sharded convs with halo exchange.

Implements the strategy directory the reference advertises but does not
ship (/root/reference/docs/guide/10_domain_parallel.md:156-172 lists
scripts/07_domain_parallel_shardtensor/*; SURVEY.md 0 confirms it is
absent). Covers all four advertised scripts in one runnable file:

  * ``--demo``  -- why naive spatial splitting fails (boundary
    corruption at tile seams) and how the halo exchange fixes it
    (doc :69-103), printed as max-abs-error vs the single-device conv.
  * default     -- domain-parallel training of a conv stack on
    ERA5-like weather grids over a (data, spatial) mesh: latitude bands
    sharded across the ``spatial`` axis (neighbor ppermute halos over
    ICI), batch across ``data`` -- the domain+DP composition of the
    doc's final script. Activation memory per device drops by the
    spatial degree: the SciML activation-wall motivation (:13-32).

Run (8 simulated devices):
  TPU_HPC_SIM_DEVICES=8 python train_domain_parallel.py --spatial-parallel 4
"""
import os as _os
import sys as _sys

# Run directly from a source checkout without installing: put the repo
# root on sys.path (the reference uses the same pattern, e.g.
# resnet_fsdp_training.py:27).
_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
)

import argparse
import sys

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpu_hpc.config import TrainingConfig
from tpu_hpc.logging_ import get_logger
from tpu_hpc.models import datasets, losses
from tpu_hpc.parallel import domain
from tpu_hpc.runtime import MeshSpec, build_mesh, init_distributed
from tpu_hpc.train import Trainer


def init_conv_stack(rng, channels, hidden, n_layers):
    """[3,3,.,.] HWIO kernels + biases; last layer maps back to
    ``channels`` (the regression head of the reference's U-Net demo)."""
    params = {}
    dims = [channels] + [hidden] * (n_layers - 1) + [channels]
    for i, (cin, cout) in enumerate(zip(dims[:-1], dims[1:])):
        rng, k = jax.random.split(rng)
        std = (2.0 / (9 * cin)) ** 0.5
        params[f"w{i}"] = std * jax.random.normal(
            k, (3, 3, cin, cout), jnp.float32
        )
        params[f"b{i}"] = jnp.zeros((cout,), jnp.float32)
    return params


def conv_stack(axis_name, params, x):
    """The domain program: every conv re-exchanges halos first."""
    n = len(params) // 2
    h = x
    for i in range(n):
        h = domain.halo_conv2d(
            h, params[f"w{i}"], params[f"b{i}"], axis_name=axis_name
        )
        if i < n - 1:
            h = jax.nn.relu(h)
    return h


def run_demo(mesh, logger) -> None:
    x = jax.random.normal(jax.random.key(0), (2, 32, 16, 3))
    kernel = 0.1 * jax.random.normal(jax.random.key(1), (3, 3, 3, 3))
    want = jax.lax.conv_general_dilated(
        x, kernel, (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    naive = domain.domain_parallel(
        lambda ax, p, t: domain.naive_split_conv2d(t, p, axis_name=ax),
        mesh,
    )(kernel, x)
    halo = domain.domain_parallel(
        lambda ax, p, t: domain.halo_conv2d(t, p, axis_name=ax),
        mesh,
    )(kernel, x)
    err_naive = float(jnp.abs(naive - want).max())
    err_halo = float(jnp.abs(halo - want).max())
    logger.info(
        "naive split: max |err| vs single-device = %.2e  <- seam rows "
        "corrupted (every tile zero-padded its own borders)", err_naive,
    )
    logger.info(
        "halo exchange: max |err| = %.2e  <- exact (neighbors' edge "
        "rows exchanged via ppermute before each conv)", err_halo,
    )


def main(argv=None) -> int:
    cfg = TrainingConfig.from_args(argv)
    extra = argparse.ArgumentParser(add_help=False)
    extra.add_argument("--spatial-parallel", type=int, default=4)
    extra.add_argument("--hidden", type=int, default=64)
    extra.add_argument("--layers", type=int, default=3)
    extra.add_argument("--lat", type=int, default=180)
    extra.add_argument("--lon", type=int, default=360)
    extra.add_argument("--demo", action="store_true")
    extra.add_argument(
        "--fsdp", action="store_true",
        help="also ZeRO-3-shard the conv params over 'data' (the "
        "domain+FSDP composition, 10_domain_parallel.md:156-172)",
    )
    ns, _ = extra.parse_known_args(argv)

    logger = get_logger()
    init_distributed()
    spatial = min(ns.spatial_parallel, jax.device_count())
    while jax.device_count() % spatial:  # degree must divide devices
        spatial -= 1
    mesh = build_mesh(MeshSpec(axes={"data": -1, "spatial": spatial}))
    logger.info(
        "mesh: %s (latitude bands on 'spatial', batch on 'data')",
        dict(mesh.shape),
    )
    if ns.demo:
        run_demo(mesh, logger)
        return 0

    # lat=180 default: divisible latitude bands (the odd-grid 181 case
    # stays the U-Net's job; domain tiles must divide evenly).
    ds = datasets.ERA5Synthetic(lat=ns.lat, lon=ns.lon)
    params = init_conv_stack(
        jax.random.key(cfg.seed), ds.channels, ns.hidden, ns.layers
    )
    model = domain.domain_parallel(conv_stack, mesh)

    def forward(p, ms, batch, step_rng):
        x, y = batch
        pred = model(p, x)
        return losses.lat_weighted_mse(pred, y), ms, {}

    specs = None
    if ns.fsdp:
        from tpu_hpc.parallel import fsdp

        # Conv stacks are small; min_size=1 shards every kernel whose
        # channel dim divides -- the point here is the composition
        # (halo ppermute over 'spatial' + FSDP all-gather over 'data'
        # in one step), not comm savings at this toy size.
        specs = fsdp.param_pspecs(
            params, axis="data", axis_size=mesh.shape["data"], min_size=1
        )
    trainer = Trainer(
        cfg, mesh, forward, params,
        batch_pspec=P("data", "spatial"),
        param_pspecs=specs,
    )
    result = trainer.fit(ds)
    summary = result["epochs"][-1]
    logger.info(
        "run summary | final loss %.5f | %.1f samples/s global | "
        "lat %d split %d-way -> %d rows/device held",
        result["final_loss"],
        summary["items_per_s"],
        ds.lat, spatial, ds.lat // spatial,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
