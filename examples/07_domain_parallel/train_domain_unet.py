"""Domain-parallel U-Net training: the FULL encoder/decoder spatially
sharded.

The reference documents domain parallelism for exactly this model
class (/root/reference/docs/guide/10_domain_parallel.md:113-149; its
U-Net, multinode_ddp_unet.py:171-214, is the realistic SciML shape
with strided downsampling). This script trains ``models/unet.py``'s
architecture under a (data x spatial) mesh via
``tpu_hpc.parallel.domain_unet``: 3x3 convs with 1-row halos,
halo-free 2x2 max pools (windows tile each shard), edge-clamped
bilinear 2x upsampling, and BatchNorm moments psum-reduced over both
mesh axes. The single-device ``apply_unet`` is the exact oracle for
this program (tests/test_domain_unet.py).

Constraint: lat must divide by spatial * 4 (two pool levels of whole
windows per device) -- the default grid is 32 x 64 for the 4-way
spatial split; the production 181-row ERA5 grid belongs on the
batch-parallel path (examples/02) or needs re-tiling.

Run (8 simulated devices):
  TPU_HPC_SIM_DEVICES=8 python train_domain_unet.py --spatial-parallel 4
"""
import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
)

import argparse

import jax
from jax.sharding import PartitionSpec as P

from tpu_hpc.config import TrainingConfig
from tpu_hpc.logging_ import get_logger
from tpu_hpc.models import datasets
from tpu_hpc.models.unet import UNetConfig, init_unet
from tpu_hpc.parallel import domain_unet
from tpu_hpc.runtime import MeshSpec, build_mesh, init_distributed
from tpu_hpc.train import Trainer


def main(argv=None) -> int:
    cfg = TrainingConfig.from_args(argv)
    extra = argparse.ArgumentParser(add_help=False)
    extra.add_argument("--spatial-parallel", type=int, default=0,
                       help="latitude-band shards (default: all "
                       "devices not taken by --data-parallel)")
    extra.add_argument("--lat", type=int, default=32)
    extra.add_argument("--lon", type=int, default=64)
    extra.add_argument("--base-features", type=int, default=16)
    own, _ = extra.parse_known_args(argv)

    logger = get_logger()
    init_distributed()
    n = jax.device_count()
    dp = cfg.data_parallel if cfg.data_parallel > 0 else 0
    spatial = own.spatial_parallel
    if not spatial:
        spatial = n // dp if dp else max(n // 2, 1)
    if not dp:
        dp = n // spatial
    if dp * spatial != n or own.lat % (spatial * 4):
        raise SystemExit(
            f"need data({dp}) x spatial({spatial}) == devices({n}) and "
            f"lat({own.lat}) % (spatial*4) == 0"
        )
    mesh = build_mesh(MeshSpec(axes={"data": dp, "spatial": spatial}))
    ds = datasets.ERA5Synthetic(
        lat=own.lat, lon=own.lon, n_vars=1, n_levels=3
    )
    param_dtype, compute_dtype = cfg.jax_dtypes()
    model_cfg = UNetConfig(
        in_channels=ds.channels, out_channels=ds.channels,
        base_features=own.base_features,
        dtype=compute_dtype, param_dtype=param_dtype,
    )
    params, model_state = init_unet(
        jax.random.key(cfg.seed), model_cfg, ds.sample_shape
    )
    n_params = sum(p.size for p in jax.tree.leaves(params))
    logger.info(
        "domain U-Net: %.2fM params | mesh %s | tile %dx%d of %dx%d",
        n_params / 1e6, dict(mesh.shape),
        own.lat // spatial, own.lon, own.lat, own.lon,
    )
    trainer = Trainer(
        cfg, mesh,
        domain_unet.make_forward(mesh, model_cfg),
        params, model_state,
        batch_pspec=P("data", "spatial"),
    )
    result = trainer.fit(ds)
    summary = result["epochs"][-1]
    logger.info(
        "run summary | final loss %.5f | %.1f samples/s global",
        result["final_loss"], summary["items_per_s"],
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
