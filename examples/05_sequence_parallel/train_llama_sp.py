"""Sequence/context-parallel Llama training: Ring Attention or Ulysses.

Implements the strategy directory the reference advertises but does not
ship (/root/reference/docs/guide/08_sequence_parallel.md:161-185 lists
scripts/05_sequence_parallel_sp/*; SURVEY.md 0 confirms it is absent).
Both documented designs are runnable here:

  * ``--attn ring``    -- Ring Attention: K/V chunks rotate around the
    ``seq`` mesh axis via ppermute (the ICI torus IS the ring), partial
    results merged with the exact online-softmax/LSE identity
    (doc pseudocode :84-142).
  * ``--attn zigzag``  -- Ring Attention with the zigzag chunk
    interleave: device i holds chunks (i, 2n-1-i), so causal work is
    perfectly balanced across the ring (the contiguous layout leaves
    the last device doing ~2x the mean).
  * ``--attn ulysses`` -- DeepSpeed-Ulysses: all-to-all scatter-heads /
    gather-sequence around plain flash attention (doc pseudocode
    :43-77; needs n_heads % seq_parallel == 0).

All other ops are token-local, so the rest of the model runs under
plain GSPMD with activations sequence-sharded (cp_constrain) -- the
long-context memory win the reference motivates with ~1M-token weather
grids (:10-17).

Run (8 simulated devices):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python train_llama_sp.py --seq-parallel 4 --attn ring
"""
import os as _os
import sys as _sys

# Run directly from a source checkout without installing: put the repo
# root on sys.path (the reference uses the same pattern, e.g.
# resnet_fsdp_training.py:27).
_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
)

import argparse
import sys

import jax
from jax.sharding import PartitionSpec as P

from tpu_hpc.config import TrainingConfig
from tpu_hpc.logging_ import get_logger
from tpu_hpc.models import datasets, llama2
from tpu_hpc.parallel.ring_attention import cp_constrain, make_ring_attn_fn
from tpu_hpc.parallel.sp_ulysses import (
    make_ulysses_attn_fn,
    validate_ulysses_degree,
)
from tpu_hpc.runtime import MeshSpec, build_mesh, init_distributed
from tpu_hpc.train import Trainer


def main(argv=None) -> int:
    cfg = TrainingConfig.from_args(argv)
    extra = argparse.ArgumentParser(add_help=False)
    extra.add_argument(
        "--attn", choices=("ring", "zigzag", "ulysses"), default="ring"
    )
    extra.add_argument("--seq-len", type=int, default=512)
    extra.add_argument(
        "--fsdp", action="store_true",
        help="shard params over the data axis (FSDP x context "
        "parallel): the composition long-context training of >8B "
        "models needs -- context parallelism alone leaves params "
        "replicated",
    )
    ns, _ = extra.parse_known_args(argv)

    logger = get_logger()
    init_distributed()
    if cfg.seq_parallel == 1:
        # Auto: widest degree <= 4 that divides the device count (a
        # non-divisor would fail mesh construction, e.g. 4 on 6 chips).
        cfg.seq_parallel = max(
            d for d in (4, 2, 1) if jax.device_count() % d == 0
        )
    mesh = build_mesh(MeshSpec(axes=cfg.mesh_axes()))
    logger.info(
        "mesh: %s | %s attention over the 'seq' axis",
        dict(mesh.shape), ns.attn,
    )

    param_dtype, compute_dtype = cfg.jax_dtypes()
    model_cfg = llama2.LlamaConfig(
        dim=256, n_layers=2, n_heads=8, vocab_size=4096,
        multiple_of=64, max_seq_len=ns.seq_len,
        dtype=compute_dtype, param_dtype=param_dtype,
    )
    zigzag_ring = None
    if ns.attn == "ulysses":
        validate_ulysses_degree(model_cfg.n_heads, cfg.seq_parallel)
        attn_fn = make_ulysses_attn_fn(mesh, "data", "seq")
    elif ns.attn == "zigzag":
        from tpu_hpc.parallel.ring_attention import (
            make_zigzag_ring_attn_fn,
        )

        # Production layout: the loader emits tokens already in zigzag
        # order, so the balanced ring needs no per-layer permute pair;
        # RoPE gets the slots' global positions instead.
        zigzag_ring = mesh.shape["seq"]
        attn_fn = make_zigzag_ring_attn_fn(
            mesh, "data", "seq", data_layout="zigzag"
        )
    else:
        attn_fn = make_ring_attn_fn(mesh, "data", "seq")
    constrain = cp_constrain(mesh, "data", "seq")

    params = llama2.init_llama(jax.random.key(cfg.seed), model_cfg)
    ds = datasets.TokenStream(
        vocab_size=model_cfg.vocab_size, seq_len=model_cfg.max_seq_len,
        zigzag_ring=zigzag_ring,
    )
    positions = ds.positions()
    param_pspecs = None
    batch_pspec = P("data")
    if ns.fsdp:
        from tpu_hpc.parallel import fsdp

        # FSDP x CP: params shard over data, activations stay
        # sequence-sharded over seq; numerics match the replicated
        # layout to reduction-order tolerance
        # (tests/test_sp.py::TestFSDPWithRing).
        param_pspecs = fsdp.param_pspecs(
            params, axis="data",
            axis_size=mesh.shape.get("data", 1),
        )
        batch_pspec = P("data", "seq")
    trainer = Trainer(
        cfg,
        mesh,
        llama2.make_forward(model_cfg, constrain, attn_fn, positions),
        params,
        param_pspecs=param_pspecs,
        batch_pspec=batch_pspec,
    )
    result = trainer.fit(ds)
    summary = result["epochs"][-1]
    tokens_per_s = summary["items_per_s"] * model_cfg.max_seq_len
    logger.info(
        "run summary | final loss %.5f | %.0f tokens/s global | "
        "seq %d split %d-way -> %d tokens/device held",
        result["final_loss"],
        tokens_per_s,
        model_cfg.max_seq_len,
        cfg.seq_parallel,
        model_cfg.max_seq_len // cfg.seq_parallel,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
