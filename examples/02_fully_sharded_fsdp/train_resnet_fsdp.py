"""ResNet CIFAR benchmark under DDP or FSDP (ZeRO-3).

Parity with two reference workloads in one script:
  * scripts/main.py:249,268-306 -- the ResNet-18/50/101/152 CIFAR-10
    benchmark with synthetic-data mode and backend switch; epoch-time
    records appended to a benchmark log (:381-397).
  * scripts/02_fully_sharded_fsdp/resnet_fsdp_training.py -- FSDP wrap
    with min_num_params=1e5 + FULL_SHARD and the CIFAR conv1 surgery
    (:186-212).

TPU-native: the full FSDP sharding-strategy matrix
(docs/guide/05_fully_sharded_fsdp.md:114-156) as one flag -- every mode
is a PartitionSpec plan over the same jitted step:
  --strategy ddp          NO_SHARD       params replicated
  --strategy fsdp         FULL_SHARD     params/grads/moments sharded
  --strategy grad-op      SHARD_GRAD_OP  params replicated, moments sharded
  --strategy hybrid       HYBRID_SHARD   shard within an island, replicate across

Run: TPU_HPC_SIM_DEVICES=8 python train_resnet_fsdp.py --depth 18 --strategy fsdp
"""
import os as _os
import sys as _sys

# Run directly from a source checkout without installing: put the repo
# root on sys.path (the reference uses the same pattern, e.g.
# resnet_fsdp_training.py:27).
_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
)

import argparse
import json
import os
import sys
import time

import jax

from tpu_hpc.config import TrainingConfig
from tpu_hpc.logging_ import get_logger
from tpu_hpc.models import datasets, resnet
from tpu_hpc.parallel import dp, fsdp
from tpu_hpc.runtime import MeshSpec, build_mesh, init_distributed
from tpu_hpc.train import Trainer


def main(argv=None) -> int:
    cfg = TrainingConfig.from_args(argv)
    extra = argparse.ArgumentParser(add_help=False)
    extra.add_argument("--depth", type=int, default=18,
                       choices=sorted(resnet.STAGE_SIZES))
    extra.add_argument(
        "--strategy", choices=("ddp", "fsdp", "grad-op", "hybrid"),
        default="fsdp",
    )
    extra.add_argument(
        "--replica-groups", type=int, default=None,
        help="HYBRID_SHARD only: number of replica islands "
             "(default: 2 when the device count allows, else 1)",
    )
    extra.add_argument("--log-file", default="resnet_benchmark.log")
    extra.add_argument(
        "--dataset", choices=("synthetic", "digits", "digits50k"),
        default="synthetic",
        help="synthetic: on-device CIFAR-shaped random batches "
        "(throughput runs, no files); digits: REAL images from disk "
        "through the native C++ loader -- host 0 prepares the record "
        "files on first run, every host barriers, then trains from "
        "the mmap'd epoch-shuffled reader (the reference's rank-0 "
        "CIFAR-10 download + barrier path, resnet_fsdp_training.py:"
        "45-87); digits50k: the CIFAR-SCALE set -- 50k/10k augmented "
        "32x32 images from the real digits, split by original image "
        "(vision.prepare_digits_at_scale), exercising the C++ "
        "prefetch ring at real-dataset size",
    )
    extra.add_argument("--dataset-dir", default="data",
                       help="where --dataset digits stores its files")
    ns, _ = extra.parse_known_args(argv)

    logger = get_logger()
    init_distributed()
    if ns.strategy == "hybrid":
        r = ns.replica_groups
        if r is None:
            r = 2 if jax.device_count() % 2 == 0 else 1
        if jax.device_count() % r:
            raise SystemExit(
                f"--replica-groups {r} must divide {jax.device_count()}"
            )
        mesh = build_mesh(
            MeshSpec(axes={"replica": r, "fsdp": jax.device_count() // r})
        )
    else:
        mesh = build_mesh(MeshSpec(axes={"data": -1}))
    param_dtype, compute_dtype = cfg.jax_dtypes()
    if ns.dataset in ("digits", "digits50k"):
        from tpu_hpc.native import vision

        prefix = os.path.join(ns.dataset_dir, ns.dataset)
        prep = (
            (lambda: vision.prepare_digits_at_scale(prefix))
            if ns.dataset == "digits50k"
            else (lambda: vision.prepare_digits(prefix))
        )
        vision.prepare_on_host0(
            prep,
            [prefix + ".train", prefix + ".test", prefix + ".json"],
        )
        meta0 = vision.read_meta(prefix)
        sample_shape = tuple(meta0["x_shape"])
        # The file's class count, not the CIFAR default: an --npz
        # dataset with more classes would otherwise silently train a
        # 10-way head (out-of-range labels zero out of the CE mask).
        num_classes = meta0["n_classes"]
    else:
        sample_shape = datasets.CIFARSynthetic().sample_shape
        num_classes = 10
    model_cfg = resnet.ResNetConfig(
        depth=ns.depth, num_classes=num_classes,
        dtype=compute_dtype, param_dtype=param_dtype,
    )
    params, model_state = resnet.init_resnet(
        jax.random.key(cfg.seed), model_cfg, sample_shape
    )
    n_params = sum(p.size for p in jax.tree.leaves(params))
    logger.info(
        "ResNet-%d (%.1fM params) | %s over %d devices",
        ns.depth, n_params / 1e6, ns.strategy, mesh.size,
    )

    opt_specs = None
    batch_spec = dp.batch_pspec()
    if ns.strategy == "fsdp":
        specs = fsdp.param_pspecs(params, axis_size=mesh.shape["data"])
    elif ns.strategy == "grad-op":
        specs, opt_specs = fsdp.grad_op_pspecs(
            params, axis_size=mesh.shape["data"]
        )
    elif ns.strategy == "hybrid":
        specs = fsdp.hybrid_shard_pspecs(params, mesh=mesh)
        batch_spec = fsdp.hybrid_shard_batch_pspec()
    else:
        specs = dp.param_pspecs(params)
    if ns.dataset in ("digits", "digits50k"):
        meta = vision.read_meta(prefix)
        ds = vision.NativeImageClassDataset(
            prefix + ".train", cfg.global_batch_size,
            tuple(meta["x_shape"]),
        )
        ds_test = vision.NativeImageClassDataset(
            prefix + ".test", cfg.global_batch_size,
            tuple(meta["x_shape"]), seed=1,
        )
        # Loader throughput: time the host-side path alone (mmap read
        # + Feistel shuffle + ring handoff) so the record shows what
        # the C++ pipeline delivers independent of device step time.
        t0 = time.perf_counter()
        probe_steps = 50
        for s in range(probe_steps):
            ds.batch_at(s, cfg.global_batch_size)
        loader_rate = (
            probe_steps * cfg.global_batch_size
            / (time.perf_counter() - t0)
        )
        logger.info(
            "native loader: %d real train images (%s), "
            "%.0f images/s host-side", ds.n_samples, meta["source"],
            loader_rate,
        )
    else:
        ds, ds_test, loader_rate = (
            datasets.CIFARSynthetic(), datasets.CIFARSynthetic(seed=1),
            None,
        )
    trainer = Trainer(
        cfg, mesh, resnet.make_forward(model_cfg), params, model_state,
        param_pspecs=specs,
        opt_param_pspecs=opt_specs,
        batch_pspec=batch_spec,
        eval_forward=resnet.make_eval_forward(model_cfg),
    )
    t0 = time.perf_counter()
    result = trainer.fit(ds)
    wall = time.perf_counter() - t0
    summary = result["epochs"][-1]
    # Held-out pass: disjoint synthetic stream, or the real test
    # split (parity: the test accuracy loop,
    # resnet_fsdp_training.py:138-155).
    test_metrics = trainer.evaluate(
        ds_test,
        n_steps=(
            max(ds_test.n_samples // cfg.global_batch_size, 1)
            if ns.dataset in ("digits", "digits50k") else None
        ),
    )
    logger.info(
        "run summary | final loss %.5f | %.1f images/s global | "
        "%.1f images/s/device | test loss %.5f | test accuracy %.2f%%",
        result["final_loss"],
        summary["items_per_s"],
        summary["items_per_s_per_device"],
        test_metrics["loss"],
        100.0 * test_metrics["accuracy"],
    )
    # Append-only benchmark record (parity: scripts/main.py:381-397,
    # which keys records by backend + NCCL version; here mesh + jax).
    if jax.process_index() == 0:
        with open(ns.log_file, "a") as f:
            f.write(json.dumps({
                "model": f"resnet{ns.depth}",
                "strategy": ns.strategy,
                "data": ns.dataset,
                "devices": mesh.size,
                "jax": jax.__version__,
                "epochs": cfg.epochs,
                "wall_s": round(wall, 2),
                "images_per_s": round(summary["items_per_s"], 2),
                **(
                    {"loader_images_per_s": round(loader_rate, 1),
                     "test_accuracy": round(
                         float(test_metrics["accuracy"]), 4)}
                    if loader_rate is not None else {}
                ),
            }) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
