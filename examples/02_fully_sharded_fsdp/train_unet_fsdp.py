"""FSDP (ZeRO-3) U-Net training: parameters sharded over the data axis.

Parity with /root/reference/scripts/02_fully_sharded_fsdp/
multinode_fsdp_unet.py (FSDP FULL_SHARD + size-based auto-wrap + BF16
mixed precision + gathered checkpoint). TPU-native: the wrap policy
becomes a size-based shard plan (min 1e5 params, like the reference's
min_num_params); XLA inserts the per-use all-gather and gradient
reduce-scatter that FSDP units did by hand.

Run: python train_unet_fsdp.py --epochs 3 [--save-every 1]
"""
import os as _os
import sys as _sys

# Run directly from a source checkout without installing: put the repo
# root on sys.path (the reference uses the same pattern, e.g.
# resnet_fsdp_training.py:27).
_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
)

import sys

import jax

from tpu_hpc.config import TrainingConfig
from tpu_hpc.logging_ import get_logger
from tpu_hpc.models import datasets, losses
from tpu_hpc.models.unet import (
    UNetConfig, apply_unet, init_unet, make_eval_forward,
)
from tpu_hpc.parallel import fsdp
from tpu_hpc.parallel.plans import describe_pspecs
from tpu_hpc.runtime import MeshSpec, build_mesh, init_distributed
from tpu_hpc.train import Trainer


def main(argv=None) -> int:
    cfg = TrainingConfig.from_args(argv)
    logger = get_logger()
    init_distributed()
    mesh = build_mesh(MeshSpec(axes={"data": cfg.data_parallel}))

    ds = datasets.ERA5Synthetic()
    param_dtype, compute_dtype = cfg.jax_dtypes()
    model_cfg = UNetConfig(
        in_channels=ds.channels, out_channels=ds.channels,
        dtype=compute_dtype, param_dtype=param_dtype,
    )
    params, model_state = init_unet(
        jax.random.key(cfg.seed), model_cfg, ds.sample_shape
    )
    pspecs = fsdp.param_pspecs(params, axis_size=mesh.shape["data"])
    if jax.process_index() == 0:
        logger.info("FSDP shard plan (first 8 entries):")
        for line in describe_pspecs(params, pspecs)[:8]:
            logger.info("  %s", line)

    def forward(p, ms, batch, step_rng):
        x, y = batch
        pred, new_ms = apply_unet(p, ms, x, model_cfg, train=True)
        return losses.lat_weighted_mse(pred, y), new_ms, {}

    ckpt_mgr = None
    if cfg.save_every:
        from tpu_hpc.ckpt import CheckpointManager

        ckpt_mgr = CheckpointManager(cfg.checkpoint_dir)

    trainer = Trainer(
        cfg, mesh, forward, params, model_state,
        param_pspecs=pspecs,
        batch_pspec=fsdp.batch_pspec(),
        checkpoint_manager=ckpt_mgr,
        eval_forward=make_eval_forward(model_cfg),
    )
    result = trainer.fit(ds)
    if ckpt_mgr is not None:
        ckpt_mgr.wait()
    if not result["epochs"]:
        logger.info("nothing to do: checkpoint already at %d epochs", cfg.epochs)
        return 0
    summary = result["epochs"][-1]
    # Held-out test-loss pass (parity: the reference UNet's test loss,
    # multinode_fsdp_unet.py).
    test_metrics = trainer.evaluate(datasets.ERA5Synthetic(seed=1))
    logger.info(
        "run summary | final loss %.5f | %.1f samples/s global | "
        "%.1f samples/s/device | test loss %.5f",
        result["final_loss"], summary["items_per_s"],
        summary["items_per_s_per_device"], test_metrics["loss"],
    )
    # Exit-code contract (docs/guide/resilience.md): resumable
    # preemption snapshots are distinguishable from success/failure.
    from tpu_hpc.resilience import exit_code_for

    return exit_code_for(result.get("preempted", False))


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
