"""Pipeline-parallel training of the FLAGSHIP model: Llama-2 stage-split.

The reference's pipeline example trains a dedicated PipelineTransformer
(/root/reference/scripts/04_pipeline_parallel_pp/
03_pipeline_training.py:198-252); here the same schedules run Llama-2
itself. Llama's transformer blocks are homogeneous at apply time (the
depth-scaled init only shapes parameter VALUES), so ``n_layers/S``
consecutive blocks form one shape-preserving stage and the whole body
pipelines as a single shard_map tick program
(tpu_hpc/models/llama_pp.py + tpu_hpc/parallel/pp.py). Embedding and
LM head run outside the pipelined body, replicated over the pipe axis.

The split/merge round-trip is exact, so the sequential oracle for this
script's program is ``llama2.apply_llama`` on the same values
(tests/test_pp_llama.py pins forwards and grads for gpipe, 1f1b-remat
and 1f1b-stash).

Run: python train_llama_pipeline.py --pipe-parallel 4 --schedule 1f1b
     python train_llama_pipeline.py --pipe-parallel 4 --schedule 1f1b \
         --pp-backward stash   # Megatron residual-stash backward
"""
import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
)

import argparse
import dataclasses

import jax
from jax.sharding import PartitionSpec as P

from tpu_hpc.config import TrainingConfig
from tpu_hpc.logging_ import get_logger
from tpu_hpc.models import datasets, llama2, llama_pp
from tpu_hpc.parallel import pp
from tpu_hpc.runtime import MeshSpec, build_mesh, init_distributed
from tpu_hpc.train import Trainer


def main(argv=None) -> int:
    cfg = TrainingConfig.from_args(argv)
    extra = argparse.ArgumentParser(add_help=False)
    extra.add_argument(
        "--schedule",
        choices=["gpipe", "1f1b", "interleaved", "interleaved-1f1b"],
        default="1f1b",
    )
    extra.add_argument("--num-microbatches", type=int, default=8)
    extra.add_argument(
        "--num-chunks", type=int, default=2,
        help="virtual stage chunks per device (interleaved schedules "
        "only): Megatron round-robin placement, bubble / num-chunks",
    )
    extra.add_argument(
        "--pp-backward", choices=["remat", "stash"], default="remat",
        help="1f1b backward: remat recomputes each stage forward "
        "(minimal HBM); stash saves the vjp residuals "
        "(Megatron-style, 4/3 instead of 5/3 of ideal FLOPs)",
    )
    args, _ = extra.parse_known_args(argv)

    logger = get_logger()
    init_distributed()
    if cfg.pipe_parallel == 1:
        dp = cfg.data_parallel if cfg.data_parallel > 0 else 1
        cfg.pipe_parallel = jax.device_count() // dp
    mesh = build_mesh(MeshSpec(axes=cfg.mesh_axes()))
    n_stages = mesh.shape.get("pipe", 1)
    M = args.num_microbatches
    interleaved = args.schedule in ("interleaved", "interleaved-1f1b")
    v = args.num_chunks if interleaved and n_stages > 1 else 1
    logger.info(
        "mesh: %s | llama-2 over %d stages%s | schedule %s | "
        "%d microbatches | bubble %.1f%%",
        dict(mesh.shape), n_stages,
        f" x {v} chunks" if v > 1 else "",
        args.schedule, M,
        100 * pp.bubble_fraction(max(n_stages, 1), M, n_chunks=v),
    )

    param_dtype, compute_dtype = cfg.jax_dtypes()
    model_cfg = llama2.LlamaConfig(
        dim=256, n_layers=max(2 * n_stages * v, 2), n_heads=8,
        vocab_size=4096, multiple_of=64, max_seq_len=256,
        dtype=compute_dtype, param_dtype=param_dtype,
    )
    params = llama2.init_llama(jax.random.key(cfg.seed), model_cfg)

    dp_size = mesh.shape.get("data", 1)
    batch_spec = P(None, "data") if dp_size > 1 else P()
    if n_stages > 1:
        split = (
            llama_pp.split_params_interleaved(
                params, model_cfg, n_stages, v
            )
            if v > 1 else
            llama_pp.split_params(params, model_cfg, n_stages)
        )
        forward = llama_pp.make_forward(
            model_cfg, mesh, n_microbatches=M,
            schedule=args.schedule, backward=args.pp_backward,
            batch_spec=batch_spec, n_chunks=v,
        )
        train_params = split
        specs = llama_pp.pp_pspecs(split)
    else:
        # One device: train unpipelined (the reference's world_size==1
        # fallback pattern) -- same model, same loss.
        train_params = params
        specs = None
        forward = llama2.make_forward(model_cfg)

    ds = datasets.TokenStream(
        vocab_size=model_cfg.vocab_size, seq_len=model_cfg.max_seq_len
    )
    trainer = Trainer(
        cfg, mesh, forward, train_params, param_pspecs=specs,
        batch_pspec=P("data") if dp_size > 1 else P(),
    )
    result = trainer.fit(ds)
    summary = result["epochs"][-1]
    tokens_per_s = summary["items_per_s"] * model_cfg.max_seq_len
    logger.info(
        "run summary | final loss %.5f | %.0f tokens/s | "
        "%d-layer llama over %d stages (%s%s)",
        result["final_loss"], tokens_per_s, model_cfg.n_layers, n_stages,
        args.schedule,
        f"-{args.pp_backward}"
        if args.schedule in ("1f1b", "interleaved-1f1b") else "",
    )
    return 0


if __name__ == "__main__":
    _sys.exit(main())
