"""Pipeline-parallel causal-LM training with GPipe / 1F1B schedules.

Parity with /root/reference/scripts/04_pipeline_parallel_pp/
03_pipeline_training.py: stage-partitioned transformer, microbatched
schedule selected by --schedule {gpipe,1f1b}, per-step tokens/s and
bubble-fraction reporting (:280-294). The manual send/recv of
01_manual_model_split.py is the ``pp.manual_stage_step`` hop; the
schedule comparison of 02_pipeline_schedules.py is --schedule.

TPU-native: stages are a sharded leading array dim on a ``pipe`` mesh
axis; activations hop stages via ppermute (ICI neighbor links); the
whole schedule is one jitted SPMD program (tpu_hpc/parallel/pp.py).

Run: python train_pipeline.py --pipe-parallel 4 --schedule 1f1b
"""
import os as _os
import sys as _sys

# Run directly from a source checkout without installing: put the repo
# root on sys.path (the reference uses the same pattern, e.g.
# resnet_fsdp_training.py:27).
_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
)

import argparse
import sys

import jax
from jax.sharding import PartitionSpec as P

from tpu_hpc.config import TrainingConfig
from tpu_hpc.logging_ import get_logger
from tpu_hpc.models import datasets, losses
from tpu_hpc.models import pipeline_transformer as ptx
from tpu_hpc.parallel import pp
from tpu_hpc.runtime import MeshSpec, build_mesh, init_distributed
from tpu_hpc.train import Trainer


def main(argv=None) -> int:
    cfg = TrainingConfig.from_args(argv)
    extra = argparse.ArgumentParser(add_help=False)
    extra.add_argument(
        "--schedule",
        choices=["gpipe", "1f1b", "interleaved", "interleaved-1f1b"],
        default="gpipe",
    )
    extra.add_argument("--num-microbatches", type=int, default=8)
    extra.add_argument(
        "--num-chunks", type=int, default=2,
        help="virtual stage chunks per device (interleaved schedule "
        "only): bubble time shrinks by this factor",
    )
    args, _ = extra.parse_known_args(argv)

    logger = get_logger()
    init_distributed()
    if cfg.pipe_parallel == 1:
        # Auto: stages fill whatever the (explicit) data axis leaves.
        dp = cfg.data_parallel if cfg.data_parallel > 0 else 1
        cfg.pipe_parallel = jax.device_count() // dp
    mesh = build_mesh(MeshSpec(axes=cfg.mesh_axes()))
    # On one device mesh_axes() drops the degenerate pipe axis; train
    # unpipelined (the reference's world_size==1 fallback pattern).
    n_stages = mesh.shape.get("pipe", 1)
    M = args.num_microbatches
    # Interleaving needs a real pipe ring; on one device fall back
    # to v=1 (the unpipelined path would silently run only chunk 0
    # of a multi-chunk model otherwise).
    v = (
        args.num_chunks
        if args.schedule in ("interleaved", "interleaved-1f1b")
        and n_stages > 1
        else 1
    )
    logger.info(
        "mesh: %s | schedule %s | %d microbatches | bubble fraction %.1f%%",
        dict(mesh.shape), args.schedule, M,
        100 * pp.bubble_fraction(n_stages, M, n_chunks=v),
    )

    param_dtype, compute_dtype = cfg.jax_dtypes()
    # Interleaved: v model chunks per device -> v*S model stages
    # round-robin on the pipe ring (stack_interleaved_stage_params).
    model_cfg = ptx.PipeConfig(
        vocab_size=4096, dim=256, n_heads=8, n_stages=n_stages * v,
        layers_per_stage=2, max_seq_len=256,
        dtype=compute_dtype, param_dtype=param_dtype,
    )
    params = ptx.init_pipeline_transformer(jax.random.key(cfg.seed), model_cfg)
    if v > 1:
        params = dict(
            params,
            stages=pp.interleave_stacked(params["stages"], n_stages),
        )
    specs = {
        "embed": jax.tree.map(lambda _: P(), params["embed"]),
        "stages": pp.stage_pspecs(params["stages"], axis="pipe")
        if n_stages > 1
        else jax.tree.map(lambda _: P(), params["stages"]),
        "head": jax.tree.map(lambda _: P(), params["head"]),
    }
    batch_spec = P(None, "data") if mesh.shape.get("data", 1) > 1 else P()
    if n_stages > 1:
        pipe = pp.pipelined(
            ptx.make_stage_fn(model_cfg), mesh, axis="pipe",
            schedule=args.schedule, batch_spec=batch_spec,
            n_chunks=v,
        )
    else:
        stage_fn = ptx.make_stage_fn(model_cfg)

        def pipe(stages, xs):  # vmap over the microbatch dim
            return jax.vmap(stage_fn, in_axes=(None, 0))(
                jax.tree.map(lambda a: a[0], stages), xs
            )

    def forward(params, model_state, batch, step_rng):
        inputs, targets = batch
        xs = ptx.embed(params, pp.microbatch(inputs, M), model_cfg)
        ys = pipe(params["stages"], xs)
        logits = ptx.head(params, ys, model_cfg)
        loss = losses.cross_entropy(logits, pp.microbatch(targets, M))
        return loss, model_state, {}

    ds = datasets.TokenStream(
        vocab_size=model_cfg.vocab_size, seq_len=model_cfg.max_seq_len
    )
    trainer = Trainer(
        cfg, mesh, forward, params,
        param_pspecs=specs,
        batch_pspec=P("data") if mesh.shape.get("data", 1) > 1 else P(),
    )
    result = trainer.fit(ds)
    summary = result["epochs"][-1]
    tokens_per_s = summary["items_per_s"] * model_cfg.max_seq_len
    logger.info(
        "run summary | final loss %.5f | %.0f tokens/s | bubble %.1f%% "
        "(%d stages, %d microbatches)",
        result["final_loss"], tokens_per_s,
        100 * pp.bubble_fraction(n_stages, M, n_chunks=v), n_stages, M,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
