"""Tensor-parallel Llama training: the Megatron col/row plan on TPU.

Parity with /root/reference/scripts/03_tensor_parallel_tp/ and
fsdp_tp/tensor_parallel_example.py: 1D ``model`` mesh, Colwise
wq/wk/wv/w1/w3, Rowwise wo/w2 -- one all-reduce per attention/FFN
block. Here the plan is a PartitionSpec rule list (parallel/tp.py) and
XLA inserts the collectives; on hardware they ride ICI.

Run (single host, all chips as TP): python train_llama_tp.py \
    --model-parallel 4 --data-parallel 1
"""
import os as _os
import sys as _sys

# Run directly from a source checkout without installing: put the repo
# root on sys.path (the reference uses the same pattern, e.g.
# resnet_fsdp_training.py:27).
_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
)

import sys

import jax

from tpu_hpc.config import TrainingConfig
from tpu_hpc.logging_ import get_logger
from tpu_hpc.models import datasets, llama2
from tpu_hpc.parallel import tp
from tpu_hpc.parallel.plans import describe_pspecs
from tpu_hpc.runtime import MeshSpec, build_mesh, init_distributed
from tpu_hpc.train import Trainer


def main(argv=None) -> int:
    cfg = TrainingConfig.from_args(argv)
    logger = get_logger()
    init_distributed()  # before any device query (multi-host contract)
    param_dtype, compute_dtype = cfg.jax_dtypes()
    model_cfg = llama2.LlamaConfig(
        dim=256, n_layers=2, n_heads=8, vocab_size=4096,
        multiple_of=64, max_seq_len=512,
        dtype=compute_dtype, param_dtype=param_dtype,
    )
    if cfg.model_parallel == 1:
        # Auto: widest TP the devices + head counts allow (1 = pure DP).
        cfg.model_parallel = tp.auto_tp_degree(
            jax.device_count(), model_cfg.n_heads, model_cfg.kv_heads
        )
        cfg.data_parallel = jax.device_count() // cfg.model_parallel
    mesh = build_mesh(MeshSpec(axes=cfg.mesh_axes()))
    logger.info("mesh: %s", dict(mesh.shape))

    tp.validate_tp_degree(
        model_cfg.n_heads, model_cfg.kv_heads, cfg.model_parallel
    )
    params = llama2.init_llama(jax.random.key(cfg.seed), model_cfg)
    # Degenerate TP (one device / indivisible heads): replicated specs.
    specs = (
        tp.param_pspecs(params, tp.llama_rules())
        if cfg.model_parallel > 1
        else None
    )
    if specs is not None:
        for line in describe_pspecs(params, specs)[:8]:
            logger.info("plan: %s", line)

    ds = datasets.TokenStream(
        vocab_size=model_cfg.vocab_size, seq_len=model_cfg.max_seq_len
    )
    trainer = Trainer(
        cfg,
        mesh,
        llama2.make_forward(model_cfg),
        params,
        param_pspecs=specs,
    )
    result = trainer.fit(ds)
    summary = result["epochs"][-1]
    tokens_per_s = summary["items_per_s"] * model_cfg.max_seq_len
    logger.info(
        "run summary | final loss %.5f | %.0f tokens/s global | "
        "%.0f tokens/s/device",
        result["final_loss"],
        tokens_per_s,
        tokens_per_s / mesh.size,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
