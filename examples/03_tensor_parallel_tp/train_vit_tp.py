"""Tensor-parallel ViT training on ERA5-like weather grids.

Parity with /root/reference/scripts/03_tensor_parallel_tp/
tensor_parallel_vit.py: SimpleViT with separate q/k/v projections so
heads shard cleanly across the TP axis (:93-110, :352-361), trained
with latitude-weighted MSE on synthetic ERA5 grids, TP degree capped at
the node size (:273).

TPU-native: the Colwise/Rowwise plan is a PartitionSpec rule set
(tp.vit_rules) -- no parallelize_module pass, no foreach=False AdamW
quirk (:372-378); GSPMD inserts one all-reduce per attention/MLP pair.

Run (8 simulated devices):
  TPU_HPC_SIM_DEVICES=8 python train_vit_tp.py --model-parallel 4
"""
import os as _os
import sys as _sys

# Run directly from a source checkout without installing: put the repo
# root on sys.path (the reference uses the same pattern, e.g.
# resnet_fsdp_training.py:27).
_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
)

import sys

import jax

from tpu_hpc.config import TrainingConfig
from tpu_hpc.logging_ import get_logger
from tpu_hpc.models import datasets, vit
from tpu_hpc.parallel import tp
from tpu_hpc.runtime import MeshSpec, build_mesh, init_distributed
from tpu_hpc.train import Trainer


def main(argv=None) -> int:
    cfg = TrainingConfig.from_args(argv)
    logger = get_logger()
    init_distributed()
    param_dtype, compute_dtype = cfg.jax_dtypes()
    model_cfg = vit.ViTConfig(
        in_channels=20, out_channels=20, patch_size=4, lat=64, lon=128,
        embed_dim=256, depth=6, n_heads=8,
        dtype=compute_dtype, param_dtype=param_dtype,
    )
    if cfg.model_parallel == 1:
        cfg.model_parallel = tp.auto_tp_degree(
            jax.device_count(), model_cfg.n_heads, model_cfg.n_heads,
            cap=4,  # the reference's node-size cap (:273)
        )
    tp.validate_tp_degree(
        model_cfg.n_heads, model_cfg.n_heads, cfg.model_parallel
    )
    mesh = build_mesh(MeshSpec(axes=cfg.mesh_axes()))
    logger.info(
        "mesh: %s | %d heads -> %d per TP shard",
        dict(mesh.shape), model_cfg.n_heads,
        model_cfg.n_heads // cfg.model_parallel,
    )

    params = vit.init_vit(jax.random.key(cfg.seed), model_cfg)
    ds = datasets.ERA5Synthetic(
        lat=model_cfg.lat, lon=model_cfg.lon, n_vars=5, n_levels=4
    )
    trainer = Trainer(
        cfg,
        mesh,
        vit.make_forward(model_cfg),
        params,
        param_pspecs=tp.param_pspecs(params, tp.vit_rules()),
    )
    result = trainer.fit(ds)
    summary = result["epochs"][-1]
    logger.info(
        "run summary | final loss %.5f | %.1f samples/s global | "
        "%.2f samples/s/device",
        result["final_loss"],
        summary["items_per_s"],
        summary["items_per_s_per_device"],
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
