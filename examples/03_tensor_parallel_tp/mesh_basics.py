"""Device-mesh basics: the one mechanism behind every strategy.

Teaching counterpart of the reference's
scripts/03_tensor_parallel_tp/01_device_mesh_basics.py (1D mesh, 2D
mesh, sub-mesh slicing, all-reduce sanity check :29-87) -- re-expressed
for TPU: `jax.sharding.Mesh` instead of `init_device_mesh`, and the
collective sanity check is a jitted `psum` whose expected value is
asserted exactly, like the reference's `result == sum(range(ws))`.

Run anywhere:  TPU_HPC_SIM_DEVICES=8 python mesh_basics.py
"""
import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
)

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpu_hpc.runtime import (
    MeshSpec, build_mesh, init_distributed, named_sharding,
)


def main() -> int:
    init_distributed()
    n = jax.device_count()
    print(f"devices: {n} x {jax.devices()[0].device_kind}")

    # -- 1D mesh: every chip on one axis (reference :29-40) --
    mesh1d = build_mesh(MeshSpec(axes={"data": n}))
    print(f"1D mesh: {dict(mesh1d.shape)}")

    # -- 2D mesh: (data, model) hybrid shape (reference :42-58) --
    tp = 2 if n % 2 == 0 else 1
    mesh2d = build_mesh(MeshSpec(axes={"data": n // tp, "model": tp}))
    print(f"2D mesh: {dict(mesh2d.shape)} axis_names={mesh2d.axis_names}")

    # -- sub-mesh: one TP group = one row of the device grid
    # (reference sub-mesh slicing :60-73). In JAX you rarely need the
    # sub-mesh object itself -- collectives are *named* over axes --
    # but the device grid is inspectable:
    row0 = mesh2d.devices[0]
    print(f"TP group 0 devices: {[d.id for d in row0]}")

    # -- collective sanity check (reference all-reduce assert :82-87):
    # each device contributes its data-axis index; psum over 'data'
    # must equal sum(range(dp)) everywhere.
    dp = mesh2d.shape["data"]
    x = jnp.arange(dp, dtype=jnp.float32)
    xs = jax.device_put(x, named_sharding(mesh2d, "data"))

    def body(v):
        return jax.lax.psum(v, "data")

    total = jax.jit(
        jax.shard_map(
            body, mesh=mesh2d, in_specs=P("data"), out_specs=P(),
        )
    )(xs)
    expected = float(sum(range(dp)))
    assert float(total[0]) == expected, (total, expected)
    print(f"psum over data axis = {float(total[0]):.0f} "
          f"(expected {expected:.0f}) -- mesh is wired correctly")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
