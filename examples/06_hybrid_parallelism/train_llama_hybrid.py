"""Hybrid FSDP x TP (+Megatron-SP) Llama training on a 2D mesh.

Parity with /root/reference/scripts/06_hybrid_parallelism/
01_fsdp_tp_hybrid.py and fsdp_tp/fsdp_tp_example.py: 2D (data, model)
mesh, Megatron TP plan per block + SequenceParallel activation
layouts, then ZeRO-3 over the data axis. The north-star workload
(SURVEY.md section 3.2) -- on hardware, TP collectives ride the inner
ICI axis, FSDP all-gather/reduce-scatter the outer.

Run: python train_llama_hybrid.py --data-parallel 2 --model-parallel 4

Real-corpus mode: ``--tokens-file corpus.tok`` trains from a
pretokenized mmap'd token binary via the native C++ prefetch reader
(tpu_hpc.native.write_token_dataset converts any 1D id array once)
instead of the synthetic TokenStream.
"""
import os as _os
import sys as _sys

# Run directly from a source checkout without installing: put the repo
# root on sys.path (the reference uses the same pattern, e.g.
# resnet_fsdp_training.py:27).
_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
)

import sys

import jax

from tpu_hpc.config import TrainingConfig
from tpu_hpc.logging_ import get_logger
from tpu_hpc.models import datasets, llama2
from tpu_hpc.parallel import hybrid, tp
from tpu_hpc.runtime import build_mesh, init_distributed
from tpu_hpc.train import Trainer


def main(argv=None) -> int:
    import argparse

    extra = argparse.ArgumentParser(add_help=False)
    extra.add_argument(
        "--tokens-file", type=str, default=None,
        help="train from this pretokenized binary "
        "(tpu_hpc.native.write_token_dataset) via the native reader "
        "instead of the synthetic TokenStream",
    )
    own, rest = extra.parse_known_args(argv)
    cfg = TrainingConfig.from_args(rest)
    logger = get_logger()
    init_distributed()  # before any device query (multi-host contract)
    param_dtype, compute_dtype = cfg.jax_dtypes()
    model_cfg = llama2.LlamaConfig(
        dim=256, n_layers=2, n_heads=8, vocab_size=4096,
        multiple_of=64, max_seq_len=512,
        dtype=compute_dtype, param_dtype=param_dtype,
    )
    if cfg.model_parallel == 1:
        # Auto: TP up to 4-wide (the reference's node-size cap,
        # tensor_parallel_vit.py:273); 1 = pure FSDP fallback.
        cfg.model_parallel = tp.auto_tp_degree(
            jax.device_count(), model_cfg.n_heads, model_cfg.kv_heads, cap=4
        )
    # mesh_spec() includes the multi-slice extent: --dcn-data-parallel N
    # spans the data/FSDP axis across N slices over DCN while TP stays
    # inside each slice (the reference's TP-on-NVLink / FSDP-on-
    # Slingshot split, fsdp_tp/fsdp_tp_example.py:12-26).
    mesh = build_mesh(cfg.mesh_spec())
    dp_size = mesh.shape["data"]
    logger.info(
        "mesh: %s (TP inner/ICI-minor, FSDP outer%s)",
        dict(mesh.shape),
        f", data across {cfg.dcn_data_parallel} slices via DCN"
        if cfg.dcn_data_parallel > 1 else "",
    )

    tp.validate_tp_degree(
        model_cfg.n_heads, model_cfg.kv_heads, cfg.model_parallel
    )
    params = llama2.init_llama(jax.random.key(cfg.seed), model_cfg)
    if cfg.model_parallel > 1:
        specs = hybrid.hybrid_pspecs(
            params, tp.llama_rules(), data_size=dp_size
        )
        constrain = tp.sp_constrain(mesh, dp_axis="data", sp_axis="model")
    else:
        # Degenerate model axis: pure ZeRO-3 over data (P2 recipe).
        from tpu_hpc.parallel import fsdp

        specs = fsdp.param_pspecs(params, axis="data", axis_size=dp_size)
        constrain = lambda x: x  # noqa: E731

    if own.tokens_file:
        from tpu_hpc.native import NativeTokenDataset

        ds = NativeTokenDataset(
            own.tokens_file, batch_size=cfg.global_batch_size,
            seq_len=model_cfg.max_seq_len, seed=cfg.seed,
        )
        if ds.max_token_id >= model_cfg.vocab_size:
            # Out-of-range ids would train silently on all-zero
            # embeddings; the file header carries the corpus max so
            # this is checkable before the first step.
            raise SystemExit(
                f"corpus max token id {ds.max_token_id} >= model "
                f"vocab_size {model_cfg.vocab_size}: retokenize or "
                "grow the vocab"
            )
        logger.info(
            "corpus: %s (%d tokens, %d windows of %d)",
            own.tokens_file, ds.n_tokens, ds.n_windows,
            model_cfg.max_seq_len,
        )
    else:
        ds = datasets.TokenStream(
            vocab_size=model_cfg.vocab_size, seq_len=model_cfg.max_seq_len
        )
    trainer = Trainer(
        cfg,
        mesh,
        llama2.make_forward(model_cfg, constrain),
        params,
        param_pspecs=specs,
    )
    result = trainer.fit(ds)
    summary = result["epochs"][-1]
    tokens_per_s = summary["items_per_s"] * model_cfg.max_seq_len
    flops = model_cfg.flops_per_token(ds.seq_len) * tokens_per_s
    logger.info(
        "run summary | final loss %.5f | %.0f tokens/s global | "
        "%.0f tokens/s/device | model TFLOP/s %.2f",
        result["final_loss"],
        tokens_per_s,
        tokens_per_s / mesh.size,
        flops / 1e12,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
