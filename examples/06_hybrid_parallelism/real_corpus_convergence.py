"""Real-corpus convergence run: prove the full stack trains.

Everything in one reproducible command: build a REAL text corpus from
local files (default: the Python standard library source tree -- ~30 MB
of real code text present on any machine, no download), tokenize it
byte-level into train/eval token binaries (deterministic split by file
hash), then train a small Llama through the native C++ loader with a
held-out eval pass every epoch. Train AND eval loss land in the
metrics JSONL -- the loss-curve artifact.

The reference's only real-data training is CIFAR-10
(/root/reference/scripts/main.py:332-397); its Llama examples train on
random tokens. This run is the LLM-side counterpart: real bytes, real
next-token loss, falling on data the model has never seen.

Run (real chip or sim):
  python real_corpus_convergence.py --steps-per-epoch 100 --epochs 5 \
      --global-batch-size 16 --metrics-path runs/convergence.jsonl
"""
import os as _os
import sys as _sys

# Run directly from a source checkout without installing: put the repo
# root on sys.path (the reference uses the same pattern, e.g.
# resnet_fsdp_training.py:27).
_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
)

import argparse
import hashlib
import os
import sys
import sysconfig

import jax

from tpu_hpc.config import TrainingConfig
from tpu_hpc.logging_ import get_logger
from tpu_hpc.models import llama2
from tpu_hpc.native import NativeTokenDataset
from tpu_hpc.native.prepare import prepare_corpus
from tpu_hpc.native.dataloader import prepare_on_host0
from tpu_hpc.parallel import fsdp, hybrid, tp
from tpu_hpc.runtime import build_mesh, init_distributed
from tpu_hpc.train import Trainer


def split_files(root: str, eval_every: int = 20):
    """Deterministic train/eval split of the ``.py`` files under
    ``root``: a file is eval iff md5(relpath) % eval_every == 0 --
    stable across hosts and runs, no RNG, disjoint by construction."""
    train, evals = [], []
    for dirpath, _, names in sorted(os.walk(root)):
        for name in sorted(names):
            if not name.endswith(".py"):
                continue
            p = os.path.join(dirpath, name)
            rel = os.path.relpath(p, root)
            h = int.from_bytes(
                hashlib.md5(rel.encode()).digest()[:4], "big"
            )
            (evals if h % eval_every == 0 else train).append(p)
    return train, evals


def main(argv=None) -> int:
    extra = argparse.ArgumentParser(add_help=False)
    extra.add_argument(
        "--corpus-root", default=sysconfig.get_paths()["stdlib"],
        help="directory of .py text files (default: the Python "
        "standard library source)",
    )
    extra.add_argument("--corpus-dir", default="data/pycorpus",
                       help="where the token binaries are written")
    extra.add_argument("--dim", type=int, default=256)
    extra.add_argument("--layers", type=int, default=4)
    extra.add_argument("--heads", type=int, default=8)
    extra.add_argument("--seq-len", type=int, default=256)
    extra.add_argument("--eval-steps", type=int, default=20,
                       help="held-out batches per eval pass")
    own, rest = extra.parse_known_args(argv)
    cfg = TrainingConfig.from_args(rest)
    logger = get_logger()
    init_distributed()

    train_tok = os.path.join(own.corpus_dir, "train.tok")
    eval_tok = os.path.join(own.corpus_dir, "eval.tok")

    def prepare():
        os.makedirs(own.corpus_dir, exist_ok=True)
        train_files, eval_files = split_files(own.corpus_root)
        if not train_files or not eval_files:
            raise SystemExit(
                f"no .py files under {own.corpus_root!r}"
            )
        info_t = prepare_corpus(train_tok, train_files)
        info_e = prepare_corpus(eval_tok, eval_files)
        logger.info(
            "corpus: %d train files -> %s tokens, %d eval files -> "
            "%s tokens (byte-level, vocab 257)",
            len(train_files), f"{info_t['n_tokens']:,}",
            len(eval_files), f"{info_e['n_tokens']:,}",
        )

    prepare_on_host0(prepare, [train_tok, eval_tok])

    param_dtype, compute_dtype = cfg.jax_dtypes()
    model_cfg = llama2.LlamaConfig(
        dim=own.dim, n_layers=own.layers, n_heads=own.heads,
        # Byte tokenizer needs 257 ids (256 bytes + EOT); round up to
        # 512 so the TP Colwise vocab shard divides any tp degree <= 8
        # (the unused tail rows train to zero logits -- harmless).
        vocab_size=512,
        multiple_of=32, max_seq_len=own.seq_len,
        dtype=compute_dtype, param_dtype=param_dtype,
    )
    if cfg.model_parallel == 1:
        cfg.model_parallel = tp.auto_tp_degree(
            jax.device_count(), model_cfg.n_heads, model_cfg.kv_heads,
            cap=4,
        )
    tp.validate_tp_degree(
        model_cfg.n_heads, model_cfg.kv_heads, cfg.model_parallel
    )
    mesh = build_mesh(cfg.mesh_spec())
    dp_size = mesh.shape["data"]
    params = llama2.init_llama(jax.random.key(cfg.seed), model_cfg)
    if cfg.model_parallel > 1:
        specs = hybrid.hybrid_pspecs(
            params, tp.llama_rules(), data_size=dp_size
        )
        constrain = tp.sp_constrain(mesh, dp_axis="data", sp_axis="model")
    else:
        specs = fsdp.param_pspecs(params, axis="data", axis_size=dp_size)
        constrain = lambda x: x  # noqa: E731

    ds = NativeTokenDataset(
        train_tok, batch_size=cfg.global_batch_size,
        seq_len=model_cfg.max_seq_len, seed=cfg.seed,
    )
    ds_eval = NativeTokenDataset(
        eval_tok, batch_size=cfg.global_batch_size,
        seq_len=model_cfg.max_seq_len, seed=cfg.seed + 1,
    )
    n_params = sum(p.size for p in jax.tree.leaves(params))
    logger.info(
        "model: %.1fM params, mesh %s | train %s tokens, eval %s "
        "tokens (held-out files)",
        n_params / 1e6, dict(mesh.shape),
        f"{ds.n_tokens:,}", f"{ds_eval.n_tokens:,}",
    )
    trainer = Trainer(
        cfg, mesh, llama2.make_forward(model_cfg, constrain), params,
        param_pspecs=specs,
    )
    result = trainer.fit(
        ds, eval_dataset=ds_eval, eval_steps=own.eval_steps
    )
    logger.info(
        "run summary | final train loss %.5f | metrics curve: %s",
        result["final_loss"], cfg.metrics_path or "(no --metrics-path)",
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
