"""The benchmark CLI's batch/accum default policy.

bench.py is the driver-facing artifact entry point; its CLI policy
(resolve_batch_accum) decides what configuration every recorded number
describes. The invariants pinned here are the lever-table protocol
from docs/guide/xla_performance_notes.md (measured case study,
ceiling-budget subsection): sweeping
--grad-accum-steps alone holds the microbatch constant, and an
explicit --batch alone reproduces the unaccumulated config.
"""
import importlib.util
import pathlib

import pytest

_BENCH = pathlib.Path(__file__).resolve().parent.parent / "bench.py"


@pytest.fixture(scope="module")
def bench():
    # Import by path: bench.py is a repo-root script, not a package
    # module, and importing it must not initialize a backend.
    spec = importlib.util.spec_from_file_location("bench_cli", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_default_is_microbatch_times_accum8(bench):
    assert bench.resolve_batch_accum(None, None, microbatch=4) == (32, 8)
    assert bench.resolve_batch_accum(None, None, microbatch=1) == (8, 8)


def test_accum_sweep_holds_microbatch_constant(bench):
    # The lever-table protocol: batch scales with accum so every
    # sweep point runs the measured-best microbatch.
    for accum in (1, 2, 4, 8, 16):
        batch, got = bench.resolve_batch_accum(None, accum, microbatch=4)
        assert got == accum
        assert batch // accum == 4
    batch, got = bench.resolve_batch_accum(None, 8, microbatch=1)
    assert (batch, got) == (8, 8)


def test_explicit_batch_runs_unaccumulated(bench):
    # --batch 4 alone must reproduce the round-2 headline config.
    assert bench.resolve_batch_accum(4, None, microbatch=4) == (4, 1)
    assert bench.resolve_batch_accum(16, None, microbatch=1) == (16, 1)


def test_explicit_batch_and_accum_pass_through(bench):
    assert bench.resolve_batch_accum(16, 4, microbatch=4) == (16, 4)


def test_invalid_accum_reaches_trainer_validation(bench):
    # 0 is not silently replaced: it flows to the Trainer, whose
    # config validation rejects it loudly (trainer.py grad_accum >= 1).
    _, accum = bench.resolve_batch_accum(None, 0, microbatch=4)
    assert accum == 0
    _, accum = bench.resolve_batch_accum(8, 0, microbatch=4)
    assert accum == 0


def test_llama_long_threads_block_flags(bench, monkeypatch):
    """--block-q/--block-k(-bwd) must reach the long-context workload
    too, so autotuned tilings apply to the seq-8192 family (the
    harness is shared with bench_llama)."""
    seen = {}

    def fake_bench_llama(steps, remat, batch, attn, block_q=512,
                         block_k=512, **kw):
        seen.update(block_q=block_q, block_k=block_k,
                    block_q_bwd=kw.get("block_q_bwd"),
                    block_k_bwd=kw.get("block_k_bwd"))
        return {"metric": "m", "value": 1, "unit": "u",
                "vs_baseline": 1}

    monkeypatch.setattr(bench, "bench_llama", fake_bench_llama)
    monkeypatch.setenv("TPU_HPC_BENCH_NO_PROBE", "1")
    rc = bench.main([
        "--workload", "llama-long", "--block-q", "256",
        "--block-k", "1024", "--block-q-bwd", "128",
        "--block-k-bwd", "512",
    ])
    assert rc == 0
    assert seen == {
        "block_q": 256, "block_k": 1024,
        "block_q_bwd": 128, "block_k_bwd": 512,
    }


def test_pp_accum_divisibility_validated(bench):
    # The PP workload validates --grad-accum-steps against the
    # pipeline microbatch size up front (a non-divisor would otherwise
    # raise deep inside tracing, bench.py round-4 parity levers).
    with pytest.raises(ValueError, match="must divide"):
        bench.bench_llama_pp(grad_accum_steps=3, microbatch_size=4)
    with pytest.raises(ValueError, match="must divide"):
        bench.bench_llama_pp(grad_accum_steps=8, microbatch_size=4)


def test_pp_model_llama_validation(bench):
    with pytest.raises(ValueError, match="stack|llama"):
        bench.bench_llama_pp(model="no-such-model")


def test_bench_model_cfg_is_single_source(bench):
    # The comparability claim of the flagship pp row rests on every
    # llama-family workload building THE same architecture from one
    # factory; a second hardcoded config literal would let them drift.
    cfg = bench.bench_model_cfg()
    assert (cfg.dim, cfg.n_layers, cfg.n_heads, cfg.vocab_size) == (
        1024, 8, 8, 32000
    )
    assert bench.bench_model_cfg(seq_len=8192).max_seq_len == 8192
    import pathlib
    src = pathlib.Path(bench.__file__).read_text()
    # Exactly one dim=1024 Llama literal: the factory's own.
    assert src.count("dim=1024, n_layers=8") == 1


def test_block_defaults_reconciled_cli_vs_functions(bench):
    """The CLI's --block-q/--block-k defaults and the bench_* function
    defaults must agree (they drifted in round 5: CLI 1024 vs function
    512, so the two entry points silently measured different flash
    tilings -- ADVICE r5). Pinned via introspection so the next retune
    must move both."""
    import inspect

    ap_defaults = {}
    for fn_name in ("bench_llama", "bench_llama_long", "bench_llama_pp"):
        sig = inspect.signature(getattr(bench, fn_name))
        ap_defaults[fn_name] = (
            sig.parameters["block_q"].default,
            sig.parameters["block_k"].default,
        )
    assert set(ap_defaults.values()) == {(512, 1024)}, ap_defaults
    src = pathlib.Path(bench.__file__).read_text()
    assert '"--block-q", type=int, default=512' in src
    assert '"--block-k", type=int, default=1024' in src


def test_records_carry_effective_flash_blocks(bench):
    """Every flash-attention artifact row must be self-describing
    about its tiling, with bwd defaults resolved; xla rows carry no
    block fields (there is no tiling to describe)."""
    rec = bench.flash_blocks_record("flash", 512, 1024, None, None)
    assert rec == {
        "flash_blocks": {"q": 512, "k": 1024, "q_bwd": 512, "k_bwd": 1024}
    }
    rec = bench.flash_blocks_record("flash", 256, 512, 128, 256)
    assert rec["flash_blocks"] == {
        "q": 256, "k": 512, "q_bwd": 128, "k_bwd": 256
    }
    assert bench.flash_blocks_record("xla", 512, 1024, None, None) == {}


def test_comm_mode_routes_to_bench_llama(bench, monkeypatch):
    """--comm-mode must reach the workload (and through it the
    Trainer's gradient-sync layer); defaulting silently to flat would
    make every comm-mode sweep measure the same thing."""
    seen = {}

    def fake_bench_llama(steps, remat, batch, attn, block_q=512,
                         block_k=1024, **kw):
        seen.update(comm_mode=kw.get("comm_mode"))
        return {"metric": "m", "value": 1, "unit": "u",
                "vs_baseline": 1}

    monkeypatch.setattr(bench, "bench_llama", fake_bench_llama)
    monkeypatch.setenv("TPU_HPC_BENCH_NO_PROBE", "1")
    rc = bench.main(["--comm-mode", "bucketed_overlap"])
    assert rc == 0
    assert seen == {"comm_mode": "bucketed_overlap"}


def test_guard_mode_routes_to_bench_llama(bench, monkeypatch):
    """--guard-mode must reach the workload (and through it the
    Trainer's numeric-health guard); a row labeled guarded that
    silently ran unguarded would misprice the guard's cost."""
    seen = {}

    def fake_bench_llama(steps, remat, batch, attn, block_q=512,
                         block_k=1024, **kw):
        seen.update(guard_mode=kw.get("guard_mode"))
        return {"metric": "m", "value": 1, "unit": "u",
                "vs_baseline": 1}

    monkeypatch.setattr(bench, "bench_llama", fake_bench_llama)
    monkeypatch.setenv("TPU_HPC_BENCH_NO_PROBE", "1")
    rc = bench.main(["--guard-mode", "skip"])
    assert rc == 0
    assert seen == {"guard_mode": "skip"}


def test_guard_mode_on_nonconsuming_workload_is_cli_error(
    bench, monkeypatch
):
    """The --comm-mode misplaced-flag discipline applies to the guard
    flag too."""
    monkeypatch.setenv("TPU_HPC_BENCH_NO_PROBE", "1")
    with pytest.raises(SystemExit) as ei:
        bench.main(["--workload", "serve", "--guard-mode", "skip"])
    assert ei.value.code == 2


def test_llama_records_carry_comm_mode(bench):
    """Training records must be attributable to their gradient-sync
    strategy: bench_llama (and llama-long through it) records
    comm_mode in every JSON row, defaulting to the flat GSPMD path."""
    import inspect

    sig = inspect.signature(bench.bench_llama)
    assert sig.parameters["comm_mode"].default == "flat"
    assert (
        inspect.signature(bench.bench_llama_long)
        .parameters["comm_mode"].default == "flat"
    )
    src = pathlib.Path(bench.__file__).read_text()
    # The record literally carries the effective mode (not a constant).
    assert '"comm_mode": comm_mode' in src


def test_serve_record_schema_matches_training_benches(bench):
    """--serve artifacts must land in the same record schema every
    training workload emits (metric/value/unit/vs_baseline), with the
    serving-native latency quantiles riding along."""
    summary = {
        "tokens_per_s_per_chip": 123.456, "serve_mfu": 0.10,
        "ttft_ms_p50": 25.0, "ttft_ms_p95": 40.0,
        "itl_ms_p50": 8.0, "itl_ms_p95": 12.0,
        "requests": 32, "slots": 8, "prefill_buckets": [128, 256],
        "recompiles": 0,
    }
    rec = bench.serve_record(summary)
    assert set(rec) >= {"metric", "value", "unit", "vs_baseline"}
    assert rec["metric"] == "serve_tokens_per_s_per_chip"
    assert rec["value"] == 123.5
    assert rec["unit"] == "tokens/s/chip"
    assert rec["vs_baseline"] == 0.25  # 0.10 MFU / 0.40 target
    assert rec["ttft_ms_p50"] == 25.0 and rec["itl_ms_p95"] == 12.0
    assert rec["serve"]["recompiles"] == 0
    # No published peak (CPU sim) -> honest None, not a fake ratio.
    no_mfu = bench.serve_record({**summary, "serve_mfu": None})
    assert no_mfu["vs_baseline"] is None


def test_emitted_record_is_schema_stamped(bench, monkeypatch, capsys):
    """PR 4: the one JSON line bench prints is a ``bench`` event in
    the unified telemetry schema -- same validator as the train and
    serve JSONL sinks."""
    from tpu_hpc.obs import validate_record

    monkeypatch.setattr(
        bench, "bench_serve",
        lambda **kw: {"metric": "serve_tokens_per_s_per_chip",
                      "value": 1, "unit": "tokens/s/chip",
                      "vs_baseline": None},
    )
    monkeypatch.setenv("TPU_HPC_BENCH_NO_PROBE", "1")
    assert bench.main(["--workload", "serve"]) == 0
    import json

    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    validate_record(rec)
    assert rec["event"] == "bench"
    assert rec["schema_version"] == 1
    assert rec["run_id"] and rec["host"]


def test_serve_mode_routes_flags(bench, monkeypatch):
    """Both spellings (--serve and --workload serve) reach bench_serve
    with the serve-specific knobs."""
    seen = {}

    def fake_bench_serve(requests, slots, max_new, disagg=False,
                         paged=False, block_size=None, kv_blocks=None,
                         prefill_chunk=None, spec="off", spec_k=None,
                         draft_ckpt=None, host_blocks=None,
                         kernel=None, kv_quant=None):
        seen.update(requests=requests, slots=slots, max_new=max_new,
                    disagg=disagg, paged=paged,
                    block_size=block_size, kv_blocks=kv_blocks,
                    prefill_chunk=prefill_chunk, spec=spec,
                    spec_k=spec_k, draft_ckpt=draft_ckpt,
                    host_blocks=host_blocks,
                    kernel=kernel, kv_quant=kv_quant)
        return {"metric": "serve_tokens_per_s_per_chip", "value": 1,
                "unit": "tokens/s/chip", "vs_baseline": None}

    monkeypatch.setattr(bench, "bench_serve", fake_bench_serve)
    monkeypatch.setenv("TPU_HPC_BENCH_NO_PROBE", "1")
    rc = bench.main([
        "--serve", "--serve-requests", "12", "--serve-slots", "4",
        "--serve-max-new", "7",
    ])
    assert rc == 0
    assert seen == {"requests": 12, "slots": 4, "max_new": 7,
                    "disagg": False, "paged": False,
                    "block_size": None, "kv_blocks": None,
                    "prefill_chunk": None, "spec": "off",
                    "spec_k": None, "draft_ckpt": None,
                    "host_blocks": None,
                    "kernel": None, "kv_quant": None}
    seen.clear()
    assert bench.main(["--workload", "serve"]) == 0
    assert seen["requests"] == 32 and seen["slots"] == 8
    assert seen["max_new"] == 64 and seen["disagg"] is False
    seen.clear()
    assert bench.main(["--workload", "serve", "--serve-disagg"]) == 0
    assert seen["disagg"] is True
    seen.clear()
    assert bench.main([
        "--workload", "serve", "--serve-paged",
        "--serve-block-size", "32", "--serve-kv-blocks", "512",
        "--serve-prefill-chunk", "128",
    ]) == 0
    assert seen["paged"] is True and seen["block_size"] == 32
    assert seen["kv_blocks"] == 512 and seen["prefill_chunk"] == 128
    seen.clear()
    assert bench.main([
        "--workload", "serve", "--serve-paged",
        "--serve-kernel", "pallas", "--serve-kv-quant", "int8",
    ]) == 0
    assert seen["kernel"] == "pallas" and seen["kv_quant"] == "int8"
    seen.clear()
    assert bench.main([
        "--workload", "serve", "--serve-paged",
        "--serve-spec", "ngram", "--spec-k", "3",
    ]) == 0
    assert seen["spec"] == "ngram" and seen["spec_k"] == 3
    seen.clear()
    assert bench.main([
        "--workload", "serve", "--serve-paged",
        "--serve-host-blocks", "4096",
    ]) == 0
    assert seen["paged"] is True and seen["host_blocks"] == 4096


def test_serve_alias_conflicts_with_explicit_workload(bench, monkeypatch):
    monkeypatch.setenv("TPU_HPC_BENCH_NO_PROBE", "1")
    with pytest.raises(SystemExit):
        bench.main(["--workload", "llama", "--serve"])


def test_loadgen_mode_routes_flags(bench, monkeypatch):
    """--workload loadgen reaches bench_loadgen with the scenario and
    sizing knobs (requests doubled vs serve: the harness measures
    queueing, which needs backlog)."""
    seen = {}

    def fake_bench_loadgen(scenario, requests, slots, max_new,
                           paged=False, block_size=None,
                           kv_blocks=None, prefill_chunk=None,
                           model="bench", spec="off", spec_k=None,
                           draft_ckpt=None, fleet=0, fleet_min=1,
                           fleet_swap_at=None,
                           fleet_router="affinity", host_blocks=None,
                           kernel=None, kv_quant=None):
        seen.update(scenario=scenario, requests=requests, slots=slots,
                    max_new=max_new, paged=paged, spec=spec,
                    host_blocks=host_blocks)
        return {"metric": "loadgen_x_ttft_ms_p95", "value": 1.0,
                "unit": "virtual_ms", "vs_baseline": None}

    monkeypatch.setattr(bench, "bench_loadgen", fake_bench_loadgen)
    monkeypatch.setenv("TPU_HPC_BENCH_NO_PROBE", "1")
    rc = bench.main([
        "--workload", "loadgen", "--loadgen-scenario", "bursty",
        "--serve-requests", "16", "--serve-slots", "4",
        "--serve-max-new", "16",
    ])
    assert rc == 0
    assert seen == {"scenario": "bursty", "requests": 32, "slots": 4,
                    "max_new": 16, "paged": False, "spec": "off",
                    "host_blocks": None}
    seen.clear()
    assert bench.main([
        "--workload", "loadgen", "--loadgen-scenario",
        "shared_prefix", "--serve-paged",
    ]) == 0
    assert seen["scenario"] == "shared_prefix"
    assert seen["paged"] is True
    seen.clear()
    assert bench.main([
        "--workload", "loadgen", "--loadgen-scenario",
        "long_idle_sessions", "--serve-paged",
        "--serve-host-blocks", "512",
    ]) == 0
    assert seen["scenario"] == "long_idle_sessions"
    assert seen["host_blocks"] == 512
    # Misplaced scenario flag = CLI error (the --comm-mode
    # discipline), never a silently-plain run recorded as the
    # scenario.
    with pytest.raises(SystemExit):
        bench.main(["--loadgen-scenario", "colocate"])
    with pytest.raises(SystemExit):
        bench.main(["--workload", "serve",
                    "--loadgen-scenario", "colocate"])


def test_paged_flags_guarded_like_comm_mode(bench, monkeypatch):
    """--serve-paged on a workload that never consumes it is a CLI
    error (a slab row labeled paged would poison the bank), and the
    paged sizing flags require --serve-paged."""
    monkeypatch.setenv("TPU_HPC_BENCH_NO_PROBE", "1")
    with pytest.raises(SystemExit):
        bench.main(["--workload", "llama", "--serve-paged"])
    for flag, val in (
        ("--serve-block-size", "16"),
        ("--serve-kv-blocks", "64"),
        ("--serve-host-blocks", "4096"),
        ("--serve-prefill-chunk", "128"),
    ):
        with pytest.raises(SystemExit):
            bench.main(["--workload", "serve", flag, val])
    # A 1-slot host tier could never hold a page (slot 0 is scratch):
    # a parse error, not a row labeled tiered that never spilled.
    with pytest.raises(SystemExit):
        bench.main(["--workload", "serve", "--serve-paged",
                    "--serve-host-blocks", "1"])
    # The tiny dev model is only legal where quantiles are
    # virtual-clock (loadgen); a wall-clock serve row on it would
    # wear the bench label while measuring a different machine.
    with pytest.raises(SystemExit):
        bench.main(["--workload", "serve", "--serve-model", "tiny"])


def test_spec_flags_guarded_like_comm_mode(bench, monkeypatch):
    """The speculative flags follow the misplaced-flag discipline: a
    spec flag on a workload (or cache layout) that cannot consume it
    is a CLI error, not a greedy row wearing a spec label."""
    monkeypatch.setenv("TPU_HPC_BENCH_NO_PROBE", "1")
    # Non-consuming workload.
    with pytest.raises(SystemExit):
        bench.main(["--workload", "llama", "--serve-spec", "ngram"])
    # Spec rides the paged engine only.
    with pytest.raises(SystemExit):
        bench.main(["--workload", "serve", "--serve-spec", "ngram"])
    # Disagg cannot consume the verify program.
    with pytest.raises(SystemExit):
        bench.main(["--workload", "serve", "--serve-paged",
                    "--serve-spec", "ngram", "--serve-disagg"])
    # Spec knobs require --serve-spec (and the ckpt requires draft
    # mode specifically).
    with pytest.raises(SystemExit):
        bench.main(["--workload", "serve", "--serve-paged",
                    "--spec-k", "4"])
    with pytest.raises(SystemExit):
        bench.main(["--workload", "serve", "--serve-paged",
                    "--serve-draft-ckpt", "/tmp/x"])
    with pytest.raises(SystemExit):
        bench.main(["--workload", "serve", "--serve-paged",
                    "--serve-spec", "ngram",
                    "--serve-draft-ckpt", "/tmp/x"])
    # k=0 must error loudly, not coerce to the default 4 (server.py's
    # guard, mirrored).
    with pytest.raises(SystemExit):
        bench.main(["--workload", "serve", "--serve-paged",
                    "--serve-spec", "ngram", "--spec-k", "0"])


def test_serve_record_carries_spec_identity(bench):
    """Speculative rows are labeled with mode/k and carry the two
    judged signals (acceptance rate, draft cost)."""
    base = {
        "requests": 8, "slots": 4, "prefill_buckets": [8],
        "recompiles": 0, "tokens_per_s_per_chip": 10.0,
        "ttft_ms_p50": 1.0, "ttft_ms_p95": 2.0,
        "itl_ms_p50": 1.0, "itl_ms_p95": 2.0,
        "kv_layout": "paged", "kv_block_size": 16, "kv_blocks": 64,
        "prefix_hit_rate": 0.0, "prefix_hit_blocks": 0,
        "spec_mode": "ngram", "spec_k": 4,
        "acceptance_rate": 0.875, "verify_steps": 10,
        "draft_ms": 1.5,
    }
    rec = bench.serve_record(base)
    # Spec rows bank under their own per-mode metric family: the
    # --bank reduction reads only top-level value + side keys, so a
    # spec row under the greedy family would set itl/ttft marks the
    # next greedy row gets judged against.
    assert rec["metric"] == "serve_spec_ngram_tokens_per_s_per_chip"
    assert rec["acceptance_rate"] == 0.875  # top level: gate-visible
    assert rec["serve"]["spec_mode"] == "ngram"
    assert rec["serve"]["spec_k"] == 4
    assert rec["serve"]["acceptance_rate"] == 0.875
    assert rec["serve"]["draft_ms"] == 1.5
    # Loadgen rows bank under their own spec metric family.
    lg = bench.loadgen_record({
        "scenario": "heavy_tail", "seed": 0, "shed": 0, "queued": 1,
        "occupancy_mean": 0.5, "stall_events": 0,
        "slo_violations": [], "recompiles": 0, "tenants": {},
        "kv_layout": "paged", "kv_block_size": 16, "kv_blocks": 64,
        "prefix_hit_rate": 0.1, "spec_mode": "ngram", "spec_k": 4,
        "acceptance_rate": 0.9, "verify_steps": 5, "draft_ms": 0.0,
        "ttft_ms_p50": 1.0, "ttft_ms_p95": 2.0, "ttft_ms_p99": 3.0,
        "itl_ms_p50": 1.0, "itl_ms_p95": 2.0,
    })
    assert lg["metric"] == \
        "loadgen_heavy_tail_paged_spec_ngram_ttft_ms_p95"
    assert lg["loadgen"]["spec_mode"] == "ngram"
    assert lg["loadgen"]["acceptance_rate"] == 0.9
    assert lg["acceptance_rate"] == 0.9  # top level: gate-visible


def test_serve_record_carries_kv_layout(bench):
    """Serve records are labeled with their cache layout; paged rows
    add block size + prefix-hit evidence."""
    base = {
        "requests": 8, "slots": 4, "prefill_buckets": [8],
        "recompiles": 0, "tokens_per_s_per_chip": 10.0,
        "ttft_ms_p50": 1.0, "ttft_ms_p95": 2.0,
        "itl_ms_p50": 1.0, "itl_ms_p95": 2.0,
    }
    rec = bench.serve_record(dict(base, kv_layout="slab"))
    assert rec["serve"]["kv_layout"] == "slab"
    assert "prefix_hit_rate" not in rec["serve"]
    rec = bench.serve_record(dict(
        base, kv_layout="paged", kv_block_size=16, kv_blocks=64,
        prefix_hit_rate=0.25, prefix_hit_blocks=12,
        batcher={"block_stalls": 3},
    ))
    assert rec["serve"]["kv_layout"] == "paged"
    assert rec["serve"]["kv_block_size"] == 16
    assert rec["serve"]["prefix_hit_rate"] == 0.25
    assert rec["serve"]["block_stalls"] == 3


def test_loadgen_record_schema_matches_training_benches(bench):
    """Loadgen rows land in the same record schema as every other
    workload, with the shed/queued admission evidence riding along."""
    summary = {
        "scenario": "multi_tenant", "seed": 0,
        "ttft_ms_p50": 5.0, "ttft_ms_p95": 20.0, "ttft_ms_p99": 30.0,
        "itl_ms_p50": 8.0, "itl_ms_p95": 12.0,
        "shed": 3, "queued": 7, "occupancy_mean": 0.8,
        "stall_events": 1, "slo_violations": [], "recompiles": 0,
        "tenants": {
            "background": {"shed": 3, "queued": 2,
                           "ttft_ms_p95": 40.0},
        },
    }
    rec = bench.loadgen_record(summary)
    assert set(rec) >= {"metric", "value", "unit", "vs_baseline"}
    assert rec["metric"] == "loadgen_multi_tenant_ttft_ms_p95"
    assert rec["value"] == 20.0 and rec["unit"] == "virtual_ms"
    assert rec["loadgen"]["shed"] == 3
    assert rec["loadgen"]["tenants"]["background"]["shed"] == 3
    from tpu_hpc.obs import stamp, validate_record

    validate_record(stamp({"event": "bench", **rec}))
