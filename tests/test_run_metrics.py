"""Run observability: the JSONL metrics log and the config snapshot.

Parity anchors: the reference's append-only benchmark_results.log
(scripts/main.py:381-397) and the metadata-rich CSV headers of its
comm benchmark (tests/torch_comm_bench.py:137-194) -- here as
structured per-run records written by the Trainer itself.
"""
import json
import math

import jax
import pytest

from tpu_hpc.config import TrainingConfig
from tpu_hpc.models import datasets, losses
from tpu_hpc.models.unet import UNetConfig, apply_unet, init_unet
from tpu_hpc.parallel import dp
from tpu_hpc.train import Trainer


@pytest.fixture(scope="module")
def tiny_setup():
    cfg_model = UNetConfig(in_channels=4, out_channels=4, base_features=4)
    params, ms = init_unet(jax.random.key(0), cfg_model, (21, 24, 4))
    ds = datasets.ERA5Synthetic(n_vars=2, n_levels=2, lat=21, lon=24)

    def forward(params, model_state, batch, step_rng):
        x, y = batch
        pred, new_ms = apply_unet(
            params, model_state, x, cfg_model, train=True
        )
        return losses.lat_weighted_mse(pred, y), new_ms, {}

    return forward, params, ms, ds


class TestConfigYaml:
    def test_round_trip(self, tmp_path):
        cfg = TrainingConfig(
            epochs=3, global_batch_size=64, learning_rate=5e-4,
            adam_moments_dtype="bfloat16", metrics_path="m.jsonl",
        )
        path = cfg.to_yaml(str(tmp_path / "c.yaml"))
        assert TrainingConfig.from_yaml(path) == cfg


class TestMetricsLog:
    def test_records_written(self, mesh8, tiny_setup, tmp_path):
        forward, params, ms, ds = tiny_setup
        mpath = str(tmp_path / "run.jsonl")
        cfg = TrainingConfig(
            epochs=2, global_batch_size=16, steps_per_epoch=2,
            metrics_path=mpath,
        )
        tr = Trainer(
            cfg, mesh8, forward, params, ms,
            param_pspecs=dp.param_pspecs(params),
            batch_pspec=dp.batch_pspec(),
        )
        tr.fit(ds)
        # Every record speaks the unified telemetry schema
        # (tpu_hpc.obs): stamped, and one validator covers the file.
        from tpu_hpc.obs import validate_file

        assert validate_file(mpath) > 0
        records = [
            json.loads(line) for line in open(mpath)
        ]
        events = [r["event"] for r in records]
        # Core run-log sequence, with the obs additions interleaved:
        # a compute span per chunk and the closing registry snapshot.
        assert [e for e in events
                if e in ("run_start", "epoch", "run_end")] == [
            "run_start", "epoch", "epoch", "run_end"
        ]
        assert events.count("span") == 2
        assert events[-1] == "metrics"
        for r in records:
            assert r["schema_version"] == 1
            assert r["run_id"] == records[0]["run_id"]
        start = records[0]
        assert start["total_steps"] == 4
        assert start["n_devices"] == 8
        assert start["config"]["global_batch_size"] == 16
        assert start["jax_version"] == jax.__version__
        for i, r in enumerate(
            r for r in records if r["event"] == "epoch"
        ):
            assert r["epoch"] == i
            assert r["step"] == (i + 1) * 2
            assert math.isfinite(r["loss"])
            assert r["items_per_s"] > 0
            assert r["s_per_step"] > 0
        # Goodput / restart accounting rides the closing record
        # (resilience: every fit leaves an auditable productive-vs-
        # overhead trail; see docs/guide/resilience.md).
        end = [r for r in records if r["event"] == "run_end"][-1]
        assert end["step"] == 4
        assert end["preempted"] is False
        assert end["attempt"] == 0
        assert end["resumed_from_step"] == 0
        assert end["goodput"]["productive_s"] > 0
        assert 0.0 <= end["goodput"]["goodput"] <= 1.0

    def test_appends_across_runs(self, mesh8, tiny_setup, tmp_path):
        """Two fits append to the same file -- the reference's
        append-only log behavior, enabling cross-run comparison."""
        forward, params, ms, ds = tiny_setup
        mpath = str(tmp_path / "run.jsonl")
        cfg = TrainingConfig(
            epochs=1, global_batch_size=16, steps_per_epoch=2,
            metrics_path=mpath,
        )
        for _ in range(2):
            tr = Trainer(
                cfg, mesh8, forward, params, ms,
                param_pspecs=dp.param_pspecs(params),
                batch_pspec=dp.batch_pspec(),
            )
            tr.fit(ds)
        events = [json.loads(x)["event"] for x in open(mpath)]
        assert [e for e in events
                if e in ("run_start", "epoch", "run_end")] == [
            "run_start", "epoch", "run_end"
        ] * 2

    def test_nested_path_created(self, mesh8, tiny_setup, tmp_path):
        """A metrics_path in a directory that does not exist yet must
        not abort the run (review finding)."""
        forward, params, ms, ds = tiny_setup
        mpath = str(tmp_path / "logs" / "deep" / "run.jsonl")
        cfg = TrainingConfig(
            epochs=1, global_batch_size=16, steps_per_epoch=1,
            metrics_path=mpath,
        )
        tr = Trainer(
            cfg, mesh8, forward, params, ms,
            param_pspecs=dp.param_pspecs(params),
            batch_pspec=dp.batch_pspec(),
        )
        tr.fit(ds)
        events = [json.loads(x)["event"] for x in open(mpath)]
        assert [e for e in events
                if e in ("run_start", "epoch", "run_end")] == [
            "run_start", "epoch", "run_end"
        ]

    def test_off_by_default(self, mesh8, tiny_setup, tmp_path):
        forward, params, ms, ds = tiny_setup
        cfg = TrainingConfig(
            epochs=1, global_batch_size=16, steps_per_epoch=1,
        )
        tr = Trainer(
            cfg, mesh8, forward, params, ms,
            param_pspecs=dp.param_pspecs(params),
            batch_pspec=dp.batch_pspec(),
        )
        tr.fit(ds)
        assert list(tmp_path.iterdir()) == []


class TestConfigSnapshot:
    def test_written_next_to_checkpoints(self, mesh8, tiny_setup,
                                         tmp_path):
        from tpu_hpc.ckpt import CheckpointManager

        forward, params, ms, ds = tiny_setup
        ckdir = str(tmp_path / "ckpt")
        cfg = TrainingConfig(
            epochs=1, global_batch_size=16, steps_per_epoch=2,
            save_every=1, checkpoint_dir=ckdir,
        )
        tr = Trainer(
            cfg, mesh8, forward, params, ms,
            param_pspecs=dp.param_pspecs(params),
            batch_pspec=dp.batch_pspec(),
            checkpoint_manager=CheckpointManager(ckdir),
        )
        tr.fit(ds)
        snap = TrainingConfig.from_yaml(f"{ckdir}/config.yaml")
        assert snap == cfg

    def test_no_snapshot_before_first_save(self, mesh8, tiny_setup,
                                           tmp_path):
        """A run that never checkpoints must not write config.yaml --
        it would relabel shards an earlier run left in the directory
        (review finding)."""
        from tpu_hpc.ckpt import CheckpointManager

        forward, params, ms, ds = tiny_setup
        ckdir = str(tmp_path / "ckpt")
        cfg = TrainingConfig(
            epochs=1, global_batch_size=16, steps_per_epoch=1,
            save_every=0, checkpoint_dir=ckdir, resume=False,
        )
        tr = Trainer(
            cfg, mesh8, forward, params, ms,
            param_pspecs=dp.param_pspecs(params),
            batch_pspec=dp.batch_pspec(),
            checkpoint_manager=CheckpointManager(ckdir),
        )
        tr.fit(ds)
        import os

        assert not os.path.exists(f"{ckdir}/config.yaml")

    def test_snapshot_records_effective_epochs(
        self, mesh8, tiny_setup, tmp_path
    ):
        """fit(epochs=) overrides must be what the snapshot says, or
        re-running from it trains a different length (review
        finding)."""
        from tpu_hpc.ckpt import CheckpointManager

        forward, params, ms, ds = tiny_setup
        ckdir = str(tmp_path / "ckpt")
        cfg = TrainingConfig(
            epochs=1, global_batch_size=16, steps_per_epoch=1,
            save_every=1, checkpoint_dir=ckdir, resume=False,
        )
        tr = Trainer(
            cfg, mesh8, forward, params, ms,
            param_pspecs=dp.param_pspecs(params),
            batch_pspec=dp.batch_pspec(),
            checkpoint_manager=CheckpointManager(ckdir),
        )
        tr.fit(ds, epochs=2)
        snap = TrainingConfig.from_yaml(f"{ckdir}/config.yaml")
        assert snap.epochs == 2


class TestThroughputMeterBounded:
    """PR 4 satellite: the per-batch sample lists must not grow host
    memory without limit on million-step runs."""

    def test_window_bounds_samples(self):
        from tpu_hpc.train.metrics import ThroughputMeter

        m = ThroughputMeter(n_devices=2, window=8)
        for _ in range(100):
            m.start_batch()
            m.end_batch(4)
        assert len(m.batch_times) == 8
        assert len(m.batch_items) == 8
        assert m.last_throughput > 0
        s = m.epoch_summary(skip_first=1)
        assert s["batches"] == 7  # newest window minus warmup skip

    def test_epoch_summary_math_unchanged(self):
        """Pinned: the windowing must not change what a summary over
        fewer-than-window batches reports."""
        from tpu_hpc.train.metrics import ThroughputMeter

        m = ThroughputMeter(n_devices=2)
        m.batch_times.extend([5.0, 1.0, 3.0])
        m.batch_items.extend([10, 10, 30])
        s = m.epoch_summary(skip_first=1)
        assert s["items_per_s"] == pytest.approx(10.0)  # 40 / 4
        assert s["items_per_s_per_device"] == pytest.approx(5.0)
        assert s["mean_batch_s"] == pytest.approx(2.0)
        assert s["total_s"] == pytest.approx(4.0)
        assert s["batches"] == 2
        # skip_first falls back to everything when it would empty the
        # window (single-batch epochs).
        assert ThroughputMeter().epoch_summary()["items_per_s"] == 0.0

    def test_rejects_bad_window(self):
        from tpu_hpc.train.metrics import ThroughputMeter

        with pytest.raises(ValueError):
            ThroughputMeter(window=0)


class TestEvalRecord:
    def test_eval_appends_record(self, mesh8, tiny_setup, tmp_path):
        """evaluate() writes an 'eval' record with the step it ran at
        and every eval metric."""
        forward, params, ms, ds = tiny_setup
        mpath = str(tmp_path / "run.jsonl")
        cfg = TrainingConfig(
            epochs=1, global_batch_size=16, steps_per_epoch=2,
            metrics_path=mpath,
        )
        tr = Trainer(
            cfg, mesh8, forward, params, ms,
            param_pspecs=dp.param_pspecs(params),
            batch_pspec=dp.batch_pspec(),
            eval_forward=lambda p, m, b: (
                jax.numpy.float32(0.5), {"acc": jax.numpy.float32(1.0)}
            ),
        )
        tr.fit(ds)
        tr.evaluate(ds, n_steps=2)
        records = [json.loads(x) for x in open(mpath)]
        ev = [r for r in records if r["event"] == "eval"]
        assert len(ev) == 1
        assert ev[0]["step"] == 2 and ev[0]["n_steps"] == 2
        assert ev[0]["loss"] == 0.5 and ev[0]["acc"] == 1.0
