"""Strided/asymmetric halo ops and the domain-parallel U-Net.

Oracle = the single-device computation on the SAME values:
``jax.lax.conv`` with SAME padding for the strided convs,
``jax.image.resize`` for the bilinear upsample, and the flax
``apply_unet`` itself for the whole network (the domain twin consumes
``init_unet``'s own trees). Parity target: the strided-downsampling
capability the reference documents for ShardTensor
(docs/guide/10_domain_parallel.md:113-149) at its U-Net's real shape
(multinode_ddp_unet.py:171-214).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hpc.parallel import domain, domain_unet
from tpu_hpc.runtime import MeshSpec, build_mesh


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(MeshSpec(axes={"data": 2, "spatial": 4}))


def oracle_conv(x, kernel, stride=1, wrap=False):
    if wrap:
        kh, s = kernel.shape[0], stride
        lo = (kh - s) // 2 if kh > s else 0
        hi = max(kh - s - lo, 0)
        parts = [x[:, x.shape[1] - lo:] if lo else None, x,
                 x[:, :hi] if hi else None]
        x = jnp.concatenate([p for p in parts if p is not None], axis=1)
        pad_h = (0, 0)
        kw = kernel.shape[1]
        w_out = -(-x.shape[2] // stride)
        tw = max((w_out - 1) * stride + kw - x.shape[2], 0)
        pad_w = (tw // 2, tw - tw // 2)
        return jax.lax.conv_general_dilated(
            x, kernel, (stride, stride), (pad_h, pad_w),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
    return jax.lax.conv_general_dilated(
        x, kernel, (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def rand(key, shape, scale=1.0):
    return scale * jax.random.normal(key, shape, jnp.float32)


class TestStridedHaloConv:
    @pytest.mark.parametrize(
        "k,stride", [(3, 2), (2, 2), (5, 2), (1, 2), (4, 2), (3, 4),
                     (4, 1), (5, 1)],
    )
    def test_matches_same_conv(self, mesh, k, stride):
        """Any (kernel, stride): halo windows land exactly where XLA
        SAME places them, including the asymmetric odd-total splits."""
        kx, kk = jax.random.split(jax.random.key(k * 10 + stride))
        x = rand(kx, (2, 32, 16, 3))
        kernel = rand(kk, (k, k, 3, 5), 0.1)
        fn = domain.domain_parallel(
            lambda ax, p, t: domain.halo_conv2d(
                t, p, axis_name=ax, stride=stride
            ),
            mesh,
        )
        got = jax.jit(fn)(kernel, x)
        want = oracle_conv(x, kernel, stride)
        np.testing.assert_allclose(got, want, atol=1e-5)

    @pytest.mark.parametrize("k,stride", [(4, 2), (3, 1), (6, 2)])
    def test_periodic_strided(self, mesh, k, stride):
        kx, kk = jax.random.split(jax.random.key(k))
        x = rand(kx, (2, 32, 16, 3))
        kernel = rand(kk, (k, k, 3, 4), 0.1)
        fn = domain.domain_parallel(
            lambda ax, p, t: domain.halo_conv2d(
                t, p, axis_name=ax, stride=stride, wrap=True
            ),
            mesh,
        )
        got = jax.jit(fn)(kernel, x)
        want = oracle_conv(x, kernel, stride, wrap=True)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_periodic_odd_split_rejected(self, mesh):
        kernel = jnp.zeros((3, 3, 3, 4))
        fn = domain.domain_parallel(
            lambda ax, p, t: domain.halo_conv2d(
                t, p, axis_name=ax, stride=2, wrap=True
            ),
            mesh,
        )
        with pytest.raises(ValueError, match="k-s even"):
            jax.jit(fn)(kernel, jnp.zeros((2, 32, 16, 3)))

    def test_stride_must_divide_tile(self, mesh):
        kernel = jnp.zeros((3, 3, 3, 4))
        fn = domain.domain_parallel(
            lambda ax, p, t: domain.halo_conv2d(
                t, p, axis_name=ax, stride=3
            ),
            mesh,
        )
        # H_loc = 32/4 = 8, not divisible by 3.
        with pytest.raises(ValueError, match="divide by stride"):
            jax.jit(fn)(kernel, jnp.zeros((2, 32, 16, 3)))

    def test_grad_matches_oracle(self, mesh):
        """The strided halo conv's vjp (transposed ppermutes + conv
        transpose) equals the single-device gradient."""
        kx, kk = jax.random.split(jax.random.key(7))
        x = rand(kx, (2, 32, 16, 3))
        kernel = rand(kk, (3, 3, 3, 5), 0.1)
        fn = domain.domain_parallel(
            lambda ax, p, t: domain.halo_conv2d(
                t, p, axis_name=ax, stride=2
            ),
            mesh,
        )

        def loss_pp(k_, x_):
            return jnp.sum(jax.jit(fn)(k_, x_) ** 2)

        def loss_or(k_, x_):
            return jnp.sum(oracle_conv(x_, k_, 2) ** 2)

        gk, gx = jax.grad(loss_pp, argnums=(0, 1))(kernel, x)
        wk, wx = jax.grad(loss_or, argnums=(0, 1))(kernel, x)
        np.testing.assert_allclose(gk, wk, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(gx, wx, rtol=1e-4, atol=1e-4)


class TestPoolAndUpsample:
    def test_pool_matches(self, mesh):
        import flax.linen as nn

        x = rand(jax.random.key(3), (2, 32, 16, 6))
        fn = domain.domain_parallel(
            lambda ax, p, t: domain.max_pool_2x2(t), mesh
        )
        got = jax.jit(fn)({}, x)
        want = nn.max_pool(x, (2, 2), strides=(2, 2))
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_upsample_matches_resize(self, mesh):
        x = rand(jax.random.key(4), (2, 16, 8, 6))
        fn = domain.domain_parallel(
            lambda ax, p, t: domain.halo_upsample2x(t, ax), mesh
        )
        got = jax.jit(fn)({}, x)
        b, h, w, c = x.shape
        want = jax.image.resize(
            x, (b, 2 * h, 2 * w, c), method="bilinear"
        )
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_upsample_grad_matches(self, mesh):
        x = rand(jax.random.key(5), (2, 16, 8, 3))
        fn = domain.domain_parallel(
            lambda ax, p, t: domain.halo_upsample2x(t, ax), mesh
        )
        g = jax.grad(lambda t: jnp.sum(jax.jit(fn)({}, t) ** 2))(x)
        b, h, w, c = x.shape
        w_ = jax.grad(
            lambda t: jnp.sum(
                jax.image.resize(
                    t, (b, 2 * h, 2 * w, c), method="bilinear"
                ) ** 2
            )
        )(x)
        np.testing.assert_allclose(g, w_, rtol=1e-4, atol=1e-5)


class TestDomainUNet:
    """The whole U-Net under the domain mesh vs flax apply_unet on the
    SAME init trees -- forward (train + eval), updated running stats,
    and parameter gradients."""

    @pytest.fixture(scope="class")
    def setup(self, mesh):
        from tpu_hpc.models.unet import UNetConfig, init_unet

        cfg = UNetConfig(in_channels=3, out_channels=3, base_features=8)
        # H=32 divides by spatial(4) * 4 (two pool levels).
        params, state = init_unet(jax.random.key(0), cfg, (32, 16, 3))
        x = rand(jax.random.key(1), (4, 32, 16, 3))
        return cfg, params, state, x

    def test_train_forward_and_stats(self, mesh, setup):
        from tpu_hpc.models.unet import apply_unet

        cfg, params, state, x = setup
        dom = domain_unet.make_domain_unet(mesh, cfg)
        got, new_state = jax.jit(
            lambda p, s, t: dom(p, s, t, train=True)
        )(params, state, x)
        want, want_state = apply_unet(params, state, x, cfg, train=True)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
        for (kp, g), (_, w) in zip(
            jax.tree.flatten_with_path(new_state)[0],
            jax.tree.flatten_with_path(want_state)[0],
        ):
            np.testing.assert_allclose(
                g, w, rtol=1e-4, atol=1e-5,
                err_msg=f"stats mismatch at {jax.tree_util.keystr(kp)}",
            )

    def test_eval_forward(self, mesh, setup):
        from tpu_hpc.models.unet import apply_unet

        cfg, params, state, x = setup
        dom = domain_unet.make_domain_unet(mesh, cfg)
        got, _ = jax.jit(
            lambda p, s, t: dom(p, s, t, train=False)
        )(params, state, x)
        want, _ = apply_unet(params, state, x, cfg, train=False)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_param_grads_match(self, mesh, setup):
        from tpu_hpc.models.unet import apply_unet

        cfg, params, state, x = setup
        y = rand(jax.random.key(2), x.shape)
        dom = domain_unet.make_domain_unet(mesh, cfg)

        def loss_dom(p):
            pred, _ = dom(p, state, x, train=True)
            return jnp.mean((pred - y) ** 2)

        def loss_or(p):
            pred, _ = apply_unet(p, state, x, cfg, train=True)
            return jnp.mean((pred - y) ** 2)

        gd = jax.jit(jax.grad(loss_dom))(params)
        go = jax.jit(jax.grad(loss_or))(params)
        for (kp, g), (_, w) in zip(
            jax.tree.flatten_with_path(gd)[0],
            jax.tree.flatten_with_path(go)[0],
        ):
            np.testing.assert_allclose(
                g, w, rtol=2e-3, atol=2e-4,
                err_msg=f"grad mismatch at {jax.tree_util.keystr(kp)}",
            )

    def test_bf16_batchnorm_stats_stay_fp32(self, mesh):
        """bf16 compute must not blow up the BatchNorm variance: the
        E[x^2]-E[x]^2 cancellation on a mean-4 activation (bf16 ulp at
        16 is 0.125) zeroes or negates a bf16-accumulated variance
        (ADVICE r5). _batch_norm now accumulates in fp32, like flax's
        _compute_stats -- the domain twin must still track the oracle
        under the example's default compute_dtype='bfloat16'."""
        from tpu_hpc.models.unet import UNetConfig, apply_unet, init_unet

        cfg = UNetConfig(
            in_channels=3, out_channels=3, base_features=8,
            dtype=jnp.bfloat16,
        )
        params, state = init_unet(jax.random.key(0), cfg, (32, 16, 3))
        # Offset, small-spread input: the regime where bf16 moment
        # accumulation loses the variance outright.
        x = rand(jax.random.key(1), (4, 32, 16, 3), 0.5) + 4.0
        dom = domain_unet.make_domain_unet(mesh, cfg)
        got, new_state = jax.jit(
            lambda p, s, t: dom(p, s, t, train=True)
        )(params, state, x)
        want, want_state = apply_unet(params, state, x, cfg, train=True)
        assert np.isfinite(np.asarray(got)).all()
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=5e-2, atol=5e-2,
        )
        for (kp, g), (_, w) in zip(
            jax.tree.flatten_with_path(new_state)[0],
            jax.tree.flatten_with_path(want_state)[0],
        ):
            g, w = np.asarray(g, np.float32), np.asarray(w, np.float32)
            assert np.isfinite(g).all(), jax.tree_util.keystr(kp)
            np.testing.assert_allclose(
                g, w, rtol=5e-2, atol=5e-2,
                err_msg=f"stats mismatch at {jax.tree_util.keystr(kp)}",
            )
            if jax.tree_util.keystr(kp).endswith("['var']"):
                # The actual regression: a negated variance.
                assert (g > 0).all(), jax.tree_util.keystr(kp)

    def test_trains_under_trainer(self, mesh, setup):
        from jax.sharding import PartitionSpec as P

        from tpu_hpc.config import TrainingConfig
        from tpu_hpc.models import datasets
        from tpu_hpc.train import Trainer

        cfg, params, state, _ = setup
        ds = datasets.ERA5Synthetic(lat=32, lon=16, n_vars=1, n_levels=3)
        forward = domain_unet.make_forward(mesh, cfg)
        tc = TrainingConfig(
            global_batch_size=4, steps_per_epoch=1, epochs=1,
            learning_rate=1e-3,
        )
        trainer = Trainer(
            tc, mesh, forward, params, state,
            batch_pspec=P("data", "spatial"),
        )
        metrics = trainer.train_step(ds.batch_at(0, 4))
        assert np.isfinite(float(jax.device_get(metrics["loss"])))
