"""tpu_hpc.loadgen -- the SLO-driven load harness.

Three invariant families:

* **reproducibility** -- a seeded scenario materializes byte-identical
  request schedules, and a seeded sim-mesh load run replayed twice
  yields bit-identical latency quantiles (virtual clock), so
  ``python -m tpu_hpc.obs.regress`` over the two runs is clean -- and
  an injected latency fault (TPU_HPC_LOADGEN_FAULTS) makes it exit
  non-zero naming the violated metric+quantile. This is the PR's
  end-to-end gate proof.
* **lifecycle telemetry** -- every arrival/admit/first-token/finish/
  shed lands as a schema-valid ``lg_*`` record, and the report's
  loadgen section reconstructs the per-tenant breakdown from them.
* **admission control** -- under a saturating burst the scheduler
  sheds ONLY the lowest-priority tenant class, emits schema-valid
  ``admission`` events, and the occupancy gauge tracks the live slot
  count through every admit/evict/shutdown transition.
"""
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from tpu_hpc import obs
from tpu_hpc.loadgen import (
    SCENARIOS,
    LoadHarness,
    build_scenario,
    parse_faults,
)
from tpu_hpc.loadgen.scenarios import (
    heavy_tail_lengths,
    onoff_arrivals,
    poisson_arrivals,
)
from tpu_hpc.models import llama2
from tpu_hpc.obs.regress import main as regress_main
from tpu_hpc.obs.report import build_report
from tpu_hpc.obs.schema import load_records, validate_file
from tpu_hpc.runtime import MeshSpec, build_mesh
from tpu_hpc.serve import Engine, ServeConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = llama2.LlamaConfig(
    dim=64, n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=128,
    multiple_of=16, max_seq_len=64, dtype=jnp.float32,
)
MAX_PROMPT, MAX_NEW = 16, 6


@pytest.fixture(scope="module")
def lg_engine(devices):
    mesh = build_mesh(MeshSpec(axes={"data": 4, "model": 2}))
    params = llama2.init_llama(jax.random.key(0), TINY)
    engine = Engine(
        params, TINY,
        ServeConfig(slots=4, max_seq_len=48, prefill_buckets=(8, 16)),
        mesh,
    )
    engine.warmup()
    return engine


@pytest.fixture()
def scoped_obs(tmp_path):
    """Fresh bus + registry per test: the harness publishes into the
    process singletons, and tests must not see each other's counters."""
    bus = obs.EventBus(path=None, run_id="lg-test",
                       flight_dir=str(tmp_path))
    reg = obs.MetricsRegistry()
    prev_bus, prev_reg = obs.set_bus(bus), obs.set_registry(reg)
    yield bus, reg
    obs.set_bus(prev_bus)
    obs.set_registry(prev_reg)


def _scenario(name, seed=7, n=24):
    return build_scenario(
        name, seed=seed, n_requests=n, vocab_size=TINY.vocab_size,
        max_prompt=MAX_PROMPT, max_new=MAX_NEW,
    )


def _run(engine, name, path, seed=7, n=24, faults=""):
    harness = LoadHarness(
        engine, _scenario(name, seed=seed, n=n),
        metrics_path=str(path), faults=parse_faults(faults),
    )
    return harness.run(n_devices=jax.device_count()), harness


# ---------------------------------------------------------------------
# scenarios.py: the catalog
# ---------------------------------------------------------------------
class TestScenarios:
    def test_same_seed_is_byte_identical(self):
        a = _scenario("multi_tenant", seed=5)
        b = _scenario("multi_tenant", seed=5)
        assert a == b  # frozen dataclasses: full deep equality

    def test_different_seed_differs(self):
        assert _scenario("steady", seed=1) != _scenario("steady", seed=2)

    def test_catalog_builds_within_engine_limits(self):
        for name in SCENARIOS:
            sc = _scenario(name)
            assert len(sc.requests) == 24
            arrivals = [r.arrival_ms for r in sc.requests]
            assert arrivals == sorted(arrivals)
            for r in sc.requests:
                assert 1 <= len(r.prompt) <= MAX_PROMPT
                assert 1 <= r.max_new_tokens <= MAX_NEW

    def test_unknown_scenario_rejected(self):
        with pytest.raises(ValueError, match="unknown scenario"):
            _scenario("nope")

    def test_multi_tenant_classes_and_slos(self):
        sc = _scenario("multi_tenant")
        names = {t.name for t in sc.tenants}
        assert names == {"interactive", "batch", "background"}
        prios = {t.name: t.priority for t in sc.tenants}
        assert prios["interactive"] > prios["batch"] > prios["background"]
        assert sc.tenant("interactive").slo["ttft_ms_p95"] > 0
        # every class actually sends traffic
        seen = {r.tenant for r in sc.requests}
        assert seen == names

    def test_decode_heavy_is_decode_bound(self):
        """The chat-style mix: prompts at most a quarter of the
        budget, generation budgets in the top quarter -- decode work
        dominates by construction (the speculative-decoding
        acceptance scenario)."""
        sc = _scenario("decode_heavy")
        for r in sc.requests:
            assert len(r.prompt) <= max(2, MAX_PROMPT // 4)
            assert r.max_new_tokens >= max(2, (3 * MAX_NEW) // 4)
        total_prompt = sum(len(r.prompt) for r in sc.requests)
        total_new = sum(r.max_new_tokens for r in sc.requests)
        assert total_new > total_prompt

    def test_heavy_tail_has_a_tail(self):
        import numpy as np

        rng = np.random.default_rng(0)
        lens = heavy_tail_lengths(
            rng, 4000, median=8.0, sigma=1.0, lo=1, hi=512
        )
        assert lens.min() >= 1 and lens.max() <= 512
        p50, p99 = np.percentile(lens, [50, 99])
        assert p99 > 3 * p50  # heavy-tailed, not uniform

    def test_arrival_processes(self):
        import numpy as np

        rng = np.random.default_rng(0)
        pois = poisson_arrivals(rng, 1000, rate_per_s=100.0)
        assert len(pois) == 1000 and np.all(np.diff(pois) >= 0)
        # mean gap ~ 10ms
        assert 8.0 < np.mean(np.diff(pois)) < 12.0
        burst = onoff_arrivals(
            rng, 100, burst_size=10, burst_rate_per_s=1000.0,
            off_ms=500.0,
        )
        gaps = np.diff(burst)
        # 9 inter-burst silences of >= 500ms, tight gaps inside bursts
        assert (gaps > 400).sum() == 9
        # validation parity with poisson_arrivals (review finding:
        # rate 0 died in ZeroDivisionError, negative rates produced
        # non-monotonic arrivals)
        with pytest.raises(ValueError, match="must be > 0"):
            poisson_arrivals(rng, 10, rate_per_s=0.0)
        with pytest.raises(ValueError, match="must be > 0"):
            onoff_arrivals(rng, 10, 4, burst_rate_per_s=0.0,
                           off_ms=1.0)
        with pytest.raises(ValueError, match="off_ms"):
            onoff_arrivals(rng, 10, 4, burst_rate_per_s=10.0,
                           off_ms=-1.0)

    def test_unknown_slo_metric_rejected_at_build(self):
        """A typoed SLO key that could never be violated would make
        every gate built on its verdict vacuous (review finding) --
        reject at construction, like parse_faults does."""
        from tpu_hpc.loadgen import TenantClass

        with pytest.raises(ValueError, match="unknown SLO metric"):
            TenantClass("t", slo={"ttft_ms_p90": 100.0})
        with pytest.raises(ValueError, match="unknown SLO metric"):
            TenantClass("t", slo={"itl_ms_p99": 20.0})
        TenantClass("t", slo={"ttft_ms_p95": 100.0})  # known: fine

    def test_fault_spec_parsing(self):
        from tpu_hpc.loadgen import FAULT_DEFAULTS

        assert parse_faults("") == dict(FAULT_DEFAULTS)
        got = parse_faults("prefill_delay=1.5, decode_delay=2")
        assert got["prefill_delay"] == 1.5
        assert got["decode_delay"] == 2.0
        with pytest.raises(ValueError, match="unknown fault key"):
            parse_faults("ttft=2")
        # Malformed values name the key, the full spec, and the
        # expected type (the resilience/faults.py discipline, shared
        # via parse_kv_spec -- a bare float() traceback would point
        # at the parser instead of the operator's typo).
        with pytest.raises(ValueError, match="positive number"):
            parse_faults("decode_delay=0")
        with pytest.raises(
            ValueError, match="'decode_delay'.*expected"
        ):
            parse_faults("decode_delay=fast")

    def test_shared_prefix_tenants_share_a_system_prompt(self):
        """Every request of a tenant opens with the SAME token
        prefix (half the prompt budget), per-tenant prefixes differ,
        and the schedule stays seed-deterministic -- the raw material
        for the paged engine's prefix trie."""
        sc = _scenario("shared_prefix")
        assert sc == _scenario("shared_prefix")
        sys_len = max(2, MAX_PROMPT // 2)
        by_tenant = {}
        for r in sc.requests:
            by_tenant.setdefault(r.tenant, []).append(r)
        assert len(by_tenant) == 3
        prefixes = {}
        for tenant, reqs in by_tenant.items():
            heads = {r.prompt[:sys_len] for r in reqs}
            assert len(heads) == 1, tenant  # one system prompt each
            prefixes[tenant] = heads.pop()
            for r in reqs:
                assert sys_len < len(r.prompt) <= MAX_PROMPT
        assert len(set(prefixes.values())) == 3  # distinct per tenant


# ---------------------------------------------------------------------
# the end-to-end gate proof (acceptance): replay-deterministic
# quantiles; injected latency fails regress naming metric+quantile
# ---------------------------------------------------------------------
class TestRegressGateEndToEnd:
    def test_replay_is_regress_clean_and_fault_fails(
        self, lg_engine, scoped_obs, tmp_path, capsys,
    ):
        pa, pb, pc = (str(tmp_path / f"{x}.jsonl") for x in "abc")
        sa, _ = _run(lg_engine, "bursty", pa)
        sb, _ = _run(lg_engine, "bursty", pb)
        # Virtual clock: the quantiles are bit-identical, not close.
        for k in ("ttft_ms_p50", "ttft_ms_p95", "ttft_ms_p99",
                  "itl_ms_p50", "itl_ms_p95", "tokens_per_s"):
            assert sa[k] == sb[k], k
        assert validate_file(pa) > 0 and validate_file(pb) > 0
        assert regress_main([pa, pb]) == 0
        capsys.readouterr()

        # The injected-latency proof: 1.5x prefill cost must inflate
        # TTFT past the 10% default tolerance and fail the gate,
        # naming the violated metric+quantile.
        _run(lg_engine, "bursty", pc, faults="prefill_delay=1.5")
        assert regress_main([pa, pc]) == 1
        out = capsys.readouterr().out
        assert "REGRESSION" in out and "ttft_ms_p95" in out

    def test_idle_gap_jump_survives_float_roundtrip(
        self, lg_engine, scoped_obs, tmp_path,
    ):
        """Review finding: the ms->s->ms round trip can land the
        jumped clock a hair SHORT of arrival_ms; re-testing the due
        predicate then advanced by 0 forever. The idle branch must
        submit the arrival it jumped to directly. (On the broken
        code this livelocks, hence the watchdog thread.)"""
        import threading

        from tpu_hpc.loadgen import LoadRequest, Scenario, TenantClass

        # 65261.45763366384 / 1e3 * 1e3 == 65261.457633663835 < it.
        bad_ms = 65261.45763366384
        assert bad_ms / 1e3 * 1e3 < bad_ms  # the adversarial float
        sc = Scenario(
            name="gap", seed=0,
            tenants=(TenantClass("default"),),
            requests=(
                LoadRequest("g0", "default", 0, 0.0,
                            (1, 2, 3), 2),
                LoadRequest("g1", "default", 0, bad_ms,
                            (4, 5), 2),
            ),
        )
        harness = LoadHarness(
            lg_engine, sc, metrics_path=str(tmp_path / "g.jsonl"),
        )
        done = []
        t = threading.Thread(
            target=lambda: done.append(harness.run()), daemon=True,
        )
        t.start()
        t.join(timeout=60)
        assert done, "harness livelocked on the idle-gap jump"
        assert done[0]["requests"] == 2
        assert len(harness.batcher.results["g1"]) == 2

    def test_fault_env_var_reaches_harness(
        self, lg_engine, scoped_obs, tmp_path, monkeypatch,
    ):
        """The TPU_HPC_LOADGEN_FAULTS env spelling (the CI fault
        path) inflates the same quantiles the kwarg does."""
        pa = str(tmp_path / "a.jsonl")
        pb = str(tmp_path / "b.jsonl")
        sa, _ = _run(lg_engine, "steady", pa)
        monkeypatch.setenv("TPU_HPC_LOADGEN_FAULTS", "decode_delay=3")
        harness = LoadHarness(
            lg_engine, _scenario("steady"), metrics_path=pb,
        )
        sb = harness.run(n_devices=jax.device_count())
        assert sb["itl_ms_p50"] == pytest.approx(3 * sa["itl_ms_p50"])

    def test_regress_cli_subprocess(
        self, lg_engine, scoped_obs, tmp_path,
    ):
        """The exact command CI runs: ``python -m tpu_hpc.obs.regress``
        in a fresh interpreter (no jax backend needed to judge)."""
        pa = str(tmp_path / "a.jsonl")
        pb = str(tmp_path / "b.jsonl")
        _run(lg_engine, "steady", pa)
        _run(lg_engine, "steady", pb)
        env = dict(os.environ)
        prev = env.get("PYTHONPATH", "")
        env["PYTHONPATH"] = REPO + (os.pathsep + prev if prev else "")
        proc = subprocess.run(
            [sys.executable, "-m", "tpu_hpc.obs.regress", pa, pb,
             "--json"],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        verdict = json.loads(proc.stdout)
        assert verdict["pass"] is True and verdict["checked"] > 0
        assert verdict["schema_version"] == 1


# ---------------------------------------------------------------------
# admission control (acceptance): saturating burst -> schema-valid
# shed events, lowest class only, report breakdown
# ---------------------------------------------------------------------
class TestAdmissionControl:
    def test_saturating_burst_sheds_lowest_class_only(
        self, lg_engine, scoped_obs, tmp_path,
    ):
        path = tmp_path / "burst.jsonl"
        summary, harness = _run(
            lg_engine, "saturating_burst", path, n=32
        )
        assert harness.batcher.stats["shed"] > 0
        assert summary["shed"] == harness.batcher.stats["shed"]
        # Only the lowest-priority class pays (queue_overflow sheds
        # newest-of-lowest first and the burst keeps higher classes
        # under the backlog bound).
        assert summary["tenants"]["background"]["shed"] > 0
        assert summary["tenants"]["interactive"]["shed"] == 0
        # Higher classes queue rather than shed under the burst.
        assert summary["tenants"]["interactive"]["queued"] > 0
        # The whole file -- lifecycle, admission decisions, stalls,
        # summary -- validates against the one schema.
        assert validate_file(str(path)) > 0
        records = load_records(str(path))
        sheds = [
            r for r in records
            if r.get("event") == "admission" and r["action"] == "shed"
        ]
        assert len(sheds) == summary["shed"]
        assert {s["tenant"] for s in sheds} == {"background"}
        assert all(s["reason"] == "queue_overflow" for s in sheds)
        queues = [
            r for r in records
            if r.get("event") == "admission" and r["action"] == "queue"
        ]
        assert queues and all(
            q["occupancy"] == 1.0 and q["pending"] > 0 for q in queues
        )
        # lg_shed lifecycle records mirror the decisions.
        lg_sheds = [r for r in records if r.get("event") == "lg_shed"]
        assert len(lg_sheds) == summary["shed"]

    def test_report_breakdown_attributes_shed_load(
        self, lg_engine, scoped_obs, tmp_path,
    ):
        path = tmp_path / "burst.jsonl"
        summary, _ = _run(lg_engine, "saturating_burst", path, n=32)
        rep = build_report(load_records(str(path)))
        lg = rep["loadgen"]
        assert lg["scenario"] == "saturating_burst"
        bg = lg["tenants"]["background"]
        assert bg["shed"] == summary["tenants"]["background"]["shed"]
        assert bg["arrivals"] == bg["admitted"] + bg["shed"]
        assert lg["admission_decisions"]["shed"] == summary["shed"]
        assert lg["tenants"]["interactive"]["queued"] > 0
        # Per-tenant ITL rides from the summary (lg_token is
        # ring-only, so events alone can't rebuild it) and lands in
        # the gate's namespace alongside queued.
        it = lg["tenants"]["interactive"]
        assert it["itl_ms_p50"] == \
            summary["tenants"]["interactive"]["itl_ms_p50"]
        from tpu_hpc.obs.regress import report_metrics

        flat = report_metrics(rep)
        assert flat["loadgen.interactive.queued"] == it["queued"]
        assert flat["loadgen.interactive.itl_ms_p95"] == \
            it["itl_ms_p95"]
        # and the human rendering names the classes
        from tpu_hpc.obs.report import format_report

        txt = format_report(rep)
        assert "Load generator" in txt and "background" in txt
        assert "admission decisions" in txt

    def test_prefill_admission_does_not_trip_the_watermark(
        self, lg_engine, scoped_obs, tmp_path,
    ):
        """Review finding: an admission tick is EXPECTED to be long
        (one big-bucket prefill costs many decode-ticks of modeled
        time); it must not read as a stall and mass-shed tenants.
        With prefill costing 20x a decode tick and no colocation,
        zero stall events and zero stall-sheds."""
        path = tmp_path / "pf.jsonl"
        harness = LoadHarness(
            lg_engine, _scenario("multi_tenant", seed=2, n=32),
            metrics_path=str(path),
            prefill_ms_per_token=10.0,  # bucket 16 -> 160ms vs 8ms
        )
        summary = harness.run(n_devices=jax.device_count())
        assert summary["stall_events"] == 0
        records = load_records(str(path))
        assert not any(
            r.get("event") == "admission"
            and r.get("reason") == "stall_watermark"
            for r in records
        )

    def test_stall_watermark_sheds_background_protects_online(
        self, lg_engine, scoped_obs, tmp_path,
    ):
        path = tmp_path / "colo.jsonl"
        summary, _ = _run(lg_engine, "colocate", path, seed=3)
        # The colocated train step trips the watermark...
        assert summary["stall_events"] > 0
        records = load_records(str(path))
        assert any(r.get("event") == "stall" for r in records)
        assert any(
            r.get("event") == "span"
            and r["name"] == "colocated_train_step"
            for r in records
        )
        # ...and any stall-shedding hits only the background class.
        stall_sheds = [
            r for r in records
            if r.get("event") == "admission"
            and r.get("reason") == "stall_watermark"
        ]
        assert all(s["tenant"] == "background" for s in stall_sheds)
        assert summary["tenants"]["online"]["shed"] == 0

    def test_overflow_accounts_for_free_slots(
        self, lg_engine, scoped_obs,
    ):
        """Review finding: with occupancy_high < 1 a tick can be
        'saturated' while slots are free; pending the admit loop will
        seat this tick must not be shed as overflow."""
        from tpu_hpc.serve import (
            AdmissionPolicy,
            ContinuousBatcher,
            Request,
        )

        batcher = ContinuousBatcher(
            lg_engine,
            policy=AdmissionPolicy(
                queue_limit=0, occupancy_high=0.25
            ),
        )
        # One long-running request occupies 1 of 4 slots ->
        # occupancy 0.25 == occupancy_high: "saturated".
        batcher.submit(Request(rid="long", prompt=[1, 2, 3],
                               max_new_tokens=8))
        batcher.step()
        assert batcher.active == 1
        # Three more: exactly the three free slots. queue_limit=0,
        # but nothing actually queues -- nothing may shed.
        for i in range(3):
            batcher.submit(Request(rid=f"s{i}", prompt=[4 + i],
                                   max_new_tokens=2))
        batcher.step()
        # All three were seated (and, at max_new=2, finished within
        # the step) -- none shed.
        assert batcher.stats["shed"] == 0
        assert batcher.stats["admitted"] == 4
        assert all(f"s{i}" in batcher.results for i in range(3))
        batcher.run()  # drain

    def test_same_tick_admissions_not_counted_queued(
        self, lg_engine, scoped_obs, tmp_path,
    ):
        """Review finding: two same-tick admissions must both count
        as un-queued even though the first slot's prefill charge
        advances the shared clock before the second's t_admit."""
        from tpu_hpc.loadgen import LoadRequest, Scenario, TenantClass

        sc = Scenario(
            name="twin", seed=0, tenants=(TenantClass("default"),),
            requests=(
                LoadRequest("t0", "default", 0, 0.0, (1, 2, 3), 2),
                LoadRequest("t1", "default", 0, 0.0, (4, 5, 6), 2),
            ),
        )
        path = tmp_path / "twin.jsonl"
        harness = LoadHarness(lg_engine, sc, metrics_path=str(path))
        summary = harness.run()
        assert summary["queued"] == 0
        admits = [
            r for r in load_records(str(path))
            if r.get("event") == "lg_admit"
        ]
        assert len(admits) == 2
        assert all(a["queued"] is False for a in admits)
        # ...and the report's breakdown agrees with the flag.
        rep = build_report(load_records(str(path)))
        assert rep["loadgen"]["tenants"]["default"]["queued"] == 0

    def test_occupancy_gauge_tracks_live_slots_every_step(
        self, lg_engine, scoped_obs,
    ):
        """Satellite pin: serve_active_slots == live slot count at
        EVERY decode step (admit and evict paths both update it), and
        0 after shutdown."""
        from tpu_hpc.serve import ContinuousBatcher, Request

        bus, reg = scoped_obs

        class GaugeCheckingEngine:
            def __init__(self, engine, batcher_ref):
                self._e = engine
                self._b = batcher_ref

            @property
            def serve_cfg(self):
                return self._e.serve_cfg

            def prefill(self, idx, prompt):
                return self._e.prefill(idx, prompt)

            def decode(self, tokens, positions):
                # At decode time every admission already updated the
                # gauge: it must equal the live slot count NOW, not
                # the count after the previous step.
                assert reg.gauge("serve_active_slots") == \
                    self._b[0].active
                return self._e.decode(tokens, positions)

        ref = [None]
        proxy = GaugeCheckingEngine(lg_engine, ref)
        batcher = ContinuousBatcher(proxy)
        ref[0] = batcher
        assert reg.gauge("serve_active_slots") == 0  # armed at init
        import numpy as np

        rng = np.random.default_rng(0)
        reqs = [
            Request(
                rid=f"g{i}",
                prompt=rng.integers(
                    0, TINY.vocab_size, size=3 + i % 9
                ).tolist(),
                max_new_tokens=1 + i % 4,
            )
            for i in range(7)  # 7 requests through 4 slots: churn
        ]
        batcher.run(reqs)
        assert batcher.stats["decode_steps"] > 0
        assert reg.gauge("serve_active_slots") == 0  # shutdown


# ---------------------------------------------------------------------
# the serve_summary ride-along: obs.report machinery for free
# ---------------------------------------------------------------------
class TestSummaryRideAlong:
    def test_report_serve_section_reads_load_run(
        self, lg_engine, scoped_obs, tmp_path,
    ):
        path = tmp_path / "mt.jsonl"
        summary, _ = _run(lg_engine, "multi_tenant", path)
        rep = build_report(load_records(str(path)))
        s = rep["serve"]
        assert s["ttft_ms_p95"] == summary["ttft_ms_p95"]
        assert s["ttft_ms_p99"] == summary["ttft_ms_p99"]
        assert s["tokens_per_s"] == summary["tokens_per_s"]
        lg = rep["loadgen"]
        assert lg["occupancy_mean"] == summary["occupancy_mean"]
        assert lg["stall_events"] == summary["stall_events"]

    def test_per_token_events_ride_the_flight_ring(
        self, lg_engine, scoped_obs, tmp_path,
    ):
        """lg_token is ring-only by design: cadence forensics without
        sink volume."""
        bus, _ = scoped_obs
        path = tmp_path / "st.jsonl"
        _run(lg_engine, "steady", path, n=8)
        assert any(
            r["event"] == "lg_token" for r in bus.ring()
        )
        on_disk = load_records(str(path))
        assert not any(r["event"] == "lg_token" for r in on_disk)

    def test_slo_verdicts_in_summary(
        self, lg_engine, scoped_obs, tmp_path,
    ):
        path = tmp_path / "mt.jsonl"
        summary, _ = _run(lg_engine, "multi_tenant", path)
        t = summary["tenants"]["interactive"]
        assert t["slo"] == {"ttft_ms_p95": 400.0, "itl_ms_p95": 60.0}
        assert isinstance(t["slo_violated"], list)
        assert isinstance(summary["slo_violations"], list)


# ---------------------------------------------------------------------
# server CLI: --loadgen mode
# ---------------------------------------------------------------------
class TestServerLoadgenCLI:
    def test_main_runs_scenario_and_prints_summary(self, capsys):
        from tpu_hpc.serve import server

        rc = server.main([
            "--loadgen", "saturating_burst", "--requests", "24",
            "--max-new", "4", "--slots", "2", "--buckets", "8",
            "--vocab", "64",
        ])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out.splitlines()[-1])
        assert summary["scenario"] == "saturating_burst"
        assert summary["recompiles"] == 0
        assert summary["virtual_clock"] is True
        assert summary["shed"] + summary["admitted"] == 24
        assert "interactive" in summary["tenants"]

    def test_main_rejects_unknown_scenario(self):
        from tpu_hpc.serve import server

        with pytest.raises(SystemExit):
            server.main(["--loadgen", "nope"])

    def test_main_rejects_degenerate_generate_budget(self, capsys):
        """Review finding: a cache that leaves < 2 generate tokens
        after the largest bucket must be an argparse error, not a
        post-bring-up ValueError traceback."""
        from tpu_hpc.serve import server

        with pytest.raises(SystemExit):
            server.main([
                "--loadgen", "steady", "--buckets", "8,16",
                "--max-seq-len", "17",
            ])
        assert "generate tokens" in capsys.readouterr().err


# ---------------------------------------------------------------------
# the harness over the PAGED engine (serve/paging.py)
# ---------------------------------------------------------------------
class TestPagedHarness:
    def _fresh_engine(self):
        # A FRESH engine per run: the prefix trie is engine state, and
        # replay determinism is only meaningful from identical (cold)
        # cache states -- hits still happen WITHIN a run, because each
        # tenant's system prompt repeats across its requests.
        from tpu_hpc.serve import PagedConfig, PagedEngine

        mesh = build_mesh(MeshSpec(axes={"data": 4, "model": 2}))
        params = llama2.init_llama(jax.random.key(0), TINY)
        engine = PagedEngine(
            params, TINY,
            ServeConfig(slots=4, max_seq_len=48,
                        prefill_buckets=(8, 16)),
            mesh,
            PagedConfig(block_size=4, num_blocks=49, prefill_chunk=8),
        )
        engine.warmup()
        return engine

    def test_shared_prefix_hits_and_deterministic_replay(
        self, scoped_obs, tmp_path,
    ):
        """The cache-efficiency acceptance path: the shared_prefix mix
        through the paged engine produces prefix hits (the trie
        resolves each tenant's system prompt physically), the summary
        carries the hit evidence into the regress namespace, and a
        seeded replay is regress-clean -- zero recompiles
        throughout."""
        pa = str(tmp_path / "a.jsonl")
        pb = str(tmp_path / "b.jsonl")
        ea = self._fresh_engine()
        warmed = ea.compile_count
        sa, _ = _run(ea, "shared_prefix", pa, seed=9, n=20)
        assert ea.compile_count == warmed
        # Per-tenant system prompts repeat: the trie must hit.
        assert ea.paged_stats["prefix_hits"] > 0
        eb = self._fresh_engine()
        sb, _ = _run(eb, "shared_prefix", pb, seed=9, n=20)
        assert sa["ttft_ms_p95"] == sb["ttft_ms_p95"]
        assert sa["itl_ms_p50"] == sb["itl_ms_p50"]
        assert sa["prefix_hit_rate"] == sb["prefix_hit_rate"]
        assert validate_file(pa) > 0
        rep = build_report(load_records(pa))
        assert rep["serve"]["kv_layout"] == "paged"
        assert rep["serve"]["prefix_hit_rate"] > 0
        # Both runs identical -> the gate is clean.
        assert regress_main([pa, pb]) == 0


# ---------------------------------------------------------------------
# long mixes (full-suite tier only)
# ---------------------------------------------------------------------
@pytest.mark.slow
class TestLongMixes:
    def test_heavy_tail_long_mix_regress_clean(
        self, lg_engine, scoped_obs, tmp_path,
    ):
        pa = str(tmp_path / "a.jsonl")
        pb = str(tmp_path / "b.jsonl")
        _run(lg_engine, "heavy_tail", pa, seed=11, n=200)
        _run(lg_engine, "heavy_tail", pb, seed=11, n=200)
        assert regress_main([pa, pb]) == 0

    def test_bursty_long_mix_deterministic_summary(
        self, lg_engine, scoped_obs, tmp_path,
    ):
        sa, _ = _run(
            lg_engine, "bursty", tmp_path / "a.jsonl", seed=13, n=200
        )
        sb, _ = _run(
            lg_engine, "bursty", tmp_path / "b.jsonl", seed=13, n=200
        )
        assert sa["ttft_ms_p99"] == sb["ttft_ms_p99"]
        assert sa["decode_steps"] == sb["decode_steps"]


# ---------------------------------------------------------------------
# the host-DRAM KV tier under the return wave (serve/tier.py,
# full-suite tier only -- the fast tier representatives live in
# test_tier.py)
# ---------------------------------------------------------------------
@pytest.mark.slow
class TestTierShedContrast:
    """End-to-end acceptance for the host tier: the same seeded
    ``long_idle_sessions`` schedule against an HBM-only pool and an
    identical pool plus host slots. The HBM-only run evicts the parked
    first-visit pages to seat the filler wave, re-prefills the return
    wave from scratch, drains too slowly, and sheds part of it; the
    tiered run spilled those pages instead, prefix-hits after the
    refill hop, and sheds nothing -- zero steady-state recompiles on
    both sides. (The bench-scale pair of this contrast is banked in
    BENCH_HISTORY.jsonl.)"""

    def _engine(self, host_blocks):
        from tpu_hpc.serve import PagedConfig, PagedEngine

        mesh = build_mesh(MeshSpec(axes={"data": 4, "model": 2}))
        params = llama2.init_llama(jax.random.key(0), TINY)
        engine = PagedEngine(
            params, TINY,
            ServeConfig(slots=4, max_seq_len=48,
                        prefill_buckets=(8, 16)),
            mesh,
            # 20 usable pages: the filler wave cannot seat without
            # reclaiming the chat wave's parked prefix pages.
            PagedConfig(block_size=4, num_blocks=21, prefill_chunk=8,
                        host_blocks=host_blocks),
        )
        engine.warmup()
        return engine

    def _drive(self, engine, path):
        # rate 15/s puts the 3x return wave above the HBM-only drain
        # rate (full re-prefill at 8 virtual-ms/token) but below the
        # tiered one (prefix hit + 0.5 ms/page refill hop) -- the
        # regime where ONLY the reclamation policy decides the shed.
        sc = build_scenario(
            "long_idle_sessions", seed=7, n_requests=48,
            vocab_size=TINY.vocab_size, max_prompt=MAX_PROMPT,
            max_new=MAX_NEW, rate_per_s=15.0,
        )
        harness = LoadHarness(
            engine, sc, metrics_path=str(path),
            prefill_ms_per_token=8.0,
        )
        return harness.run(n_devices=jax.device_count())

    def test_return_wave_sheds_only_without_the_tier(
        self, scoped_obs, tmp_path,
    ):
        hbm = self._engine(0)
        warmed_hbm = hbm.compile_count
        sh = self._drive(hbm, tmp_path / "hbm.jsonl")

        tiered = self._engine(129)
        warmed_tier = tiered.compile_count
        st = self._drive(tiered, tmp_path / "tier.jsonl")

        # The contrast: identical HBM budget, identical schedule --
        # only the reclamation policy differs, and only the HBM-only
        # run sheds returning users.
        assert sh["tenants"]["return"]["shed"] > 0
        assert st["tenants"]["return"]["shed"] == 0
        assert st["tenants"]["filler"]["shed"] == 0
        assert st["tenants"]["chat"]["shed"] == 0
        # Mechanism, not luck: the HBM-only pool churned through
        # evictions and never hit; the tiered pool spilled the parked
        # chains, refilled them on the return wave, and resolved
        # return prompts from the trie.
        assert hbm.paged_stats["prefix_hits"] == 0
        assert st["prefix_hit_rate"] > 0
        assert tiered.host_tier.stats["kv_spill_pages"] > 0
        assert tiered.host_tier.stats["kv_refill_pages"] > 0
        assert (
            tiered.paged_stats["trie_evictions"]
            < hbm.paged_stats["trie_evictions"]
        )
        # Zero steady-state recompiles on both sides of the contrast.
        assert hbm.compile_count == warmed_hbm
        assert tiered.compile_count == warmed_tier
