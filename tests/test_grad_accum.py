"""Gradient accumulation: same optimizer trajectory as the full batch.

The contract (trainer.make_step_fn grad_accum): splitting the global
batch into N sequential microbatches and summing gradients must land on
the same updated parameters as one full-batch step -- gradient of the
mean equals the mean of per-microbatch gradients when microbatches are
equal-sized. Verified against the real Llama step on a sharded mesh,
including the scanned-epoch fast path and checkpoint-relevant step
accounting (one optimizer step per global batch regardless of accum).
"""
import dataclasses

import jax
import jax.numpy as jnp
import pytest

from tpu_hpc.config import TrainingConfig
from tpu_hpc.models import datasets, llama2
from tpu_hpc.parallel import fsdp
from tpu_hpc.runtime import MeshSpec, build_mesh
from tpu_hpc.train import Trainer

# fp32 compute for the equivalence tests: in bf16 the microbatched and
# full-batch matmuls accumulate in different orders, and an adaptive
# optimizer's first step amplifies those last-ulp gradient differences
# to O(lr) on near-zero entries. SGD is linear in the gradient, so the
# mean-of-means == full-mean identity holds to float roundoff.
MODEL = llama2.LlamaConfig(
    dim=32, n_layers=2, n_heads=4, vocab_size=64, multiple_of=16,
    max_seq_len=16, dtype=jnp.float32,
)


def _trainer(accum: int, mesh, steps: int = 2, global_batch: int = 8) -> Trainer:
    cfg = TrainingConfig(
        global_batch_size=global_batch,
        steps_per_epoch=steps,
        epochs=1,
        learning_rate=1e-2,
        weight_decay=0.0,  # SGD+momentum: linear in grads (see above)
        grad_accum_steps=accum,
    )
    params = llama2.init_llama(jax.random.key(0), MODEL)
    specs = fsdp.param_pspecs(params, axis="data", axis_size=mesh.shape["data"])
    return Trainer(
        cfg, mesh, llama2.make_forward(MODEL), params, param_pspecs=specs
    )


@pytest.fixture(scope="module")
def mesh(request):
    # 4-way data mesh (explicit subset: microbatches of 8/4=2 must
    # still cover the axis, so dp=4 is the interesting shape).
    return build_mesh(
        MeshSpec(axes={"data": 4}), devices=jax.devices()[:4]
    )


def _leaf_allclose(a, b, **kw):
    flat_a = jax.tree.leaves(a)
    flat_b = jax.tree.leaves(b)
    assert len(flat_a) == len(flat_b)
    for x, y in zip(flat_a, flat_b):
        assert jnp.allclose(x, y, **kw), (x - y).max()


@pytest.mark.parametrize("accum", [2, 4])
def test_matches_full_batch_step(mesh, accum):
    # Batch scaled so each microbatch still covers the 4-way data axis.
    bs = 4 * accum
    ds = datasets.TokenStream(vocab_size=MODEL.vocab_size, seq_len=MODEL.max_seq_len)
    t_full = _trainer(1, mesh, global_batch=bs)
    t_acc = _trainer(accum, mesh, global_batch=bs)
    batch = ds.batch_at(0, bs)
    m_full = t_full.train_step(batch)
    m_acc = t_acc.train_step(batch)
    assert jnp.allclose(
        m_full["loss"], m_acc["loss"], rtol=1e-5, atol=1e-6
    )
    _leaf_allclose(
        t_full.state.params, t_acc.state.params, rtol=1e-5, atol=1e-6
    )
    # One optimizer step per global batch, independent of accumulation:
    # checkpoints and the (seed, step)-indexed data stream line up.
    assert int(jax.device_get(t_acc.state.step)) == 1


def test_scanned_epoch_path(mesh):
    """grad_accum composes with the whole-epoch lax.scan fast path."""
    ds = datasets.TokenStream(vocab_size=MODEL.vocab_size, seq_len=MODEL.max_seq_len)
    t_full = _trainer(1, mesh)
    t_acc = _trainer(2, mesh)
    r_full = t_full.fit(ds)
    r_acc = t_acc.fit(ds)
    assert abs(r_full["final_loss"] - r_acc["final_loss"]) < 1e-4
    _leaf_allclose(
        t_full.state.params, t_acc.state.params, rtol=1e-4, atol=1e-5
    )


def test_indivisible_batch_rejected(mesh):
    with pytest.raises(ValueError, match="not divisible"):
        _trainer(3, mesh)


def test_undersized_microbatch_rejected(mesh):
    # global 8 / accum 8 = microbatch 1 on a 4-way data axis: GSPMD
    # would pad silently and idle 3 of 4 chips every pass -- reject.
    with pytest.raises(ValueError, match="microbatch"):
        _trainer(8, mesh)


def test_zero_accum_rejected(mesh):
    with pytest.raises(ValueError, match="grad_accum_steps"):
        _trainer(0, mesh)


def test_param_layout_preserved(mesh):
    """Accumulated step keeps the planned FSDP layout (out_shardings
    pin; a scan carrying grads must not re-layout params)."""
    ds = datasets.TokenStream(vocab_size=MODEL.vocab_size, seq_len=MODEL.max_seq_len)
    t = _trainer(2, mesh)
    before = jax.tree.map(lambda a: a.sharding, t.state.params)
    t.train_step(ds.batch_at(0, 8))
    after = jax.tree.map(lambda a: a.sharding, t.state.params)
    assert jax.tree.all(
        jax.tree.map(lambda x, y: x == y, before, after)
    )
