"""The driver-facing entry points must stay green.

``dryrun_multichip`` is the external evidence that the full hybrid
FSDPxTP(+SP) train step compiles and executes over a multi-device mesh
(SURVEY.md section 3.2); ``entry`` is the single-chip compile check.
"""
import os
import sys

import jax
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft  # noqa: E402


def test_entry_compiles_and_runs():
    fn, args = graft.entry()
    loss = jax.jit(fn)(*args)
    assert loss.shape == ()
    assert float(loss) > 0


def test_dryrun_multichip_in_process(devices):
    # Under the pytest CPU-sim env jax already exposes 8 devices, so the
    # in-process fast path runs (no subprocess).
    graft.dryrun_multichip(8)


def test_dryrun_multichip_subprocess_path():
    # Force the re-exec path regardless of this process's device count:
    # ask for more devices than are visible.  The child provisions its
    # own virtual CPU mesh of that size.
    graft.dryrun_multichip(16)
