"""Roofline estimator: validated against the round-2 measured step.

The bench model's measured single-chip numbers (BENCH_PREOUTAGE_r02,
docs/guide/xla_performance_notes.md step budget: 76 ms step, 50% MFU,
pure-matmul bound ~38 ms) are the ground truth the estimator must
bracket -- a roofline that contradicts the one real measurement we
own is worse than none."""
import pytest

from tpu_hpc.checks import roofline
from tpu_hpc.models import llama2

BENCH = llama2.LlamaConfig(
    dim=1024, n_layers=8, n_heads=8, vocab_size=32000,
    multiple_of=256, max_seq_len=2048,
)


def test_single_chip_brackets_the_measured_step():
    r = roofline.estimate(BENCH, chip="v5e", global_batch=4)
    # Matmul lower bound ~38 ms (xla_performance_notes.md budget).
    assert 35 < r.compute_s * 1e3 < 41
    assert r.bound == "compute"
    # Measured: 76 ms -> the bound must be below it, and the measured
    # 50% MFU must not exceed the estimator's ceiling.
    assert r.step_time_lower_bound_s < 0.076
    assert r.mfu_upper_bound >= 0.50


def test_comm_bytes_invariant_under_grad_accum():
    """Accumulation splits the same rows into microbatches; total TP
    collective bytes per step must not change (regression: an early
    version multiplied whole-batch bytes by the accum factor)."""
    a1 = roofline.estimate(
        llama2.PRESETS["7b"], chip="v5e", dp=4, axis2=8,
        global_batch=32, seq_len=4096, grad_accum=1,
    )
    a8 = roofline.estimate(
        llama2.PRESETS["7b"], chip="v5e", dp=4, axis2=8,
        global_batch=32, seq_len=4096, grad_accum=8,
    )
    assert a1.comm_breakdown["tp_model_axis"] == pytest.approx(
        a8.comm_breakdown["tp_model_axis"]
    )
    # Param re-reads DO scale with accum (each microbatch re-reads).
    assert (
        a8.memory_breakdown["param_reads"]
        > a1.memory_breakdown["param_reads"]
    )


def test_layouts_emit_their_own_comm_terms():
    tp = roofline.estimate(
        llama2.PRESETS["7b"], chip="v5e", dp=2, axis2=4,
        layout="tp", global_batch=8, seq_len=4096,
    )
    cp = roofline.estimate(
        llama2.PRESETS["7b"], chip="v5e", dp=2, axis2=4,
        layout="cp", global_batch=8, seq_len=4096,
    )
    assert "tp_model_axis" in tp.comm_breakdown
    assert "kv_ring_context_axis" in cp.comm_breakdown
    assert "fsdp_data_axis" in tp.comm_breakdown
    # GQA makes the KV ring far cheaper than SP's residual reductions.
    assert (
        cp.comm_breakdown["kv_ring_context_axis"]
        < tp.comm_breakdown["tp_model_axis"]
    )


def test_bf16_moments_shrink_memory_bound():
    f32 = roofline.estimate(BENCH, chip="v5e", global_batch=4)
    bf16 = roofline.estimate(
        BENCH, chip="v5e", global_batch=4, moments_dtype="bfloat16"
    )
    assert bf16.memory_s < f32.memory_s


def test_bound_is_max_of_components():
    r = roofline.estimate(
        llama2.PRESETS["7b"], chip="v5e", dp=4, axis2=8,
        global_batch=32, seq_len=4096,
    )
    assert r.step_time_lower_bound_s == max(
        r.compute_s, r.memory_s, r.comm_s
    )
    assert 0 < r.mfu_upper_bound <= 1.0


def test_cli_json(capsys):
    roofline.main([
        "--model", "7b", "--chip", "v5e", "--dp", "4", "--tp", "8",
        "--global-batch", "32", "--seq-len", "4096", "--json",
    ])
    import json

    out = json.loads(capsys.readouterr().out)
    assert out["bound"] in ("compute", "memory", "comm")
    assert out["step_time_lower_bound_ms"] > 0


def test_estimate_accepts_chip_spec_instance():
    # A ChipSpec (e.g. host-calibrated measured rates) can replace the
    # CHIPS-key lookup; derated rates must move the bounds accordingly.
    spec = roofline.CHIPS["v5e"]
    import dataclasses
    derated = dataclasses.replace(
        spec, name="v5e-measured",
        peak_bf16_flops=spec.peak_bf16_flops * 0.5,
        hbm_gbps=spec.hbm_gbps * 0.5,
    )
    base = roofline.estimate(BENCH, chip=spec, global_batch=4)
    slow = roofline.estimate(BENCH, chip=derated, global_batch=4)
    assert slow.compute_s == pytest.approx(2 * base.compute_s)
    assert slow.memory_s == pytest.approx(2 * base.memory_s)
    assert slow.chip.name == "v5e-measured"


def test_measured_chip_spec_substitutes_microbench_rates(monkeypatch):
    # The calibration path swaps in the microbench's measured matmul
    # and HBM rates, keeps spec ICI/capacity, and tags the name --
    # verified against fixed fake rates (the real microbench needs a
    # real chip; its marginal-rate protocol is hardware-timing based).
    from tpu_hpc.checks import env_check

    monkeypatch.setattr(
        env_check, "chip_microbench",
        lambda: {"matmul_tflops": 192.0, "hbm_gb_s": 657.0},
    )
    spec = roofline.measured_chip_spec(roofline.CHIPS["v5e"])
    assert spec.name == "v5e-measured"
    assert spec.peak_bf16_flops == pytest.approx(192.0e12)
    assert spec.hbm_gbps == pytest.approx(657.0)
    assert spec.ici_gbps == roofline.CHIPS["v5e"].ici_gbps
    assert spec.hbm_gib == roofline.CHIPS["v5e"].hbm_gib


class TestPPLayout:
    """Pipeline roofline: schedule_factor carries bubble + remat."""

    def test_schedule_factor_exact(self):
        # 4 stages, 8 microbatches: bubble stretch (8+3)/8; the
        # default remat backward costs 5/3 in fwd-units (loss forward
        # + combined-program fwd slot + vjp recompute), the stash
        # backward 4/3 (residuals saved at forward time).
        r = roofline.estimate(
            BENCH, dp=1, axis2=4, layout="pp",
            global_batch=8, grad_accum=8,
        )
        assert r.layout == "pp"
        assert r.schedule_factor == pytest.approx((11 / 8) * (5 / 3))
        stash = roofline.estimate(
            BENCH, dp=1, axis2=4, layout="pp",
            global_batch=8, grad_accum=8, pp_backward="stash",
        )
        assert stash.schedule_factor == pytest.approx((11 / 8) * (4 / 3))
        # MFU ceiling is depressed by exactly the schedule factor when
        # the schedule term binds.
        if r.bound == "schedule":
            assert r.mfu_upper_bound == pytest.approx(
                1 / r.schedule_factor
            )

    def test_more_microbatches_shrink_bubble(self):
        r8 = roofline.estimate(
            BENCH, dp=1, axis2=4, layout="pp",
            global_batch=8, grad_accum=8,
        )
        r32 = roofline.estimate(
            BENCH, dp=1, axis2=4, layout="pp",
            global_batch=32, grad_accum=32,
        )
        assert r32.schedule_factor < r8.schedule_factor

    def test_stage_hops_and_ddp_terms(self):
        r = roofline.estimate(
            BENCH, dp=2, axis2=4, layout="pp",
            global_batch=16, grad_accum=8,
        )
        assert "pp_stage_hops" in r.comm_breakdown
        assert "ddp_grad_allreduce" in r.comm_breakdown

    def test_stash_pays_memory_for_its_flops(self):
        # Stash lowers the schedule factor but adds residual traffic:
        # the roofline must not present it as strictly free.
        remat = roofline.estimate(
            BENCH, dp=1, axis2=4, layout="pp",
            global_batch=8, grad_accum=8,
        )
        stash = roofline.estimate(
            BENCH, dp=1, axis2=4, layout="pp",
            global_batch=8, grad_accum=8, pp_backward="stash",
        )
        assert stash.schedule_factor < remat.schedule_factor
        assert stash.memory_s > remat.memory_s
        assert "stash_residuals" in stash.memory_breakdown
        assert "stash_residuals" not in remat.memory_breakdown

    def test_layers_must_divide_stages(self):
        with pytest.raises(ValueError, match="divisible by"):
            roofline.estimate(
                BENCH, dp=1, axis2=3, layout="pp",
                global_batch=6, grad_accum=6,
            )


class TestSlices:
    """Multi-slice data axis: the cross-slice phase rides DCN."""

    def test_dcn_binds_when_slow(self):
        import dataclasses as dc

        # A chip with near-zero DCN share: two slices must slow the
        # FSDP axis vs one; single-slice result must be unchanged.
        slow_dcn = dc.replace(
            roofline.CHIPS["v5e"], name="slow-dcn", dcn_gbps=0.1
        )
        one = roofline.estimate(
            BENCH, chip=slow_dcn, dp=8, global_batch=16, slices=1
        )
        two = roofline.estimate(
            BENCH, chip=slow_dcn, dp=8, global_batch=16, slices=2
        )
        assert two.comm_breakdown["fsdp_data_axis"] > \
            one.comm_breakdown["fsdp_data_axis"]
        assert two.slices == 2

    def test_slices_must_divide_dp(self):
        with pytest.raises(ValueError, match="divisible by slices"):
            roofline.estimate(
                BENCH, dp=3, global_batch=6, slices=2
            )

