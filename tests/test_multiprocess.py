"""REAL multi-process distributed init: two OS processes rendezvous
through ``init_distributed`` and reduce across the process boundary.

This is the no-hardware equivalent of the reference's multi-node
smoke tests (tests/test_torchrun.py, tests/check_environment.py): the
coordinator bootstrap, launcher-env detection, global device view and
a cross-process collective are all exercised for real -- each worker
is a separate interpreter with one local CPU device, and the psum
result must contain the other process's contribution. The unit tests
in test_runtime.py only check env *parsing*; this checks the wire.
"""
import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    """Ephemeral coordinator port: a fixed number collides with prior
    leaked workers or parallel jobs on the same host."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]

WORKER = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    # The CPU backend has no cross-process collective implementation
    # by default ("Multiprocess computations aren't implemented on
    # the CPU backend"); jaxlib ships Gloo for exactly this -- opt in
    # BEFORE jax.distributed.initialize or the cross-process psum
    # below cannot run.
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    from tpu_hpc.runtime import init_distributed

    info = init_distributed(verbose=False)
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 2, jax.device_count()
    mesh = Mesh(np.asarray(jax.devices()).reshape(2), ("data",))
    local = jnp.full((1,), float(jax.process_index() + 1))
    arr = jax.make_array_from_single_device_arrays(
        (2,), NamedSharding(mesh, P("data")),
        [jax.device_put(local, jax.local_devices()[0])],
    )
    total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(arr)
    print("RESULT", info.launcher, jax.process_index(),
          float(total.addressable_shards[0].data))
    """
).format(repo=REPO)


def _launch(rank_env) -> "list[subprocess.Popen]":
    procs = []
    for pid in (0, 1):
        env = dict(os.environ)
        # A clean slate: the host env may carry accelerator-plugin or
        # launcher vars that would win the detection cascade.
        for v in (
            "JAX_PROCESS_ID", "JAX_NUM_PROCESSES",
            "JAX_COORDINATOR_ADDRESS", "JAX_COORDINATOR_PORT",
            "OMPI_COMM_WORLD_RANK",
            "OMPI_COMM_WORLD_SIZE", "MASTER_ADDR", "MASTER_PORT",
            "TPU_WORKER_ID", "TPU_WORKER_HOSTNAMES", "SLURM_PROCID",
            "SLURM_NTASKS", "TPU_HPC_SIM_DEVICES", "XLA_FLAGS",
        ):
            env.pop(v, None)
        env["JAX_PLATFORMS"] = "cpu"
        env.update(rank_env(pid))
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", WORKER],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        )
    return procs


def _collect(procs, expect_launcher: str):
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, f"worker failed:\n{err[-1500:]}"
            line = [
                l for l in out.splitlines() if l.startswith("RESULT")
            ][-1]
            outs.append(line.split())
    finally:
        # One worker failing/timing out must not leak the other at the
        # rendezvous barrier (it would hold the coordinator port for
        # every later test on this host).
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for _, launcher, _, total in outs:
        assert launcher == expect_launcher
        # 1.0 (process 0) + 2.0 (process 1): the reduction crossed
        # the process boundary.
        assert float(total) == 3.0
    assert {o[2] for o in outs} == {"0", "1"}


def test_explicit_launcher_two_processes():
    """JAX_PROCESS_ID/JAX_NUM_PROCESSES/JAX_COORDINATOR_ADDRESS: the
    'explicit' rung of the detection cascade, end-to-end."""
    port = _free_port()
    procs = _launch(
        lambda pid: {
            "JAX_PROCESS_ID": str(pid),
            "JAX_NUM_PROCESSES": "2",
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        }
    )
    _collect(procs, "explicit")


def test_openmpi_launcher_two_processes():
    """OMPI_COMM_WORLD_* + MASTER_ADDR (the mpiexec contract the
    reference rides, utils/distributed.py:49-60 + :103-121): detection,
    MASTER_ADDR->coordinator shim, and the actual rendezvous."""
    port = _free_port()
    procs = _launch(
        lambda pid: {
            "OMPI_COMM_WORLD_RANK": str(pid),
            "OMPI_COMM_WORLD_SIZE": "2",
            "MASTER_ADDR": "127.0.0.1",
            "MASTER_PORT": str(port),
        }
    )
    _collect(procs, "openmpi")


HYBRID_WORKER = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    # Gloo CPU collectives: see WORKER above -- the FSDP gathers in
    # this test cross the process boundary.
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    from tpu_hpc.runtime import init_distributed

    info = init_distributed(verbose=False)
    import jax.numpy as jnp
    from tpu_hpc.ckpt import CheckpointManager
    from tpu_hpc.config import TrainingConfig
    from tpu_hpc.models import datasets, llama2
    from tpu_hpc.parallel import hybrid, tp
    from tpu_hpc.runtime import MeshSpec, build_mesh
    from tpu_hpc.train import Trainer

    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4, jax.local_device_count()

    mode = os.environ["TEST_MODE"]          # a: 2 steps + ckpt
    ckpt_dir = os.environ["TEST_CKPT_DIR"]  # b: resume 2 more
                                            # c: 4 straight, no ckpt
    # data axis rows = device pairs -> rows 0-1 live on process 0,
    # rows 2-3 on process 1: FSDP param gathers MUST cross the
    # process boundary; the model axis pairs devices within a host.
    mesh = build_mesh(MeshSpec(axes={{"data": 4, "model": 2}}))
    model_cfg = llama2.LlamaConfig(
        dim=64, n_layers=2, n_heads=4, vocab_size=256,
        multiple_of=32, max_seq_len=32,
    )
    params = llama2.init_llama(jax.random.key(0), model_cfg)
    specs = hybrid.hybrid_pspecs(
        params, tp.llama_rules(), data_size=4, min_size=1000
    )
    constrain = tp.sp_constrain(mesh, dp_axis="data", sp_axis="model")
    cfg = TrainingConfig(
        global_batch_size=8, steps_per_epoch=2,
        epochs=1 if mode == "a" else 2,
        save_every=1, resume=(mode == "b"), learning_rate=1e-2,
    )
    mgr = (
        CheckpointManager(ckpt_dir, async_save=False)
        if mode in ("a", "b") else None
    )
    trainer = Trainer(
        cfg, mesh, llama2.make_forward(model_cfg, constrain), params,
        param_pspecs=specs, checkpoint_manager=mgr,
    )
    # Prove the process-spanning layout: at least one param is laid
    # out over all 8 devices (4 of them non-addressable from here).
    span = any(
        len(l.sharding.device_set) == 8
        for l in jax.tree.leaves(trainer.state.params)
    )
    ds = datasets.TokenStream(vocab_size=256, seq_len=32)
    res = trainer.fit(ds)
    if mgr is not None:
        mgr.close()
    print("RESULT", mode, jax.process_index(),
          repr(float(res["final_loss"])), int(span))
    """
).format(repo=REPO)


def _run_hybrid_pair(mode: str, ckpt_dir: str):
    """Launch one 2-process x 4-sim-device hybrid run; return the
    per-rank (loss_repr, span) results."""
    port = _free_port()
    procs = []
    for pid in (0, 1):
        env = dict(os.environ)
        for v in (
            "JAX_PROCESS_ID", "JAX_NUM_PROCESSES",
            "JAX_COORDINATOR_ADDRESS", "JAX_COORDINATOR_PORT",
            "OMPI_COMM_WORLD_RANK", "OMPI_COMM_WORLD_SIZE",
            "MASTER_ADDR", "MASTER_PORT", "TPU_WORKER_ID",
            "TPU_WORKER_HOSTNAMES", "SLURM_PROCID", "SLURM_NTASKS",
            "TPU_HPC_SIM_DEVICES",
        ):
            env.pop(v, None)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "JAX_PROCESS_ID": str(pid),
            "JAX_NUM_PROCESSES": "2",
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "TEST_MODE": mode,
            "TEST_CKPT_DIR": ckpt_dir,
        })
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", HYBRID_WORKER],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=420)
            assert p.returncode == 0, (
                f"hybrid worker ({mode}) failed:\n{err[-2000:]}"
            )
            line = [
                l for l in out.splitlines() if l.startswith("RESULT")
            ][-1]
            outs.append(line.split())
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    assert {o[2] for o in outs} == {"0", "1"}
    # Both ranks computed the identical global loss.
    assert outs[0][3] == outs[1][3], outs
    assert all(o[4] == "1" for o in outs), (
        "no param spanned both processes -- the mesh did not cross "
        "the host boundary"
    )
    return outs[0][3]


@pytest.mark.slow
def test_hybrid_fsdp_tp_trainer_across_two_processes(tmp_path):
    """The multi-node rehearsal (reference utils/distributed.py:124-158
    + fsdp_tp/fsdp_tp_example.py:80-97, without hardware): 2 processes
    x 4 sim devices run the hybrid FSDPxTP Trainer over a
    process-spanning {data:4, model:2} mesh -- FSDP all-gathers cross
    the process boundary -- checkpoint at step 2 across both
    processes, and a fresh process pair resumes bit-exact: its step-4
    loss equals a never-interrupted 4-step run's."""
    ckpt = str(tmp_path / "ckpt")
    loss_a = _run_hybrid_pair("a", ckpt)          # steps 1-2 + save
    loss_b = _run_hybrid_pair("b", ckpt)          # restore, steps 3-4
    loss_c = _run_hybrid_pair("c", str(tmp_path / "unused"))  # 1-4
    assert loss_b == loss_c, (
        f"resumed run diverged: resumed {loss_b} vs continuous {loss_c}"
    )
    assert loss_a != loss_b  # sanity: training actually progressed
