"""REAL multi-process distributed init: two OS processes rendezvous
through ``init_distributed`` and reduce across the process boundary.

This is the no-hardware equivalent of the reference's multi-node
smoke tests (tests/test_torchrun.py, tests/check_environment.py): the
coordinator bootstrap, launcher-env detection, global device view and
a cross-process collective are all exercised for real -- each worker
is a separate interpreter with one local CPU device, and the psum
result must contain the other process's contribution. The unit tests
in test_runtime.py only check env *parsing*; this checks the wire.
"""
import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    """Ephemeral coordinator port: a fixed number collides with prior
    leaked workers or parallel jobs on the same host."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]

WORKER = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    from tpu_hpc.runtime import init_distributed

    info = init_distributed(verbose=False)
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 2, jax.device_count()
    mesh = Mesh(np.asarray(jax.devices()).reshape(2), ("data",))
    local = jnp.full((1,), float(jax.process_index() + 1))
    arr = jax.make_array_from_single_device_arrays(
        (2,), NamedSharding(mesh, P("data")),
        [jax.device_put(local, jax.local_devices()[0])],
    )
    total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(arr)
    print("RESULT", info.launcher, jax.process_index(),
          float(total.addressable_shards[0].data))
    """
).format(repo=REPO)


def _launch(rank_env) -> "list[subprocess.Popen]":
    procs = []
    for pid in (0, 1):
        env = dict(os.environ)
        # A clean slate: the host env may carry accelerator-plugin or
        # launcher vars that would win the detection cascade.
        for v in (
            "JAX_PROCESS_ID", "JAX_NUM_PROCESSES",
            "JAX_COORDINATOR_ADDRESS", "JAX_COORDINATOR_PORT",
            "OMPI_COMM_WORLD_RANK",
            "OMPI_COMM_WORLD_SIZE", "MASTER_ADDR", "MASTER_PORT",
            "TPU_WORKER_ID", "TPU_WORKER_HOSTNAMES", "SLURM_PROCID",
            "SLURM_NTASKS", "TPU_HPC_SIM_DEVICES", "XLA_FLAGS",
        ):
            env.pop(v, None)
        env["JAX_PLATFORMS"] = "cpu"
        env.update(rank_env(pid))
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", WORKER],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        )
    return procs


def _collect(procs, expect_launcher: str):
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, f"worker failed:\n{err[-1500:]}"
            line = [
                l for l in out.splitlines() if l.startswith("RESULT")
            ][-1]
            outs.append(line.split())
    finally:
        # One worker failing/timing out must not leak the other at the
        # rendezvous barrier (it would hold the coordinator port for
        # every later test on this host).
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for _, launcher, _, total in outs:
        assert launcher == expect_launcher
        # 1.0 (process 0) + 2.0 (process 1): the reduction crossed
        # the process boundary.
        assert float(total) == 3.0
    assert {o[2] for o in outs} == {"0", "1"}


def test_explicit_launcher_two_processes():
    """JAX_PROCESS_ID/JAX_NUM_PROCESSES/JAX_COORDINATOR_ADDRESS: the
    'explicit' rung of the detection cascade, end-to-end."""
    port = _free_port()
    procs = _launch(
        lambda pid: {
            "JAX_PROCESS_ID": str(pid),
            "JAX_NUM_PROCESSES": "2",
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        }
    )
    _collect(procs, "explicit")


def test_openmpi_launcher_two_processes():
    """OMPI_COMM_WORLD_* + MASTER_ADDR (the mpiexec contract the
    reference rides, utils/distributed.py:49-60 + :103-121): detection,
    MASTER_ADDR->coordinator shim, and the actual rendezvous."""
    port = _free_port()
    procs = _launch(
        lambda pid: {
            "OMPI_COMM_WORLD_RANK": str(pid),
            "OMPI_COMM_WORLD_SIZE": "2",
            "MASTER_ADDR": "127.0.0.1",
            "MASTER_PORT": str(port),
        }
    )
    _collect(procs, "openmpi")
