"""obs/regress.py + obs/bank.py -- the perf-regression gate and the
banked-history converter.

Pure-host tests (no mesh): the gate's comparison semantics (direction
inference, tolerances, absolute SLO bounds), its pinned exit codes
(0 pass / 1 regression / 2 unusable input), and the --bank pipeline
over driver-style BENCH captures -- including the repo's own committed
BENCH_HISTORY.jsonl staying schema-valid.
"""
import json
import os

import pytest

from tpu_hpc.obs.bank import lift_capture, lift_file
from tpu_hpc.obs.bank import main as bank_main
from tpu_hpc.obs.regress import (
    bank_metrics,
    compare,
    lower_is_better,
    report_metrics,
)
from tpu_hpc.obs.regress import main as regress_main
from tpu_hpc.obs.schema import stamp, validate_file, validate_record

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------
# comparison semantics
# ---------------------------------------------------------------------
class TestCompare:
    def test_direction_inference(self):
        assert lower_is_better("serve.ttft_ms_p95")
        assert lower_is_better("loadgen.background.shed")
        assert lower_is_better("loadgen.stall_events")
        assert not lower_is_better("goodput")
        assert not lower_is_better("mfu")
        assert not lower_is_better("serve.tokens_per_s_per_chip")
        # Reshard-cost metrics: time, wire traffic, and transient peak
        # all regress UPWARD.
        assert lower_is_better("reshard_exchange_ms")
        assert lower_is_better("reshard_exchange_wire_bytes")
        assert lower_is_better("reshard.peak_inflight_bytes")
        # Paged KV cache efficiency (serve/paging.py): a DROPPING hit
        # rate and RISING block stalls are the regressions.
        assert not lower_is_better("serve.prefix_hit_rate")
        assert not lower_is_better("serve.kv_blocks_free_min")
        assert lower_is_better("serve.block_stalls")
        # Speculative decoding (serve/spec.py): acceptance_rate and
        # accepted regress by DROPPING; draft_ms and rejected by
        # RISING -- the --bank gate judges speculative rows instead
        # of skipping them.
        assert not lower_is_better("serve.acceptance_rate")
        assert not lower_is_better("loadgen_heavy_tail_accepted")
        assert lower_is_better("serve.draft_ms")
        assert lower_is_better("loadgen_heavy_tail_rejected")
        # Composite banked names take their direction from the LEAF:
        # an acceptance side key must not inherit the headline
        # latency metric's "ttft" token.
        assert not lower_is_better(
            "loadgen_x_paged_spec_ngram_ttft_ms_p95.acceptance_rate"
        )
        assert lower_is_better(
            "serve_spec_ngram_tokens_per_s_per_chip.itl_ms_p50"
        )
        # Elastic topology morphing (elastic/coordinator.py): stall
        # seconds, wire bytes, and morph counts all regress UPWARD --
        # a run that morphs more, moves more bytes, or stalls longer
        # for the same fault storm got worse.
        assert lower_is_better("elastic_morph_stall_s")
        assert lower_is_better("elastic_morph_stall_s.morphs")
        assert lower_is_better("elastic_morph_stall_s.morph_wire_bytes")
        assert lower_is_better("elastic.wire_bytes")
        assert lower_is_better("elastic.stall_s")
        # Host KV tier (serve/tier.py): pages thrashing across the
        # HBM/DRAM boundary, wire volume over the hop, and the
        # returning tenant's latency/shed all regress UPWARD;
        # resident_sessions (like prefix_hit_rate) regresses by
        # DROPPING -- higher-is-better by deliberate token absence.
        assert lower_is_better("serve.kv_spill_wire_bytes")
        assert lower_is_better("serve.kv_refill_wire_bytes")
        assert lower_is_better("serve.kv_hop_ms_p95")
        tiered = "loadgen_long_idle_sessions_paged_tiered_ttft_ms_p95"
        assert lower_is_better(tiered)
        assert lower_is_better(f"{tiered}.ttft_on_return_ms_p95")
        assert lower_is_better(f"{tiered}.shed_on_return")
        assert lower_is_better(f"{tiered}.kv_spill_wire_bytes")
        assert lower_is_better(f"{tiered}.kv_refill_wire_bytes")
        assert not lower_is_better(f"{tiered}.resident_sessions")
        # Live telemetry plane (obs/digest, obs/live, obs/slo): burn
        # pages, stale publishers, flagged stragglers, and the banked
        # sketch quantile error all regress UPWARD; slo_attainment and
        # budget_remaining regress by DROPPING -- higher-is-better by
        # deliberate token absence, like prefix_hit_rate.
        assert lower_is_better("slo.burns")
        assert lower_is_better("live.digest_stale")
        assert lower_is_better("live.stragglers")
        assert lower_is_better("obs.digest_quantile_rel_err")
        assert lower_is_better("obs.digest_publish_ms")
        assert not lower_is_better("slo.slo_attainment")
        assert not lower_is_better("slo.budget_remaining")
        # Quantized KV pages (kernels/paged_attention.py): the banked
        # logit_rmse pin regresses UPWARD -- a quantizer change that
        # widens the pre-softmax drift fails the gate even while the
        # latency headline rides within tolerance. Composite banked
        # names judge the rmse LEAF, and the kernel/quant family
        # suffixes keep the latency direction of their headline.
        assert lower_is_better("logit_rmse")
        assert lower_is_better(
            "loadgen_decode_heavy_paged_q8_ttft_ms_p95.logit_rmse"
        )
        assert lower_is_better(
            "loadgen_shared_prefix_paged_pallas_ttft_ms_p95"
        )
        assert lower_is_better(
            "loadgen_decode_heavy_paged_pallas_q8_ttft_ms_p95"
        )
        assert not lower_is_better(
            "serve_pallas_q8_tokens_per_s_per_chip"
        )

    def test_spec_config_fields_not_compared(self):
        """spec_k is config; drafted/accepted/rejected/verify_steps
        are raw workload-scaled counts (an IMPROVED acceptance rate
        means FEWER verify steps) -- the gate judges acceptance_rate
        and draft_ms only."""
        from tpu_hpc.obs.regress import report_metrics

        flat = report_metrics({
            "serve": {
                "spec_mode": "ngram", "spec_k": 4,
                "acceptance_rate": 0.9, "draft_ms": 2.5,
                "drafted": 100, "accepted": 90, "rejected": 10,
                "verify_steps": 30, "requests": 8,
            },
        })
        assert flat == {
            "serve.acceptance_rate": 0.9,
            "serve.draft_ms": 2.5,
        }

    def test_live_plane_flattening(self):
        """The report's live block flattens to the judged verdict
        counters (stale/straggler/burn counts, attainment, budget);
        the per-role tables and digest counts are identity detail
        the gate must not diff."""
        flat = report_metrics({
            "live": {
                "digests": 120, "digest_stale": 1,
                "stragglers": ["replica:2"], "slo_burns": 1,
                "slo_attainment": 0.93, "budget_remaining": -5.2,
                "roles": {"replica": {"keys": {}}},
            },
        })
        assert flat == {
            "live.digest_stale": 1.0,
            "live.stragglers": 1.0,
            "slo.burns": 1.0,
            "slo.slo_attainment": 0.93,
            "slo.budget_remaining": -5.2,
        }
        # None attainment (no SLO traffic): the optional leaves stay
        # absent instead of becoming NaN-ish zeros.
        flat = report_metrics({
            "live": {
                "digests": 3, "digest_stale": 0, "stragglers": [],
                "slo_burns": 0, "slo_attainment": None,
                "budget_remaining": None,
            },
        })
        assert flat == {
            "live.digest_stale": 0.0,
            "live.stragglers": 0.0,
            "slo.burns": 0.0,
        }

    def test_paged_config_fields_not_compared(self):
        """kv_block_size/kv_blocks (+free_min) are pool CONFIG, and
        prefill_chunks/raw hit counts DROP when the cache improves: a
        deliberate re-size or a better trie must not read as a perf
        regression -- the gate judges prefix_hit_rate and
        block_stalls only."""
        from tpu_hpc.obs.regress import report_metrics

        flat = report_metrics({
            "serve": {
                "prefix_hit_rate": 0.5, "kv_block_size": 16,
                "kv_blocks": 64, "kv_blocks_free_min": 3,
                "prefill_chunks": 9, "prefix_hits": 4,
                "prefix_hit_blocks": 12, "kv_layout": "paged",
                "block_stalls": 2, "requests": 8,
            },
        })
        assert flat == {
            "serve.prefix_hit_rate": 0.5,
            "serve.block_stalls": 2.0,
        }

    def test_tier_config_fields_not_compared(self):
        """kv_host_blocks/inflight are tier CONFIG, used/free follow
        it, and the spill/refill EVENT counts scale with workload --
        the gate judges the wire bytes and the hop quantiles only."""
        from tpu_hpc.obs.regress import report_metrics

        flat = report_metrics({
            "serve": {
                "kv_host_blocks": 64, "kv_host_used": 10,
                "kv_host_free": 53, "kv_host_drops": 1,
                "kv_host_inflight_bytes": 1 << 20,
                "kv_spills": 3, "kv_spill_pages": 12,
                "kv_refills": 2, "kv_refill_pages": 8,
                "kv_spill_wire_bytes": 4096.0,
                "kv_refill_wire_bytes": 2048.0,
                "kv_hop_ms_p50": 0.4, "kv_hop_ms_p95": 0.9,
                "requests": 8,
            },
        })
        assert flat == {
            "serve.kv_spill_wire_bytes": 4096.0,
            "serve.kv_refill_wire_bytes": 2048.0,
            "serve.kv_hop_ms_p50": 0.4,
            "serve.kv_hop_ms_p95": 0.9,
        }

    def test_identical_passes(self):
        m = {"serve.ttft_ms_p95": 10.0, "goodput": 0.9}
        violations, checked = compare(m, dict(m))
        assert violations == [] and checked == 2

    def test_latency_inflation_fails_with_name(self):
        base = {"serve.ttft_ms_p95": 10.0}
        cand = {"serve.ttft_ms_p95": 15.0}
        violations, _ = compare(base, cand)
        assert len(violations) == 1
        v = violations[0]
        assert v["metric"] == "serve.ttft_ms_p95"
        assert v["direction"] == "lower"

    def test_throughput_drop_fails_improvement_passes(self):
        base = {"mfu": 0.50}
        assert compare(base, {"mfu": 0.40})[0]
        assert compare(base, {"mfu": 0.60})[0] == []
        # 10% default tolerance: a 5% dip rides
        assert compare(base, {"mfu": 0.475})[0] == []

    def test_tolerance_overrides(self):
        base = {"serve.ttft_ms_p95": 100.0}
        cand = {"serve.ttft_ms_p95": 107.0}
        assert compare(base, cand, tol=0.10)[0] == []
        assert compare(base, cand, tol=0.05)[0]
        slo = {"metrics": {"serve.ttft_ms_p95": {"tol": 0.02}}}
        assert compare(base, cand, slo=slo, tol=0.10)[0]
        slo = {"default_tol": 0.02}
        assert compare(base, cand, slo=slo, tol=0.10)[0]

    def test_absolute_slo_bounds_apply_to_candidate_alone(self):
        # Baseline already over the bound: the relative check passes
        # but the SLO still fires -- SLOs are absolute promises.
        slo = {"metrics": {"serve.ttft_ms_p95": {"max": 200.0},
                           "goodput": {"min": 0.8}}}
        base = {"serve.ttft_ms_p95": 300.0, "goodput": 0.5}
        cand = {"serve.ttft_ms_p95": 290.0, "goodput": 0.55}
        violations, _ = compare(base, cand, slo=slo)
        kinds = {v["metric"]: v["kind"] for v in violations}
        assert kinds == {"serve.ttft_ms_p95": "slo_max",
                         "goodput": "slo_min"}

    def test_one_sided_metrics_skipped(self):
        violations, checked = compare(
            {"old_metric": 1.0}, {"new_metric": 2.0}
        )
        assert violations == [] and checked == 0

    def test_passing_slo_bounds_count_as_checks(self):
        """Review finding: an SLO-only gate (no overlapping baseline
        metrics) whose absolute bounds all PASS must count its checks
        -- checked == 0 would turn a healthy run into exit 2."""
        slo = {"metrics": {
            "serve.ttft_ms_p95": {"max": 200.0},
            "goodput": {"min": 0.5, "max": 1.0},
        }}
        violations, checked = compare(
            {}, {"serve.ttft_ms_p95": 50.0, "goodput": 0.9}, slo=slo,
        )
        assert violations == []
        assert checked == 3  # one max + one min + one max, all pass

    def test_bound_on_missing_metric_is_a_violation(self):
        """Review finding: an absolute SLO bound naming a metric the
        candidate never produced (typo, wrong run type) must fail the
        gate, not silently never fire. tol-only entries stay quiet --
        they are modifiers for the relative pass, not promises."""
        slo = {"metrics": {
            "serve.ttft_ms_95": {"max": 200.0},        # typoed p95
            "serve.ttft_ms_p95": {"tol": 0.05},        # tol-only: ok
        }}
        violations, checked = compare(
            {"goodput": 0.9}, {"goodput": 0.9}, slo=slo,
        )
        assert checked == 2  # goodput relative + the missing bound
        assert len(violations) == 1
        assert violations[0]["kind"] == "slo_missing"
        assert violations[0]["metric"] == "serve.ttft_ms_95"


# ---------------------------------------------------------------------
# report flattening
# ---------------------------------------------------------------------
class TestReportMetrics:
    def test_flattens_all_sections(self):
        rep = {
            "goodput": {"combined": {"goodput": 0.9}},
            "mfu": {"mfu": 0.5},
            "serve": {"ttft_ms_p95": 12.0, "tokens_per_s": 100.0,
                      "requests": 8},
            "loadgen": {
                "tenants": {
                    "bg": {"ttft_ms_p50": 1.0, "ttft_ms_p95": 2.0,
                           "ttft_ms_p99": 3.0, "itl_ms_p50": 0.5,
                           "itl_ms_p95": 0.8, "shed": 4,
                           "queued": 6},
                },
                "occupancy_mean": 0.7,
                "stall_events": 2,
                "shed": 4,
            },
        }
        flat = report_metrics(rep)
        assert flat["goodput"] == 0.9
        assert flat["mfu"] == 0.5
        assert flat["serve.ttft_ms_p95"] == 12.0
        assert "serve.requests" not in flat  # workload size, not perf
        assert flat["loadgen.bg.ttft_ms_p95"] == 2.0
        assert flat["loadgen.bg.itl_ms_p95"] == 0.8
        assert flat["loadgen.bg.shed"] == 4.0
        # Per-tenant queued IS gated (docs promise it): shifting
        # queueing between classes at constant total must not pass.
        assert flat["loadgen.bg.queued"] == 6.0
        assert flat["loadgen.occupancy_mean"] == 0.7
        assert flat["loadgen.stall_events"] == 2.0

    def test_missing_sections_tolerated(self):
        assert report_metrics({"goodput": None, "mfu": None,
                               "serve": None, "loadgen": None}) == {}


# ---------------------------------------------------------------------
# CLI exit codes (pinned)
# ---------------------------------------------------------------------
def _write_run(path, ttft_p95=10.0, ttft_p99=12.0):
    """A minimal schema-valid serve run: one summary record."""
    rec = stamp({
        "event": "serve_summary",
        "requests": 4, "tokens": 16, "wall_s": 1.0,
        "tokens_per_s": 16.0, "tokens_per_s_per_chip": 2.0,
        "ttft_ms_p50": 5.0, "ttft_ms_p95": ttft_p95,
        "ttft_ms_p99": ttft_p99,
        "itl_ms_p50": 1.0, "itl_ms_p95": 2.0, "prefill_tokens": 32,
    })
    validate_record(rec)
    path.write_text(json.dumps(rec) + "\n")


class TestCLI:
    def test_pass_fail_exit_codes(self, tmp_path, capsys):
        a, b, c = (tmp_path / f"{x}.jsonl" for x in "abc")
        _write_run(a)
        _write_run(b)
        _write_run(c, ttft_p95=20.0)
        assert regress_main([str(a), str(b)]) == 0
        assert regress_main([str(a), str(c)]) == 1
        out = capsys.readouterr().out
        assert "REGRESSION: serve.ttft_ms_p95" in out

    def test_unusable_input_is_2(self, tmp_path, capsys):
        good = tmp_path / "good.jsonl"
        _write_run(good)
        missing = tmp_path / "gone.jsonl"
        assert regress_main([str(good), str(missing)]) == 2
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        assert regress_main([str(good), str(empty)]) == 2
        invalid = tmp_path / "bad.jsonl"
        invalid.write_text('{"event": "mystery"}\n')
        assert regress_main([str(good), str(invalid)]) == 2
        capsys.readouterr()

    def test_nothing_to_compare_is_2(self, tmp_path, capsys):
        """A gate with zero comparable metrics must fail loudly, not
        pass vacuously."""
        a = tmp_path / "a.jsonl"
        b = tmp_path / "b.jsonl"
        # schema-valid but metric-free records
        rec = stamp({"event": "fault", "kind": "kill"})
        a.write_text(json.dumps(rec) + "\n")
        b.write_text(json.dumps(rec) + "\n")
        assert regress_main([str(a), str(b)]) == 2
        capsys.readouterr()

    def test_json_verdict(self, tmp_path, capsys):
        a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        _write_run(a)
        _write_run(b, ttft_p95=20.0, ttft_p99=30.0)
        assert regress_main([str(a), str(b), "--json"]) == 1
        verdict = json.loads(capsys.readouterr().out)
        assert verdict["pass"] is False
        assert verdict["schema_version"] == 1
        named = {v["metric"] for v in verdict["violations"]}
        assert named == {"serve.ttft_ms_p95", "serve.ttft_ms_p99"}

    def test_slo_config_file(self, tmp_path, capsys):
        a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        _write_run(a)
        _write_run(b, ttft_p95=10.5)
        slo = tmp_path / "slo.json"
        slo.write_text(json.dumps({
            "metrics": {"serve.ttft_ms_p95": {"tol": 0.01}}
        }))
        assert regress_main([str(a), str(b)]) == 0
        assert regress_main([str(a), str(b), "--slo", str(slo)]) == 1
        capsys.readouterr()


# ---------------------------------------------------------------------
# the bank: converter + --bank mode
# ---------------------------------------------------------------------
def _capture(n, rc, parsed, tail=""):
    return {"n": n, "cmd": "python bench.py", "rc": rc, "tail": tail,
            "parsed": parsed}


class TestBank:
    def test_lift_success_and_failure(self):
        ok = lift_capture(_capture(
            1, 0,
            {"metric": "m", "value": 10.0, "unit": "tok/s",
             "vs_baseline": 1.0},
            tail="llama bench | MFU 46.3% (peak)",
        ), "BENCH_r01.json")
        validate_record(ok)
        assert ok["value"] == 10.0 and ok["round"] == 1
        assert ok["mfu"] == pytest.approx(0.463)
        bad = lift_capture(
            _capture(2, 3, None, tail="probe failed\nbackend down"),
            "BENCH_r02.json",
        )
        validate_record(bad)
        assert bad["value"] is None and bad["unit"] == "FAILED"
        assert bad["error"] == "backend down"

    def test_cli_writes_validated_history(self, tmp_path, capsys):
        src = tmp_path / "BENCH_r01.json"
        src.write_text(json.dumps(_capture(
            1, 0, {"metric": "m", "value": 5.0, "unit": "u"},
        )))
        rows = tmp_path / "extra.jsonl"
        rows.write_text(json.dumps(
            {"metric": "m2", "value": 7.0, "unit": "u",
             "workload": "x"}
        ) + "\n")
        out = tmp_path / "HIST.jsonl"
        assert bank_main([str(src), str(rows), "-o", str(out)]) == 0
        assert validate_file(str(out)) == 2
        capsys.readouterr()

    def test_cli_rejects_garbage(self, tmp_path, capsys):
        bad = tmp_path / "junk.json"
        bad.write_text(json.dumps({"whatever": 1}))
        assert bank_main([str(bad), "-o", str(tmp_path / "o")]) == 2
        capsys.readouterr()

    def test_bank_lifts_acceptance_rate_side_key(self):
        """acceptance_rate is a banked side key: a speculative row's
        mechanism metric rides the gate next to its latency
        quantiles (higher-is-better), so a stale draft fails --bank
        even when ttft/itl still ride within tolerance."""
        from tpu_hpc.obs.regress import bank_metrics, compare

        def row(acc):
            return {
                "event": "bench",
                "metric": "loadgen_x_paged_spec_ngram_ttft_ms_p95",
                "value": 100.0, "acceptance_rate": acc,
            }

        base = bank_metrics([row(0.9)])
        key = "loadgen_x_paged_spec_ngram_ttft_ms_p95.acceptance_rate"
        assert base[key] == 0.9
        violations, _ = compare(base, bank_metrics([row(0.5)]))
        assert [v["metric"] for v in violations] == [key]
        assert compare(base, bank_metrics([row(0.95)]))[0] == []

    def test_bank_lifts_tier_side_keys(self):
        """The host-tier row's mechanism metrics are banked side
        keys: TTFT-on-return and shed_on_return (lower), spill/refill
        wire bytes (lower), resident_sessions (higher) ride the
        --bank gate next to the tiered latency headline -- a tier
        that starts shedding returns or thrashing pages fails even
        while p95 TTFT holds."""
        from tpu_hpc.obs.regress import bank_metrics, compare

        name = "loadgen_long_idle_sessions_paged_tiered_ttft_ms_p95"

        def row(ret_p95=40.0, shed=0, resident=20, spill=4096.0):
            return {
                "event": "bench", "metric": name, "value": 100.0,
                "ttft_on_return_ms_p50": 20.0,
                "ttft_on_return_ms_p95": ret_p95,
                "shed_on_return": shed,
                "resident_sessions": resident,
                "kv_spill_wire_bytes": spill,
                "kv_refill_wire_bytes": spill / 2,
            }

        base = bank_metrics([row()])
        for key in (
            "ttft_on_return_ms_p50", "ttft_on_return_ms_p95",
            "shed_on_return", "resident_sessions",
            "kv_spill_wire_bytes", "kv_refill_wire_bytes",
        ):
            assert f"{name}.{key}" in base, key
        assert compare(base, bank_metrics([row()]))[0] == []
        for bad, key in (
            (row(ret_p95=80.0), "ttft_on_return_ms_p95"),
            (row(shed=5), "shed_on_return"),
            (row(resident=2), "resident_sessions"),
            (row(spill=40960.0), "kv_spill_wire_bytes"),
        ):
            violations, _ = compare(base, bank_metrics([bad]))
            assert f"{name}.{key}" in [
                v["metric"] for v in violations
            ], key

    def test_bank_metrics_keep_high_water_mark(self):
        records = [
            stamp({"event": "bench", "metric": "tok_per_chip",
                   "value": v, "unit": "tok/s"})
            for v in (100.0, 120.0, None, 110.0)
        ]
        records.append(stamp({
            "event": "bench", "metric": "serve_tps",
            "value": 50.0, "unit": "tok/s",
            "ttft_ms_p95": 40.0,
        }))
        records.append(stamp({
            "event": "bench", "metric": "serve_tps",
            "value": 45.0, "unit": "tok/s",
            "ttft_ms_p95": 30.0,
        }))
        best = bank_metrics(records)
        assert best["tok_per_chip"] == 120.0          # max (higher)
        assert best["serve_tps"] == 50.0
        assert best["serve_tps.ttft_ms_p95"] == 30.0  # min (lower)

    def test_bank_mode_gates_candidate(self, tmp_path, capsys):
        bank = tmp_path / "hist.jsonl"
        bank.write_text("\n".join(json.dumps(stamp({
            "event": "bench", "metric": "tok_per_chip",
            "value": v, "unit": "tok/s",
        })) for v in (100.0, 120.0)) + "\n")
        good = tmp_path / "good.jsonl"
        good.write_text(json.dumps(stamp({
            "event": "bench", "metric": "tok_per_chip",
            "value": 118.0, "unit": "tok/s",
        })) + "\n")
        slow = tmp_path / "slow.jsonl"
        slow.write_text(json.dumps(stamp({
            "event": "bench", "metric": "tok_per_chip",
            "value": 90.0, "unit": "tok/s",
        })) + "\n")
        assert regress_main(
            ["--bank", str(bank), str(good)]
        ) == 0
        assert regress_main(
            ["--bank", str(bank), str(slow)]
        ) == 1
        out = capsys.readouterr().out
        assert "tok_per_chip" in out

    def test_bank_candidate_judged_by_latest_not_best(
        self, tmp_path, capsys,
    ):
        """Review finding: a candidate file holding several rounds
        must be judged by its NEWEST record per metric -- a regressed
        latest round must not hide behind a better earlier row."""
        bank = tmp_path / "hist.jsonl"
        bank.write_text(json.dumps(stamp({
            "event": "bench", "metric": "tok_per_chip",
            "value": 56.0, "unit": "tok/s",
        })) + "\n")
        cand = tmp_path / "cand.jsonl"
        cand.write_text("\n".join(json.dumps(stamp({
            "event": "bench", "metric": "tok_per_chip",
            "value": v, "unit": "tok/s",
        })) for v in (57.0, 50.0)) + "\n")  # newest round regressed
        assert regress_main(["--bank", str(bank), str(cand)]) == 1
        assert "tok_per_chip" in capsys.readouterr().out
        # The bank (baseline) side still keeps the high-water mark.
        assert bank_metrics([json.loads(l) for l in
                             cand.read_text().splitlines()],
                            keep="best")["tok_per_chip"] == 57.0

    def test_reshard_cost_regression_fails_the_bank_diff(
        self, tmp_path, capsys,
    ):
        """Satellite pin: comm/bench.py's reshard rows ride the bank
        gate -- a slower execute OR more wire bytes than the banked
        history fails with the metric named (both are lower-is-better
        by the direction tokens)."""
        def rows(ms, wire):
            return [
                stamp({
                    "event": "bench", "metric": "reshard_exchange_ms",
                    "value": ms, "unit": "ms", "op": "reshard_exchange",
                }),
                stamp({
                    "event": "bench",
                    "metric": "reshard_exchange_wire_bytes",
                    "value": wire, "unit": "bytes",
                    "op": "reshard_exchange",
                }),
            ]

        def write(path, recs):
            path.write_text(
                "\n".join(json.dumps(r) for r in recs) + "\n"
            )
            return str(path)

        bank = write(tmp_path / "hist.jsonl", rows(2.0, 28000))
        ok = write(tmp_path / "ok.jsonl", rows(2.1, 28000))
        slow = write(tmp_path / "slow.jsonl", rows(4.0, 28000))
        fat = write(tmp_path / "fat.jsonl", rows(2.0, 60000))
        assert regress_main(["--bank", bank, ok]) == 0
        assert regress_main(["--bank", bank, slow]) == 1
        assert "reshard_exchange_ms" in capsys.readouterr().out
        assert regress_main(["--bank", bank, fat]) == 1
        assert "reshard_exchange_wire_bytes" in (
            capsys.readouterr().out
        )

    def test_live_reshard_bench_rows_ride_the_gate(self, tmp_path):
        """End to end: real run_reshard_bench rows on the sim mesh are
        schema-valid JSONL the bank gate accepts (exit 0 against
        themselves)."""
        import jax

        from tpu_hpc.comm.bench import run_reshard_bench
        from tpu_hpc.runtime import MeshSpec, build_mesh

        mesh = build_mesh(MeshSpec(axes={"data": jax.device_count()}))
        records = run_reshard_bench(
            mesh, sizes=[256], warmup=0, iters=1,
            ops=("reshard_exchange",),
        )
        assert records
        path = tmp_path / "rs.jsonl"
        path.write_text(
            "\n".join(json.dumps(r) for r in records) + "\n"
        )
        assert validate_file(str(path)) == len(records)
        assert regress_main(
            ["--bank", str(path), str(path), "--tol", "0.5"]
        ) == 0

    def test_committed_history_artifact_is_valid(self):
        """The repo's own BENCH_HISTORY.jsonl (the bank `regress
        --bank` trusts) stays schema-valid and keeps the trajectory's
        known high-water marks."""
        path = os.path.join(REPO, "BENCH_HISTORY.jsonl")
        assert os.path.exists(path), "run python -m tpu_hpc.obs.bank"
        assert validate_file(path) > 0
        from tpu_hpc.obs.schema import load_records

        best = bank_metrics(load_records(path))
        # The round-5 autotuned headline (HW_QUEUE_r05/bench_bk1024).
        assert best["llama2_train_tokens_per_s_per_chip"] == \
            pytest.approx(124170.6)
        # mfu rides as a quantile-style extra where a round's tail
        # carried the human headline line (driver capture r01). NOTE:
        # mfu on a latency-free metric is higher-is-better, and
        # bank_metrics treats it so.
        assert best["llama2_train_tokens_per_s_per_chip.mfu"] == \
            pytest.approx(0.463)
