"""Comm/compute-overlap layer: bucketed gradient sync, the pipelined
gather-matmul, and the Trainer's comm_mode wiring.

The load-bearing guarantees:
  * bucket assignment is deterministic, size-capped, dtype-pure, and
    reverse-ordered (the DDP idiom);
  * the collective-matmul gather never materializes the gathered
    weight (zero all-gathers in HLO, ring ppermutes instead);
  * comm_mode="bucketed_overlap"/"hierarchical" train step-identically
    to the flat GSPMD path on a small Llama config (the acceptance
    parity), and flat mode's compiled program has NOT grown
    collectives from the comm_mode plumbing (the no-creep guard).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_hpc.checks import hlo
from tpu_hpc.comm import overlap as ov
from tpu_hpc.config import TrainingConfig
from tpu_hpc.models import datasets, llama2
from tpu_hpc.parallel import fsdp, hybrid, tp
from tpu_hpc.runtime import MeshSpec, build_mesh
from tpu_hpc.train import Trainer

MODEL = llama2.LlamaConfig(
    dim=64, n_layers=2, n_heads=4, vocab_size=128, multiple_of=32,
    max_seq_len=32,
)


@pytest.fixture(scope="module")
def params():
    return llama2.init_llama(jax.random.key(0), MODEL)


@pytest.fixture(scope="module")
def token_ds():
    return datasets.TokenStream(vocab_size=128, seq_len=32)


class TestBucketAssignment:
    def _leaves(self, *shapes, dtype=jnp.float32):
        return [
            jax.ShapeDtypeStruct(s, d) if isinstance(d, jnp.dtype)
            else jax.ShapeDtypeStruct(s, jnp.dtype(d))
            for s, d in shapes
        ]

    def test_reverse_order_and_cap(self):
        leaves = self._leaves(
            ((100,), "float32"), ((100,), "float32"), ((100,), "float32")
        )
        # 400-byte leaves, 800-byte cap: two per bucket, reverse walk.
        buckets = ov.assign_buckets(leaves, 800)
        assert buckets == [[2, 1], [0]]

    def test_oversized_leaf_gets_own_bucket(self):
        leaves = self._leaves(((1000,), "float32"), ((1,), "float32"))
        buckets = ov.assign_buckets(leaves, 16)
        assert buckets == [[1], [0]]
        assert all(b for b in buckets)

    def test_dtype_change_cuts_bucket(self):
        leaves = self._leaves(
            ((4,), "float32"), ((4,), "bfloat16"), ((4,), "bfloat16")
        )
        buckets = ov.assign_buckets(leaves, 1 << 20)
        assert buckets == [[2, 1], [0]]

    def test_every_leaf_exactly_once(self, params):
        leaves = jax.tree.leaves(params)
        buckets = ov.assign_buckets(leaves, 4096)
        flat = sorted(i for b in buckets for i in b)
        assert flat == list(range(len(leaves)))

    def test_zero_cap_rejected(self):
        with pytest.raises(ValueError, match="bucket_bytes"):
            ov.assign_buckets([], 0)


class TestPipelinedGather:
    def test_ring_all_gather_matches_flat(self, mesh8):
        x = jnp.arange(40.0).reshape(8, 5)
        out = ov.ppermute_all_gather(mesh8, "data")(
            jax.device_put(x, NamedSharding(mesh8, P("data")))
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def test_gather_matmul_matches_dense(self, mesh8):
        x = jax.random.normal(jax.random.key(0), (16, 24))
        w = jax.random.normal(jax.random.key(1), (24, 6))
        gm = ov.make_pipelined_gather_matmul(mesh8, "data")
        y = gm(
            jax.device_put(x, NamedSharding(mesh8, P("data"))),
            jax.device_put(w, NamedSharding(mesh8, P("data"))),
        )
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(x @ w), rtol=1e-5, atol=1e-5
        )

    def test_gather_matmul_never_materializes_w(self, mesh8):
        # The collective-matmul claim in HLO: ring collective-permutes,
        # ZERO all-gathers -- peak weight memory stays one shard.
        x = jnp.ones((16, 24))
        w = jnp.ones((24, 6))
        text = hlo.lowered_text(
            ov.make_pipelined_gather_matmul(mesh8, "data"),
            jax.device_put(x, NamedSharding(mesh8, P("data"))),
            jax.device_put(w, NamedSharding(mesh8, P("data"))),
        )
        counts = hlo.collective_counts(text)
        assert counts["all-gather"] == 0, counts
        assert counts["collective-permute"] >= 1, counts

    def test_ring_all_gather_is_permutes_only(self, mesh8):
        text = hlo.lowered_text(
            ov.ppermute_all_gather(mesh8, "data"), jnp.arange(8.0)
        )
        counts = hlo.collective_counts(text)
        assert counts["all-gather"] == 0, counts
        assert counts["collective-permute"] >= 1, counts


def _losses(comm_mode, mesh, batch_pspec, ds, params, steps=3,
            grad_accum=1, bucket_mb=1, batch=8):
    cfg = TrainingConfig(
        global_batch_size=batch, steps_per_epoch=1, epochs=1,
        learning_rate=1e-2, comm_mode=comm_mode,
        comm_bucket_mb=bucket_mb, grad_accum_steps=grad_accum,
    )
    tr = Trainer(
        cfg, mesh, llama2.make_forward(MODEL, lambda t: t), params,
        batch_pspec=batch_pspec,
    )
    out = []
    for s in range(steps):
        m = tr.train_step(ds.batch_at(s, batch))
        out.append(float(jax.device_get(m["loss"])))
    return out


@pytest.fixture(scope="module")
def flat_losses(mesh8, params, token_ds):
    """The flat-sync 3-step loss trajectory every manual mode must
    reproduce (computed once: a Trainer build + compile is the
    expensive part of each parity check)."""
    return _losses("flat", mesh8, P("data"), token_ds, params)


class TestTrainerCommMode:
    """Acceptance parity: manual gradient-sync modes yield
    step-identical losses vs flat sync for a small Llama config (the
    reductions reassociate, so 'identical' means float-reassociation
    tolerance: observed drift ~1e-6 over 3 steps)."""

    def test_bucketed_overlap_matches_flat(self, mesh8, params, token_ds,
                                           flat_losses):
        buck = _losses(
            "bucketed_overlap", mesh8, P("data"), token_ds, params
        )
        np.testing.assert_allclose(buck, flat_losses, rtol=1e-5, atol=1e-5)

    def test_hierarchical_matches_flat(self, devices, params, token_ds,
                                       flat_losses):
        mesh_h = build_mesh(MeshSpec(axes={"dcn": 2, "data": 4}))
        hier = _losses(
            "hierarchical", mesh_h, P(("dcn", "data")), token_ds, params
        )
        np.testing.assert_allclose(hier, flat_losses, rtol=1e-5, atol=1e-5)

    def test_bucketed_with_grad_accum_matches_flat(self, mesh8, params,
                                                   token_ds):
        # psum is linear: per-microbatch sync + summation == syncing
        # the accumulated gradient; the trajectories must agree.
        # (batch 16: each accum microbatch must still cover the axis.)
        flat = _losses(
            "flat", mesh8, P("data"), token_ds, params, grad_accum=2,
            batch=16,
        )
        buck = _losses(
            "bucketed_overlap", mesh8, P("data"), token_ds, params,
            grad_accum=2, batch=16,
        )
        np.testing.assert_allclose(buck, flat, rtol=1e-5, atol=1e-5)

    def test_bucketed_sync_reduces_per_bucket(self, mesh8, params,
                                              token_ds):
        # The synced value_and_grad's lowered program carries one
        # all-reduce per bucket (+ the loss pmean): bucketing really
        # splits the sync into schedulable pieces instead of one
        # monolithic collective.
        svag = ov.make_synced_value_and_grad(
            llama2.make_forward(MODEL, lambda t: t), mesh8, P("data"),
            params, "bucketed_overlap", bucket_bytes=16 * 1024,
        )
        batch = jax.device_put(
            token_ds.batch_at(0, 8), NamedSharding(mesh8, P("data"))
        )
        text = hlo.lowered_text(
            svag, params, {}, batch, jax.random.key(0)
        )
        n_buckets = len(ov.assign_buckets(
            jax.tree.leaves(params), 16 * 1024
        ))
        counts = hlo.collective_counts(text)
        assert n_buckets > 1
        assert counts["all-reduce"] == n_buckets + 1, (counts, n_buckets)

    def test_flat_mode_no_collective_creep(self, mesh8, params, token_ds):
        # The comm_mode plumbing must leave the default path's program
        # alone: the scanned epoch chunk (the hot loop) carries exactly
        # the collectives of one compiled step plus the data
        # generator's fixed layout ops -- nothing more -- and the
        # counts are chunk-length invariant (scan never unrolls into
        # duplicated collectives).
        cfg = TrainingConfig(
            global_batch_size=8, steps_per_epoch=2, epochs=1,
            learning_rate=1e-2,
        )
        tr = Trainer(
            cfg, mesh8, llama2.make_forward(MODEL, lambda t: t), params,
            batch_pspec=P("data"),
        )
        sharding = NamedSharding(mesh8, P("data"))
        batch = jax.device_put(token_ds.batch_at(0, 8), sharding)
        step_counts = hlo.collective_counts(
            hlo.compiled_text(tr._step_impl, tr.state, batch)
        )
        gen = token_ds.traced_batch

        def gen_only(step):
            return jax.tree.map(
                lambda a: jax.lax.with_sharding_constraint(a, sharding),
                gen(step, 8),
            )

        gen_counts = hlo.collective_counts(
            hlo.compiled_text(gen_only, jnp.zeros((), jnp.int32))
        )
        epoch1 = hlo.collective_counts(
            tr._get_epoch_fn(token_ds, 1).as_text()
        )
        epoch2 = hlo.collective_counts(
            tr._get_epoch_fn(token_ds, 2).as_text()
        )
        assert sum(step_counts.values()) > 0
        assert epoch2 == epoch1, (epoch2, epoch1)
        expected = {
            op: step_counts[op] + gen_counts[op] for op in step_counts
        }
        assert epoch2 == expected, (epoch2, expected)


class TestValidation:
    def test_sharded_params_rejected(self, mesh8, params):
        specs = fsdp.param_pspecs(params, axis_size=8, min_size=100)
        with pytest.raises(ValueError, match="replicated params"):
            fsdp.validate_grad_sync_mode("bucketed_overlap", specs)

    def test_unknown_mode_rejected(self, params):
        with pytest.raises(ValueError, match="unknown comm_mode"):
            fsdp.validate_grad_sync_mode("turbo", None)

    def test_flat_passes_any_plan(self, params):
        specs = fsdp.param_pspecs(params, axis_size=8, min_size=100)
        assert fsdp.validate_grad_sync_mode("flat", specs) == "flat"

    def test_hybrid_plan_rejects_manual(self, params):
        # A hybrid FSDPxTP tree claims dims by design, so the same
        # plan-time validation the Trainer runs must reject the
        # DDP-family manual modes for it (and pass flat through).
        specs = hybrid.hybrid_pspecs(
            params, tp.llama_rules(), data_size=2, min_size=100
        )
        with pytest.raises(ValueError, match="replicated params"):
            fsdp.validate_grad_sync_mode("hierarchical", specs)
        assert fsdp.validate_grad_sync_mode("flat", specs) == "flat"

    def test_trainer_rejects_hier_on_one_axis(self, mesh8, params,
                                              token_ds):
        with pytest.raises(ValueError, match="two sync axes"):
            _losses("hierarchical", mesh8, P("data"), token_ds, params,
                    steps=0)

    def test_unsharded_batch_rejected(self):
        with pytest.raises(ValueError, match="no mesh axis"):
            ov.sync_axes_from_batch_pspec(P())

    def test_integer_aux_rejected(self, mesh8):
        # No reduction is universally correct for a non-inexact leaf
        # (a batch count wants psum, a replicated counter identity),
        # so the manual path must refuse rather than silently return
        # one shard's local value where flat returns the global one.
        def fwd(p, ms, batch, rng):
            loss = jnp.mean(batch["x"] * p["w"])
            return loss, ms, {"n": jnp.int32(3)}

        params = {"w": jnp.ones(())}
        vg = ov.make_synced_value_and_grad(
            fwd, mesh8, P("data"), params, "bucketed_overlap"
        )
        with pytest.raises(ValueError, match="non-inexact"):
            jax.eval_shape(
                vg, params, {}, {"x": jnp.ones((8,))}, jax.random.key(0)
            )

    def test_rng_decorrelated_across_shards(self, mesh8):
        # The step rng arrives replicated; each shard must fold its
        # position in, or every data shard draws the identical
        # dropout mask. Observable: the pmean of per-shard draws must
        # differ from the single draw all shards would share.
        def fwd(p, ms, batch, rng):
            draw = jax.random.normal(rng, ())
            loss = jnp.mean(batch["x"] * p["w"]) * 0.0 + draw * 0.0
            return loss, ms, {"draw": draw}

        params = {"w": jnp.ones(())}
        vg = ov.make_synced_value_and_grad(
            fwd, mesh8, P("data"), params, "bucketed_overlap"
        )
        rng = jax.random.key(7)
        (_, (_, aux)), _ = vg(params, {}, {"x": jnp.ones((8,))}, rng)
        shared = float(jax.random.normal(rng, ()))
        assert abs(float(aux["draw"]) - shared) > 1e-6
