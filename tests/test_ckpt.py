"""Checkpoint round-trip + auto-resume tests.

The capability tier the reference could only exercise on-cluster
(SURVEY 5.4): sharded save/restore, cross-layout restore (save FSDP,
restore DP), snapshot auto-resume mid-run, consolidated export.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hpc.ckpt import CheckpointManager
from tpu_hpc.config import TrainingConfig
from tpu_hpc.models import datasets, losses
from tpu_hpc.models.unet import UNetConfig, apply_unet, init_unet
from tpu_hpc.parallel import dp, fsdp
from tpu_hpc.train import Trainer


def _forward(cfg_model):
    def forward(params, model_state, batch, step_rng):
        x, y = batch
        pred, new_ms = apply_unet(params, model_state, x, cfg_model, train=True)
        return losses.lat_weighted_mse(pred, y), new_ms, {}

    return forward


@pytest.fixture()
def setup(tmp_path):
    cfg_model = UNetConfig(in_channels=4, out_channels=4, base_features=4)
    params, ms = init_unet(jax.random.key(0), cfg_model, (21, 24, 4))
    ds = datasets.ERA5Synthetic(n_vars=2, n_levels=2, lat=21, lon=24)
    return cfg_model, params, ms, ds, str(tmp_path / "ckpts")


def _trainer(cfg_model, params, ms, mesh, ckpt_dir, pspec_fn, **cfg_kw):
    cfg = TrainingConfig(
        global_batch_size=16, steps_per_epoch=2, learning_rate=1e-2,
        save_every=1, checkpoint_dir=ckpt_dir, **cfg_kw,
    )
    mgr = CheckpointManager(ckpt_dir, async_save=False)
    return Trainer(
        cfg, mesh, _forward(cfg_model), params, ms,
        param_pspecs=pspec_fn(params),
        checkpoint_manager=mgr,
    )


def test_save_restore_roundtrip(mesh8, setup):
    cfg_model, params, ms, ds, ckpt_dir = setup
    tr = _trainer(cfg_model, params, ms, mesh8, ckpt_dir, dp.param_pspecs,
                  epochs=1)
    tr.fit(ds)
    tr.checkpoint_manager.wait()
    assert tr.checkpoint_manager.all_steps() == [2]
    restored = tr.checkpoint_manager.restore_latest(tr.state)
    for a, b in zip(jax.tree.leaves(restored.params),
                    jax.tree.leaves(tr.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_auto_resume_continues_from_step(mesh8, setup):
    cfg_model, params, ms, ds, ckpt_dir = setup
    tr1 = _trainer(cfg_model, params, ms, mesh8, ckpt_dir, dp.param_pspecs,
                   epochs=2)
    r1 = tr1.fit(ds)
    tr1.checkpoint_manager.wait()

    # Fresh trainer, same dir: must resume at step 4, run 1 more epoch.
    tr2 = _trainer(cfg_model, params, ms, mesh8, ckpt_dir, dp.param_pspecs,
                   epochs=3)
    r2 = tr2.fit(ds)
    assert int(jax.device_get(tr2.state.step)) == 6
    # epochs 0,1 were skipped: only 1 epoch summary recorded
    assert len(r2["epochs"]) == 1


def test_cross_layout_restore_fsdp_to_dp(mesh8, setup):
    """Save under FSDP sharding, restore into a DP (replicated) layout:
    the portability the reference needed the gather-to-rank0 dance for."""
    cfg_model, params, ms, ds, ckpt_dir = setup
    tr_fsdp = _trainer(
        cfg_model, params, ms, mesh8, ckpt_dir,
        lambda p: fsdp.param_pspecs(p, axis_size=8, min_size=200),
        epochs=1,
    )
    tr_fsdp.fit(ds)
    tr_fsdp.checkpoint_manager.wait()

    tr_dp = _trainer(cfg_model, params, ms, mesh8, ckpt_dir, dp.param_pspecs,
                     epochs=1)
    restored = tr_dp.checkpoint_manager.restore_latest(tr_dp.state)
    assert restored is not None
    leaf = jax.tree.leaves(restored.params)[0]
    assert leaf.sharding.is_fully_replicated
    for a, b in zip(jax.tree.leaves(restored.params),
                    jax.tree.leaves(tr_fsdp.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_elastic_restore_onto_smaller_mesh(mesh8, setup):
    """Elastic reshape: train FSDP-sharded on 8 devices, resume on a
    4-device mesh -- a shrunken pod after preemption. The reference's
    torch.save world cannot do this without a manual gather/re-shard
    dance; here the checkpoint is layout-free and the restore target's
    shardings re-tile it. Training must continue bit-for-bit from the
    same params and keep stepping."""
    from tpu_hpc.runtime import MeshSpec, build_mesh

    cfg_model, params, ms, ds, ckpt_dir = setup
    tr8 = _trainer(
        cfg_model, params, ms, mesh8, ckpt_dir,
        lambda p: fsdp.param_pspecs(p, axis_size=8, min_size=200),
        epochs=1,
    )
    tr8.fit(ds)
    tr8.checkpoint_manager.wait()

    mesh4 = build_mesh(
        MeshSpec(axes={"data": 4}), devices=jax.devices()[:4]
    )
    tr4 = _trainer(
        cfg_model, params, ms, mesh4, ckpt_dir,
        lambda p: fsdp.param_pspecs(p, axis_size=4, min_size=200),
        epochs=2,
    )
    resumed = tr4.maybe_resume()
    assert resumed == 2  # picked up at the 8-device run's last step
    for a, b in zip(jax.tree.leaves(tr4.state.params),
                    jax.tree.leaves(tr8.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # And it keeps training on the smaller mesh.
    r = tr4.fit(ds)
    assert int(jax.device_get(tr4.state.step)) == 4
    assert np.isfinite(r["final_loss"])


def test_mid_epoch_resume_stream_alignment(mesh8, setup, tmp_path):
    """Interrupted-and-resumed training must be bit-identical to an
    uninterrupted run: state.step drives the data/RNG stream, so a
    checkpoint landing mid-epoch must not replay or skip batches."""
    cfg_model, params, ms, ds, _ = setup

    def make(ckpt_dir, epochs):
        cfg = TrainingConfig(
            global_batch_size=16, steps_per_epoch=3, learning_rate=1e-2,
            epochs=epochs, checkpoint_dir=ckpt_dir,
        )
        mgr = CheckpointManager(ckpt_dir, async_save=False)
        return Trainer(
            cfg, mesh8, _forward(cfg_model), params, ms,
            param_pspecs=dp.param_pspecs(params), checkpoint_manager=mgr,
        )

    # Uninterrupted: 2 epochs x 3 steps.
    tr_full = make(str(tmp_path / "full"), epochs=2)
    tr_full.fit(ds)

    # Interrupted mid-epoch: run 2 steps manually, save at step 2, then
    # resume and fit to the same total.
    tr_a = make(str(tmp_path / "resume"), epochs=2)
    for s in range(2):
        tr_a.train_step(ds.batch_at(s, 16))
    tr_a.checkpoint_manager.save(tr_a.state)
    tr_a.checkpoint_manager.wait()

    tr_b = make(str(tmp_path / "resume"), epochs=2)
    tr_b.fit(ds)
    assert int(jax.device_get(tr_b.state.step)) == 6
    for a, b in zip(jax.tree.leaves(tr_full.state.params),
                    jax.tree.leaves(tr_b.state.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        )


def test_export_consolidated(mesh8, setup, tmp_path):
    cfg_model, params, ms, ds, ckpt_dir = setup
    tr = _trainer(cfg_model, params, ms, mesh8, ckpt_dir,
                  lambda p: fsdp.param_pspecs(p, axis_size=8, min_size=200),
                  epochs=1)
    tr.fit(ds)
    out = str(tmp_path / "full_state.npz")
    tr.checkpoint_manager.export_consolidated(tr.state.params, out)
    loaded = np.load(out)
    assert len(loaded.files) == len(jax.tree.leaves(tr.state.params))


def test_sigterm_snapshots_and_stops(mesh8, setup):
    """Preemption model: SIGTERM mid-run -> snapshot + clean stop, and
    a relaunch resumes from the saved step (the reference's
    PBS-resubmission + snapshot pattern, SURVEY 5.3 -- here the signal
    is handled in-process since TPU-VM spot events deliver SIGTERM)."""
    import os
    import signal

    cfg_model, params, ms, ds, ckpt_dir = setup

    class PreemptingDataset:
        """Host-fed dataset that delivers SIGTERM during step 3."""

        def __init__(self, inner):
            self.inner = inner

        def batch_at(self, step, batch_size):
            if step == 3:
                os.kill(os.getpid(), signal.SIGTERM)
            return jax.device_get(self.inner.batch_at(step, batch_size))

    tr = _trainer(cfg_model, params, ms, mesh8, ckpt_dir, dp.param_pspecs,
                  epochs=5)
    result = tr.fit(PreemptingDataset(ds))
    # Stopped early (epoch 1 of 5), with a snapshot at the boundary.
    assert len(result["epochs"]) < 5
    steps = tr.checkpoint_manager.all_steps()
    assert steps and max(steps) == 4
    # Default SIGTERM disposition restored after fit.
    assert signal.getsignal(signal.SIGTERM) is signal.SIG_DFL

    tr2 = _trainer(cfg_model, params, ms, mesh8, ckpt_dir, dp.param_pspecs,
                   epochs=5, resume=True)
    assert tr2.maybe_resume() == 4


def test_restore_fp32_checkpoint_into_bf16_moments_run(tmp_path, mesh8):
    """Switching adam_moments_dtype to bfloat16 mid-training (the
    16 GiB-chip unlock, REPORT_70b_128chip_2M.md) must restore an
    existing fp32-moments checkpoint: orbax casts into the template's
    dtype, training continues, and the moments stay bf16."""
    import optax

    from tpu_hpc.models import llama2

    mesh = mesh8
    m = llama2.LlamaConfig(
        dim=32, n_layers=1, n_heads=4, vocab_size=64,
        multiple_of=16, max_seq_len=16,
    )
    params = llama2.init_llama(jax.random.key(0), m)
    ds = datasets.TokenStream(vocab_size=64, seq_len=16)
    d = str(tmp_path / "ck")

    cfg32 = TrainingConfig(
        global_batch_size=8, steps_per_epoch=2, epochs=1,
        weight_decay=0.1, save_every=1, learning_rate=1e-2,
    )
    Trainer(
        cfg32, mesh, llama2.make_forward(m), params,
        checkpoint_manager=CheckpointManager(d, async_save=False),
    ).fit(ds)

    cfg16 = TrainingConfig(
        global_batch_size=8, steps_per_epoch=2, epochs=2,
        weight_decay=0.1, resume=True, learning_rate=1e-2,
        adam_moments_dtype="bfloat16",
    )
    t16 = Trainer(
        cfg16, mesh, llama2.make_forward(m), params,
        checkpoint_manager=CheckpointManager(d, async_save=False),
    )
    out = t16.fit(ds)
    assert jnp.isfinite(out["final_loss"])
    adam = [
        s for s in jax.tree.leaves(
            t16.state.opt_state,
            is_leaf=lambda x: isinstance(x, optax.ScaleByAdamState),
        )
        if isinstance(s, optax.ScaleByAdamState)
    ]
    assert adam
    for s in adam:
        for leaf in jax.tree.leaves(s.mu) + jax.tree.leaves(s.nu):
            assert leaf.dtype == jnp.bfloat16
