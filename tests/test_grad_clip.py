"""Global gradient-norm clipping (cfg.max_grad_norm).

The standard LLM-pretraining stabilizer the reference's toy steps
never needed. The invariants: the clip caps the update-driving
gradient norm exactly, a generous threshold is a no-op (bit-exact
trajectory vs clipping off), and the threshold is accum-invariant
because the clip sees the full accumulated gradient.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpu_hpc.config import TrainingConfig
from tpu_hpc.train.trainer import make_optimizer


def _grads(scale):
    return {
        "w": jnp.full((4, 4), scale, jnp.float32),
        "b": jnp.full((4,), -scale, jnp.float32),
    }


def _gnorm(tree):
    return float(optax.global_norm(tree))


class TestClip:
    def test_caps_the_norm(self):
        cfg = TrainingConfig(max_grad_norm=1.0, weight_decay=0.1)
        tx = make_optimizer(cfg)
        params = _grads(0.0)
        state = tx.init(params)
        big = _grads(100.0)
        # Apply the clip alone to check the norm it forwards: compare
        # the update against the same optimizer fed the pre-clipped
        # gradient.
        clipped, _ = optax.clip_by_global_norm(1.0).update(
            big, optax.clip_by_global_norm(1.0).init(params)
        )
        assert _gnorm(clipped) == pytest.approx(1.0, rel=1e-5)
        u_via_cfg, _ = tx.update(big, state, params)
        ref = make_optimizer(
            TrainingConfig(max_grad_norm=0.0, weight_decay=0.1)
        )
        u_ref, _ = ref.update(clipped, ref.init(params), params)
        assert jax.tree.all(
            jax.tree.map(
                lambda a, b: jnp.allclose(a, b), u_via_cfg, u_ref
            )
        )

    def test_generous_threshold_is_noop(self):
        g = _grads(0.5)
        params = _grads(0.0)
        on = make_optimizer(
            TrainingConfig(max_grad_norm=1e9, weight_decay=0.1)
        )
        off = make_optimizer(
            TrainingConfig(max_grad_norm=0.0, weight_decay=0.1)
        )
        u_on, _ = on.update(g, on.init(params), params)
        u_off, _ = off.update(g, off.init(params), params)
        np.testing.assert_array_equal(
            np.asarray(u_on["w"]), np.asarray(u_off["w"])
        )

    def test_sgd_path_clips_too(self):
        cfg = TrainingConfig(max_grad_norm=1.0, weight_decay=0.0)
        tx = make_optimizer(cfg)
        params = _grads(0.0)
        u, _ = tx.update(_grads(100.0), tx.init(params), params)
        # SGD update = -lr * clipped grad
        assert _gnorm(u) == pytest.approx(cfg.learning_rate, rel=1e-5)

    def test_negative_rejected(self):
        with pytest.raises(ValueError, match="max_grad_norm"):
            make_optimizer(TrainingConfig(max_grad_norm=-1.0))


class TestClipTraining:
    def test_trains_and_is_accum_invariant(self, mesh8):
        """The clip threshold means the same thing at accum 1 and 4:
        the jitted step applies it to the full accumulated gradient,
        so both runs follow the identical trajectory. fp32 + SGD, the
        same recipe as tests/test_grad_accum.py: bf16 microbatched
        matmuls reduce in a different order and an adaptive
        optimizer's first step amplifies last-ulp differences; the
        clip's norm division is the only nonlinearity exercised."""
        from tpu_hpc.models import datasets, llama2
        from tpu_hpc.train import Trainer

        model = llama2.LlamaConfig(
            dim=64, n_layers=2, n_heads=4, vocab_size=128,
            multiple_of=32, max_seq_len=32, dtype=jnp.float32,
        )
        ds = datasets.TokenStream(vocab_size=128, seq_len=32)

        def run(accum):
            cfg = TrainingConfig(
                epochs=1, steps_per_epoch=3, global_batch_size=32,
                learning_rate=1e-2, weight_decay=0.0,
                max_grad_norm=0.1,  # tight: actively clips at init
                grad_accum_steps=accum,
            )
            params = llama2.init_llama(jax.random.key(0), model)
            tr = Trainer(
                cfg, mesh8, llama2.make_forward(model), params
            )
            tr.fit(ds)
            return jax.device_get(tr.state.params)

        p1, p4 = run(1), run(4)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=1e-4, atol=1e-6
            ),
            p1, p4,
        )


class TestGradNormMetric:
    def test_reported_iff_clipping(self, mesh8):
        """metrics['grad_norm'] = the pre-clip accumulated-gradient
        norm, present exactly when max_grad_norm > 0 (unclipped
        configs keep their pinned collective signatures)."""
        from tpu_hpc.parallel import dp
        from tpu_hpc.train import Trainer

        def forward(params, ms, batch, rng):
            x, y = batch
            pred = x @ params["w"]
            return jnp.mean((pred - y) ** 2), ms, {}

        def run(clip):
            cfg = TrainingConfig(
                epochs=1, steps_per_epoch=1, global_batch_size=8,
                learning_rate=0.0, max_grad_norm=clip,
            )
            params = {"w": jnp.zeros((4, 4))}
            tr = Trainer(
                cfg, mesh8, forward, params,
                param_pspecs=dp.param_pspecs(params),
                batch_pspec=dp.batch_pspec(),
            )
            x = jnp.ones((8, 4))
            y = jnp.zeros((8, 4))
            return tr.train_step((x, y)), params

        m, _ = run(0.0)
        assert "grad_norm" not in m
        m, _ = run(1e9)  # generous threshold: reports, never clips
        assert "grad_norm" in m

    def test_value_matches_manual_norm(self, mesh8):
        from tpu_hpc.parallel import dp
        from tpu_hpc.train import Trainer

        def forward(params, ms, batch, rng):
            x, y = batch
            pred = x @ params["w"]
            return jnp.mean((pred - y) ** 2), ms, {}

        cfg = TrainingConfig(
            epochs=1, steps_per_epoch=1, global_batch_size=8,
            learning_rate=0.0, max_grad_norm=1e9,
        )
        params = {"w": jnp.zeros((4, 4), jnp.float32)}
        tr = Trainer(
            cfg, mesh8, forward, params,
            param_pspecs=dp.param_pspecs(params),
            batch_pspec=dp.batch_pspec(),
        )
        x = jnp.ones((8, 4), jnp.float32)
        y = jnp.ones((8, 4), jnp.float32)
        m = tr.train_step((x, y))
        g = jax.grad(
            lambda w: jnp.mean((x @ w - y) ** 2)
        )(params["w"])
        assert float(m["grad_norm"]) == pytest.approx(
            float(jnp.linalg.norm(g)), rel=1e-5
        )

    def test_explicit_optimizer_with_clip_rejected(self, mesh8):
        """An explicit optimizer bypasses make_optimizer's clip chain;
        silently ignoring max_grad_norm would train unclipped while
        the metric implies otherwise (review finding)."""
        import optax as _optax

        from tpu_hpc.parallel import dp
        from tpu_hpc.train import Trainer

        params = {"w": jnp.zeros((4, 4))}
        with pytest.raises(ValueError, match="explicitly passed"):
            Trainer(
                TrainingConfig(max_grad_norm=1.0),
                mesh8,
                lambda p, ms, b, r: (jnp.float32(0), ms, {}),
                params,
                param_pspecs=dp.param_pspecs(params),
                batch_pspec=dp.batch_pspec(),
                optimizer=_optax.adamw(1e-3),
            )


    def test_epoch_record_carries_grad_norm(self, mesh8, tmp_path):
        """The per-epoch JSONL record includes grad_norm when clipping
        is on (review finding: the record branch had no test)."""
        import json
        import math

        from tpu_hpc.models import datasets
        from tpu_hpc.parallel import dp
        from tpu_hpc.train import Trainer

        def forward(params, ms, batch, rng):
            x, y = batch
            return jnp.mean((x @ params["w"] - y) ** 2), ms, {}

        mpath = str(tmp_path / "run.jsonl")
        cfg = TrainingConfig(
            epochs=1, steps_per_epoch=2, global_batch_size=16,
            learning_rate=1e-2, max_grad_norm=1e9, metrics_path=mpath,
        )
        params = {"w": jnp.zeros((20, 1))}
        tr = Trainer(
            cfg, mesh8, forward, params,
            param_pspecs=dp.param_pspecs(params),
            batch_pspec=dp.batch_pspec(),
        )
        tr.fit(datasets.ToyRegression())
        records = [json.loads(x) for x in open(mpath)]
        # The closing record is the resilience goodput summary; the
        # last EPOCH record is the one that carries grad_norm.
        epoch = [r for r in records if r["event"] == "epoch"][-1]
        assert epoch["event"] == "epoch"
        assert math.isfinite(epoch["grad_norm"])

    def test_forward_grad_norm_aux_collision_rejected(self, mesh8):
        """A forward aux named grad_norm + clipping on must raise, not
        silently flip the metric's meaning (review finding)."""
        from tpu_hpc.parallel import dp
        from tpu_hpc.train import Trainer

        def forward(params, ms, batch, rng):
            x, y = batch
            loss = jnp.mean((x @ params["w"] - y) ** 2)
            return loss, ms, {"grad_norm": loss}

        cfg = TrainingConfig(
            epochs=1, steps_per_epoch=1, global_batch_size=8,
            max_grad_norm=1.0,
        )
        params = {"w": jnp.zeros((4, 4))}
        tr = Trainer(
            cfg, mesh8, forward, params,
            param_pspecs=dp.param_pspecs(params),
            batch_pspec=dp.batch_pspec(),
        )
        with pytest.raises(ValueError, match="grad_norm"):
            tr.train_step((jnp.ones((8, 4)), jnp.ones((8, 4))))
