"""The serving engine: KV-cache correctness, continuous batching, and
the zero-recompile steady state.

Three invariant families:
  * **parity** -- greedy decode THROUGH the cache is token-exact
    against the no-cache full forward pass (llama2.apply_llama), the
    oracle that pins the functional replay in serve/engine.py to the
    training model's math;
  * **slot invariants** -- evict/admit mid-stream reuses slots safely
    (stale cache rows unreachable behind the per-slot length mask),
    position counters track prompt + generated and feed RoPE;
  * **compile discipline** -- after warmup, a replayed request mix
    touching every program shape triggers ZERO new compiles (the
    engine's executable-table counter is the guard).

All on the 8-device simulated mesh (data=4 x model=2: batch slots
shard over data, KV heads over the TP axis), fp32 compute so
"token-exact" means exact.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hpc.models import llama2
from tpu_hpc.runtime import MeshSpec, build_mesh
from tpu_hpc.serve import (
    ContinuousBatcher,
    Engine,
    Request,
    ServeConfig,
    ServeMeter,
)
from tpu_hpc.serve.engine import kv_cache_pspec


TINY = llama2.LlamaConfig(
    dim=64, n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=128,
    multiple_of=16, max_seq_len=64, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def serve_mesh(devices):
    return build_mesh(MeshSpec(axes={"data": 4, "model": 2}))


@pytest.fixture(scope="module")
def tiny_params():
    return llama2.init_llama(jax.random.key(0), TINY)


@pytest.fixture(scope="module")
def warm_engine(tiny_params, serve_mesh):
    engine = Engine(
        tiny_params, TINY,
        ServeConfig(slots=4, max_seq_len=48, prefill_buckets=(8, 16)),
        serve_mesh,
    )
    engine.warmup()
    return engine


_ORACLE_LEN = 32  # fixed oracle shape; covers every test's prompt+new


@pytest.fixture(scope="module")
def greedy_oracle(tiny_params):
    """Greedy continuation via the full NO-CACHE forward pass
    (llama2.apply_llama -- the training model, not engine code).

    Jitted once at a fixed padded length: under the causal mask,
    logits at row i depend only on tokens <= i, so reading row
    ``len-1`` of a zero-padded [1, 32] forward is exactly the
    unpadded full forward -- one compile serves every prompt length
    in the file."""
    fwd = jax.jit(
        lambda toks: llama2.apply_llama(tiny_params, toks, TINY)
    )

    def oracle(params, prompt, steps):
        assert params is tiny_params  # one param tree per module
        toks = list(prompt)
        out = []
        for _ in range(steps):
            assert len(toks) <= _ORACLE_LEN
            padded = np.zeros((1, _ORACLE_LEN), np.int32)
            padded[0, :len(toks)] = toks
            logits = fwd(jnp.asarray(padded))
            t = int(jnp.argmax(logits[0, len(toks) - 1]))
            out.append(t)
            toks.append(t)
        return out

    return oracle


class TestGreedyParity:
    def test_single_request_token_exact(
        self, warm_engine, tiny_params, greedy_oracle
    ):
        rng = np.random.default_rng(0)
        prompt = rng.integers(0, TINY.vocab_size, size=11).tolist()
        got = ContinuousBatcher(warm_engine).run(
            [Request(rid="a", prompt=prompt, max_new_tokens=4)]
        )["a"]
        assert got == greedy_oracle(tiny_params, prompt, 4)

    def test_prompt_of_one_token(
        self, warm_engine, tiny_params, greedy_oracle
    ):
        got = ContinuousBatcher(warm_engine).run(
            [Request(rid="a", prompt=[5], max_new_tokens=4)]
        )["a"]
        assert got == greedy_oracle(tiny_params, [5], 4)

    def test_both_buckets_agree_with_oracle(
        self, warm_engine, tiny_params, greedy_oracle
    ):
        # Lengths straddling the bucket boundary: 7 pads to bucket 8,
        # 9 to bucket 16 -- padding must not leak into the logits.
        rng = np.random.default_rng(1)
        for n in (7, 9, 16):
            prompt = rng.integers(0, TINY.vocab_size, size=n).tolist()
            got = ContinuousBatcher(warm_engine).run(
                [Request(rid="a", prompt=prompt, max_new_tokens=2)]
            )["a"]
            assert got == greedy_oracle(tiny_params, prompt, 2), n


class TestContinuousBatching:
    def test_mixed_stream_matches_solo_oracles(
        self, warm_engine, tiny_params, greedy_oracle
    ):
        """Staggered lengths force mid-stream evictions and
        re-admissions; every request must still generate exactly its
        solo greedy continuation (slots are isolated)."""
        rng = np.random.default_rng(2)
        shapes = [(5, 3), (11, 6), (7, 1), (13, 4), (4, 5), (9, 2)]
        reqs = [
            Request(
                rid=f"r{i}",
                prompt=rng.integers(
                    0, TINY.vocab_size, size=plen
                ).tolist(),
                max_new_tokens=mnew,
            )
            for i, (plen, mnew) in enumerate(shapes)
        ]
        batcher = ContinuousBatcher(warm_engine)
        got = batcher.run(reqs)
        for r in reqs:
            assert got[r.rid] == greedy_oracle(
                tiny_params, r.prompt, r.max_new_tokens
            ), r.rid
        # 6 requests through 4 slots: reuse actually happened.
        assert batcher.stats["admitted"] == len(shapes)
        assert batcher.stats["admitted"] > warm_engine.serve_cfg.slots
        assert batcher.stats["evicted"] == len(shapes)

    def test_position_counters_track_prompt_plus_generated(
        self, warm_engine
    ):
        batcher = ContinuousBatcher(warm_engine)
        batcher.submit(Request(rid="a", prompt=[1, 2, 3],
                               max_new_tokens=5))
        batcher.step()  # admit (prefill -> 1 token) + 1 decode
        assert batcher.slot_positions()[0] == 4  # 3 prompt + 1 decoded
        batcher.step()
        assert batcher.slot_positions()[0] == 5
        batcher.run()  # drain
        assert len(batcher.results["a"]) == 5

    def test_eos_stops_early(
        self, warm_engine, tiny_params, greedy_oracle
    ):
        prompt = [3, 1, 4, 1, 5]
        free_run = greedy_oracle(tiny_params, prompt, 6)
        eos = free_run[2]
        got = ContinuousBatcher(warm_engine).run([
            Request(rid="a", prompt=prompt, max_new_tokens=6,
                    eos_id=eos)
        ])["a"]
        # Cut at (and including) the FIRST occurrence of the EOS id.
        assert got == free_run[:free_run.index(eos) + 1]

    def test_capacity_and_validation_errors(self, warm_engine):
        batcher = ContinuousBatcher(warm_engine)
        with pytest.raises(ValueError, match="cache capacity"):
            batcher.submit(
                Request(rid="big", prompt=[1] * 16, max_new_tokens=40)
            )
        # Oversized prompt fails at SUBMIT, not mid-drain where it
        # would abort every other in-flight request.
        with pytest.raises(ValueError, match="largest"):
            batcher.submit(
                Request(rid="wide", prompt=[1] * 17, max_new_tokens=2)
            )
        with pytest.raises(ValueError, match="empty prompt"):
            Request(rid="e", prompt=[], max_new_tokens=1)
        with pytest.raises(ValueError, match="exceeds the largest"):
            warm_engine.prefill(0, list(range(17)))
        batcher.submit(Request(rid="a", prompt=[1], max_new_tokens=1))
        with pytest.raises(ValueError, match="duplicate"):
            batcher.submit(
                Request(rid="a", prompt=[1], max_new_tokens=1)
            )


class TestCompileDiscipline:
    def test_warm_engine_serves_mix_with_zero_recompiles(
        self, warm_engine
    ):
        """The acceptance guard: a replayed request mix hitting every
        bucket and forcing slot churn adds NO executables after
        warmup."""
        warmed = warm_engine.compile_count
        assert warmed == 3  # two prefill buckets + one decode program
        rng = np.random.default_rng(3)
        reqs = [
            Request(
                rid=f"m{i}",
                prompt=rng.integers(
                    0, TINY.vocab_size, size=4 + (5 * i) % 13
                ).tolist(),
                max_new_tokens=1 + i % 5,
            )
            for i in range(9)
        ]
        ContinuousBatcher(warm_engine).run(reqs)
        assert warm_engine.compile_count == warmed

    def test_cache_layout_on_mesh(self, warm_engine, serve_mesh):
        # Slots shard over data, KV heads over model; the resident
        # cache must actually carry that sharding.
        spec = kv_cache_pspec(serve_mesh, 4, TINY.kv_heads)
        assert spec == jax.sharding.PartitionSpec(
            None, "data", None, "model", None
        )
        assert warm_engine.ks.sharding.spec == spec
        assert warm_engine.vs.sharding.spec == spec
        assert warm_engine.cache_bytes == (
            2 * TINY.n_layers * 4 * 48 * TINY.kv_heads
            * TINY.head_dim * 4  # fp32 cache follows the compute dtype
        )

    def test_bucket_selection(self):
        scfg = ServeConfig(
            slots=2, max_seq_len=64, prefill_buckets=(16, 8, 32)
        )
        assert scfg.prefill_buckets == (8, 16, 32)  # sorted
        assert scfg.bucket_for(1) == 8
        assert scfg.bucket_for(9) == 16
        assert scfg.bucket_for(32) == 32
        with pytest.raises(ValueError, match="largest"):
            scfg.bucket_for(33)
        with pytest.raises(ValueError, match="exceed the cache"):
            ServeConfig(slots=2, max_seq_len=16, prefill_buckets=(32,))


class TestPagedMode:
    """The token-exact oracle holds in PAGED mode (serve/paging.py):
    the same greedy_oracle that pins the slab engine pins the
    block-table cache, prefix-hit or miss, with chunked prefill on.
    The full paged suite (allocator properties, budget discipline,
    disagg hop) lives in tests/test_paging.py; this section keeps the
    oracle contract in the file that owns it."""

    @pytest.fixture(scope="class")
    def warm_paged(self, tiny_params, serve_mesh):
        from tpu_hpc.serve import PagedConfig, PagedEngine

        engine = PagedEngine(
            tiny_params, TINY,
            ServeConfig(slots=4, max_seq_len=48,
                        prefill_buckets=(8, 16)),
            serve_mesh,
            PagedConfig(block_size=4, num_blocks=48, prefill_chunk=8),
        )
        engine.warmup()
        return engine

    def test_paged_decode_token_exact_hit_and_miss(
        self, warm_paged, tiny_params, greedy_oracle
    ):
        rng = np.random.default_rng(20)
        prompt = rng.integers(0, TINY.vocab_size, size=13).tolist()
        want = greedy_oracle(tiny_params, prompt, 4)
        cold = ContinuousBatcher(warm_paged).run(
            [Request(rid="cold", prompt=prompt, max_new_tokens=4)]
        )["cold"]
        warm = ContinuousBatcher(warm_paged).run(
            [Request(rid="warm", prompt=prompt, max_new_tokens=4)]
        )["warm"]
        assert cold == want
        assert warm == want  # through a prefix hit
        assert warm_paged.paged_stats["prefix_hits"] >= 1

    def test_paged_zero_recompiles_with_chunking(self, warm_paged):
        warmed = warm_paged.compile_count
        rng = np.random.default_rng(21)
        reqs = [
            Request(
                rid=f"pg{i}",
                prompt=rng.integers(
                    0, TINY.vocab_size, size=2 + (5 * i) % 14
                ).tolist(),
                max_new_tokens=1 + i % 4,
            )
            for i in range(7)
        ]
        ContinuousBatcher(warm_paged).run(reqs)
        assert warm_paged.compile_count == warmed


class TestSpecOracle:
    """The token-exact oracle holds in SPECULATIVE mode
    (serve/spec.py): the same greedy_oracle that pins the slab and
    paged engines pins draft-model and prompt-lookup speculation --
    prefix-hit and miss, with chunked prefill on, accept and reject
    paths both exercised. Speculation must provably change latency
    only, never the greedy token stream. The full suite (seeded
    sampling, compile pins, page accounting) lives in
    tests/test_spec.py; this section keeps the oracle contract in
    the file that owns it."""

    def _spec_engine(self, tiny_params, serve_mesh, mode, draft=None):
        from tpu_hpc.serve import (
            PagedConfig,
            PagedEngine,
            SpecConfig,
            attach_spec,
        )

        engine = PagedEngine(
            tiny_params, TINY,
            ServeConfig(slots=4, max_seq_len=48,
                        prefill_buckets=(8, 16)),
            serve_mesh,
            PagedConfig(block_size=4, num_blocks=48, prefill_chunk=8),
        )
        attach_spec(
            engine, SpecConfig(mode=mode, k=3),
            draft_params=draft[0] if draft else None,
            draft_cfg=draft[1] if draft else None,
        )
        engine.warmup()
        return engine

    @pytest.mark.parametrize("mode", ("ngram", "draft"))
    def test_spec_greedy_token_exact_hit_and_miss(
        self, tiny_params, serve_mesh, greedy_oracle, mode
    ):
        import dataclasses

        draft = None
        if mode == "draft":
            dcfg = dataclasses.replace(TINY, n_layers=1)
            draft = (
                llama2.init_llama(jax.random.key(9), dcfg), dcfg
            )
        engine = self._spec_engine(
            tiny_params, serve_mesh, mode, draft=draft
        )
        rng = np.random.default_rng(30)
        prompt = rng.integers(0, TINY.vocab_size, size=13).tolist()
        want = greedy_oracle(tiny_params, prompt, 8)
        cold = ContinuousBatcher(engine).run(
            [Request(rid="cold", prompt=prompt, max_new_tokens=8)]
        )["cold"]
        warm = ContinuousBatcher(engine).run(
            [Request(rid="warm", prompt=prompt, max_new_tokens=8)]
        )["warm"]
        assert cold == want
        assert warm == want  # through a prefix hit
        assert engine.paged_stats["prefix_hits"] >= 1
        assert engine.spec.stats["verify_steps"] > 0

    def test_disagg_cannot_consume_spec(self, tiny_params, serve_mesh):
        """The verify program is a single-mesh paged program; a
        disagg engine wearing a spec label would silently decode
        greedy -- attach must refuse (the CLI guards mirror this)."""
        from tpu_hpc.serve import SpecConfig, attach_spec
        from tpu_hpc.serve.disagg import DisaggEngine

        with pytest.raises(ValueError, match="paged"):
            attach_spec(
                object.__new__(DisaggEngine), SpecConfig(mode="ngram")
            )


class TestServingWeights:
    def test_trainer_checkpoint_restores_into_serving_layout(
        self, tiny_params, serve_mesh, tmp_path
    ):
        """Save a TrainState in the TRAINING (FSDPxTP) layout, restore
        via load_serving_params: values identical, layout = the
        serving plan (TP over model, replicated over data)."""
        from tpu_hpc.ckpt import CheckpointManager
        from tpu_hpc.parallel import hybrid, tp
        from tpu_hpc.parallel.plans import shardings_for
        from tpu_hpc.serve.weights import (
            load_serving_params,
            serving_pspecs,
        )
        from tpu_hpc.train.trainer import TrainState, make_adamw

        specs = hybrid.hybrid_pspecs(
            tiny_params, tp.llama_rules(), data_size=4, min_size=100
        )
        placed = jax.jit(
            lambda t: t,
            out_shardings=shardings_for(serve_mesh, specs),
        )(tiny_params)
        opt = make_adamw(3e-4, 0.1)
        state = TrainState(
            step=jnp.zeros((), jnp.int32),
            params=placed,
            opt_state=opt.init(placed),
            model_state={},
        )
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        mgr.save_now(state, step=3)
        mgr.close()

        served = load_serving_params(str(tmp_path), TINY, serve_mesh)
        for a, b in zip(
            jax.tree.leaves(tiny_params), jax.tree.leaves(served)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        want = serving_pspecs(tiny_params, serve_mesh)
        for leaf, spec in zip(
            jax.tree.leaves(served),
            jax.tree.leaves(
                want,
                is_leaf=lambda x: isinstance(
                    x, jax.sharding.PartitionSpec
                ),
            ),
        ):
            assert leaf.sharding.spec == spec

    def test_missing_checkpoint_raises(self, serve_mesh, tmp_path):
        from tpu_hpc.serve.weights import load_serving_params

        with pytest.raises(FileNotFoundError):
            load_serving_params(
                str(tmp_path / "nothing"), TINY, serve_mesh
            )

    def test_opt_state_template_restores_sharded(self, serve_mesh):
        """The discarded AdamW moments still transit HBM during the
        restore; at real model sizes a replicated template would OOM
        every chip, so large moment leaves must carry a distributed
        sharding in the restore template."""
        from tpu_hpc.serve.weights import (
            abstract_train_state,
            serving_pspecs,
        )

        cfg = llama2.PRESETS["7b"]
        abstract = jax.eval_shape(
            lambda: llama2.init_llama(jax.random.key(0), cfg)
        )
        tmpl = abstract_train_state(
            cfg, serve_mesh, serving_pspecs(abstract, serve_mesh)
        )
        big = [
            leaf for leaf in jax.tree.leaves(tmpl.opt_state)
            if int(np.prod(leaf.shape)) >= 100_000
        ]
        assert big, "7B AdamW state has large moment leaves"
        for leaf in big:
            assert any(
                e is not None for e in leaf.sharding.spec
            ), f"moment leaf {leaf.shape} left replicated"


class TestReplayServerCLI:
    def test_main_runs_replay_and_prints_summary(self, capsys):
        """The `python -m tpu_hpc.serve` wiring end-to-end on the sim
        mesh (the exact configuration launch/README.md points at):
        flag parsing, mesh bring-up, warmup, drain, summary JSON."""
        from tpu_hpc.serve import server

        rc = server.main([
            "--requests", "3", "--max-new", "2", "--slots", "2",
            "--buckets", "8", "--prompt-lens", "3,6", "--vocab", "64",
        ])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out.splitlines()[-1])
        assert summary["requests"] == 3
        assert summary["tokens"] == 6
        assert summary["recompiles"] == 0
        assert summary["batcher"]["admitted"] == 3
        assert summary["compiled_programs"] == 2  # 1 bucket + decode

    def test_main_rejects_prompt_longer_than_buckets(self):
        from tpu_hpc.serve import server

        with pytest.raises(SystemExit):
            server.main([
                "--buckets", "8", "--prompt-lens", "9",
            ])


class TestServeMetrics:
    def test_meter_records_and_summary(self, tmp_path):
        import time

        path = str(tmp_path / "serve.jsonl")
        meter = ServeMeter(metrics_path=path)
        for rid in ("a", "b"):
            meter.submitted(rid)
            time.sleep(0.002)  # queue wait: must show up in TTFT
            meter.admitted(rid)
            meter.token(rid, first=True)
            time.sleep(0.002)
            meter.token(rid)
            meter.finished(rid)
        s = meter.summary(n_devices=8)
        assert s["requests"] == 2 and s["tokens"] == 4
        assert s["tokens_per_s"] > 0
        assert s["tokens_per_s_per_chip"] == pytest.approx(
            s["tokens_per_s"] / 8
        )
        assert s["ttft_ms_p50"] >= 0 and s["itl_ms_p50"] > 0
        meter.write_summary(s)
        records = [
            json.loads(l)
            for l in open(path).read().splitlines()
        ]
        events = [r["event"] for r in records]
        assert events == ["request", "request", "serve_summary"]
        # PR 4: serving records ride the unified telemetry schema --
        # the same validator covers train, serve, and bench sinks.
        from tpu_hpc.obs import validate_file

        assert validate_file(path) == 3
        for r in records[:2]:
            # TTFT from SUBMISSION: the queue wait is inside it.
            assert r["ttft_ms"] >= r["queue_ms"] > 0

    def test_serving_mfu_counts_prefill_and_decode_tokens(self):
        s = ServeMeter()
        s.admitted("a", prefill_tokens=10)
        s.token("a", first=True)
        summary = s.summary(
            n_devices=1, n_params=10**9,
            peak_flops_per_device=100e12,
        )
        from tpu_hpc.train.metrics import mfu

        # throughput = GENERATED tokens; MFU = ALL forwarded tokens
        # (padded prefill + generated) on the 2N inference estimate.
        assert summary["tokens"] == 1
        assert summary["prefill_tokens"] == 10
        forwarded_per_s = (1 + 10) / summary["wall_s"]
        assert summary["serve_mfu"] == pytest.approx(
            mfu(forwarded_per_s, 10**9, 1, 100e12, mode="inference")
        )
        assert summary["serve_mfu"] > mfu(
            summary["tokens_per_s"], 10**9, 1, 100e12,
            mode="inference",
        )


class TestMfuModes:
    def test_inference_mode_is_one_third_of_train(self):
        # Same throughput, 2N vs 6N: inference MFU must read exactly
        # 3x lower FLOPs -> 1/3 of the train number.
        from tpu_hpc.train.metrics import mfu

        t = mfu(1e5, 7e9, 8, 197e12, mode="train")
        i = mfu(1e5, 7e9, 8, 197e12, mode="inference")
        assert t == pytest.approx(3 * i)

    def test_default_stays_train_and_bad_mode_rejected(self):
        from tpu_hpc.train.metrics import mfu

        assert mfu(1e5, 7e9, 8, 197e12) == mfu(
            1e5, 7e9, 8, 197e12, mode="train"
        )
        with pytest.raises(ValueError, match="unknown mfu mode"):
            mfu(1e5, 7e9, 8, 197e12, mode="decode")

    def test_attn_flops_add_on_in_both_modes(self):
        from tpu_hpc.train.metrics import mfu

        base = mfu(1e5, 7e9, 8, 197e12, mode="inference")
        more = mfu(
            1e5, 7e9, 8, 197e12, attn_flops_per_token=2e9,
            mode="inference",
        )
        assert more > base
