"""Communication-signature regression tests: each strategy's compiled
HLO must contain exactly the collective *kinds* its design promises.

The reference diagnoses comm behavior by reading NCCL_DEBUG=INFO logs
on a live cluster (docs/guide/nccl_tuning.md:153-173); under XLA the
compiled module is inspectable offline, so the comm pattern of every
recipe is pinned as a test: a layout change that silently turns TP's
one-all-reduce-per-block into resharding all-to-alls (or FSDP's
gathers into full rematerializations) fails here, not in a profile
three rounds later.
"""
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_hpc.models import llama2
from tpu_hpc.parallel import fsdp, hybrid, ring_attention as ra, sp_ulysses, tp
from tpu_hpc.parallel.plans import shardings_for
from tpu_hpc.runtime import MeshSpec, build_mesh

MODEL = llama2.LlamaConfig(
    dim=64, n_layers=2, n_heads=4, vocab_size=128, multiple_of=32,
    max_seq_len=32,
)

# Single-sourced collective-kind list (also drives the fit report).
from tpu_hpc.checks.fit import _COLLECTIVES as _OPS  # noqa: E402


def _signature(fn, *args) -> dict:
    hlo = jax.jit(fn).lower(*args).compile().as_text()
    return {op: hlo.count(f"{op}(") + hlo.count(f"{op}-start(")
            for op in _OPS}


def _loss(params, tokens, cfg=MODEL, constrain=None, attn_fn=None):
    logits = llama2.apply_llama(
        params, tokens,  cfg,
        constrain if constrain is not None else (lambda x: x),
        attn_fn,
    )
    return jnp.mean(logits.astype(jnp.float32) ** 2)


@pytest.fixture(scope="module")
def params():
    return llama2.init_llama(jax.random.key(0), MODEL)


@pytest.fixture(scope="module")
def tokens():
    return jax.random.randint(
        jax.random.key(1), (4, 32), 0, MODEL.vocab_size, jnp.int32
    )


def test_tp_emits_reductions_not_resharding(params, tokens, devices):
    """Megatron TP fwd+bwd: block reductions (all-reduce, or RS/AG
    under sequence-parallel layouts) -- and no all-to-all, which would
    mean the plan degenerated into generic resharding."""
    mesh = build_mesh(MeshSpec(axes={"model": 4}), devices=devices[:4])
    specs = tp.param_pspecs(params, tp.llama_rules())
    p_sharded = jax.device_put(params, shardings_for(mesh, specs))
    sig = _signature(
        jax.grad(_loss), p_sharded,
        jax.device_put(tokens, NamedSharding(mesh, P())),
    )
    assert sig["all-reduce"] + sig["reduce-scatter"] > 0, sig
    assert sig["all-to-all"] == 0, sig


def test_fsdp_emits_param_gathers(params, tokens, devices):
    """ZeRO-3: parameter all-gathers before use; gradients reduced
    (all-reduce or reduce-scatter, backend-dependent legalization)."""
    mesh = build_mesh(MeshSpec(axes={"data": 4}), devices=devices[:4])
    specs = fsdp.param_pspecs(params, axis_size=4, min_size=1000)
    p_sharded = jax.device_put(params, shardings_for(mesh, specs))
    sig = _signature(
        jax.grad(_loss), p_sharded,
        jax.device_put(tokens, NamedSharding(mesh, P("data"))),
    )
    assert sig["all-gather"] > 0, sig
    assert sig["all-reduce"] + sig["reduce-scatter"] > 0, sig


def test_ulysses_emits_all_to_all(params, tokens, devices):
    """Ulysses: the head-scatter/seq-gather exchange IS an all-to-all
    -- its absence means the hook fell back to local attention."""
    mesh = build_mesh(MeshSpec(axes={"data": 1, "context": 4}),
                      devices=devices[:4])
    attn = sp_ulysses.make_ulysses_attn_fn(mesh, "data", "context")
    constrain = ra.cp_constrain(mesh, "data", "context")
    sig = _signature(
        lambda p, t: _loss(p, t, constrain=constrain, attn_fn=attn),
        params,
        jax.device_put(tokens, NamedSharding(mesh, P(None, "context"))),
    )
    assert sig["all-to-all"] > 0, sig


def test_ring_emits_collective_permute(params, tokens, devices):
    """Ring attention: KV rotation is neighbor ppermute hops."""
    mesh = build_mesh(MeshSpec(axes={"data": 1, "context": 4}),
                      devices=devices[:4])
    attn = ra.make_ring_attn_fn(mesh, "data", "context")
    constrain = ra.cp_constrain(mesh, "data", "context")
    sig = _signature(
        lambda p, t: _loss(p, t, constrain=constrain, attn_fn=attn),
        params,
        jax.device_put(tokens, NamedSharding(mesh, P(None, "context"))),
    )
    assert sig["collective-permute"] > 0, sig


def test_hybrid_emits_both_families(params, tokens, devices):
    """FSDPxTP(+SP): param gathers (FSDP + SP boundary) AND block
    reductions in one program -- the two comm domains of the
    reference's hybrid example in one compiled module."""
    mesh = build_mesh(MeshSpec(axes={"data": 2, "model": 2}),
                      devices=devices[:4])
    specs = hybrid.hybrid_pspecs(
        params, tp.llama_rules(), data_size=2, min_size=1000
    )
    constrain = tp.sp_constrain(mesh, dp_axis="data", sp_axis="model")
    p_sharded = jax.device_put(params, shardings_for(mesh, specs))
    sig = _signature(
        jax.grad(lambda p, t: _loss(p, t, constrain=constrain)),
        p_sharded,
        jax.device_put(tokens, NamedSharding(mesh, P("data"))),
    )
    assert sig["all-gather"] > 0, sig
    assert sig["all-reduce"] + sig["reduce-scatter"] > 0, sig


@pytest.mark.parametrize("schedule", ["1f1b", "interleaved-1f1b"])
def test_pp_custom_backwards_emit_ring_permutes_only(devices, schedule):
    """The custom_vjp pipeline backwards: activations and cotangents
    move by collective-permute ring hops -- no all-to-alls (a
    resharding fallback would mean the stacked-stage layout broke) and
    no all-gathers (stage params must stay device-local)."""
    from tpu_hpc.models import pipeline_transformer as ptx
    from tpu_hpc.parallel import pp

    mesh = build_mesh(MeshSpec(axes={"pipe": 4}), devices=jax.devices()[:4])
    v = 2 if schedule == "interleaved-1f1b" else 1
    cfg = ptx.PipeConfig(
        vocab_size=64, dim=32, n_heads=2, n_stages=4 * v,
        layers_per_stage=1, max_seq_len=16,
    )
    p = ptx.init_pipeline_transformer(jax.random.key(0), cfg)
    pipe = pp.pipelined(
        ptx.make_stage_fn(cfg), mesh, axis="pipe",
        schedule=schedule, n_chunks=v,
    )

    def loss(params, tokens, targets):
        from tpu_hpc.models import losses

        xs = ptx.embed(params, pp.microbatch(tokens, 4), cfg)
        stacked = (
            pp.interleave_stacked(params["stages"], 4)
            if v == 2 else params["stages"]
        )
        logits = ptx.head(params, pipe(stacked, xs), cfg)
        return losses.cross_entropy(logits, pp.microbatch(targets, 4))

    tokens = jax.random.randint(
        jax.random.key(1), (8, 16), 0, 64, jnp.int32
    )
    sig = _signature(
        jax.grad(loss), p, tokens,
        jax.random.randint(jax.random.key(2), (8, 16), 0, 64, jnp.int32),
    )
    assert sig["collective-permute"] > 0, sig
    assert sig["all-to-all"] == 0, sig
    assert sig["all-gather"] == 0, sig
