"""logging_.get_logger: per-call level honored after first configure.

Regression (PR 4 satellite): ``basicConfig`` runs once, and the old
implementation dropped the ``level`` argument of every call after it
-- ``get_logger(name, DEBUG)`` in a worker was a silent no-op once any
module had logged first.
"""
import logging

from tpu_hpc.logging_ import get_logger


def test_level_honored_after_first_configure():
    # First call configures the root handler (whatever level).
    get_logger("tpu_hpc.lvltest")
    # A LATER explicit level must take effect on that logger...
    lg = get_logger("tpu_hpc.lvltest", logging.DEBUG)
    assert lg.level == logging.DEBUG
    assert lg.isEnabledFor(logging.DEBUG)
    # ...and be revisable.
    assert get_logger(
        "tpu_hpc.lvltest", logging.WARNING
    ).level == logging.WARNING


def test_default_call_does_not_clobber_explicit_level():
    get_logger("tpu_hpc.lvltest2", logging.DEBUG)
    lg = get_logger("tpu_hpc.lvltest2")  # no level: leave it alone
    assert lg.level == logging.DEBUG


def test_same_logger_object_returned():
    assert get_logger("tpu_hpc.same") is get_logger("tpu_hpc.same")
