"""Native C++ data pipeline: build, determinism, prefetch ordering,
statistics, and Trainer integration via the host-fed path."""
import os

import numpy as np
import pytest

from tpu_hpc.native import NativeERA5Stream, native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="g++ unavailable"
)


def make_stream(**kw):
    kw.setdefault("batch_size", 4)
    kw.setdefault("lat", 8)
    kw.setdefault("lon", 16)
    kw.setdefault("channels", 3)
    return NativeERA5Stream(**kw)


def test_deterministic_across_instances():
    a = make_stream(seed=7)
    b = make_stream(seed=7)
    xa, ya = a.batch_at(0, 4)
    xb, yb = b.batch_at(0, 4)
    np.testing.assert_array_equal(xa, xb)
    np.testing.assert_array_equal(ya, yb)
    a.close(); b.close()


def test_random_access_equals_sequential():
    """Prefetch-ring batches must be byte-identical to synchronous
    random-access generation (the determinism contract)."""
    seq = make_stream(seed=3)
    ra = make_stream(seed=3)
    got = [seq.next() for _ in range(5)]
    # Out-of-order access on the second stream bypasses its ring.
    for step in (4, 2, 0, 3, 1):
        x, y = ra.batch_at(step, 4)
        np.testing.assert_array_equal(x, got[step][0])
        np.testing.assert_array_equal(y, got[step][1])
    seq.close(); ra.close()


def test_distinct_steps_and_seeds():
    s = make_stream(seed=0)
    x0, _ = s.batch_at(0, 4)
    x1, _ = s.batch_at(1, 4)
    assert np.abs(x0 - x1).max() > 0.1
    s.close()
    s2 = make_stream(seed=1)
    x0b, _ = s2.batch_at(0, 4)
    assert np.abs(x0 - x0b).max() > 0.1
    s2.close()


def test_resume_resyncs_ring():
    """Checkpoint-resume pattern: first read at step N (not 0) must
    reseek the prefetch ring, and sequential reads from N must keep
    riding it with the right bytes (ADVICE r1: the ring previously
    kept filling 0..depth-1 forever after a resume)."""
    oracle = make_stream(seed=5)
    want = [oracle.next() for _ in range(14)]
    s = make_stream(seed=5)
    for step in range(10, 14):  # resume at 10, then sequential
        x, y = s.batch_at(step, 4)
        np.testing.assert_array_equal(x, want[step][0])
        np.testing.assert_array_equal(y, want[step][1])
    assert s._next_seq == 14
    # Seek backwards too (e.g. re-run an epoch).
    x, _ = s.batch_at(2, 4)
    np.testing.assert_array_equal(x, want[2][0])
    oracle.close(); s.close()


def test_gaussian_statistics():
    s = make_stream(batch_size=32, lat=16, lon=32, channels=4)
    x, y = s.batch_at(0, 32)
    assert abs(float(x.mean())) < 0.02
    assert abs(float(x.std()) - 1.0) < 0.02
    # y = 0.5x + 0.1n -> residual std 0.1.
    resid = y - 0.5 * x
    assert abs(float(resid.std()) - 0.1) < 0.01
    s.close()


def test_trainer_host_fed_path(mesh8):
    """The stream satisfies the Trainer's dataset contract (no
    traced_batch attribute -> per-step host-fed loop)."""
    import jax.numpy as jnp

    from tpu_hpc.config import TrainingConfig
    from tpu_hpc.train import Trainer

    s = make_stream(batch_size=8, lat=8, lon=16, channels=3)
    params = {"w": jnp.zeros((3, 3))}

    def forward(p, ms, batch, rng):
        x, y = batch
        pred = jnp.einsum("bhwc,cd->bhwd", x, p["w"])
        return jnp.mean((pred - y) ** 2), ms, {}

    cfg = TrainingConfig(
        epochs=1, steps_per_epoch=3, global_batch_size=8
    )
    result = Trainer(cfg, mesh8, forward, params).fit(s)
    assert np.isfinite(result["final_loss"])
    s.close()


class TestFileDataset:
    """mmap'd binary dataset + Feistel epoch shuffle: the real-data
    path (reference: downloaded CIFAR + DataLoader workers,
    resnet_fsdp_training.py:45-87)."""

    @pytest.fixture()
    def dataset_file(self, tmp_path):
        from tpu_hpc.native import write_dataset

        rng = np.random.default_rng(0)
        x = rng.standard_normal((40, 4, 6)).astype(np.float32)
        y = (rng.random(40) > 0.5).astype(np.float32)
        path = str(tmp_path / "toy.tpuhpc")
        write_dataset(path, x, y)
        return path, x, y

    def make(self, path, batch=4, **kw):
        from tpu_hpc.native import NativeFileDataset

        return NativeFileDataset(
            path, batch_size=batch, x_shape=(4, 6), y_shape=(), **kw
        )

    def test_round_trip_exact_bytes(self, dataset_file):
        path, x, y = dataset_file
        ds = self.make(path)
        assert ds.n_samples == 40
        seen = {}
        for step in range(10):  # one full epoch (40 / batch 4)
            bx, by = ds.batch_at(step, 4)
            for i in range(4):
                # Match each served sample back to a source row.
                hits = np.where(
                    np.all(np.isclose(x, bx[i]), axis=(1, 2))
                )[0]
                assert len(hits) == 1
                idx = int(hits[0])
                assert idx not in seen, "epoch must not repeat samples"
                seen[idx] = True
                np.testing.assert_array_equal(by[i], y[idx])
        assert len(seen) == 40, "epoch must visit every sample"
        ds.close()

    def test_epochs_reshuffle_deterministically(self, dataset_file):
        path, x, _ = dataset_file
        a = self.make(path, seed=3)
        b = self.make(path, seed=3)
        e0 = np.concatenate([a.batch_at(s, 4)[0] for s in range(10)])
        e1 = np.concatenate([a.batch_at(s, 4)[0] for s in range(10, 20)])
        assert not np.array_equal(e0, e1), "epoch 1 must reshuffle"
        e0b = np.concatenate([b.batch_at(s, 4)[0] for s in range(10)])
        np.testing.assert_array_equal(e0, e0b)  # same seed, same order
        a.close(); b.close()

    def test_resume_and_random_access(self, dataset_file):
        path, _, _ = dataset_file
        ref = self.make(path, seed=7)
        want = [ref.next() for _ in range(8)]
        ds = self.make(path, seed=7)
        for step in (5, 6, 7):  # resume mid-epoch, then sequential
            bx, by = ds.batch_at(step, 4)
            np.testing.assert_array_equal(bx, want[step][0])
            np.testing.assert_array_equal(by, want[step][1])
        bx, _ = ds.batch_at(0, 4)  # backward jump (eval re-read)
        np.testing.assert_array_equal(bx, want[0][0])
        ref.close(); ds.close()

    def test_bad_file_rejected(self, tmp_path):
        bad = tmp_path / "bad.bin"
        bad.write_bytes(b"not a dataset")
        with pytest.raises(ValueError, match="not a tpu_hpc dataset"):
            self.make(str(bad))

    def test_trainer_integration(self, mesh8, dataset_file):
        import jax.numpy as jnp

        from tpu_hpc.config import TrainingConfig
        from tpu_hpc.train import Trainer

        path, _, _ = dataset_file
        ds = self.make(path, batch=8)
        params = {"w": jnp.zeros((24,))}

        def forward(p, ms, batch, rng):
            x, y = batch
            logit = x.reshape(x.shape[0], -1) @ p["w"]
            loss = jnp.mean(
                jnp.maximum(logit, 0) - logit * y
                + jnp.log1p(jnp.exp(-jnp.abs(logit)))
            )
            return loss, ms, {}

        cfg = TrainingConfig(
            epochs=1, steps_per_epoch=5, global_batch_size=8,
            learning_rate=0.5,
        )
        result = Trainer(cfg, mesh8, forward, params).fit(ds)
        assert np.isfinite(result["final_loss"])
        ds.close()


class TestTokenDataset:
    """mmap'd token corpus -> next-token (inputs, targets) windows:
    the LLM-pretraining data path the reference never built (its Llama
    examples train on random tokens, 03_pipeline_training.py:220-230)."""

    S = 8  # window seq_len; corpus below yields (257-1)/8 = 32 windows

    @pytest.fixture()
    def corpus_file(self, tmp_path):
        from tpu_hpc.native import write_token_dataset

        tokens = np.arange(257, dtype=np.int64)  # unique ids: every
        # window is a distinct pattern, so served rows map uniquely
        path = str(tmp_path / "toy.tokens")
        write_token_dataset(path, tokens)
        return path, tokens

    def make(self, path, batch=4, **kw):
        from tpu_hpc.native import NativeTokenDataset

        return NativeTokenDataset(
            path, batch_size=batch, seq_len=self.S, **kw
        )

    def test_windows_are_shifted_pairs(self, corpus_file):
        path, tokens = corpus_file
        ds = self.make(path)
        assert ds.n_tokens == 257 and ds.n_windows == 32
        assert ds.max_token_id == 256  # header-carried vocab bound
        starts = set()
        for step in range(8):  # one epoch: 32 windows / batch 4
            bx, by = ds.batch_at(step, 4)
            assert bx.dtype == np.int32 and bx.shape == (4, self.S)
            for i in range(4):
                # Every served row must be a contiguous corpus window
                # with the target shifted one token.
                hits = [
                    w for w in range(32)
                    if np.array_equal(
                        bx[i], tokens[w * self.S:(w + 1) * self.S]
                    )
                    and np.array_equal(
                        by[i],
                        tokens[w * self.S + 1:(w + 1) * self.S + 1],
                    )
                ]
                assert len(hits) == 1
                assert hits[0] not in starts, "epoch must not repeat"
                starts.add(hits[0])
        assert len(starts) == 32, "epoch must visit every window"
        ds.close()

    def test_uint16_vs_uint32_storage(self, tmp_path):
        from tpu_hpc.native import write_token_dataset

        small = np.arange(100, dtype=np.int64)
        big = small.copy(); big[0] = 70000  # forces uint32
        p16 = write_token_dataset(str(tmp_path / "a.tok"), small)
        p32 = write_token_dataset(str(tmp_path / "b.tok"), big)
        assert (
            os.path.getsize(p32) - os.path.getsize(p16) == 2 * 100
        )
        # The >uint16 id lives at corpus position 0 = window 0, so one
        # full epoch of inputs must serve it back intact: the uint32
        # storage path round-trips values uint16 cannot hold.
        ds = self.make(p32, batch=2)
        epoch_steps = ds.n_windows // 2
        served = np.concatenate(
            [ds.batch_at(s, 2)[0].ravel() for s in range(epoch_steps)]
        )
        assert 70000 in served
        ds.close()

    def test_epochs_reshuffle_deterministically(self, corpus_file):
        path, _ = corpus_file
        a = self.make(path, seed=3)
        b = self.make(path, seed=3)
        e0 = np.concatenate([a.batch_at(s, 4)[0] for s in range(8)])
        e1 = np.concatenate([a.batch_at(s, 4)[0] for s in range(8, 16)])
        assert not np.array_equal(e0, e1), "epoch 1 must reshuffle"
        np.testing.assert_array_equal(
            e0, np.concatenate([b.batch_at(s, 4)[0] for s in range(8)])
        )
        a.close(); b.close()

    def test_resume_and_random_access(self, corpus_file):
        path, _ = corpus_file
        ref = self.make(path, seed=7)
        want = [ref.next() for _ in range(6)]
        ds = self.make(path, seed=7)
        for step in (3, 4, 5):  # resume mid-epoch, then sequential
            bx, by = ds.batch_at(step, 4)
            np.testing.assert_array_equal(bx, want[step][0])
            np.testing.assert_array_equal(by, want[step][1])
        bx, _ = ds.batch_at(0, 4)  # backward jump (eval re-read)
        np.testing.assert_array_equal(bx, want[0][0])
        ref.close(); ds.close()

    def test_bad_inputs_rejected(self, tmp_path):
        from tpu_hpc.native import write_token_dataset

        with pytest.raises(ValueError, match="1D"):
            write_token_dataset(
                str(tmp_path / "x"), np.zeros((2, 2), np.int32)
            )
        with pytest.raises(ValueError, match="integers"):
            write_token_dataset(
                str(tmp_path / "x"), np.zeros(10, np.float32)
            )
        bad = tmp_path / "bad.tok"
        bad.write_bytes(b"nope")
        with pytest.raises(ValueError, match="not a tpu_hpc token"):
            self.make(str(bad))

    def test_zero_seq_len_rejected(self, corpus_file):
        from tpu_hpc.native import NativeTokenDataset

        path, _ = corpus_file
        # Must be a Python ValueError, not a SIGFPE in the C++ window
        # division.
        with pytest.raises(ValueError, match="must be positive"):
            NativeTokenDataset(path, batch_size=4, seq_len=0)

    def test_short_corpus_message_names_the_cause(self, corpus_file):
        from tpu_hpc.native import NativeTokenDataset

        path, _ = corpus_file  # 257 tokens
        with pytest.raises(ValueError, match="corpus too short"):
            NativeTokenDataset(path, batch_size=4, seq_len=512)
        with pytest.raises(FileNotFoundError):
            NativeTokenDataset(
                path + ".missing", batch_size=4, seq_len=8
            )

    def test_corrupt_header_rejected_not_segfault(self, tmp_path):
        # A huge n_tokens in a tiny file must be a clean rejection
        # (the overflow-safe capacity check), not an out-of-bounds
        # mmap read.
        bad = tmp_path / "huge.tok"
        hdr = np.asarray(
            [0x3154435048555054, 1 << 62, 2, 0], np.uint64
        )
        with open(bad, "wb") as f:
            hdr.tofile(f)
            np.zeros(8, np.uint16).tofile(f)
        with pytest.raises(ValueError):
            self.make(str(bad))

    def test_trainer_llama_integration(self, mesh8, corpus_file):
        """Train the tiny Llama from a native token file end-to-end:
        the real LLM data path through the real Trainer."""
        import jax

        from tpu_hpc.config import TrainingConfig
        from tpu_hpc.models import llama2
        from tpu_hpc.train import Trainer

        path, _ = corpus_file
        ds = self.make(path, batch=8)
        cfg_m = llama2.LlamaConfig(
            dim=32, n_layers=1, n_heads=2, vocab_size=512,
            multiple_of=16, max_seq_len=self.S,
        )
        params = llama2.init_llama(jax.random.key(0), cfg_m)
        cfg = TrainingConfig(
            epochs=1, steps_per_epoch=3, global_batch_size=8,
            learning_rate=1e-3,
        )
        trainer = Trainer(
            cfg, mesh8, llama2.make_forward(cfg_m, lambda x: x, None),
            params,
        )
        result = trainer.fit(ds)
        assert result["final_loss"] is not None
        assert np.isfinite(result["final_loss"])
        ds.close()
