"""The numeric-health guard + checkpoint integrity, end to end.

Chaos-matrix discipline (the ``chaos`` marker): every fault class the
resilience story claims to survive has a deterministic injection and a
test that drives the REAL Trainer / Orbax / supervisor through it.
Tier-1 keeps one fast representative per NEW fault class here
(nan-skip, nan-rollback-supervised, bitflip, spike, straggler,
quarantine); the full sweep over the matrix rides the ``slow`` marker
with the rest of the round gate.

The two acceptance proofs (ISSUE 9):

* ``TestSupervisedRollback``: ``nan_loss_at_step=N`` with the fault
  armed on EVERY attempt -- the guard detects the poisoned step
  exactly, quarantines, records a skip window, exits EXIT_ROLLBACK;
  the supervisor relaunches from the last-good checkpoint and the run
  can ONLY complete because the stream really skipped the poisoned
  data index. guard_rollback event + combined-goodput report pinned.
* ``TestBitflipChecksum``: ``bitflip_ckpt_at_step=N`` rewrites one
  tensor through orbax (parseable files, wrong content); only the
  sidecar checksums can catch it -- restore falls back to the older
  step, quarantines the corpse, and the events say so.
"""
import json
import os
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hpc import obs
from tpu_hpc.ckpt import CheckpointManager, integrity
from tpu_hpc.config import TrainingConfig
from tpu_hpc.obs.report import build_report
from tpu_hpc.obs.schema import load_records, validate_file
from tpu_hpc.resilience import (
    EXIT_ROLLBACK,
    GuardError,
    GuardPolicy,
    fault_plan_from_env,
)
from tpu_hpc.resilience import guard as guard_lib
from tpu_hpc.resilience.supervisor import run_supervised
from tpu_hpc.runtime import MeshSpec, build_mesh
from tpu_hpc.train import Trainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------
# GuardPolicy classification (host-side, pure)
# ---------------------------------------------------------------------
def _row(loss_finite=1.0, grad_norm=1.0, update_norm=0.1, nonfinite=0):
    return {
        "health_loss_finite": loss_finite,
        "health_grad_norm": grad_norm,
        "health_update_norm": update_norm,
        "health_nonfinite": nonfinite,
    }


class TestGuardPolicy:
    def test_healthy_steps_feed_median(self):
        p = GuardPolicy(mode="skip", spike_factor=3.0)
        for s in range(4):
            assert p.classify(s, _row(grad_norm=1.0 + 0.01 * s)).healthy
        assert p.watermark == pytest.approx(1.015)

    def test_poisoned_on_nonfinite(self):
        p = GuardPolicy(mode="skip")
        assert p.classify(0, _row(loss_finite=0.0)).verdict == "poisoned"
        assert p.classify(1, _row(nonfinite=2)).verdict == "poisoned"
        assert (
            p.classify(2, _row(grad_norm=float("nan"))).verdict
            == "poisoned"
        )
        # Anomalous steps never enter the median window.
        assert p.watermark is None

    def test_spike_needs_warm_median(self):
        p = GuardPolicy(mode="skip", spike_factor=3.0, min_samples=3)
        # Cold: a huge first norm is NOT a spike (nothing to compare).
        assert p.classify(0, _row(grad_norm=100.0)).healthy
        for s in range(1, 4):
            p.classify(s, _row(grad_norm=1.0))
        v = p.classify(4, _row(grad_norm=50.0))
        assert v.verdict == "spike"
        assert v.ratio > 3.0
        # The spike did not poison the median it was judged against.
        before = p.watermark
        p.classify(5, _row(grad_norm=1.0))
        assert p.watermark == pytest.approx(before, rel=0.5)

    def test_wants_rollback_matrix(self):
        skip = GuardPolicy(mode="skip")
        roll = GuardPolicy(mode="rollback", spike_action="rollback")
        event = GuardPolicy(mode="rollback", spike_action="event")
        poisoned = skip.classify(0, _row(loss_finite=0.0))
        assert not skip.wants_rollback(poisoned)
        assert roll.wants_rollback(poisoned)
        for s in range(1, 5):
            for p in (roll, event):
                p.classify(s, _row())
        spike = roll.classify(5, _row(grad_norm=1e3))
        assert roll.wants_rollback(spike)
        spike2 = event.classify(5, _row(grad_norm=1e3))
        assert not event.wants_rollback(spike2)

    def test_config_validation(self):
        with pytest.raises(ValueError, match="guard mode"):
            GuardPolicy(mode="offf")
        with pytest.raises(ValueError, match="guard_spike_action"):
            GuardPolicy(mode="skip", spike_action="explode")
        with pytest.raises(ValueError, match="guard_window"):
            GuardPolicy(mode="skip", window=1)
        cfg = TrainingConfig(guard_mode="off")
        assert GuardPolicy.from_config(cfg) is None
        cfg = TrainingConfig(guard_mode="skip", guard_spike_factor=5.0)
        p = GuardPolicy.from_config(cfg)
        assert p.mode == "skip" and p.spike_factor == 5.0
        with pytest.raises(ValueError, match="guard mode"):
            GuardPolicy.from_config(TrainingConfig(guard_mode="banana"))


class TestSkipWindows:
    def test_offset_and_boundary(self):
        windows = [
            {"from_step": 3, "data_from": 3, "data_to": 5},
            {"from_step": 10, "data_from": 13, "data_to": 13},
        ]
        assert guard_lib.offset_at(windows, 0) == 0
        assert guard_lib.offset_at(windows, 2) == 0
        assert guard_lib.offset_at(windows, 3) == 3
        assert guard_lib.offset_at(windows, 9) == 3
        assert guard_lib.offset_at(windows, 10) == 4
        assert guard_lib.next_boundary(windows, 0) == 3
        assert guard_lib.next_boundary(windows, 3) == 10
        assert guard_lib.next_boundary(windows, 10) is None

    def test_state_roundtrip(self, tmp_path):
        d = str(tmp_path)
        assert guard_lib.load_state(d)["skip_windows"] == []
        guard_lib.record_rollback(
            d, {"from_step": 4, "data_from": 4, "data_to": 5}
        )
        st = guard_lib.record_rollback(
            d, {"from_step": 2, "data_from": 2, "data_to": 2}
        )
        assert st["rollbacks"] == 2
        # Windows stay sorted by from_step regardless of append order.
        loaded = guard_lib.load_state(d)
        assert [w["from_step"] for w in loaded["skip_windows"]] == [2, 4]
        # A torn/garbage guard file degrades to empty, never crashes.
        (tmp_path / guard_lib.GUARD_STATE_FILE).write_text("{oops")
        assert guard_lib.load_state(d)["skip_windows"] == []


# ---------------------------------------------------------------------
# fault spec parsing (satellite: loud value errors, last-wins dupes)
# ---------------------------------------------------------------------
class TestFaultParse:
    def test_new_kinds_parse(self):
        plan = fault_plan_from_env({
            "TPU_HPC_FAULTS":
                "nan_loss_at_step=3,grad_spike_at_step=5,"
                "grad_spike_scale=100.0,bitflip_ckpt_at_step=6,"
                "straggler_ms=250,straggler_at_step=4,on_attempt=-1",
        })
        assert plan.nan_loss_at_step == 3
        assert plan.grad_spike_at_step == 5
        assert plan.grad_spike_scale == 100.0
        assert plan.bitflip_ckpt_at_step == 6
        assert plan.straggler_ms == 250.0
        assert plan.straggler_at_step == 4
        assert plan.on_attempt == -1
        assert plan.active  # -1 = every attempt
        assert fault_plan_from_env({
            "TPU_HPC_FAULTS": "nan_loss_at_step=3,on_attempt=-1",
            "TPU_HPC_ATTEMPT": "7",
        }).active

    def test_bad_int_value_names_key_and_spec(self):
        with pytest.raises(ValueError, match="kill_at_step") as ei:
            fault_plan_from_env(
                {"TPU_HPC_FAULTS": "kill_at_step=soon"}
            )
        msg = str(ei.value)
        assert "soon" in msg and "kill_at_step=soon" in msg
        assert "integer" in msg

    def test_bad_float_value_names_key_and_spec(self):
        with pytest.raises(ValueError, match="straggler_ms"):
            fault_plan_from_env(
                {"TPU_HPC_FAULTS": "straggler_ms=fast"}
            )

    def test_duplicate_key_last_wins(self):
        plan = fault_plan_from_env(
            {"TPU_HPC_FAULTS": "kill_at_step=2,kill_at_step=5"}
        )
        assert plan.kill_at_step == 5


# ---------------------------------------------------------------------
# checkpoint content integrity (unit level)
# ---------------------------------------------------------------------
class TestIntegrityUnit:
    def test_checksum_roundtrip_and_flip(self):
        state = {
            "w": jnp.arange(16, dtype=jnp.float32),
            "b": jnp.ones((4,), jnp.bfloat16),
        }
        sums = integrity.leaf_checksums(state)
        assert set(sums) == {"w", "b"}
        assert integrity.verify_tree(state, sums) == []
        flipped = dict(state)
        arr = np.array(state["w"], copy=True)
        arr.view(np.uint8)[5] ^= 0x01  # one bit
        flipped["w"] = jnp.asarray(arr)
        assert integrity.verify_tree(flipped, sums) == ["w"]

    def test_dtype_switch_is_not_corruption(self):
        state = {"mu": jnp.ones((8,), jnp.float32)}
        sums = integrity.leaf_checksums(state)
        cast = {"mu": state["mu"].astype(jnp.bfloat16)}
        # orbax's legal restore-into-different-dtype: skipped, clean.
        assert integrity.verify_tree(cast, sums) == []

    def test_unknown_paths_skipped(self):
        sums = integrity.leaf_checksums({"a": jnp.ones((2,))})
        assert integrity.verify_tree({"b": jnp.zeros((2,))}, sums) == []

    def test_async_manager_writes_and_verifies_checksums(
        self, tmp_path, fresh_bus
    ):
        """Async managers compute the sidecar checksums on a
        background thread (the save-side device_get+crc must not
        serialize the hot loop); restore joins the thread and still
        verifies."""
        from tpu_hpc.reshard.elastic import read_sidecar

        ck = str(tmp_path / "ck")
        mgr = CheckpointManager(ck, async_save=True)
        state = {"w": jnp.arange(8, dtype=jnp.float32)}
        mgr.save(state, step=1)
        restored = mgr.restore_latest(
            {"w": jnp.zeros((8,), jnp.float32)}
        )
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.arange(8, dtype=np.float32)
        )
        meta = read_sidecar(ck, 1)
        assert meta is not None and "checksums" in meta
        mgr.close()


# ---------------------------------------------------------------------
# in-process trainer chaos (the fast tier-1 representatives)
# ---------------------------------------------------------------------
class LinearDS:
    """Deterministic per-step batches keyed by the DATA index."""

    def batch_at(self, step, bs):
        k = jax.random.key(int(step) % 97)
        x = jax.random.normal(k, (bs, 4), jnp.float32)
        return x, x @ jnp.arange(4.0)


def _forward(params, model_state, batch, step_rng):
    x, y = batch
    pred = x @ params["w"]
    return jnp.mean((pred - y) ** 2), model_state, {}


@pytest.fixture()
def fresh_bus():
    """Isolated event bus per test: no sink, no flight dir, fresh
    run_id -- a previous test's flight_dir must not swallow dumps."""
    prev = obs.set_bus(obs.EventBus(path="", flight_dir=""))
    yield obs.get_bus()
    obs.set_bus(prev)


def _make_trainer(mesh, ckpt_dir, metrics, guard_mode="rollback",
                  epochs=3, **cfg_kw):
    cfg = TrainingConfig(
        epochs=epochs, steps_per_epoch=2, global_batch_size=8,
        learning_rate=1e-2, save_every=1, checkpoint_dir=ckpt_dir,
        metrics_path=metrics, guard_mode=guard_mode, **cfg_kw,
    )
    mgr = CheckpointManager(ckpt_dir, async_save=False)
    return Trainer(
        cfg, mesh, _forward, {"w": jnp.zeros((4,), jnp.float32)},
        checkpoint_manager=mgr,
    )


@pytest.mark.chaos
class TestGuardSkip:
    def test_nan_batch_skipped_on_device(
        self, mesh8, tmp_path, monkeypatch, fresh_bus
    ):
        """guard_mode='skip': a NaN loss at data index 3 drops that
        update on-device -- params stay finite, the stream advances,
        the run completes, and the verdict is a schema-stamped event."""
        monkeypatch.setenv(
            "TPU_HPC_FAULTS", "nan_loss_at_step=3,on_attempt=-1"
        )
        metrics = str(tmp_path / "run.jsonl")
        tr = _make_trainer(
            mesh8, str(tmp_path / "ck"), metrics, guard_mode="skip",
            capture_on_anomaly=True,
        )
        res = tr.fit(LinearDS())
        assert int(jax.device_get(tr.state.step)) == 6
        assert np.isfinite(res["final_loss"])
        assert np.isfinite(np.asarray(tr.state.params["w"])).all()
        assert res["rolled_back"] is False
        recs = load_records(metrics)
        verdicts = [
            r for r in recs if r["event"] == "guard_verdict"
        ]
        assert [(v["step"], v["verdict"], v["action"])
                for v in verdicts] == [(3, "poisoned", "skip")]
        assert verdicts[0]["data_index"] == 3
        # The symptom->evidence join (obs/trace.py): the verdict and
        # the guard-triggered capture share the poisoned STEP's trace
        # id, so the evidence bundle greps to the record that caused
        # it.
        assert verdicts[0]["trace_id"].endswith(":step:3")
        caps = [
            r for r in recs if r["event"] == "capture_triggered"
        ]
        assert len(caps) == 1
        assert caps[0]["reason"] == "guard_poisoned"
        assert caps[0]["trace_id"] == verdicts[0]["trace_id"]
        assert validate_file(metrics) > 0

    def test_skip_without_anomaly_is_bit_identical_and_same_compiles(
        self, mesh8, tmp_path, fresh_bus
    ):
        """The zero-cost claim, pinned: on a healthy run the guard
        changes NOTHING -- final params bit-identical to guard-off,
        and the same number of compiled epoch programs (the health
        vector rides the existing jitted chunk; no extra compiles in
        steady state)."""
        ds = LinearDS()
        tr_off = _make_trainer(
            mesh8, str(tmp_path / "a"), "", guard_mode="off"
        )
        tr_on = _make_trainer(
            mesh8, str(tmp_path / "b"), "", guard_mode="skip"
        )
        tr_off.fit(ds)
        tr_on.fit(ds)
        np.testing.assert_array_equal(
            np.asarray(tr_off.state.params["w"]),
            np.asarray(tr_on.state.params["w"]),
        )
        # One AOT-compiled executable per distinct chunk length,
        # guard on or off: enabling the guard must not change the
        # steady-state compile count.
        assert len(tr_on._epoch_fns) == len(tr_off._epoch_fns)


@pytest.mark.chaos
class TestGuardSpike:
    def test_spike_detected_against_rolling_median(
        self, mesh8, tmp_path, monkeypatch, fresh_bus
    ):
        """grad_spike_at_step: a finite 1e4x gradient at data index 5
        is flagged 'spike' against the rolling healthy median; the
        default action is an event (record, keep going)."""
        monkeypatch.setenv(
            "TPU_HPC_FAULTS", "grad_spike_at_step=5,on_attempt=-1"
        )
        metrics = str(tmp_path / "run.jsonl")
        tr = _make_trainer(
            mesh8, str(tmp_path / "ck"), metrics,
            guard_mode="skip", epochs=4, guard_spike_factor=10.0,
        )
        res = tr.fit(LinearDS())
        assert int(jax.device_get(tr.state.step)) == 8
        assert res["rolled_back"] is False
        verdicts = [
            r for r in load_records(metrics)
            if r["event"] == "guard_verdict"
        ]
        spikes = [v for v in verdicts if v["verdict"] == "spike"]
        # Detection onset is exact; the injected update knocks the
        # model off its trajectory, so the immediately following
        # (genuine) recovery steps may legitimately spike too.
        assert spikes and spikes[0]["step"] == 5
        assert all(v["step"] >= 5 for v in spikes)
        assert spikes[0]["action"] == "event"
        assert spikes[0]["ratio"] > 10.0


@pytest.mark.chaos
class TestGuardRollbackInProcess:
    def test_rollback_pair_skips_poisoned_window_deterministically(
        self, mesh8, tmp_path, monkeypatch, fresh_bus
    ):
        """The rollback round trip without the supervisor: attempt 0
        poisons at data index 3, rolls back (quarantine + skip window
        + rolled_back=True => EXIT_ROLLBACK); the relaunch -- with the
        fault STILL armed -- completes because the stream skipped the
        index. Run twice: bit-identical final params (deterministic
        under seed)."""
        monkeypatch.setenv(
            "TPU_HPC_FAULTS", "nan_loss_at_step=3,on_attempt=-1"
        )

        def pair(tag):
            ck = str(tmp_path / tag / "ck")
            metrics = str(tmp_path / tag / "run.jsonl")
            tr0 = _make_trainer(mesh8, ck, metrics)
            r0 = tr0.fit(LinearDS())
            assert r0["rolled_back"] is True
            from tpu_hpc.resilience import exit_code_for

            assert exit_code_for(
                r0["preempted"], r0["rolled_back"]
            ) == EXIT_ROLLBACK
            state = guard_lib.load_state(ck)
            assert state["skip_windows"] == [
                {"from_step": 3, "data_from": 3, "data_to": 3}
            ]
            tr1 = _make_trainer(mesh8, ck, metrics)
            r1 = tr1.fit(LinearDS())
            assert r1["rolled_back"] is False
            assert int(jax.device_get(tr1.state.step)) == 6
            assert np.isfinite(r1["final_loss"])
            return np.asarray(tr1.state.params["w"]), metrics

        w_a, metrics = pair("a")
        w_b, _ = pair("b")
        np.testing.assert_array_equal(w_a, w_b)

        recs = load_records(metrics)
        rollbacks = [
            r for r in recs if r["event"] == "guard_rollback"
        ]
        # Detection names the poisoned step exactly (within 1 step).
        assert len(rollbacks) == 1
        assert rollbacks[0]["first_bad"] == 3
        assert rollbacks[0]["to_step"] == 2
        # The resumed attempt's run_start proves the restore target.
        starts = [r for r in recs if r["event"] == "run_start"]
        assert starts[-1]["start_step"] == 2

    def test_rollback_without_predating_checkpoint_is_loud(
        self, mesh8, tmp_path, monkeypatch, fresh_bus
    ):
        """Anomaly before the first save: the guard must fail loudly,
        not silently restart-from-0 into the same poison."""
        monkeypatch.setenv(
            "TPU_HPC_FAULTS", "nan_loss_at_step=0,on_attempt=-1"
        )
        tr = _make_trainer(
            mesh8, str(tmp_path / "ck"), "", guard_mode="rollback"
        )
        with pytest.raises(GuardError, match="no checkpoint predates"):
            tr.fit(LinearDS())

    def test_rollback_mode_requires_checkpoint_manager(self, mesh8):
        cfg = TrainingConfig(guard_mode="rollback")
        with pytest.raises(ValueError, match="checkpoint_manager"):
            Trainer(
                cfg, mesh8, _forward,
                {"w": jnp.zeros((4,), jnp.float32)},
            )


@pytest.mark.chaos
class TestBitflipChecksum:
    def test_silent_corruption_caught_and_quarantined(
        self, mesh8, tmp_path, monkeypatch, fresh_bus
    ):
        """bitflip_ckpt_at_step=6: the final snapshot is rewritten
        through orbax with one flipped bit -- parseable, wrong. The
        next restore verifies checksums, treats the mismatch like a
        torn write (falls back to step 4), quarantines the corpse as
        ``6.corrupt`` so later restarts never re-probe it, and emits
        ckpt_integrity + ckpt_fallback events the report can see."""
        ck = str(tmp_path / "ck")
        metrics = str(tmp_path / "run.jsonl")
        monkeypatch.setenv("TPU_HPC_FAULTS", "bitflip_ckpt_at_step=6")
        tr = _make_trainer(mesh8, ck, metrics, guard_mode="off")
        tr.fit(LinearDS())
        assert tr.checkpoint_manager.all_steps() == [2, 4, 6]

        monkeypatch.setenv("TPU_HPC_ATTEMPT", "1")  # fault scoped out
        tr2 = _make_trainer(
            mesh8, ck, metrics, guard_mode="off", epochs=4
        )
        assert tr2.maybe_resume() == 4  # fell back below 6
        assert os.path.isdir(os.path.join(ck, "6.corrupt"))
        # The quarantined step's sidecar went with it (the replayed
        # save below will write a FRESH step 6 + sidecar).
        assert not os.path.exists(
            os.path.join(ck, ".tpu_hpc_meta", "6.json")
        )
        res = tr2.fit(LinearDS())
        assert int(jax.device_get(tr2.state.step)) == 8
        assert np.isfinite(res["final_loss"])

        recs = load_records(metrics)
        integ = [r for r in recs if r["event"] == "ckpt_integrity"]
        # One mismatch for the flipped step, then verified-ok restores
        # of the fallback step (once for the explicit maybe_resume
        # above, once inside fit's own resume).
        assert [(r["step"], r["verdict"]) for r in integ] == [
            (6, "mismatch"), (4, "ok"), (4, "ok"),
        ]
        falls = [r for r in recs if r["event"] == "ckpt_fallback"]
        assert len(falls) == 1 and falls[0]["step"] == 6
        assert falls[0]["quarantined"] == "6.corrupt"
        starts = [r for r in recs if r["event"] == "run_start"]
        assert starts[-1]["start_step"] == 4  # fell back below 6
        # Report + regress gate surface all of it.
        rep = build_report(recs)
        assert rep["ckpt"]["fallbacks"] == 1
        assert rep["ckpt"]["integrity_failures"] == 1
        from tpu_hpc.obs.regress import report_metrics

        flat = report_metrics(rep)
        assert flat["ckpt.fallbacks"] == 1.0
        assert flat["ckpt.integrity_failures"] == 1.0

    def test_bitflip_is_deterministic(
        self, mesh8, tmp_path, monkeypatch, fresh_bus
    ):
        """Same seed, same flip, same fallback target -- the chaos
        matrix must be replayable."""
        targets = []
        for tag in ("a", "b"):
            ck = str(tmp_path / tag)
            monkeypatch.setenv(
                "TPU_HPC_FAULTS", "bitflip_ckpt_at_step=4"
            )
            monkeypatch.delenv("TPU_HPC_ATTEMPT", raising=False)
            tr = _make_trainer(
                mesh8, ck, "", guard_mode="off", epochs=2
            )
            tr.fit(LinearDS())
            monkeypatch.setenv("TPU_HPC_ATTEMPT", "1")
            tr2 = _make_trainer(
                mesh8, ck, "", guard_mode="off", epochs=3
            )
            tr2.fit(LinearDS())
            targets.append(
                (
                    int(jax.device_get(tr2.state.step)),
                    sorted(
                        d for d in os.listdir(ck)
                        if d.endswith(".corrupt")
                    ),
                )
            )
        assert targets[0] == targets[1] == (6, ["4.corrupt"])


@pytest.mark.chaos
class TestQuarantineTornWrite:
    def test_torn_write_quarantined_no_reprobe(
        self, mesh8, tmp_path, monkeypatch, fresh_bus
    ):
        """The torn-write fault (garbage files) now also quarantines:
        the second restart must find the corpse already renamed aside
        instead of re-probing it through the retry ladder."""
        ck = str(tmp_path / "ck")
        monkeypatch.setenv("TPU_HPC_FAULTS", "corrupt_ckpt_at_step=6")
        tr = _make_trainer(mesh8, ck, "", guard_mode="off")
        tr.fit(LinearDS())

        monkeypatch.setenv("TPU_HPC_ATTEMPT", "1")
        tr2 = _make_trainer(mesh8, ck, "", guard_mode="off", epochs=3)
        assert tr2.maybe_resume() == 4
        assert os.path.isdir(os.path.join(ck, "6.corrupt"))
        assert 6 not in tr2.checkpoint_manager.all_steps()
        # A third manager never even sees step 6.
        mgr3 = CheckpointManager(ck, async_save=False)
        assert 6 not in mgr3.all_steps()
        mgr3.close()

    def test_systemic_failure_never_quarantines(
        self, tmp_path, fresh_bus
    ):
        """Quarantine is deferred until an OLDER step restores
        successfully: a systemic failure (wrong relaunch config --
        every step fails structurally) must leave every snapshot and
        sidecar in place, keep the typed loud-failure error, and let
        a corrected relaunch restore normally."""
        from tpu_hpc.ckpt import TopologyMismatchError

        ck = str(tmp_path / "ck")
        mgr = CheckpointManager(ck, async_save=False)
        state = {"w": jnp.ones((4,), jnp.float32)}
        mgr.save(state, step=2)
        mgr.save(state, step=4)
        with pytest.raises(TopologyMismatchError, match="shape"):
            mgr.restore_latest({"w": jnp.zeros((5,), jnp.float32)})
        # Nothing renamed, nothing deleted: the snapshots are FINE.
        assert mgr.all_steps() == [2, 4]
        assert not any(
            d.endswith(".corrupt") for d in os.listdir(ck)
        )
        restored = mgr.restore_latest(
            {"w": jnp.zeros((4,), jnp.float32)}
        )
        assert restored is not None
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.ones((4,), np.float32)
        )
        mgr.close()


@pytest.mark.chaos
class TestStraggler:
    def test_straggler_delay_trips_stall_watermark(
        self, tmp_path, monkeypatch, fresh_bus
    ):
        """straggler_ms from straggler_at_step: the injected per-chunk
        delay lands INSIDE the metered window, so the rolling
        step-time watermark flags the degradation (a ``stall`` event)
        -- the gray-failure class binary liveness cannot see."""
        monkeypatch.setenv(
            "TPU_HPC_FAULTS",
            "straggler_ms=400,straggler_at_step=7,on_attempt=-1",
        )
        metrics = str(tmp_path / "run.jsonl")
        mesh1 = build_mesh(
            MeshSpec(axes={"data": 1}), devices=jax.devices()[:1]
        )
        cfg = TrainingConfig(
            epochs=8, steps_per_epoch=1, global_batch_size=8,
            learning_rate=1e-2, metrics_path=metrics,
        )
        tr = Trainer(
            cfg, mesh1, _forward,
            {"w": jnp.zeros((4,), jnp.float32)},
        )
        tr.fit(LinearDS())
        recs = load_records(metrics)
        stalls = [r for r in recs if r["event"] == "stall"]
        assert stalls, "injected 400ms delay never tripped the stall"
        assert all(r["step"] >= 7 for r in stalls)
        assert any(
            r["event"] == "fault" and r["kind"] == "straggler"
            for r in obs.get_bus().ring()
        )


# ---------------------------------------------------------------------
# THE acceptance run: supervised rollback, subprocess end to end
# ---------------------------------------------------------------------
WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    for var in ("TPU_VISIBLE_DEVICES", "TPU_CHIPS_PER_PROCESS_BOUNDS",
                "PALLAS_AXON_POOL_IPS", "AXON_POOL_SVC_OVERRIDE",
                "TPU_WORKER_HOSTNAMES"):
        os.environ.pop(var, None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from tpu_hpc import resilience
    from tpu_hpc.ckpt import CheckpointManager
    from tpu_hpc.config import TrainingConfig
    from tpu_hpc.runtime import MeshSpec, build_mesh
    from tpu_hpc.train import Trainer

    class DS:
        def batch_at(self, step, bs):
            k = jax.random.key(int(step) % 97)
            x = jax.random.normal(k, (bs, 4), jnp.float32)
            return x, x @ jnp.arange(4.0)

    def forward(params, model_state, batch, step_rng):
        x, y = batch
        pred = x @ params["w"]
        return jnp.mean((pred - y) ** 2), model_state, {}

    ckpt_dir = os.environ["WORK_CKPT"]
    cfg = TrainingConfig(
        epochs=int(os.environ.get("WORK_EPOCHS", "3")),
        steps_per_epoch=2, global_batch_size=8, learning_rate=1e-2,
        save_every=1, checkpoint_dir=ckpt_dir,
        metrics_path=os.environ.get("WORK_METRICS", ""),
        guard_mode=os.environ.get("WORK_GUARD", "off"),
        guard_spike_action=os.environ.get("WORK_SPIKE_ACTION", "event"),
    )
    mesh = build_mesh(
        MeshSpec(axes={"data": 1}), devices=jax.devices()[:1]
    )
    mgr = CheckpointManager(ckpt_dir, async_save=False)
    trainer = Trainer(
        cfg, mesh, forward, {"w": jnp.zeros((4,), jnp.float32)},
        checkpoint_manager=mgr,
    )
    result = trainer.fit(DS())
    print("FINAL_STEP", int(jax.device_get(trainer.state.step)),
          flush=True)
    sys.exit(resilience.exit_code_for(
        result["preempted"], result.get("rolled_back", False)
    ))
""")


@pytest.fixture()
def worker(tmp_path):
    path = tmp_path / "worker.py"
    path.write_text(WORKER)

    def run(env_extra, timeout=240, argv_prefix=()):
        env = dict(os.environ)
        prev = env.get("PYTHONPATH", "")
        env["PYTHONPATH"] = REPO + (os.pathsep + prev if prev else "")
        env["WORK_CKPT"] = str(tmp_path / "ckpts")
        env["WORK_METRICS"] = str(tmp_path / "run.jsonl")
        env.update({k: str(v) for k, v in env_extra.items()})
        return subprocess.run(
            [*argv_prefix, sys.executable, str(path)],
            capture_output=True, text=True, timeout=timeout,
            env=env, cwd=REPO,
        )

    return run


def _metrics(tmp_path):
    path = tmp_path / "run.jsonl"
    if not path.exists():
        return []
    return [json.loads(x) for x in open(path)]


@pytest.mark.chaos
class TestSupervisedRollback:
    def test_nan_rollback_relaunch_completes(self, worker, tmp_path):
        """ISSUE 9 acceptance: nan_loss_at_step=3 (armed on EVERY
        attempt) under the supervisor. The guard detects the poisoned
        step exactly, exits EXIT_ROLLBACK (a healthy-process exit:
        restart budget untouched, rollback budget charged), the
        relaunch resumes from the last-good checkpoint, skips the
        poisoned data index -- the ONLY way it can survive with the
        fault still armed -- and completes, leaving a guard_rollback
        event and a combined-goodput report."""
        sup_dir = str(tmp_path / "sup")
        proc = worker(
            {
                "TPU_HPC_FAULTS": "nan_loss_at_step=3,on_attempt=-1",
                "WORK_GUARD": "rollback",
            },
            argv_prefix=(
                sys.executable, "-m", "tpu_hpc.resilience.supervisor",
                "--max-restarts", "0", "--max-rollbacks", "2",
                "--log-dir", sup_dir, "--backoff", "0.1", "--",
            ),
        )
        assert proc.returncode == 0, proc.stderr[-3000:]

        events = [
            json.loads(x)
            for x in open(os.path.join(sup_dir, "supervisor.jsonl"))
        ]
        ends = [e for e in events if e["event"] == "attempt_end"]
        assert [e["rc"] for e in ends] == [EXIT_ROLLBACK, 0]
        assert "guard rollback" in ends[0]["meaning"]
        restarts = [e for e in events if e["event"] == "restarting"]
        assert restarts[0]["why"] == (
            "guard rollback to last-good snapshot"
        )

        a1 = open(os.path.join(sup_dir, "run.attempt1.log")).read()
        assert "FINAL_STEP 6" in a1

        recs = _metrics(tmp_path)
        rollbacks = [
            r for r in recs if r["event"] == "guard_rollback"
        ]
        assert len(rollbacks) == 1
        assert rollbacks[0]["first_bad"] == 3  # detected exactly
        assert rollbacks[0]["to_step"] == 2
        starts = [r for r in recs if r["event"] == "run_start"]
        assert starts[-1]["start_step"] == 2
        # Combined-goodput record: both attempts in one report, plus
        # the guard section naming the rollback.
        rep = build_report(recs)
        assert rep["goodput"] is not None
        assert rep["goodput"]["combined"]["productive_s"] > 0
        assert rep["guard"] is not None
        assert len(rep["guard"]["rollbacks"]) == 1
        assert rep["guard"]["lost_steps"] == 2  # steps 2..3 redone
        from tpu_hpc.obs.regress import report_metrics

        flat = report_metrics(rep)
        assert flat["guard.rollbacks"] == 1.0
        # The skip window survived on disk for any later restart.
        state = guard_lib.load_state(str(tmp_path / "ckpts"))
        assert state["rollbacks"] == 1


class TestRollbackBudget:
    def test_rollbacks_bounded_separately_from_failures(self, tmp_path):
        """EXIT_ROLLBACK exits never burn the restart budget but are
        bounded by --max-rollbacks: a run that keeps poisoning itself
        must not relaunch forever."""
        rc = run_supervised(
            [sys.executable, "-c",
             f"import sys; sys.exit({EXIT_ROLLBACK})"],
            max_restarts=5, max_rollbacks=2,
            log_dir=str(tmp_path), backoff=0.01,
        )
        assert rc == EXIT_ROLLBACK
        events = [
            json.loads(x)
            for x in open(os.path.join(str(tmp_path),
                                       "supervisor.jsonl"))
        ]
        ends = [e for e in events if e["event"] == "attempt_end"]
        assert [e["rc"] for e in ends] == [EXIT_ROLLBACK] * 3
        give = [e for e in events if e["event"] == "giving_up"]
        assert "rollback budget" in give[0]["why"]

    def test_rollback_then_success_under_tight_restart_budget(
        self, tmp_path
    ):
        """max_restarts=0 with one rollback: still succeeds -- the
        rollback exit must not consume the (empty) failure budget."""
        child = (
            "import os, sys; "
            "sys.exit(0 if int(os.environ['TPU_HPC_ATTEMPT']) >= 1 "
            f"else {EXIT_ROLLBACK})"
        )
        rc = run_supervised(
            [sys.executable, "-c", child],
            max_restarts=0, max_rollbacks=3,
            log_dir=str(tmp_path), backoff=0.01,
        )
        assert rc == 0


class TestRegressGateDirections:
    def test_robustness_counters_are_lower_is_better(self):
        """Satellite: the regress gate must treat guard/rollback/
        fallback counts as regressions when they go UP -- a robustness
        gate, not just a perf gate."""
        from tpu_hpc.obs.regress import compare, lower_is_better

        for name in (
            "guard.rollbacks", "guard.poisoned", "guard.spikes",
            "guard.skipped", "guard.lost_steps", "ckpt.fallbacks",
            "ckpt.integrity_failures",
        ):
            assert lower_is_better(name), name
        violations, checked = compare(
            {"guard.rollbacks": 0.0, "ckpt.fallbacks": 0.0},
            {"guard.rollbacks": 2.0, "ckpt.fallbacks": 1.0},
        )
        assert checked == 2
        assert {v["metric"] for v in violations} == {
            "guard.rollbacks", "ckpt.fallbacks",
        }


# ---------------------------------------------------------------------
# the full chaos matrix (slow tier: every fault class, one sweep)
# ---------------------------------------------------------------------
@pytest.mark.slow
@pytest.mark.chaos
class TestChaosMatrixFull:
    @pytest.mark.parametrize(
        "name,faults,guard,spike_action,sup_args,expect_rcs",
        [
            (
                "nan-skip",
                "nan_loss_at_step=3,on_attempt=-1", "skip", "event",
                ("--max-restarts", "0"), [0],
            ),
            (
                "nan-rollback",
                "nan_loss_at_step=3,on_attempt=-1", "rollback",
                "event",
                ("--max-restarts", "0", "--max-rollbacks", "2"),
                [EXIT_ROLLBACK, 0],
            ),
            (
                "spike-rollback",
                "grad_spike_at_step=5,on_attempt=-1", "rollback",
                "rollback",
                ("--max-restarts", "0", "--max-rollbacks", "2"),
                [EXIT_ROLLBACK, 0],
            ),
            (
                "kill-guarded",
                "kill_at_step=4", "skip", "event",
                ("--max-restarts", "2"), [137, 0],
            ),
        ],
    )
    def test_matrix(
        self, worker, tmp_path, name, faults, guard, spike_action,
        sup_args, expect_rcs,
    ):
        """Every row: inject, supervise, survive, leave evidence."""
        sup_dir = str(tmp_path / "sup")
        epochs = "4" if "spike" in name else "3"
        proc = worker(
            {
                "TPU_HPC_FAULTS": faults,
                "WORK_GUARD": guard,
                "WORK_SPIKE_ACTION": spike_action,
                "WORK_EPOCHS": epochs,
            },
            argv_prefix=(
                sys.executable, "-m", "tpu_hpc.resilience.supervisor",
                *sup_args, "--log-dir", sup_dir, "--backoff", "0.1",
                "--",
            ),
        )
        assert proc.returncode == 0, (name, proc.stderr[-3000:])
        events = [
            json.loads(x)
            for x in open(os.path.join(sup_dir, "supervisor.jsonl"))
        ]
        ends = [e for e in events if e["event"] == "attempt_end"]
        assert [e["rc"] for e in ends] == expect_rcs, name
        final = int(epochs) * 2
        last_log = os.path.join(
            sup_dir, f"run.attempt{len(expect_rcs) - 1}.log"
        )
        assert f"FINAL_STEP {final}" in open(last_log).read(), name
        recs = _metrics(tmp_path)
        if EXIT_ROLLBACK in expect_rcs:
            assert any(
                r["event"] == "guard_rollback" for r in recs
            ), name
        elif guard == "skip" and "nan" in faults:
            assert any(
                r["event"] == "guard_verdict"
                and r["action"] == "skip"
                for r in recs
            ), name
