"""Profiling wrapper: window triggering, trace artifacts, Trainer wiring."""
import glob
import os

import jax
import jax.numpy as jnp
import numpy as np

from tpu_hpc.profiling import TrainingProfiler, training_profiler


def test_window_triggering(tmp_path):
    prof = TrainingProfiler(str(tmp_path), start_step=2, num_steps=3)
    prof.step(0)
    assert not prof.active
    prof.step(2)
    assert prof.active
    jnp.ones(8).block_until_ready()  # give the trace something
    prof.step(5)
    assert not prof.active
    # A trace directory with events must exist (TensorBoard layout).
    assert glob.glob(
        os.path.join(str(tmp_path), "plugins", "profile", "*")
    )


def test_chunk_boundary_triggering(tmp_path):
    """Regression: chunked loops only call step() at epoch boundaries
    (0, 20, 40...); a window like [3, 8) must still open at the first
    boundary past start_step."""
    prof = TrainingProfiler(str(tmp_path), start_step=3, num_steps=5)
    prof.step(0)
    assert not prof.active
    prof.step(20)
    assert prof.active
    jnp.ones(8).block_until_ready()
    prof.step(40)
    assert not prof.active


def test_context_manager_stops_on_error(tmp_path):
    try:
        with training_profiler(str(tmp_path), start_step=0) as prof:
            prof.step(0)
            assert prof.active
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    assert not prof.active


def test_exhausted_loop_inside_window_closes_trace(tmp_path):
    """Regression (PR 4 satellite): a loop that ends INSIDE the trace
    window (step never reaches start+num) used to leak the open
    jax.profiler trace for the life of the process -- blocking every
    later start_trace. The class is now its own context manager."""
    with TrainingProfiler(
        str(tmp_path / "a"), start_step=0, num_steps=100
    ) as prof:
        prof.step(0)
        assert prof.active
        jnp.ones(8).block_until_ready()
        # loop exhausts here, far short of step 100
    assert not prof.active
    # The leaked-trace symptom: a second profiler could not start. It
    # can now, proving the first really closed.
    with TrainingProfiler(
        str(tmp_path / "b"), start_step=0, num_steps=1
    ) as prof2:
        prof2.step(0)
        assert prof2.active
        jnp.ones(8).block_until_ready()
    assert not prof2.active


def test_stop_clears_active_even_when_stop_trace_raises(
    tmp_path, monkeypatch
):
    """A stop_trace that raises (full disk mid-write) must still mark
    the profiler inactive, or every later stop re-raises on an
    already-dead trace."""
    prof = TrainingProfiler(str(tmp_path), start_step=0, num_steps=5)
    prof.step(0)
    assert prof.active
    real_stop = jax.profiler.stop_trace

    def boom():
        real_stop()
        raise OSError("disk full")

    monkeypatch.setattr(jax.profiler, "stop_trace", boom)
    try:
        prof.stop()
    except OSError:
        pass
    assert not prof.active
    monkeypatch.setattr(jax.profiler, "stop_trace", real_stop)
    prof.stop()  # idempotent now, must not re-raise


def test_trainer_profile_flag(tmp_path, mesh8):
    from tpu_hpc.config import TrainingConfig
    from tpu_hpc.models import datasets
    from tpu_hpc.train import Trainer

    ds = datasets.ToyRegression()
    params = {"w": jnp.zeros((20, 1))}

    def forward(p, ms, batch, rng):
        x, y = batch
        return jnp.mean((x @ p["w"] - y) ** 2), ms, {}

    cfg = TrainingConfig(
        epochs=2, steps_per_epoch=2, global_batch_size=8,
        profile=True, profile_dir=str(tmp_path), profile_start_step=2,
        profile_num_steps=2,
    )
    result = Trainer(cfg, mesh8, forward, params).fit(ds)
    assert np.isfinite(result["final_loss"])
    assert glob.glob(
        os.path.join(str(tmp_path), "plugins", "profile", "*")
    )
