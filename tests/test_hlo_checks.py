"""checks/hlo.py: the collective-counting instrument, plus the
broadcast HLO-cost pin it exists to make cheap.

The counter must read both dialects (lowered StableHLO for shard_map
programs, compiled HLO for GSPMD-inserted collectives) and report
replica-group shapes without depending on device numbering -- the
hierarchical decomposition guards in test_hierarchical.py are built
on exactly these properties.
"""
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_hpc.checks import hlo
from tpu_hpc.comm import primitives


class TestCollectiveCounts:
    def test_stablehlo_spelling(self, mesh8):
        text = hlo.lowered_text(
            primitives.all_reduce(mesh8, "data"), jnp.arange(8.0)
        )
        assert "stablehlo.all_reduce" in text
        counts = hlo.collective_counts(text)
        assert counts["all-reduce"] == 1
        assert sum(counts.values()) == 1

    def test_compiled_hlo_spelling(self, mesh8):
        x = jax.device_put(
            jnp.arange(8.0), NamedSharding(mesh8, P("data"))
        )
        text = hlo.compiled_text(primitives.all_reduce(mesh8, "data"), x)
        counts = hlo.collective_counts(text)
        assert counts["all-reduce"] == 1, counts

    def test_counts_cover_the_fit_report_list(self):
        # Single source: the fit report's signature list IS this list.
        from tpu_hpc.checks.fit import _COLLECTIVES

        assert tuple(_COLLECTIVES) == hlo.COLLECTIVE_OPS

    def test_group_shapes_stablehlo(self, mesh8):
        text = hlo.lowered_text(
            primitives.all_reduce(mesh8, "data"), jnp.arange(8.0)
        )
        assert hlo.collective_group_shapes(text, "all-reduce") == [(1, 8)]

    def test_group_shapes_compiled(self, mesh8):
        x = jax.device_put(
            jnp.arange(8.0), NamedSharding(mesh8, P("data"))
        )
        text = hlo.compiled_text(primitives.all_reduce(mesh8, "data"), x)
        shapes = hlo.collective_group_shapes(text, "all-reduce")
        assert shapes and shapes[0] == (1, 8), shapes

    def test_no_collectives_counts_zero(self):
        text = hlo.lowered_text(lambda x: x * 2.0, jnp.arange(4.0))
        assert sum(hlo.collective_counts(text).values()) == 0

    def test_group_shapes_iota_form(self):
        # Newer XLA on large meshes prints replica groups in the iota
        # form instead of a dense id list; the shape is in the literal.
        text = (
            "%ar = f32[8] all-reduce-start(f32[8] %p), "
            "replica_groups=[2,4]<=[8], to_apply=%add\n"
        )
        assert hlo.collective_group_shapes(text, "all-reduce") == [(2, 4)]

    def test_group_shapes_no_neighbor_bleed(self):
        # An op with no replica_groups of its own (collective-permute
        # uses source_target_pairs) must report (1, 0) even when a
        # grouped collective follows in the same program -- the search
        # window is bounded by the next collective mention.
        text = (
            "%cp = f32[4] collective-permute(f32[4] %p), "
            "source_target_pairs={{0,1},{1,0}}\n"
            "%ag = f32[8] all-gather(f32[4] %cp), "
            "replica_groups={{0,1,2,3},{4,5,6,7}}, dimensions={0}\n"
        )
        assert hlo.collective_group_shapes(
            text, "collective-permute"
        ) == [(1, 0)]
        assert hlo.collective_group_shapes(text, "all-gather") == [(2, 4)]


class TestBroadcastCost:
    """Satellite pin: primitives.broadcast builds its contribution with
    a jnp.where mask over the full payload -- the cost question is
    whether that lowers to ONE masked psum or degenerates into a psum
    per root candidate. Pinned: exactly one all-reduce, zero other
    collectives, in lowered AND compiled form, independent of the
    axis size (8 here vs 4 below)."""

    def test_one_psum_lowered(self, mesh8):
        text = hlo.lowered_text(
            primitives.broadcast(mesh8, "data", root=3), jnp.arange(16.0)
        )
        counts = hlo.collective_counts(text)
        assert counts["all-reduce"] == 1, counts
        assert sum(counts.values()) == 1, counts

    def test_one_psum_compiled(self, mesh8):
        x = jax.device_put(
            jnp.arange(16.0), NamedSharding(mesh8, P("data"))
        )
        text = hlo.compiled_text(
            primitives.broadcast(mesh8, "data", root=3), x
        )
        counts = hlo.collective_counts(text)
        assert counts["all-reduce"] == 1, counts
        assert sum(counts.values()) == 1, counts

    @pytest.mark.parametrize("root", [0, 2])
    def test_cost_independent_of_axis_size_and_root(self, devices, root):
        from tpu_hpc.runtime import MeshSpec, build_mesh

        mesh4 = build_mesh(MeshSpec(axes={"data": 4}), devices=devices[:4])
        text = hlo.lowered_text(
            primitives.broadcast(mesh4, "data", root=root),
            jnp.arange(8.0),
        )
        counts = hlo.collective_counts(text)
        assert counts["all-reduce"] == 1, counts
        assert sum(counts.values()) == 1, counts
