"""Pallas paged-attention kernels (kernels/paged_attention.py): direct
kernel parity, int8 page quantization, and the engine-level contract.

Five invariant families:
  * **direct kernel parity** -- property-style: random block tables
    (ragged lengths, pages recycled across slots, inactive slots, dead
    table entries pointing at a NaN-poisoned page) through
    ``paged_decode_attention`` / ``paged_prefill_attention`` in
    interpret mode match a dense gather-softmax reference. The poison
    page proves the scalar-prefetch index map redirects every dead
    read to the scratch page -- if the kernel touched it, NaN leaks.
  * **int8 quantization** -- per-page quantize/dequantize round trip
    bounded by half a scale step, the all-zero-page scale floor, and
    kernel-side in-register dequant matching the dequantized-pool
    reference exactly (same math, different read path).
  * **bounded divergence** -- the deterministic ``int8_logit_rmse``
    probe at TINY's attention dims stays under the pinned tolerance,
    and greedy decode through an int8 pool is token-exact across
    kernels (pallas vs gather on the SAME quantized pool -- the kernel
    contract) and vs the fp oracle at this scale.
  * **engine token exactness + compile discipline** -- a churn mix
    (more requests than slots, a fully-cached prompt, a shared-prefix
    CoW divergence, a chunk-stride crosser) through kernel="pallas"
    matches kernel="gather" token for token and the no-cache oracle,
    with ZERO new executables after warmup.
  * **sweep** (``-m kernels``, slow) -- the block-size x dtype grid;
    tier-1 keeps the (block_size=4, float32) representative per
    kernel family above.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hpc.kernels.attention import pick_block_sizes
from tpu_hpc.kernels.paged_attention import (
    INT8_SCALE_FLOOR,
    SCRATCH_PAGE,
    dequantize_pages_int8,
    int8_logit_rmse,
    paged_decode_attention,
    paged_prefill_attention,
    quantize_pages_int8,
)
from tpu_hpc.models import llama2
from tpu_hpc.runtime import MeshSpec, build_mesh
from tpu_hpc.serve import (
    ContinuousBatcher,
    PagedConfig,
    PagedEngine,
    Request,
    ServeConfig,
    SpecConfig,
    attach_spec,
)
from tpu_hpc.serve.paging import SCRATCH_BLOCK


TINY = llama2.LlamaConfig(
    dim=64, n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=128,
    multiple_of=16, max_seq_len=64, dtype=jnp.float32,
)
SERVE = ServeConfig(slots=4, max_seq_len=48, prefill_buckets=(8, 16))

# Bounded-divergence pin: int8_logit_rmse at TINY's attention dims
# (head_dim=16, kv_heads=2, n_heads=4, block_size=4) measures ~0.007;
# the pin leaves ~3x headroom without admitting a broken quantizer
# (a scale bug shows up at >0.1 immediately).
INT8_LOGIT_TOL = 0.02


@pytest.fixture(scope="module")
def serve_mesh(devices):
    return build_mesh(MeshSpec(axes={"data": 4, "model": 2}))


@pytest.fixture(scope="module")
def tiny_params():
    return llama2.init_llama(jax.random.key(0), TINY)


def _engine(tiny_params, serve_mesh, **kw):
    eng = PagedEngine(
        tiny_params, TINY, SERVE, serve_mesh,
        PagedConfig(
            block_size=4, num_blocks=48, prefill_chunk=8, **kw
        ),
    )
    eng.warmup()
    return eng


@pytest.fixture(scope="module")
def gather_engine(tiny_params, serve_mesh):
    return _engine(tiny_params, serve_mesh)


@pytest.fixture(scope="module")
def pallas_engine(tiny_params, serve_mesh):
    return _engine(tiny_params, serve_mesh, kernel="pallas")


@pytest.fixture(scope="module")
def pallas_q8_engine(tiny_params, serve_mesh):
    return _engine(
        tiny_params, serve_mesh, kernel="pallas", kv_quant="int8"
    )


@pytest.fixture(scope="module")
def gather_q8_engine(tiny_params, serve_mesh):
    return _engine(
        tiny_params, serve_mesh, kernel="gather", kv_quant="int8"
    )


_ORACLE_LEN = 48


@pytest.fixture(scope="module")
def greedy_oracle(tiny_params):
    """Greedy continuation via the full NO-CACHE forward pass -- the
    same fixed-padded-length oracle tests/test_paging.py pins the
    gather path against."""
    fwd = jax.jit(
        lambda toks: llama2.apply_llama(tiny_params, toks, TINY)
    )

    def oracle(prompt, steps):
        toks = list(prompt)
        out = []
        for _ in range(steps):
            assert len(toks) <= _ORACLE_LEN
            padded = np.zeros((1, _ORACLE_LEN), np.int32)
            padded[0, :len(toks)] = toks
            logits = fwd(jnp.asarray(padded))
            t = int(jnp.argmax(logits[0, len(toks) - 1]))
            out.append(t)
            toks.append(t)
        return out

    return oracle


def _drain(engine, reqs):
    batcher = ContinuousBatcher(engine)
    return batcher, batcher.run(reqs)


def _churn_mix():
    """More requests than slots; a fully-cached repeat prompt; a
    shared-prefix divergence (CoW on the partially-shared page); a
    prompt crossing the prefill chunk stride. Deterministic."""
    rng = np.random.default_rng(20)
    base = rng.integers(0, TINY.vocab_size, size=12).tolist()
    tail = rng.integers(0, TINY.vocab_size, size=2).tolist()
    short = rng.integers(0, TINY.vocab_size, size=4).tolist()
    longp = rng.integers(0, TINY.vocab_size, size=13).tolist()
    mid = rng.integers(0, TINY.vocab_size, size=7).tolist()
    return [
        Request(rid="r0", prompt=base, max_new_tokens=6),
        Request(rid="r1", prompt=list(base), max_new_tokens=6),
        Request(rid="r2", prompt=base[:8] + tail, max_new_tokens=6),
        Request(rid="r3", prompt=short, max_new_tokens=6),
        Request(rid="r4", prompt=longp, max_new_tokens=5),
        Request(rid="r5", prompt=mid, max_new_tokens=4),
    ]


# ---------------------------------------------------------------------
# Dense references (numpy, fp32, no flash tricks)
# ---------------------------------------------------------------------


def _softmax(x, axis=-1):
    m = np.max(x, axis=axis, keepdims=True)
    e = np.exp(x - m)
    return e / np.sum(e, axis=axis, keepdims=True)


def _ref_decode(q, k_pages, v_pages, tables, pos, active, block_size):
    slots, hkv, g, d = q.shape
    out = np.zeros(q.shape, np.float32)
    for s in range(slots):
        if not active[s]:
            continue
        length = int(pos[s]) + 1
        n = -(-length // block_size)
        ids = tables[s, :n]
        k = k_pages[ids].reshape(n * block_size, hkv, d)[:length]
        v = v_pages[ids].reshape(n * block_size, hkv, d)[:length]
        scores = np.einsum("hgd,thd->hgt", q[s], k) * d ** -0.5
        out[s] = np.einsum(
            "hgt,thd->hgd", _softmax(scores), v
        )
    return out


def _ref_prefill(q, k_pages, v_pages, table, start, block_size):
    hkv, bucket, g, d = q.shape
    ctx = start + bucket
    n = -(-ctx // block_size)
    k = k_pages[table[:n]].reshape(n * block_size, hkv, d)[:ctx]
    v = v_pages[table[:n]].reshape(n * block_size, hkv, d)[:ctx]
    scores = np.einsum("hqgd,thd->hqgt", q, k) * d ** -0.5
    qpos = start + np.arange(bucket)
    causal = np.arange(ctx)[None, :] <= qpos[:, None]  # (bucket, ctx)
    scores = np.where(causal[None, :, None, :], scores, -1e30)
    return np.einsum("hqgt,thd->hqgd", _softmax(scores), v)


def _random_case(
    rng, *, slots=4, hkv=2, g=2, d=16, block_size=4, max_blocks=6,
    num_blocks=24, dtype=np.float32, poison=True,
):
    """Random pool + tables. Page 0 is scratch (zeros, the engine
    contract); the LAST page is NaN-poisoned and never allocated --
    every dead table entry points at it, so a kernel that fails to
    redirect dead reads to scratch poisons its output."""
    pool = rng.standard_normal(
        (num_blocks, block_size, hkv, d)
    ).astype(dtype)
    pool[SCRATCH_PAGE] = 0.0
    poison_page = num_blocks - 1
    if poison:
        pool[poison_page] = np.nan
    k_pages = pool
    v_pages = rng.standard_normal(pool.shape).astype(dtype)
    v_pages[SCRATCH_PAGE] = 0.0
    if poison:
        v_pages[poison_page] = np.nan
    q = rng.standard_normal((slots, hkv, g, d)).astype(dtype)
    pos = rng.integers(
        0, max_blocks * block_size, size=slots
    ).astype(np.int32)
    active = (rng.random(slots) < 0.75).astype(np.int32)
    active[0], active[-1] = 1, 0  # force one live, one dead slot
    tables = np.zeros((slots, max_blocks), np.int32)
    for s in range(slots):
        # pages drawn per-slot from the same small pool: overlap
        # across slots is the recycled/shared-page case
        tables[s] = rng.choice(
            np.arange(1, poison_page), size=max_blocks, replace=False
        )
        n_live = -(-(int(pos[s]) + 1) // block_size)
        tables[s, n_live:] = poison_page
        if not active[s]:
            tables[s] = poison_page  # dead slot: every entry poison
    return q, k_pages, v_pages, tables, pos, active


def _fresh_table_row(rng, num_blocks, max_blocks, ctx_pages):
    """A prefill table row: ``ctx_pages`` live pages, every later
    entry pointed at the poison page (the engine pads dead entries
    with scratch; poison proves the index map never reads them)."""
    poison_page = num_blocks - 1
    row = rng.choice(
        np.arange(1, poison_page), size=max_blocks, replace=False
    ).astype(np.int32)
    row[ctx_pages:] = poison_page
    return row


# ---------------------------------------------------------------------
# Direct kernel parity
# ---------------------------------------------------------------------


class TestDecodeKernelParity:
    def test_random_tables_match_dense_reference(self):
        rng = np.random.default_rng(0)
        for trial in range(4):
            q, kp, vp, tables, pos, active = _random_case(rng)
            out = np.asarray(paged_decode_attention(
                jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                jnp.asarray(tables), jnp.asarray(pos),
                jnp.asarray(active),
                block_size=4, max_blocks=6, interpret=True,
            ))
            ref = _ref_decode(q, kp, vp, tables, pos, active, 4)
            assert np.isfinite(out).all(), trial  # poison stayed out
            np.testing.assert_allclose(
                out, ref, atol=2e-5, rtol=2e-5, err_msg=f"trial {trial}"
            )
            assert not out[active == 0].any()  # dead slots exact zeros

    def test_int8_pool_matches_dequantized_reference(self):
        """In-register dequant is the same math as reading a
        dequantized pool: parity is tight, not merely bounded."""
        rng = np.random.default_rng(1)
        q, kp, vp, tables, pos, active = _random_case(rng, poison=False)
        kq, ksc = quantize_pages_int8(jnp.asarray(kp))
        vq, vsc = quantize_pages_int8(jnp.asarray(vp))
        out = np.asarray(paged_decode_attention(
            jnp.asarray(q), kq, vq,
            jnp.asarray(tables), jnp.asarray(pos), jnp.asarray(active),
            block_size=4, max_blocks=6,
            k_scale=ksc, v_scale=vsc, interpret=True,
        ))
        ref = _ref_decode(
            q, np.asarray(dequantize_pages_int8(kq, ksc)),
            np.asarray(dequantize_pages_int8(vq, vsc)),
            tables, pos, active, 4,
        )
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
        # and bounded divergence vs the UNQUANTIZED pool
        exact = _ref_decode(q, kp, vp, tables, pos, active, 4)
        assert np.max(np.abs(out - exact)) < 0.05


class TestPrefillKernelParity:
    @pytest.mark.parametrize("start", [0, 8, 16])
    def test_chunk_matches_dense_causal_reference(self, start):
        """One compiled shape serves every chunk: ``start`` is data.
        start=0 is the first chunk, 8/16 are continuation chunks whose
        q rows attend across earlier pages."""
        rng = np.random.default_rng(2)
        hkv, bucket, g, d, bs, mb = 2, 8, 2, 16, 4, 6
        _, kp, vp, _, _, _ = _random_case(rng)
        ctx_pages = -(-(start + bucket) // bs)
        table = _fresh_table_row(rng, kp.shape[0], mb, ctx_pages)
        q = rng.standard_normal((hkv, bucket, g, d)).astype(np.float32)
        out = np.asarray(paged_prefill_attention(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(table), jnp.asarray(start, jnp.int32),
            block_size=bs, max_blocks=mb, interpret=True,
        ))
        ref = _ref_prefill(q, kp, vp, table, start, bs)
        assert np.isfinite(out).all()
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_odd_bucket_falls_back_to_one_q_block(self):
        """bucket % block_q != 0 collapses to a single q block rather
        than padding games -- the engine's odd trailing chunk."""
        rng = np.random.default_rng(3)
        hkv, bucket, g, d, bs, mb = 2, 6, 2, 16, 4, 6
        _, kp, vp, _, _, _ = _random_case(rng)
        table = _fresh_table_row(rng, kp.shape[0], mb, -(-bucket // bs))
        q = rng.standard_normal((hkv, bucket, g, d)).astype(np.float32)
        out = np.asarray(paged_prefill_attention(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(table), jnp.asarray(0, jnp.int32),
            block_size=bs, max_blocks=mb, block_q=4, interpret=True,
        ))
        ref = _ref_prefill(q, kp, vp, table, 0, bs)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_multi_q_block_accumulators_reinit_per_block(self):
        """bucket=8 at block_q=4 runs two q blocks over the same kv
        walk: the VMEM accumulators must re-init at j==0 of EACH q
        block, and the causal mask must track the block offset."""
        rng = np.random.default_rng(8)
        hkv, bucket, g, d, bs, mb = 2, 8, 2, 16, 4, 6
        _, kp, vp, _, _, _ = _random_case(rng)
        table = _fresh_table_row(
            rng, kp.shape[0], mb, -(-(8 + bucket) // bs)
        )
        q = rng.standard_normal((hkv, bucket, g, d)).astype(np.float32)
        out = np.asarray(paged_prefill_attention(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(table), jnp.asarray(8, jnp.int32),
            block_size=bs, max_blocks=mb, block_q=4, interpret=True,
        ))
        ref = _ref_prefill(q, kp, vp, table, 8, bs)
        assert np.isfinite(out).all()
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_int8_chunk_matches_dequantized_reference(self):
        rng = np.random.default_rng(4)
        hkv, bucket, g, d, bs, mb = 2, 8, 2, 16, 4, 6
        _, kp, vp, _, _, _ = _random_case(rng, poison=False)
        table = _fresh_table_row(
            rng, kp.shape[0], mb, -(-(8 + bucket) // bs)
        )
        kq, ksc = quantize_pages_int8(jnp.asarray(kp))
        vq, vsc = quantize_pages_int8(jnp.asarray(vp))
        q = rng.standard_normal((hkv, bucket, g, d)).astype(np.float32)
        out = np.asarray(paged_prefill_attention(
            jnp.asarray(q), kq, vq,
            jnp.asarray(table), jnp.asarray(8, jnp.int32),
            block_size=bs, max_blocks=mb,
            k_scale=ksc, v_scale=vsc, interpret=True,
        ))
        ref = _ref_prefill(
            q, np.asarray(dequantize_pages_int8(kq, ksc)),
            np.asarray(dequantize_pages_int8(vq, vsc)),
            table, 8, bs,
        )
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


# ---------------------------------------------------------------------
# int8 quantization + the divergence probe
# ---------------------------------------------------------------------


class TestInt8Quantization:
    def test_roundtrip_bounded_by_half_a_scale_step(self):
        rng = np.random.default_rng(5)
        pages = jnp.asarray(
            rng.standard_normal((6, 4, 2, 16)).astype(np.float32)
        )
        q8, sc = quantize_pages_int8(pages)
        assert q8.dtype == jnp.int8
        assert sc.shape == (6,)
        back = dequantize_pages_int8(q8, sc)
        err = np.abs(np.asarray(back) - np.asarray(pages))
        assert np.all(
            err <= np.asarray(sc)[:, None, None, None] * 0.5 + 1e-7
        )

    def test_zero_page_scale_floor_no_nans(self):
        q8, sc = quantize_pages_int8(jnp.zeros((3, 4, 2, 16)))
        assert np.all(np.asarray(sc) == INT8_SCALE_FLOOR)
        assert not np.asarray(q8).any()
        assert np.isfinite(
            np.asarray(dequantize_pages_int8(q8, sc))
        ).all()

    def test_logit_rmse_probe_pins_the_tolerance(self):
        """The probe is deterministic (no engine, no clock) and stays
        under the pinned bound at TINY's attention dims -- this is the
        number docs/guide/serving.md quotes for when int8 is safe."""
        kw = dict(
            head_dim=16, kv_heads=2, n_heads=4,
            seq_len=48, block_size=4,
        )
        r = int8_logit_rmse(**kw)
        assert r == int8_logit_rmse(**kw)
        assert 0.0 < r < INT8_LOGIT_TOL

    def test_probe_validates_shapes(self):
        with pytest.raises(ValueError, match="multiple of block_size"):
            int8_logit_rmse(head_dim=16, kv_heads=2, seq_len=50,
                            block_size=4)
        with pytest.raises(ValueError, match="multiple of kv_heads"):
            int8_logit_rmse(head_dim=16, kv_heads=2, n_heads=3)


# ---------------------------------------------------------------------
# Engine-level contract
# ---------------------------------------------------------------------


class TestEngineParity:
    def test_scratch_sentinels_agree(self):
        assert SCRATCH_PAGE == SCRATCH_BLOCK == 0

    def test_pick_block_sizes_single_source(self):
        assert pick_block_sizes(512, 512, 40, 200) == (128, 256)

    def test_pallas_token_exact_vs_gather_and_oracle(
        self, gather_engine, pallas_engine, greedy_oracle
    ):
        """The churn mix (slot churn, fully-cached prompt, CoW
        divergence, chunk crosser) decodes identically through both
        read paths, and both match the no-cache oracle."""
        _, want = _drain(gather_engine, _churn_mix())
        _, got = _drain(pallas_engine, _churn_mix())
        assert got == want
        for r in _churn_mix():
            assert got[r.rid] == greedy_oracle(
                r.prompt, r.max_new_tokens
            ), r.rid

    def test_pallas_prefix_hits_and_zero_recompiles(
        self, pallas_engine, greedy_oracle
    ):
        """Replaying the mix hits the prefix trie (pages written by
        the previous drain, read back through the Pallas kernels) with
        ZERO new executables: tables, positions and chunk starts are
        all data."""
        n0 = pallas_engine.compile_count
        hits0 = pallas_engine.paged_stats["prefix_hits"]
        for _ in range(2):
            reqs = _churn_mix()
            _, got = _drain(pallas_engine, reqs)
            for r in reqs:
                assert got[r.rid] == greedy_oracle(
                    r.prompt, r.max_new_tokens
                ), r.rid
        assert pallas_engine.compile_count == n0
        assert pallas_engine.paged_stats["prefix_hits"] > hits0

    def test_summary_reports_kernel_and_quant(
        self, pallas_q8_engine, gather_engine
    ):
        s = pallas_q8_engine.paged_summary()
        assert s["kv_kernel"] == "pallas"
        assert s["kv_quant"] == "int8"
        s = gather_engine.paged_summary()
        assert s["kv_kernel"] == "gather"
        assert s["kv_quant"] == "none"

    def test_config_validation(self):
        with pytest.raises(ValueError, match="kernel"):
            PagedConfig(block_size=4, num_blocks=8, kernel="triton")
        with pytest.raises(ValueError, match="kv_quant"):
            PagedConfig(block_size=4, num_blocks=8, kv_quant="fp4")


class TestEngineInt8:
    def test_int8_token_exact_across_kernels(
        self, gather_q8_engine, pallas_q8_engine
    ):
        """The kernel contract under quantization: pallas and gather
        read the SAME int8 pool, so their streams are token-exact
        even where quantization drifts from fp."""
        _, want = _drain(gather_q8_engine, _churn_mix())
        _, got = _drain(pallas_q8_engine, _churn_mix())
        assert got == want

    def test_int8_bounded_divergence_vs_fp_oracle(
        self, pallas_q8_engine, greedy_oracle
    ):
        """int8 vs fp is a BOUNDED-divergence contract (the probe pin
        above); at TINY's scale the drift flips no greedy argmax, so
        the streams happen to stay token-exact -- pinned as such."""
        reqs = _churn_mix()
        _, got = _drain(pallas_q8_engine, reqs)
        for r in reqs:
            assert got[r.rid] == greedy_oracle(
                r.prompt, r.max_new_tokens
            ), r.rid

    def test_int8_zero_recompiles_under_churn(self, pallas_q8_engine):
        n0 = pallas_q8_engine.compile_count
        _drain(pallas_q8_engine, _churn_mix())
        assert pallas_q8_engine.compile_count == n0

    def test_spec_rejects_quantized_pool(self, pallas_q8_engine):
        with pytest.raises(ValueError, match="quantized KV pool"):
            attach_spec(pallas_q8_engine, SpecConfig(mode="ngram"))


# ---------------------------------------------------------------------
# Sweep: block-size x dtype grid (-m kernels; slowlisted)
# ---------------------------------------------------------------------

_SWEEP = [(4, "bfloat16"), (8, "float32"), (8, "bfloat16")]


@pytest.mark.kernels
class TestKernelSweep:
    """The grid beyond tier-1's (block_size=4, float32)
    representative. bf16 pools compare against an fp32 reference over
    the SAME bf16-rounded pages; tolerance covers the p-matrix
    bf16 cast in the flash inner loop."""

    @pytest.mark.parametrize("block_size,dtype", _SWEEP)
    def test_decode_grid(self, block_size, dtype):
        rng = np.random.default_rng(6)
        tol = 2e-5 if dtype == "float32" else 6e-2
        for trial in range(2):
            q, kp, vp, tables, pos, active = _random_case(
                rng, block_size=block_size,
                dtype=np.float32,
            )
            qj = jnp.asarray(q).astype(dtype)
            kj = jnp.asarray(kp).astype(dtype)
            vj = jnp.asarray(vp).astype(dtype)
            out = np.asarray(paged_decode_attention(
                qj, kj, vj,
                jnp.asarray(tables), jnp.asarray(pos),
                jnp.asarray(active),
                block_size=block_size, max_blocks=6, interpret=True,
            )).astype(np.float32)
            ref = _ref_decode(
                np.asarray(qj, np.float32), np.asarray(kj, np.float32),
                np.asarray(vj, np.float32), tables, pos, active,
                block_size,
            )
            assert np.isfinite(out).all(), (trial, dtype)
            np.testing.assert_allclose(
                out, ref, atol=tol, rtol=tol,
                err_msg=f"trial {trial} bs={block_size} {dtype}",
            )

    @pytest.mark.parametrize("block_size,dtype", _SWEEP)
    def test_prefill_grid(self, block_size, dtype):
        rng = np.random.default_rng(7)
        tol = 2e-5 if dtype == "float32" else 6e-2
        hkv, bucket, g, d = 2, 8, 2, 16
        _, kp, vp, _, _, _ = _random_case(
            rng, block_size=block_size
        )
        for start in (0, 8):
            ctx_pages = -(-(start + bucket) // block_size)
            table = _fresh_table_row(rng, kp.shape[0], 6, ctx_pages)
            q = rng.standard_normal(
                (hkv, bucket, g, d)
            ).astype(np.float32)
            qj = jnp.asarray(q).astype(dtype)
            kj = jnp.asarray(kp).astype(dtype)
            vj = jnp.asarray(vp).astype(dtype)
            out = np.asarray(paged_prefill_attention(
                qj, kj, vj,
                jnp.asarray(table), jnp.asarray(start, jnp.int32),
                block_size=block_size, max_blocks=6, interpret=True,
            )).astype(np.float32)
            ref = _ref_prefill(
                np.asarray(qj, np.float32), np.asarray(kj, np.float32),
                np.asarray(vj, np.float32), table, start, block_size,
            )
            assert np.isfinite(out).all(), (start, dtype)
            np.testing.assert_allclose(
                out, ref, atol=tol, rtol=tol,
                err_msg=f"start {start} bs={block_size} {dtype}",
            )
