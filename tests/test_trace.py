"""tpu_hpc.obs.trace -- end-to-end causal tracing.

Five invariant families:

* **trace contexts** -- derived ids are pure in (run_id, kind, key),
  ambient activation stamps every emit on the thread (explicit ids
  win), and span durations come from the MONOTONIC clock (a wall-time
  jump mid-span must not corrupt a phase share).
* **complete traces** -- a seeded ``decode_heavy`` (speculative) and
  ``shared_prefix`` (disagg-paged) loadgen run each yield a complete
  per-request trace: every lifecycle event carries the request's
  trace_id, the analyzer reconstructs with ZERO orphan spans, and the
  critical path attributes >= 95% of TTFT to named phases -- with
  zero engine recompiles from the propagation.
* **fault attribution** -- an injected ``TPU_HPC_LOADGEN_FAULTS``
  prefill delay produces a trace whose critical path names the
  injected phase.
* **anomaly capture** -- a stall (loadgen colocation theft, or the
  trainer's injected straggler fault) auto-triggers EXACTLY ONE
  bounded profiler capture + flight dump correlated by trace_id; a
  clean run triggers none.
* **schema discipline** -- the new ``trace_ctx``/``device_memory``/
  ``capture_triggered`` kinds round-trip the validator, and a tier-1
  lint walks the tree asserting every ``span(name)``/event kind used
  in-source is registered in the canonical schema tables.
"""
import ast
import glob
import json
import os

import jax
import jax.numpy as jnp
import pytest

from tpu_hpc import obs
from tpu_hpc.loadgen import LoadHarness, build_scenario, parse_faults
from tpu_hpc.models import llama2
from tpu_hpc.obs import schema as schema_mod
from tpu_hpc.obs.regress import lower_is_better, report_metrics
from tpu_hpc.obs.report import build_report
from tpu_hpc.obs.schema import load_records, validate_record
from tpu_hpc.obs.trace import (
    AnomalyCapture,
    activate,
    analyze,
    build_traces,
    chrome_trace,
    main as trace_main,
    parse_trace_id,
    request_trace_id,
    step_trace_id,
    trace_id_for,
)
from tpu_hpc.runtime import MeshSpec, build_mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = llama2.LlamaConfig(
    dim=64, n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=128,
    multiple_of=16, max_seq_len=256, dtype=jnp.float32,
)
MAX_PROMPT, MAX_NEW = 16, 6

LIFECYCLE = (
    "trace_ctx", "lg_arrival", "lg_admit", "lg_first_token",
    "lg_finish", "lg_shed", "admission", "request",
)


@pytest.fixture(scope="module")
def tiny_params():
    return llama2.init_llama(jax.random.key(0), TINY)


@pytest.fixture(scope="module")
def slab_engine(tiny_params, devices):
    from tpu_hpc.serve import Engine, ServeConfig

    mesh = build_mesh(MeshSpec(axes={"data": 4, "model": 2}))
    engine = Engine(
        tiny_params, TINY,
        ServeConfig(slots=4, max_seq_len=48, prefill_buckets=(8, 16)),
        mesh,
    )
    engine.warmup()
    return engine


@pytest.fixture()
def scoped_obs(tmp_path):
    """Fresh bus + registry per test (the loadgen fixture
    discipline); flight dir armed so capture dumps have a home."""
    bus = obs.EventBus(path=None, run_id="trace-test",
                       flight_dir=str(tmp_path))
    reg = obs.MetricsRegistry()
    prev_bus, prev_reg = obs.set_bus(bus), obs.set_registry(reg)
    yield bus, reg
    obs.set_bus(prev_bus)
    obs.set_registry(prev_reg)


def _scenario(name, seed=7, n=16):
    return build_scenario(
        name, seed=seed, n_requests=n, vocab_size=TINY.vocab_size,
        max_prompt=MAX_PROMPT, max_new=MAX_NEW,
    )


def _run(engine, name, path, faults="", capture=None, n=16):
    harness = LoadHarness(
        engine, _scenario(name, n=n), metrics_path=str(path),
        faults=parse_faults(faults), capture=capture,
    )
    return harness.run(n_devices=jax.device_count()), harness


def _assert_complete_traces(path, expect_requests):
    """The acceptance bundle: every lifecycle event trace-tagged,
    zero orphan spans, >= 95% of TTFT attributed to named phases."""
    records = load_records(str(path))
    # Every per-request lifecycle event must carry its trace id.
    # (Batch-level admission "queue" summaries name no request, so
    # they carry none by design.)
    life = [
        r for r in records
        if r["event"] in LIFECYCLE
        and (r["event"] != "admission" or "rid" in r)
    ]
    assert life, "no lifecycle events in the run log"
    missing = [r for r in life if "trace_id" not in r]
    assert not missing, f"lifecycle events without trace_id: {missing[:3]}"
    rep = analyze(records)
    assert rep["orphan_spans"] == 0
    req = rep["requests"]
    assert req["count"] == expect_requests
    assert req["complete"] + req["shed"] == expect_requests
    for q in ("p50", "p95", "p99"):
        cp = req["ttft_critical_path"][q]
        assert cp["attributed"] >= 0.95, (q, cp)
        assert cp["dominant"] in cp["phases_ms"]
    return rep


# ---------------------------------------------------------------------
# trace ids + ambient activation
# ---------------------------------------------------------------------
class TestTraceContexts:
    def test_ids_are_pure_and_parse(self, scoped_obs):
        a = request_trace_id("r0001")
        assert a == request_trace_id("r0001")
        assert a == "trace-test:req:r0001"
        assert parse_trace_id(a) == ("trace-test", "req", "r0001")
        assert step_trace_id(42) == "trace-test:step:42"
        run, kind, key = parse_trace_id(trace_id_for("tick", 7))
        assert (kind, key) == ("tick", "7")
        # Non-canonical ids degrade, not crash.
        assert parse_trace_id("weird")[0] is None

    def test_activate_stamps_ambient_and_nests(self, scoped_obs):
        bus, _ = scoped_obs
        tid = request_trace_id("rX")
        with activate(tid):
            rec = bus.emit("fault", kind="test")
            assert rec["trace_id"] == tid
            with activate("other:req:rY"):
                assert bus.emit("fault", kind="t2")["trace_id"] == (
                    "other:req:rY"
                )
            # restored after the nested block
            assert bus.emit("fault", kind="t3")["trace_id"] == tid
            # an explicit id always wins over the ambient one
            assert bus.emit(
                "fault", kind="t4", trace_id="explicit:req:z"
            )["trace_id"] == "explicit:req:z"
        assert "trace_id" not in bus.emit("fault", kind="t5")

    def test_span_duration_survives_wall_clock_jump(
        self, scoped_obs, monkeypatch
    ):
        """The satellite pin: durations come from the monotonic
        clock. A wall-clock step (NTP slew) mid-span must not turn a
        phase share negative -- and every span carries t_mono next to
        the wall stamp."""
        bus, _ = scoped_obs
        import time as time_mod

        real_time = time_mod.time
        with obs.span("warmup", bus=bus, annotate=False):
            # Wall clock jumps 1000 s BACKWARD mid-span.
            monkeypatch.setattr(
                time_mod, "time", lambda: real_time() - 1000.0
            )
        rec = list(bus.ring())[-1]
        assert rec["event"] == "span" and rec["name"] == "warmup"
        assert 0.0 <= rec["dur_s"] < 10.0
        assert "t_mono" in rec
        with obs.span("warmup", bus=bus, annotate=False):
            pass
        rec2 = list(bus.ring())[-1]
        assert rec2["t_mono"] > rec["t_mono"]


# ---------------------------------------------------------------------
# schema: new kinds round-trip + the canonical-name lint
# ---------------------------------------------------------------------
class TestSchemaKinds:
    def _roundtrip(self, tmp_path, rec):
        rec = schema_mod.stamp(rec, run_id="r", host="h", pid=1)
        validate_record(rec)
        p = tmp_path / "k.jsonl"
        p.write_text(json.dumps(rec) + "\n")
        loaded = load_records(str(p))
        assert loaded == [rec]

    def test_trace_ctx_roundtrip(self, tmp_path):
        self._roundtrip(tmp_path, {
            "event": "trace_ctx", "trace_id": "r:req:a", "kind": "req",
            "key": "a", "tenant": "t", "t_wall": 1.0, "t_mono": 2.0,
        })

    def test_device_memory_roundtrip(self, tmp_path):
        self._roundtrip(tmp_path, {
            "event": "device_memory", "hbm_peak_bytes": 123,
            "n_devices": 4, "hbm_in_use_bytes": 7,
            "hbm_limit_bytes": 999, "per_device": {"d0": {"peak": 1}},
        })

    def test_capture_triggered_roundtrip(self, tmp_path):
        self._roundtrip(tmp_path, {
            "event": "capture_triggered", "reason": "stall",
            "trace_id": "r:step:5", "step": 5, "n_steps": 2,
            "profile_dir": "/p", "flight_path": "/f",
        })

    def test_health_digest_roundtrip(self, tmp_path):
        self._roundtrip(tmp_path, {
            "event": "health_digest", "role": "replica", "key": "2",
            "t": 0.35, "seq": 6, "counters": {"ticks": 40.0},
            "gauges": {"occupancy": 0.5},
            "hists": {"tick_ms": {"alpha": 0.01, "count": 0,
                                  "sum": 0.0, "zero": 0,
                                  "buckets": {}}},
            "alpha": 0.01, "step_s": 0.008, "watermark_s": 0.009,
            "period_s": 0.05,
        })

    def test_digest_stale_roundtrip(self, tmp_path):
        self._roundtrip(tmp_path, {
            "event": "digest_stale", "role": "replica", "key": "1",
            "age_s": 3.2, "stale_after_s": 2.0, "last_t": 0.4,
            "last_seq": 7,
        })

    def test_slo_burn_roundtrip(self, tmp_path):
        self._roundtrip(tmp_path, {
            "event": "slo_burn", "burn_fast": 12.0, "burn_slow": 10.5,
            "threshold": 5.0, "budget": 0.01, "fast_window_s": 0.5,
            "slow_window_s": 2.0, "error_rate_fast": 0.12,
            "error_rate_slow": 0.105, "good": 300.0, "bad": 40.0,
            "budget_remaining": -10.76, "reason": "fleet_itl_slo",
            "t": 1.25, "trace_id": "r:slo:diurnal",
        })

    def test_live_plane_names_are_registered(self):
        # The live-plane satellite: its kinds + span ride the same
        # canonical tables the AST lint below walks -- pinned here so
        # a schema refactor cannot drop them silently.
        for kind in ("health_digest", "digest_stale", "slo_burn"):
            assert kind in schema_mod.EVENTS, kind
        assert "digest_publish" in schema_mod.SPANS

    def test_new_kinds_stay_closed(self):
        with pytest.raises(schema_mod.SchemaError, match="unknown"):
            validate_record(schema_mod.stamp({
                "event": "trace_ctx", "trace_id": "a", "kind": "req",
                "key": "k", "bogus": 1,
            }))
        with pytest.raises(schema_mod.SchemaError, match="unknown"):
            validate_record(schema_mod.stamp({
                "event": "slo_burn", "burn_fast": 1.0,
                "burn_slow": 1.0, "threshold": 5.0, "budget": 0.01,
                "bogus": 1,
            }))


def _literal_str(node):
    return (
        node.value
        if isinstance(node, ast.Constant)
        and isinstance(node.value, str)
        else None
    )


class TestSchemaNameLint:
    """Every span name / event kind used in-tree must be registered
    in the canonical schema tables -- silent namespace drift is how
    telemetry cardinality explodes as subsystems grow."""

    def _tree_calls(self):
        for path in glob.glob(
            os.path.join(REPO, "tpu_hpc", "**", "*.py"),
            recursive=True,
        ):
            src = open(path).read()
            tree = ast.parse(src, filename=path)
            for node in ast.walk(tree):
                yield path, node

    def test_every_span_name_is_registered(self):
        bad = []
        for path, node in self._tree_calls():
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = (
                func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else ""
            )
            if name not in ("span", "emit_span", "_emit_span"):
                continue
            if not node.args:
                continue
            lit = _literal_str(node.args[0])
            if lit is not None and lit not in schema_mod.SPANS:
                bad.append((path, node.lineno, lit))
        assert not bad, (
            f"span names not in obs.schema.SPANS: {bad} -- register "
            "them (with a description) or reuse a canonical name"
        )

    def test_every_emitted_kind_is_registered(self):
        bad = []
        for path, node in self._tree_calls():
            lit = None
            if isinstance(node, ast.Call):
                func = node.func
                name = (
                    func.attr if isinstance(func, ast.Attribute)
                    else func.id if isinstance(func, ast.Name) else ""
                )
                if name == "emit" and node.args:
                    lit = _literal_str(node.args[0])
            elif isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if k is not None and _literal_str(k) == "event":
                        lit = _literal_str(v)
            if lit is not None and lit not in schema_mod.EVENTS:
                bad.append((path, node.lineno, lit))
        assert not bad, (
            f"event kinds not in obs.schema.EVENTS: {bad}"
        )

    def test_span_table_documents_every_name(self):
        for name, desc in schema_mod.SPANS.items():
            assert desc and isinstance(desc, str), name


# ---------------------------------------------------------------------
# device-memory satellite
# ---------------------------------------------------------------------
class _FakeDevice:
    def __init__(self, name, peak):
        self._name, self._peak = name, peak

    def memory_stats(self):
        return {
            "bytes_in_use": self._peak // 2,
            "bytes_limit": 4 * self._peak,
            "peak_bytes_in_use": self._peak,
        }

    def __str__(self):
        return self._name


class TestDeviceMemory:
    def test_summary_emits_event_and_gauge(self, scoped_obs, tmp_path):
        from tpu_hpc.profiling import device_memory_summary

        bus, reg = scoped_obs
        sink = str(tmp_path / "mem.jsonl")
        stats = device_memory_summary(
            devices=[_FakeDevice("d0", 100), _FakeDevice("d1", 300)],
            emit=True, sink=sink,
        )
        assert set(stats) == {"d0", "d1"}
        recs = load_records(sink)
        assert len(recs) == 1 and recs[0]["event"] == "device_memory"
        assert recs[0]["hbm_peak_bytes"] == 300
        assert recs[0]["n_devices"] == 2
        assert reg.gauge("hbm_peak_bytes") == 300.0
        # The report's memory section and the regress namespace see it.
        rep = build_report(recs)
        assert rep["memory"]["hbm_peak_bytes"] == 300
        flat = report_metrics(rep)
        assert flat["memory.hbm_peak_bytes"] == 300.0
        assert lower_is_better("memory.hbm_peak_bytes")

    def test_no_stats_no_emit(self, scoped_obs):
        from tpu_hpc.profiling import device_memory_summary

        class NoStats:
            def memory_stats(self):
                return None

        bus, reg = scoped_obs
        assert device_memory_summary(devices=[NoStats()]) is None
        assert reg.gauge("hbm_peak_bytes") is None


# ---------------------------------------------------------------------
# AnomalyCapture unit behavior
# ---------------------------------------------------------------------
class TestAnomalyCapture:
    def test_one_shot_bundle_and_rearm(self, scoped_obs, tmp_path):
        bus, _ = scoped_obs
        cap = AnomalyCapture(str(tmp_path / "prof"), n_steps=2)
        sink = str(tmp_path / "cap.jsonl")
        rec = cap.trigger(
            "stall", trace_id="trace-test:step:9", step=9, sink=sink
        )
        assert rec is not None and rec["event"] == "capture_triggered"
        assert rec["trace_id"] == "trace-test:step:9"
        assert rec["flight_path"] and os.path.exists(rec["flight_path"])
        # The flight dump filename is keyed by the trace key.
        assert ".9." in os.path.basename(rec["flight_path"])
        # One-shot: an anomaly storm gets one bundle.
        assert cap.trigger("stall", step=10, sink=sink) is None
        assert cap.captures == 1 and not cap.armed
        cap.step(11)
        cap.close()
        cap.rearm()
        assert cap.armed
        recs = load_records(sink)
        kinds = [r["event"] for r in recs]
        assert kinds.count("capture_triggered") == 1

    def test_flight_dump_falls_back_to_capture_dir(self, tmp_path):
        """--capture-dir promises flight evidence even when no
        TPU_HPC_FLIGHT_DIR is armed: with an unconfigured bus, the
        dump lands under the capture's own profile dir instead of
        silently never happening."""
        bus = obs.EventBus(path=None, run_id="nofd", flight_dir=None)
        prev = obs.set_bus(bus)
        try:
            cap = AnomalyCapture(str(tmp_path / "prof"), n_steps=1)
            rec = cap.trigger(
                "stall", trace_id="nofd:tick:3", arm_profiler=False
            )
        finally:
            obs.set_bus(prev)
        assert rec["flight_path"]
        assert rec["flight_path"].startswith(str(tmp_path / "prof"))
        assert os.path.exists(rec["flight_path"])
        assert ".3." in os.path.basename(rec["flight_path"])

    def test_rearm_never_renumbers_into_old_bundle(
        self, scoped_obs, tmp_path
    ):
        """Evidence must not clobber evidence: after a rearm, the
        next capture's profiler dir continues the lifetime numbering
        (capture2), never re-using capture1."""
        cap = AnomalyCapture(str(tmp_path / "prof"), n_steps=1)
        r1 = cap.trigger("stall", step=1)
        cap.close()
        cap.rearm()
        r2 = cap.trigger("stall", step=2)
        cap.close()
        assert cap.captures == 2
        dirs = {r["profile_dir"] for r in (r1, r2) if r["profile_dir"]}
        assert len(dirs) == len(
            [r for r in (r1, r2) if r["profile_dir"]]
        ), (r1["profile_dir"], r2["profile_dir"])

    def test_post_run_trigger_never_arms_a_profiler(
        self, scoped_obs, tmp_path
    ):
        """arm_profiler=False (the SLO-breach-at-summary path): the
        bundle is flight dump + memory snapshot only -- there are no
        future steps to ever close a profiler window, so none may
        open (a leaked open trace blocks every later start_trace in
        the process)."""
        cap = AnomalyCapture(str(tmp_path / "prof"), n_steps=4)
        rec = cap.trigger("slo_breach", arm_profiler=False)
        assert rec is not None
        assert rec.get("profile_dir") is None
        assert rec["n_steps"] == 0
        assert cap._prof is None
        assert rec["flight_path"]

    def test_validation(self, tmp_path):
        with pytest.raises(ValueError, match="n_steps"):
            AnomalyCapture(str(tmp_path), n_steps=0)
        with pytest.raises(ValueError, match="max_captures"):
            AnomalyCapture(str(tmp_path), max_captures=0)


# ---------------------------------------------------------------------
# analyzer units + CLI contract
# ---------------------------------------------------------------------
def _stamped(rec):
    return schema_mod.stamp(rec, run_id="r", host="h", pid=1)


class TestAnalyzer:
    def test_orphan_spans_counted(self):
        anchored = [
            _stamped({"event": "lg_arrival", "rid": "a", "tenant": "t",
                      "arrival_ms": 0.0, "trace_id": "r:req:a"}),
            _stamped({"event": "span", "name": "prefill_chunk",
                      "dur_s": 0.01, "trace_id": "r:req:a"}),
            _stamped({"event": "span", "name": "prefill_chunk",
                      "dur_s": 0.01, "trace_id": "r:req:GHOST"}),
        ]
        traces = build_traces(anchored)
        assert traces["orphan_spans"] == 1
        # Step spans are self-anchoring -- no lifecycle needed.
        steps = [_stamped({
            "event": "span", "name": "compute", "dur_s": 0.5,
            "trace_id": "r:step:3", "step": 3,
        })]
        assert build_traces(steps)["orphan_spans"] == 0
        rep = analyze(steps)
        assert rep["steps"]["count"] == 1
        assert rep["steps"]["critical_path"]["p95"]["dominant"] == (
            "compute"
        )

    def test_json_cli_contract(self, tmp_path, capsys):
        p = tmp_path / "run.jsonl"
        recs = [
            _stamped({"event": "trace_ctx", "trace_id": "r:req:a",
                      "kind": "req", "key": "a"}),
            _stamped({"event": "lg_arrival", "rid": "a", "tenant": "t",
                      "arrival_ms": 0.0, "trace_id": "r:req:a"}),
            _stamped({"event": "lg_admit", "rid": "a", "tenant": "t",
                      "queue_ms": 1.0, "trace_id": "r:req:a"}),
            _stamped({"event": "lg_first_token", "rid": "a",
                      "tenant": "t", "ttft_ms": 5.0,
                      "trace_id": "r:req:a"}),
            _stamped({"event": "lg_finish", "rid": "a", "tenant": "t",
                      "tokens": 3, "total_ms": 9.0,
                      "trace_id": "r:req:a"}),
        ]
        p.write_text("".join(json.dumps(r) + "\n" for r in recs))
        chrome = tmp_path / "chrome.json"
        rc = trace_main([str(p), "--json", "--chrome", str(chrome)])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        # The --json contract other drivers key on.
        for key in ("schema_version", "run_id", "n_records",
                    "orphan_spans", "requests", "steps", "captures"):
            assert key in out, key
        assert out["schema_version"] == schema_mod.SCHEMA_VERSION
        assert out["orphan_spans"] == 0
        req = out["requests"]
        assert req["count"] == 1 and req["complete"] == 1
        for q in ("p50", "p95", "p99"):
            assert q in req["ttft_ms"]
            cp = req["ttft_critical_path"][q]
            for key in ("rid", "ttft_ms", "phases_ms", "shares",
                        "dominant", "attributed"):
                assert key in cp, key
        ct = json.loads(chrome.read_text())
        assert ct["traceEvents"], "empty chrome trace"
        phases = [e["name"] for e in ct["traceEvents"]
                  if e.get("ph") == "X"]
        assert "queue" in phases and "decode" in phases

    def test_cli_rejects_missing_and_empty(self, tmp_path, capsys):
        assert trace_main([str(tmp_path / "nope.jsonl")]) == 2
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        assert trace_main([str(empty)]) == 2

    def test_cli_merges_flight_dumps(self, scoped_obs, tmp_path,
                                     capsys):
        bus, _ = scoped_obs
        with activate("trace-test:step:1"):
            obs.emit_span("compute", 0.25, bus=bus, step=1)
        bus.dump_flight("merge_test")
        run = tmp_path / "run.jsonl"
        run.write_text(json.dumps(_stamped({
            "event": "span", "name": "ckpt", "dur_s": 0.01,
            "trace_id": "trace-test:step:1",
        })) + "\n")
        rc = trace_main([
            str(run), "--flight-dir", str(tmp_path), "--json",
        ])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        # The ring's compute span merged with the sink's ckpt span
        # into ONE step trace.
        cp = out["steps"]["critical_path"]["p95"]
        assert set(cp["phases_ms"]) == {"compute", "ckpt"}

    def test_merge_dedupes_sink_and_flight_copies(
        self, scoped_obs, tmp_path, capsys
    ):
        """The bus writes ONE stamped record to both the sink and the
        flight ring; merging a run log with its dumps must not count
        that record twice (doubled span durations would corrupt every
        phase share). Two dumps of the same ring must not triple it."""
        bus, _ = scoped_obs
        run = tmp_path / "run.jsonl"
        with activate("trace-test:step:5"):
            obs.emit_span(
                "compute", 0.5, bus=bus, step=5, sink=str(run)
            )
        bus.dump_flight("dedup_a")
        bus.dump_flight("dedup_b")
        rc = trace_main([
            str(run), "--flight-dir", str(tmp_path), "--json",
        ])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        cp = out["steps"]["critical_path"]["p95"]
        assert cp["phases_ms"] == {"compute": 500.0}


# ---------------------------------------------------------------------
# loadgen end to end: complete traces, fault attribution, capture
# ---------------------------------------------------------------------
class TestLoadgenTraces:
    def test_steady_run_has_complete_traces(
        self, slab_engine, scoped_obs, tmp_path
    ):
        path = tmp_path / "steady.jsonl"
        _run(slab_engine, "steady", path)
        rep = _assert_complete_traces(path, 16)
        assert rep["captures"] == []

    def test_injected_fault_names_the_phase(
        self, slab_engine, scoped_obs, tmp_path
    ):
        """The sim-mesh smoke: a prefill_delay fault must surface as
        the critical path naming prefill -- the analyzer turns the
        injected latency into an attributed, named phase."""
        clean = tmp_path / "clean.jsonl"
        _run(slab_engine, "steady", clean)
        clean_rep = analyze(load_records(str(clean)))
        faulted = tmp_path / "faulted.jsonl"
        _run(slab_engine, "steady", faulted, faults="prefill_delay=6")
        rep = _assert_complete_traces(faulted, 16)
        cp = rep["requests"]["ttft_critical_path"]["p50"]
        assert cp["dominant"] == "prefill", cp
        grew = (
            rep["requests"]["phase_totals_ms"]["prefill"]
            / clean_rep["requests"]["phase_totals_ms"]["prefill"]
        )
        assert grew > 4.0, grew

    def test_stall_triggers_exactly_one_capture(
        self, slab_engine, scoped_obs, tmp_path
    ):
        """Colocation theft trips the stall watermark -> exactly one
        bounded profiler capture + flight dump, correlated by the
        triggering tick's trace id."""
        cap = AnomalyCapture(str(tmp_path / "prof"), n_steps=3)
        path = tmp_path / "colocate.jsonl"
        summary, harness = _run(
            slab_engine, "colocate", path, capture=cap, n=24
        )
        assert summary["stall_events"] >= 1
        assert cap.captures == 1
        # The summary is the join point banked rows and on-disk
        # evidence must agree on.
        assert summary["captures"] == 1
        recs = load_records(str(path))
        caps = [
            r for r in recs if r["event"] == "capture_triggered"
        ]
        assert len(caps) == 1
        cap_rec = caps[0]
        assert cap_rec["reason"] == "stall"
        # Correlation: the capture is keyed by a stall event's trace.
        stall_tids = {
            r["trace_id"] for r in recs if r["event"] == "stall"
        }
        assert cap_rec["trace_id"] in stall_tids
        assert os.path.exists(cap_rec["flight_path"])
        if cap_rec.get("profile_dir"):
            assert os.path.isdir(cap_rec["profile_dir"])
        # The bounded window closed by itself (no leaked trace).
        assert cap._prof is None
        # The analyzer surfaces the capture next to the timelines.
        rep = analyze(recs)
        assert [c["reason"] for c in rep["captures"]] == ["stall"]

    def test_clean_run_never_captures(
        self, slab_engine, scoped_obs, tmp_path
    ):
        cap = AnomalyCapture(str(tmp_path / "prof"), n_steps=3)
        path = tmp_path / "clean.jsonl"
        summary, _ = _run(slab_engine, "steady", path, capture=cap)
        assert cap.captures == 0
        assert summary["captures"] == 0
        recs = load_records(str(path))
        assert not [
            r for r in recs if r["event"] == "capture_triggered"
        ]


# ---------------------------------------------------------------------
# the two acceptance engines: speculative + disagg-paged
# ---------------------------------------------------------------------
class TestSpecAndDisaggTraces:
    def test_decode_heavy_spec_trace_complete_zero_recompiles(
        self, tiny_params, scoped_obs, tmp_path, devices
    ):
        from tpu_hpc.serve import (
            PagedConfig, PagedEngine, ServeConfig, SpecConfig,
            attach_spec,
        )

        mesh = build_mesh(MeshSpec(axes={"data": 4, "model": 2}))
        engine = PagedEngine(
            tiny_params, TINY,
            ServeConfig(slots=4, max_seq_len=48,
                        prefill_buckets=(8, 16)),
            mesh,
            PagedConfig(block_size=4, num_blocks=48, prefill_chunk=8),
        )
        attach_spec(engine, SpecConfig(mode="ngram", k=3))
        engine.warmup()
        before = engine.compile_count_total
        path = tmp_path / "decode_heavy.jsonl"
        summary, _ = _run(engine, "decode_heavy", path)
        # Trace propagation must not cost a single recompile.
        assert engine.compile_count_total == before
        assert summary["spec_mode"] == "ngram"
        rep = _assert_complete_traces(path, 16)
        assert rep["requests"]["complete"] == 16

    def test_shared_prefix_disagg_paged_trace_complete(
        self, tiny_params, scoped_obs, tmp_path, devices
    ):
        from tpu_hpc.serve import (
            DisaggEngine, PagedConfig, ServeConfig,
            split_serving_meshes,
        )

        small = llama2.LlamaConfig(
            dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            vocab_size=128, multiple_of=16, max_seq_len=64,
            dtype=jnp.float32,
        )
        pm, dm = split_serving_meshes(8, small)
        engine = DisaggEngine(
            tiny_params, small,
            ServeConfig(slots=4, max_seq_len=48,
                        prefill_buckets=(8, 16)),
            pm, dm,
            paged=PagedConfig(block_size=4, num_blocks=48,
                              prefill_chunk=8),
        )
        engine.warmup()
        before = engine.compile_count
        path = tmp_path / "shared_prefix.jsonl"
        summary, _ = _run(engine, "shared_prefix", path)
        assert engine.compile_count == before
        assert summary["prefix_hit_rate"] > 0.0
        _assert_complete_traces(path, 16)
        # Ring-only detail (engine spans, kv_block page events, the
        # disagg kv hop) joined the traces ambiently.
        bus, _ = scoped_obs
        ring = list(bus.ring())
        tagged_kv = [
            e for e in ring
            if e.get("event") == "kv_block" and "trace_id" in e
        ]
        assert tagged_kv, "kv_block ring events lost their trace ids"
        hop = [
            e for e in ring
            if e.get("event") == "span"
            and e.get("name") == "kv_transfer"
        ]
        assert hop and all("trace_id" in e for e in hop), (
            "the disagg KV hop must join the request trace"
        )


# ---------------------------------------------------------------------
# server CLI: the misplaced-flag discipline for --capture-dir
# ---------------------------------------------------------------------
class TestServerCaptureFlag:
    def test_capture_dir_requires_loadgen(self, capsys):
        from tpu_hpc.serve import server

        with pytest.raises(SystemExit):
            server.main(["--capture-dir", "/tmp/x"])
        assert "--loadgen" in capsys.readouterr().err


# ---------------------------------------------------------------------
# trainer: step traces + straggler-fault capture
# ---------------------------------------------------------------------
def _forward(params, model_state, batch, step_rng):
    x, y = batch
    pred = x @ params["w"]
    return jnp.mean((pred - y) ** 2), model_state, {}


class _LinearDS:
    def batch_at(self, step, bs):
        k = jax.random.key(int(step) % 97)
        x = jax.random.normal(k, (bs, 4), jnp.float32)
        return x, x @ jnp.arange(4.0)


class TestTrainerCapture:
    def _fit(self, tmp_path, monkeypatch, faults=None,
             stall_factor=None):
        from tpu_hpc.config import TrainingConfig
        from tpu_hpc.train import Trainer

        if faults:
            monkeypatch.setenv("TPU_HPC_FAULTS", faults)
        else:
            monkeypatch.delenv("TPU_HPC_FAULTS", raising=False)
        metrics = str(tmp_path / "run.jsonl")
        mesh1 = build_mesh(
            MeshSpec(axes={"data": 1}), devices=jax.devices()[:1]
        )
        cfg = TrainingConfig(
            epochs=9, steps_per_epoch=1, global_batch_size=8,
            learning_rate=1e-2, metrics_path=metrics,
            capture_on_anomaly=True, capture_steps=2,
            profile_dir=str(tmp_path / "prof"),
        )
        tr = Trainer(
            cfg, mesh1, _forward,
            {"w": jnp.zeros((4,), jnp.float32)},
        )
        if stall_factor is not None:
            # Deterministic clean run: millisecond chunks on a busy
            # CI host can legitimately breach the default 3x
            # watermark on scheduler noise alone; a huge factor pins
            # "no stall => no capture" without depending on machine
            # quiet.
            tr.stall = obs.StallDetector(factor=stall_factor)
        tr.fit(_LinearDS())
        return tr, load_records(metrics)

    def test_straggler_fault_triggers_one_capture(
        self, tmp_path, monkeypatch, scoped_obs
    ):
        tr, recs = self._fit(
            tmp_path, monkeypatch,
            faults="straggler_ms=400,straggler_at_step=7,on_attempt=-1",
        )
        stalls = [r for r in recs if r["event"] == "stall"]
        assert stalls and all("trace_id" in r for r in stalls)
        caps = [
            r for r in recs if r["event"] == "capture_triggered"
        ]
        assert len(caps) == 1, (
            "exactly one capture per run (one-shot latch)"
        )
        cap = caps[0]
        assert cap["trace_id"] == stalls[0]["trace_id"]
        assert os.path.exists(cap["flight_path"])
        # Trainer phase spans carry per-step trace ids and the
        # analyzer reconstructs step timelines from them.
        rep = analyze(recs)
        assert rep["orphan_spans"] == 0
        steps = rep["steps"]
        assert steps["count"] >= 8
        # The straggler chunks (step >= 7, the injected 400 ms sleep)
        # must show up as step traces whose critical path names
        # compute -- the sleep lands inside the metered compute
        # window by design (the chaos-matrix contract). Pinned on the
        # specific chunks, not the p99 pick: first-chunk compile time
        # can legitimately be the run's slowest step.
        traces = build_traces(recs)
        strag = [
            st for st in traces["steps"].values()
            if st.step >= 7 and st.wall_ms > 300
        ]
        assert strag, "injected straggler chunks missing from traces"
        for st in strag:
            assert st.breakdown()["dominant"] == "compute"
        # The capture window closed with the run (no leaked trace).
        assert tr.capture is not None and tr.capture._prof is None

    def test_bad_capture_steps_fails_at_construction(self, devices):
        """The fail-at-construction discipline: a degenerate
        capture_steps must not survive until a mid-fit traceback
        after full bring-up."""
        from tpu_hpc.config import TrainingConfig
        from tpu_hpc.train import Trainer

        mesh1 = build_mesh(
            MeshSpec(axes={"data": 1}), devices=jax.devices()[:1]
        )
        cfg = TrainingConfig(
            epochs=1, steps_per_epoch=1, global_batch_size=8,
            capture_on_anomaly=True, capture_steps=0,
        )
        with pytest.raises(ValueError, match="capture_steps"):
            Trainer(
                cfg, mesh1, _forward,
                {"w": jnp.zeros((4,), jnp.float32)},
            )

    def test_clean_run_no_capture(
        self, tmp_path, monkeypatch, scoped_obs
    ):
        tr, recs = self._fit(tmp_path, monkeypatch, stall_factor=1e6)
        assert not [
            r for r in recs if r["event"] == "capture_triggered"
        ]
        assert tr.capture is not None and tr.capture.captures == 0
