"""Tests for models, datasets, and losses."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hpc.models import datasets, losses
from tpu_hpc.models.unet import UNetConfig, apply_unet, init_unet


class TestDatasets:
    def test_era5_shapes_and_determinism(self):
        ds = datasets.ERA5Synthetic(n_vars=2, n_levels=3, lat=45, lon=90)
        x, y = ds.batch_at(0, 4)
        assert x.shape == (4, 45, 90, 6)
        x2, _ = ds.batch_at(0, 4)
        np.testing.assert_array_equal(np.asarray(x), np.asarray(x2))
        x3, _ = ds.batch_at(1, 4)
        assert not np.array_equal(np.asarray(x), np.asarray(x3))

    def test_token_stream_shift(self):
        ds = datasets.TokenStream(vocab_size=100, seq_len=16)
        inp, tgt = ds.batch_at(0, 2)
        assert inp.shape == (2, 16) and tgt.shape == (2, 16)
        np.testing.assert_array_equal(np.asarray(inp[:, 1:]), np.asarray(tgt[:, :-1]))

    def test_shard_batch(self, mesh8):
        ds = datasets.ToyRegression()
        batch = ds.batch_at(0, 16)
        sb = datasets.shard_batch(batch, mesh8)
        assert len(sb[0].addressable_shards) == 8


class TestLosses:
    def test_latitude_weights(self):
        w = losses.latitude_weights(181)
        assert w.shape == (181,)
        # poles get ~zero weight, equator max; normalized to mean 1
        assert float(w[0]) < 1e-6 and float(w[90]) > 1.0
        assert float(w.mean()) == pytest.approx(1.0, rel=1e-5)

    def test_lat_weighted_mse_matches_plain_when_uniform(self):
        # For predictions equal everywhere except a lat-independent
        # perturbation, weighting by mean-1 weights keeps the value.
        x = jnp.ones((2, 5, 4, 3))
        y = jnp.zeros_like(x)
        lw = losses.lat_weighted_mse(x, y)
        assert float(lw) == pytest.approx(1.0, rel=1e-5)

    def test_cross_entropy_matches_optax(self):
        import optax

        logits = jax.random.normal(jax.random.key(0), (4, 7, 13))
        targets = jax.random.randint(jax.random.key(1), (4, 7), 0, 13)
        ours = losses.cross_entropy(logits, targets)
        ref = optax.softmax_cross_entropy_with_integer_labels(
            logits, targets
        ).mean()
        np.testing.assert_allclose(float(ours), float(ref), rtol=1e-5)


class TestUNet:
    def test_odd_grid_roundtrip(self):
        """The reference kept bilinear upsampling precisely to survive
        odd grid sizes like 181 lat (multinode_ddp_unet.py:203-213)."""
        cfg = UNetConfig(in_channels=6, out_channels=6, base_features=8)
        params, ms = init_unet(jax.random.key(0), cfg, (45, 90, 6))
        x = jnp.ones((2, 45, 90, 6))
        out, new_ms = apply_unet(params, ms, x, cfg, train=True)
        assert out.shape == (2, 45, 90, 6)
        assert "batch_stats" in new_ms

    def test_eval_mode_uses_running_stats(self):
        cfg = UNetConfig(in_channels=3, out_channels=3, base_features=4)
        params, ms = init_unet(jax.random.key(0), cfg, (16, 16, 3))
        x = jax.random.normal(jax.random.key(1), (2, 16, 16, 3))
        out_eval, ms2 = apply_unet(params, ms, x, cfg, train=False)
        assert ms2 is ms  # eval does not mutate state
        out_eval2, _ = apply_unet(params, ms, x, cfg, train=False)
        np.testing.assert_array_equal(np.asarray(out_eval), np.asarray(out_eval2))


class TestLlamaCacheBounds:
    """The module-level lru_caches in models/llama2.py must be bounded
    (a long-lived server sees many shapes/configs) and safe to evict:
    every entry recomputes from its key alone."""

    def test_caches_are_bounded(self):
        from tpu_hpc.models import llama2

        for fn in (
            llama2._make_embed_lookup,
            llama2.count_params,
            llama2.count_params_by_part,
        ):
            assert fn.cache_info().maxsize == llama2._CACHE_MAXSIZE

    def test_embed_lookup_eviction_is_value_safe(self):
        from tpu_hpc.models import llama2

        table = jnp.arange(12, dtype=jnp.float32).reshape(6, 2)
        tokens = jnp.asarray([[1, 4, 4]], jnp.int32)
        before = llama2._make_embed_lookup(6, "float32")
        want = np.asarray(before(table, tokens))
        # steady state: the same key returns the SAME callable (stable
        # jit identity -- no retrace between calls)
        assert llama2._make_embed_lookup(6, "float32") is before
        # force eviction with > maxsize fresh keys
        for v in range(1000, 1000 + llama2._CACHE_MAXSIZE + 4):
            llama2._make_embed_lookup(v, "float32")
        after = llama2._make_embed_lookup(6, "float32")
        assert after is not before  # evicted -> rebuilt...
        np.testing.assert_array_equal(
            np.asarray(after(table, tokens)), want
        )  # ...but value-identical, gradient factory included
        g = jax.grad(
            lambda t: after(t, tokens).sum()
        )(table)
        assert g.shape == table.shape
        np.testing.assert_array_equal(
            np.asarray(g[4]), np.asarray([2.0, 2.0])
        )

    def test_count_params_eviction_recomputes_identically(self):
        from tpu_hpc.models import llama2

        cfg = llama2.LlamaConfig(
            dim=32, n_layers=1, n_heads=2, vocab_size=64,
            multiple_of=16, max_seq_len=16,
        )
        n = llama2.count_params(cfg)
        assert llama2.count_params.cache_info().currsize <= \
            llama2._CACHE_MAXSIZE
        # Eviction = the entry disappears and the next call recomputes
        # from the key alone; cache_clear IS that removal, without
        # paying maxsize eval_shape calls to churn it out naturally.
        llama2.count_params.cache_clear()
        llama2.count_params_by_part.cache_clear()
        assert llama2.count_params(cfg) == n
        parts = llama2.count_params_by_part(cfg)
        assert parts["per_layer"] * cfg.n_layers + parts["embed"] \
            + parts["head"] + parts["other"] == n
