"""Tests for models, datasets, and losses."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hpc.models import datasets, losses
from tpu_hpc.models.unet import UNetConfig, apply_unet, init_unet


class TestDatasets:
    def test_era5_shapes_and_determinism(self):
        ds = datasets.ERA5Synthetic(n_vars=2, n_levels=3, lat=45, lon=90)
        x, y = ds.batch_at(0, 4)
        assert x.shape == (4, 45, 90, 6)
        x2, _ = ds.batch_at(0, 4)
        np.testing.assert_array_equal(np.asarray(x), np.asarray(x2))
        x3, _ = ds.batch_at(1, 4)
        assert not np.array_equal(np.asarray(x), np.asarray(x3))

    def test_token_stream_shift(self):
        ds = datasets.TokenStream(vocab_size=100, seq_len=16)
        inp, tgt = ds.batch_at(0, 2)
        assert inp.shape == (2, 16) and tgt.shape == (2, 16)
        np.testing.assert_array_equal(np.asarray(inp[:, 1:]), np.asarray(tgt[:, :-1]))

    def test_shard_batch(self, mesh8):
        ds = datasets.ToyRegression()
        batch = ds.batch_at(0, 16)
        sb = datasets.shard_batch(batch, mesh8)
        assert len(sb[0].addressable_shards) == 8


class TestLosses:
    def test_latitude_weights(self):
        w = losses.latitude_weights(181)
        assert w.shape == (181,)
        # poles get ~zero weight, equator max; normalized to mean 1
        assert float(w[0]) < 1e-6 and float(w[90]) > 1.0
        assert float(w.mean()) == pytest.approx(1.0, rel=1e-5)

    def test_lat_weighted_mse_matches_plain_when_uniform(self):
        # For predictions equal everywhere except a lat-independent
        # perturbation, weighting by mean-1 weights keeps the value.
        x = jnp.ones((2, 5, 4, 3))
        y = jnp.zeros_like(x)
        lw = losses.lat_weighted_mse(x, y)
        assert float(lw) == pytest.approx(1.0, rel=1e-5)

    def test_cross_entropy_matches_optax(self):
        import optax

        logits = jax.random.normal(jax.random.key(0), (4, 7, 13))
        targets = jax.random.randint(jax.random.key(1), (4, 7), 0, 13)
        ours = losses.cross_entropy(logits, targets)
        ref = optax.softmax_cross_entropy_with_integer_labels(
            logits, targets
        ).mean()
        np.testing.assert_allclose(float(ours), float(ref), rtol=1e-5)


class TestUNet:
    def test_odd_grid_roundtrip(self):
        """The reference kept bilinear upsampling precisely to survive
        odd grid sizes like 181 lat (multinode_ddp_unet.py:203-213)."""
        cfg = UNetConfig(in_channels=6, out_channels=6, base_features=8)
        params, ms = init_unet(jax.random.key(0), cfg, (45, 90, 6))
        x = jnp.ones((2, 45, 90, 6))
        out, new_ms = apply_unet(params, ms, x, cfg, train=True)
        assert out.shape == (2, 45, 90, 6)
        assert "batch_stats" in new_ms

    def test_eval_mode_uses_running_stats(self):
        cfg = UNetConfig(in_channels=3, out_channels=3, base_features=4)
        params, ms = init_unet(jax.random.key(0), cfg, (16, 16, 3))
        x = jax.random.normal(jax.random.key(1), (2, 16, 16, 3))
        out_eval, ms2 = apply_unet(params, ms, x, cfg, train=False)
        assert ms2 is ms  # eval does not mutate state
        out_eval2, _ = apply_unet(params, ms, x, cfg, train=False)
        np.testing.assert_array_equal(np.asarray(out_eval), np.asarray(out_eval2))
