"""The topology-aware collective planner (tpu_hpc.comm.planner).

Load-bearing guarantees:

  * the topology fingerprint is stable across process restarts (the
    on-disk cost-table cache key must survive a relaunch) and moves
    when the topology does;
  * the analytic alpha-beta fallback is sane: cost strictly increases
    with bytes, the DCN tier is strictly costlier than ICI at equal
    bytes, and the flat-vs-hierarchical decision crosses over exactly
    once (flat below, hierarchical above);
  * a fixed measured table yields deterministic decisions, drives the
    decision (a steep table flips the model's verdict), and a
    corrupt/partial table file degrades to the fallback with a warning
    instead of crashing its consumer;
  * comm-bench rows carry the fingerprint + dtype the tables key on;
  * Trainer comm_mode="auto" is numerically step-identical to flat,
    emits a schema-stamped comm_plan event, and the resolved
    decomposition is confirmed in compiled HLO (collective counts
    equal an explicitly-configured trainer's);
  * reshard plans accept max_inflight_bytes="auto" and stay
    bit-identical to the unbounded move;
  * the CLI guards follow the misplaced-flag discipline.
"""
import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_hpc.checks import hlo
from tpu_hpc.comm import planner
from tpu_hpc.config import TrainingConfig
from tpu_hpc.models import datasets, llama2
from tpu_hpc.obs import schema
from tpu_hpc.runtime import MeshSpec, build_mesh
from tpu_hpc.train import Trainer

MODEL = llama2.LlamaConfig(
    dim=64, n_layers=2, n_heads=4, vocab_size=128, multiple_of=32,
    max_seq_len=32,
)


@pytest.fixture(scope="module")
def params():
    return llama2.init_llama(jax.random.key(0), MODEL)


@pytest.fixture(scope="module")
def token_ds():
    return datasets.TokenStream(vocab_size=128, seq_len=32)


def _steep_table(fp: planner.TopologyFingerprint) -> planner.CostTable:
    """A measured table whose all_reduce cost grows superlinearly --
    small buckets are disproportionately cheap, so the planner's
    bucketed pipeline beats one flat collective."""
    t = planner.CostTable(fingerprint=fp.canonical(), digest=fp.digest)
    t.add("all_reduce", "float32", 64 * 1024, 1e-5)
    t.add("all_reduce", "float32", 8 * 2 ** 20, 1e-1)
    return t


# -- fingerprint -------------------------------------------------------
class TestFingerprint:
    def test_stable_across_process_restarts(self):
        prog = (
            "import os;"
            "os.environ['JAX_PLATFORMS']='cpu';"
            "os.environ['XLA_FLAGS']="
            "'--xla_force_host_platform_device_count=8';"
            "from tpu_hpc.comm import planner;"
            "print(planner.fingerprint_devices().digest)"
        )
        digests = {
            subprocess.run(
                [sys.executable, "-c", prog],
                capture_output=True, text=True, check=True,
            ).stdout.strip()
            for _ in range(2)
        }
        assert len(digests) == 1
        # ... and equal to this process's view of the same topology.
        assert digests == {planner.fingerprint_devices().digest}

    def test_mesh_and_devices_agree(self, mesh8, mesh_2d):
        # The fingerprint is a function of the DEVICE SET: every mesh
        # over the full sim must key the same table (flat and
        # hierarchical rows of one sweep land in one file).
        fd = planner.fingerprint_devices().digest
        assert planner.fingerprint_mesh(mesh8).digest == fd
        assert planner.fingerprint_mesh(mesh_2d).digest == fd

    def test_modeled_slices_change_the_digest(self):
        one = planner.fingerprint_devices()
        two = planner.fingerprint_devices(slices=2)
        assert one.digest != two.digest
        assert not one.two_tier and two.two_tier

    def test_canonical_axes_follow_two_tier_spec(self):
        fp = planner.fingerprint_devices()
        assert dict(fp.axes) == {"dcn": 2, "ici": 4}


# -- the analytic fallback ---------------------------------------------
class TestModel:
    def test_cost_strictly_increases_with_bytes(self):
        fp2 = planner.fingerprint_devices(slices=2)
        sizes = [2 ** k for k in range(10, 31, 2)]
        for op in ("all_reduce", "all_gather", "hier_all_reduce",
                   "transfer"):
            costs = [planner.model_cost(op, s, fp2) for s in sizes]
            assert all(
                b > a for a, b in zip(costs, costs[1:])
            ), (op, costs)

    def test_dcn_costlier_than_ici_at_equal_bytes(self):
        for nbytes in (0, 1024, 2 ** 20, 2 ** 30):
            assert planner.tier_cost("dcn", nbytes) > planner.tier_cost(
                "ici", nbytes
            )

    def test_crossover_flat_below_hier_above(self):
        pl = planner.Planner.for_devices(
            slices=2, table_dir="/nonexistent"
        )
        modes = [
            pl.plan("all_reduce", s).mode
            for s in (4096, 65536, 2 ** 20, 2 ** 24, 2 ** 28)
        ]
        assert modes[0] == "flat"
        assert modes[-1] == "hierarchical"
        # Exactly one crossover: once hierarchical, always (the
        # decomposition's advantage grows with bytes).
        flips = sum(
            1 for a, b in zip(modes, modes[1:]) if a != b
        )
        assert flips == 1, modes

    def test_single_tier_topology_never_offers_hier(self):
        pl = planner.Planner.for_devices(table_dir="/nonexistent")
        d = pl.plan("all_reduce", 2 ** 28)
        assert d.mode == "flat"
        assert [c["mode"] for c in d.candidates] == ["flat"]


# -- measured tables ---------------------------------------------------
class TestTable:
    def test_decisions_deterministic_for_fixed_table(
        self, mesh8, tmp_path
    ):
        fp = planner.fingerprint_mesh(mesh8)
        _steep_table(fp).save(str(tmp_path))
        mk = lambda: planner.Planner.for_mesh(  # noqa: E731
            mesh8, table_dir=str(tmp_path)
        )
        a = mk().plan_grad_sync(4 * 2 ** 20)
        b = mk().plan_grad_sync(4 * 2 ** 20)
        assert a.summary() == b.summary()
        assert mk().plan("all_reduce", 12345).summary() == \
            mk().plan("all_reduce", 12345).summary()

    def test_measured_table_drives_the_decision(self, mesh8, tmp_path):
        fp = planner.fingerprint_mesh(mesh8)
        _steep_table(fp).save(str(tmp_path))
        pl = planner.Planner.for_mesh(mesh8, table_dir=str(tmp_path))
        d = pl.plan_grad_sync(4 * 2 ** 20)
        assert d.source == "measured"
        assert d.mode == "bucketed_overlap"
        assert d.bucket_bytes < 4 * 2 ** 20
        # The same payload with no table: the model keeps flat at this
        # size -- the table, not the fallback, made the call.
        bare = planner.Planner.for_mesh(
            mesh8, table_dir="/nonexistent"
        ).plan_grad_sync(64 * 1024)
        assert bare.source == "model"

    def test_roundtrip_preserves_lookups(self, mesh8, tmp_path):
        fp = planner.fingerprint_mesh(mesh8)
        t = _steep_table(fp)
        path = t.save(str(tmp_path))
        back = planner.load_table(path)
        for n in (1000, 64 * 1024, 2 ** 20, 64 * 2 ** 20):
            assert back.lookup("all_reduce", "float32", n) == \
                pytest.approx(t.lookup("all_reduce", "float32", n))

    def test_corrupt_table_degrades_with_warning(
        self, mesh8, tmp_path, caplog
    ):
        fp = planner.fingerprint_mesh(mesh8)
        path = tmp_path / f"{fp.digest}.json"
        path.write_text("{definitely not json")
        with caplog.at_level("WARNING", logger="tpu_hpc.comm.planner"):
            pl = planner.Planner.for_mesh(
                mesh8, table_dir=str(tmp_path)
            )
        assert pl.table is None
        assert any(
            "corrupt cost table" in r.getMessage()
            for r in caplog.records
        )
        # ... and the planner still answers, honestly labeled.
        assert pl.plan("all_reduce", 2 ** 20).source == "model"

    def test_partial_table_degrades_too(self, mesh8, tmp_path, caplog):
        fp = planner.fingerprint_mesh(mesh8)
        path = tmp_path / f"{fp.digest}.json"
        path.write_text(json.dumps({
            "table_version": planner.TABLE_VERSION,
            "fingerprint": fp.canonical(),
            # "digest" and "entries" missing: a torn write survived.
        }))
        with caplog.at_level("WARNING", logger="tpu_hpc.comm.planner"):
            pl = planner.Planner.for_mesh(
                mesh8, table_dir=str(tmp_path)
            )
        assert pl.table is None
        assert pl.plan_grad_sync(2 ** 20).source == "model"

    def test_explicit_corrupt_table_is_fatal(self, tmp_path):
        # --table PATH names a specific file: silently falling back
        # would run a different experiment than the flag claims.
        bad = tmp_path / "t.json"
        bad.write_text("[]")
        with pytest.raises(planner.CostTableError):
            planner.load_table(str(bad))

    def test_inventory_states(self, mesh8, tmp_path):
        fp = planner.fingerprint_mesh(mesh8)
        empty = tmp_path / "empty"
        assert planner.table_inventory(str(empty))["status"] == "absent"
        other = tmp_path / "other"
        other.mkdir()
        (other / "feedfeedfeed.json").write_text("{}")
        assert planner.table_inventory(str(other))["status"] == "stale"
        _steep_table(fp).save(str(tmp_path))
        inv = planner.table_inventory(str(tmp_path))
        assert inv["status"] == "measured"
        assert inv["entries"] == 2
        assert "all_reduce" in inv["ops"]
        assert fp.digest in planner.format_inventory(inv)


# -- bench rows feed the tables ---------------------------------------
class TestBenchRows:
    def test_rows_carry_fingerprint_and_dtype(self, mesh8):
        from tpu_hpc.comm.bench import CommBenchmark

        recs = CommBenchmark(
            mesh=mesh8, sizes=(1000,), warmup=0, iters=1,
            ops=("all_reduce",),
        ).run()
        fp = planner.fingerprint_mesh(mesh8)
        assert recs[0]["dtype"] == "float32"
        assert recs[0]["fingerprint"] == fp.digest
        table = planner.CostTable.from_rows(recs, fingerprint=fp)
        assert table.lookup("all_reduce", "float32", 4000) is not None
        assert table.lookup("all_reduce", "bfloat16", 4000) is None

    def test_from_rows_rejects_fingerprintless_rows(self):
        with pytest.raises(planner.CostTableError):
            planner.CostTable.from_rows(
                [{"op": "all_reduce", "bytes_per_shard": 10,
                  "mean_s": 1.0}]
            )


# -- the Trainer consumer ---------------------------------------------
class TestTrainerAuto:
    def _losses(self, mode, mesh, ds, params, metrics_path="",
                comm_plan=None, bucket_mb=1):
        cfg = TrainingConfig(
            global_batch_size=8, steps_per_epoch=1, epochs=1,
            learning_rate=1e-2, comm_mode=mode,
            comm_bucket_mb=bucket_mb, metrics_path=metrics_path,
        )
        tr = Trainer(
            cfg, mesh, llama2.make_forward(MODEL, lambda t: t),
            params, batch_pspec=P("data"), comm_plan=comm_plan,
        )
        out = [
            float(jax.device_get(tr.train_step(ds.batch_at(s, 8))["loss"]))
            for s in range(3)
        ]
        return out, tr

    def test_auto_matches_flat_and_logs_decision(
        self, mesh8, params, token_ds, tmp_path, monkeypatch
    ):
        monkeypatch.setenv(
            planner.ENV_TABLE_DIR, str(tmp_path / "none")
        )
        flat, _ = self._losses("flat", mesh8, token_ds, params)
        mp = str(tmp_path / "run.jsonl")
        auto, tr = self._losses(
            "auto", mesh8, token_ds, params, metrics_path=mp
        )
        # No table, small payload: the model keeps flat -- and the
        # step is identical because it IS the flat step.
        assert tr.comm_mode_resolved == "flat"
        assert tr.comm_plan.source == "model"
        np.testing.assert_allclose(auto, flat, rtol=1e-5, atol=1e-5)
        recs = schema.load_records(mp)  # schema-validates every line
        (cp,) = [r for r in recs if r["event"] == "comm_plan"]
        assert cp["mode"] == "flat"
        assert cp["resolved_from"] == "auto"
        assert cp["payload_bytes"] > 0
        assert cp["fingerprint"] == \
            planner.fingerprint_mesh(mesh8).digest

    def test_auto_measured_table_resolves_manual_and_matches_flat(
        self, mesh8, params, token_ds, tmp_path, monkeypatch
    ):
        fp = planner.fingerprint_mesh(mesh8)
        _steep_table(fp).save(str(tmp_path))
        monkeypatch.setenv(planner.ENV_TABLE_DIR, str(tmp_path))
        flat, _ = self._losses("flat", mesh8, token_ds, params)
        auto, tr = self._losses("auto", mesh8, token_ds, params)
        assert tr.comm_mode_resolved == "bucketed_overlap"
        assert tr.comm_plan.source == "measured"
        # Acceptance pin: the planner-chosen decomposition trains
        # step-identically to flat (float-reassociation tolerance,
        # the PR-3 parity contract).
        np.testing.assert_allclose(auto, flat, rtol=1e-5, atol=1e-5)

    def test_auto_decomposition_confirmed_in_compiled_hlo(
        self, mesh8, params, token_ds, tmp_path, monkeypatch
    ):
        # The planner's decision must be what actually lowered: the
        # auto step's compiled collective counts equal an explicitly
        # configured trainer's at the planner's bucket size, and
        # differ from flat's (the buckets really split the sync).
        fp = planner.fingerprint_mesh(mesh8)
        _steep_table(fp).save(str(tmp_path))
        monkeypatch.setenv(planner.ENV_TABLE_DIR, str(tmp_path))
        _, tr_auto = self._losses("auto", mesh8, token_ds, params)
        assert tr_auto.comm_mode_resolved == "bucketed_overlap"

        from tpu_hpc.comm import overlap as ov

        n_buckets = len(ov.assign_buckets(
            jax.tree.leaves(params), tr_auto.comm_plan.bucket_bytes
        ))
        assert n_buckets > 1
        batch = jax.device_put(
            token_ds.batch_at(0, 8), NamedSharding(mesh8, P("data"))
        )
        auto_counts = hlo.collective_counts(
            hlo.compiled_text(tr_auto._step_impl, tr_auto.state, batch)
        )
        monkeypatch.delenv(planner.ENV_TABLE_DIR)
        tr_flat = Trainer(
            TrainingConfig(
                global_batch_size=8, steps_per_epoch=1, epochs=1,
                learning_rate=1e-2,
            ),
            mesh8, llama2.make_forward(MODEL, lambda t: t),
            params, batch_pspec=P("data"),
        )
        flat_counts = hlo.collective_counts(
            hlo.compiled_text(tr_flat._step_impl, tr_flat.state, batch)
        )
        # Bucketed sync = exactly one all-reduce per bucket + the
        # loss pmean (the shard_map program is explicit about its
        # collectives) -- and a different program than flat's, where
        # GSPMD inserts one reduction per gradient leaf instead.
        assert auto_counts["all-reduce"] == n_buckets + 1
        assert auto_counts["all-reduce"] != flat_counts["all-reduce"]

    def test_auto_zero_steady_state_recompiles(
        self, mesh8, params, token_ds, tmp_path, monkeypatch
    ):
        # The scanned epoch program is chunk-length invariant under
        # auto: the planner resolves once at build, never per step.
        monkeypatch.setenv(
            planner.ENV_TABLE_DIR, str(tmp_path / "none")
        )
        cfg = TrainingConfig(
            global_batch_size=8, steps_per_epoch=2, epochs=1,
            learning_rate=1e-2, comm_mode="auto",
        )
        tr = Trainer(
            cfg, mesh8, llama2.make_forward(MODEL, lambda t: t),
            params, batch_pspec=P("data"),
        )
        epoch1 = hlo.collective_counts(
            tr._get_epoch_fn(token_ds, 1).as_text()
        )
        epoch2 = hlo.collective_counts(
            tr._get_epoch_fn(token_ds, 2).as_text()
        )
        assert epoch2 == epoch1
        assert len(tr._epoch_fns) == 2  # one per chunk length, cached

    def test_sharded_plan_forces_flat(self, mesh8, params, tmp_path,
                                      monkeypatch):
        monkeypatch.setenv(
            planner.ENV_TABLE_DIR, str(tmp_path / "none")
        )
        from tpu_hpc.parallel import fsdp

        specs = fsdp.param_pspecs(params, axis_size=8, min_size=100)
        d = planner.plan_trainer_grad_sync(
            mesh8, P("data"), specs, params
        )
        assert d.mode == "flat"
        assert d.source == "constraint"
        assert "sharded" in d.reason

    def test_unsyncable_batch_pspec_names_the_right_cause(
        self, mesh8, params, tmp_path, monkeypatch
    ):
        # Replicated params + a batch pspec that shards no axis: the
        # comm_plan reason must blame the pspec, not the params --
        # the event exists to send the operator to the RIGHT knob.
        monkeypatch.setenv(
            planner.ENV_TABLE_DIR, str(tmp_path / "none")
        )
        d = planner.plan_trainer_grad_sync(
            mesh8, P(), jax.tree.map(lambda _: P(), params), params
        )
        assert d.mode == "flat"
        assert d.source == "constraint"
        assert "batch pspec" in d.reason
        assert "sharded" not in d.reason


# -- the reshard consumer ---------------------------------------------
class TestReshardAuto:
    def test_auto_bound_resolves_and_stays_bit_identical(self, mesh8):
        import jax.numpy as jnp

        from tpu_hpc import reshard

        x = jax.device_put(
            jnp.arange(8 * 4096, dtype=jnp.float32).reshape(8, 4096),
            NamedSharding(mesh8, P("data")),
        )
        tgt = NamedSharding(mesh8, P(None, "data"))
        plan = reshard.plan_reshard(
            {"x": x}, {"x": tgt}, max_inflight_bytes="auto"
        )
        assert isinstance(plan.max_inflight_bytes, int)
        assert plan.inflight_source == "planner"
        s = plan.summary()
        assert s["inflight_source"] == "planner"
        assert s["predicted_cost_ms"] > 0
        ref = reshard.plan_reshard({"x": x}, {"x": tgt})
        np.testing.assert_array_equal(
            np.asarray(plan.execute({"x": x})["x"]),
            np.asarray(ref.execute({"x": x})["x"]),
        )

    def test_auto_bound_is_deterministic(self, mesh8):
        import jax.numpy as jnp

        from tpu_hpc import reshard

        x = jax.ShapeDtypeStruct(
            (8, 1 << 20), jnp.float32,
            sharding=NamedSharding(mesh8, P("data")),
        )
        tgt = NamedSharding(mesh8, P(None, "data"))
        bounds = {
            reshard.plan_reshard(
                {"x": x}, {"x": tgt}, max_inflight_bytes="auto"
            ).max_inflight_bytes
            for _ in range(2)
        }
        assert len(bounds) == 1

    def test_non_int_bound_rejected(self, mesh8):
        import jax.numpy as jnp

        from tpu_hpc import reshard

        x = jax.ShapeDtypeStruct(
            (8, 8), jnp.float32,
            sharding=NamedSharding(mesh8, P("data")),
        )
        with pytest.raises(TypeError, match="'auto'"):
            reshard.plan_reshard(
                {"x": x}, {"x": NamedSharding(mesh8, P(None, "data"))},
                max_inflight_bytes="automatic",
            )


# -- the disagg consumer ----------------------------------------------
class TestDisaggAuto:
    def test_auto_sizes_the_kv_hop(self):
        import jax.numpy as jnp

        from tpu_hpc.serve.disagg import (
            DisaggEngine,
            split_serving_meshes,
        )
        from tpu_hpc.serve.engine import ServeConfig

        tiny = llama2.LlamaConfig(
            dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            vocab_size=128, multiple_of=16, max_seq_len=64,
            dtype=jnp.float32,
        )
        scfg = ServeConfig(
            slots=4, max_seq_len=48, prefill_buckets=(8, 16)
        )
        pm, dm = split_serving_meshes(8, tiny)
        eng = DisaggEngine(
            llama2.init_llama(jax.random.key(0), tiny), tiny, scfg,
            pm, dm, max_inflight_bytes="auto",
        )
        # Resolved at construction: an int the reshard plans can
        # consume, provenance recorded in the tier summary.
        assert isinstance(eng.max_inflight_bytes, int)
        assert eng.max_inflight_bytes > 0
        assert eng.inflight_source == "planner"
        assert eng.describe()["inflight_source"] == "planner"


# -- CLI ---------------------------------------------------------------
class TestPlannerCLI:
    def test_explain_prints_decision_and_source(self, capsys):
        rc = planner.main(
            ["--explain", "all_reduce", "1048576", "--slices", "2"]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "mode=hierarchical" in out
        assert "alpha-beta fallback" in out
        assert "flat" in out  # the losing candidate is shown too

    def test_explain_json(self, capsys):
        rc = planner.main(
            ["--explain", "grad_sync", "16777216", "--slices", "2",
             "--json"]
        )
        assert rc == 0
        d = json.loads(capsys.readouterr().out)
        assert d["mode"] in (
            "flat", "bucketed_overlap", "hierarchical"
        )
        assert d["op"] == "grad_sync"

    def test_sweep_shows_the_crossover(self, tmp_path, capsys):
        out = tmp_path / "sweep.jsonl"
        rc = planner.main([
            "--sweep", "4096", "65536", "1048576", "16777216",
            "--slices", "2", "--output", str(out),
        ])
        assert rc == 0
        rows = [json.loads(l) for l in out.read_text().splitlines()]
        for r in rows:
            schema.validate_record(r)
        modes = [r["mode"] for r in rows]
        assert modes[0] == "flat" and modes[-1] == "hierarchical"
        # metric names carry the size (the bank-gate lesson).
        assert rows[0]["metric"] == "comm_planner_all_reduce_n4096_pred_ms"

    def test_misplaced_flags_error(self):
        with pytest.raises(SystemExit):
            planner.main(["--output", "/tmp/x.jsonl"])  # no action
        with pytest.raises(SystemExit):
            planner.main([
                "--explain", "all_reduce", "100",
                "--output", "/tmp/x.jsonl",  # --output needs --sweep
            ])
        with pytest.raises(SystemExit):
            planner.main([
                "--explain", "all_reduce", "100",
                "--table", "a.json", "--table-dir", "b",
            ])

    def test_bench_comm_table_requires_auto(self):
        import bench

        with pytest.raises(SystemExit):
            bench.main(["--workload", "llama", "--comm-table",
                        "t.json", "--steps", "1"])

    def test_bench_comm_mode_auto_needs_sync_workload(self):
        import bench

        with pytest.raises(SystemExit):
            bench.main(["--workload", "serve", "--comm-mode", "auto"])

    def test_serve_inflight_auto_requires_disagg(self):
        from tpu_hpc.serve import server

        with pytest.raises(SystemExit):
            server.main([
                "--disagg-max-inflight-mb", "auto", "--requests", "1",
            ])
        with pytest.raises(SystemExit):
            server.main([
                "--disagg", "--disagg-max-inflight-mb", "nope",
                "--requests", "1",
            ])
        with pytest.raises(SystemExit):
            server.main([
                "--disagg", "--disagg-max-inflight-mb", "0",
                "--requests", "1",
            ])

    def test_serve_inflight_auto_survives_the_range_check(self):
        # Regression (caught live): the >= 1 range check compared the
        # raw flag value, and "auto" < 1 is a TypeError -- the guard
        # must skip the sentinel. Pair "auto" with a LATER parse error
        # (--kv-block-size without --paged) so a clean SystemExit
        # proves the range check let "auto" through.
        from tpu_hpc.serve import server

        with pytest.raises(SystemExit):
            server.main([
                "--disagg", "--disagg-max-inflight-mb", "auto",
                "--kv-block-size", "16", "--requests", "1",
            ])


class TestBenchResolveAuto:
    def test_resolution_matches_planner(self, tmp_path, monkeypatch):
        import bench

        monkeypatch.setenv(
            planner.ENV_TABLE_DIR, str(tmp_path / "none")
        )
        d = bench.resolve_comm_auto(MODEL)
        assert d.op == "grad_sync"
        assert d.mode in (
            "flat", "bucketed_overlap", "hierarchical"
        )
        assert d.source in ("measured", "model")
        # Exact payload: every llama param byte is accounted.
        params = llama2.init_llama(jax.random.key(0), MODEL)
        nbytes = sum(
            l.size * l.dtype.itemsize for l in jax.tree.leaves(params)
        )
        assert d.payload_bytes == nbytes
