"""Real-image vision path: prepare -> native record file -> Trainer.

The file-reader drop-in the C++ loader's header promises
(native/src/dataloader.cpp), exercised end to end: scikit-learn's real
handwritten digits -> record files -> mmap'd epoch-shuffled batches ->
int32 labels; plus the BatchNorm eval regression the real data caught
(running stats at flax's 0.99 default never converged -- eval accuracy
stayed near chance while train-mode accuracy saturated).
"""
import numpy as np
import pytest

from tpu_hpc.native import dataloader as dl
from tpu_hpc.native import vision

pytest.importorskip(
    "sklearn", reason="the bundled real dataset needs scikit-learn"
)
pytestmark = pytest.mark.skipif(
    not dl.native_available(), reason="native loader unavailable"
)


@pytest.fixture(scope="module")
def digits(tmp_path_factory):
    prefix = str(tmp_path_factory.mktemp("vis") / "digits")
    meta = vision.prepare_digits(prefix)
    return prefix, meta


class TestPrepare:
    def test_meta_and_files(self, digits):
        prefix, meta = digits
        assert meta["x_shape"] == [8, 8, 1]
        assert meta["n_classes"] == 10
        assert meta["n_train"] + meta["n_test"] == 1797
        assert vision.read_meta(prefix) == meta

    def test_split_disjoint_and_complete(self, digits):
        # Every sample lands in exactly one split: pixel-sum
        # fingerprints of train+test together must equal the source's.
        from sklearn.datasets import load_digits

        prefix, meta = digits
        want = np.sort((load_digits().images / 16.0).sum((1, 2)))
        got = []
        for split, n in (("train", meta["n_train"]),
                         ("test", meta["n_test"])):
            ds = vision.NativeImageClassDataset(
                f"{prefix}.{split}", 1, (8, 8, 1)
            )
            for i in range(n):
                x, _ = ds.batch_at(i, 1)
                got.append(float(x.sum()))
            ds.close()
        np.testing.assert_allclose(
            np.sort(np.asarray(got)), want, rtol=1e-5
        )

    def test_labels_are_int32_in_range(self, digits):
        prefix, meta = digits
        ds = vision.NativeImageClassDataset(
            prefix + ".train", 64, tuple(meta["x_shape"])
        )
        _, y = ds.batch_at(0, 64)
        assert y.dtype == np.int32 and y.shape == (64,)
        assert 0 <= y.min() and y.max() < meta["n_classes"]
        ds.close()

    def test_epoch_visits_every_sample_once(self, digits):
        prefix, meta = digits
        n = meta["n_test"]
        ds = vision.NativeImageClassDataset(
            prefix + ".test", 1, tuple(meta["x_shape"])
        )
        sums = sorted(
            float(ds.batch_at(i, 1)[0].sum()) for i in range(n)
        )
        sums2 = sorted(
            float(ds.batch_at(n + i, 1)[0].sum()) for i in range(n)
        )
        assert np.allclose(sums, sums2)  # epoch 2 = same set, reshuffled
        ds.close()

    def test_npz_source(self, tmp_path):
        x = np.random.default_rng(0).normal(size=(20, 4, 4)).astype(
            np.float32
        )
        y = np.arange(20) % 3
        npz = tmp_path / "d.npz"
        np.savez(npz, x=x, y=y)
        meta = vision.prepare_digits(
            str(tmp_path / "own"), npz_path=str(npz)
        )
        assert meta["x_shape"] == [4, 4, 1]
        assert meta["n_classes"] == 3


class TestBatchNormEvalRegression:
    def test_eval_mode_tracks_train_mode(self, digits):
        # The regression: with flax's default momentum 0.99 the
        # running stats stayed ~30% at init after 100 steps and
        # eval-mode predictions were near chance while train-mode hit
        # 100%. With the torch-parity 0.9 they must agree.
        import jax
        import jax.numpy as jnp

        from tpu_hpc.models import resnet

        prefix, meta = digits
        assert resnet.BN_MOMENTUM == 0.9  # torch momentum 0.1
        cfg = resnet.ResNetConfig(depth=18)
        params, ms = resnet.init_resnet(
            jax.random.key(0), cfg, tuple(meta["x_shape"])
        )
        ds = vision.NativeImageClassDataset(
            prefix + ".train", 32, tuple(meta["x_shape"])
        )
        import optax

        opt = optax.adam(1e-3)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, ms, opt_state, x, y):
            def loss_fn(p):
                logits, new_ms = resnet.apply_resnet(
                    p, ms, x, cfg, train=True
                )
                from tpu_hpc.models.losses import cross_entropy

                return cross_entropy(logits, y), new_ms

            (loss, new_ms), g = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            upd, opt_state = opt.update(g, opt_state)
            return optax.apply_updates(params, upd), new_ms, opt_state

        for i in range(60):
            x, y = ds.batch_at(i, 32)
            params, ms, opt_state = step(
                params, ms, opt_state, jnp.asarray(x), jnp.asarray(y)
            )
        x, y = ds.batch_at(0, 32)
        logits, _ = resnet.apply_resnet(
            params, ms, jnp.asarray(x), cfg, train=False
        )
        acc = float((logits.argmax(-1) == jnp.asarray(y)).mean())
        ds.close()
        assert acc > 0.8, (
            f"eval-mode accuracy {acc} near chance: BatchNorm running "
            "stats not converging (momentum regression)"
        )


class TestPrepareAtScale:
    """CIFAR-scale augmented set: real source images, honest split."""

    @pytest.fixture(scope="class")
    def scaled(self, tmp_path_factory):
        prefix = str(tmp_path_factory.mktemp("vis50k") / "digits50k")
        meta = vision.prepare_digits_at_scale(
            prefix, n_train=600, n_test=150, size=32
        )
        return prefix, meta

    def test_meta_and_shapes(self, scaled):
        prefix, meta = scaled
        assert meta["x_shape"] == [32, 32, 1]
        assert meta["n_classes"] == 10
        assert meta["n_source_images"] == 1797
        ds = vision.NativeImageClassDataset(
            prefix + ".train", 32, tuple(meta["x_shape"])
        )
        x, y = ds.batch_at(0, 32)
        assert x.shape == (32, 32, 32, 1) and x.dtype == np.float32
        assert float(x.min()) >= 0.0 and float(x.max()) <= 1.0
        assert y.dtype == np.int32 and set(y) <= set(range(10))
        ds.close()

    def test_augmentations_vary(self, scaled):
        """Augmented images must not be byte-duplicates of each other
        (600 draws from 1437 source images would collide constantly
        if augmentation were a no-op)."""
        prefix, meta = scaled
        ds = vision.NativeImageClassDataset(
            prefix + ".train", 64, tuple(meta["x_shape"])
        )
        x, _ = ds.batch_at(0, 64)
        flat = x.reshape(64, -1)
        dists = np.linalg.norm(flat[:, None] - flat[None, :], axis=-1)
        np.fill_diagonal(dists, np.inf)
        assert dists.min() > 1e-3
        ds.close()

    def test_deterministic(self, tmp_path):
        a = str(tmp_path / "a")
        b = str(tmp_path / "b")
        vision.prepare_digits_at_scale(a, n_train=50, n_test=20)
        vision.prepare_digits_at_scale(b, n_train=50, n_test=20)
        with open(a + ".train", "rb") as fa, open(b + ".train", "rb") as fb:
            assert fa.read() == fb.read()
