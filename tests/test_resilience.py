"""The resilience subsystem, end to end on CPU.

Every claim the subsystem makes is driven through the deterministic
fault injector (tpu_hpc/resilience/faults.py) against the REAL
Trainer, the REAL Orbax checkpoints, and the REAL supervisor in
subprocesses -- the acceptance run for the package is
``TestSupervisedTraining::test_kill_restart_resume``: kill-at-step
under the supervisor, restart, resume from the latest checkpoint at a
step <= the kill point, complete, and report goodput/restart
accounting in the metrics JSONL.
"""
import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from tpu_hpc.resilience import (
    EXIT_HANG,
    EXIT_RESUMABLE,
    FaultPlan,
    HangWatchdog,
    Heartbeat,
    PreemptionGuard,
    backoff_delays,
    fault_plan_from_env,
    retry_call,
)
from tpu_hpc.resilience import faults
from tpu_hpc.resilience.supervisor import (
    Supervisor,
    run_supervised,
    unique_attempt_path,
)
from tpu_hpc.train.metrics import GoodputMeter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------
# retry/backoff
# ---------------------------------------------------------------------
class TestRetry:
    def test_jitter_bounds(self):
        """Delay k lies in [d_k, d_k*(1+jitter)] with
        d_k = min(base*2^k, max) -- the documented, testable bound."""
        base, mx, jit = 0.25, 2.0, 0.5
        delays = list(backoff_delays(6, base, mx, jit, seed=7))
        assert len(delays) == 6
        for k, d in enumerate(delays):
            dk = min(base * 2 ** k, mx)
            assert dk <= d <= dk * (1 + jit), (k, d)

    def test_deterministic_given_seed(self):
        a = list(backoff_delays(5, seed=3))
        b = list(backoff_delays(5, seed=3))
        c = list(backoff_delays(5, seed=4))
        assert a == b
        assert a != c

    def test_retry_call_recovers(self):
        calls, slept = [], []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        out = retry_call(
            flaky, retries=3, base_delay=0.1, jitter=0.0,
            sleep=slept.append, seed=0,
        )
        assert out == "ok"
        assert len(calls) == 3
        assert slept == [0.1, 0.2]  # jitter 0: exact exponential

    def test_budget_exhaustion_reraises_last(self):
        def always():
            raise ValueError("perma")

        with pytest.raises(ValueError, match="perma"):
            retry_call(
                always, retries=2, base_delay=0.0, jitter=0.0,
                sleep=lambda _: None,
            )

    def test_retry_on_filters(self):
        def boom():
            raise KeyError("not transient")

        with pytest.raises(KeyError):
            retry_call(
                boom, retries=5, retry_on=(OSError,),
                sleep=lambda _: None,
            )


# ---------------------------------------------------------------------
# fault plan
# ---------------------------------------------------------------------
class TestFaultPlan:
    def test_env_parse(self):
        env = {
            "TPU_HPC_FAULTS":
                "kill_at_step=6, stall_at_step=3, stall_s=12.5,"
                "on_attempt=1",
            "TPU_HPC_ATTEMPT": "1",
        }
        plan = fault_plan_from_env(env)
        assert plan.kill_at_step == 6
        assert plan.stall_at_step == 3
        assert plan.stall_s == 12.5
        assert plan.on_attempt == 1 and plan.attempt == 1
        assert plan.active

    def test_unset_is_none(self):
        assert fault_plan_from_env({}) is None

    def test_unknown_key_rejected(self):
        """A typo'd fault spec must not let a resilience test pass
        vacuously by injecting nothing."""
        with pytest.raises(ValueError, match="unknown fault key"):
            fault_plan_from_env({"TPU_HPC_FAULTS": "kil_at_step=3"})

    def test_attempt_scoping(self):
        plan = fault_plan_from_env({
            "TPU_HPC_FAULTS": "kill_at_step=2",
            "TPU_HPC_ATTEMPT": "1",
        })
        assert not plan.active
        plan.on_step(10)  # inactive: must be a no-op (we survive)

    def test_corrupt_checkpoint_walks_files(self, tmp_path):
        d = tmp_path / "step"
        (d / "sub").mkdir(parents=True)
        (d / "a.bin").write_bytes(b"x" * 100)
        (d / "sub" / "b.json").write_text("{}")
        plan = FaultPlan(corrupt_ckpt_at_step=5)
        assert plan.wants_ckpt_corruption(5)
        assert not plan.wants_ckpt_corruption(4)
        assert plan.corrupt_checkpoint(str(d)) == 2
        assert b"CORRUPTED" in (d / "a.bin").read_bytes()


# ---------------------------------------------------------------------
# heartbeat + watchdog
# ---------------------------------------------------------------------
class TestHeartbeat:
    def test_tick_read_roundtrip(self, tmp_path):
        path = str(tmp_path / "hb.json")
        hb = Heartbeat(path, attempt=2)
        hb.tick(17, loss=0.5)
        rec = Heartbeat.read(path)
        assert rec["step"] == 17
        assert rec["attempt"] == 2
        assert rec["pid"] == os.getpid()
        assert rec["loss"] == 0.5
        # Atomic: no tmp-file debris next to the heartbeat.
        assert os.listdir(tmp_path) == ["hb.json"]

    def test_read_torn_file_is_none(self, tmp_path):
        path = tmp_path / "hb.json"
        path.write_text('{"step": 1')  # torn mid-write
        assert Heartbeat.read(str(path)) is None
        assert Heartbeat.read(str(tmp_path / "absent")) is None

    def test_from_env_contract(self, tmp_path):
        assert Heartbeat.from_env({}) is None
        hb = Heartbeat.from_env({
            "TPU_HPC_HEARTBEAT": str(tmp_path / "h.json")
        })
        assert hb is not None


class TestHangWatchdog:
    def test_fires_without_ticks(self, tmp_path):
        fired = []
        wd = HangWatchdog(
            0.25, poll_s=0.05,
            dump_path=str(tmp_path / "hang.dump"),
            on_hang=fired.append,
        ).start()
        try:
            deadline = time.monotonic() + 5.0
            while not fired and time.monotonic() < deadline:
                time.sleep(0.05)
        finally:
            wd.stop()
        assert fired and fired[0] >= 0.25
        dump = (tmp_path / "hang.dump").read_text()
        assert "hang watchdog" in dump
        # The diagnostic must carry stacks (faulthandler output).
        assert "Thread" in dump or "File" in dump

    def test_ticks_keep_it_quiet(self):
        wd = HangWatchdog(
            0.4, poll_s=0.05, on_hang=lambda s: None
        ).start()
        try:
            for _ in range(10):
                time.sleep(0.05)
                wd.tick()
            assert not wd.fired
        finally:
            wd.stop()

    def test_dump_path_never_overwritten(self, tmp_path):
        base = tmp_path / "hang.dump"
        base.write_text("previous failure evidence")
        wd = HangWatchdog(
            0.1, poll_s=0.02, dump_path=str(base),
            on_hang=lambda s: None,
        ).start()
        try:
            deadline = time.monotonic() + 5.0
            while not wd.fired and time.monotonic() < deadline:
                time.sleep(0.02)
        finally:
            wd.stop()
        assert base.read_text() == "previous failure evidence"
        assert (tmp_path / "hang.dump.1").exists()

    def test_rejects_nonpositive_timeout(self):
        with pytest.raises(ValueError):
            HangWatchdog(0)


# ---------------------------------------------------------------------
# preemption guard + goodput
# ---------------------------------------------------------------------
class TestPreemptionGuard:
    def test_flag_and_restore(self):
        before = signal.getsignal(signal.SIGTERM)
        with PreemptionGuard() as guard:
            assert not guard.triggered
            os.kill(os.getpid(), signal.SIGTERM)
            deadline = time.monotonic() + 2.0
            while not guard.triggered and time.monotonic() < deadline:
                time.sleep(0.01)
            assert guard.triggered
        assert signal.getsignal(signal.SIGTERM) == before


class TestGoodputMeter:
    def test_buckets_and_fraction(self):
        g = GoodputMeter()
        g.add("productive", 3.0)
        g.add("ckpt", 0.5)
        with g.measure("restore"):
            time.sleep(0.01)
        s = g.summary()
        assert s["productive_s"] == 3.0
        assert s["ckpt_s"] == 0.5
        assert s["restore_s"] >= 0.01
        assert 0.0 <= s["goodput"]
        assert s["other_s"] >= 0.0

    def test_unknown_bucket_rejected(self):
        with pytest.raises(ValueError, match="unknown goodput"):
            GoodputMeter().add("coffee", 1.0)


# ---------------------------------------------------------------------
# supervisor (subprocess children, in-process supervisor loop)
# ---------------------------------------------------------------------
def _attempt_gated_cmd(threshold: int):
    """A child that fails until TPU_HPC_ATTEMPT >= threshold."""
    return [
        sys.executable, "-c",
        "import os, sys; "
        f"sys.exit(0 if int(os.environ['TPU_HPC_ATTEMPT']) >= "
        f"{threshold} else 1)",
    ]


class TestSupervisor:
    def test_restart_until_success(self, tmp_path):
        d = str(tmp_path)
        rc = run_supervised(
            _attempt_gated_cmd(2), max_restarts=3, log_dir=d,
            backoff=0.01,
        )
        assert rc == 0
        logs = sorted(
            f for f in os.listdir(d) if f.startswith("run.attempt")
        )
        assert logs == [
            "run.attempt0.log", "run.attempt1.log", "run.attempt2.log"
        ]
        events = [
            json.loads(x)
            for x in open(os.path.join(d, "supervisor.jsonl"))
        ]
        ends = [e for e in events if e["event"] == "attempt_end"]
        assert [e["rc"] for e in ends] == [1, 1, 0]

    def test_budget_exhaustion_propagates_rc(self, tmp_path):
        rc = run_supervised(
            [sys.executable, "-c", "import sys; sys.exit(3)"],
            max_restarts=1, log_dir=str(tmp_path), backoff=0.01,
        )
        assert rc == 3
        logs = [
            f for f in os.listdir(tmp_path)
            if f.startswith("run.attempt")
        ]
        assert len(logs) == 2  # initial + 1 restart, then gave up

    def test_no_restart_on_marked_codes(self, tmp_path):
        rc = run_supervised(
            [sys.executable, "-c", "import sys; sys.exit(2)"],
            max_restarts=5, log_dir=str(tmp_path), backoff=0.01,
            no_restart_on=(2,),
        )
        assert rc == 2
        logs = [
            f for f in os.listdir(tmp_path)
            if f.startswith("run.attempt")
        ]
        assert len(logs) == 1  # usage errors don't burn the budget

    def test_attempt_logs_never_overwritten(self, tmp_path):
        """VERDICT item 9: a previous supervision's failure dump in
        the same directory survives the next one."""
        d = str(tmp_path)
        prev = os.path.join(d, "run.attempt0.log")
        with open(prev, "w") as f:
            f.write("evidence from an earlier run")
        assert unique_attempt_path(d, 0) == prev + ".1"
        rc = run_supervised(
            _attempt_gated_cmd(0), max_restarts=0, log_dir=d,
        )
        assert rc == 0
        assert open(prev).read() == "evidence from an earlier run"
        assert os.path.exists(prev + ".1")

    def test_heartbeat_stall_kills_and_restarts(self, tmp_path):
        """A child wedged past the heartbeat timeout is killed and
        restarted; the stall is recorded as EXIT_HANG policy-wise."""
        hb = str(tmp_path / "hb.json")
        child = (
            "import os, sys, time\n"
            "if int(os.environ['TPU_HPC_ATTEMPT']) >= 1:\n"
            "    sys.exit(0)\n"
            "time.sleep(60)\n"  # never ticks the heartbeat: wedged
        )
        t0 = time.monotonic()
        rc = run_supervised(
            [sys.executable, "-c", child],
            max_restarts=2, log_dir=str(tmp_path), heartbeat=hb,
            heartbeat_timeout=1.5, backoff=0.01, kill_grace_s=2.0,
        )
        assert rc == 0
        assert time.monotonic() - t0 < 30  # killed, not waited out
        events = [
            json.loads(x)
            for x in open(tmp_path / "supervisor.jsonl")
        ]
        assert any(e["event"] == "heartbeat_stall" for e in events)
        ends = [
            e for e in events if e["event"] == "attempt_end"
        ]
        assert ends[0]["rc"] == EXIT_HANG
        assert ends[0]["reason"] == "heartbeat-stall"
        assert ends[-1]["rc"] == 0

    def test_stale_heartbeat_cleared_between_attempts(self, tmp_path):
        """A child that TICKED and then wedged must not poison the
        restart: the stale heartbeat file is cleared at attempt start,
        or every restarted child would be insta-killed as stalled and
        one hang would burn the whole budget."""
        hb = str(tmp_path / "hb.json")
        child = (
            "import json, os, sys, time\n"
            "if int(os.environ['TPU_HPC_ATTEMPT']) >= 1:\n"
            # Runs LONGER than several polls but SHORTER than the
            # timeout: only the stale file from attempt 0 (whose
            # mtime is already past the timeout) could get it killed.
            "    time.sleep(1.0)\n"
            "    sys.exit(0)\n"
            "json.dump({'step': 1}, open(os.environ"
            "['TPU_HPC_HEARTBEAT'], 'w'))\n"
            "time.sleep(60)\n"  # wedged after ticking
        )
        rc = run_supervised(
            [sys.executable, "-c", child],
            max_restarts=1, log_dir=str(tmp_path), heartbeat=hb,
            heartbeat_timeout=1.5, backoff=0.01, kill_grace_s=2.0,
        )
        assert rc == 0  # attempt 1 survived past the stale-file age

    def test_cli_requires_separator(self):
        from tpu_hpc.resilience.supervisor import _split_argv

        with pytest.raises(SystemExit):
            _split_argv(["python", "x.py"])
        opts, cmd = _split_argv(["--max-restarts", "2", "--", "x"])
        assert opts == ["--max-restarts", "2"]
        assert cmd == ["x"]


# ---------------------------------------------------------------------
# the real Trainer under injected faults (subprocess workers)
# ---------------------------------------------------------------------
WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    for var in ("TPU_VISIBLE_DEVICES", "TPU_CHIPS_PER_PROCESS_BOUNDS",
                "PALLAS_AXON_POOL_IPS", "AXON_POOL_SVC_OVERRIDE",
                "TPU_WORKER_HOSTNAMES"):
        os.environ.pop(var, None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from tpu_hpc import resilience
    from tpu_hpc.ckpt import CheckpointManager
    from tpu_hpc.config import TrainingConfig
    from tpu_hpc.runtime import MeshSpec, build_mesh
    from tpu_hpc.train import Trainer

    class DS:
        # Deterministic per-step batches: resume replays the exact
        # stream (host-fed path -- per-step loop inside each chunk).
        def batch_at(self, step, bs):
            k = jax.random.key(int(step) % 97)
            x = jax.random.normal(k, (bs, 4), jnp.float32)
            return x, x @ jnp.arange(4.0)

    def forward(params, model_state, batch, step_rng):
        x, y = batch
        pred = x @ params["w"]
        return jnp.mean((pred - y) ** 2), model_state, {}

    ckpt_dir = os.environ["WORK_CKPT"]
    cfg = TrainingConfig(
        epochs=int(os.environ.get("WORK_EPOCHS", "3")),
        steps_per_epoch=2, global_batch_size=8, learning_rate=1e-2,
        save_every=1, checkpoint_dir=ckpt_dir,
        metrics_path=os.environ.get("WORK_METRICS", ""),
    )
    mesh = build_mesh(
        MeshSpec(axes={"data": 1}), devices=jax.devices()[:1]
    )
    mgr = CheckpointManager(ckpt_dir, async_save=False)
    trainer = Trainer(
        cfg, mesh, forward, {"w": jnp.zeros((4,), jnp.float32)},
        checkpoint_manager=mgr,
    )
    result = trainer.fit(DS())
    print("FINAL_STEP", int(jax.device_get(trainer.state.step)),
          flush=True)
    sys.exit(resilience.exit_code_for(result["preempted"]))
""")


@pytest.fixture()
def worker(tmp_path):
    path = tmp_path / "worker.py"
    path.write_text(WORKER)

    def run(env_extra, timeout=240, argv_prefix=()):
        env = dict(os.environ)
        prev = env.get("PYTHONPATH", "")
        env["PYTHONPATH"] = REPO + (os.pathsep + prev if prev else "")
        env["WORK_CKPT"] = str(tmp_path / "ckpts")
        env["WORK_METRICS"] = str(tmp_path / "run.jsonl")
        env.update({k: str(v) for k, v in env_extra.items()})
        return subprocess.run(
            [*argv_prefix, sys.executable, str(path)],
            capture_output=True, text=True, timeout=timeout,
            env=env, cwd=REPO,
        )

    return run


def _metrics(tmp_path):
    path = tmp_path / "run.jsonl"
    if not path.exists():
        return []
    return [json.loads(x) for x in open(path)]


class TestSupervisedTraining:
    def test_kill_restart_resume(self, worker, tmp_path):
        """THE acceptance run: kill-at-step-4 under the supervisor.
        Attempt 0 checkpoints step 2, is SIGKILLed at step 4 BEFORE
        the step-4 save; attempt 1 resumes from step 2 (= N' <= N),
        re-trains the killed span, completes to step 6, and the
        metrics JSONL carries per-attempt goodput/restart accounting.
        """
        sup_dir = str(tmp_path / "sup")
        proc = worker(
            {"TPU_HPC_FAULTS": "kill_at_step=4"},
            argv_prefix=(
                sys.executable, "-m", "tpu_hpc.resilience.supervisor",
                "--max-restarts", "2", "--log-dir", sup_dir,
                "--heartbeat", str(tmp_path / "hb.json"),
                "--backoff", "0.1", "--",
            ),
        )
        assert proc.returncode == 0, proc.stderr[-3000:]

        # Supervisor accounting: SIGKILL (137) then success.
        events = [
            json.loads(x)
            for x in open(os.path.join(sup_dir, "supervisor.jsonl"))
        ]
        ends = [e for e in events if e["event"] == "attempt_end"]
        assert [e["rc"] for e in ends] == [137, 0]

        # Attempt-unique child logs; the resumed attempt completed.
        a1 = open(os.path.join(sup_dir, "run.attempt1.log")).read()
        assert "FINAL_STEP 6" in a1

        # Trainer-side restart accounting in the metrics JSONL.
        recs = _metrics(tmp_path)
        starts = [r for r in recs if r["event"] == "run_start"]
        assert len(starts) == 2
        assert starts[0]["start_step"] == 0
        # Resumed from the newest checkpoint <= the kill step: the
        # step-4 save had not happened when the kill fired.
        assert starts[1]["start_step"] == 2
        run_ends = [r for r in recs if r["event"] == "run_end"]
        assert len(run_ends) == 1  # attempt 0 died before its epilogue
        end = run_ends[0]
        assert end["attempt"] == 1
        assert end["resumed_from_step"] == 2
        assert end["step"] == 6
        assert end["preempted"] is False
        g = end["goodput"]
        assert g["goodput"] >= 0.0
        assert g["productive_s"] > 0.0
        assert g["restore_s"] > 0.0  # the resume really restored

        # The heartbeat contract was exercised under the supervisor.
        hb = Heartbeat.read(str(tmp_path / "hb.json"))
        assert hb is not None and hb["step"] == 6

    def test_preempt_emergency_save_resumable_exit(
        self, worker, tmp_path
    ):
        """SIGTERM (injected preemption notice) -> snapshot at the
        current step -> EXIT_RESUMABLE; the bare relaunch resumes and
        completes with exit 0."""
        proc = worker({"TPU_HPC_FAULTS": "preempt_at_step=2"})
        assert proc.returncode == EXIT_RESUMABLE, proc.stderr[-3000:]
        recs = _metrics(tmp_path)
        end = [r for r in recs if r["event"] == "run_end"][-1]
        assert end["preempted"] is True
        assert end["step"] == 2
        assert os.path.isdir(tmp_path / "ckpts" / "2")

        # Relaunch clean (fault scoped to attempt 0 via env ordinal).
        proc2 = worker({"TPU_HPC_ATTEMPT": "1"})
        assert proc2.returncode == 0, proc2.stderr[-3000:]
        assert "FINAL_STEP 6" in proc2.stdout
        starts = [
            r for r in _metrics(tmp_path) if r["event"] == "run_start"
        ]
        assert starts[-1]["start_step"] == 2

    def test_hang_watchdog_aborts_with_diagnostics(
        self, worker, tmp_path
    ):
        """A stalled step (wedged-collective stand-in) is aborted by
        the in-process watchdog with EXIT_HANG and a stack dump,
        instead of hanging the allocation."""
        proc = worker({
            "TPU_HPC_FAULTS": "stall_at_step=2,stall_s=120",
            "TPU_HPC_HANG_TIMEOUT": "4",
        })
        assert proc.returncode == EXIT_HANG, (
            proc.returncode, proc.stderr[-3000:]
        )
        dump = tmp_path / "ckpts" / "hang.attempt0.dump"
        assert dump.exists()
        assert "hang watchdog" in dump.read_text()

    def test_corrupt_ckpt_falls_back_to_previous(
        self, worker, tmp_path
    ):
        """corrupt_ckpt_at_step=6 garbles the FINAL snapshot of run 1
        (a torn write); run 2's restore retries, falls back to step 4,
        and still completes -- the self-healing restore path."""
        proc = worker({"TPU_HPC_FAULTS": "corrupt_ckpt_at_step=6"})
        assert proc.returncode == 0, proc.stderr[-3000:]

        proc2 = worker(
            {"TPU_HPC_ATTEMPT": "1", "WORK_EPOCHS": "4"}
        )
        assert proc2.returncode == 0, proc2.stderr[-3000:]
        assert "FINAL_STEP 8" in proc2.stdout
        starts = [
            r for r in _metrics(tmp_path) if r["event"] == "run_start"
        ]
        # Step 6 was unreadable: resumed from 4, not 6.
        assert starts[-1]["start_step"] == 4


class TestCheckpointReplay:
    def test_replay_save_below_latest_preserves_old_step(
        self, tmp_path
    ):
        """A replay save at a step BELOW the newest surviving snapshot
        (possible after restore(step) or a restore fallback): orbax
        declines the save (should_save is False when a later step
        exists), and the stashed-aside old copy must be put back, not
        deleted -- it is the only copy of that step."""
        import jax.numpy as jnp

        from tpu_hpc.ckpt import CheckpointManager

        mgr = CheckpointManager(str(tmp_path / "ck"), async_save=False)
        state = {"w": jnp.ones((4,))}
        for s in (2, 3, 4):
            assert mgr.save(state, step=s)
        assert not mgr.save({"w": jnp.full((4,), 9.0)}, step=3)
        assert 3 in mgr.all_steps()
        restored = mgr.restore(3, state)
        assert float(restored["w"][0]) == 1.0  # the ORIGINAL copy
        mgr.close()


class TestFaultHelpers:
    def test_corrupt_file(self, tmp_path):
        p = tmp_path / "data.bin"
        p.write_bytes(b"A" * 1000)
        faults.corrupt_file(str(p))
        data = p.read_bytes()
        assert data == b"\x00TPU_HPC_FAULT_CORRUPTED\x00"


def _preempt_gated_cmd(threshold: int):
    """A child that takes a clean preemption snapshot (EXIT_RESUMABLE)
    until TPU_HPC_ATTEMPT >= threshold."""
    return [
        sys.executable, "-c",
        "import os, sys; "
        f"sys.exit(0 if int(os.environ['TPU_HPC_ATTEMPT']) >= "
        f"{threshold} else 75)",
    ]


class TestResumableBudgetCarveOut:
    def test_preemptions_do_not_burn_the_failure_budget(self, tmp_path):
        """signals.py contract: EXIT_RESUMABLE means 'nothing is
        wrong, relaunch me' -- three preemptions must ride through a
        max_restarts=1 supervisor and still reach success."""
        rc = run_supervised(
            _preempt_gated_cmd(3), max_restarts=1,
            log_dir=str(tmp_path), backoff=0.01,
        )
        assert rc == 0
        events = [
            json.loads(x)
            for x in open(os.path.join(str(tmp_path), "supervisor.jsonl"))
        ]
        ends = [e for e in events if e["event"] == "attempt_end"]
        assert [e["rc"] for e in ends] == [75, 75, 75, 0]
        restarts = [e for e in events if e["event"] == "restarting"]
        assert all(
            e.get("why") == "resumable preemption snapshot"
            for e in restarts
        )

    def test_crashes_still_bounded(self, tmp_path):
        rc = run_supervised(
            [sys.executable, "-c", "import sys; sys.exit(3)"],
            max_restarts=1, log_dir=str(tmp_path), backoff=0.01,
        )
        assert rc == 3
        events = [
            json.loads(x)
            for x in open(os.path.join(str(tmp_path), "supervisor.jsonl"))
        ]
        ends = [e for e in events if e["event"] == "attempt_end"]
        assert [e["rc"] for e in ends] == [3, 3]  # 1 restart, then stop

    def test_preemption_cap_bounds_the_loop(self, tmp_path):
        """The carve-out is generous, not infinite: a preemption
        cadence outpacing checkpoints must eventually give up."""
        rc = run_supervised(
            [sys.executable, "-c", "import sys; sys.exit(75)"],
            max_restarts=5, max_preemptions=2,
            log_dir=str(tmp_path), backoff=0.01,
        )
        assert rc == 75
        events = [
            json.loads(x)
            for x in open(os.path.join(str(tmp_path), "supervisor.jsonl"))
        ]
        ends = [e for e in events if e["event"] == "attempt_end"]
        assert [e["rc"] for e in ends] == [75, 75, 75]  # cap + 1 runs
        give = [e for e in events if e["event"] == "giving_up"]
        assert "preemption budget" in give[0]["why"]
