"""Pytest configuration: simulate an 8-device TPU-like mesh on CPU.

The reference has no unit-test suite at all -- its "tests" are runtime
verification scripts that need a real cluster (see SURVEY.md section 4,
/root/reference/tests/README.md). JAX lets us do better: with
``--xla_force_host_platform_device_count=8`` every sharding recipe
(DP/FSDP/TP/PP/SP/ring/domain) is unit-testable on a laptop CPU.

Must set env vars before jax is imported anywhere.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Keep CPU compilation deterministic and quiet in CI.
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402
import pytest  # noqa: E402

# The hosting environment may pre-register an accelerator plugin that
# overrides JAX_PLATFORMS at interpreter startup (sitecustomize); force
# the simulated-CPU backend again post-import.
jax.config.update("jax_platforms", "cpu")


# fast/slow split (VERDICT item 8): the tier-1 core must stay under
# ~10 minutes on a 2-core CPU host so it runs on every change; the
# full suite (no -m filter) is the round gate. This is the measured
# slowlist -- every entry's wall time (comment) comes from a full
# --durations=0 run on the CI-class container; together they cut the
# suite from ~32 min to ~10 min while the core keeps at least one
# cheap test on every subsystem. Durable coverage note: everything
# here still runs in the unfiltered suite.
SLOW_NODEIDS = frozenset(nodeid for nodeid, _ in [
    ("tests/test_autotune.py::test_bwd_tiling_is_numerics_invariant", "10s"),
    ("tests/test_ckpt.py::test_auto_resume_continues_from_step", "14s"),
    ("tests/test_ckpt.py::test_elastic_restore_onto_smaller_mesh", "13s"),
    ("tests/test_ckpt.py::test_mid_epoch_resume_stream_alignment", "19s"),
    ("tests/test_ckpt.py::test_restore_fp32_checkpoint_into_bf16_moments_run", "8s"),
    ("tests/test_ckpt.py::test_save_restore_roundtrip", "18s"),
    ("tests/test_doctor.py::TestAccumEscalation::test_accum_raised_until_fit", "27s"),
    ("tests/test_doctor.py::TestCandidates::test_cp_only_with_long_context", "48s"),
    ("tests/test_doctor.py::TestCandidates::test_gqa_head_divisibility", "58s"),
    ("tests/test_doctor.py::TestCandidates::test_meshes_are_legal", "17s"),
    ("tests/test_doctor.py::TestOutput::test_json_mode", "12s"),
    ("tests/test_doctor.py::TestOutput::test_no_fit_verdict", "87s"),
    ("tests/test_doctor.py::TestOutput::test_tight_marker", "13s"),
    ("tests/test_doctor.py::TestRanking::test_fitting_plans_rank_above_nonfitting", "74s"),
    ("tests/test_doctor.py::TestSlices::test_markdown_names_slices", "14s"),
    ("tests/test_doctor.py::TestSlices::test_slices_filter_and_dcn_cost", "14s"),
    ("tests/test_domain_unet.py::TestDomainUNet::test_param_grads_match", "11s"),
    ("tests/test_domain_unet.py::TestDomainUNet::test_train_forward_and_stats", "12s"),
    # test_eval's module-scoped ``trained`` fixture is a full fit
    # (~2 min); ANY fast-tier test in the module drags it into the
    # fast run, so the whole fixture family rides the slow tier.
    ("tests/test_eval.py::test_evaluate_returns_loss_and_accuracy", "105s"),
    ("tests/test_eval.py::test_evaluate_deterministic", "126s"),
    ("tests/test_eval.py::test_evaluate_matches_per_step_path", "5s"),
    ("tests/test_eval.py::test_evaluate_does_not_touch_state", "2s"),
    ("tests/test_eval.py::test_eval_forward_uses_inference_mode", "2s"),
    ("tests/test_eval.py::test_fit_with_eval_dataset_records_curve", "48s"),
    ("tests/test_fit.py::TestCPLayout::test_cp_step_compiles_on_sim_mesh", "16s"),
    ("tests/test_fit.py::test_model_presets", "10s"),
    ("tests/test_fit.py::test_sizing_table_rows_fit", "15s"),
    ("tests/test_fsdp_modes.py::TestHybridShard::test_matches_dp_numerics", "11s"),
    ("tests/test_fsdp_modes.py::TestShardGradOp::test_matches_full_shard_numerics", "13s"),
    ("tests/test_grad_clip.py::TestClipTraining::test_trains_and_is_accum_invariant", "10s"),
    ("tests/test_graft_entry.py::test_dryrun_multichip_in_process", "54s"),
    ("tests/test_graft_entry.py::test_dryrun_multichip_subprocess_path", "68s"),
    ("tests/test_pp.py::TestInterleaved::test_grads_match_oracle[interleaved-1f1b]", "13s"),
    ("tests/test_pp.py::TestInterleaved::test_grads_match_oracle[interleaved]", "15s"),
    ("tests/test_pp.py::TestInterleaved::test_indivisible_microbatches_still_correct[interleaved-1f1b]", "19s"),
    ("tests/test_pp.py::TestInterleaved::test_indivisible_microbatches_still_correct[interleaved]", "20s"),
    ("tests/test_pp.py::TestInterleaved::test_interleaved_1f1b_stash_grads_match_oracle", "15s"),
    ("tests/test_pp.py::TestInterleaved::test_interleaved_stash_wraparound_and_partial_group", "20s"),
    ("tests/test_pp.py::TestInterleaved::test_ppxdp_grads_match_oracle[interleaved-1f1b]", "10s"),
    ("tests/test_pp.py::TestInterleaved::test_ppxdp_grads_match_oracle[interleaved]", "15s"),
    ("tests/test_pp.py::TestStashBackward::test_grads_match_oracle", "12s"),
    ("tests/test_pp.py::TestStashBackward::test_ppxdp_grads_match_oracle", "13s"),
    ("tests/test_pp.py::TestStashBackward::test_stash_ring_wraparound", "9s"),
    # Pallas paged-attention sweep (tests/test_paged_kernels.py):
    # tier-1 keeps the (block_size=4, float32) representative per
    # kernel family; the rest of the block-size x dtype grid rides
    # the slow tier under the ``kernels`` marker.
    ("tests/test_paged_kernels.py::TestKernelSweep::test_decode_grid[4-bfloat16]", "1s"),
    ("tests/test_paged_kernels.py::TestKernelSweep::test_decode_grid[8-float32]", "1s"),
    ("tests/test_paged_kernels.py::TestKernelSweep::test_decode_grid[8-bfloat16]", "1s"),
    ("tests/test_paged_kernels.py::TestKernelSweep::test_prefill_grid[4-bfloat16]", "1s"),
    ("tests/test_paged_kernels.py::TestKernelSweep::test_prefill_grid[8-float32]", "1s"),
    ("tests/test_paged_kernels.py::TestKernelSweep::test_prefill_grid[8-bfloat16]", "1s"),
    ("tests/test_overlap.py::TestTrainerCommMode::test_bucketed_with_grad_accum_matches_flat", "10s"),
    ("tests/test_overlap.py::TestTrainerCommMode::test_flat_mode_no_collective_creep", "14s"),
    ("tests/test_pp.py::test_grads_match_oracle[1f1b]", "10s"),
    ("tests/test_precision.py::test_trainer_preserves_param_dtype_through_updates", "31s"),
    ("tests/test_precision.py::test_unet_vit_param_dtype_follows_config", "10s"),
    ("tests/test_profiling.py::test_window_triggering", "14s"),
    ("tests/test_resnet.py::test_forward_shape[50]", "14s"),
    ("tests/test_serve.py::TestReplayServerCLI::test_main_runs_replay_and_prints_summary", "8s"),
    ("tests/test_serve.py::TestServingWeights::test_trainer_checkpoint_restores_into_serving_layout", "9s"),
    # Speculative decoding (tests/test_spec.py): the tier-1 core keeps
    # one oracle test per draft source (ngram + independent draft),
    # the churn compile pin and the CLI guards; the heavier variants
    # (batch-composition determinism, self-draft accept-all,
    # draft-mode sampled determinism, loadgen determinism, drain
    # accounting, eos/prefix-hit long streams) ride the slow tier.
    ("tests/test_serve.py::TestSpecOracle::test_spec_greedy_token_exact_hit_and_miss[draft]", "9s"),
    ("tests/test_spec.py::TestGreedyOracle::test_self_draft_accepts_everything", "9s"),
    ("tests/test_spec.py::TestGreedyOracle::test_eos_mid_acceptance_truncates_exactly", "8s"),
    ("tests/test_spec.py::TestGreedyOracle::test_prefix_hit_and_long_stream_acceptance", "7s"),
    ("tests/test_spec.py::TestSeededSampling::test_seed_changes_the_stream", "9s"),
    ("tests/test_spec.py::TestSeededSampling::test_draft_mode_sampling_deterministic", "16s"),
    ("tests/test_spec.py::TestPageAccounting::test_pools_drain_to_idle_and_invariants_hold", "9s"),
    ("tests/test_spec.py::TestServerCLI::test_loadgen_with_spec_is_deterministic", "14s"),
    # Serving fleet (tests/test_fleet.py): the tier-1 core keeps one
    # fast representative per fault class (kill/redispatch, corrupt
    # swap, slow replica, scale-down drain) plus the diurnal
    # acceptance; the 8-combination chaos sweep rides the slow tier.
    ("tests/test_fleet.py::TestChaosSweep::test_sweep_no_loss_no_shed_above_floor[kill-affinity]", "3s"),
    ("tests/test_fleet.py::TestChaosSweep::test_sweep_no_loss_no_shed_above_floor[kill-round_robin]", "3s"),
    ("tests/test_fleet.py::TestChaosSweep::test_sweep_no_loss_no_shed_above_floor[slow-affinity]", "3s"),
    ("tests/test_fleet.py::TestChaosSweep::test_sweep_no_loss_no_shed_above_floor[slow-round_robin]", "3s"),
    ("tests/test_fleet.py::TestChaosSweep::test_sweep_no_loss_no_shed_above_floor[kill_slow-affinity]", "3s"),
    ("tests/test_fleet.py::TestChaosSweep::test_sweep_no_loss_no_shed_above_floor[kill_slow-round_robin]", "3s"),
    ("tests/test_fleet.py::TestChaosSweep::test_sweep_no_loss_no_shed_above_floor[corrupt_swap-affinity]", "3s"),
    ("tests/test_fleet.py::TestChaosSweep::test_sweep_no_loss_no_shed_above_floor[corrupt_swap-round_robin]", "3s"),
    # MPMD pipeline (tests/test_mpmd.py): the tier-1 core keeps the
    # chaos acceptance's two fault classes (stage kill + stage nan,
    # both bit-identity pinned), the parity/compile pins and the
    # budget units; the heartbeat-timeout / straggler variants and
    # the flapping-stage integration (each builds its own pipeline =
    # a full per-stage AOT warmup) ride the slow tier.
    ("tests/test_mpmd.py::TestHeartbeat::test_wedged_stage_detected_by_heartbeat_timeout", "8s"),
    ("tests/test_mpmd.py::TestStraggler::test_straggler_detected_and_bubble_grows", "7s"),
    ("tests/test_mpmd.py::TestBudgets::test_flapping_stage_exhausts_own_budget", "8s"),
    # Slice remap (elastic x MPMD): the remap chaos acceptance builds
    # TWO full pipelines (clean reference + storm) and the unfired-
    # fault guard a third; the cheap construction-time guard
    # (slice_up without slice_down) stays in the fast core. The SPMD
    # morph acceptance lives in tests/test_elastic.py, whose storm
    # fixture is module-scoped and stays fast.
    ("tests/test_mpmd.py::TestSliceRemap::test_slice_loss_remaps_without_burning_budget", "23s"),
    ("tests/test_mpmd.py::TestSliceRemap::test_unfired_slice_fault_fails_loudly", "6s"),
    ("tests/test_reshard.py::TestLongShapes::test_long_shape_bounded_parity_sweep", "35s"),
    # Wall-clock re-partition (elastic PR): the grown suite crossed
    # the tier-1 870s budget on the 1-core sim machine, so each
    # variant family below keeps its FASTEST representative in the
    # fast core and the heavier variants ride the slow tier -- every
    # behavior stays pinned somewhere, tier-1 stays inside its wall.
    ("tests/test_grad_accum.py::test_matches_full_batch_step[2]", "8s"),
    ("tests/test_pp.py::test_remat_stage_numerics_unchanged[interleaved-2]", "7s"),
    ("tests/test_pp.py::test_ppxdp_grads_match_oracle[1f1b]", "6s"),
    ("tests/test_pp_llama.py::test_interleaved_matches_sequential_oracle[interleaved-1f1b]", "8s"),
    ("tests/test_pp_llama.py::test_grads_match_sequential_oracle[gpipe-remat]", "7s"),
    ("tests/test_resnet.py::test_param_counts_match_torchvision", "8s"),
    ("tests/test_resnet.py::test_forward_shape[18]", "6s"),
    ("tests/test_spec.py::TestSeededSampling::test_batch_composition_invariance", "18s"),
    ("tests/test_doctor.py::TestRanking::test_sorted_best_first", "13s"),
    ("tests/test_ckpt.py::test_cross_layout_restore_fsdp_to_dp", "7s"),
    ("tests/test_precision.py::test_resnet_param_dtype_follows_config", "6s"),
    ("tests/test_resnet.py::test_fsdp_training_step", "60s"),
    ("tests/test_run_metrics.py::TestMetricsLog::test_appends_across_runs", "13s"),
    ("tests/test_runtime.py::TestHybridMesh::test_end_to_end_train_step_over_two_slices", "12s"),
    ("tests/test_sp.py::TestFSDPWithRing::test_fsdp_cp_trainer_bitexact_vs_replicated", "29s"),
    ("tests/test_sp.py::TestZigzagDataLayout::test_loss_and_grads_match_contiguous", "30s"),
    ("tests/test_train_dp.py::TestDPTraining::test_loss_decreases", "20s"),
    ("tests/test_train_dp.py::TestDPTraining::test_params_replicated", "9s"),
    ("tests/test_train_dp.py::TestFSDPTraining::test_fsdp_training_matches_dp", "20s"),
    ("tests/test_vision.py::TestBatchNormEvalRegression::test_eval_mode_tracks_train_mode", "68s"),
])


def pytest_collection_modifyitems(config, items):
    """fast/slow split: measured-heavy tests get the ``slow`` marker
    centrally (SLOW_NODEIDS above); everything else IS the fast core,
    marked so ``-m fast`` and ``-m 'not slow'`` select the same
    suite -- one partition, no test left in neither tier."""
    for item in items:
        if item.nodeid in SLOW_NODEIDS:
            item.add_marker(pytest.mark.slow)
        if "slow" not in item.keywords:
            item.add_marker(pytest.mark.fast)
    # Self-maintenance: a renamed/re-parametrized slow test must not
    # silently drop into the fast tier. Checked per collected file so
    # single-file runs stay valid; skipped entirely for nodeid-level
    # selections or --deselect, where partial collection of a file is
    # expected (a single-test dev run must not abort on the file's
    # OTHER slowlist entries).
    if any("::" in a for a in config.args) or config.getoption(
        "deselect", None
    ):
        return
    present_files = {item.nodeid.split("::", 1)[0] for item in items}
    seen = {item.nodeid for item in items}
    stale = sorted(
        n for n in SLOW_NODEIDS
        if n.split("::", 1)[0] in present_files and n not in seen
    )
    if stale:
        raise pytest.UsageError(
            "conftest SLOW_NODEIDS entries match no collected test "
            f"(renamed? re-parametrized?): {stale}"
        )


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 simulated devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    """1D 8-way data mesh."""
    from tpu_hpc.runtime import MeshSpec, build_mesh

    return build_mesh(MeshSpec(axes={"data": 8}))


@pytest.fixture(scope="session")
def mesh_2d(devices):
    """2D (data=2, model=4) mesh, the hybrid FSDPxTP shape."""
    from tpu_hpc.runtime import MeshSpec, build_mesh

    return build_mesh(MeshSpec(axes={"data": 2, "model": 4}))
