"""Pytest configuration: simulate an 8-device TPU-like mesh on CPU.

The reference has no unit-test suite at all -- its "tests" are runtime
verification scripts that need a real cluster (see SURVEY.md section 4,
/root/reference/tests/README.md). JAX lets us do better: with
``--xla_force_host_platform_device_count=8`` every sharding recipe
(DP/FSDP/TP/PP/SP/ring/domain) is unit-testable on a laptop CPU.

Must set env vars before jax is imported anywhere.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Keep CPU compilation deterministic and quiet in CI.
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402
import pytest  # noqa: E402

# The hosting environment may pre-register an accelerator plugin that
# overrides JAX_PLATFORMS at interpreter startup (sitecustomize); force
# the simulated-CPU backend again post-import.
jax.config.update("jax_platforms", "cpu")


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 simulated devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    """1D 8-way data mesh."""
    from tpu_hpc.runtime import MeshSpec, build_mesh

    return build_mesh(MeshSpec(axes={"data": 8}))


@pytest.fixture(scope="session")
def mesh_2d(devices):
    """2D (data=2, model=4) mesh, the hybrid FSDPxTP shape."""
    from tpu_hpc.runtime import MeshSpec, build_mesh

    return build_mesh(MeshSpec(axes={"data": 2, "model": 4}))
