"""Tests for the unified TrainingConfig (parity: utils/config.py)."""
import pytest

from tpu_hpc.config import TrainingConfig


def test_defaults():
    c = TrainingConfig()
    assert c.epochs == 5
    assert c.compute_dtype == "bfloat16"


def test_from_args_overrides():
    c = TrainingConfig.from_args(
        ["--epochs", "3", "--learning-rate", "0.01", "--model-parallel", "4"]
    )
    assert c.epochs == 3
    assert c.learning_rate == 0.01
    assert c.model_parallel == 4


def test_from_args_tolerates_unknown_flags():
    c = TrainingConfig.from_args(["--epochs", "2", "--my-extra-flag", "x"])
    assert c.epochs == 2


def test_yaml_roundtrip(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text("epochs: 7\nglobal_batch_size: 64\nprofile: true\n")
    c = TrainingConfig.from_yaml(str(p))
    assert c.epochs == 7
    assert c.global_batch_size == 64
    assert c.profile is True


def test_yaml_unknown_key_rejected(tmp_path):
    p = tmp_path / "bad.yaml"
    p.write_text("nonexistent_key: 1\n")
    with pytest.raises(ValueError, match="unknown config keys"):
        TrainingConfig.from_yaml(str(p))


def test_cli_overrides_yaml(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text("epochs: 7\n")
    c = TrainingConfig.from_args(["--config", str(p), "--epochs", "9"])
    assert c.epochs == 9


def test_mesh_axes():
    c = TrainingConfig(data_parallel=2, model_parallel=4)
    assert c.mesh_axes() == {"data": 2, "model": 4}
    c2 = TrainingConfig(pipe_parallel=4, data_parallel=2)
    assert list(c2.mesh_axes()) == ["pipe", "data"]


def test_mesh_spec_dcn():
    c = TrainingConfig(
        data_parallel=2, model_parallel=2, dcn_data_parallel=2
    )
    spec = c.mesh_spec()
    assert spec.dcn_axes == {"data": 2}
    assert spec.resolved_sizes(8) == {"data": 4, "model": 2}
    # Default: single slice, no dcn axes.
    assert TrainingConfig().mesh_spec().dcn_axes == {}
    # CLI plumbing.
    c2 = TrainingConfig.from_args(["--dcn-data-parallel", "2"])
    assert c2.dcn_data_parallel == 2


def test_mesh_spec_rejects_bad_dcn():
    with pytest.raises(ValueError, match="dcn_data_parallel"):
        TrainingConfig(dcn_data_parallel=0).mesh_spec()


def test_comm_mode_fields(tmp_path):
    c = TrainingConfig()
    assert c.comm_mode == "flat"
    assert c.comm_bucket_mb == 25
    # CLI plumbing (the bench sweeps pass these through).
    c2 = TrainingConfig.from_args(
        ["--comm-mode", "bucketed_overlap", "--comm-bucket-mb", "8"]
    )
    assert c2.comm_mode == "bucketed_overlap"
    assert c2.comm_bucket_mb == 8
    # YAML roundtrip keeps the comm layer in the run snapshot.
    p = tmp_path / "cfg.yaml"
    c2.to_yaml(str(p))
    assert TrainingConfig.from_yaml(str(p)).comm_mode == "bucketed_overlap"
