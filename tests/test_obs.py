"""tpu_hpc.obs -- the unified telemetry spine.

Covers the spine itself (event bus + flight recorder, spans, metrics
registry, stall watermark, schema, report CLI) and its integration
acceptance runs: a sim-mesh training run whose JSONL validates against
the shared schema and yields a goodput/MFU/step-time report, and a
faulted run (TPU_HPC_FAULTS) that leaves a flight-recorder dump of the
last pre-fault events.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

from tpu_hpc import obs
from tpu_hpc.obs.registry import MetricsRegistry
from tpu_hpc.obs.report import build_report, format_report
from tpu_hpc.obs.report import main as report_main
from tpu_hpc.obs.schema import (
    SCHEMA_VERSION,
    SchemaError,
    stamp,
    validate_file,
    validate_record,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def bus(tmp_path):
    """A scoped process bus (file sink + flight dir in tmp), restored
    afterwards so the singleton never leaks between tests."""
    b = obs.EventBus(
        path=str(tmp_path / "events.jsonl"), run_id="test-run",
        ring_size=8, flight_dir=str(tmp_path),
    )
    prev = obs.set_bus(b)
    yield b
    obs.set_bus(prev)


@pytest.fixture()
def registry():
    """A scoped process registry, restored afterwards."""
    r = MetricsRegistry(hist_window=4)
    prev = obs.set_registry(r)
    yield r
    obs.set_registry(prev)


# ---------------------------------------------------------------------
# events.py: bus + flight recorder
# ---------------------------------------------------------------------
class TestEventBus:
    def test_emit_stamps_and_sinks(self, bus):
        rec = bus.emit("fault", kind="kill", step=3)
        assert rec["schema_version"] == SCHEMA_VERSION
        assert rec["run_id"] == "test-run"
        assert rec["host"] and rec["pid"] == os.getpid()
        assert rec["time"] > 0
        on_disk = [json.loads(x) for x in open(bus.path)]
        assert on_disk == [rec]
        validate_file(bus.path)

    def test_none_fields_dropped(self, bus):
        rec = bus.emit("fault", kind="stall", step=None)
        assert "step" not in rec

    def test_ring_is_bounded(self, bus):
        for i in range(20):
            bus.emit("fault", kind="kill", step=i)
        ring = list(bus.ring())
        assert len(ring) == 8  # ring_size
        assert [r["step"] for r in ring] == list(range(12, 20))

    def test_same_file_as_path_and_sink_written_once(self, bus):
        bus.emit("fault", kind="kill", sink=bus.path)
        assert len(open(bus.path).readlines()) == 1

    def test_dump_flight_header_and_events(self, bus, tmp_path):
        bus.emit("fault", kind="kill", step=1)
        path = bus.dump_flight("preempt")
        assert path and os.path.dirname(path) == str(tmp_path)
        recs = [json.loads(x) for x in open(path)]
        assert recs[0]["event"] == "flight_dump"
        assert recs[0]["reason"] == "preempt"
        assert recs[0]["n_events"] == 1
        assert recs[1]["event"] == "fault"
        validate_file(path)

    def test_dump_never_clobbers(self, bus):
        first = bus.dump_flight("hang")
        second = bus.dump_flight("hang")
        assert second != first and os.path.exists(first)

    def test_dump_without_destination_is_noop(self):
        b = obs.EventBus(flight_dir=None)
        assert b.dump_flight("preempt") is None

    def test_empty_string_paths_mean_off(self, tmp_path, monkeypatch):
        """'' is the documented off spelling (metrics_path='') and a
        set-but-empty env var must disable, not crash, every emit
        (review finding)."""
        monkeypatch.chdir(tmp_path)
        b = obs.EventBus(path="", flight_dir="")
        b.emit("fault", kind="kill", sink="")
        assert b.dump_flight("preempt") is None
        assert list(tmp_path.iterdir()) == []

    def test_module_level_dump_uses_current_bus(self, bus):
        bus.emit("fault", kind="kill")
        path = obs.dump_flight("kill")
        assert path and "kill" in os.path.basename(path)

    def test_fault_announce_is_one_shot(self, bus):
        """A ``step >= N`` fault match re-fires every later chunk;
        the telemetry event must not (review finding)."""
        from tpu_hpc.resilience.faults import FaultPlan

        plan = FaultPlan(stall_at_step=2, stall_s=0.0)
        for step in (2, 3, 4):
            plan.on_step(step)
        stalls = [
            r for r in bus.ring()
            if r["event"] == "fault" and r["kind"] == "stall"
        ]
        assert len(stalls) == 1 and stalls[0]["step"] == 2


# ---------------------------------------------------------------------
# spans.py
# ---------------------------------------------------------------------
class TestSpans:
    def test_nesting_records_parent_and_depth(self, bus):
        with obs.span("outer", annotate=False):
            with obs.span("inner", annotate=False):
                pass
        recs = [json.loads(x) for x in open(bus.path)]
        by = {r["name"]: r for r in recs}
        assert by["inner"]["parent"] == "outer"
        assert by["inner"]["depth"] == 1
        assert by["outer"]["depth"] == 0 and "parent" not in by["outer"]
        assert by["inner"]["dur_s"] >= 0

    def test_exception_still_emits_and_pops(self, bus):
        with pytest.raises(RuntimeError):
            with obs.span("doomed", annotate=False):
                raise RuntimeError("boom")
        recs = [json.loads(x) for x in open(bus.path)]
        assert [r["name"] for r in recs] == ["doomed"]
        # The stack unwound: a following span is top-level again.
        with obs.span("after", annotate=False):
            pass
        recs = [json.loads(x) for x in open(bus.path)]
        assert recs[-1]["depth"] == 0

    def test_emit_span_feeds_registry_histogram(self, bus, registry):
        obs.emit_span("ckpt", 0.25, hist="train_ckpt_s", step=4)
        assert registry.histogram_summary("train_ckpt_s")["count"] == 1


# ---------------------------------------------------------------------
# registry.py
# ---------------------------------------------------------------------
class TestRegistry:
    def test_counters_gauges(self, registry):
        registry.inc("steps", 2)
        registry.inc("steps")
        registry.set_gauge("loss", 0.5)
        assert registry.counter("steps") == 3
        assert registry.gauge("loss") == 0.5

    def test_counter_rejects_negative(self, registry):
        with pytest.raises(ValueError, match="gauge"):
            registry.inc("steps", -1)

    def test_histogram_is_windowed(self, registry):
        for v in range(10):
            registry.observe("lat", float(v))
        s = registry.histogram_summary("lat")
        assert s["count"] == 4  # hist_window
        assert s["min"] == 6.0 and s["max"] == 9.0

    def test_prometheus_text(self, registry):
        registry.inc("steps")
        registry.set_gauge("serve/mfu", 0.4)  # needs sanitizing
        registry.observe("ttft", 1.0)
        text = registry.prometheus_text()
        assert "# TYPE tpu_hpc_steps counter" in text
        assert "tpu_hpc_serve_mfu 0.4" in text
        assert 'tpu_hpc_ttft{quantile="0.95"} 1.0' in text
        assert "tpu_hpc_ttft_count 1" in text

    def test_exposition_format_contract(self, registry):
        """The exposition-format contract: HELP precedes TYPE for
        described metrics (escaped per the text format), histogram
        summaries always carry _sum AND _count next to the
        quantiles, and undescribed metrics emit TYPE only."""
        registry.inc("reqs", 2, help="Requests served")
        registry.set_gauge("depth", 3.0,
                           help="Queue depth\nwith \\ tricky text")
        registry.observe("lat_ms", 2.0, help="Latency (ms)")
        registry.observe("lat_ms", 4.0)
        registry.inc("plain")  # no description -> no HELP line
        lines = registry.prometheus_text().splitlines()
        idx = {ln: i for i, ln in enumerate(lines)}
        assert "# HELP tpu_hpc_reqs Requests served" in idx
        assert idx["# HELP tpu_hpc_reqs Requests served"] + 1 == (
            idx["# TYPE tpu_hpc_reqs counter"]
        )
        # Escaping: newline -> \n, backslash -> \\ (one line each).
        assert (
            "# HELP tpu_hpc_depth Queue depth\\nwith \\\\ tricky text"
            in idx
        )
        assert "# TYPE tpu_hpc_lat_ms summary" in idx
        assert "tpu_hpc_lat_ms_sum 6.0" in idx
        assert "tpu_hpc_lat_ms_count 2" in idx
        assert 'tpu_hpc_lat_ms{quantile="0.5"} 3.0' in idx
        assert 'tpu_hpc_lat_ms{quantile="0.99"}' in " ".join(lines)
        assert not any(ln.startswith("# HELP tpu_hpc_plain")
                       for ln in lines)
        assert "# TYPE tpu_hpc_plain counter" in idx
        # First description wins; re-describing is a no-op.
        registry.describe("reqs", "changed")
        assert "# HELP tpu_hpc_reqs Requests served" in (
            registry.prometheus_text()
        )

    def test_write_prometheus_atomic_and_env_gated(
        self, registry, tmp_path, monkeypatch
    ):
        monkeypatch.delenv("TPU_HPC_PROM_FILE", raising=False)
        assert registry.write_prometheus() is None  # no env: no-op
        path = str(tmp_path / "metrics.prom")
        registry.inc("x")
        assert registry.write_prometheus(path) == path
        assert "tpu_hpc_x 1.0" in open(path).read()
        assert os.listdir(tmp_path) == ["metrics.prom"]  # no tmp left

    def test_emit_snapshot_validates(self, bus, registry):
        registry.inc("steps")
        rec = registry.emit_snapshot(step=7)
        validate_record(rec)
        assert rec["metrics"]["counters"]["steps"] == 1.0


# ---------------------------------------------------------------------
# quantiles.py: the estimator the regress gate trusts
# ---------------------------------------------------------------------
class TestQuantileMath:
    """The windowed-histogram quantiles feed the regression gate; they
    are pinned EXACTLY (not approximately) to numpy's default
    percentile estimator on known distributions."""

    @pytest.mark.parametrize("dist", ["uniform", "lognormal", "normal"])
    @pytest.mark.parametrize("q", [0.0, 0.5, 0.95, 0.99, 1.0])
    def test_matches_numpy_percentile(self, dist, q):
        import numpy as np

        from tpu_hpc.obs.quantiles import quantile

        rng = np.random.default_rng(42)
        vals = {
            "uniform": rng.uniform(0, 100, size=1001),
            "lognormal": rng.lognormal(2.0, 1.0, size=997),
            "normal": rng.normal(50, 10, size=256),
        }[dist]
        got = quantile(sorted(vals.tolist()), q)
        want = float(np.percentile(vals, 100 * q))
        assert got == pytest.approx(want, rel=1e-12), (dist, q)

    def test_edge_cases(self):
        from tpu_hpc.obs.quantiles import quantile

        assert quantile([], 0.5) == 0.0
        assert quantile([3.0], 0.0) == 3.0
        assert quantile([3.0], 0.99) == 3.0
        assert quantile([1.0, 2.0], 0.5) == 1.5
        with pytest.raises(ValueError, match="must be in"):
            quantile([1.0], 1.5)

    def test_summarize_keys(self):
        from tpu_hpc.obs.quantiles import summarize

        s = summarize([5.0, 1.0, 3.0])
        assert set(s) == {"p50", "p95", "p99"}
        assert s["p50"] == 3.0

    def test_registry_histogram_matches_numpy_on_window(
        self, registry,
    ):
        """The registry's summary quantiles are over the most recent
        window only -- and on that window they ARE numpy's
        percentiles."""
        import numpy as np

        rng = np.random.default_rng(7)
        vals = rng.lognormal(1.0, 0.8, size=10).tolist()
        for v in vals:
            registry.observe("lat", v)
        window = vals[-4:]  # registry fixture: hist_window=4
        s = registry.histogram_summary("lat")
        assert s["count"] == 4
        for key, q in (("p50", 50), ("p95", 95), ("p99", 99)):
            assert s[key] == pytest.approx(
                float(np.percentile(window, q)), rel=1e-12
            ), key

    def test_serve_meter_quantiles_match_numpy(self):
        """ServeMeter's TTFT quantiles come from the same estimator
        (the gate compares meter numbers against meter numbers)."""
        import numpy as np

        from tpu_hpc.serve.metrics import ServeMeter

        t = [0.0]
        meter = ServeMeter(clock=lambda: t[0])
        rng = np.random.default_rng(3)
        ttfts = rng.uniform(0.01, 0.2, size=25)
        for i, ttft in enumerate(ttfts):
            rid = f"r{i}"
            t[0] = float(i)
            meter.submitted(rid)
            meter.admitted(rid)
            t[0] = float(i) + float(ttft)
            meter.token(rid, first=True)
            meter.finished(rid)
        s = meter.summary()
        assert s["ttft_ms_p95"] == pytest.approx(
            1e3 * float(np.percentile(ttfts, 95)), rel=1e-9
        )
        assert s["ttft_ms_p99"] == pytest.approx(
            1e3 * float(np.percentile(ttfts, 99)), rel=1e-9
        )


# ---------------------------------------------------------------------
# stall.py
# ---------------------------------------------------------------------
class TestStallDetector:
    def test_quiet_until_warm_then_flags_breach(self, bus):
        det = obs.StallDetector(window=8, factor=3.0, min_samples=5)
        for step in range(5):
            assert det.observe(step, 1.0) is None
        info = det.observe(5, 10.0)
        assert info is not None and info["ratio"] == pytest.approx(10.0)
        recs = [json.loads(x) for x in open(bus.path)]
        assert [r["event"] for r in recs] == ["stall"]
        validate_file(bus.path)

    def test_stays_slow_rebaselines(self, bus):
        det = obs.StallDetector(window=4, factor=3.0, min_samples=2)
        for step in range(4):
            det.observe(step, 1.0)
        assert det.observe(4, 10.0) is not None
        # The slow regime persists; once the window is full of it,
        # the watermark has followed and alarming stops.
        flagged = [
            det.observe(5 + i, 10.0) is not None for i in range(6)
        ]
        assert flagged[-1] is False

    def test_heartbeat_extra_only_when_known(self):
        det = obs.StallDetector(min_samples=2)
        assert det.heartbeat_extra() == {}
        det.observe(1, 0.5)
        det.observe(2, 0.5)
        extra = det.heartbeat_extra()
        assert extra["step_s"] == 0.5
        assert extra["watermark_s"] == 0.5

    def test_rejects_bad_params(self):
        with pytest.raises(ValueError):
            obs.StallDetector(factor=1.0)
        with pytest.raises(ValueError):
            obs.StallDetector(min_samples=1)
        with pytest.raises(ValueError, match="min_samples"):
            # A window smaller than min_samples can never warm up:
            # the detector would be silently off forever.
            obs.StallDetector(window=3, min_samples=5)

    def test_zero_watermark_window_never_divides(self):
        """A window full of zero-duration steps (virtual-clock ticks
        that did no metered work -- chunked prefill filling every
        slot) must read as not-warm, not as an infinite-ratio stall:
        caught live as a ZeroDivisionError in the shared_prefix paged
        loadgen run."""
        det = obs.StallDetector(window=8, factor=3.0, min_samples=2)
        for step in range(4):
            assert det.observe(step, 0.0) is None
        assert det.observe(4, 1.0) is None  # no division, no stall
        # Once real durations dominate the window, breaches fire
        # again.
        for step in range(5, 11):
            det.observe(step, 1.0)
        assert det.observe(11, 10.0) is not None


# ---------------------------------------------------------------------
# schema.py
# ---------------------------------------------------------------------
class TestSchema:
    def _ok(self, **extra):
        return stamp({"event": "fault", "kind": "kill", **extra})

    def test_valid_record_passes(self):
        validate_record(self._ok())

    def test_unknown_event_rejected(self):
        with pytest.raises(SchemaError, match="unknown event"):
            validate_record(stamp({"event": "nope"}))

    def test_missing_required_rejected(self):
        with pytest.raises(SchemaError, match="missing required"):
            validate_record(stamp({"event": "fault"}))

    def test_closed_kind_rejects_unknown_field(self):
        with pytest.raises(SchemaError, match="unknown fields"):
            validate_record(self._ok(surprise=1))

    def test_open_kind_accepts_extras(self):
        validate_record(stamp({
            "event": "bench", "metric": "m", "value": 1, "unit": "u",
            "workload": "llama", "flash_blocks": {"q": 512},
        }))

    def test_schema_version_enforced(self):
        rec = self._ok()
        rec["schema_version"] = 999
        with pytest.raises(SchemaError, match="schema_version"):
            validate_record(rec)

    def test_validate_file_names_bad_line(self, tmp_path):
        p = tmp_path / "bad.jsonl"
        p.write_text(
            json.dumps(self._ok()) + "\n" + "{not json}\n"
        )
        with pytest.raises(SchemaError, match="bad.jsonl:2"):
            validate_file(str(p))

    def test_stamp_never_overwrites(self):
        rec = stamp({"event": "fault", "kind": "kill", "time": 42.0},
                    run_id="mine")
        assert rec["time"] == 42.0 and rec["run_id"] == "mine"


# ---------------------------------------------------------------------
# report.py
# ---------------------------------------------------------------------
def _training_records():
    """A synthetic but schema-valid two-attempt run."""
    recs = [
        {"event": "run_start", "start_step": 0, "total_steps": 4,
         "n_devices": 8, "n_processes": 1, "device_kind": "cpu",
         "jax_version": "0", "run_id": "r",
         "config": {"model_flops_per_item": 1e9}},
        {"event": "span", "name": "compute", "dur_s": 8.0, "step": 2},
        {"event": "span", "name": "data", "dur_s": 1.0, "step": 2},
        {"event": "span", "name": "ckpt", "dur_s": 1.0, "step": 2},
        {"event": "epoch", "epoch": 0, "step": 2, "loss": 1.0,
         "items_per_s": 100.0, "items_per_s_per_device": 12.5,
         "s_per_step": 4.0},
        {"event": "run_end", "step": 2, "preempted": True,
         "attempt": 0, "resumed_from_step": 0,
         "goodput": {"total_s": 10.0, "productive_s": 8.0,
                     "ckpt_s": 1.0, "restore_s": 0.0, "other_s": 1.0,
                     "goodput": 0.8}},
        {"event": "stall", "step": 2, "step_s": 9.0,
         "watermark_s": 3.0, "ratio": 3.0},
        {"event": "epoch", "epoch": 1, "step": 4, "loss": 0.5,
         "items_per_s": 100.0, "items_per_s_per_device": 12.5,
         "s_per_step": 4.0},
        {"event": "run_end", "step": 4, "preempted": False,
         "attempt": 1, "resumed_from_step": 2,
         "goodput": {"total_s": 10.0, "productive_s": 9.0,
                     "ckpt_s": 0.5, "restore_s": 0.5, "other_s": 0.0,
                     "goodput": 0.9}},
    ]
    return [stamp(r) for r in recs]


class TestReport:
    def test_phase_breakdown_and_goodput(self):
        rep = build_report(_training_records())
        assert rep["phases"]["compute"]["total_s"] == 8.0
        assert rep["phases"]["compute"]["share"] == pytest.approx(0.8)
        gp = rep["goodput"]
        assert len(gp["attempts"]) == 2
        assert gp["combined"]["goodput"] == pytest.approx(17 / 20)
        assert len(rep["timeline"]) == 2
        assert rep["stalls"] == 1

    def test_nested_spans_do_not_double_count(self):
        """A child span's time is inside its parent's: only top-level
        spans feed the share denominator (review finding)."""
        recs = [stamp(r) for r in (
            {"event": "span", "name": "step", "dur_s": 10.0},
            {"event": "span", "name": "data", "dur_s": 4.0,
             "parent": "step", "depth": 1},
        )]
        phases = build_report(recs)["phases"]
        assert phases["step"]["share"] == pytest.approx(1.0)
        assert phases["data"]["share"] == pytest.approx(0.4)

    def test_mfu_weights_attempts_in_file_order(self):
        """A resumed run's MFU weights each attempt's chunks from its own
        start_step (review finding: seeding from the LAST run_start
        clamped earlier attempts to ~1-step weights)."""
        def epoch(step, rate, s_per_step):
            return {"event": "epoch", "epoch": 0, "step": step,
                    "loss": 1.0, "items_per_s": rate,
                    "items_per_s_per_device": rate,
                    "s_per_step": s_per_step}

        def start(step):
            return {"event": "run_start", "start_step": step,
                    "total_steps": 4, "n_devices": 1,
                    "n_processes": 1, "device_kind": "cpu",
                    "jax_version": "0",
                    "config": {"model_flops_per_item": 1.0}}

        recs = [stamp(r) for r in (
            start(0), epoch(2, 100.0, 1.0),   # attempt 0: 2s at 100/s
            start(2), epoch(4, 50.0, 1.0),    # attempt 1: 2s at 50/s
        )]
        rep = build_report(recs, peak_flops_per_device=1.0)
        # Equal 2-step chunks: plain average, NOT last-attempt-biased.
        assert rep["mfu"]["items_per_s"] == pytest.approx(75.0)

    def test_mfu_from_config_and_peak(self):
        rep = build_report(
            _training_records(), peak_flops_per_device=1e12,
        )
        # 100 items/s * 1e9 FLOP/item / (8 dev * 1e12 FLOP/s/dev)
        assert rep["mfu"]["mfu"] == pytest.approx(0.0125)

    def test_format_names_fused_phases(self):
        txt = format_report(build_report(_training_records()))
        assert "goodput" in txt and "Restart timeline" in txt
        # 'sync' was not measured on this run: the table says why
        # instead of silently omitting the canonical phase.
        assert "sync" in txt and "fused" in txt

    def test_cli_json_and_markdown(self, tmp_path, capsys):
        p = tmp_path / "run.jsonl"
        p.write_text(
            "\n".join(json.dumps(r) for r in _training_records())
        )
        assert report_main([str(p), "--json",
                            "--peak-flops", "1e12"]) == 0
        rep = json.loads(capsys.readouterr().out)
        assert rep["goodput"]["combined"]["productive_s"] == 17.0
        assert report_main([str(p)]) == 0
        assert "Step-time breakdown" in capsys.readouterr().out

    def test_json_contract_pinned(self, tmp_path, capsys):
        """The driver contract obs/regress.py and CI consume: the
        JSON report carries schema_version; exit codes are 0 (report
        produced) / 2 (empty or invalid input) -- nothing else."""
        p = tmp_path / "run.jsonl"
        p.write_text(
            "\n".join(json.dumps(r) for r in _training_records())
        )
        assert report_main([str(p), "--json"]) == 0
        rep = json.loads(capsys.readouterr().out)
        assert rep["schema_version"] == SCHEMA_VERSION
        # build_report (the --json payload) and the records agree on
        # the schema generation -- one constant, two consumers.
        from tpu_hpc.obs.report import build_report

        assert build_report(_training_records())["schema_version"] \
            == SCHEMA_VERSION

    def test_cli_rejects_invalid_and_missing(self, tmp_path, capsys):
        bad = tmp_path / "bad.jsonl"
        bad.write_text('{"event": "mystery"}\n')
        assert report_main([str(bad)]) == 2
        assert report_main([str(tmp_path / "gone.jsonl")]) == 2
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        assert report_main([str(empty)]) == 2
        capsys.readouterr()

    def test_cli_no_validate_salvages(self, tmp_path, capsys):
        p = tmp_path / "drifted.jsonl"
        recs = _training_records() + [{"event": "mystery"}]
        p.write_text("\n".join(json.dumps(r) for r in recs))
        assert report_main([str(p), "--no-validate"]) == 0
        capsys.readouterr()


# ---------------------------------------------------------------------
# integration: training -> one validated JSONL -> report  (the
# acceptance run for the PR: train and serve records share a schema)
# ---------------------------------------------------------------------
class TestTrainingReportSmoke:
    @pytest.fixture()
    def run_jsonl(self, mesh8, tmp_path):
        import jax
        import jax.numpy as jnp

        from tpu_hpc.config import TrainingConfig
        from tpu_hpc.parallel import dp
        from tpu_hpc.train import Trainer

        class DS:
            def batch_at(self, step, bs):
                k = jax.random.key(int(step) % 97)
                x = jax.random.normal(k, (bs, 4), jnp.float32)
                return x, x @ jnp.arange(4.0)

        def forward(params, model_state, batch, step_rng):
            x, y = batch
            pred = x @ params["w"]
            return jnp.mean((pred - y) ** 2), model_state, {}

        mpath = str(tmp_path / "run.jsonl")
        cfg = TrainingConfig(
            epochs=2, global_batch_size=16, steps_per_epoch=2,
            metrics_path=mpath, model_flops_per_item=1e6,
        )
        tr = Trainer(
            cfg, mesh8, forward, {"w": jnp.zeros((4,), jnp.float32)},
            param_pspecs=dp.param_pspecs(
                {"w": jnp.zeros((4,), jnp.float32)}
            ),
            batch_pspec=dp.batch_pspec(),
        )
        tr.fit(DS())
        return mpath

    def test_run_jsonl_validates_and_reports(self, run_jsonl, capsys):
        # Every record the Trainer wrote speaks the one schema.
        assert validate_file(run_jsonl) > 0
        events = [json.loads(x)["event"] for x in open(run_jsonl)]
        assert events[0] == "run_start" and events[-1] == "metrics"
        assert "span" in events and "run_end" in events
        # The report CLI turns it into a non-empty goodput/MFU/
        # step-time breakdown (sim CPU: peak supplied by flag).
        assert report_main([run_jsonl, "--json",
                            "--peak-flops", "1e12"]) == 0
        rep = json.loads(capsys.readouterr().out)
        assert rep["phases"]["compute"]["total_s"] > 0
        assert rep["phases"]["compute"]["count"] == 2
        gp = rep["goodput"]["combined"]
        assert gp["productive_s"] > 0 and 0 < gp["goodput"] <= 1
        assert rep["mfu"] is not None and rep["mfu"]["mfu"] > 0
        assert rep["timeline"][0]["disposition"] == "completed"

    def test_report_module_cli(self, run_jsonl):
        """The exact command the docs teach: ``python -m
        tpu_hpc.obs.report run.jsonl`` (fresh interpreter -- the
        report must not need a jax backend)."""
        env = dict(os.environ)
        prev = env.get("PYTHONPATH", "")
        env["PYTHONPATH"] = REPO + (os.pathsep + prev if prev else "")
        proc = subprocess.run(
            [sys.executable, "-m", "tpu_hpc.obs.report", run_jsonl,
             "--peak-flops", "1e12"],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "Step-time breakdown" in proc.stdout
        assert "goodput" in proc.stdout


# ---------------------------------------------------------------------
# integration: a faulted sim-mesh run leaves a flight-recorder dump
# ---------------------------------------------------------------------
FAULT_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    for var in ("TPU_VISIBLE_DEVICES", "TPU_CHIPS_PER_PROCESS_BOUNDS",
                "PALLAS_AXON_POOL_IPS", "AXON_POOL_SVC_OVERRIDE",
                "TPU_WORKER_HOSTNAMES"):
        os.environ.pop(var, None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from tpu_hpc.config import TrainingConfig
    from tpu_hpc.parallel import dp
    from tpu_hpc.runtime import MeshSpec, build_mesh
    from tpu_hpc.train import Trainer

    class DS:
        def batch_at(self, step, bs):
            k = jax.random.key(int(step) % 97)
            x = jax.random.normal(k, (bs, 4), jnp.float32)
            return x, x @ jnp.arange(4.0)

    def forward(params, model_state, batch, step_rng):
        x, y = batch
        pred = x @ params["w"]
        return jnp.mean((pred - y) ** 2), model_state, {}

    cfg = TrainingConfig(
        epochs=3, steps_per_epoch=2, global_batch_size=16,
        metrics_path=os.environ["WORK_METRICS"],
        checkpoint_dir=os.environ["WORK_CKPT"],
    )
    mesh = build_mesh(MeshSpec(axes={"data": 8}))
    params = {"w": jnp.zeros((4,), jnp.float32)}
    trainer = Trainer(
        cfg, mesh, forward, params,
        param_pspecs=dp.param_pspecs(params),
        batch_pspec=dp.batch_pspec(),
    )
    trainer.fit(DS())
    print("SURVIVED", flush=True)  # kill_at_step must prevent this
""")


class TestFaultedRunFlightDump:
    def test_sigkill_fault_leaves_pre_fault_evidence(self, tmp_path):
        """Acceptance: on the 8-device sim mesh, a TPU_HPC_FAULTS
        hard-kill run dumps a flight file holding the events leading
        up to the kill -- the fault record itself last."""
        worker = tmp_path / "worker.py"
        worker.write_text(FAULT_WORKER)
        env = dict(os.environ)
        prev = env.get("PYTHONPATH", "")
        env["PYTHONPATH"] = REPO + (os.pathsep + prev if prev else "")
        env["WORK_METRICS"] = str(tmp_path / "run.jsonl")
        env["WORK_CKPT"] = str(tmp_path / "ckpt")
        env["TPU_HPC_FAULTS"] = "kill_at_step=4"
        env["TPU_HPC_FLIGHT_DIR"] = str(tmp_path / "flight")
        proc = subprocess.run(
            [sys.executable, str(worker)], capture_output=True,
            text=True, timeout=240, env=env, cwd=REPO,
        )
        assert proc.returncode == -9, proc.stderr[-2000:]
        assert "SURVIVED" not in proc.stdout
        dumps = os.listdir(tmp_path / "flight")
        assert len(dumps) == 1 and "fault_kill" in dumps[0]
        dump = os.path.join(str(tmp_path / "flight"), dumps[0])
        recs = [json.loads(x) for x in open(dump)]
        assert validate_file(dump) == len(recs)
        assert recs[0]["event"] == "flight_dump"
        assert recs[0]["reason"] == "fault_kill"
        events = [r["event"] for r in recs[1:]]
        # The ring replays the run up to the kill: the run_start, the
        # pre-fault progress, and the injected fault itself, in order.
        assert events[0] == "run_start"
        assert "span" in events and "epoch" in events
        assert events[-1] == "fault"
        assert recs[-1]["kind"] == "kill" and recs[-1]["step"] == 4
        # One run_id threads every record (the join key for forensics).
        assert len({r["run_id"] for r in recs}) == 1
