"""The doctor: strategy chooser = fit + roofline, ranked.

All analytic (analyze(do_compile=False) is eval_shape-only), so these
run fast and off-device. The invariants: legality of the candidate
meshes, accumulation escalation until fit, ranking (fit first, then
throughput bound, HBM headroom as tie-break), and the honest no-fit
verdict.
"""
import json

import pytest

from tpu_hpc.checks.doctor import (
    ACCUM_LADDER,
    diagnose,
    main,
    to_markdown,
)


@pytest.fixture(scope="module")
def plans_7b32():
    return diagnose("7b", chips=32, chip="v5e", global_batch=256)


class TestCandidates:
    def test_meshes_are_legal(self, plans_7b32):
        for p in plans_7b32:
            assert p.dp * p.axis2 == 32
            assert 256 % p.dp == 0
            if p.layout == "tp":
                assert 32 % p.axis2 == 0 and p.axis2 <= 8

    def test_gqa_head_divisibility(self):
        # 70B: 64 query heads, 8 KV heads -> tp must divide 8 (pp
        # plans follow the layer count instead, 80 layers).
        plans = diagnose("70b", chips=64, chip="v4", global_batch=256)
        tp_degrees = {p.axis2 for p in plans if p.layout == "tp"}
        assert tp_degrees <= {1, 2, 4, 8}
        for p in plans:
            if p.layout == "pp":
                assert 80 % p.axis2 == 0

    def test_cp_only_with_long_context(self):
        no_cp = diagnose("7b", chips=16, chip="v4", global_batch=64)
        assert all(p.layout in ("tp", "pp") for p in no_cp)
        with_cp = diagnose(
            "7b", chips=16, chip="v4", global_batch=64,
            long_context=True,
        )
        assert any(p.layout == "cp" for p in with_cp)
        for p in with_cp:
            if p.layout == "cp":
                assert 4096 % p.axis2 == 0


class TestRanking:
    def test_sorted_best_first(self, plans_7b32):
        scores = [p.score for p in plans_7b32]
        assert scores == sorted(scores, reverse=True)

    def test_fitting_plans_rank_above_nonfitting(self):
        plans = diagnose("70b", chips=16, chip="v5e", global_batch=64)
        seen_nonfit = False
        for p in plans:
            if not p.fits:
                seen_nonfit = True
            else:
                assert not seen_nonfit, "a fitting plan ranked below a non-fitting one"

    def test_speed_ties_break_toward_headroom(self, plans_7b32):
        best = plans_7b32[0]
        for p in plans_7b32[1:]:
            if (
                p.fits
                and p.roofline.tokens_per_s_per_chip_bound
                == best.roofline.tokens_per_s_per_chip_bound
            ):
                assert best.hbm_frac <= p.hbm_frac


class TestAccumEscalation:
    def test_accum_raised_until_fit(self):
        """13B on 16 v4 chips at a 1M-token batch does not fit
        unaccumulated (REPORT_13b_16chip_1M ran accum 32); the doctor
        must find a fitting accum on the ladder, and it must divide
        the batch with microbatches covering dp."""
        plans = diagnose("13b", chips=16, chip="v4", global_batch=256)
        best = plans[0]
        assert best.fits and best.grad_accum > 1
        assert best.grad_accum in ACCUM_LADDER
        assert 256 % best.grad_accum == 0
        assert (256 // best.grad_accum) % best.dp == 0


class TestOutput:
    def test_markdown_recommends_and_reproduces(self, plans_7b32):
        md = to_markdown(
            plans_7b32, model="7b", chips=32, chip_name="v5e",
            global_batch=256, seq_len=4096, moments_dtype="float32",
        )
        assert "Recommended:" in md
        assert "tpu_hpc.checks.fit" in md
        assert "tpu_hpc.checks.roofline" in md
        best = plans_7b32[0]
        assert f"--dp {best.dp}" in md

    def test_no_fit_verdict(self, capsys):
        # 70B on 8 small chips: nothing can fit.
        rc = main([
            "--model", "70b", "--chips", "8", "--chip", "v5e",
            "--global-batch", "64",
        ])
        out = capsys.readouterr().out
        assert rc == 1
        assert "No plan fits" in out

    def test_json_mode(self, capsys):
        rc = main([
            "--model", "7b", "--chips", "8", "--chip", "v4",
            "--global-batch", "64", "--json",
        ])
        assert rc == 0
        rows = json.loads(capsys.readouterr().out)
        assert rows and all(
            {"mesh", "fits", "bound", "grad_accum"} <= set(r)
            for r in rows
        )

    def test_tight_marker(self):
        """Plans above 90% HBM are labeled 'tight', not a bare yes."""
        plans = diagnose("7b", chips=32, chip="v5e", global_batch=256)
        md = to_markdown(
            plans, model="7b", chips=32, chip_name="v5e",
            global_batch=256, seq_len=4096, moments_dtype="float32",
        )
        for p in plans:
            if p.fits and p.hbm_frac > 0.9:
                assert "tight" in md
                break


class TestPipelinePlans:
    """Chapter-11 parity: pipeline is in the decision space
    (/root/reference/docs/guide/11_choosing_a_strategy.md:109-127)."""

    def test_pp_plans_enumerated(self, plans_7b32):
        pp = [p for p in plans_7b32 if p.layout == "pp"]
        assert pp, "doctor must rank pipeline candidates"
        for p in pp:
            assert p.axis2 >= 2
            # 7b has 32 layers; stages must divide them.
            assert 32 % p.axis2 == 0
            assert p.roofline.schedule_factor > 1.0

    def test_pp_mfu_ceiling_below_tp(self, plans_7b32):
        # The bubble+remat schedule factor must depress every pp
        # plan's MFU ceiling below the pure-compute 100% line.
        for p in plans_7b32:
            if p.layout == "pp":
                assert p.roofline.mfu_upper_bound < 1.0


class TestSlices:
    def test_slices_filter_and_dcn_cost(self):
        plans = diagnose(
            "7b", chips=32, chip="v5e", global_batch=256, slices=2
        )
        assert plans
        for p in plans:
            # The second axis never straddles slices.
            assert p.dp % 2 == 0
            assert p.roofline.slices == 2

    def test_markdown_names_slices(self):
        plans = diagnose(
            "7b", chips=32, chip="v5e", global_batch=256, slices=2
        )
        md = to_markdown(
            plans, model="7b", chips=32, chip_name="v5e",
            global_batch=256, seq_len=4096, moments_dtype="float32",
            slices=2,
        )
        assert "across 2 slices" in md
