"""The live telemetry plane (obs/digest.py, obs/live.py, obs/slo.py).

What's pinned here, layer by layer:

* the sketch's CONTRACT: every quantile within ``alpha`` relative
  error of the exact nearest-rank value, and the merge associative /
  commutative / duplication-safe under random interleavings -- the
  algebra the whole fleet rollup rests on;
* the rollup's idempotence: re-reading channels, reading them in any
  order, or merging partial rollups from two aggregators converge to
  the same view (cumulative counters + latest-seq-per-source);
* burn-rate alerting: fast AND slow windows must both burn to page,
  the page fires exactly once, never on a clean replay, and never
  before the slow window is covered (no cold-start false positives);
* ``digest_stale`` is non-vacuous: a clean run flags nothing, a
  killed replica's silence is flagged as a first-class event;
* the end-to-end acceptance: a virtual-clock 4-replica fleet run with
  one ``slow_replica`` fault drives a deterministic
  ``python -m tpu_hpc.obs.live --json`` rollup that names the slow
  replica as the straggler, with zero recompiles and exactly one
  ``slo_burn``-triggered capture bundle correlated by trace_id;
* the committed BENCH_LIVE rows pass ``regress --bank``.
"""
import json
import math
import os
import random

import jax
import jax.numpy as jnp
import pytest

from tpu_hpc import obs
from tpu_hpc.loadgen import build_scenario, parse_faults
from tpu_hpc.models import llama2
from tpu_hpc.obs.digest import (
    DEFAULT_ALPHA,
    ENV_DIGEST_DIR,
    DigestPublisher,
    LogBucketSketch,
    merge_digest_hists,
    read_channel,
    read_digest_dir,
)
from tpu_hpc.obs.live import (
    ENV_FLEET_PROM_FILE,
    Rollup,
    fleet_prometheus_text,
    format_scoreboard,
    rollup_from_dir,
    stale_entries,
)
from tpu_hpc.obs.live import main as live_main
from tpu_hpc.obs.regress import main as regress_main
from tpu_hpc.obs.regress import report_metrics
from tpu_hpc.obs.report import build_report, format_report
from tpu_hpc.obs.schema import load_records
from tpu_hpc.obs.slo import BurnRateMonitor
from tpu_hpc.serve import PagedConfig, ServeConfig
from tpu_hpc.serve.fleet import (
    FleetConfig,
    FleetHarness,
    LiveConfig,
    build_fleet_engines,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = llama2.LlamaConfig(
    dim=64, n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=128,
    multiple_of=16, max_seq_len=64, dtype=jnp.float32,
)
SERVE = ServeConfig(slots=4, max_seq_len=48, prefill_buckets=(8, 16))
PAGED = PagedConfig(block_size=4, num_blocks=48, prefill_chunk=8)
N_REPLICAS = 4


# ---------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------
def _exact_q(vals, q):
    """Exact nearest-rank quantile -- the reference the sketch's
    alpha bound is judged against."""
    s = sorted(vals)
    return s[max(0, math.ceil(q * len(s)) - 1)]


def _assert_sketch_equal(a: LogBucketSketch, b: LogBucketSketch):
    """Merge-order equality: buckets/counts/min/max are exact; ``sum``
    is a float accumulated in merge order, so it is compared to
    tolerance, never bit-exactly."""
    da, db = a.to_dict(), b.to_dict()
    sa, sb = da.pop("sum"), db.pop("sum")
    assert da == db
    assert sa == pytest.approx(sb, rel=1e-9)


def _digest(role, key, seq, t, counters=None, gauges=None, hists=None,
            host="h0", pid=1, **extra):
    rec = {
        "event": "health_digest", "role": role, "key": str(key),
        "seq": seq, "t": t, "host": host, "pid": pid,
        "counters": counters or {}, "gauges": gauges or {},
        "hists": {k: v.to_dict() for k, v in (hists or {}).items()},
        "alpha": DEFAULT_ALPHA,
    }
    rec.update(extra)
    return rec


# ---------------------------------------------------------------------
# LogBucketSketch: the alpha bound + the merge algebra
# ---------------------------------------------------------------------
class TestSketch:
    def test_quantiles_within_alpha_of_exact(self):
        rng = random.Random(11)
        vals = [rng.lognormvariate(0.0, 2.0) for _ in range(5000)]
        sk = LogBucketSketch()
        for v in vals:
            sk.add(v)
        for q in (0.01, 0.1, 0.5, 0.9, 0.95, 0.99, 0.999):
            exact = _exact_q(vals, q)
            est = sk.quantile(q)
            assert abs(est - exact) / exact <= DEFAULT_ALPHA + 1e-9, q

    def test_merged_quantiles_keep_the_bound(self):
        """The headline property: quantiles of the UNION of streams,
        computed from merged sketches, hold the same alpha bound --
        sample-window histograms cannot do this."""
        rng = random.Random(12)
        streams = [
            [rng.lognormvariate(0.0, 1.5) for _ in range(2000)],
            [rng.uniform(0.1, 100.0) for _ in range(3000)],
            [rng.expovariate(0.02) + 1e-6 for _ in range(1000)],
        ]
        merged = LogBucketSketch()
        for s in streams:
            sk = LogBucketSketch()
            for v in s:
                sk.add(v)
            merged.merge(sk)
        union = [v for s in streams for v in s]
        assert merged.count == len(union)
        for q in (0.5, 0.9, 0.99, 0.999):
            exact = _exact_q(union, q)
            assert abs(merged.quantile(q) - exact) / exact \
                <= DEFAULT_ALPHA + 1e-9, q

    def test_merge_commutative_associative_random_interleavings(self):
        rng = random.Random(13)
        parts = []
        for _ in range(6):
            sk = LogBucketSketch()
            for _ in range(rng.randint(1, 400)):
                sk.add(rng.lognormvariate(0.0, 2.0))
            parts.append(sk)

        def merge_order(order):
            out = LogBucketSketch()
            for i in order:
                out.merge(LogBucketSketch.from_dict(parts[i].to_dict()))
            return out

        ref = merge_order(range(len(parts)))
        for _ in range(5):
            order = list(range(len(parts)))
            rng.shuffle(order)
            _assert_sketch_equal(ref, merge_order(order))
        # Associativity: (a+b)+c == a+(b+c), via pairwise grouping.
        a, b, c = (
            LogBucketSketch.from_dict(parts[i].to_dict())
            for i in range(3)
        )
        left = a.merge(b).merge(c)
        a2, b2, c2 = (
            LogBucketSketch.from_dict(parts[i].to_dict())
            for i in range(3)
        )
        right = a2.merge(b2.merge(c2))
        _assert_sketch_equal(left, right)

    def test_merge_alpha_mismatch_raises(self):
        with pytest.raises(ValueError, match="alpha"):
            LogBucketSketch(0.01).merge(LogBucketSketch(0.02))

    def test_zero_and_negative_clamp(self):
        sk = LogBucketSketch()
        sk.add(0.0)
        sk.add(-3.0)
        sk.add(1e-15)
        assert sk.zero == 3 and sk.count == 3 and not sk.buckets
        assert sk.quantile(0.5) == 0.0

    def test_wire_roundtrip_is_lossless(self):
        rng = random.Random(14)
        sk = LogBucketSketch()
        for _ in range(1000):
            sk.add(rng.lognormvariate(1.0, 1.0))
        rt = LogBucketSketch.from_dict(
            json.loads(json.dumps(sk.to_dict()))
        )
        assert rt.to_dict() == sk.to_dict()
        assert rt.summary() == sk.summary()

    def test_empty_summary(self):
        s = LogBucketSketch().summary()
        assert s["count"] == 0 and s["p999"] == 0.0

    def test_merge_digest_hists(self):
        a, b = LogBucketSketch(), LogBucketSketch()
        a.add(1.0), b.add(100.0)
        out = merge_digest_hists([
            {"hists": {"x_ms": a.to_dict()}},
            {"hists": {"x_ms": b.to_dict()}},
        ])
        assert out["x_ms"].count == 2


# ---------------------------------------------------------------------
# Rollup: idempotent, order-free, mergeable
# ---------------------------------------------------------------------
class TestRollup:
    def _records(self):
        sk = LogBucketSketch()
        sk.add(8.0, n=10)
        recs = []
        for key in ("0", "1", "2"):
            for seq in range(3):
                recs.append(_digest(
                    "replica", key, seq, 0.1 * (seq + 1),
                    counters={"ticks": 10.0 * (seq + 1)},
                    gauges={"occupancy": 0.5},
                    hists={"tick_ms": sk}, step_s=0.008,
                ))
        return recs

    def test_ingest_idempotent_and_order_free(self):
        recs = self._records()
        ref = Rollup().ingest(recs).build(now=0.3)
        rng = random.Random(15)
        for _ in range(5):
            shuffled = recs + recs[::2]  # duplicates too
            rng.shuffle(shuffled)
            got = Rollup().ingest(shuffled).build(now=0.3)
            # The digest COUNT sees the duplicates; the VIEW must not.
            ref.pop("digests", None), got.pop("digests", None)
            assert got == ref

    def test_stale_record_never_replaces_newer_seq(self):
        recs = self._records()
        roll = Rollup().ingest(recs)
        view1 = roll.build(now=0.3)
        roll.ingest([recs[0]])  # seq 0 replay after seq 2 seen
        view2 = roll.build(now=0.3)
        view1.pop("digests"), view2.pop("digests")
        assert view1 == view2

    def test_merge_two_partial_rollups_converges(self):
        recs = self._records()
        ref = Rollup().ingest(recs).build(now=0.3)
        a = Rollup().ingest(recs[:5])
        b = Rollup().ingest(recs[3:])  # overlapping coverage
        got = a.merge(b).build(now=0.3)
        ref.pop("digests"), got.pop("digests")
        assert got == ref

    def test_restarted_pid_counters_sum(self):
        """A restarted process (new pid) is a NEW source: its
        cumulative counters SUM with its predecessor's final totals
        instead of replacing them."""
        recs = [
            _digest("host", "0", 5, 1.0, counters={"steps": 50.0},
                    pid=100),
            _digest("host", "0", 0, 2.0, counters={"steps": 7.0},
                    pid=200),
        ]
        view = Rollup().ingest(recs).build(now=2.0)
        row = view["roles"]["host"]["keys"]["0"]
        assert row["counters"]["steps"] == 57.0
        assert row["sources"] == 2

    def test_straggler_self_excluded_strict_and_needs_peers(self):
        def view_for(signals, factor=3.0):
            recs = [
                _digest("stage", str(i), 0, 1.0, step_s=s)
                for i, s in enumerate(signals)
            ]
            return Rollup(
                stale_after_s=10.0, straggler_factor=factor
            ).ingest(recs).build(now=1.0)

        # 4x the peer median: flagged.
        assert view_for([0.008, 0.008, 0.008, 0.032])["stragglers"] \
            == ["stage:3"]
        # EXACTLY factor x median: strict >, not flagged.
        assert view_for([0.01, 0.01, 0.01, 0.03])["stragglers"] == []
        # Two members: either could be the slow one -- never flagged.
        assert view_for([0.008, 0.8])["stragglers"] == []
        # Self-exclusion: the straggler must not drag the median.
        v = view_for([0.008, 0.009, 0.01, 0.09])
        assert v["stragglers"] == ["stage:3"]

    def test_stale_flag_and_entries(self):
        recs = [
            _digest("replica", "0", 9, 10.0),
            _digest("replica", "1", 4, 3.0),
        ]
        view = Rollup(stale_after_s=2.0).ingest(recs).build(now=10.0)
        assert view["stale"] == ["replica:1"]
        assert not view["roles"]["replica"]["keys"]["0"]["stale"]
        (e,) = stale_entries(view)
        assert e["role"] == "replica" and e["key"] == "1"
        assert e["age_s"] == 7.0 and e["last_seq"] == 4

    def test_validation(self):
        with pytest.raises(ValueError, match="stale_after_s"):
            Rollup(stale_after_s=0.0)
        with pytest.raises(ValueError, match="straggler_factor"):
            Rollup(straggler_factor=1.0)

    def test_prometheus_text_and_scoreboard(self):
        sk = LogBucketSketch()
        sk.add(8.0, n=100)
        recs = [
            _digest("replica", "0", 0, 1.0,
                    counters={"slo_good": 90.0, "slo_bad": 10.0},
                    gauges={"occupancy": 0.7},
                    hists={"tick_ms": sk}, step_s=0.008),
        ]
        view = Rollup().ingest(recs).build(now=1.0)
        text = fleet_prometheus_text(view)
        assert 'tpu_hpc_fleet_slo_good{role="replica",key="0"} 90.0' \
            in text
        assert 'quantile="0.999"' in text
        assert "tpu_hpc_fleet_slo_attainment 0.9" in text
        board = format_scoreboard(view)
        assert "replica" in board and "SLO: attainment 0.9000" in board


# ---------------------------------------------------------------------
# BurnRateMonitor: two windows, one page
# ---------------------------------------------------------------------
class _StubCapture:
    def __init__(self):
        self.calls = []

    def trigger(self, reason, trace_id=None, step=None, sink=None,
                arm_profiler=True):
        self.calls.append((reason, trace_id, arm_profiler))


@pytest.fixture()
def scoped_obs(tmp_path):
    bus = obs.EventBus(path=None, run_id="fleet-test",
                       flight_dir=str(tmp_path))
    reg = obs.MetricsRegistry()
    prev_bus, prev_reg = obs.set_bus(bus), obs.set_registry(reg)
    yield bus, reg
    obs.set_bus(prev_bus)
    obs.set_registry(prev_reg)


class TestBurnRate:
    def _mon(self, **kw):
        kw.setdefault("target", 0.99)
        kw.setdefault("fast_window_s", 5.0)
        kw.setdefault("slow_window_s", 50.0)
        kw.setdefault("threshold", 10.0)
        return BurnRateMonitor(**kw)

    def test_fires_exactly_once_on_sustained_breach(self, scoped_obs):
        cap = _StubCapture()
        mon = self._mon()
        fired = []
        good = bad = 0.0
        for t in range(0, 120):
            good += 8.0
            bad += 2.0  # 20% error rate = burn 20 vs threshold 10
            rec = mon.observe(
                float(t), good, bad, trace_id="fleet-test:slo:x",
                capture=cap,
            )
            if rec:
                fired.append((t, rec))
        assert len(fired) == 1
        t_fire, rec = fired[0]
        # Fires at the FIRST sample where the slow window is covered
        # (t=50), never earlier -- no cold-start page.
        assert t_fire == 50
        assert rec["event"] == "slo_burn"
        assert rec["burn_fast"] >= 10 and rec["burn_slow"] >= 10
        assert rec["trace_id"] == "fleet-test:slo:x"
        assert mon.burns == 1 and mon.fired
        assert cap.calls == [("slo_burn", "fleet-test:slo:x", False)]
        # rearm: the next sustained burn may page again.
        mon.rearm()
        good += 8.0
        bad += 2.0
        assert mon.observe(120.0, good, bad) is not None

    def test_never_fires_on_clean_replay(self, scoped_obs):
        mon = self._mon()
        good = 0.0
        for t in range(0, 200):
            good += 10.0
            assert mon.observe(float(t), good, 0.0) is None
        assert mon.burns == 0 and not mon.fired
        assert mon.budget_remaining() == pytest.approx(1.0)

    def test_fast_spike_alone_does_not_page(self, scoped_obs):
        """One bad burst trips the fast window but not the slow one:
        no page -- the multi-window construction's whole point."""
        mon = self._mon()
        good = bad = 0.0
        for t in range(0, 100):
            if 60 <= t < 63:
                bad += 10.0  # 100% errors for 3s of a 50s window
            else:
                good += 10.0
            assert mon.observe(float(t), good, bad) is None, t
        assert mon.burns == 0

    def test_slow_window_coverage_gates_cold_start(self, scoped_obs):
        mon = self._mon()
        bad = 0.0
        for t in range(0, 50):  # all errors, but slow window uncovered
            bad += 10.0
            assert mon.observe(float(t), 0.0, bad) is None, t

    def test_time_backwards_raises(self, scoped_obs):
        mon = self._mon()
        mon.observe(10.0, 1.0, 0.0)
        with pytest.raises(ValueError, match="backwards"):
            mon.observe(9.0, 2.0, 0.0)

    def test_validation(self):
        with pytest.raises(ValueError, match="target"):
            BurnRateMonitor(target=1.0)
        with pytest.raises(ValueError, match="slow_window_s"):
            BurnRateMonitor(fast_window_s=10.0, slow_window_s=5.0)
        with pytest.raises(ValueError, match="threshold"):
            BurnRateMonitor(threshold=0.0)


# ---------------------------------------------------------------------
# DigestPublisher: channels, env gating, the registry backend
# ---------------------------------------------------------------------
class TestDigestPublisher:
    def test_channel_names_never_clobber(self, tmp_path, scoped_obs):
        p1 = DigestPublisher(str(tmp_path), "replica", "0")
        p1.publish(t=1.0)
        p2 = DigestPublisher(str(tmp_path), "replica", "0")
        p2.publish(t=2.0)
        assert p1.path != p2.path
        assert os.path.exists(p1.path) and os.path.exists(p2.path)
        # Both channels' records surface in a directory read.
        recs = read_digest_dir(str(tmp_path))
        assert len(recs) == 2

    def test_from_env_unset_is_none(self, monkeypatch):
        monkeypatch.delenv(ENV_DIGEST_DIR, raising=False)
        assert DigestPublisher.from_env(role="host", key="0") is None

    def test_due_rate_limits(self, tmp_path):
        pub = DigestPublisher(
            str(tmp_path), "host", "0", period_s=1.0
        )
        assert pub.due(0.0)
        pub.last_publish_t = 0.0
        assert not pub.due(0.5)
        assert pub.due(1.0)

    def test_publish_registry_uses_sketch_backend(
        self, tmp_path, scoped_obs
    ):
        """The Trainer's per-host path: counters/gauges verbatim, the
        histograms from the registry's mergeable sketch backend, and
        the publish cost banked into obs.digest_publish_ms."""
        _, reg = scoped_obs
        reg.inc("steps_total", 12)
        reg.set_gauge("lr", 0.001)
        for v in (1.0, 2.0, 4.0, 8.0):
            reg.observe("step_ms", v)
        pub = DigestPublisher(str(tmp_path), "host", "0")
        rec = pub.publish_registry(t=5.0, step_s=0.1, step=12)
        assert rec["counters"]["steps_total"] == 12.0
        assert rec["gauges"]["lr"] == 0.001
        assert rec["hists"]["step_ms"]["count"] == 4
        sk = LogBucketSketch.from_dict(rec["hists"]["step_ms"])
        assert sk.quantile(0.999) == pytest.approx(8.0, rel=0.01)
        # The channel file holds the byte-identical record.
        (on_disk,) = read_channel(pub.path)
        assert on_disk == rec
        # The plane's own overhead is metered on the registry...
        snap = reg.snapshot()
        assert snap["histograms"]["obs.digest_publish_ms"]["count"] >= 1
        # ...and the sketch backend surfaces p99.9 in the textfile.
        assert 'quantile="0.999"' in reg.prometheus_text()

    def test_sketch_snapshot_is_isolated(self, scoped_obs):
        _, reg = scoped_obs
        reg.observe("x_ms", 1.0)
        snap = reg.sketch_snapshot()
        snap["x_ms"].add(99.0)
        assert reg.sketch_snapshot()["x_ms"].count == 1

    def test_read_channel_fails_loudly_on_torn_json(self, tmp_path):
        p = tmp_path / "digest.host.0.pid1.jsonl"
        p.write_text('{"event": "health_digest"}\n{torn\n')
        with pytest.raises(ValueError, match="not JSON"):
            read_channel(str(p))


# ---------------------------------------------------------------------
# the fleet acceptance: straggler + burn + capture, deterministically
# ---------------------------------------------------------------------
@pytest.fixture(scope="module")
def live_params():
    return llama2.init_llama(jax.random.key(0), TINY)


@pytest.fixture(scope="module")
def live_engines(live_params, devices):
    engines = build_fleet_engines(
        live_params, TINY, SERVE, PAGED, N_REPLICAS
    )
    for e in engines:
        e._params0 = e.params
    return engines


@pytest.fixture()
def engines(live_engines):
    for e in live_engines:
        e.reset_pool(force=True)
        if e.params is not e._params0:
            e.swap_params(e._params0)
    return live_engines


def _scenario(n=96, rate=240.0):
    return build_scenario(
        "diurnal", seed=7, n_requests=n, vocab_size=TINY.vocab_size,
        max_prompt=16, max_new=6, rate_per_s=rate,
    )


def _fleet_run(engines, tmp_path, monkeypatch, *, faults, live):
    digest_dir = str(tmp_path / "digests")
    monkeypatch.setenv(ENV_DIGEST_DIR, digest_dir)
    metrics_path = str(tmp_path / "run.jsonl")
    capture = obs.AnomalyCapture(profile_dir=str(tmp_path / "prof"))
    harness = FleetHarness(
        engines, _scenario(),
        FleetConfig(initial_replicas=N_REPLICAS,
                    min_replicas=N_REPLICAS,
                    max_replicas=N_REPLICAS),
        metrics_path=metrics_path,
        faults=parse_faults(faults),
        live_cfg=live, capture=capture,
    )
    n0 = harness.fleet.compile_count_total()
    summary = harness.run(n_devices=jax.device_count())
    summary["_recompiles"] = (
        harness.fleet.compile_count_total() - n0
    )
    return summary, harness, digest_dir, metrics_path


class TestFleetLiveAcceptance:
    def test_slow_replica_straggler_burn_and_capture(
        self, engines, tmp_path, monkeypatch, scoped_obs, capsys
    ):
        """The ISSUE's acceptance run: 4 replicas on the virtual
        clock, replica 1 slowed 4x. The rollup names it as the
        straggler, the fleet SLO burns exactly once, the burn arms
        exactly one capture bundle correlated by trace_id, zero
        recompiles -- and the external CLI reader of the same channel
        directory reproduces the harness's own rollup exactly."""
        prom_path = str(tmp_path / "fleet.prom")
        monkeypatch.setenv(ENV_FLEET_PROM_FILE, prom_path)
        live = LiveConfig(
            period_s=0.02, itl_slo_ms=16.0, slo_target=0.99,
            fast_window_s=0.1, slow_window_s=0.4, burn_threshold=5.0,
            stale_after_s=30.0, straggler_factor=3.0,
        )
        summary, harness, digest_dir, metrics_path = _fleet_run(
            engines, tmp_path, monkeypatch,
            faults="slow_replica=1:4", live=live,
        )
        assert summary["_recompiles"] == 0

        lv = summary["live"]
        assert lv["stragglers"] == ["replica:1"]
        assert lv["stale_keys"] == [] and lv["digest_stale"] == 0
        assert lv["slo_burns"] == 1
        assert 0.0 < lv["slo_attainment"] < 1.0
        assert lv["slo_bad"] > 0
        assert lv["trace_id"] == "fleet-test:slo:diurnal"
        assert lv["digests"] >= N_REPLICAS

        # Exactly one slo_burn, exactly one capture bundle, one
        # correlated story: all three join on the trace_id.
        events = load_records(metrics_path, validate=True)
        burns = [e for e in events if e["event"] == "slo_burn"]
        caps = [e for e in events if e["event"] == "capture_triggered"]
        assert len(burns) == 1 and len(caps) == 1
        assert burns[0]["trace_id"] == lv["trace_id"]
        assert caps[0]["trace_id"] == lv["trace_id"]
        assert caps[0]["reason"] == "slo_burn"
        assert burns[0]["burn_fast"] >= live.burn_threshold
        assert burns[0]["burn_slow"] >= live.burn_threshold
        assert not [e for e in events if e["event"] == "digest_stale"]

        # The driver contract: the external reader over the same
        # channel directory, same knobs, reproduces the harness's own
        # final rollup EXACTLY -- and twice in a row, byte-identically.
        cli = [
            digest_dir, "--json", "--now", str(harness.wall),
            "--stale-after", str(live.stale_after_s),
            "--straggler-factor", str(live.straggler_factor),
        ]
        assert live_main(cli) == 0
        out1 = capsys.readouterr().out
        assert live_main(cli) == 0
        out2 = capsys.readouterr().out
        assert out1 == out2
        view = json.loads(out1)
        assert view == harness.telemetry.last_view
        assert view["stragglers"] == ["replica:1"]
        assert view["roles"]["replica"]["keys"]["1"]["straggler"]
        assert view["slo"]["attainment"] == lv["slo_attainment"]

        # The fleet-merged Prometheus textfile (finalize writes it
        # through $TPU_HPC_FLEET_PROM_FILE).
        prom = open(prom_path).read()
        assert 'tpu_hpc_fleet_straggler{role="replica",key="1"} 1' \
            in prom
        assert 'tpu_hpc_fleet_straggler{role="replica",key="0"} 0' \
            in prom
        assert "tpu_hpc_fleet_slo_attainment" in prom

        # Report + regress ride the same run log: the Fleet rollup
        # section renders, and the gate sees the verdict counters.
        rep = build_report(events)
        assert rep["live"]["slo_burns"] == 1
        assert rep["live"]["stragglers"] == ["replica:1"]
        assert "Fleet rollup" in format_report(rep)
        flat = report_metrics(rep)
        assert flat["slo.burns"] == 1.0
        assert flat["live.stragglers"] == 1.0
        assert flat["live.digest_stale"] == 0.0

    def test_killed_replica_goes_digest_stale(
        self, engines, tmp_path, monkeypatch, scoped_obs
    ):
        """digest_stale is non-vacuous: a replica silenced mid-run
        stops publishing and the aggregation flags exactly that key,
        exactly once -- and once the PR-14 restart brings the replica
        back and it publishes again, the LIVE verdict clears (stale is
        a live condition; the event is the incident record). The same
        run's healthy SLO never pages (the monitor's clean-replay
        side, fleet-path edition)."""
        live = LiveConfig(
            period_s=0.02, itl_slo_ms=100.0, slo_target=0.99,
            fast_window_s=0.1, slow_window_s=0.4, burn_threshold=5.0,
            stale_after_s=0.25, straggler_factor=3.0,
        )
        summary, harness, digest_dir, metrics_path = _fleet_run(
            engines, tmp_path, monkeypatch,
            faults="replica_kill_at=12", live=live,
        )
        lv = summary["live"]
        assert lv["digest_stale"] == 1
        assert lv["slo_burns"] == 0 and lv["stragglers"] == []
        # The killed replica restarted (jittered backoff) and resumed
        # publishing, so the FINAL rollup is clean again.
        assert lv["stale_keys"] == []

        events = load_records(metrics_path, validate=True)
        stale = [e for e in events if e["event"] == "digest_stale"]
        assert len(stale) == 1  # flagged once, not re-spammed per tick
        assert stale[0]["age_s"] > live.stale_after_s
        # The flagged key is the replica the health monitor lost.
        (down,) = [e for e in events if e["event"] == "replica_down"]
        assert stale[0]["key"] == str(down["replica"])
        assert not [e for e in events if e["event"] == "slo_burn"]
        assert not [
            e for e in events if e["event"] == "capture_triggered"
        ]

    def test_live_cfg_without_env_refuses(
        self, engines, monkeypatch, scoped_obs
    ):
        monkeypatch.delenv(ENV_DIGEST_DIR, raising=False)
        with pytest.raises(ValueError, match="TPU_HPC_DIGEST_DIR"):
            FleetHarness(
                engines, _scenario(), FleetConfig(
                    initial_replicas=N_REPLICAS,
                    min_replicas=N_REPLICAS,
                    max_replicas=N_REPLICAS,
                ),
                live_cfg=LiveConfig(),
            )


# ---------------------------------------------------------------------
# CLI contract + the banked rows
# ---------------------------------------------------------------------
class TestLiveCli:
    def test_no_dir_exits_2(self, monkeypatch, capsys):
        monkeypatch.delenv(ENV_DIGEST_DIR, raising=False)
        assert live_main(["--json"]) == 2
        assert "no digest dir" in capsys.readouterr().err

    def test_empty_dir_exits_2(self, tmp_path, capsys):
        assert live_main([str(tmp_path), "--json"]) == 2
        assert "no health digests" in capsys.readouterr().err

    def test_scoreboard_default_output(
        self, tmp_path, scoped_obs, capsys
    ):
        pub = DigestPublisher(str(tmp_path), "replica", "0")
        pub.publish(t=1.0, counters={"ticks": 5.0})
        assert live_main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "fleet rollup" in out and "replica" in out

    def test_prom_flag_writes_textfile(
        self, tmp_path, scoped_obs, capsys
    ):
        pub = DigestPublisher(str(tmp_path / "d"), "replica", "0")
        pub.publish(t=1.0, counters={"ticks": 5.0})
        prom = tmp_path / "fleet.prom"
        assert live_main(
            [str(tmp_path / "d"), "--json", "--prom", str(prom)]
        ) == 0
        assert "tpu_hpc_fleet_ticks" in prom.read_text()
        capsys.readouterr()

    def test_bench_rows_are_valid_and_inside_the_bound(
        self, tmp_path, scoped_obs, capsys
    ):
        out = tmp_path / "bench.jsonl"
        assert live_main(["--bench", str(out)]) == 0
        capsys.readouterr()
        rows = load_records(str(out), validate=True)
        by_metric = {r["metric"]: r for r in rows}
        assert by_metric["obs.digest_publish_ms"]["value"] > 0
        # The measured merged-quantile error must sit under the
        # pinned alpha bound -- the sketch's contract, measured.
        assert by_metric["obs.digest_quantile_rel_err"]["value"] \
            <= DEFAULT_ALPHA

    def test_committed_live_rows_pass_the_bank_gate(self, capsys):
        """CI leg of the acceptance: the committed BENCH_LIVE rows
        are schema-valid and pass ``regress --bank`` against the
        committed history."""
        hist = os.path.join(REPO, "BENCH_HISTORY.jsonl")
        rows = os.path.join(REPO, "BENCH_LIVE_r19.jsonl")
        recs = load_records(rows, validate=True)
        metrics = {r["metric"] for r in recs}
        assert "obs.digest_publish_ms" in metrics
        assert "obs.digest_quantile_rel_err" in metrics
        rc = regress_main([hist, rows, "--bank"])
        assert rc == 0, capsys.readouterr().out
