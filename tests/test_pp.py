"""Pipeline parallelism: schedules vs the single-device oracle.

The reference can only validate PP by running it on 4 GPUs and eyeballing
the loss (03_pipeline_training.py); here both schedules are checked
numerically against the unpipelined model, including gradients.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hpc.models import losses, pipeline_transformer as ptx
from tpu_hpc.parallel import pp
from tpu_hpc.runtime import MeshSpec, build_mesh

CFG = ptx.PipeConfig(
    vocab_size=64, dim=32, n_heads=2, n_stages=4, layers_per_stage=1,
    max_seq_len=16,
)


@pytest.fixture(scope="module")
def setup():
    mesh = build_mesh(
        MeshSpec(axes={"pipe": 4}), devices=jax.devices()[:4]
    )
    params = ptx.init_pipeline_transformer(jax.random.key(0), CFG)
    tokens = jax.random.randint(
        jax.random.key(1), (8, 16), 0, CFG.vocab_size, dtype=jnp.int32
    )
    targets = jax.random.randint(
        jax.random.key(2), (8, 16), 0, CFG.vocab_size, dtype=jnp.int32
    )
    return mesh, params, tokens, targets


def _pipe_loss_fn(mesh, schedule, n_micro=4, batch_spec=None):
    kwargs = {} if batch_spec is None else {"batch_spec": batch_spec}
    pipe = pp.pipelined(
        ptx.make_stage_fn(CFG), mesh, axis="pipe", schedule=schedule, **kwargs
    )

    def loss(params, tokens, targets):
        xs = ptx.embed(params, pp.microbatch(tokens, n_micro), CFG)
        ys = pipe(params["stages"], xs)
        logits = ptx.head(params, ys, CFG)
        return losses.cross_entropy(logits, pp.microbatch(targets, n_micro))

    return loss


def _oracle_loss(params, tokens, targets):
    logits = ptx.apply_sequential(params, tokens, CFG)
    return losses.cross_entropy(logits, targets)


def _tree_allclose(a, b, atol):
    flat_a, _ = jax.tree_util.tree_flatten_with_path(a)
    flat_b = jax.tree.leaves(b)
    for (path, la), lb in zip(flat_a, flat_b):
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(lb), atol=atol, rtol=1e-3,
            err_msg=f"mismatch at {jax.tree_util.keystr(path)}",
        )


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_forward_matches_oracle(setup, schedule):
    mesh, params, tokens, targets = setup
    loss = jax.jit(_pipe_loss_fn(mesh, schedule))(params, tokens, targets)
    oracle = jax.jit(_oracle_loss)(params, tokens, targets)
    np.testing.assert_allclose(float(loss), float(oracle), atol=1e-5)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_grads_match_oracle(setup, schedule):
    mesh, params, tokens, targets = setup
    g_pipe = jax.jit(jax.grad(_pipe_loss_fn(mesh, schedule)))(
        params, tokens, targets
    )
    g_oracle = jax.jit(jax.grad(_oracle_loss))(params, tokens, targets)
    _tree_allclose(g_pipe, g_oracle, atol=2e-4)


def test_pp_composes_with_dp(setup):
    """PP x DP on a 2D mesh: microbatch dim sharded over data while
    stages shard over pipe (SURVEY 5.7's 3D-composition sketch)."""
    _, params, tokens, targets = setup
    mesh2 = build_mesh(MeshSpec(axes={"data": 2, "pipe": 4}))
    from jax.sharding import PartitionSpec as P

    loss_fn = _pipe_loss_fn(mesh2, "gpipe", batch_spec=P(None, "data"))
    loss = jax.jit(loss_fn)(params, tokens, targets)
    oracle = _oracle_loss(params, tokens, targets)
    np.testing.assert_allclose(float(loss), float(oracle), atol=1e-5)


def test_pp_over_dcn_spanning_pipe_axis(setup):
    """PP with the pipe axis spanning emulated slices (dcn_axes): the
    70B+ layout from ch. 11 -- PP is the bandwidth-tolerant axis that
    belongs on the slice boundary. The stage ppermute must still cross
    the emulated-slice seam correctly."""
    _, params, tokens, targets = setup
    mesh = build_mesh(
        MeshSpec(axes={"data": 2, "pipe": 2}, dcn_axes={"pipe": 2})
    )
    assert mesh.shape == {"data": 2, "pipe": 4}
    from jax.sharding import PartitionSpec as P

    loss_fn = _pipe_loss_fn(mesh, "gpipe", batch_spec=P(None, "data"))
    loss = jax.jit(loss_fn)(params, tokens, targets)
    oracle = _oracle_loss(params, tokens, targets)
    np.testing.assert_allclose(float(loss), float(oracle), atol=1e-5)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_ppxdp_grads_match_oracle(setup, schedule):
    """Regression: 1F1B's custom vjp must psum stage grads over the
    data axis (shard_map's own transpose does this for GPipe; the
    hand-written backward once dropped it, silently training on
    half-batch gradients)."""
    _, params, tokens, targets = setup
    mesh2 = build_mesh(MeshSpec(axes={"data": 2, "pipe": 4}))
    from jax.sharding import PartitionSpec as P

    loss_fn = _pipe_loss_fn(mesh2, schedule, batch_spec=P(None, "data"))
    g_pipe = jax.jit(jax.grad(loss_fn))(params, tokens, targets)
    g_oracle = jax.jit(jax.grad(_oracle_loss))(params, tokens, targets)
    _tree_allclose(g_pipe, g_oracle, atol=2e-4)


def test_bubble_fraction():
    # 4 stages, 8 microbatches: 3 idle ticks of 11 total.
    assert pp.bubble_fraction(4, 8) == pytest.approx(3 / 11)
    assert pp.bubble_fraction(1, 8) == 0.0


def test_microbatch_roundtrip():
    x = jnp.arange(24).reshape(8, 3)
    xs = pp.microbatch(x, 4)
    assert xs.shape == (4, 2, 3)
    np.testing.assert_array_equal(pp.unmicrobatch(xs), x)
    with pytest.raises(ValueError):
        pp.microbatch(x, 3)


def test_manual_stage_step(setup):
    """Educational send/recv hop: stage i's activation lands on i+1
    (parity: 01_manual_model_split.py's explicit dist.send/recv)."""
    mesh, *_ = setup
    shift = pp.manual_stage_step(mesh, axis="pipe")
    x = jnp.arange(8.0).reshape(4, 2)  # row i lives on stage i
    y = np.asarray(shift(x))
    np.testing.assert_array_equal(y[1:], np.asarray(x[:3]))
    np.testing.assert_array_equal(y[0], np.zeros(2))
