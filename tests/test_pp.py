"""Pipeline parallelism: schedules vs the single-device oracle.

The reference can only validate PP by running it on 4 GPUs and eyeballing
the loss (03_pipeline_training.py); here both schedules are checked
numerically against the unpipelined model, including gradients.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hpc.models import losses, pipeline_transformer as ptx
from tpu_hpc.parallel import pp
from tpu_hpc.runtime import MeshSpec, build_mesh

CFG = ptx.PipeConfig(
    vocab_size=64, dim=32, n_heads=2, n_stages=4, layers_per_stage=1,
    max_seq_len=16,
)


@pytest.fixture(scope="module")
def setup():
    mesh = build_mesh(
        MeshSpec(axes={"pipe": 4}), devices=jax.devices()[:4]
    )
    params = ptx.init_pipeline_transformer(jax.random.key(0), CFG)
    tokens = jax.random.randint(
        jax.random.key(1), (8, 16), 0, CFG.vocab_size, dtype=jnp.int32
    )
    targets = jax.random.randint(
        jax.random.key(2), (8, 16), 0, CFG.vocab_size, dtype=jnp.int32
    )
    return mesh, params, tokens, targets


def _pipe_loss_fn(mesh, schedule, n_micro=4, batch_spec=None,
                  backward="remat"):
    kwargs = {} if batch_spec is None else {"batch_spec": batch_spec}
    pipe = pp.pipelined(
        ptx.make_stage_fn(CFG), mesh, axis="pipe", schedule=schedule,
        backward=backward, **kwargs
    )

    def loss(params, tokens, targets):
        xs = ptx.embed(params, pp.microbatch(tokens, n_micro), CFG)
        ys = pipe(params["stages"], xs)
        logits = ptx.head(params, ys, CFG)
        return losses.cross_entropy(logits, pp.microbatch(targets, n_micro))

    return loss


def _oracle_loss(params, tokens, targets):
    logits = ptx.apply_sequential(params, tokens, CFG)
    return losses.cross_entropy(logits, targets)


def _tree_allclose(a, b, atol):
    flat_a, _ = jax.tree_util.tree_flatten_with_path(a)
    flat_b = jax.tree.leaves(b)
    for (path, la), lb in zip(flat_a, flat_b):
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(lb), atol=atol, rtol=1e-3,
            err_msg=f"mismatch at {jax.tree_util.keystr(path)}",
        )


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_forward_matches_oracle(setup, schedule):
    mesh, params, tokens, targets = setup
    loss = jax.jit(_pipe_loss_fn(mesh, schedule))(params, tokens, targets)
    oracle = jax.jit(_oracle_loss)(params, tokens, targets)
    np.testing.assert_allclose(float(loss), float(oracle), atol=1e-5)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_grads_match_oracle(setup, schedule):
    mesh, params, tokens, targets = setup
    g_pipe = jax.jit(jax.grad(_pipe_loss_fn(mesh, schedule)))(
        params, tokens, targets
    )
    g_oracle = jax.jit(jax.grad(_oracle_loss))(params, tokens, targets)
    _tree_allclose(g_pipe, g_oracle, atol=2e-4)


class TestStashBackward:
    """1f1b backward='stash' (the Megatron choice): vjp residuals are
    saved at forward time instead of rematerialized -- 4/3 of ideal
    FLOPs instead of remat's 5/3, numerics identical."""

    def test_grads_match_oracle(self, setup):
        mesh, params, tokens, targets = setup
        g_pipe = jax.jit(jax.grad(
            _pipe_loss_fn(mesh, "1f1b", backward="stash")
        ))(params, tokens, targets)
        g_oracle = jax.jit(jax.grad(_oracle_loss))(params, tokens, targets)
        _tree_allclose(g_pipe, g_oracle, atol=2e-4)

    def test_ppxdp_grads_match_oracle(self, setup):
        from jax.sharding import PartitionSpec as P

        _, params, tokens, targets = setup
        mesh2 = build_mesh(MeshSpec(axes={"data": 2, "pipe": 4}))
        g_pipe = jax.jit(jax.grad(_pipe_loss_fn(
            mesh2, "1f1b", batch_spec=P(None, "data"), backward="stash"
        )))(params, tokens, targets)
        g_oracle = jax.jit(jax.grad(_oracle_loss))(params, tokens, targets)
        _tree_allclose(g_pipe, g_oracle, atol=2e-4)

    def test_stash_ring_wraparound(self, setup):
        # M=16 microbatches through the depth-2S=8 ring: every slot is
        # reused twice -- a slot-collision bug (residuals overwritten
        # before their backward reads them) would corrupt gradients
        # here and nowhere in the smaller oracle tests.
        mesh, params, tokens16, targets16 = setup
        tokens = jnp.tile(tokens16, (2, 1))
        targets = jnp.tile(targets16, (2, 1))
        g_stash = jax.jit(jax.grad(_pipe_loss_fn(
            mesh, "1f1b", n_micro=16, backward="stash"
        )))(params, tokens, targets)
        g_remat = jax.jit(jax.grad(_pipe_loss_fn(
            mesh, "1f1b", n_micro=16, backward="remat"
        )))(params, tokens, targets)
        _tree_allclose(g_stash, g_remat, atol=1e-5)

    def test_stash_rejected_off_1f1b(self, setup):
        mesh, *_ = setup
        with pytest.raises(ValueError, match="only applies to the 1f1b"):
            pp.pipelined(
                ptx.make_stage_fn(CFG), mesh, axis="pipe",
                schedule="gpipe", backward="stash",
            )

    def test_unknown_backward_rejected(self, setup):
        mesh, *_ = setup
        with pytest.raises(ValueError, match="remat|stash"):
            pp.pipelined(
                ptx.make_stage_fn(CFG), mesh, axis="pipe",
                schedule="1f1b", backward="checkpointless",
            )


def test_pp_composes_with_dp(setup):
    """PP x DP on a 2D mesh: microbatch dim sharded over data while
    stages shard over pipe (SURVEY 5.7's 3D-composition sketch)."""
    _, params, tokens, targets = setup
    mesh2 = build_mesh(MeshSpec(axes={"data": 2, "pipe": 4}))
    from jax.sharding import PartitionSpec as P

    loss_fn = _pipe_loss_fn(mesh2, "gpipe", batch_spec=P(None, "data"))
    loss = jax.jit(loss_fn)(params, tokens, targets)
    oracle = _oracle_loss(params, tokens, targets)
    np.testing.assert_allclose(float(loss), float(oracle), atol=1e-5)


def test_pp_over_dcn_spanning_pipe_axis(setup):
    """PP with the pipe axis spanning emulated slices (dcn_axes): the
    70B+ layout from ch. 11 -- PP is the bandwidth-tolerant axis that
    belongs on the slice boundary. The stage ppermute must still cross
    the emulated-slice seam correctly."""
    _, params, tokens, targets = setup
    mesh = build_mesh(
        MeshSpec(axes={"data": 2, "pipe": 2}, dcn_axes={"pipe": 2})
    )
    assert mesh.shape == {"data": 2, "pipe": 4}
    from jax.sharding import PartitionSpec as P

    loss_fn = _pipe_loss_fn(mesh, "gpipe", batch_spec=P(None, "data"))
    loss = jax.jit(loss_fn)(params, tokens, targets)
    oracle = _oracle_loss(params, tokens, targets)
    np.testing.assert_allclose(float(loss), float(oracle), atol=1e-5)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_ppxdp_grads_match_oracle(setup, schedule):
    """Regression: 1F1B's custom vjp must psum stage grads over the
    data axis (shard_map's own transpose does this for GPipe; the
    hand-written backward once dropped it, silently training on
    half-batch gradients)."""
    _, params, tokens, targets = setup
    mesh2 = build_mesh(MeshSpec(axes={"data": 2, "pipe": 4}))
    from jax.sharding import PartitionSpec as P

    loss_fn = _pipe_loss_fn(mesh2, schedule, batch_spec=P(None, "data"))
    g_pipe = jax.jit(jax.grad(loss_fn))(params, tokens, targets)
    g_oracle = jax.jit(jax.grad(_oracle_loss))(params, tokens, targets)
    _tree_allclose(g_pipe, g_oracle, atol=2e-4)


def test_bubble_fraction():
    # 4 stages, 8 microbatches: 3 idle ticks of 11 total.
    assert pp.bubble_fraction(4, 8) == pytest.approx(3 / 11)
    assert pp.bubble_fraction(1, 8) == 0.0


def test_microbatch_roundtrip():
    x = jnp.arange(24).reshape(8, 3)
    xs = pp.microbatch(x, 4)
    assert xs.shape == (4, 2, 3)
    np.testing.assert_array_equal(pp.unmicrobatch(xs), x)
    with pytest.raises(ValueError):
        pp.microbatch(x, 3)


def test_manual_stage_step(setup):
    """Educational send/recv hop: stage i's activation lands on i+1
    (parity: 01_manual_model_split.py's explicit dist.send/recv)."""
    mesh, *_ = setup
    shift = pp.manual_stage_step(mesh, axis="pipe")
    x = jnp.arange(8.0).reshape(4, 2)  # row i lives on stage i
    y = np.asarray(shift(x))
    np.testing.assert_array_equal(y[1:], np.asarray(x[:3]))
    np.testing.assert_array_equal(y[0], np.zeros(2))


class TestInterleaved:
    """Interleaved (virtual-chunk) schedule: 8 model stages round-robin
    on 4 devices (v=2) must match the sequential oracle, forward and
    backward -- the beyond-reference schedule that cuts bubble time by
    the chunk count."""

    CFG8 = ptx.PipeConfig(
        vocab_size=64, dim=32, n_heads=2, n_stages=8,
        layers_per_stage=1, max_seq_len=16,
    )

    def _loss_fn(self, mesh, n_micro=4, v=2, schedule="interleaved"):
        cfg = self.CFG8
        pipe = pp.pipelined(
            ptx.make_stage_fn(cfg), mesh, axis="pipe",
            schedule=schedule, n_chunks=v,
        )

        def loss(params, tokens, targets):
            xs = ptx.embed(params, pp.microbatch(tokens, n_micro), cfg)
            per = [
                jax.tree.map(lambda a: a[g], params["stages"])
                for g in range(cfg.n_stages)
            ]
            stacked = pp.stack_interleaved_stage_params(per, 4)
            ys = pipe(stacked, xs)
            logits = ptx.head(params, ys, cfg)
            return losses.cross_entropy(
                logits, pp.microbatch(targets, n_micro)
            )

        return loss

    @pytest.fixture(scope="class")
    def setup8(self):
        mesh = build_mesh(
            MeshSpec(axes={"pipe": 4}), devices=jax.devices()[:4]
        )
        params = ptx.init_pipeline_transformer(
            jax.random.key(0), self.CFG8
        )
        tokens = jax.random.randint(
            jax.random.key(1), (8, 16), 0, 64, dtype=jnp.int32
        )
        targets = jax.random.randint(
            jax.random.key(2), (8, 16), 0, 64, dtype=jnp.int32
        )
        return mesh, params, tokens, targets

    def _oracle(self, params, tokens, targets):
        logits = ptx.apply_sequential(params, tokens, self.CFG8)
        return losses.cross_entropy(logits, targets)

    @pytest.mark.parametrize(
        "schedule", ["interleaved", "interleaved-1f1b"]
    )
    def test_forward_matches_oracle(self, setup8, schedule):
        mesh, params, tokens, targets = setup8
        loss = jax.jit(self._loss_fn(mesh, schedule=schedule))(
            params, tokens, targets
        )
        oracle = self._oracle(params, tokens, targets)
        np.testing.assert_allclose(float(loss), float(oracle), atol=1e-5)

    @pytest.mark.parametrize(
        "schedule", ["interleaved", "interleaved-1f1b"]
    )
    def test_grads_match_oracle(self, setup8, schedule):
        mesh, params, tokens, targets = setup8
        g = jax.jit(jax.grad(self._loss_fn(mesh, schedule=schedule)))(
            params, tokens, targets
        )
        g_ref = jax.jit(jax.grad(self._oracle))(params, tokens, targets)
        _tree_allclose(g, g_ref, atol=2e-4)

    def test_interleaved_1f1b_reduces_to_1f1b_at_v1(self, setup):
        """v=1: the dilated tick formulas collapse to plain 1F1B's
        exactly, so loss AND grads must match the 1f1b schedule."""
        mesh, params, tokens, targets = setup

        pipe = pp.pipelined(
            ptx.make_stage_fn(CFG), mesh, axis="pipe",
            schedule="interleaved-1f1b", n_chunks=1,
        )

        def loss(params, tokens, targets):
            xs = ptx.embed(params, pp.microbatch(tokens, 4), CFG)
            per = [
                jax.tree.map(lambda a: a[g], params["stages"])
                for g in range(4)
            ]
            ys = pipe(pp.stack_interleaved_stage_params(per, 4), xs)
            logits = ptx.head(params, ys, CFG)
            return losses.cross_entropy(logits, pp.microbatch(targets, 4))

        got = jax.jit(jax.value_and_grad(loss))(params, tokens, targets)
        want = jax.jit(
            jax.value_and_grad(_pipe_loss_fn(mesh, "1f1b"))
        )(params, tokens, targets)
        np.testing.assert_allclose(float(got[0]), float(want[0]), atol=1e-6)
        _tree_allclose(got[1], want[1], atol=1e-5)

    def test_single_chunk_reduces_to_gpipe(self, setup):
        """v=1 on the 4-stage model: same loss as the gpipe schedule."""
        mesh, params, tokens, targets = setup

        pipe = pp.pipelined(
            ptx.make_stage_fn(CFG), mesh, axis="pipe",
            schedule="interleaved", n_chunks=1,
        )

        def loss(params, tokens, targets):
            xs = ptx.embed(params, pp.microbatch(tokens, 4), CFG)
            per = [
                jax.tree.map(lambda a: a[g], params["stages"])
                for g in range(4)
            ]
            ys = pipe(pp.stack_interleaved_stage_params(per, 4), xs)
            logits = ptx.head(params, ys, CFG)
            return losses.cross_entropy(logits, pp.microbatch(targets, 4))

        got = jax.jit(loss)(params, tokens, targets)
        want = jax.jit(_pipe_loss_fn(mesh, "gpipe"))(params, tokens, targets)
        np.testing.assert_allclose(float(got), float(want), atol=1e-6)

    @pytest.mark.parametrize(
        "schedule", ["interleaved", "interleaved-1f1b"]
    )
    def test_indivisible_microbatches_still_correct(self, setup8, schedule):
        """M=2 microbatches on S=4 devices (partial round-robin group):
        the exact tick count makes this legal -- with extra bubble
        ticks, not wrong numerics."""
        mesh, params, tokens, targets = setup8
        got = jax.jit(
            jax.value_and_grad(
                self._loss_fn(mesh, n_micro=2, schedule=schedule)
            )
        )(params, tokens, targets)
        want_loss = self._oracle(params, tokens, targets)
        want_g = jax.jit(jax.grad(self._oracle))(params, tokens, targets)
        np.testing.assert_allclose(
            float(got[0]), float(want_loss), atol=1e-5
        )
        _tree_allclose(got[1], want_g, atol=2e-4)

    @pytest.mark.parametrize(
        "schedule", ["interleaved", "interleaved-1f1b"]
    )
    def test_ppxdp_grads_match_oracle(self, setup8, schedule):
        """Interleaved x DP on a 2D mesh: param grads must include
        every data shard's contribution (shard_map's transpose psums
        them on the autodiff path; the interleaved-1f1b custom_vjp
        must hand-insert the same psum -- pinned like the gpipe/1f1b
        composition tests)."""
        mesh2 = build_mesh(MeshSpec(axes={"data": 2, "pipe": 4}))
        _, params, tokens, targets = setup8
        from jax.sharding import PartitionSpec as P

        cfg = self.CFG8
        pipe = pp.pipelined(
            ptx.make_stage_fn(cfg), mesh2, axis="pipe",
            schedule=schedule, n_chunks=2,
            batch_spec=P(None, "data"),
        )

        def loss(params, tokens, targets):
            xs = ptx.embed(params, pp.microbatch(tokens, 4), cfg)
            ys = pipe(
                pp.interleave_stacked(params["stages"], 4), xs
            )
            logits = ptx.head(params, ys, cfg)
            return losses.cross_entropy(
                logits, pp.microbatch(targets, 4)
            )

        g = jax.jit(jax.grad(loss))(params, tokens, targets)
        g_ref = jax.jit(jax.grad(self._oracle))(params, tokens, targets)
        _tree_allclose(g, g_ref, atol=2e-4)

    def test_interleaved_1f1b_stash_grads_match_oracle(self, setup8):
        # The stash backward on the interleaved schedule: residuals
        # saved per (chunk, slot) at forward time; grads must match
        # the oracle like the remat backward does.
        mesh, params, tokens, targets = setup8
        cfg = self.CFG8
        pipe = pp.pipelined(
            ptx.make_stage_fn(cfg), mesh, axis="pipe",
            schedule="interleaved-1f1b", n_chunks=2, backward="stash",
        )

        def loss(params, tokens, targets):
            xs = ptx.embed(params, pp.microbatch(tokens, 4), cfg)
            per = [
                jax.tree.map(lambda a: a[g], params["stages"])
                for g in range(cfg.n_stages)
            ]
            ys = pipe(pp.stack_interleaved_stage_params(per, 4), xs)
            logits = ptx.head(params, ys, cfg)
            return losses.cross_entropy(
                logits, pp.microbatch(targets, 4)
            )

        g = jax.jit(jax.grad(loss))(params, tokens, targets)
        g_ref = jax.jit(jax.grad(self._oracle))(params, tokens, targets)
        _tree_allclose(g, g_ref, atol=2e-4)

    def test_interleaved_stash_wraparound_and_partial_group(self, setup8):
        # M=14 with S=4, V=2 (DB=3S=12): ring slots wrap AND
        # M % S != 0 exercises the dilated partial-group tail on the
        # stash path.
        mesh, params, tokens, targets = setup8
        cfg = self.CFG8
        tokens14 = jnp.tile(tokens, (2, 1))[:14]
        targets14 = jnp.tile(targets, (2, 1))[:14]
        grads = {}
        for bwd in ("remat", "stash"):
            pipe = pp.pipelined(
                ptx.make_stage_fn(cfg), mesh, axis="pipe",
                schedule="interleaved-1f1b", n_chunks=2, backward=bwd,
            )

            def loss(params, tokens, targets):
                xs = ptx.embed(params, pp.microbatch(tokens, 14), cfg)
                per = [
                    jax.tree.map(lambda a: a[g], params["stages"])
                    for g in range(cfg.n_stages)
                ]
                ys = pipe(pp.stack_interleaved_stage_params(per, 4), xs)
                logits = ptx.head(params, ys, cfg)
                return losses.cross_entropy(
                    logits, pp.microbatch(targets, 14)
                )

            grads[bwd] = jax.jit(jax.grad(loss))(
                params, tokens14, targets14
            )
        _tree_allclose(grads["stash"], grads["remat"], atol=1e-5)

    def test_chunk_mismatch_rejected(self, setup8):
        mesh, params, tokens, targets = setup8
        cfg = self.CFG8
        pipe = pp.pipelined(
            ptx.make_stage_fn(cfg), mesh, axis="pipe",
            schedule="interleaved", n_chunks=4,  # params carry 2
        )

        def loss(params, tokens, targets):
            xs = ptx.embed(params, pp.microbatch(tokens, 4), cfg)
            ys = pipe(pp.interleave_stacked(params["stages"], 4), xs)
            logits = ptx.head(params, ys, cfg)
            return losses.cross_entropy(logits, pp.microbatch(targets, 4))

        with pytest.raises(ValueError, match="chunks per"):
            jax.jit(loss)(params, tokens, targets)

    def test_interleave_stacked_matches_list_helper(self):
        per = [{"w": jnp.full((1,), float(g))} for g in range(8)]
        stacked = pp.stack_stage_params(per)
        a = pp.stack_interleaved_stage_params(per, 4)
        b = pp.interleave_stacked(stacked, 4)
        np.testing.assert_array_equal(
            np.asarray(a["w"]), np.asarray(b["w"])
        )

    def test_interleaved_layout(self):
        per = [{"w": jnp.full((1,), float(g))} for g in range(8)]
        stacked = pp.stack_interleaved_stage_params(per, 4)
        # Position s*v + j holds global stage j*S + s (S=4, v=2).
        order = [float(stacked["w"][i, 0]) for i in range(8)]
        assert order == [0.0, 4.0, 1.0, 5.0, 2.0, 6.0, 3.0, 7.0]

    def test_bubble_shrinks_with_chunks(self):
        assert pp.bubble_fraction(4, 8, n_chunks=2) < pp.bubble_fraction(4, 8)


@pytest.mark.parametrize("schedule,v", [("gpipe", 1), ("interleaved", 2)])
def test_remat_stage_numerics_unchanged(setup, schedule, v):
    """remat_stage trades FLOPs for memory; values must be identical
    (checkpointing recomputes the same forward). The interleaved case
    runs v=2 so checkpointing is exercised against the dynamic
    per-chunk param gather, not a degenerate single-chunk layout."""
    mesh, params, tokens, targets = setup
    cfg = (
        CFG if v == 1
        else ptx.PipeConfig(
            vocab_size=64, dim=32, n_heads=2, n_stages=4 * v,
            layers_per_stage=1, max_seq_len=16,
        )
    )
    if v > 1:
        params = ptx.init_pipeline_transformer(jax.random.key(0), cfg)

    def build(remat):
        pipe = pp.pipelined(
            ptx.make_stage_fn(cfg), mesh, axis="pipe",
            schedule=schedule, n_chunks=v, remat_stage=remat,
        )

        def loss(params, tokens, targets):
            xs = ptx.embed(params, pp.microbatch(tokens, 4), cfg)
            stages = (
                pp.interleave_stacked(params["stages"], 4)
                if schedule == "interleaved" else params["stages"]
            )
            logits = ptx.head(params, pipe(stages, xs), cfg)
            return losses.cross_entropy(
                logits, pp.microbatch(targets, 4)
            )

        return loss

    g_plain = jax.jit(jax.grad(build(False)))(params, tokens, targets)
    g_remat = jax.jit(jax.grad(build(True)))(params, tokens, targets)
    _tree_allclose(g_plain, g_remat, atol=1e-6)


def test_chunks_require_interleaved(setup):
    mesh, *_ = setup
    with pytest.raises(ValueError, match="only applies"):
        pp.pipelined(
            ptx.make_stage_fn(CFG), mesh, axis="pipe",
            schedule="gpipe", n_chunks=2,
        )


def test_remat_stage_rejected_under_1f1b(setup):
    """1f1b's custom_vjp already remats each stage forward; a silently
    ignored remat_stage flag would mislead memory tuning."""
    mesh, *_ = setup
    with pytest.raises(ValueError, match="remat_stage"):
        pp.pipelined(
            ptx.make_stage_fn(CFG), mesh, axis="pipe",
            schedule="1f1b", remat_stage=True,
        )
