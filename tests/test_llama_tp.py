"""Llama-2 model + TP/hybrid plan tests.

What the reference could never unit-test (no cluster-free mode,
SURVEY.md section 4) we verify on the 8-device CPU mesh: model
correctness (shapes, causality, GQA), TP-sharded forward equals
replicated forward numerically, and the hybrid 2D recipe trains.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_hpc.models import datasets, llama2
from tpu_hpc.parallel import hybrid, tp
from tpu_hpc.parallel.plans import pspec_tree, shardings_for


TINY = llama2.LlamaConfig(
    dim=64,
    n_layers=2,
    n_heads=4,
    vocab_size=256,
    multiple_of=32,
    max_seq_len=64,
    dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def tiny_params():
    return llama2.init_llama(jax.random.key(0), TINY)


def test_forward_shape(tiny_params):
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = llama2.apply_llama(tiny_params, tokens, TINY)
    assert logits.shape == (2, 16, TINY.vocab_size)
    assert logits.dtype == jnp.float32


def test_causality(tiny_params):
    """Logits at position t must not depend on tokens after t."""
    rng = np.random.default_rng(0)
    a = rng.integers(0, TINY.vocab_size, (1, 16)).astype(np.int32)
    b = a.copy()
    b[0, 10:] = rng.integers(0, TINY.vocab_size, 6)
    la = llama2.apply_llama(tiny_params, jnp.asarray(a), TINY)
    lb = llama2.apply_llama(tiny_params, jnp.asarray(b), TINY)
    np.testing.assert_allclose(la[0, :10], lb[0, :10], atol=1e-5)
    assert not np.allclose(la[0, 10:], lb[0, 10:], atol=1e-5)


def test_gqa_matches_mha_head_count():
    """GQA param shapes: kv projections carry kv_heads * head_dim."""
    cfg = llama2.LlamaConfig(
        dim=64, n_layers=1, n_heads=8, n_kv_heads=2, vocab_size=64,
        multiple_of=16, dtype=jnp.float32,
    )
    params = llama2.init_llama(jax.random.key(0), cfg)
    att = params["layers_0"]["attention"]
    assert att["wq"]["kernel"].shape == (64, 64)
    assert att["wk"]["kernel"].shape == (64, 2 * cfg.head_dim)
    logits = llama2.apply_llama(
        params, jnp.zeros((1, 8), jnp.int32), cfg
    )
    assert logits.shape == (1, 8, 64)


def test_ffn_hidden_rule():
    """2/3 rule + multiple_of rounding parity (reference :231-272)."""
    cfg = llama2.LlamaConfig(dim=4096, multiple_of=256)
    # int(2*16384/3) = 10922 -> rounded up to 11008 (Llama-2 7B value)
    assert cfg.ffn_hidden == 11008


def test_rope_rotation_is_norm_preserving():
    cos, sin = llama2.rope_cos_sin(16, 8)
    x = jax.random.normal(jax.random.key(1), (2, 16, 4, 8))
    r = llama2.apply_rope(x, cos, sin)
    np.testing.assert_allclose(
        jnp.linalg.norm(x, axis=-1), jnp.linalg.norm(r, axis=-1),
        rtol=1e-5,
    )
    # position 0 is unrotated
    np.testing.assert_allclose(r[:, 0], x[:, 0], atol=1e-6)


def test_tp_rules_cover_llama(tiny_params):
    """Every matmul-bearing param gets a model-axis shard."""
    specs = tp.param_pspecs(tiny_params, tp.llama_rules())
    flat = {
        "/".join(str(getattr(k, "key", k)) for k in path): spec
        for path, spec in jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda x: isinstance(x, P)
        )[0]
    }
    assert flat["tok_embeddings/embedding"] == P("model", None)
    assert flat["layers_0/attention/wq/kernel"] == P(None, "model")
    assert flat["layers_0/attention/wo/kernel"] == P("model", None)
    assert flat["layers_0/feed_forward/w1/kernel"] == P(None, "model")
    assert flat["layers_0/feed_forward/w2/kernel"] == P("model", None)
    assert flat["output/kernel"] == P(None, "model")
    assert flat["norm/scale"] == P()


def test_tp_forward_matches_replicated(mesh_2d, tiny_params):
    """TP-sharded forward == replicated forward (the correctness bar
    the reference asserts by inspection, 01_device_mesh_basics.py:82-87
    -- here it is a numeric equality test)."""
    tokens = jax.random.randint(jax.random.key(2), (4, 16), 0, 256)
    expected = llama2.apply_llama(tiny_params, tokens, TINY)

    specs = tp.param_pspecs(tiny_params, tp.llama_rules())
    sharded = jax.jit(
        lambda t: t, out_shardings=shardings_for(mesh_2d, specs)
    )(tiny_params)

    fn = jax.jit(lambda p, t: llama2.apply_llama(p, t, TINY))
    got = fn(sharded, tokens)
    np.testing.assert_allclose(got, expected, atol=2e-4, rtol=2e-4)


def test_tp_sp_forward_matches_replicated(mesh_2d, tiny_params):
    """Megatron-SP activation constraint preserves numerics."""
    tokens = jax.random.randint(jax.random.key(3), (4, 16), 0, 256)
    expected = llama2.apply_llama(tiny_params, tokens, TINY)

    specs = tp.param_pspecs(tiny_params, tp.llama_rules())
    sharded = jax.jit(
        lambda t: t, out_shardings=shardings_for(mesh_2d, specs)
    )(tiny_params)
    constrain = tp.sp_constrain(mesh_2d, dp_axis="data", sp_axis="model")
    fn = jax.jit(
        lambda p, t: llama2.apply_llama(p, t, TINY, constrain=constrain)
    )
    got = fn(sharded, tokens)
    np.testing.assert_allclose(got, expected, atol=2e-4, rtol=2e-4)


def test_hybrid_pspecs_compose(tiny_params):
    """FSDP extends the TP plan on remaining dims, honoring min_size."""
    specs = hybrid.hybrid_pspecs(
        tiny_params, tp.llama_rules(), data_size=2, min_size=1000
    )
    flat = {
        "/".join(str(getattr(k, "key", k)) for k in path): spec
        for path, spec in jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda x: isinstance(x, P)
        )[0]
    }
    # wq kernel (64, 64): model on dim 1 from TP, data fills dim 0.
    assert flat["layers_0/attention/wq/kernel"] == P("data", "model")
    # embedding (256, 64): model on dim 0, data fills dim 1.
    assert flat["tok_embeddings/embedding"] == P("model", "data")
    # tiny norm scales stay replicated.
    assert flat["norm/scale"] == P()


def test_hybrid_training_step(mesh_2d, tiny_params):
    """Full hybrid FSDPxTP+SP training steps on the 2D mesh (parity:
    fsdp_tp_example.py train loop :203-211). Targets are random tokens
    so loss sits near ln(vocab); we verify the step executes under the
    2D plan, loss is sane, and params actually move."""
    import numpy as np

    from tpu_hpc.config import TrainingConfig
    from tpu_hpc.train import Trainer

    cfg = TrainingConfig(
        epochs=1, steps_per_epoch=4, global_batch_size=4,
        learning_rate=3e-3, weight_decay=0.01,
    )
    ds = datasets.TokenStream(vocab_size=TINY.vocab_size, seq_len=16)
    constrain = tp.sp_constrain(mesh_2d)
    trainer = Trainer(
        cfg,
        mesh_2d,
        llama2.make_forward(TINY, constrain),
        tiny_params,
        param_pspecs=hybrid.hybrid_pspecs(
            tiny_params, tp.llama_rules(), data_size=2, min_size=1000
        ),
        batch_pspec=P("data"),
    )
    w_before = np.asarray(
        jax.device_get(trainer.state.params["output"]["kernel"])
    )
    for i in range(3):
        metrics = trainer.train_step(ds.batch_at(i, 4))
        loss = float(metrics["loss"])
        assert np.isfinite(loss)
        # random targets: loss stays near ln(vocab)
        assert abs(loss - np.log(TINY.vocab_size)) < 1.0
    w_after = np.asarray(
        jax.device_get(trainer.state.params["output"]["kernel"])
    )
    assert not np.allclose(w_before, w_after)


def test_validate_tp_degree():
    tp.validate_tp_degree(8, 8, 4)
    with pytest.raises(ValueError):
        tp.validate_tp_degree(6, 6, 4)
    with pytest.raises(ValueError):
        tp.validate_tp_degree(8, 2, 4)


def test_auto_tp_degree():
    # 8 devices, 8 heads: full TP; cap enforces the node-size rule.
    assert tp.auto_tp_degree(8, 8, 8) == 8
    assert tp.auto_tp_degree(8, 8, 8, cap=4) == 4
    # 6 devices, 8 heads: only 2 divides both.
    assert tp.auto_tp_degree(6, 8, 8) == 2
    # GQA: kv_heads constrains harder than n_heads.
    assert tp.auto_tp_degree(8, 8, 2) == 2
    # Nothing fits -> 1 (pure-DP fallback).
    assert tp.auto_tp_degree(1, 8, 8) == 1
    assert tp.auto_tp_degree(5, 8, 8) == 1


def test_mlp_rules_anchor_on_path_components():
    from jax.sharding import PartitionSpec as P

    from tpu_hpc.parallel.plans import apply_rules

    rules = tp.mlp_rules()
    # 'main' must not be claimed by the 'in' rule, 'group' not by 'up'.
    assert apply_rules(rules, "main/kernel") == P()
    assert apply_rules(rules, "group/kernel") == P()
    assert apply_rules(rules, "in/kernel") == P(None, "model")
    assert apply_rules(rules, "block/up/kernel") == P(None, "model")
    assert apply_rules(rules, "block/down/kernel") == P("model", None)


def test_tp_flash_attn_fn_matches_local(devices):
    """The Pallas-flash-under-shard_map factory (heads on the TP axis,
    batch on data) must reproduce the model's local attention path --
    the production attention configuration for hybrid FSDPxTP
    (fit.py --attn flash, bench.py). On the CPU sim the kernel runs
    its XLA reference path; the sharding layout is what's under test."""
    from tpu_hpc.runtime import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(axes={"data": 2, "model": 4}))
    cfg = llama2.LlamaConfig(
        dim=32, n_layers=2, n_heads=4, vocab_size=64,
        multiple_of=16, max_seq_len=32, dtype=jnp.float32,
    )
    params = llama2.init_llama(jax.random.key(0), cfg)
    tokens = jax.random.randint(
        jax.random.key(1), (4, 32), 0, 64, dtype=jnp.int32
    )
    local = llama2.apply_llama(params, tokens, cfg)
    attn = tp.make_tp_flash_attn_fn(mesh, "data", "model", impl="xla")
    con = tp.sp_constrain(mesh, dp_axis="data", sp_axis="model")
    sharded = jax.jit(
        lambda p, t: llama2.apply_llama(p, t, cfg, con, attn)
    )(params, tokens)
    np.testing.assert_allclose(
        np.asarray(sharded), np.asarray(local), atol=2e-4
    )


def test_tp_flash_attn_fn_single_device_passthrough():
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1), ("data",))
    fn = tp.make_tp_flash_attn_fn(mesh, "data", None, impl="xla")
    q = jax.random.normal(jax.random.key(0), (2, 16, 4, 8))
    out = fn(q, q, q)
    assert out.shape == q.shape
