"""Domain parallelism: halo exchange correctness, the naive-split
failure proof, and gradient equivalence.

Oracle = single-device SAME convolution: the spatially-sharded result
must match it exactly, forward and backward (the property the
reference attributes to ShardTensor, docs/guide/10_domain_parallel.md:
113-149, implemented here with ppermute + autodiff transposition).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hpc.parallel import domain
from tpu_hpc.runtime import MeshSpec, build_mesh


def single_device_conv(x, kernel, wrap=False):
    if wrap:
        kh = kernel.shape[0]
        x = jnp.concatenate(
            [x[:, -(kh // 2):], x, x[:, : kh // 2]], axis=1
        )
        pad_h = (0, 0)
    else:
        pad_h = (kernel.shape[0] // 2,) * 2
    return jax.lax.conv_general_dilated(
        x, kernel, (1, 1),
        (pad_h, (kernel.shape[1] // 2,) * 2),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


@pytest.fixture(scope="module")
def spatial_mesh(devices):
    return build_mesh(MeshSpec(axes={"data": 2, "spatial": 4}))


def rand_case(key, b=2, h=32, w=16, cin=3, cout=5, k=3):
    kx, kk = jax.random.split(key)
    x = jax.random.normal(kx, (b, h, w, cin), jnp.float32)
    kernel = jax.random.normal(kk, (k, k, cin, cout), jnp.float32) * 0.1
    return x, kernel


class TestNaiveSplitFails:
    def test_boundary_corruption(self, spatial_mesh):
        """The reference's teaching demo (10_domain_parallel.md:69-86)
        as an executable assertion: naive per-tile padding corrupts
        seam rows; interior rows are fine."""
        x, kernel = rand_case(jax.random.key(0))
        naive = domain.domain_parallel(
            lambda ax, p, t: domain.naive_split_conv2d(
                t, p, axis_name=ax
            ),
            spatial_mesh,
        )
        got = np.asarray(jax.jit(naive)(kernel, x))
        want = np.asarray(single_device_conv(x, kernel))
        # Seam rows (tile edges at multiples of H/4 = 8) are WRONG...
        assert not np.allclose(got, want, atol=1e-5)
        # ...but each tile's interior is untouched.
        np.testing.assert_allclose(
            got[:, 1:7], want[:, 1:7], atol=1e-5
        )
        seam_err = np.abs(got[:, 7:9] - want[:, 7:9]).max()
        assert seam_err > 1e-3


class TestHaloConv:
    def test_matches_single_device(self, spatial_mesh):
        x, kernel = rand_case(jax.random.key(1))
        halo = domain.domain_parallel(
            lambda ax, p, t: domain.halo_conv2d(t, p, axis_name=ax),
            spatial_mesh,
        )
        got = jax.jit(halo)(kernel, x)
        want = single_device_conv(x, kernel)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_5x5_kernel_two_row_halo(self, spatial_mesh):
        x, kernel = rand_case(jax.random.key(2), k=5)
        halo = domain.domain_parallel(
            lambda ax, p, t: domain.halo_conv2d(t, p, axis_name=ax),
            spatial_mesh,
        )
        got = jax.jit(halo)(kernel, x)
        want = single_device_conv(x, kernel)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_periodic_wrap(self, spatial_mesh):
        """wrap=True closes the ring -- the periodic-longitude case."""
        x, kernel = rand_case(jax.random.key(3))
        halo = domain.domain_parallel(
            lambda ax, p, t: domain.halo_conv2d(
                t, p, axis_name=ax, wrap=True
            ),
            spatial_mesh,
        )
        got = jax.jit(halo)(kernel, x)
        want = single_device_conv(x, kernel, wrap=True)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_stacked_convs(self, spatial_mesh):
        """Two chained halo convs == two chained SAME convs (halos
        re-exchanged between layers)."""
        x, k1 = rand_case(jax.random.key(4), cout=3)
        k2 = jax.random.normal(
            jax.random.key(5), (3, 3, 3, 2), jnp.float32
        ) * 0.1

        def stack(ax, params, t):
            a, b = params
            h = jax.nn.relu(domain.halo_conv2d(t, a, axis_name=ax))
            return domain.halo_conv2d(h, b, axis_name=ax)

        halo = domain.domain_parallel(stack, spatial_mesh)
        got = jax.jit(halo)((k1, k2), x)
        want = single_device_conv(
            jax.nn.relu(single_device_conv(x, k1)), k2
        )
        np.testing.assert_allclose(got, want, atol=1e-5)


class TestGradients:
    def test_grad_matches_single_device(self, spatial_mesh):
        """mean(conv).grad across tile boundaries equals the
        single-device gradient -- what ShardTensor calls
        'gradient-correct reductions' (10_domain_parallel.md:123-141),
        obtained here purely from ppermute's linear transpose."""
        x, kernel = rand_case(jax.random.key(6))
        halo = domain.domain_parallel(
            lambda ax, p, t: domain.halo_conv2d(t, p, axis_name=ax),
            spatial_mesh,
        )

        def loss_halo(kernel, x):
            return jnp.mean(halo(kernel, x) ** 2)

        def loss_ref(kernel, x):
            return jnp.mean(single_device_conv(x, kernel) ** 2)

        gk, gx = jax.jit(jax.grad(loss_halo, argnums=(0, 1)))(kernel, x)
        gk_ref, gx_ref = jax.grad(loss_ref, argnums=(0, 1))(kernel, x)
        np.testing.assert_allclose(gk, gk_ref, atol=1e-5)
        np.testing.assert_allclose(gx, gx_ref, atol=1e-5)


class TestDomainFsdpComposition:
    def test_fsdp_sharded_kernel_matches_oracle(self, spatial_mesh):
        """Domain + FSDP in one step (the reference's advertised
        domain+FSDP script, 10_domain_parallel.md:156-172): the conv
        kernel ZeRO-3-sharded over 'data' while its input rides
        spatial halos -- forward and kernel-gradient must still equal
        the single-device oracle."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        x, kernel = rand_case(jax.random.key(11), cin=4, cout=4)
        # Shard the kernel's output-channel dim over data (ZeRO-3);
        # XLA all-gathers it before the conv, reduce-scatters dk.
        kernel = jax.device_put(
            kernel, NamedSharding(spatial_mesh, P(None, None, None, "data"))
        )
        x = jax.device_put(
            x, NamedSharding(spatial_mesh, P("data", "spatial"))
        )
        halo = domain.domain_parallel(
            lambda ax, p, t: domain.halo_conv2d(t, p, axis_name=ax),
            spatial_mesh,
        )

        def loss_halo(kernel, x):
            return jnp.mean(halo(kernel, x) ** 2)

        val, gk = jax.jit(
            jax.value_and_grad(loss_halo)
        )(kernel, x)
        ref_loss = lambda k, x: jnp.mean(  # noqa: E731
            single_device_conv(x, k) ** 2
        )
        k_host, x_host = jax.device_get(kernel), jax.device_get(x)
        gk_ref = jax.grad(ref_loss)(k_host, x_host)
        np.testing.assert_allclose(
            jax.device_get(gk), gk_ref, atol=1e-5
        )
        np.testing.assert_allclose(
            float(val), float(ref_loss(k_host, x_host)), atol=1e-5
        )


class TestHaloExchange:
    def test_halo_contents(self, spatial_mesh):
        """Each tile's pad rows are exactly the neighbor's edge rows
        (zeros at the global boundary)."""
        h_loc = 8
        x = jnp.arange(2 * 32 * 4 * 1, dtype=jnp.float32).reshape(
            2, 32, 4, 1
        )
        padded = domain.domain_parallel(
            lambda ax, p, t: domain.halo_exchange(t, ax, 1),
            spatial_mesh,
        )(None, x)
        # Global result has shape [2, 4*(h_loc+2), 4, 1]; tile i spans
        # rows [i*10, (i+1)*10).
        padded = np.asarray(padded)
        x = np.asarray(x)
        for i in range(4):
            tile = padded[:, i * 10:(i + 1) * 10]
            if i == 0:
                np.testing.assert_allclose(tile[:, 0], 0.0)
            else:
                np.testing.assert_allclose(
                    tile[:, 0], x[:, i * h_loc - 1]
                )
            np.testing.assert_allclose(
                tile[:, 1:9], x[:, i * h_loc:(i + 1) * h_loc]
            )
            if i == 3:
                np.testing.assert_allclose(tile[:, 9], 0.0)
            else:
                np.testing.assert_allclose(
                    tile[:, 9], x[:, (i + 1) * h_loc]
                )

    def test_halo_too_large(self, spatial_mesh):
        x = jnp.zeros((2, 32, 4, 1))
        with pytest.raises(ValueError):
            domain.domain_parallel(
                lambda ax, p, t: domain.halo_exchange(t, ax, 9),
                spatial_mesh,
            )(None, x)


def test_halo_conv2d_rejects_uneven_stride():
    """A stride that does not divide the local tile height would make
    devices emit fractional output rows; it must refuse rather than
    silently diverge from the oracle. (Strided convs themselves are
    supported -- see tests/test_domain_unet.py.)"""
    import jax

    x = jnp.zeros((1, 8, 8, 1))
    kern = jnp.zeros((3, 3, 1, 1))
    with pytest.raises(ValueError, match="divide by stride"):
        jax.eval_shape(
            lambda: domain.halo_conv2d(
                x, kern, axis_name="spatial", stride=3
            )
        )


class TestGlobalExtentOverrides:
    """halo_conv2d's global_h/global_w explicit-override semantics:
    None derives from the tile; a GIVEN value must be validated, and a
    falsy 0 must error instead of silently falling back to the local
    extent (ADVICE r5)."""

    def test_explicit_global_matches_default(self, spatial_mesh):
        x, kernel = rand_case(jax.random.key(11))
        def conv(gh, gw):
            fn = domain.domain_parallel(
                lambda ax, p, t: domain.halo_conv2d(
                    t, p, axis_name=ax, global_h=gh, global_w=gw
                ),
                spatial_mesh,
            )
            return jax.jit(fn)(kernel, x)
        np.testing.assert_allclose(
            conv(32, 16), conv(None, None), atol=1e-6
        )

    @pytest.mark.parametrize("gh,gw", [(0, None), (None, 0), (-4, None)])
    def test_zero_or_negative_rejected(self, spatial_mesh, gh, gw):
        x, kernel = rand_case(jax.random.key(12))
        fn = domain.domain_parallel(
            lambda ax, p, t: domain.halo_conv2d(
                t, p, axis_name=ax, global_h=gh, global_w=gw
            ),
            spatial_mesh,
        )
        with pytest.raises(ValueError, match="global_[hw]"):
            jax.jit(fn)(kernel, x)

    def test_non_multiple_global_h_rejected(self, spatial_mesh):
        # H_loc = 32/4 = 8; a global H of 30 cannot tile into it.
        x, kernel = rand_case(jax.random.key(13))
        fn = domain.domain_parallel(
            lambda ax, p, t: domain.halo_conv2d(
                t, p, axis_name=ax, global_h=30
            ),
            spatial_mesh,
        )
        with pytest.raises(ValueError, match="multiple of the"):
            jax.jit(fn)(kernel, x)
