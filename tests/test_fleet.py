"""The serving fleet (serve/fleet.py): chaos matrix + satellites.

Failure-mode matrix (each row is detect -> recover -> pinned here):

* replica kill mid-decode  -> heartbeat timeout -> redispatch from
  prompt + committed tokens; greedy streams BYTE-IDENTICAL to the
  no-failure run, zero lost requests, zero recompiles;
* corrupt weight swap      -> content-checksum catch -> rollback; the
  old weights keep serving byte-identically and the update aborts;
* slow replica             -> cross-replica tick watermark -> router
  sheds new load away from it before the SLO classes pay;
* scale-down               -> drain-before-release: the parked
  replica finishes every in-flight decode first;
* diurnal + kill + swap    -> the end-to-end acceptance: zero shed
  above the SLO-class floor, zero lost, recompiles 0, and the banked
  fleet rows pass ``regress --bank`` against the committed history.

Satellites pinned here too: typed fleet fault-spec parsing (shared
parse helper), the meter request_shed protocol (no hasattr
duck-check), jittered restart backoff reuse, and the regress/report
fleet namespace.

All engines are tiny fp32 paged engines on 2-device sim-mesh slices
(8 devices / 4 replicas), chunked prefill on -- the redispatch
replay's prompt+committed can exceed any single bucket.
"""
import json
import time

import jax
import jax.numpy as jnp
import pytest

from tpu_hpc import obs
from tpu_hpc.loadgen import (
    FAULT_DEFAULTS,
    LoadHarness,
    build_scenario,
    parse_faults,
)
from tpu_hpc.models import llama2
from tpu_hpc.obs.regress import (
    lower_is_better,
    main as regress_main,
    report_metrics,
)
from tpu_hpc.obs.report import build_report
from tpu_hpc.obs.schema import load_records, validate_record
from tpu_hpc.serve import (
    ContinuousBatcher,
    Engine,
    PagedConfig,
    Request,
    ServeConfig,
    ServeMeter,
)
from tpu_hpc.serve.fleet import (
    DRAINING,
    LIVE,
    STANDBY,
    FleetConfig,
    FleetHarness,
    build_fleet_engines,
)

import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = llama2.LlamaConfig(
    dim=64, n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=128,
    multiple_of=16, max_seq_len=64, dtype=jnp.float32,
)
SERVE = ServeConfig(slots=4, max_seq_len=48, prefill_buckets=(8, 16))
PAGED = PagedConfig(block_size=4, num_blocks=48, prefill_chunk=8)
MAX_PROMPT, MAX_NEW = 16, 6
N_REPLICAS = 4


@pytest.fixture(scope="module")
def fleet_params():
    return llama2.init_llama(jax.random.key(0), TINY)


@pytest.fixture(scope="module")
def fleet_params_v2():
    return llama2.init_llama(jax.random.key(1), TINY)


@pytest.fixture(scope="module")
def fleet_engines(fleet_params, devices):
    """Four warmed paged replicas on disjoint 2-device slices --
    shared across the module (warmup is the expensive part); each
    test resets pools + weights via the ``engines`` fixture below."""
    engines = build_fleet_engines(
        fleet_params, TINY, SERVE, PAGED, N_REPLICAS
    )
    for e in engines:
        e._params0 = e.params   # the reset target
    return engines


@pytest.fixture()
def engines(fleet_engines):
    """Fresh-state view of the shared engines: pools flushed, original
    weights restored -- so chaos tests cannot leak state into each
    other through the module-scoped executables."""
    for e in fleet_engines:
        e.reset_pool(force=True)
        if e.params is not e._params0:
            e.swap_params(e._params0)
    return fleet_engines


@pytest.fixture()
def scoped_obs(tmp_path):
    bus = obs.EventBus(path=None, run_id="fleet-test",
                       flight_dir=str(tmp_path))
    reg = obs.MetricsRegistry()
    prev_bus, prev_reg = obs.set_bus(bus), obs.set_registry(reg)
    yield bus, reg
    obs.set_bus(prev_bus)
    obs.set_registry(prev_reg)


def _scenario(name, seed=7, n=16, rate=40.0):
    return build_scenario(
        name, seed=seed, n_requests=n, vocab_size=TINY.vocab_size,
        max_prompt=MAX_PROMPT, max_new=MAX_NEW, rate_per_s=rate,
    )


def _cfg(**kw):
    kw.setdefault("initial_replicas", 2)
    kw.setdefault("min_replicas", 2)
    kw.setdefault("max_replicas", 2)
    return FleetConfig(**kw)


def _run(engines, scenario, cfg, faults="", path=None, **kw):
    harness = FleetHarness(
        engines[:cfg.max_replicas or len(engines)], scenario,
        cfg, metrics_path=str(path) if path else None,
        faults=parse_faults(faults), **kw,
    )
    n0 = harness.fleet.compile_count_total()
    summary = harness.run(n_devices=jax.device_count())
    summary["_recompiles"] = harness.fleet.compile_count_total() - n0
    return summary, harness


# ---------------------------------------------------------------------
# satellite: typed fault-spec parsing on the shared helper
# ---------------------------------------------------------------------
class TestFleetFaultParsing:
    def test_defaults_cover_fleet_keys(self):
        got = parse_faults("")
        assert got == dict(FAULT_DEFAULTS)
        assert got["replica_kill_at"] is None
        assert got["swap_corrupt"] is False
        assert got["slow_replica"] is None

    def test_fleet_keys_parse(self):
        got = parse_faults(
            "replica_kill_at=12, swap_corrupt=1, slow_replica=2:3.5"
        )
        assert got["replica_kill_at"] == 12
        assert got["swap_corrupt"] is True
        assert got["slow_replica"] == (2, 3.5)

    @pytest.mark.parametrize("spec,frag", [
        ("replica_kill_at=-1", "non-negative integer"),
        ("replica_kill_at=soon", "non-negative integer"),
        ("swap_corrupt=2", "0 or 1"),
        ("slow_replica=3", "<replica>:<factor>"),
        ("slow_replica=a:2", "<replica>:<factor>"),
        ("slow_replica=1:0", "<replica>:<factor>"),
    ])
    def test_malformed_values_name_key_spec_and_type(
        self, spec, frag
    ):
        key = spec.split("=")[0]
        with pytest.raises(ValueError) as e:
            parse_faults(spec)
        msg = str(e.value)
        # The typed-error contract: key + full spec + expected type.
        assert key in msg and spec in msg and frag in msg

    def test_shared_helper_with_resilience_faults(self):
        # One parse loop for both fault env vars: TPU_HPC_FAULTS
        # rides the same helper, same message shape.
        from tpu_hpc.resilience.faults import fault_plan_from_env

        with pytest.raises(ValueError, match="unknown fault key"):
            fault_plan_from_env({"TPU_HPC_FAULTS": "kill_at=3"})
        with pytest.raises(ValueError, match="expected an integer"):
            fault_plan_from_env(
                {"TPU_HPC_FAULTS": "kill_at_step=soon"}
            )

    def test_single_engine_harness_rejects_fleet_faults(self):
        # A fleet fault on the single-engine harness must fail loudly
        # -- silently injecting nothing would make its chaos test
        # pass vacuously.
        with pytest.raises(ValueError, match="fleet fault"):
            LoadHarness(
                object(), _scenario("steady"),
                faults=parse_faults("replica_kill_at=5"),
            )
        # replica_kill_at=0 is a legal ARMED value that compares
        # equal to False -- the guard must use identity, not
        # membership (review finding).
        with pytest.raises(ValueError, match="fleet fault"):
            LoadHarness(
                object(), _scenario("steady"),
                faults=parse_faults("replica_kill_at=0"),
            )

    def test_harness_rejects_slow_index_out_of_range(
        self, engines
    ):
        with pytest.raises(ValueError, match="nonexistent replica"):
            FleetHarness(
                engines[:2], _scenario("steady"), _cfg(),
                faults=parse_faults("slow_replica=7:3"),
            )

    def test_harness_rejects_corrupt_fault_without_a_swap(
        self, engines
    ):
        # swap_corrupt with nothing scheduled to corrupt injects
        # nothing -- same vacuous-chaos class as a typoed key.
        with pytest.raises(ValueError, match="swap_corrupt"):
            FleetHarness(
                engines[:2], _scenario("steady"), _cfg(),
                faults=parse_faults("swap_corrupt=1"),
            )


# ---------------------------------------------------------------------
# satellite: the meter request_shed protocol (no hasattr duck-check)
# ---------------------------------------------------------------------
class _FakeSlabEngine:
    is_paged = False
    spec = None
    serve_cfg = ServeConfig(
        slots=1, max_seq_len=32, prefill_buckets=(8,)
    )


class TestMeterShedProtocol:
    def test_typoed_meter_loses_shed_loudly(self):
        class BadMeter:
            clock = staticmethod(time.perf_counter)

            def submitted(self, rid):
                pass

            # request_shed misspelled: the old hasattr duck-check
            # silently dropped shed telemetry; now it must raise.
            def request_sched(self, rid, reason=""):
                pass

        batcher = ContinuousBatcher(
            _FakeSlabEngine(), meter=BadMeter()
        )
        req = Request(rid="r0", prompt=[1, 2], max_new_tokens=2)
        batcher.submit(req)
        with pytest.raises(AttributeError, match="request_shed"):
            batcher._shed(req, "test", 1.0)

    def test_base_meter_implements_the_protocol(self):
        meter = ServeMeter()
        batcher = ContinuousBatcher(_FakeSlabEngine(), meter=meter)
        req = Request(rid="r0", prompt=[1, 2], max_new_tokens=2)
        batcher.submit(req)
        batcher._shed(req, "test", 1.0)
        assert meter.shed == 1


# ---------------------------------------------------------------------
# engine-side swap primitives
# ---------------------------------------------------------------------
class TestEngineSwap:
    def test_swap_params_zero_recompiles(
        self, engines, fleet_params_v2
    ):
        from tpu_hpc.serve.weights import place_params

        e = engines[0]
        before = e.compile_count
        placed = place_params(
            fleet_params_v2, e.mesh, e.param_pspecs
        )
        e.swap_params(placed)
        assert e.compile_count == before
        e.swap_params(e._params0)
        assert e.compile_count == before

    def test_swap_params_rejects_shape_mismatch(self, engines):
        other_cfg = llama2.LlamaConfig(
            dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
            vocab_size=128, multiple_of=16, max_seq_len=64,
            dtype=jnp.float32,
        )
        other = llama2.init_llama(jax.random.key(2), other_cfg)
        with pytest.raises(ValueError, match="swap_params"):
            engines[0].swap_params(other)

    def test_reset_pool_refuses_undrained(self, engines):
        e = engines[0]
        e.admit(0, list(range(8)), 4)
        with pytest.raises(RuntimeError, match="undrained"):
            e.reset_pool()
        e.reset_pool(force=True)
        e.allocator.check_invariant()
        assert e.allocator.used_blocks == 0


# ---------------------------------------------------------------------
# router: prefix affinity vs the round-robin control
# ---------------------------------------------------------------------
class TestRouterAffinity:
    def test_affinity_holds_single_replica_hit_rate(
        self, engines, scoped_obs
    ):
        """The acceptance bar: fleet-aggregate hit rate with affinity
        routing >= the single-replica hit rate on the same schedule;
        round-robin (the degraded control) lands strictly below
        affinity -- it divides every tenant's prefix across N cold
        tries."""
        # Single-replica baseline on the same seeded schedule.
        single = LoadHarness(engines[0], _scenario("shared_prefix",
                                                   n=24))
        single.drive()
        s = engines[0].paged_summary()
        single_rate = s["prefix_hit_rate"]
        assert single_rate > 0

        for e in engines:
            e.reset_pool(force=True)
        sa, _ = _run(
            engines, _scenario("shared_prefix", n=24),
            _cfg(router="affinity"),
        )
        for e in engines:
            e.reset_pool(force=True)
        sr, _ = _run(
            engines, _scenario("shared_prefix", n=24),
            _cfg(router="round_robin"),
        )
        assert sa["prefix_affinity_hit_rate"] >= single_rate - 1e-9
        assert sr["prefix_affinity_hit_rate"] \
            < sa["prefix_affinity_hit_rate"]
        assert sa["lost_requests"] == 0
        assert sr["lost_requests"] == 0

    def test_router_skips_draining_and_dead(self, engines):
        harness = FleetHarness(
            engines[:2], _scenario("steady", n=4), _cfg(),
            faults=parse_faults(""),
        )
        fleet = harness.fleet
        fleet.replicas[0].status = DRAINING
        req = Request(rid="x0", prompt=list(range(8)),
                      max_new_tokens=2)
        assert fleet.route(req).idx == 1
        fleet.replicas[1].status = STANDBY
        assert fleet.route(req) is None
        fleet.replicas[0].status = LIVE
        fleet.replicas[1].status = LIVE


# ---------------------------------------------------------------------
# chaos: replica kill -> redispatch (tier-1 representative)
# ---------------------------------------------------------------------
class TestKillRedispatch:
    def test_kill_mid_decode_redispatch_byte_identical(
        self, engines, scoped_obs, tmp_path
    ):
        clean, h0 = _run(engines, _scenario("steady", n=12), _cfg())
        res_clean = dict(h0.fleet.results)
        assert clean["lost_requests"] == 0

        for e in engines:
            e.reset_pool(force=True)
        path = tmp_path / "kill.jsonl"
        chaos, h1 = _run(
            engines, _scenario("steady", n=12), _cfg(),
            faults="replica_kill_at=8", path=path,
        )
        fl = chaos["fleet"]
        assert fl["replica_down"] == 1
        assert fl["redispatched"] >= 1
        assert chaos["lost_requests"] == 0
        assert chaos["shed"] == 0
        assert chaos["_recompiles"] == 0
        # THE redispatch contract: every resumed greedy stream is
        # byte-identical to the no-failure run.
        assert dict(h1.fleet.results) == res_clean
        # The evidence trail is schema-valid and names the failure.
        records = load_records(str(path), validate=True)
        kinds = {r["event"] for r in records}
        assert "replica_down" in kinds and "redispatch" in kinds
        down = [r for r in records if r["event"] == "replica_down"]
        assert down[0]["reason"] == "heartbeat_timeout"
        assert down[0]["redispatched"] == fl["redispatched"]

    def test_dead_replica_restarts_with_backoff_and_serves(
        self, engines, scoped_obs, tmp_path
    ):
        """The jittered-backoff restart path (resilience/retry
        reused): after the kill, the replica comes back, and traffic
        spread over a long window lands on it again."""
        path = tmp_path / "restart.jsonl"
        chaos, h = _run(
            engines, _scenario("steady", n=24, rate=15.0), _cfg(),
            faults="replica_kill_at=6", path=path,
        )
        fl = chaos["fleet"]
        assert fl["replica_down"] == 1
        assert fl["restarts"] == 1
        assert chaos["lost_requests"] == 0
        ups = [
            r for r in load_records(str(path), validate=True)
            if r["event"] == "replica_up"
        ]
        assert any(r["reason"] == "restart" for r in ups)
        # The restarted replica rejoined the serving set.
        assert len(h.fleet.live) == 2


# ---------------------------------------------------------------------
# chaos: weight hot-swap (clean + corrupt -> rollback)
# ---------------------------------------------------------------------
class TestWeightSwap:
    def test_clean_swap_rolls_through_fleet(
        self, engines, scoped_obs, fleet_params_v2, tmp_path
    ):
        path = tmp_path / "swap.jsonl"
        s, h = _run(
            engines, _scenario("steady", n=12), _cfg(),
            path=path, swap_at=6, swap_weights=fleet_params_v2,
        )
        fl = s["fleet"]
        assert fl["weights_version"] == 1
        assert fl["swapped_replicas"] >= 1
        assert fl["swap_rollbacks"] == 0
        assert s["lost_requests"] == 0
        assert s["_recompiles"] == 0
        events = [
            r for r in load_records(str(path), validate=True)
            if r["event"] == "weight_swap"
        ]
        statuses = [r["status"] for r in events]
        assert "drain_start" in statuses and "swapped" in statuses
        # Post-run, every live replica runs the new version, and a
        # fresh request is served by the NEW weights (its stream
        # differs from the old model's continuation).
        assert all(
            r.weights_version == 1 for r in h.fleet.live
        )
        assert fl["mixed_weights"] is False

    def test_corrupt_swap_checksum_rollback_old_weights_serve(
        self, engines, scoped_obs, fleet_params_v2, tmp_path
    ):
        clean, h0 = _run(engines, _scenario("steady", n=12), _cfg())
        res_clean = dict(h0.fleet.results)
        for e in engines:
            e.reset_pool(force=True)
        path = tmp_path / "corrupt.jsonl"
        s, h1 = _run(
            engines, _scenario("steady", n=12), _cfg(),
            faults="swap_corrupt=1", path=path,
            swap_at=6, swap_weights=fleet_params_v2,
        )
        fl = s["fleet"]
        assert fl["swap_rollbacks"] == 1
        assert fl["swapped_replicas"] == 0
        assert fl["weights_version"] == 0   # update aborted
        # First-replica corruption aborts before anything swapped:
        # the fleet stays version-uniform (a LATER-replica corruption
        # would leave it mixed, and this flag is how that surfaces).
        assert fl["mixed_weights"] is False
        assert s["lost_requests"] == 0
        # Old weights kept serving: byte-identical to the clean run.
        assert dict(h1.fleet.results) == res_clean
        events = [
            r for r in load_records(str(path), validate=True)
            if r["event"] == "weight_swap"
        ]
        statuses = [r["status"] for r in events]
        assert "corrupt" in statuses and "rolled_back" in statuses
        corrupt = [r for r in events if r["status"] == "corrupt"]
        assert corrupt[0]["mismatched"] >= 1


# ---------------------------------------------------------------------
# chaos: slow replica -> router sheds load away
# ---------------------------------------------------------------------
class TestSlowReplica:
    def test_router_routes_away_from_slow_replica(
        self, engines, scoped_obs
    ):
        """Detection protects NEW load: requests already decoding on
        the slow replica pay its inter-token latency (nothing short
        of migration could save them), but once the cross-replica
        watermark warms, arrivals route to healthy replicas --
        ownership must skew healthy, and the virtual makespan must
        beat the no-detection control (slow_factor set beyond
        reach): with detection the healthy replica absorbs the mix
        at 1x decode speed instead of half the requests grinding at
        the fault's factor."""
        s, h = _run(
            engines, _scenario("multi_tenant", n=32, rate=60.0),
            _cfg(health_window=2),
            faults="slow_replica=1:8",
        )
        assert s["lost_requests"] == 0
        owners = list(h.fleet.owner.values())
        assert owners.count(0) > owners.count(1)

        for e in engines:
            e.reset_pool(force=True)
        blind, _ = _run(
            engines, _scenario("multi_tenant", n=32, rate=60.0),
            _cfg(health_window=2, slow_factor=1e9),
            faults="slow_replica=1:8",
        )
        assert blind["lost_requests"] == 0
        assert s["wall_s"] < blind["wall_s"]


# ---------------------------------------------------------------------
# autoscaler
# ---------------------------------------------------------------------
class TestAutoscale:
    def test_scale_down_drains_before_release(
        self, engines, scoped_obs, tmp_path
    ):
        path = tmp_path / "scale.jsonl"
        s, h = _run(
            engines, _scenario("steady", n=16, rate=10.0),
            _cfg(initial_replicas=2, min_replicas=1,
                 max_replicas=2, scale_window=6, scale_cooldown=8),
            path=path,
        )
        fl = s["fleet"]
        assert fl["scale_downs"] >= 1
        # Drain-before-release: nothing was lost or shed to the
        # shrink, and the shrink event fired on an EMPTY replica
        # (the batcher is parked only after its last eviction).
        assert s["lost_requests"] == 0
        assert s["shed"] == 0
        parked = [
            r for r in h.fleet.replicas if r.status == STANDBY
        ]
        assert parked and all(r.batcher is None for r in parked)

    def test_scale_up_on_saturation(self, engines, scoped_obs):
        s, h = _run(
            engines, _scenario("saturating_burst", n=32),
            FleetConfig(
                initial_replicas=1, min_replicas=1, max_replicas=2,
                scale_window=4, scale_cooldown=4,
                scale_up_occupancy=0.7,
            ),
        )
        fl = s["fleet"]
        assert fl["scale_ups"] >= 1
        assert fl["live_max"] == 2
        assert s["lost_requests"] == 0


# ---------------------------------------------------------------------
# the end-to-end acceptance: diurnal + mid-run swap + replica kill
# ---------------------------------------------------------------------
class TestDiurnalEndToEnd:
    def test_diurnal_with_swap_and_kill_no_loss_no_shed_above_floor(
        self, engines, scoped_obs, fleet_params, tmp_path
    ):
        """The PR's acceptance run: diurnal traffic, a mid-run model
        update AND a replica kill. Zero shed above the SLO-class
        floor, zero lost requests, recompiles 0 -- and the streams
        are byte-identical to the no-failure replay (the update
        republishes the same weights, so the swap machinery runs
        end-to-end -- checksum, drain, place, pool flush -- without
        changing the greedy oracle)."""
        sc = _scenario("diurnal", seed=11, n=32, rate=80.0)
        clean, h0 = _run(
            engines, sc,
            _cfg(initial_replicas=2, min_replicas=2,
                 max_replicas=3),
        )
        res_clean = dict(h0.fleet.results)
        for e in engines:
            e.reset_pool(force=True)
        path = tmp_path / "diurnal.jsonl"
        s, h1 = _run(
            engines, _scenario("diurnal", seed=11, n=32, rate=80.0),
            _cfg(initial_replicas=2, min_replicas=2,
                 max_replicas=3),
            faults="replica_kill_at=20", path=path,
            swap_at=30, swap_weights=fleet_params,
        )
        fl = s["fleet"]
        assert fl["replica_down"] == 1
        assert fl["swapped_replicas"] >= 1
        assert s["lost_requests"] == 0
        assert s["_recompiles"] == 0
        # Zero shed above the SLO-class floor (background is the
        # floor class -- the only one admission control may drop).
        for name, t in s["tenants"].items():
            if name != "background":
                assert t["shed"] == 0, name
        assert dict(h1.fleet.results) == res_clean
        # The run's JSONL is one schema-valid evidence trail, and the
        # report's fleet section reconstructs the story.
        records = load_records(str(path), validate=True)
        rep = build_report(records)
        assert rep["fleet"] is not None
        assert rep["fleet"]["replica_down"] == 1
        assert rep["fleet"]["redispatched"] == fl["redispatched"]
        flat = report_metrics(rep)
        assert flat["fleet.replica_down"] == 1.0
        assert "fleet.prefix_affinity_hit_rate" in flat


class TestChaosSweep:
    """The full chaos sweep (slow tier): every fault class against
    the diurnal mix at a larger scale, both routers -- the tier-1
    classes above keep one fast representative each."""

    @pytest.mark.parametrize("router", ["affinity", "round_robin"])
    @pytest.mark.parametrize("faults", [
        "replica_kill_at=30",
        "slow_replica=1:6",
        "replica_kill_at=25,slow_replica=2:4",
        "swap_corrupt=1",
    ], ids=["kill", "slow", "kill_slow", "corrupt_swap"])
    def test_sweep_no_loss_no_shed_above_floor(
        self, engines, scoped_obs, fleet_params_v2, faults, router
    ):
        swap = "swap_corrupt" in faults
        s, h = _run(
            engines, _scenario("diurnal", seed=3, n=48, rate=100.0),
            FleetConfig(
                initial_replicas=2, min_replicas=1, max_replicas=4,
                router=router, scale_window=8, scale_cooldown=12,
            ),
            faults=faults,
            swap_at=40 if swap else None,
            swap_weights=fleet_params_v2 if swap else None,
        )
        assert s["lost_requests"] == 0
        assert s["_recompiles"] == 0
        for name, t in s["tenants"].items():
            if name != "background":
                assert t["shed"] == 0, (faults, router, name)
        for e in engines:
            e.reset_pool(force=True)


# ---------------------------------------------------------------------
# CI wiring: schema, regress directions, the committed banked rows
# ---------------------------------------------------------------------
class TestFleetObsWiring:
    def test_fleet_events_round_trip_schema(self):
        from tpu_hpc.obs.schema import stamp

        for rec in (
            {"event": "fleet_route", "rid": "r1", "replica": 0,
             "tenant": "t", "affinity": True},
            {"event": "replica_down", "replica": 1,
             "reason": "heartbeat_timeout", "inflight": 3,
             "redispatched": 3, "last_beat_age_s": 0.3},
            {"event": "replica_up", "replica": 1,
             "reason": "restart", "weights_version": 2},
            {"event": "redispatch", "rid": "r1", "from_replica": 1,
             "to_replica": 0, "committed": 4, "tenant": "t"},
            {"event": "fleet_scale", "action": "grow", "live": 3,
             "replica": 2, "occupancy": 0.9, "reason": "occupancy"},
            {"event": "weight_swap", "replica": 0, "version": 2,
             "status": "rolled_back", "reason": "mismatch",
             "mismatched": 1},
        ):
            validate_record(stamp(rec))

    def test_fleet_events_stay_closed(self):
        from tpu_hpc.obs.schema import SchemaError, stamp

        with pytest.raises(SchemaError, match="unknown"):
            validate_record(stamp({
                "event": "redispatch", "rid": "r", "from_replica": 0,
                "to_replica": 1, "bogus": 1,
            }))

    def test_regress_directions_for_fleet_metrics(self):
        # The robustness counters regress by going UP...
        assert lower_is_better("fleet.redispatched")
        assert lower_is_better("fleet.replica_down")
        assert lower_is_better("fleet.swap_rollbacks")
        assert lower_is_better(
            "loadgen_diurnal_fleet_ttft_ms_p95.lost_requests"
        )
        assert lower_is_better(
            "loadgen_diurnal_fleet_ttft_ms_p95.redispatched"
        )
        # ...while the router mechanism regresses by going DOWN
        # (higher-is-better by token absence, the acceptance_rate
        # pattern).
        assert not lower_is_better("fleet.prefix_affinity_hit_rate")
        assert not lower_is_better(
            "loadgen_diurnal_fleet_ttft_ms_p95.prefix_affinity_hit_rate"
        )

    def test_banked_side_keys_carry_fleet_mechanisms(self):
        # The bank reduction reads ONLY the record top level, so the
        # affinity outcome AND the robustness counters must be side
        # keys (and bench.loadgen_record lifts them) -- nested-only
        # counters would make the gate's robustness-drift promise
        # vacuous (review finding).
        from tpu_hpc.obs.regress import _BANKED_SIDE_KEYS

        for k in ("prefix_affinity_hit_rate", "redispatched",
                  "replica_down", "swap_rollbacks", "lost_requests"):
            assert k in _BANKED_SIDE_KEYS, k
        import json

        for line in open(os.path.join(REPO, "BENCH_FLEET_r14.jsonl")):
            rec = json.loads(line)
            for k in ("prefix_affinity_hit_rate", "redispatched",
                      "replica_down", "swap_rollbacks",
                      "lost_requests"):
                assert k in rec, (rec["metric"], k)

    def test_committed_fleet_rows_pass_the_bank_gate(self, capsys):
        """The acceptance's CI leg: the banked diurnal/shared_prefix
        fleet rows are schema-valid and pass ``regress --bank``
        against the committed BENCH_HISTORY.jsonl high-water marks."""
        hist = os.path.join(REPO, "BENCH_HISTORY.jsonl")
        rows = os.path.join(REPO, "BENCH_FLEET_r14.jsonl")
        recs = load_records(rows, validate=True)
        metrics = {r["metric"] for r in recs}
        assert "loadgen_diurnal_fleet_ttft_ms_p95" in metrics
        rc = regress_main([hist, rows, "--bank"])
        assert rc == 0, capsys.readouterr().out
