"""Tests for runtime: mesh construction, launcher detection, topology."""
import os

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec

from tpu_hpc.runtime import (
    MeshSpec,
    build_mesh,
    get_host_info,
    local_batch_size,
    named_sharding,
)
from tpu_hpc.runtime.topology import device_summary, topology_report


class TestMesh:
    def test_1d(self, devices):
        m = build_mesh(MeshSpec(axes={"data": 8}))
        assert m.shape == {"data": 8}

    def test_2d(self, devices):
        m = build_mesh(MeshSpec(axes={"data": 2, "model": 4}))
        assert m.shape == {"data": 2, "model": 4}
        assert m.axis_names == ("data", "model")

    def test_wildcard(self, devices):
        m = build_mesh(MeshSpec(axes={"data": -1, "model": 2}))
        assert m.shape == {"data": 4, "model": 2}

    def test_two_wildcards_rejected(self):
        with pytest.raises(ValueError, match="at most one"):
            MeshSpec(axes={"a": -1, "b": -1}).resolved_sizes(8)

    def test_too_many_devices(self, devices):
        with pytest.raises(ValueError, match="needs"):
            build_mesh(MeshSpec(axes={"data": 16}))

    def test_subset_of_devices(self, devices):
        m = build_mesh(MeshSpec(axes={"data": 4}), devices=devices[:4])
        assert m.shape == {"data": 4}

    def test_sharded_array_placement(self, mesh_2d):
        x = jnp.arange(32.0).reshape(8, 4)
        s = named_sharding(mesh_2d, "data", "model")
        xs = jax.device_put(x, s)
        assert xs.sharding.is_equivalent_to(s, x.ndim)
        assert len(xs.addressable_shards) == 8
        assert xs.addressable_shards[0].data.shape == (4, 1)

    def test_local_batch_size(self, mesh8):
        assert local_batch_size(32, mesh8, "data") == 4
        with pytest.raises(ValueError, match="not divisible"):
            local_batch_size(30, mesh8, "data")


class TestHybridMesh:
    """Multi-slice (ICI x DCN) meshes: the TPU analogue of the
    reference's NVLink-intra / Slingshot-inter fabric doctrine
    (fsdp_tp/fsdp_tp_example.py:12-26). CPU-sim devices carry no slice
    identity, so build_hybrid_mesh emulates slices as contiguous device
    chunks -- the layout contract tested here is the same one real
    slice_index grouping produces."""

    def test_shape_is_ici_times_dcn(self, devices):
        m = build_mesh(
            MeshSpec(axes={"data": 2, "model": 2}, dcn_axes={"data": 2})
        )
        assert m.shape == {"data": 4, "model": 2}
        assert m.axis_names == ("data", "model")

    def test_dcn_component_varies_slowest(self, devices):
        # Slice 0 (first contiguous half of the device list) must own
        # the first dcn block of the data axis: rows 0..1; slice 1 rows
        # 2..3. A transposed/interleaved layout would put cross-slice
        # hops inside the fast intra-slice phase.
        devs = jax.devices()
        m = build_mesh(
            MeshSpec(axes={"data": 2, "model": 2}, dcn_axes={"data": 2})
        )
        assert set(m.devices[:2].ravel()) == set(devs[:4])
        assert set(m.devices[2:].ravel()) == set(devs[4:])

    def test_wildcard_resolves_per_slice(self, devices):
        m = build_mesh(
            MeshSpec(axes={"data": -1, "model": 2}, dcn_axes={"data": 2})
        )
        assert m.shape == {"data": 4, "model": 2}

    def test_pure_dcn_axis(self, devices):
        # ICI extent 1: the axis exists only across slices (e.g. pure
        # cross-slice FSDP with a full-slice TP axis).
        m = build_mesh(
            MeshSpec(axes={"data": 1, "model": 4}, dcn_axes={"data": 2})
        )
        assert m.shape == {"data": 2, "model": 4}

    def test_unknown_dcn_axis_rejected(self):
        with pytest.raises(ValueError, match="not present"):
            MeshSpec(axes={"data": 2}, dcn_axes={"model": 2})

    def test_indivisible_slices_rejected(self, devices):
        with pytest.raises(ValueError, match="not divisible"):
            MeshSpec(
                axes={"data": -1}, dcn_axes={"data": 3}
            ).resolved_sizes(8)

    def test_collective_runs_over_hybrid_mesh(self, devices):
        # psum over the hybrid data axis decomposes into intra-slice +
        # cross-slice phases; the result must still be the plain sum.
        m = build_mesh(
            MeshSpec(axes={"data": 2, "model": 2}, dcn_axes={"data": 2})
        )
        x = jnp.arange(8.0)
        s = named_sharding(m, "data")

        @jax.jit
        def total(v):
            return jnp.sum(v)

        assert float(total(jax.device_put(x, s))) == 28.0

    def test_end_to_end_train_step_over_two_slices(self, devices):
        # VERDICT r3 weak #7: dcn_axes was spec-tested only. Run the
        # REAL hybrid FSDPxTP Trainer step over a two-slice ICI x DCN
        # mesh and pin its loss to the single-slice mesh of the same
        # logical shape -- the device order differs (DCN component
        # slowest) but the math must not.
        from tpu_hpc.config import TrainingConfig
        from tpu_hpc.models import datasets, llama2
        from tpu_hpc.parallel import hybrid, tp
        from tpu_hpc.train import Trainer

        def one_step(mesh):
            cfg_m = llama2.LlamaConfig(
                dim=64, n_layers=2, n_heads=4, vocab_size=256,
                multiple_of=32, max_seq_len=32,
            )
            params = llama2.init_llama(jax.random.key(0), cfg_m)
            specs = hybrid.hybrid_pspecs(
                params, tp.llama_rules(), data_size=4, min_size=1000
            )
            constrain = tp.sp_constrain(
                mesh, dp_axis="data", sp_axis="model"
            )
            cfg = TrainingConfig(
                global_batch_size=4, steps_per_epoch=1, epochs=1
            )
            tr = Trainer(
                cfg, mesh,
                llama2.make_forward(cfg_m, constrain), params,
                param_pspecs=specs,
            )
            ds = datasets.TokenStream(vocab_size=256, seq_len=32)
            m = tr.train_step(ds.batch_at(0, 4))
            return float(jax.device_get(m["loss"]))

        two_slice = one_step(build_mesh(
            MeshSpec(axes={"data": 2, "model": 2}, dcn_axes={"data": 2})
        ))
        one_slice = one_step(build_mesh(
            MeshSpec(axes={"data": 4, "model": 2})
        ))
        assert two_slice == pytest.approx(one_slice, rel=1e-6)

    def test_slice_groups_single(self, devices):
        from tpu_hpc.runtime import slice_groups

        groups = slice_groups(jax.devices())
        assert len(groups) == 1 and len(groups[0]) == 8


class TestHostInfo:
    def _clear(self, monkeypatch):
        for v in (
            "JAX_PROCESS_ID",
            "JAX_NUM_PROCESSES",
            "JAX_COORDINATOR_ADDRESS",
            "TPU_WORKER_ID",
            "TPU_WORKER_HOSTNAMES",
            "SLURM_PROCID",
            "SLURM_NTASKS",
            "OMPI_COMM_WORLD_RANK",
            "OMPI_COMM_WORLD_SIZE",
            "PALS_RANKID",
            "PALS_SIZE",
            "PMI_RANK",
            "PMI_SIZE",
            "MASTER_ADDR",
            "MASTER_PORT",
        ):
            monkeypatch.delenv(v, raising=False)

    def test_single_fallback(self, monkeypatch):
        self._clear(monkeypatch)
        info = get_host_info()
        assert (info.process_id, info.num_processes) == (0, 1)
        assert info.launcher == "single"
        assert not info.is_distributed

    def test_explicit(self, monkeypatch):
        self._clear(monkeypatch)
        monkeypatch.setenv("JAX_PROCESS_ID", "3")
        monkeypatch.setenv("JAX_NUM_PROCESSES", "8")
        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
        info = get_host_info()
        assert (info.process_id, info.num_processes) == (3, 8)
        assert info.coordinator_address == "10.0.0.1:1234"
        assert info.launcher == "explicit"

    def test_tpu_pod(self, monkeypatch):
        self._clear(monkeypatch)
        monkeypatch.setenv("TPU_WORKER_ID", "2")
        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "t0,t1,t2,t3")
        info = get_host_info()
        assert (info.process_id, info.num_processes) == (2, 4)
        assert info.coordinator_address.startswith("t0:")
        assert info.launcher == "tpu_pod"

    def test_openmpi(self, monkeypatch):
        self._clear(monkeypatch)
        monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "1")
        monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "4")
        monkeypatch.setenv("MASTER_ADDR", "head")
        monkeypatch.setenv("MASTER_PORT", "2222")
        info = get_host_info()
        assert info.launcher == "openmpi"
        assert info.coordinator_address == "head:2222"

    def test_cray_pals(self, monkeypatch):
        self._clear(monkeypatch)
        monkeypatch.setenv("PALS_RANKID", "5")
        monkeypatch.setenv("PALS_SIZE", "8")
        info = get_host_info()
        assert info.launcher == "cray_pals"
        assert info.process_id == 5

    def test_priority_explicit_beats_ompi(self, monkeypatch):
        self._clear(monkeypatch)
        monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "1")
        monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "4")
        monkeypatch.setenv("JAX_PROCESS_ID", "0")
        monkeypatch.setenv("JAX_NUM_PROCESSES", "2")
        assert get_host_info().launcher == "explicit"


class TestTopology:
    def test_device_summary(self, devices):
        recs = device_summary()
        assert len(recs) == 8
        assert all("device_kind" in r for r in recs)

    def test_topology_report(self, devices):
        rep = topology_report()
        assert rep["global_device_count"] == 8
        assert rep["process_count"] == 1


class TestTuning:
    """XLA/libtpu performance presets (the reference's NCCL-tuning env
    block, nccl_tuning.md:11-66, as versioned code)."""

    def test_profiles_are_flag_strings(self):
        from tpu_hpc.runtime import tuning

        for name, env in tuning.PROFILES.items():
            for var, flags in env.items():
                assert var in ("LIBTPU_INIT_ARGS", "XLA_FLAGS")
                assert all(f.startswith("--") for f in flags.split())

    def test_user_flags_preserved_and_win(self):
        from tpu_hpc.runtime import tuning

        env = tuning.tuning_env(
            "collective-overlap",
            base={"LIBTPU_INIT_ARGS": "--xla_enable_async_all_gather=false"},
        )
        merged = env["LIBTPU_INIT_ARGS"]
        # The user's setting wins by *dedup*, not parser order: the
        # preset's conflicting flag is dropped entirely so correctness
        # does not depend on libtpu's duplicate-flag handling.
        assert "--xla_enable_async_all_gather=true" not in merged
        assert "--xla_enable_async_all_gather=false" in merged
        names = [t.split("=", 1)[0] for t in merged.split()]
        assert len(names) == len(set(names)), "duplicate flag survived"
        # Non-conflicting preset flags still present.
        assert "--xla_tpu_enable_latency_hiding_scheduler=true" in merged

    def test_unknown_profile_rejected(self):
        from tpu_hpc.runtime import tuning

        with pytest.raises(ValueError, match="unknown tuning profile"):
            tuning.tuning_env("turbo")

    def test_apply_after_backend_init_rejected(self, devices):
        from tpu_hpc.runtime import tuning

        with pytest.raises(RuntimeError, match="after the JAX backend"):
            tuning.apply_tuning()

    def test_shell_mode(self, capsys):
        from tpu_hpc.runtime import tuning

        tuning.main(["--profile", "data-parallel", "--shell"])
        out = capsys.readouterr().out
        assert out.startswith("export LIBTPU_INIT_ARGS='--xla_tpu")

    def test_data_parallel_is_superset_of_overlap(self):
        from tpu_hpc.runtime import tuning

        overlap = set(
            tuning.PROFILES["collective-overlap"]["LIBTPU_INIT_ARGS"].split()
        )
        dp_flags = set(
            tuning.PROFILES["data-parallel"]["LIBTPU_INIT_ARGS"].split()
        )
        assert overlap < dp_flags  # docs promise a strict superset

    def test_apply_before_backend_init(self):
        """The positive path needs a fresh process (this test session's
        backend is already up): apply_tuning sets the env, then jax
        initializes normally (LIBTPU_INIT_ARGS is inert on CPU; the
        sim machinery handles platform forcing)."""
        from tpu_hpc.runtime.sim import run_in_sim_subprocess

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        code = (
            f"import sys; sys.path.insert(0, {repo!r})\n"
            "from tpu_hpc.runtime import tuning\n"
            "import os\n"
            "tuning.apply_tuning('collective-overlap')\n"
            "assert os.environ['LIBTPU_INIT_ARGS'].startswith('--xla_tpu')\n"
            "import jax\n"
            "print('TUNED_OK', jax.device_count())\n"
        )
        proc = run_in_sim_subprocess(["-c", code], 2, timeout=180)
        assert proc.returncode == 0, proc.stderr[-800:]
        assert "TUNED_OK 2" in proc.stdout


class TestMemoryBoundProfile:
    def test_force_profile_overrides_existing_flag(self):
        """memory-bound exists to flip the scheduler flag an earlier
        collective-overlap export set to true -- under plain
        user-wins dedup it would silently no-op in exactly that
        scenario, so it must override."""
        from tpu_hpc.runtime import tuning

        pre = tuning.tuning_env("collective-overlap", base={})
        env = tuning.tuning_env("memory-bound", base=pre)
        merged = env["LIBTPU_INIT_ARGS"]
        assert "--xla_tpu_enable_latency_hiding_scheduler=false" in merged
        assert "--xla_tpu_enable_latency_hiding_scheduler=true" not in merged
        names = [t.split("=", 1)[0] for t in merged.split()]
        assert len(names) == len(set(names))
        # Unrelated flags from the earlier export survive.
        assert "--xla_tpu_enable_async_collective_fusion=true" in merged
