"""Speculative decoding + seeded sampling (serve/spec.py).

Four invariant families:
  * **greedy oracle** -- the speculative greedy stream is
    byte-identical to the non-speculative greedy stream (which
    tests/test_serve.py pins against the no-cache forward): draft and
    n-gram modes, prefix hit and miss, chunked prefill, accept and
    reject paths. Speculation changes latency only, never tokens.
  * **seeded sampling** -- same (request seed, temperature, top_p)
    replays the same tokens regardless of batch composition or slot
    placement; different seeds diverge; greedy co-residents of a
    sampled batch stay oracle-exact.
  * **compile discipline** -- accept/reject churn (and the draft
    engine) adds ZERO executables after warmup
    (``compile_count_total`` is the pinned counter).
  * **page accounting** -- speculative writes stay inside the
    admission-time reservation: the allocator invariant holds after
    churn and BOTH pools drain back to idle.

All on the 8-device simulated mesh, fp32 compute so byte-identical
means exact.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_hpc.models import llama2
from tpu_hpc.runtime import MeshSpec, build_mesh
from tpu_hpc.serve import (
    ContinuousBatcher,
    PagedConfig,
    PagedEngine,
    Request,
    ServeConfig,
    SpecConfig,
    attach_spec,
    derive_request_seed,
)
from tpu_hpc.serve.spec import (
    NgramIndex, ngram_propose, sampling_probs,
)

TINY = llama2.LlamaConfig(
    dim=64, n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=128,
    multiple_of=16, max_seq_len=256, dtype=jnp.float32,
)
DRAFT = llama2.LlamaConfig(
    dim=64, n_layers=1, n_heads=4, n_kv_heads=2, vocab_size=128,
    multiple_of=16, max_seq_len=256, dtype=jnp.float32,
)
K = 3


@pytest.fixture(scope="module")
def spec_mesh(devices):
    return build_mesh(MeshSpec(axes={"data": 4, "model": 2}))


@pytest.fixture(scope="module")
def tiny_params():
    return llama2.init_llama(jax.random.key(0), TINY)


@pytest.fixture(scope="module")
def draft_params():
    return llama2.init_llama(jax.random.key(7), DRAFT)


def make_engine(params, mesh, spec=None, draft=None):
    engine = PagedEngine(
        params, TINY,
        ServeConfig(slots=4, max_seq_len=48, prefill_buckets=(8, 16)),
        mesh,
        PagedConfig(block_size=4, num_blocks=48, prefill_chunk=8),
    )
    if spec is not None:
        attach_spec(
            engine, spec,
            draft_params=draft[0] if draft else None,
            draft_cfg=draft[1] if draft else None,
        )
    engine.warmup()
    return engine


@pytest.fixture(scope="module")
def baseline_streams(tiny_params, spec_mesh):
    """The non-speculative greedy streams every spec mode must
    reproduce byte-identically (itself pinned against the no-cache
    oracle in tests/test_serve.py)."""
    engine = make_engine(tiny_params, spec_mesh)
    return ContinuousBatcher(engine).run(_mix())


def _mix():
    rng = np.random.default_rng(11)
    shapes = [(11, 6), (5, 8), (13, 3), (7, 5), (9, 7), (4, 2)]
    return [
        Request(
            rid=f"r{i}",
            prompt=rng.integers(
                0, TINY.vocab_size, size=plen
            ).tolist(),
            max_new_tokens=mnew,
        )
        for i, (plen, mnew) in enumerate(shapes)
    ]


class TestNgramProposer:
    def test_matches_most_recent_occurrence(self):
        h = [1, 2, 3, 9, 9, 2, 3, 7, 7, 2, 3]
        # Trailing 2-gram (2, 3): most recent earlier occurrence at
        # index 5 -> propose what followed it.
        assert ngram_propose(h, 3, max_n=2) == [7, 7, 2]

    def test_falls_back_to_shorter_grams(self):
        h = [5, 6, 1, 2, 6]
        # No earlier (2, 6) bigram; unigram 6 at index 1 -> [1, 2, 6].
        assert ngram_propose(h, 4, max_n=2) == [1, 2, 6]

    def test_no_match_is_empty(self):
        assert ngram_propose([1, 2, 3, 4], 4) == []
        assert ngram_propose([7], 4) == []
        assert ngram_propose([], 4) == []

    def test_proposal_capped_at_k(self):
        h = [1, 2, 3, 4, 5, 1, 2]
        assert ngram_propose(h, 2, max_n=2) == [3, 4]

    def test_index_matches_rescan_incrementally(self):
        # The batcher's incremental NgramIndex must propose
        # byte-identically to the reference rescan at EVERY prefix of
        # a random history (repetitive small alphabet so bigram and
        # unigram matches, fallbacks, and no-match all occur), for
        # every (k, max_n) the config space allows.
        rng = np.random.default_rng(11)
        for max_n in (1, 2, 3):
            for k in (1, 4):
                toks = rng.integers(0, 5, size=200).tolist()
                index = NgramIndex(max_n=max_n)
                for i, tok in enumerate(toks):
                    index.append(tok)
                    h = toks[:i + 1]
                    assert index.propose(k) == ngram_propose(
                        h, k, max_n=max_n
                    ), (max_n, k, i)

    def test_index_seeded_from_history(self):
        h = [1, 2, 3, 9, 9, 2, 3, 7, 7, 2, 3]
        assert NgramIndex(h).propose(3) == ngram_propose(
            h, 3, max_n=2
        )
        assert NgramIndex([]).propose(3) == []
        assert NgramIndex([7]).propose(3) == []


class TestSamplingHead:
    def test_greedy_is_exact_onehot_argmax(self):
        logits = jnp.asarray(
            np.random.default_rng(0).normal(size=(2, 3, 16)),
            jnp.float32,
        )
        p = sampling_probs(
            logits, jnp.zeros(2), jnp.ones(2)
        )
        want = jax.nn.one_hot(
            jnp.argmax(logits, -1), 16, dtype=jnp.float32
        )
        np.testing.assert_array_equal(np.asarray(p), np.asarray(want))

    def test_top_p_filters_the_tail(self):
        logits = jnp.log(jnp.asarray(
            [[[0.5, 0.3, 0.15, 0.05]]], jnp.float32
        ))
        p = sampling_probs(
            logits, jnp.ones(1), jnp.asarray([0.7], jnp.float32)
        )[0, 0]
        # 0.5 + 0.3 crosses 0.7 -> only the top two survive.
        assert float(p[2]) == 0.0 and float(p[3]) == 0.0
        assert float(p[0]) == pytest.approx(0.625, abs=1e-5)
        assert float(jnp.sum(p)) == pytest.approx(1.0, abs=1e-5)

    def test_top_p_one_keeps_everything(self):
        logits = jnp.asarray(
            np.random.default_rng(1).normal(size=(1, 1, 8)),
            jnp.float32,
        )
        p = sampling_probs(logits, jnp.ones(1), jnp.ones(1))[0, 0]
        soft = jax.nn.softmax(logits[0, 0])
        np.testing.assert_allclose(
            np.asarray(p), np.asarray(soft), rtol=1e-5
        )


class TestGreedyOracle:
    """Speculation must change latency only -- never the greedy
    stream. Every mode, against the same churned request mix the
    non-speculative engine produced."""

    def test_ngram_stream_byte_identical(
        self, tiny_params, spec_mesh, baseline_streams
    ):
        engine = make_engine(
            tiny_params, spec_mesh, SpecConfig(mode="ngram", k=K)
        )
        got = ContinuousBatcher(engine).run(_mix())
        assert got == baseline_streams

    def test_draft_stream_byte_identical(
        self, tiny_params, draft_params, spec_mesh, baseline_streams
    ):
        engine = make_engine(
            tiny_params, spec_mesh, SpecConfig(mode="draft", k=K),
            draft=(draft_params, DRAFT),
        )
        got = ContinuousBatcher(engine).run(_mix())
        assert got == baseline_streams
        # An independent random draft rarely guesses the argmax:
        # the reject path demonstrably ran.
        assert engine.spec.stats["rejected"] > 0

    def test_self_draft_accepts_everything(
        self, tiny_params, spec_mesh, baseline_streams
    ):
        """draft == target: every draft must pass verification (q and
        p are the same one-hot), the stream stays byte-identical, and
        the accept path demonstrably ran."""
        engine = make_engine(
            tiny_params, spec_mesh, SpecConfig(mode="draft", k=K),
            draft=(tiny_params, TINY),
        )
        got = ContinuousBatcher(engine).run(_mix())
        assert got == baseline_streams
        s = engine.spec.stats
        assert s["drafted"] > 0
        assert s["accepted"] == s["drafted"]

    def test_prefix_hit_and_long_stream_acceptance(
        self, tiny_params, spec_mesh
    ):
        """Warm-trie admissions (prefix hit) keep the oracle, and a
        long greedy continuation (which cycles) gives prompt lookup
        real acceptance -- the mechanism behind the banked ITL win."""
        rng = np.random.default_rng(21)
        prompt = rng.integers(0, TINY.vocab_size, size=13).tolist()
        base = make_engine(tiny_params, spec_mesh)
        want = ContinuousBatcher(base).run(
            [Request(rid="w", prompt=prompt, max_new_tokens=30)]
        )["w"]
        engine = make_engine(
            tiny_params, spec_mesh, SpecConfig(mode="ngram", k=K)
        )
        cold = ContinuousBatcher(engine).run(
            [Request(rid="cold", prompt=prompt, max_new_tokens=30)]
        )["cold"]
        warm = ContinuousBatcher(engine).run(
            [Request(rid="warm", prompt=prompt, max_new_tokens=30)]
        )["warm"]
        assert cold == want
        assert warm == want
        assert engine.paged_stats["prefix_hits"] >= 1
        s = engine.spec.stats
        assert s["accepted"] > 0, "cycling stream should accept"

    def test_eos_mid_acceptance_truncates_exactly(
        self, tiny_params, spec_mesh
    ):
        """An EOS inside an accepted run must cut the stream exactly
        where non-speculative decode stops (inclusive), discarding
        the speculative tail."""
        prompt = [3, 1, 4, 1, 5]
        base = make_engine(tiny_params, spec_mesh)
        free = ContinuousBatcher(base).run(
            [Request(rid="f", prompt=prompt, max_new_tokens=24)]
        )["f"]
        # Pick an EOS from the middle of the free-run stream.
        eos = free[len(free) // 2]
        cut = free[:free.index(eos) + 1]
        engine = make_engine(
            tiny_params, spec_mesh, SpecConfig(mode="ngram", k=K)
        )
        got = ContinuousBatcher(engine).run([
            Request(rid="e", prompt=prompt, max_new_tokens=24,
                    eos_id=eos)
        ])["e"]
        assert got == cut

    def test_max_new_budget_exact(self, tiny_params, spec_mesh):
        """Emission caps: every request generates EXACTLY max_new
        tokens (n_valid = min(k, remaining - 1) keeps the last verify
        step from overshooting), including max_new 1 and 2."""
        engine = make_engine(
            tiny_params, spec_mesh, SpecConfig(mode="ngram", k=K)
        )
        rng = np.random.default_rng(5)
        reqs = [
            Request(
                rid=f"b{i}",
                prompt=rng.integers(0, 128, size=6 + i).tolist(),
                max_new_tokens=m,
            )
            for i, m in enumerate((1, 2, 3, 7))
        ]
        got = ContinuousBatcher(engine).run(reqs)
        for r in reqs:
            assert len(got[r.rid]) == r.max_new_tokens, r.rid


class TestSeededSampling:
    def _sampled(self, rid="x", seed=42, temperature=0.8, top_p=0.9,
                 max_new=8):
        rng = np.random.default_rng(33)
        return Request(
            rid=rid, prompt=rng.integers(0, 128, size=9).tolist(),
            max_new_tokens=max_new, temperature=temperature,
            top_p=top_p, seed=seed,
        )

    def _others(self, n=3):
        rng = np.random.default_rng(34)
        return [
            Request(
                rid=f"o{i}",
                prompt=rng.integers(0, 128, size=5 + 2 * i).tolist(),
                max_new_tokens=5, temperature=0.5, top_p=0.95, seed=i,
            )
            for i in range(n)
        ]

    def test_batch_composition_invariance(
        self, tiny_params, spec_mesh
    ):
        """Same (seed, temperature, top_p) -> same tokens whether the
        request runs alone, with company, or admitted last (different
        slot). The key folds in (request seed, position) only."""
        solo = ContinuousBatcher(
            make_engine(tiny_params, spec_mesh,
                        SpecConfig(mode="ngram", k=K))
        ).run([self._sampled()])["x"]
        batched = ContinuousBatcher(
            make_engine(tiny_params, spec_mesh,
                        SpecConfig(mode="ngram", k=K))
        ).run(self._others() + [self._sampled()])["x"]
        assert solo == batched
        # Replay: bit-identical run-to-run too.
        again = ContinuousBatcher(
            make_engine(tiny_params, spec_mesh,
                        SpecConfig(mode="ngram", k=K))
        ).run([self._sampled()])["x"]
        assert again == solo

    def test_seed_changes_the_stream(self, tiny_params, spec_mesh):
        a = ContinuousBatcher(
            make_engine(tiny_params, spec_mesh,
                        SpecConfig(mode="ngram", k=K))
        ).run([self._sampled(seed=42)])["x"]
        b = ContinuousBatcher(
            make_engine(tiny_params, spec_mesh,
                        SpecConfig(mode="ngram", k=K))
        ).run([self._sampled(seed=43)])["x"]
        assert a != b

    def test_greedy_coresident_stays_oracle_exact(
        self, tiny_params, spec_mesh, baseline_streams
    ):
        """Greedy requests sharing a batch with sampled ones must
        still match the non-speculative greedy streams exactly."""
        engine = make_engine(
            tiny_params, spec_mesh, SpecConfig(mode="ngram", k=K)
        )
        got = ContinuousBatcher(engine).run(
            _mix() + [self._sampled(rid="s")]
        )
        for r in _mix():
            assert got[r.rid] == baseline_streams[r.rid], r.rid

    def test_draft_mode_sampling_deterministic(
        self, tiny_params, draft_params, spec_mesh
    ):
        """Rejection sampling through a draft model is deterministic
        per seed too (draft draw, acceptance u, and residual draw all
        fold the same per-request streams)."""
        runs = [
            ContinuousBatcher(
                make_engine(
                    tiny_params, spec_mesh,
                    SpecConfig(mode="draft", k=K),
                    draft=(draft_params, DRAFT),
                )
            ).run(self._others() + [self._sampled()])["x"]
            for _ in range(2)
        ]
        assert runs[0] == runs[1]

    def test_sampling_requires_spec_engine(
        self, tiny_params, spec_mesh
    ):
        engine = make_engine(tiny_params, spec_mesh)
        batcher = ContinuousBatcher(engine)
        with pytest.raises(ValueError, match="speculative"):
            batcher.submit(self._sampled())

    def test_derive_request_seed_stable(self):
        assert derive_request_seed("r1") == derive_request_seed("r1")
        assert derive_request_seed("r1") != derive_request_seed("r2")
        assert derive_request_seed("r1", seed=5) == 5


class TestCompileDiscipline:
    def test_zero_recompiles_across_accept_reject_churn(
        self, tiny_params, draft_params, spec_mesh
    ):
        """The acceptance guard: accept/reject churn, sampled AND
        greedy requests, prefix hits, chunked prefill -- ZERO new
        executables on either engine after warmup."""
        engine = make_engine(
            tiny_params, spec_mesh, SpecConfig(mode="draft", k=K),
            draft=(draft_params, DRAFT),
        )
        warmed = engine.compile_count_total
        rng = np.random.default_rng(3)
        reqs = [
            Request(
                rid=f"m{i}",
                prompt=rng.integers(
                    0, TINY.vocab_size, size=4 + (5 * i) % 13
                ).tolist(),
                max_new_tokens=1 + i % 5,
                temperature=0.7 if i % 2 else 0.0,
                seed=i,
            )
            for i in range(9)
        ]
        ContinuousBatcher(engine).run(reqs)
        assert engine.compile_count_total == warmed

    def test_spec_engine_compiles_its_own_program_set(
        self, tiny_params, spec_mesh
    ):
        engine = make_engine(
            tiny_params, spec_mesh, SpecConfig(mode="ngram", k=K)
        )
        # 2 spec prefill buckets + verify + copy_block; no draft side.
        assert engine.compile_count_total == 4

    def test_spec_validation(self, tiny_params, spec_mesh):
        from tpu_hpc.serve.engine import Engine

        with pytest.raises(ValueError, match="unknown spec mode"):
            SpecConfig(mode="medusa")
        with pytest.raises(ValueError, match="k must be >= 1"):
            SpecConfig(k=0)
        slab = Engine(
            tiny_params, TINY,
            ServeConfig(slots=2, max_seq_len=48,
                        prefill_buckets=(16,)),
            spec_mesh,
        )
        with pytest.raises(ValueError, match="paged"):
            attach_spec(slab, SpecConfig(mode="ngram"))
        paged = PagedEngine(
            tiny_params, TINY,
            ServeConfig(slots=2, max_seq_len=48,
                        prefill_buckets=(16,)),
            spec_mesh,
            PagedConfig(block_size=4, num_blocks=32),
        )
        with pytest.raises(ValueError, match="draft_params"):
            attach_spec(paged, SpecConfig(mode="draft"))
        with pytest.raises(ValueError, match="largest prefill"):
            attach_spec(paged, SpecConfig(mode="ngram", k=17))
        draft_bad_vocab = llama2.LlamaConfig(
            dim=64, n_layers=1, n_heads=4, n_kv_heads=2,
            vocab_size=64, multiple_of=16, max_seq_len=256,
            dtype=jnp.float32,
        )
        with pytest.raises(ValueError, match="vocab"):
            attach_spec(
                paged, SpecConfig(mode="draft"),
                draft_params=llama2.init_llama(
                    jax.random.key(1), draft_bad_vocab
                ),
                draft_cfg=draft_bad_vocab,
            )
        # Attach-after-warmup would leave the spec programs to
        # lazy-compile mid-traffic: fail fast instead.
        paged.warmup()
        with pytest.raises(ValueError, match="BEFORE engine.warmup"):
            attach_spec(paged, SpecConfig(mode="ngram"))


class TestPageAccounting:
    def test_pools_drain_to_idle_and_invariants_hold(
        self, tiny_params, draft_params, spec_mesh
    ):
        """Speculative writes stay inside the admission reservation:
        after a churned drain the allocator identity holds on BOTH
        pools and every non-trie page is back on the free list."""
        engine = make_engine(
            tiny_params, spec_mesh, SpecConfig(mode="draft", k=K),
            draft=(draft_params, DRAFT),
        )
        ContinuousBatcher(engine).run(_mix())
        engine.allocator.check_invariant()
        engine.spec.draft.allocator.check_invariant()
        # No live requests -> every held page belongs to the trie.
        assert not engine._slot_state
        assert not engine.spec.draft._slot_state

    def test_request_seed_rides_slot_state(
        self, tiny_params, spec_mesh
    ):
        engine = make_engine(
            tiny_params, spec_mesh, SpecConfig(mode="ngram", k=K)
        )
        engine.admit(0, [1, 2, 3, 4, 5], 4, sampling=(99, 0.5, 0.9))
        st = engine.slot_state(0)
        assert (st.seed, st.temperature, st.top_p) == (99, 0.5, 0.9)
        engine.release(0)


class TestServerCLI:
    def test_replay_with_spec_reports_summary(self, capsys):
        from tpu_hpc.serve import server
        import json

        rc = server.main([
            "--requests", "3", "--max-new", "6", "--slots", "2",
            "--buckets", "8", "--prompt-lens", "3,6", "--vocab", "64",
            "--paged", "--kv-block-size", "4",
            "--spec", "ngram", "--spec-k", "2",
        ])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out.splitlines()[-1])
        assert summary["spec_mode"] == "ngram"
        assert summary["spec_k"] == 2
        assert summary["recompiles"] == 0
        assert "acceptance_rate" in summary
        assert summary["batcher"]["verify_steps"] > 0

    def test_spec_flags_guarded(self):
        from tpu_hpc.serve import server

        # --spec rides --paged.
        with pytest.raises(SystemExit):
            server.main(["--spec", "ngram"])
        # --spec + --disagg is a parse error.
        with pytest.raises(SystemExit):
            server.main(["--paged", "--spec", "ngram", "--disagg"])
        # Spec knobs require --spec.
        with pytest.raises(SystemExit):
            server.main(["--paged", "--spec-k", "4"])
        with pytest.raises(SystemExit):
            server.main(["--paged", "--temperature", "0.8"])
        # Draft knobs require --spec draft specifically.
        with pytest.raises(SystemExit):
            server.main(["--paged", "--spec", "ngram",
                         "--spec-draft-ckpt", "/tmp/x"])
        # --top-p rides --temperature.
        with pytest.raises(SystemExit):
            server.main(["--paged", "--spec", "ngram",
                         "--top-p", "0.9"])
        # --temperature is replay-only.
        with pytest.raises(SystemExit):
            server.main(["--paged", "--spec", "ngram",
                         "--loadgen", "steady",
                         "--temperature", "0.5"])
        # Out-of-range sampling knobs are parse errors too -- not a
        # post-bring-up Request.__post_init__ traceback.
        with pytest.raises(SystemExit):
            server.main(["--paged", "--spec", "ngram",
                         "--temperature", "-0.5"])
        with pytest.raises(SystemExit):
            server.main(["--paged", "--spec", "ngram",
                         "--temperature", "0.7", "--top-p", "1.5"])

    def test_loadgen_with_spec_is_deterministic(self):
        """The virtual-clock summary stays byte-identical per
        (scenario, seed) with speculation on -- and speculation
        improves ITL p50 vs the plain paged run at the same shape
        (the banked-row mechanism, in miniature)."""
        from tpu_hpc.serve import server

        def run(spec):
            args = [
                "--loadgen", "steady", "--requests", "8",
                "--max-new", "24", "--slots", "2",
                "--buckets", "16,32", "--vocab", "64",
                "--paged",
            ]
            if spec:
                args += ["--spec", "ngram"]
            from tpu_hpc.serve.engine import ServeConfig  # noqa: F401
            import io
            import contextlib
            import json

            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                rc = server.main(args)
            assert rc == 0
            return json.loads(buf.getvalue().splitlines()[-1])

        a = run(spec=True)
        b = run(spec=True)
        for key in ("ttft_ms_p50", "ttft_ms_p95", "itl_ms_p50",
                    "itl_ms_p95", "tokens", "acceptance_rate",
                    "draft_ms"):
            assert a[key] == b[key], key
        assert a["recompiles"] == 0
        plain = run(spec=False)
        assert a["itl_ms_p50"] <= plain["itl_ms_p50"]
        assert a["spec_mode"] == "ngram"
