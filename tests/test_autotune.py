"""Flash-attention autotuner + independent backward tiling.

The backward dq/dkv kernels may be tiled independently of the forward
(blockwise_attention block_q_bwd/block_k_bwd). Invariants: tiling is
a schedule choice, never a numerics choice -- gradients must be
identical across tilings -- and the autotuner must rank candidates by
measured time with honest records.
"""
import jax
import jax.numpy as jnp
import pytest

from tpu_hpc.kernels import autotune
from tpu_hpc.kernels.attention import blockwise_attention

B, S, H, D = 2, 256, 2, 64


def _qkv(seed=0):
    kq, kk, kv = jax.random.split(jax.random.key(seed), 3)
    shape = (B, S, H, D)
    return (
        jax.random.normal(kq, shape, jnp.float32),
        jax.random.normal(kk, shape, jnp.float32),
        jax.random.normal(kv, shape, jnp.float32),
    )


def _grads(block_q_bwd, block_k_bwd):
    q, k, v = _qkv()

    def loss(q, k, v):
        out, _ = blockwise_attention(
            q, k, v, causal=True, impl="pallas_interpret",
            block_q=128, block_k=128,
            block_q_bwd=block_q_bwd, block_k_bwd=block_k_bwd,
        )
        return jnp.sum(out * out)

    return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)


def test_bwd_tiling_is_numerics_invariant():
    base = _grads(None, None)
    for bq, bk in ((256, 128), (128, 256), (256, 256)):
        other = _grads(bq, bk)
        for g0, g1 in zip(base, other):
            assert jnp.allclose(g0, g1, atol=1e-5), (bq, bk)


def test_autotune_ranks_and_records():
    records = autotune.autotune(
        seq_len=S, batch=B, n_heads=H, head_dim=D,
        mode="grad", candidates=((128, 128), (128, 256)),
        iters=2, impl="pallas_interpret",
    )
    assert len(records) == 2
    times = [r.ms_per_call for r in records]
    assert times == sorted(times)
    md = autotune.to_markdown(
        records, seq_len=S, batch=B, n_heads=H, kv_heads=H,
        head_dim=D, device_kind="cpu-interpret",
    )
    assert "Best:" in md and "ms/call" in md


def test_autotune_sweep_bwd_appends_pinned_fwd_rows():
    records = autotune.autotune(
        seq_len=S, batch=B, n_heads=H, head_dim=D,
        mode="grad", candidates=((128, 128), (256, 256)),
        sweep_bwd=True, iters=1, impl="pallas_interpret",
    )
    # 2 shared-tiling rows + 1 bwd-only row (the best fwd pair is
    # skipped as already measured).
    assert len(records) == 3
    bwd_rows = [r for r in records if r.block_q_bwd is not None]
    assert len(bwd_rows) == 1
    # The bwd-only row must pin its forward tiling to the FASTEST
    # shared-tiling pair.
    best_shared = min(
        (r for r in records if r.block_q_bwd is None),
        key=lambda r: r.ms_per_call,
    )
    assert (bwd_rows[0].block_q, bwd_rows[0].block_k) == (
        best_shared.block_q, best_shared.block_k
    )
    # And its bwd pair is the other candidate (the best pair itself is
    # skipped as already measured with shared tiling).
    assert (bwd_rows[0].block_q_bwd, bwd_rows[0].block_k_bwd) != (
        best_shared.block_q, best_shared.block_k
    )


def test_autotune_rejects_unknown_mode():
    with pytest.raises(ValueError):
        autotune.autotune(
            seq_len=S, batch=B, n_heads=H, head_dim=D, mode="bogus",
            candidates=((128, 128),), iters=1, impl="pallas_interpret",
        )


def test_autotune_rejects_no_fitting_candidate():
    with pytest.raises(ValueError, match="no candidate fits"):
        autotune.autotune(
            seq_len=128, batch=B, n_heads=H, head_dim=D,
            candidates=((256, 256),), iters=1, impl="pallas_interpret",
        )


def test_autotune_warns_on_fwd_sweep_bwd(capsys):
    records = autotune.autotune(
        seq_len=S, batch=B, n_heads=H, head_dim=D,
        mode="fwd", sweep_bwd=True, candidates=((128, 128),),
        iters=1, impl="pallas_interpret",
    )
    # The no-op is visible, and no bwd rows were appended.
    assert "ignoring" in capsys.readouterr().err
    assert all(r.block_q_bwd is None for r in records)
