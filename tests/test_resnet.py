"""ResNet family: shapes, depth variants, batch-stats updates, FSDP
training step (the reference could only validate these by running on
the cluster -- resnet_fsdp_training.py)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hpc.models import datasets, resnet


@pytest.mark.parametrize("depth", [18, 50])
def test_forward_shape(depth):
    cfg = resnet.ResNetConfig(depth=depth, num_classes=10)
    params, ms = resnet.init_resnet(jax.random.key(0), cfg)
    x = jnp.zeros((2, 32, 32, 3))
    logits, _ = resnet.apply_resnet(params, ms, x, cfg, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32


def test_param_counts_match_torchvision():
    """CIFAR-stem ResNet-18 ~= 11.2M params, ResNet-50 ~= 23.5M --
    the torchvision sizes the reference instantiates (scripts/
    main.py:249) minus the stem difference."""
    p18, _ = resnet.init_resnet(
        jax.random.key(0), resnet.ResNetConfig(depth=18)
    )
    n18 = sum(p.size for p in jax.tree.leaves(p18))
    assert 10.5e6 < n18 < 11.5e6
    p50, _ = resnet.init_resnet(
        jax.random.key(0), resnet.ResNetConfig(depth=50)
    )
    n50 = sum(p.size for p in jax.tree.leaves(p50))
    assert 23e6 < n50 < 24.5e6


def test_imagenet_stem_downsamples():
    cfg = resnet.ResNetConfig(depth=18, cifar_stem=False)
    params, ms = resnet.init_resnet(
        jax.random.key(0), cfg, sample_shape=(64, 64, 3)
    )
    x = jnp.zeros((1, 64, 64, 3))
    logits, _ = resnet.apply_resnet(params, ms, x, cfg, train=False)
    assert logits.shape == (1, 10)


def test_batch_stats_update():
    cfg = resnet.ResNetConfig(depth=18)
    params, ms = resnet.init_resnet(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (4, 32, 32, 3)) + 3.0
    _, new_ms = resnet.apply_resnet(params, ms, x, cfg, train=True)
    before = jax.tree.leaves(ms["batch_stats"])
    after = jax.tree.leaves(new_ms["batch_stats"])
    assert any(
        float(jnp.abs(a - b).max()) > 1e-6
        for a, b in zip(before, after)
    )


def test_fsdp_training_step(mesh8):
    from tpu_hpc.config import TrainingConfig
    from tpu_hpc.parallel import fsdp
    from tpu_hpc.train import Trainer

    cfg_m = resnet.ResNetConfig(depth=18)
    params, ms = resnet.init_resnet(jax.random.key(0), cfg_m)
    specs = fsdp.param_pspecs(params, axis_size=8)
    # The wrap policy must actually shard something big and leave
    # small tensors replicated.
    from jax.sharding import PartitionSpec as P
    flat = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    assert any(s != P() for s in flat) and any(s == P() for s in flat)

    cfg = TrainingConfig(
        epochs=1, steps_per_epoch=2, global_batch_size=16,
        learning_rate=1e-2,
    )
    trainer = Trainer(
        cfg, mesh8, resnet.make_forward(cfg_m), params, ms,
        param_pspecs=specs,
    )
    result = trainer.fit(datasets.CIFARSynthetic())
    assert np.isfinite(result["final_loss"])
