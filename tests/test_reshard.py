"""The resharding engine: exact wire model, parity vs the naive
reference, the max_inflight_bytes contract, and elastic resume.

Four invariant families:

* **wire model** -- modeled wire bytes come from the shardings'
  device->index maps, so hand-checkable cases must match exactly
  (equivalent placements 0, replicated->sharded 0, known overlaps);
* **parity** -- for random param trees and random source->target
  ``NamedSharding`` pairs (non-divisible shapes, bf16, degenerate
  1-sized axes, scalars, mesh-shape changes) the planned execution is
  BIT-identical to the naive replicate-then-shard reference
  (device_get -> host -> device_put): the engine moves bytes, it never
  touches them;
* **memory bound** -- a plan built under ``max_inflight_bytes``
  decomposes big moves into chunks, and the per-step compiled HLO's
  largest live tensor (checks/hlo.max_tensor_bytes -- compiled HLO is
  per-device) stays within the step's modeled HBM ceiling, while the
  unbounded program for the same leaf materializes the FULL array
  (GSPMD's involuntary full rematerialization -- the failure mode the
  decomposition exists to forbid);
* **elastic resume** -- a checkpoint saved on one mesh shape restores
  onto a different shape through the explicit reshard path, bit-exact,
  end-to-end under the supervisor with fault injection
  (TestElasticSupervised = the acceptance run), and a structurally
  incompatible checkpoint raises the typed TopologyMismatchError
  naming both topologies.
"""
import json
import os
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_hpc import reshard
from tpu_hpc.checks import hlo
from tpu_hpc.runtime import MeshSpec, build_mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def mesh_a(devices):
    """4-device 1-D mesh -- the 'before' topology."""
    return build_mesh(MeshSpec(axes={"data": 4}), devices=devices[:4])


@pytest.fixture(scope="module")
def mesh_b(devices):
    """4-device 2x2 mesh over the SAME chips -- the 'after' topology."""
    return build_mesh(
        MeshSpec(axes={"data": 2, "model": 2}), devices=devices[:4]
    )


def _put(mesh, spec, arr):
    return jax.device_put(arr, NamedSharding(mesh, spec))


def _naive(x, tgt):
    """The replicate-then-shard reference: gather everything to host,
    place it in the target layout. Trivially correct, maximally
    memory-hungry -- the behavior the engine must match bit-for-bit
    while never being forced to replicate."""
    return jax.device_put(np.asarray(jax.device_get(x)), tgt)


def _assert_moved(out, x, tgt):
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(_naive(x, tgt))
    )
    assert out.sharding.is_equivalent_to(tgt, out.ndim)


# ---------------------------------------------------------------------
# wire model
# ---------------------------------------------------------------------
class TestWireModel:
    def test_equivalent_placements_are_noop(self, mesh_2d):
        x = _put(mesh_2d, P("data"), jnp.zeros((8, 4)))
        plan = reshard.plan_reshard(
            {"x": x}, {"x": NamedSharding(mesh_2d, P("data"))}
        )
        assert plan.steps[0].kind == "noop"
        assert plan.wire_bytes == 0
        # noop passthrough: the SAME array comes back, no move at all.
        assert plan.execute({"x": x})["x"] is x

    def test_equivalence_across_mesh_spellings(self, mesh_2d):
        """P(('data','model')) on the 2x4 mesh assigns exactly what
        P('data') does on a flat 8-mesh over the same devices: the
        planner must see through the spelling."""
        mesh8 = build_mesh(MeshSpec(axes={"data": 8}))
        x = _put(mesh8, P("data"), jnp.arange(16.0))
        plan = reshard.plan_reshard(
            {"x": x},
            {"x": NamedSharding(mesh_2d, P(("data", "model")))},
        )
        assert plan.steps[0].kind == "noop"

    def test_replicated_to_sharded_is_local(self, mesh_2d):
        x = _put(mesh_2d, P(), jnp.zeros((8, 4)))
        plan = reshard.plan_reshard(
            {"x": x}, {"x": NamedSharding(mesh_2d, P("data", "model"))}
        )
        step = plan.steps[0]
        assert step.kind == "local"
        assert step.wire_bytes == 0
        out = plan.execute({"x": x})["x"]
        _assert_moved(out, x, NamedSharding(mesh_2d, P("data", "model")))

    def test_exchange_wire_bytes_hand_case(self, mesh_2d):
        """64x32 fp32, P('data') -> P(None,'model') on the 2x4 mesh:
        every device needs 64x8 elems (2048 B), already holds the
        32x8 intersection (1024 B) -> 8 x 1024 B = 8 KiB wire."""
        x = _put(
            mesh_2d, P("data"),
            jnp.zeros((64, 32), jnp.float32),
        )
        plan = reshard.plan_reshard(
            {"x": x}, {"x": NamedSharding(mesh_2d, P(None, "model"))}
        )
        step = plan.steps[0]
        assert step.kind == "exchange"
        assert step.wire_bytes == 8 * 1024

    def test_gather_wire_and_kind(self, mesh_2d):
        """Sharded -> fully replicated: every device fetches what it
        lacks; the step is 'gather' and lowers to an all-gather."""
        x = _put(mesh_2d, P("data"), jnp.zeros((8, 4), jnp.float32))
        plan = reshard.plan_reshard(
            {"x": x}, {"x": NamedSharding(mesh_2d, P())}
        )
        step = plan.steps[0]
        assert step.kind == "gather"
        # 8 devices each hold half (64 B) and need the rest (64 B).
        assert step.wire_bytes == 8 * 64
        counts = hlo.collective_counts(plan.step_hlo(0)[0])
        assert counts["all-gather"] >= 1

    def test_summary_and_describe(self, mesh_2d):
        x = _put(mesh_2d, P("data"), jnp.zeros((8, 4)))
        plan = reshard.plan_reshard(
            {"x": x}, {"x": NamedSharding(mesh_2d, P())}
        )
        s = plan.summary()
        assert s["steps"] == 1 and s["kinds"] == {"gather": 1}
        assert "gather" in plan.describe()


# ---------------------------------------------------------------------
# parity: random trees x random sharding pairs == naive reference
# ---------------------------------------------------------------------
# (shape, dtype): non-divisible dims, a scalar, a degenerate 1-sized
# axis, bf16 -- the shapes the satellite calls out.
_LEAF_CASES = (
    ((8, 12), jnp.float32),
    ((7, 4), jnp.bfloat16),
    ((16,), jnp.int32),
    ((1, 8, 6), jnp.float32),
    ((), jnp.float32),
    ((5,), jnp.bfloat16),
)


def _random_spec(rng, shape, mesh):
    """A random legal PartitionSpec: each dim claims an unused mesh
    axis (or axis pair) that divides it, or stays unsharded."""
    used = set()
    entries = []
    for dim in shape:
        opts = [None]
        free = [a for a in mesh.axis_names if a not in used]
        for ax in free:
            if mesh.shape[ax] > 1 and dim % mesh.shape[ax] == 0:
                opts.append(ax)
        if len(free) == 2:
            prod = mesh.shape[free[0]] * mesh.shape[free[1]]
            if dim % prod == 0:
                opts.append(tuple(free))
        pick = opts[int(rng.integers(len(opts)))]
        if isinstance(pick, str):
            used.add(pick)
        elif isinstance(pick, tuple):
            used.update(pick)
        entries.append(pick)
    return P(*entries)


class TestParity:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_tree_random_pairs_same_mesh(self, mesh_2d, seed):
        rng = np.random.default_rng(seed)
        tree, targets = {}, {}
        for i, (shape, dtype) in enumerate(_LEAF_CASES):
            data = rng.integers(-100, 100, size=shape or (1,))
            arr = jnp.asarray(
                data.reshape(shape) if shape else data[0], dtype
            )
            src = _random_spec(rng, shape, mesh_2d)
            tgt = _random_spec(rng, shape, mesh_2d)
            tree[f"l{i}"] = _put(mesh_2d, src, arr)
            targets[f"l{i}"] = NamedSharding(mesh_2d, tgt)
        out = reshard.apply(tree, targets)
        for k in tree:
            _assert_moved(out[k], tree[k], targets[k])

    @pytest.mark.parametrize("seed", [0, 1])
    def test_random_pairs_bounded(self, mesh_2d, seed):
        """Same property under a tight bound: chunked decomposition
        must stay bit-identical (uneven final chunks included)."""
        rng = np.random.default_rng(seed)
        tree, targets = {}, {}
        for i, (shape, dtype) in enumerate(_LEAF_CASES):
            data = rng.integers(-100, 100, size=shape or (1,))
            arr = jnp.asarray(
                data.reshape(shape) if shape else data[0], dtype
            )
            tree[f"l{i}"] = _put(
                mesh_2d, _random_spec(rng, shape, mesh_2d), arr
            )
            targets[f"l{i}"] = NamedSharding(
                mesh_2d, _random_spec(rng, shape, mesh_2d)
            )
        out = reshard.apply(tree, targets, max_inflight_bytes=96)
        for k in tree:
            _assert_moved(out[k], tree[k], targets[k])

    def test_mesh_shape_change(self, mesh_a, mesh_b, mesh_2d):
        """Cross-topology moves: 4 -> 2x2 over the same chips, 2x4
        (8 chips) -> 4 (a shrink), 4 -> 2x4 (a grow)."""
        rng = np.random.default_rng(7)
        x = jnp.asarray(rng.integers(-9, 9, size=(8, 4)), jnp.float32)
        cases = [
            (mesh_a, P("data"), mesh_b, P(None, "model")),
            (mesh_2d, P("data", "model"), mesh_a, P("data")),
            (mesh_a, P(None), mesh_2d, P(("data", "model"))),
        ]
        for src_mesh, src, tgt_mesh, tgt in cases:
            arr = _put(src_mesh, src, x)
            sharding = NamedSharding(tgt_mesh, tgt)
            plan = reshard.plan_reshard({"x": arr}, {"x": sharding})
            assert plan.steps[0].kind in ("transfer", "local", "noop")
            _assert_moved(plan.execute({"x": arr})["x"], arr, sharding)

    def test_mesh_change_bounded_chunked(self, mesh_a, mesh_b):
        """Cross-mesh chunked path, odd extent: 10 rows under a bound
        forcing 3-row chunks (last chunk is 1 row)."""
        x = _put(
            mesh_a, P(None, "data"),
            jnp.arange(10 * 8, dtype=jnp.float32).reshape(10, 8),
        )
        tgt = NamedSharding(mesh_b, P("data", "model"))
        plan = reshard.plan_reshard(
            {"x": x}, {"x": tgt}, max_inflight_bytes=3 * 8 * 4
        )
        step = plan.steps[0]
        assert step.kind == "transfer" and step.chunk is not None
        assert step.chunk.count == 4  # ceil(10 / 3)
        _assert_moved(plan.execute({"x": x})["x"], x, tgt)

    def test_single_sharding_broadcast_target(self, mesh_2d):
        tree = {
            "a": _put(mesh_2d, P("data"), jnp.zeros((8, 2))),
            "b": _put(mesh_2d, P(), jnp.ones((4,))),
        }
        rep = NamedSharding(mesh_2d, P())
        out = reshard.apply(tree, rep)
        for k in tree:
            assert out[k].sharding.is_fully_replicated

    def test_host_leaves_are_placed(self, mesh_2d):
        """Leaves with no committed sharding (host numpy, fresh jnp
        arrays) take the 'place' path."""
        tree = {"w": jnp.arange(8.0)}
        tgt = {"w": NamedSharding(mesh_2d, P("data"))}
        plan = reshard.plan_reshard(tree, tgt)
        assert plan.steps[0].kind == "place"
        out = plan.execute(tree)
        np.testing.assert_array_equal(
            np.asarray(out["w"]), np.arange(8.0)
        )

    def test_copy_noop_gives_fresh_buffers(self, mesh_2d):
        """The serve weight-placement contract: with copy_noop=True an
        already-correctly-placed leaf still comes back as a FRESH
        array (safe next to donation of the source tree), while the
        default passes the input through untouched."""
        x = _put(mesh_2d, P("data"), jnp.arange(8.0))
        tgt = {"x": NamedSharding(mesh_2d, P("data"))}
        assert reshard.apply({"x": x}, tgt)["x"] is x
        fresh = reshard.apply({"x": x}, tgt, copy_noop=True)["x"]
        assert fresh is not x
        np.testing.assert_array_equal(
            np.asarray(fresh), np.asarray(x)
        )

    def test_copy_noop_severs_device_put_aliasing(
        self, mesh_a, devices
    ):
        """device_put onto an overlapping device set can return
        buffers ALIASED with the source; under copy_noop the executor
        must sever that (the fresh-buffer contract holds on every
        path): deleting the source afterwards leaves the output
        readable."""
        sub = build_mesh(MeshSpec(axes={"data": 2}),
                         devices=devices[:2])
        x = _put(mesh_a, P(), jnp.arange(12.0))
        out = reshard.apply(
            {"x": x}, {"x": NamedSharding(sub, P())}, copy_noop=True
        )["x"]
        x.delete()
        np.testing.assert_array_equal(
            np.asarray(out), np.arange(12.0)
        )

    def test_place_params_fresh_buffer_contract(self, mesh_2d):
        """serve/weights.place_params keeps the old jitted-identity
        guarantee through the engine: no output leaf aliases its
        input, even when the input is already in the serving layout."""
        from tpu_hpc.serve.weights import place_params

        params = {"w": _put(mesh_2d, P(None, "model"),
                            jnp.zeros((4, 8)))}
        out = place_params(params, mesh_2d, {"w": P(None, "model")})
        assert out["w"] is not params["w"]

    def test_donate_frees_disjoint_tier_sources(self, devices):
        """The cross-tier memory contract (the disagg KV hop's shape):
        donate=True deletes each source buffer as its stage's target
        materializes when the tiers are DISJOINT -- the case jit
        donation cannot reach and buffer aliasing cannot occur."""
        lo = build_mesh(MeshSpec(axes={"data": 4}),
                        devices=devices[:4])
        hi = build_mesh(MeshSpec(axes={"data": 2, "model": 2}),
                        devices=devices[4:])
        x = _put(lo, P("data"), jnp.arange(32.0).reshape(8, 4))
        tgt = {"x": NamedSharding(hi, P(None, "model"))}
        out = reshard.apply({"x": x}, tgt, donate=True)
        assert x.is_deleted()
        np.testing.assert_array_equal(
            np.asarray(out["x"]), np.arange(32.0).reshape(8, 4)
        )

    def test_donate_keeps_overlapping_set_sources_alive(
        self, mesh_a, mesh_b
    ):
        """Overlapping device sets (the elastic-restore shape): jax
        may hand back ALIASED buffers from device_put, so donate must
        NOT hard-delete the source -- the output has to survive, and
        noop leaves pass through untouched."""
        x = _put(mesh_a, P("data"), jnp.arange(32.0).reshape(8, 4))
        tgt = {"x": NamedSharding(mesh_b, P(None, "model"))}
        out = reshard.apply({"x": x}, tgt, donate=True)
        np.testing.assert_array_equal(
            np.asarray(out["x"]), np.arange(32.0).reshape(8, 4)
        )
        y = _put(mesh_a, P("data"), jnp.arange(8.0))
        out2 = reshard.apply(
            {"y": y}, {"y": NamedSharding(mesh_a, P("data"))},
            donate=True,
        )
        assert out2["y"] is y and not y.is_deleted()

    def test_mismatched_tree_rejected(self, mesh_2d):
        x = _put(mesh_2d, P(), jnp.zeros((4,)))
        plan = reshard.plan_reshard(
            {"x": x}, {"x": NamedSharding(mesh_2d, P("data"))}
        )
        with pytest.raises(ValueError, match="does not match"):
            plan.execute({"x": _put(mesh_2d, P(), jnp.zeros((8,)))})


class TestLongShapes:
    def test_long_shape_bounded_parity_sweep(self, mesh_2d):
        """The slow-tier sweep: long shapes, more seeds, tight bounds
        driving chunk counts into the tens -- the same bit-identity
        property at a scale where a modeling bug would actually show
        (uneven final chunks, multi-axis specs, bf16)."""
        shapes = [
            ((256, 96), jnp.float32),
            ((130, 64), jnp.bfloat16),
            ((64, 48, 2), jnp.float32),
            ((1024,), jnp.int32),
            ((999,), jnp.bfloat16),
        ]
        for seed in range(4):
            rng = np.random.default_rng(seed)
            for shape, dtype in shapes:
                arr = jnp.asarray(
                    rng.integers(-100, 100, size=shape), dtype
                )
                src = _random_spec(rng, shape, mesh_2d)
                tgt = _random_spec(rng, shape, mesh_2d)
                x = _put(mesh_2d, src, arr)
                sharding = NamedSharding(mesh_2d, tgt)
                bound = max(256, x.nbytes // 7)
                plan = reshard.plan_reshard(
                    {"x": x}, {"x": sharding},
                    max_inflight_bytes=bound,
                )
                step = plan.steps[0]
                if step.chunk is not None and step.bound_met:
                    assert step.inflight_bytes <= bound
                _assert_moved(
                    plan.execute({"x": x})["x"], x, sharding
                )


# ---------------------------------------------------------------------
# the max_inflight_bytes contract, pinned via HLO introspection
# ---------------------------------------------------------------------
class TestMemoryBound:
    def test_max_tensor_bytes_reads_both_dialects(self, mesh_2d):
        """The instrument must not pass vacuously on lowered
        (StableHLO) text: both the compiled ``f32[64,32]`` and the
        StableHLO ``tensor<64x32xf32>`` spellings are measured."""
        x = _put(mesh_2d, P("data"), jnp.zeros((64, 32), jnp.float32))
        plan = reshard.plan_reshard(
            {"x": x}, {"x": NamedSharding(mesh_2d, P())}
        )
        compiled = plan.step_hlo(0, compiled=True)[0]
        lowered = plan.step_hlo(0, compiled=False)[0]
        assert hlo.max_tensor_bytes(compiled) == 64 * 32 * 4
        assert hlo.max_tensor_bytes(lowered) == 64 * 32 * 4

    def test_unbounded_exchange_materializes_full_replica(self, mesh_2d):
        """The failure mode: GSPMD solves P('data') -> P(None,'model')
        by involuntary full rematerialization -- the compiled per-device
        HLO holds the FULL 8 KiB array."""
        x = _put(
            mesh_2d, P("data"), jnp.zeros((64, 32), jnp.float32)
        )
        plan = reshard.plan_reshard(
            {"x": x}, {"x": NamedSharding(mesh_2d, P(None, "model"))}
        )
        assert plan.steps[0].chunk is None
        mx = max(hlo.max_tensor_bytes(t) for t in plan.step_hlo(0))
        assert mx == 64 * 32 * 4  # the full replica

    def test_bounded_plan_never_materializes_full_replica(self, mesh_2d):
        """THE acceptance pin: under max_inflight_bytes, every step
        program's largest live per-device tensor stays within the
        step's modeled HBM ceiling -- no program is ever allowed the
        full array the unbounded path materializes."""
        full = 64 * 32 * 4
        bound = full // 4
        x = _put(
            mesh_2d, P("data"), jnp.zeros((64, 32), jnp.float32)
        )
        plan = reshard.plan_reshard(
            {"x": x},
            {"x": NamedSharding(mesh_2d, P(None, "model"))},
            max_inflight_bytes=bound,
        )
        step = plan.steps[0]
        assert step.chunk is not None and step.bound_met
        assert step.inflight_bytes <= bound
        assert plan.peak_inflight_bytes <= bound
        for text in plan.step_hlo(0):
            mx = hlo.max_tensor_bytes(text)
            assert mx <= step.hbm_bound_bytes, (mx, step.hbm_bound_bytes)
            assert mx < full
        # And it still moves the bytes correctly.
        out = plan.execute({"x": x})["x"]
        assert out.sharding.is_equivalent_to(
            NamedSharding(mesh_2d, P(None, "model")), 2
        )

    def test_bound_unachievable_is_reported_not_fatal(self, mesh_2d):
        """A leaf that cannot chunk under the bound (single row
        already over it) still moves, with bound_met=False on record
        -- the plan is honest, not stuck."""
        x = _put(
            mesh_2d, P("data"), jnp.zeros((8, 64), jnp.float32)
        )
        plan = reshard.plan_reshard(
            {"x": x},
            {"x": NamedSharding(mesh_2d, P(None, "model"))},
            max_inflight_bytes=16,  # one 256 B row >> 16 B
        )
        assert not plan.bound_met
        assert not plan.steps[0].bound_met
        out = plan.execute({"x": x})["x"]
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(x)
        )

    def test_gather_is_exempt_from_chunking(self, mesh_2d):
        """Target-replicated moves: the full per-device copy is the
        REQUESTED residency; the bound must not chunk what it cannot
        reduce."""
        x = _put(mesh_2d, P("data"), jnp.zeros((64, 32), jnp.float32))
        plan = reshard.plan_reshard(
            {"x": x}, {"x": NamedSharding(mesh_2d, P())},
            max_inflight_bytes=128,
        )
        step = plan.steps[0]
        assert step.kind == "gather" and step.chunk is None
        assert step.inflight_bytes == 0

    def test_repeat_execute_uses_cached_programs(self, mesh_2d):
        x = _put(mesh_2d, P("data"), jnp.zeros((64, 32), jnp.float32))
        plan = reshard.plan_reshard(
            {"x": x},
            {"x": NamedSharding(mesh_2d, P(None, "model"))},
            max_inflight_bytes=2048,
        )
        plan.execute({"x": x})
        n_programs = len(plan._programs)
        plan.execute({"x": x})
        assert len(plan._programs) == n_programs


# ---------------------------------------------------------------------
# obs integration: the reshard_plan event + gauges
# ---------------------------------------------------------------------
class TestObsIntegration:
    def test_execution_emits_schema_valid_plan_event(
        self, mesh_2d, tmp_path
    ):
        from tpu_hpc import obs

        sink = str(tmp_path / "reshard.jsonl")
        x = _put(mesh_2d, P("data"), jnp.zeros((64, 32), jnp.float32))
        reshard.apply(
            {"x": x},
            {"x": NamedSharding(mesh_2d, P(None, "model"))},
            max_inflight_bytes=2048, label="test_move", sink=sink,
        )
        assert obs.validate_file(sink) >= 2  # span + reshard_plan
        recs = [json.loads(l) for l in open(sink)]
        plans = [r for r in recs if r["event"] == "reshard_plan"]
        assert len(plans) == 1
        rec = plans[0]
        assert rec["label"] == "test_move"
        assert rec["chunked_steps"] == 1
        assert rec["measured_bytes"] == 64 * 32 * 4
        assert rec["wire_bytes"] > 0
        spans = [r for r in recs if r["event"] == "span"]
        assert any(s["name"] == "reshard" for s in spans)

    def test_peak_hbm_gauge_set(self, mesh_2d):
        from tpu_hpc import obs

        x = _put(mesh_2d, P("data"), jnp.zeros((64, 32), jnp.float32))
        reshard.apply(
            {"x": x}, {"x": NamedSharding(mesh_2d, P(None, "model"))},
            max_inflight_bytes=2048,
        )
        reg = obs.get_registry()
        assert reg.gauge("reshard_peak_hbm_bytes") > 0
        assert reg.gauge("reshard_inflight_bytes") == 0  # reset after
        assert reg.counter("reshard_wire_bytes_total") > 0

    def test_peak_hbm_gauge_sums_packed_stages(self, mesh_2d):
        """An unbounded plan packs every same-mesh leaf into ONE
        program, so the modeled peak is the per-stage SUM, not the
        largest single leaf."""
        from tpu_hpc import obs

        tree = {
            "a": _put(mesh_2d, P("data"),
                      jnp.zeros((8, 8), jnp.float32)),
            "b": _put(mesh_2d, P("data"),
                      jnp.zeros((8, 8), jnp.float32)),
        }
        tgt = NamedSharding(mesh_2d, P(None, "model"))
        plan = reshard.plan_reshard(tree, {"a": tgt, "b": tgt})
        plan.execute(tree)
        one = (
            plan.steps[0].src_resident_bytes
            + plan.steps[0].resident_bytes
            + plan.steps[0].inflight_bytes
        )
        assert obs.get_registry().gauge(
            "reshard_peak_hbm_bytes"
        ) == 2 * one


# ---------------------------------------------------------------------
# elastic resume (in-process): sidecar -> reshard path -> bit-exact
# ---------------------------------------------------------------------
class TestElasticRestore:
    def _state(self, mesh, spec, value=None):
        w = (
            jnp.arange(32.0, dtype=jnp.float32).reshape(8, 4)
            if value is None else value
        )
        return {
            "w": _put(mesh, spec, w),
            "step": _put(mesh, P(), jnp.int32(7)),
        }

    def test_cross_topology_restore_bit_exact(
        self, mesh_a, mesh_b, tmp_path
    ):
        from tpu_hpc.ckpt import CheckpointManager

        mgr = CheckpointManager(str(tmp_path / "ck"), async_save=False)
        saved = self._state(mesh_a, P("data"))
        mgr.save(saved, step=7)
        mgr.wait()
        template = self._state(mesh_b, P(None, "model"),
                               value=jnp.zeros((8, 4)))
        restored = mgr.restore_latest(template)
        info = mgr.last_restore_info
        assert info["elastic"] is True
        assert info["src_mesh"] == {"data": 4}
        assert info["tgt_mesh"] == {"data": 2, "model": 2}
        assert info["plan"]["steps"] == 2
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.asarray(saved["w"])
        )
        assert restored["w"].sharding.is_equivalent_to(
            template["w"].sharding, 2
        )
        mgr.close()

    def test_same_topology_stays_on_direct_path(self, mesh_a, tmp_path):
        from tpu_hpc.ckpt import CheckpointManager

        mgr = CheckpointManager(str(tmp_path / "ck"), async_save=False)
        saved = self._state(mesh_a, P("data"))
        mgr.save(saved, step=3)
        mgr.wait()
        restored = mgr.restore_latest(saved)
        assert mgr.last_restore_info == {"step": 3, "elastic": False}
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.asarray(saved["w"])
        )
        mgr.close()

    def test_missing_sidecar_falls_back_to_direct(
        self, mesh_a, mesh_b, tmp_path
    ):
        """Pre-sidecar checkpoints (or a lost meta dir) restore
        exactly as before -- opaquely, but correctly."""
        import shutil

        from tpu_hpc.ckpt import CheckpointManager
        from tpu_hpc.reshard.elastic import SIDECAR_DIR

        d = str(tmp_path / "ck")
        mgr = CheckpointManager(d, async_save=False)
        saved = self._state(mesh_a, P("data"))
        mgr.save(saved, step=7)
        mgr.wait()
        shutil.rmtree(os.path.join(d, SIDECAR_DIR))
        template = self._state(mesh_b, P(None, "model"))
        restored = mgr.restore_latest(template)
        assert mgr.last_restore_info == {"step": 7, "elastic": False}
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.asarray(saved["w"])
        )
        mgr.close()

    def test_structural_mismatch_raises_typed_error(
        self, mesh_a, mesh_b, tmp_path
    ):
        """Satellite pin: a wrong-model relaunch surfaces a
        TopologyMismatchError naming source vs. live topology and the
        elastic-resume docs, not a generic orbax traceback."""
        from tpu_hpc.ckpt import CheckpointManager, TopologyMismatchError

        mgr = CheckpointManager(str(tmp_path / "ck"), async_save=False)
        mgr.save(self._state(mesh_a, P("data")), step=7)
        mgr.wait()
        bad_template = {
            "w": _put(mesh_b, P(None, "model"), jnp.zeros((16, 4))),
            "step": _put(mesh_b, P(), jnp.int32(0)),
        }
        with pytest.raises(TopologyMismatchError) as e:
            mgr.restore_latest(bad_template)
        msg = str(e.value)
        assert "{'data': 4}" in msg           # source topology
        assert "{'data': 2, 'model': 2}" in msg  # live topology
        assert "resharding.md" in msg
        mgr.close()

    def test_elastic_restore_lands_every_leaf_on_the_live_mesh(
        self, mesh_a, mesh_b, tmp_path
    ):
        """Replicated leaves (state.step) are assignment-equivalent
        across the throwaway source mesh and the live mesh; a naive
        passthrough would keep them COMMITTED to the source mesh, the
        next save's sidecar would record the stale topology, and the
        restart after THAT would mis-route. Pin the full round trip:
        restore -> all leaves on the live mesh -> save -> sidecar
        names the live mesh -> next restore takes the direct path."""
        from tpu_hpc.ckpt import CheckpointManager
        from tpu_hpc.reshard import read_sidecar

        d1, d2 = str(tmp_path / "ck1"), str(tmp_path / "ck2")
        mgr = CheckpointManager(d1, async_save=False)
        mgr.save(self._state(mesh_a, P("data")), step=7)
        mgr.wait()
        template = self._state(mesh_b, P(None, "model"))
        restored = mgr.restore_latest(template)
        for leaf in jax.tree.leaves(restored):
            assert leaf.sharding.mesh == mesh_b, leaf.sharding
        mgr.close()
        # The resumed run saves; its sidecar must name the LIVE mesh.
        mgr2 = CheckpointManager(d2, async_save=False)
        mgr2.save(restored, step=8)
        mgr2.wait()
        meta = read_sidecar(d2, 8)
        assert meta["mesh"] == {"data": 2, "model": 2}
        again = mgr2.restore_latest(template)
        assert mgr2.last_restore_info == {"step": 8, "elastic": False}
        np.testing.assert_array_equal(
            np.asarray(again["w"]), np.asarray(restored["w"])
        )
        mgr2.close()

    def test_dtype_switch_casts_like_the_direct_path(
        self, mesh_a, mesh_b, tmp_path
    ):
        """A dtype change on relaunch (the fp32->bf16 moments unlock)
        is a legal config change, not a structural mismatch: the
        elastic path restores into the LIVE dtype (orbax casts at
        restore time, exactly as the direct path does) and reshards
        the cast bytes."""
        from tpu_hpc.ckpt import CheckpointManager

        mgr = CheckpointManager(str(tmp_path / "ck"), async_save=False)
        saved = self._state(mesh_a, P("data"))  # float32 w
        mgr.save(saved, step=7)
        mgr.wait()
        template = {
            "w": _put(mesh_b, P(None, "model"),
                      jnp.zeros((8, 4), jnp.bfloat16)),
            "step": _put(mesh_b, P(), jnp.int32(0)),
        }
        restored = mgr.restore_latest(template)
        assert mgr.last_restore_info["elastic"] is True
        assert restored["w"].dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(restored["w"]),
            np.asarray(saved["w"]).astype(jnp.bfloat16),
        )
        mgr.close()

    def test_sidecar_pruned_with_checkpoints(self, mesh_a, tmp_path):
        from tpu_hpc.ckpt import CheckpointManager
        from tpu_hpc.reshard.elastic import SIDECAR_DIR

        d = str(tmp_path / "ck")
        mgr = CheckpointManager(d, max_to_keep=2, async_save=False)
        for s in (1, 2, 3, 4):
            mgr.save(self._state(mesh_a, P("data")), step=s)
            mgr.wait()
        names = sorted(os.listdir(os.path.join(d, SIDECAR_DIR)))
        kept = {f"{s}.json" for s in mgr.all_steps()}
        # The topology-history file shares the dir but is GC'd by
        # entry, not by file -- it never matches the per-step scan.
        assert set(names) - {"topology_history.json"} == kept
        mgr.close()

    def test_topology_history_pruned_with_checkpoints(
        self, mesh_a, tmp_path
    ):
        """The morph-history file is GC'd alongside the sidecars:
        ``save`` entries for collected checkpoints vanish, morph
        entries older than the oldest retained checkpoint vanish,
        and everything at or past the retention floor survives --
        the history cannot grow without bound on a long run."""
        from tpu_hpc.ckpt import CheckpointManager
        from tpu_hpc.reshard.elastic import (
            append_topology_history,
            read_topology_history,
        )

        d = str(tmp_path / "ck")
        mgr = CheckpointManager(d, max_to_keep=2, async_save=False)
        # Interleave saves with coordinator-style morph entries, the
        # shape a real elastic run writes.
        for s in (1, 2, 3, 4):
            mgr.save(self._state(mesh_a, P("data")), step=s)
            mgr.wait()
            append_topology_history(
                d, s, {"axes": {"data": 4}},
                reason="morph-shrink" if s % 2 else "morph-grow",
            )
        kept_steps = set(mgr.all_steps())
        assert kept_steps == {3, 4}
        history = read_topology_history(d)
        assert history, "history must survive pruning, trimmed"
        floor = min(kept_steps)
        for entry in history:
            if entry["reason"] == "save":
                assert entry["step"] in kept_steps
            else:
                assert entry["step"] >= floor
        # Both retained saves and both retained morphs are present.
        assert {
            e["step"] for e in history if e["reason"] == "save"
        } == kept_steps
        assert {
            e["reason"] for e in history if e["reason"] != "save"
        } == {"morph-shrink", "morph-grow"}
        # Stale entries are genuinely gone, not just shadowed.
        assert all(e["step"] >= floor for e in history)
        mgr.close()


# ---------------------------------------------------------------------
# THE acceptance run: supervised kill -> restart onto a DIFFERENT mesh
# ---------------------------------------------------------------------
ELASTIC_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    for var in ("TPU_VISIBLE_DEVICES", "TPU_CHIPS_PER_PROCESS_BOUNDS",
                "PALLAS_AXON_POOL_IPS", "AXON_POOL_SVC_OVERRIDE",
                "TPU_WORKER_HOSTNAMES"):
        os.environ.pop(var, None)
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tpu_hpc import resilience
    from tpu_hpc.ckpt import CheckpointManager
    from tpu_hpc.config import TrainingConfig
    from tpu_hpc.runtime import MeshSpec, build_mesh
    from tpu_hpc.train import Trainer

    # THE elastic contract: attempt 0 trains on data=4; every restart
    # lands on a 2x2 data x model mesh over the same chips -- the
    # preempted-pod-comes-back-smaller/reshaped scenario.
    attempt = int(os.environ.get("TPU_HPC_ATTEMPT", "0"))
    devs = jax.devices()
    if attempt == 0:
        mesh = build_mesh(
            MeshSpec(axes={"data": 4}), devices=devs[:4]
        )
        pspecs = {"w": P("data", None)}
    else:
        mesh = build_mesh(
            MeshSpec(axes={"data": 2, "model": 2}), devices=devs[:4]
        )
        pspecs = {"w": P(None, "model")}

    class DS:
        # Deterministic per-step batches keyed on the step index, so
        # the stream is mesh-shape independent.
        def batch_at(self, step, bs):
            k = jax.random.key(int(step) % 97)
            x = jax.random.normal(k, (bs, 8), jnp.float32)
            y = x @ jnp.arange(16.0, dtype=jnp.float32).reshape(8, 2)
            return x, y

    def forward(params, model_state, batch, step_rng):
        x, y = batch
        pred = x @ params["w"]
        return jnp.mean((pred - y) ** 2), model_state, {}

    ckpt_dir = os.environ["WORK_CKPT"]
    cfg = TrainingConfig(
        epochs=3, steps_per_epoch=2, global_batch_size=8,
        learning_rate=1e-2, save_every=1, checkpoint_dir=ckpt_dir,
        metrics_path=os.environ.get("WORK_METRICS", ""),
    )
    mgr = CheckpointManager(ckpt_dir, async_save=False)
    trainer = Trainer(
        cfg, mesh, forward, {"w": jnp.zeros((8, 2), jnp.float32)},
        param_pspecs=pspecs, checkpoint_manager=mgr,
    )
    if attempt >= 1:
        # Bit-exactness evidence BEFORE training continues: the
        # elastic restore of the newest step must byte-equal a direct
        # explicit-step restore of the same data.
        restored = mgr.restore_latest(trainer.state)
        info = mgr.last_restore_info
        assert info is not None and info["elastic"], info
        step = info["step"]
        ref = mgr.restore(step, restored)
        for a, b in zip(jax.tree.leaves(restored),
                        jax.tree.leaves(ref)):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            )
        print("ELASTIC_BITEXACT step", step,
              "src", info["src_mesh"], "tgt", info["tgt_mesh"],
              flush=True)
    result = trainer.fit(DS())
    print("FINAL_STEP", int(jax.device_get(trainer.state.step)),
          flush=True)
    sys.exit(resilience.exit_code_for(result["preempted"]))
""")


class TestElasticSupervised:
    def test_kill_restart_resumes_on_different_mesh(self, tmp_path):
        """Train on data=4, SIGKILL at step 4 via TPU_HPC_FAULTS;
        the supervisor restarts onto data=2 x model=2; the elastic
        reshard path restores step 2 bit-exact; training completes;
        the metrics JSONL carries ONE resumed run (2 run_starts, 1
        run_end at attempt 1, resumed_from_step 2) plus the
        elastic_restore event with its plan record."""
        worker = tmp_path / "worker.py"
        worker.write_text(ELASTIC_WORKER)
        sup_dir = str(tmp_path / "sup")
        env = dict(os.environ)
        prev = env.get("PYTHONPATH", "")
        env["PYTHONPATH"] = REPO + (os.pathsep + prev if prev else "")
        env["WORK_CKPT"] = str(tmp_path / "ckpts")
        env["WORK_METRICS"] = str(tmp_path / "run.jsonl")
        env["TPU_HPC_FAULTS"] = "kill_at_step=4"
        proc = subprocess.run(
            [
                sys.executable, "-m", "tpu_hpc.resilience.supervisor",
                "--max-restarts", "2", "--log-dir", sup_dir,
                "--backoff", "0.1", "--",
                sys.executable, str(worker),
            ],
            capture_output=True, text=True, timeout=420, env=env,
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr[-3000:]

        events = [
            json.loads(x)
            for x in open(os.path.join(sup_dir, "supervisor.jsonl"))
        ]
        ends = [e for e in events if e["event"] == "attempt_end"]
        assert [e["rc"] for e in ends] == [137, 0]

        a1 = open(os.path.join(sup_dir, "run.attempt1.log")).read()
        assert "ELASTIC_BITEXACT step 2" in a1
        assert "FINAL_STEP 6" in a1

        recs = [json.loads(x) for x in open(tmp_path / "run.jsonl")]
        # Schema discipline: the whole run log (elastic_restore
        # included) validates.
        from tpu_hpc.obs.schema import validate_file

        validate_file(str(tmp_path / "run.jsonl"))
        starts = [r for r in recs if r["event"] == "run_start"]
        assert len(starts) == 2
        assert starts[0]["start_step"] == 0
        assert starts[1]["start_step"] == 2
        elastic = [r for r in recs if r["event"] == "elastic_restore"]
        assert elastic, "elastic_restore event missing from run log"
        e = elastic[-1]
        assert e["from_step"] == 2
        assert e["src_mesh"] == {"data": 4}
        assert e["tgt_mesh"] == {"data": 2, "model": 2}
        assert e["plan"]["steps"] >= 2
        run_ends = [r for r in recs if r["event"] == "run_end"]
        assert len(run_ends) == 1  # a SINGLE resumed run
        end = run_ends[0]
        assert end["attempt"] == 1
        assert end["resumed_from_step"] == 2
        assert end["step"] == 6
        assert end["preempted"] is False
        assert end["goodput"]["restore_s"] > 0.0
