"""Disaggregated prefill/decode serving (serve/disagg.py).

The invariants, on the 8-device sim mesh split into two 4-chip tiers:

* **token parity** -- greedy decode through the disaggregated path
  (prefill tier -> reshard KV hop -> decode tier) is token-exact
  against the single-tier engine, which is itself pinned token-exact
  against the no-cache forward (tests/test_serve.py's oracle chain);
* **compile discipline** -- after warmup (both tiers' tables, the
  extract/insert executables, and every KV plan's programs via a
  dummy transfer) a mixed request stream triggers ZERO new compiles;
* **tier attribution** -- the replay summary carries the per-tier
  meshes, kv-transfer count/bytes and hop-latency quantiles, and the
  batcher stats fold in the transfer load;
* **flag discipline** -- ``--disagg`` on a workload that cannot
  consume it (--loadgen) is a CLI error, as is a disagg sizing flag
  without --disagg (the --comm-mode guard discipline).
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hpc.models import llama2
from tpu_hpc.runtime import MeshSpec, build_mesh
from tpu_hpc.serve import (
    ContinuousBatcher,
    DisaggEngine,
    Engine,
    Request,
    ServeConfig,
    split_serving_meshes,
)

TINY = llama2.LlamaConfig(
    dim=64, n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=128,
    multiple_of=16, max_seq_len=64, dtype=jnp.float32,
)
SCFG = ServeConfig(slots=4, max_seq_len=48, prefill_buckets=(8, 16))


@pytest.fixture(scope="module")
def tiny_params():
    return llama2.init_llama(jax.random.key(0), TINY)


@pytest.fixture(scope="module")
def warm_disagg(tiny_params, devices):
    prefill_mesh, decode_mesh = split_serving_meshes(8, TINY)
    engine = DisaggEngine(
        tiny_params, TINY, SCFG, prefill_mesh, decode_mesh,
        max_inflight_bytes=1 << 14,
    )
    engine.warmup()
    return engine


@pytest.fixture(scope="module")
def warm_single(tiny_params, devices):
    mesh = build_mesh(MeshSpec(axes={"data": 4, "model": 2}))
    engine = Engine(tiny_params, TINY, SCFG, mesh)
    engine.warmup()
    return engine


def _mix(seed=0, n=6, max_new=5):
    rng = np.random.default_rng(seed)
    lens = (7, 11, 9, 16, 3, 13)
    return [
        Request(
            rid=f"r{i}",
            prompt=rng.integers(0, TINY.vocab_size, size=lens[i % len(
                lens
            )]).tolist(),
            max_new_tokens=max_new,
        )
        for i in range(n)
    ]


class TestDisaggParity:
    def test_mixed_stream_token_exact_vs_single_tier(
        self, warm_single, warm_disagg
    ):
        """Both buckets, slot reuse, mid-stream admissions: every
        request's tokens equal the single-tier engine's -- the KV hop
        moved the bytes, nothing else."""
        a = ContinuousBatcher(warm_single).run(_mix())
        b = ContinuousBatcher(warm_disagg).run(_mix())
        assert a == b

    def test_tiers_are_disjoint_and_validated(self, tiny_params):
        pm, dm = split_serving_meshes(8, TINY)
        assert not (
            set(pm.devices.flat) & set(dm.devices.flat)
        )
        with pytest.raises(ValueError, match="disjoint"):
            DisaggEngine(tiny_params, TINY, SCFG, pm, pm)

    def test_split_needs_two_devices(self):
        with pytest.raises(ValueError, match=">= 2 devices"):
            split_serving_meshes(1, TINY)
        with pytest.raises(ValueError, match="prefill tier"):
            split_serving_meshes(8, TINY, prefill_devices=8)


class TestDisaggCompileDiscipline:
    def test_zero_recompiles_after_warmup(self, warm_disagg):
        n = warm_disagg.compile_count
        ContinuousBatcher(warm_disagg).run(_mix(seed=3))
        assert warm_disagg.compile_count == n

    def test_transfer_stats_ride_batcher(self, warm_disagg):
        before = warm_disagg.transfer_stats["kv_transfers"]
        batcher = ContinuousBatcher(warm_disagg)
        batcher.run(_mix(seed=4, n=3))
        assert (
            warm_disagg.transfer_stats["kv_transfers"] == before + 3
        )
        assert batcher.stats["kv_transfers"] == before + 3
        assert batcher.stats["kv_transfer_bytes"] > 0

    def test_describe_reports_tiers_and_plans(self, warm_disagg):
        d = warm_disagg.describe()
        assert set(d["prefill_mesh"]) and set(d["decode_mesh"])
        assert sorted(d["kv_plans"]) == [8, 16]
        for plan in d["kv_plans"].values():
            assert plan["bound_met"] is True
            assert plan["max_inflight_bytes"] == 1 << 14


class TestDisaggCLI:
    def test_replay_main_with_disagg(self, capsys):
        from tpu_hpc.serve import server

        rc = server.main([
            "--requests", "3", "--max-new", "2", "--slots", "2",
            "--buckets", "8", "--prompt-lens", "3,6", "--vocab", "64",
            "--disagg", "--disagg-max-inflight-mb", "1",
        ])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out.splitlines()[-1])
        assert summary["recompiles"] == 0
        d = summary["disagg"]
        assert d["kv_transfers"] == 3
        assert d["kv_transfer_bytes"] > 0
        assert "kv_transfer_ms_p95" in d
        assert summary["batcher"]["kv_transfers"] == 3

    def test_disagg_with_loadgen_is_cli_error(self):
        """Misplaced-flag discipline: the loadgen harness cannot
        consume the tier split -- silent single-tier would be a lie."""
        from tpu_hpc.serve import server

        with pytest.raises(SystemExit):
            server.main([
                "--loadgen", "steady", "--disagg",
            ])

    def test_disagg_sizing_without_disagg_is_cli_error(self):
        from tpu_hpc.serve import server

        with pytest.raises(SystemExit):
            server.main(["--disagg-max-inflight-mb", "4"])

    def test_bench_serve_disagg_flag_guard(self):
        """bench.py: --serve-disagg on a non-serve workload errors."""
        import bench

        with pytest.raises(SystemExit):
            bench.main(["--workload", "llama", "--serve-disagg",
                        "--steps", "1"])
