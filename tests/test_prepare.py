"""Corpus preparation: text -> token binary -> training batches.

Covers tpu_hpc/native/prepare.py: the streaming writer's header
patching vs the one-shot writer, the byte tokenizer's reversibility,
document/EOT layout, CLI, and the end-to-end path a user follows
(prepare a corpus from text, open it with NativeTokenDataset).
"""
import subprocess
import sys

import numpy as np
import pytest

from tpu_hpc.native import write_token_dataset
from tpu_hpc.native.prepare import (
    TokenDatasetWriter,
    byte_tokenizer,
    iter_documents,
    main,
    prepare_corpus,
    resolve_tokenizer,
)


class TestWriter:
    def test_streamed_equals_oneshot(self, tmp_path):
        """Chunked appends produce the identical file to the one-shot
        writer when the dtype choice agrees."""
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 257, size=10_000, dtype=np.int64)
        tokens[-1] = 256  # pin max id so both writers agree on it
        one = str(tmp_path / "one.bin")
        write_token_dataset(one, tokens)
        streamed = str(tmp_path / "str.bin")
        with TokenDatasetWriter(streamed, vocab_size=257) as w:
            for i in range(0, tokens.size, 997):  # ragged chunks
                w.append(tokens[i:i + 997])
        assert open(one, "rb").read() == open(streamed, "rb").read()

    def test_dtype_follows_vocab(self, tmp_path):
        w16 = TokenDatasetWriter(str(tmp_path / "a"), vocab_size=65536)
        w32 = TokenDatasetWriter(str(tmp_path / "b"), vocab_size=65537)
        assert w16.dtype == np.uint16 and w32.dtype == np.uint32
        for w in (w16, w32):
            w.append(np.arange(10))
            w.close()

    def test_out_of_vocab_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="outside vocab_size"):
            with TokenDatasetWriter(str(tmp_path / "a"), 100) as w:
                w.append(np.asarray([5, 100]))
        # the context manager removed the partial file
        assert not (tmp_path / "a").exists()

    def test_too_short_corpus_rejected(self, tmp_path):
        w = TokenDatasetWriter(str(tmp_path / "a"), 100)
        w.append(np.asarray([1]))
        with pytest.raises(ValueError, match="at least 2 tokens"):
            w.close()
        assert not (tmp_path / "a").exists()

    def test_failed_prepare_removes_partial_file(self, tmp_path):
        path = tmp_path / "a"
        with pytest.raises(RuntimeError):
            with TokenDatasetWriter(str(path), 300) as w:
                w.append(np.arange(100))
                raise RuntimeError("tokenizer exploded")
        assert not path.exists()


class TestTokenizers:
    def test_byte_roundtrip(self):
        encode, vocab, eot = byte_tokenizer()
        text = "halo exchange über the mesh\n"
        ids = encode(text)
        assert vocab == 257 and eot == 256
        assert bytes(ids.astype(np.uint8)).decode("utf-8") == text

    def test_resolve_rejects_unknown(self):
        with pytest.raises(ValueError, match="unknown tokenizer"):
            resolve_tokenizer("sentencepiece")


class TestPrepare:
    def test_end_to_end_text_to_batches(self, tmp_path):
        """The full user path: two text documents -> corpus file ->
        NativeTokenDataset windows with the EOT separator in place."""
        native = pytest.importorskip("tpu_hpc.native.dataloader")
        if not native.native_available():
            pytest.skip("native loader unavailable")
        a, b = tmp_path / "a.txt", tmp_path / "b.txt"
        a.write_text("first document\n")
        b.write_text("second one\n")
        out = str(tmp_path / "corpus.bin")
        info = prepare_corpus(out, [str(a), str(b)])
        raw = (a.read_text() + "\x00" + b.read_text()).encode()
        # EOT id 256 sits where the \x00 placeholder is
        expect = np.frombuffer(raw, np.uint8).astype(np.int64)
        expect[expect == 0] = 256
        expect = np.append(expect, 256)  # trailing doc separator
        assert info["n_tokens"] == expect.size
        ds = native.NativeTokenDataset(
            out, batch_size=2, seq_len=8, seed=0
        )
        try:
            x, y = ds.batch_at(0, 2)
            # every (input, target) pair is a shifted window of expect
            flat = expect
            for row_x, row_y in zip(np.asarray(x), np.asarray(y)):
                starts = np.flatnonzero(flat[:-8] == row_x[0])
                assert any(
                    np.array_equal(flat[s:s + 8], row_x)
                    and np.array_equal(flat[s + 1:s + 9], row_y)
                    for s in starts
                )
        finally:
            ds.close()

    def test_no_eot_flag(self, tmp_path):
        a = tmp_path / "a.txt"
        a.write_text("ten chars!")
        out = str(tmp_path / "c.bin")
        info = prepare_corpus(out, [str(a)], append_eot=False)
        assert info["n_tokens"] == 10

    def test_custom_encode_requires_vocab(self, tmp_path):
        with pytest.raises(ValueError, match="requires vocab_size"):
            prepare_corpus(
                str(tmp_path / "c.bin"), [], encode=lambda t: [1]
            )

    def test_custom_documents_iterable(self, tmp_path):
        out = str(tmp_path / "c.bin")
        info = prepare_corpus(
            out, [], documents=["abc", "de"],
            encode=lambda t: np.frombuffer(t.encode(), np.uint8),
            vocab_size=257, eot_id=256,
        )
        assert info["n_tokens"] == 3 + 1 + 2 + 1

    def test_iter_documents_bounded_chunks(self, tmp_path):
        """Fixed-size reads: bounded memory even with no newlines,
        exact reassembly, and no UTF-8 tearing at chunk edges."""
        p = tmp_path / "t.txt"
        text = ("ünïcödé " * 200)  # newline-free, multi-byte chars
        p.write_text(text, encoding="utf-8")
        chunks = list(iter_documents([str(p)], chunk_bytes=64))
        assert len(chunks) > 1
        assert all(len(c) <= 64 for c in chunks)
        assert "".join(chunks) == text

    def test_chunk_unsafe_encodes_whole_file(self, tmp_path):
        """BPE-style tokenizers must see each file in one piece --
        chunk boundaries would change the ids (review finding)."""
        p = tmp_path / "t.txt"
        p.write_text("x" * 500)
        calls = []

        def encode(text):
            calls.append(len(text))
            return np.frombuffer(text.encode(), np.uint8)

        prepare_corpus(
            str(tmp_path / "c.bin"), [str(p)], encode=encode,
            vocab_size=257, chunk_safe=False,
        )
        assert calls == [500]

    def test_byte_tokenizer_streams_in_chunks(self, tmp_path, monkeypatch):
        """The byte path stays O(chunk): a file bigger than the chunk
        size is encoded in several pieces with identical output."""
        import tpu_hpc.native.prepare as prep

        p = tmp_path / "t.txt"
        p.write_text("abc" * 1000)
        monkeypatch.setattr(
            prep, "iter_documents",
            lambda paths, chunk_bytes=64: iter_documents(
                paths, chunk_bytes=64
            ),
        )
        out = str(tmp_path / "c.bin")
        info = prep.prepare_corpus(out, [str(p)])
        assert info["n_tokens"] == 3001  # 3000 bytes + EOT
        data = np.fromfile(out, np.uint16, offset=32)
        assert bytes(data[:-1].astype(np.uint8)).decode() == "abc" * 1000


class TestCLI:
    def test_main_writes_corpus(self, tmp_path, capsys):
        a = tmp_path / "a.txt"
        a.write_text("hello corpus\n")
        out = str(tmp_path / "c.bin")
        assert main([str(a), "--out", out]) == 0
        hdr = np.fromfile(out, np.uint64, count=4)
        assert int(hdr[1]) == 14  # 13 bytes + EOT

    def test_module_invocation(self, tmp_path):
        a = tmp_path / "a.txt"
        a.write_text("module run\n")
        out = str(tmp_path / "c.bin")
        r = subprocess.run(
            [sys.executable, "-m", "tpu_hpc.native.prepare",
             str(a), "--out", out],
            capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 0, r.stderr
        assert "wrote" in r.stderr
