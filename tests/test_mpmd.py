"""MPMD pipeline runtime (tpu_hpc.parallel.mpmd): per-stage AOT
programs with per-stage fault domains.

The pinned contracts:

* SPMD-vs-MPMD parity: the same microbatch schedule produces
  BIT-IDENTICAL per-microbatch losses against the SPMD shard_map
  engine (pp.pipelined), and gradients agreeing to float32-ulp
  accumulation noise (measured ~3e-9; the scan transpose fuses its
  per-tick vjps differently than standalone programs).
* Zero-recompile steady state: after warmup, no worker's executable
  table ever grows.
* The chaos acceptance: a stage killed mid-run is detected BY NAME,
  only that stage restarts (healthy stages keep their worker objects,
  executables and resident weights -- compile counters pinned), the
  in-flight microbatches replay, and the final params + loss stream
  are bit-identical to the no-fault run. The stage_nan_at variant
  recovers through the per-stage guard path with the poisoned window
  recorded.
* Vacuous-pass guards: stage faults on a non-MPMD run fail loudly
  (SPMD Trainer + a fault naming a nonexistent stage), and the typed
  parse discipline names key/spec/expected type.
* Per-stage budgets: a flapping stage exhausts its OWN budget
  (StageBudgetExhausted with the right exit code), never the
  whole-run one.
"""
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hpc import obs
from tpu_hpc.models import losses, pipeline_transformer as ptx
from tpu_hpc.parallel import mpmd, pp
from tpu_hpc.resilience.faults import fault_plan_from_env
from tpu_hpc.resilience.signals import EXIT_ROLLBACK
from tpu_hpc.runtime import MeshSpec, build_mesh

CFG = ptx.PipeConfig(
    vocab_size=64, dim=32, n_heads=2, n_stages=4, layers_per_stage=1,
    max_seq_len=16,
)
M = 4  # microbatches; batch 8 -> microbatch size 2


@pytest.fixture(scope="module")
def data():
    params = ptx.init_pipeline_transformer(jax.random.key(0), CFG)
    tokens = np.asarray(jax.random.randint(
        jax.random.key(1), (8, 16), 0, CFG.vocab_size, dtype=jnp.int32
    ))
    targets = np.asarray(jax.random.randint(
        jax.random.key(2), (8, 16), 0, CFG.vocab_size, dtype=jnp.int32
    ))
    return params, tokens, targets


@pytest.fixture()
def fresh_bus(tmp_path):
    """Isolated bus with a JSONL sink for event assertions."""
    sink = str(tmp_path / "events.jsonl")
    prev = obs.set_bus(obs.EventBus(path=sink, flight_dir=""))
    yield sink
    obs.set_bus(prev)


def _build(data, fault_spec=None, events_path=None, **cfg_kw):
    params, tokens, _ = data
    plan = (
        fault_plan_from_env({"TPU_HPC_FAULTS": fault_spec})
        if fault_spec else None
    )
    bundle = ptx.mpmd_bundle(params, CFG)
    cfg = mpmd.MpmdConfig(n_microbatches=M, **cfg_kw)
    return mpmd.MpmdPipeline(
        bundle, cfg, fault_plan=plan, events_path=events_path
    ).build(tokens)


def _tree_equal(a, b) -> bool:
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


@pytest.fixture(scope="module")
def clean_run(data):
    """One shared clean pipeline: parity grads off the fresh state,
    then a 3-step training run -- the bit-identity baseline every
    chaos variant compares against."""
    params, tokens, targets = data
    pipe = _build(data)
    warm_counts = list(pipe.compile_counts)
    loss_v, grads, edge = pipe.loss_and_grads(tokens, targets)
    batches = [(tokens, targets)] * 3
    result = pipe.train(batches)
    states = [pipe.stage_state(s) for s in range(CFG.n_stages)]
    return {
        "pipe": pipe, "warm_counts": warm_counts,
        "loss_v": loss_v, "grads": grads, "edge": edge,
        "result": result, "states": states, "batches": batches,
    }


# ---------------------------------------------------------------------
# parity: SPMD engine vs MPMD runtime on the same schedule
# ---------------------------------------------------------------------
class TestParity:
    @pytest.fixture(scope="class")
    def spmd_ref(self, data):
        """Per-microbatch loss vector + grads through the SPMD
        shard_map engine (pp.pipelined gpipe) -- the same microbatch
        schedule, the same mean-of-per-microbatch-means loss."""
        params, tokens, targets = data
        mesh = build_mesh(
            MeshSpec(axes={"pipe": 4}), devices=jax.devices()[:4]
        )
        pipe = pp.pipelined(
            ptx.make_stage_fn(CFG), mesh, axis="pipe",
            schedule="gpipe",
        )

        def loss_vec(params, tokens, targets):
            xs = ptx.embed(params, pp.microbatch(tokens, M), CFG)
            ys = pipe(params["stages"], xs)
            logits = jax.vmap(lambda y: ptx.head(params, y, CFG))(ys)
            return jax.vmap(losses.cross_entropy)(
                logits, pp.microbatch(targets, M)
            )

        lv = jax.jit(loss_vec)(params, tokens, targets)
        g = jax.jit(jax.grad(
            lambda p, t, y: jnp.mean(loss_vec(p, t, y))
        ))(params, tokens, targets)
        return np.asarray(lv), g

    def test_losses_bitwise_identical(self, clean_run, spmd_ref):
        lv_spmd, _ = spmd_ref
        np.testing.assert_array_equal(
            np.asarray(clean_run["loss_v"], np.float32), lv_spmd
        )

    def test_stage_grads_match_spmd(self, clean_run, spmd_ref):
        _, g = spmd_ref
        for s in range(CFG.n_stages):
            ref = jax.tree.map(lambda a: np.asarray(a[s]), g["stages"])
            got = clean_run["grads"][s]
            for (path, r), gg in zip(
                jax.tree_util.tree_flatten_with_path(ref)[0],
                jax.tree.leaves(got),
            ):
                np.testing.assert_allclose(
                    r, gg, atol=1e-7, rtol=1e-5,
                    err_msg=f"stage {s} {jax.tree_util.keystr(path)}",
                )

    def test_edge_grads_match_spmd(self, clean_run, spmd_ref):
        _, g = spmd_ref
        for name in ("embed", "head"):
            for r, gg in zip(
                jax.tree.leaves(g[name]),
                jax.tree.leaves(clean_run["edge"][name]),
            ):
                np.testing.assert_allclose(
                    np.asarray(r), gg, atol=1e-6, rtol=1e-5,
                    err_msg=name,
                )

    def test_mean_loss_matches_sequential_oracle(self, data, clean_run):
        params, tokens, targets = data
        logits = ptx.apply_sequential(params, tokens, CFG)
        oracle = float(losses.cross_entropy(logits, targets))
        got = float(np.mean(clean_run["loss_v"]))
        np.testing.assert_allclose(got, oracle, atol=1e-5)


# ---------------------------------------------------------------------
# zero-recompile steady state
# ---------------------------------------------------------------------
def test_steady_state_zero_recompiles(clean_run):
    # 1 parity pass + 3 training steps (with recovery-free updates,
    # snapshots, health checks) after warmup: no executable table
    # ever grew.
    pipe = clean_run["pipe"]
    assert pipe.compile_counts == clean_run["warm_counts"]


def test_needs_one_device_per_stage(data):
    params, *_ = data
    bundle = ptx.mpmd_bundle(params, CFG)
    with pytest.raises(ValueError, match="disjoint fault domains"):
        mpmd.MpmdPipeline(
            bundle, mpmd.MpmdConfig(n_microbatches=M),
            devices=jax.devices()[:2],
        )


# ---------------------------------------------------------------------
# the chaos acceptance (tier-1): kill / nan / straggler / heartbeat
# ---------------------------------------------------------------------
class TestStageKill:
    def test_kill_recovers_stage_local_and_bit_identical(
        self, data, clean_run, fresh_bus
    ):
        params, tokens, targets = data
        pipe = _build(
            data, fault_spec="stage_kill_at=1:1",
            events_path=fresh_bus,
        )
        before = list(pipe.workers)
        counts_before = list(pipe.compile_counts)
        res = pipe.train(clean_run["batches"])

        # Detection named the stage; exactly one stage-local restart.
        assert res["recoveries"] == [{
            "stage": 1, "reason": "crash", "step": 1,
            "mttr_s": res["recoveries"][0]["mttr_s"],
            "kind": "restart",
        }]
        assert res["stage_restarts"] == {1: 1}
        assert res["recovery_mttr_s"] > 0
        # The dead stage held every microbatch of the step in flight
        # (the kill fires at its last forward dispatch) -- all
        # replayed.
        assert res["redispatched"] == M
        # Healthy stages: same worker objects, same executables, same
        # compile counters.
        for s in (0, 2, 3):
            assert pipe.workers[s] is before[s]
            assert pipe.compile_counts[s] == counts_before[s]
        assert pipe.workers[1] is not before[1]
        # The headline: loss stream AND final params bit-identical to
        # the no-fault run.
        assert res["losses"] == clean_run["result"]["losses"]
        for s in range(CFG.n_stages):
            assert _tree_equal(
                pipe.stage_state(s), clean_run["states"][s]
            ), f"stage {s} final state diverged"

        # The evidence trail is schema-valid and names the stage.
        from tpu_hpc.obs.schema import load_records, validate_file

        validate_file(fresh_bus)
        recs = load_records(fresh_bus)
        downs = [r for r in recs if r["event"] == "stage_down"]
        ups = [r for r in recs if r["event"] == "stage_up"]
        redis = [
            r for r in recs if r["event"] == "stage_redispatch"
        ]
        assert [d["stage"] for d in downs] == [1]
        assert downs[0]["reason"] == "crash"
        assert [u["stage"] for u in ups] == [1]
        assert ups[0]["reason"] == "restart"
        assert ups[0]["mttr_s"] > 0
        assert len(redis) == M
        assert {r["stage"] for r in redis} == {1}


class TestStageNan:
    def test_nan_recovers_via_guard_path(
        self, data, clean_run, fresh_bus
    ):
        pipe = _build(
            data, fault_spec="stage_nan_at=2:1",
            events_path=fresh_bus,
        )
        before = list(pipe.workers)
        res = pipe.train(clean_run["batches"])
        # Guard-poisoned detection at stage granularity, rollback
        # charged against the stage's ROLLBACK budget.
        assert res["stage_rollbacks"] == {2: 1}
        assert res["stage_restarts"] == {}
        assert res["recoveries"][0]["reason"] == "guard-poisoned"
        # The poisoned window is recorded.
        assert res["poisoned_windows"] == [{
            "stage": 2, "step": 1, "microbatch": 0,
            "phase": "forward",
        }]
        # Stage-local: healthy stages untouched.
        for s in (0, 1, 3):
            assert pipe.workers[s] is before[s]
        # Bit-identical to the no-fault run (the transient SDC's
        # poisoned attempt never committed an update).
        assert res["losses"] == clean_run["result"]["losses"]
        for s in range(CFG.n_stages):
            assert _tree_equal(
                pipe.stage_state(s), clean_run["states"][s]
            )

        from tpu_hpc.obs.schema import load_records

        recs = load_records(fresh_bus)
        verdicts = [
            r for r in recs if r["event"] == "guard_verdict"
        ]
        assert any(
            v["verdict"] == "poisoned" and v.get("stage") == 2
            for v in verdicts
        )
        rollbacks = [
            r for r in recs if r["event"] == "guard_rollback"
        ]
        assert rollbacks and rollbacks[0]["stage"] == 2
        downs = [r for r in recs if r["event"] == "stage_down"]
        assert downs[0]["reason"] == "guard-poisoned"


class TestStraggler:
    def test_straggler_detected_and_bubble_grows(
        self, data, clean_run, fresh_bus
    ):
        pipe = _build(
            data, fault_spec="stage_straggler=1:8",
            events_path=fresh_bus,
        )
        res = pipe.train(clean_run["batches"])
        # Numerics are untouched -- a slow stage is degraded, not
        # wrong.
        assert res["losses"] == clean_run["result"]["losses"]
        # Cross-stage slow detection names the stage; the bubble
        # telemetry carries it.
        assert res["stragglers"].get(1, 0) >= 1
        assert res["bubble_fraction"] > \
            clean_run["result"]["bubble_fraction"]

        from tpu_hpc.obs.schema import load_records

        bubbles = [
            r for r in load_records(fresh_bus)
            if r["event"] == "pipeline_bubble"
        ]
        assert any(
            b.get("straggler_stage") == 1 for b in bubbles
        )


class TestHeartbeat:
    def test_wedged_stage_detected_by_heartbeat_timeout(
        self, data, clean_run, fresh_bus
    ):
        params, tokens, targets = data
        pipe = _build(data, events_path=fresh_bus)
        pipe.workers[2].wedged = True
        loss0 = pipe.run_step(0, tokens, targets)
        assert pipe.recoveries[0]["reason"] == "heartbeat-timeout"
        assert pipe.recoveries[0]["stage"] == 2
        assert not pipe.workers[2].wedged  # fresh worker
        # The replayed step is the clean step 0.
        assert loss0 == clean_run["result"]["losses"][0]

        from tpu_hpc.obs.schema import load_records

        downs = [
            r for r in load_records(fresh_bus)
            if r["event"] == "stage_down"
        ]
        assert downs[0]["reason"] == "heartbeat-timeout"
        assert downs[0]["beat_age_s"] == pytest.approx(
            pipe.cfg.heartbeat_timeout_s
        )


# ---------------------------------------------------------------------
# budgets: stage-scoped accounting
# ---------------------------------------------------------------------
class TestBudgets:
    def test_supervisor_charges_per_stage(self):
        sup = mpmd.StageSupervisor(max_restarts=2, max_rollbacks=1)
        assert sup.charge(0, "restart") == 1
        assert sup.charge(0, "restart") == 2
        # Stage 1's budget is its own.
        assert sup.charge(1, "restart") == 1
        with pytest.raises(mpmd.StageBudgetExhausted) as ei:
            sup.charge(0, "restart")
        assert ei.value.stage == 0
        assert ei.value.exit_code == 1  # restart-class: plain failure

    def test_rollback_budget_exit_code(self):
        sup = mpmd.StageSupervisor(max_restarts=2, max_rollbacks=1)
        sup.charge(3, "rollback")
        with pytest.raises(mpmd.StageBudgetExhausted) as ei:
            sup.charge(3, "rollback")
        # Rollback-class exhaustion dies with EXIT_ROLLBACK so the
        # PROCESS supervisor charges its rollback budget -- never the
        # failure budget.
        assert ei.value.exit_code == EXIT_ROLLBACK
        # The restart book is untouched by rollback charges.
        assert sup.restarts == {}

    def test_flapping_stage_exhausts_own_budget(self, data):
        pipe = _build(data, max_stage_restarts=1)
        params, tokens, targets = data
        pipe.workers[1].wedged = True
        orig = pipe._new_worker

        def wedged_worker(sid):
            w = orig(sid)
            w.wedged = True  # the replacement flaps too
            return w

        pipe._new_worker = wedged_worker
        with pytest.raises(mpmd.StageBudgetExhausted) as ei:
            pipe.run_step(0, tokens, targets)
        assert ei.value.stage == 1
        assert ei.value.kind == "restart"

    def test_config_default_rides_supervisor_env(self, monkeypatch):
        monkeypatch.setenv(mpmd.ENV_MAX_STAGE_RESTARTS, "7")
        assert mpmd.MpmdConfig(
            n_microbatches=2
        ).max_stage_restarts == 7
        monkeypatch.delenv(mpmd.ENV_MAX_STAGE_RESTARTS)
        assert mpmd.MpmdConfig(
            n_microbatches=2
        ).max_stage_restarts == 3


def test_supervisor_exports_stage_budget(tmp_path):
    from tpu_hpc.resilience.supervisor import Supervisor

    probe = (
        "import os, sys; sys.exit(0 if os.environ.get("
        "'TPU_HPC_MAX_STAGE_RESTARTS') == '2' else 3)"
    )
    sup = Supervisor(
        [sys.executable, "-c", probe],
        max_restarts=0, max_stage_restarts=2,
        log_dir=str(tmp_path),
    )
    assert sup.run() == 0
    # Unset flag: nothing exported (the child keeps its own default).
    absent = (
        "import os, sys; sys.exit(0 if "
        "'TPU_HPC_MAX_STAGE_RESTARTS' not in os.environ else 3)"
    )
    prev = os.environ.pop("TPU_HPC_MAX_STAGE_RESTARTS", None)
    try:
        sup2 = Supervisor(
            [sys.executable, "-c", absent], max_restarts=0,
            log_dir=str(tmp_path / "b"),
        )
        assert sup2.run() == 0
    finally:
        if prev is not None:
            os.environ["TPU_HPC_MAX_STAGE_RESTARTS"] = prev
    with pytest.raises(ValueError, match="max_stage_restarts"):
        Supervisor(["true"], max_stage_restarts=-1)


# ---------------------------------------------------------------------
# fault parse + vacuous-pass guards
# ---------------------------------------------------------------------
class TestStageFaultSpec:
    def test_typed_parse(self):
        plan = fault_plan_from_env({
            "TPU_HPC_FAULTS":
                "stage_kill_at=1:2,stage_straggler=0:2.5",
        })
        assert plan.stage_kill_at == (1, 2)
        assert plan.stage_straggler == (0, 2.5)
        assert plan.stage_fault_keys() == [
            "stage_kill_at", "stage_straggler",
        ]

    def test_malformed_value_names_key_and_type(self):
        with pytest.raises(ValueError, match=r"stage_kill_at.*step"):
            fault_plan_from_env(
                {"TPU_HPC_FAULTS": "stage_kill_at=3"}
            )
        with pytest.raises(
            ValueError, match=r"stage_straggler.*factor"
        ):
            fault_plan_from_env(
                {"TPU_HPC_FAULTS": "stage_straggler=1:0"}
            )

    def test_spmd_trainer_rejects_stage_faults(
        self, monkeypatch, tmp_path
    ):
        from tpu_hpc.config import TrainingConfig
        from tpu_hpc.train import Trainer

        monkeypatch.setenv("TPU_HPC_FAULTS", "stage_kill_at=0:1")
        mesh = build_mesh(
            MeshSpec(axes={"data": 1}), devices=jax.devices()[:1]
        )
        cfg = TrainingConfig(
            epochs=1, steps_per_epoch=1, global_batch_size=8,
            metrics_path="",
        )

        def forward(params, model_state, batch, step_rng):
            x, y = batch
            return jnp.mean((x @ params["w"] - y) ** 2), \
                model_state, {}

        with pytest.raises(ValueError, match="stage_kill_at"):
            Trainer(
                cfg, mesh, forward,
                {"w": jnp.zeros((4,), jnp.float32)},
            )

    def test_nonexistent_stage_rejected_at_build(self, data):
        with pytest.raises(ValueError, match="pass vacuously"):
            _build(data, fault_spec="stage_kill_at=9:1")
        with pytest.raises(ValueError, match="pass vacuously"):
            _build(data, fault_spec="stage_straggler=7:2.0")


# ---------------------------------------------------------------------
# slice loss: remap onto a surviving device, budget untouched
# ---------------------------------------------------------------------
class TestSliceRemap:
    def test_slice_loss_remaps_without_burning_budget(
        self, data, clean_run, fresh_bus
    ):
        """The stage whose slice goes away remaps onto a surviving
        device instead of dying through the restart path: zero budget
        burned, zero redispatches, loss stream + final params
        bit-identical to the no-fault run."""
        pipe = _build(
            data,
            fault_spec="slice_down_at_step=1,slice_up_at_step=2",
            events_path=fresh_bus,
        )
        last = CFG.n_stages - 1
        home = list(pipe._home_devices)
        res = pipe.train(clean_run["batches"])

        # The remap trail: off the lost slice onto stage 0's device,
        # then back home when the slice returns.
        assert [r["reason"] for r in res["stage_remaps"]] == [
            "slice-lost", "slice-restored",
        ]
        assert [r["stage"] for r in res["stage_remaps"]] == [
            last, last,
        ]
        assert res["stage_remaps"][0]["to_device"] == str(home[0])
        assert res["stage_remaps"][1]["to_device"] == str(home[last])
        assert pipe.devices[last] is home[last]

        # The headline: NOT a stage failure. No restart budget
        # charged, no recovery row, nothing replayed.
        assert res["stage_restarts"] == {}
        assert res["stage_rollbacks"] == {}
        assert res["recoveries"] == []
        assert res["redispatched"] == 0

        # Bit-identical continuity across both remaps.
        assert res["losses"] == clean_run["result"]["losses"]
        for s in range(CFG.n_stages):
            assert _tree_equal(
                pipe.stage_state(s), clean_run["states"][s]
            ), f"stage {s} final state diverged"

        # Evidence trail: stage_remap events (schema-valid), and NO
        # stage_down/stage_up pair -- this is not the crash path.
        from tpu_hpc.obs.schema import load_records, validate_file

        validate_file(fresh_bus)
        recs = load_records(fresh_bus)
        remaps = [r for r in recs if r["event"] == "stage_remap"]
        assert [r["reason"] for r in remaps] == [
            "slice-lost", "slice-restored",
        ]
        assert all(r["stage"] == last for r in remaps)
        assert not [r for r in recs if r["event"] == "stage_down"]
        faults = [r for r in recs if r["event"] == "fault"]
        assert [f["kind"] for f in faults] == [
            "slice_down", "slice_up",
        ]

    def test_unfired_slice_fault_fails_loudly(self, data):
        pipe = _build(data, fault_spec="slice_down_at_step=99")
        params, tokens, targets = data
        with pytest.raises(RuntimeError, match="never fired"):
            pipe.train([(tokens, targets)])

    def test_slice_up_without_down_rejected(self, data):
        with pytest.raises(
            ValueError, match="slice_up_at_step"
        ):
            _build(data, fault_spec="slice_up_at_step=1")


# ---------------------------------------------------------------------
# snapshot integrity
# ---------------------------------------------------------------------
def test_corrupt_snapshot_fails_restore_loudly(clean_run):
    import copy

    from tpu_hpc.ckpt.integrity import CkptIntegrityError

    pipe = clean_run["pipe"]
    snap = copy.deepcopy(pipe.snapshots[1])  # corrupt a COPY only
    leaf = next(iter(jax.tree.leaves(snap["state"])))
    leaf.flat[0] += 1.0  # one silent in-memory flip
    with pytest.raises(CkptIntegrityError, match="stage 1"):
        pipe.workers[1].load_state(snap)


# ---------------------------------------------------------------------
# obs: schema kinds, report section, regress directions
# ---------------------------------------------------------------------
class TestObs:
    def test_new_kinds_round_trip(self):
        from tpu_hpc.obs.schema import (
            SCHEMA_VERSION, SchemaError, validate_record,
        )

        base = {"schema_version": SCHEMA_VERSION, "time": 0.0}
        validate_record({
            **base, "event": "stage_down", "stage": 1,
            "reason": "crash", "step": 3, "microbatch": 2,
            "inflight": 4, "beat_age_s": 4.0,
        })
        validate_record({
            **base, "event": "stage_up", "stage": 1,
            "reason": "restart", "restore_step": 3, "mttr_s": 5.0,
            "compile_count": 3,
        })
        validate_record({
            **base, "event": "stage_redispatch", "stage": 1,
            "microbatch": 0, "step": 3,
        })
        validate_record({
            **base, "event": "pipeline_bubble", "step": 3,
            "bubble_fraction": 0.4, "makespan_s": 10.0,
            "straggler_stage": 2,
        })
        with pytest.raises(SchemaError, match="missing required"):
            validate_record({
                **base, "event": "stage_down", "stage": 1,
            })
        with pytest.raises(SchemaError, match="unknown fields"):
            validate_record({
                **base, "event": "stage_up", "stage": 1,
                "reason": "restart", "bogus": 1,
            })

    def test_report_and_regress_pipeline_section(self):
        # Record-driven (cheap): the runtime's real event stream is
        # already schema-validated field-by-field in TestStageKill;
        # this pins what the report/regress layers DO with it.
        from tpu_hpc.obs.regress import (
            lower_is_better, report_metrics,
        )
        from tpu_hpc.obs.report import build_report, format_report
        from tpu_hpc.obs.schema import stamp, validate_record

        recs = [stamp(r) for r in (
            {"event": "stage_down", "stage": 1, "reason": "crash",
             "step": 1, "microbatch": 3, "inflight": M},
            {"event": "stage_up", "stage": 1, "reason": "restart",
             "restore_step": 1, "mttr_s": 5.0, "compile_count": 3},
            *({"event": "stage_redispatch", "stage": 1,
               "microbatch": m, "step": 1} for m in range(M)),
            *({"event": "pipeline_bubble", "step": s,
               "bubble_fraction": 0.45, "makespan_s": 10.0}
              for s in range(3)),
        )]
        for r in recs:
            validate_record(r)
        rep = build_report(recs)
        pl = rep["pipeline"]
        assert pl["stage_down"] == 1
        assert pl["restarts"] == 1
        assert pl["redispatched"] == M
        assert pl["recovery_mttr_s"] == pytest.approx(5.0)
        assert pl["bubble_fraction"] == pytest.approx(0.45)
        assert "1" in pl["stages"]
        text = format_report(rep)
        assert "MPMD pipeline" in text
        assert "stage 1 timeline" in text

        flat = report_metrics(rep)
        for name in (
            "pipeline.stage_down", "pipeline.redispatched",
            "pipeline.bubble_fraction", "pipeline.recovery_mttr_s",
        ):
            assert name in flat
            assert lower_is_better(name), name


# ---------------------------------------------------------------------
# the banked artifact (the fleet/paged evidence discipline)
# ---------------------------------------------------------------------
def test_committed_mpmd_rows_pass_the_bank_gate(capsys):
    """The banked pp_mpmd_* rows (clean family + the chaos-kill
    family whose recovery MTTR / redispatch counts are gate-judged
    baselines) are schema-valid and pass ``regress --bank`` against
    the committed BENCH_HISTORY.jsonl high-water marks."""
    from tpu_hpc.obs.regress import main as regress_main
    from tpu_hpc.obs.schema import load_records

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    hist = os.path.join(repo, "BENCH_HISTORY.jsonl")
    rows = os.path.join(repo, "BENCH_MPMD_r15.jsonl")
    recs = load_records(rows, validate=True)
    metrics = {r["metric"]: r for r in recs}
    assert "pp_mpmd_tokens_per_s_per_chip" in metrics
    assert "pp_mpmd-chaos_tokens_per_s_per_chip" in metrics
    clean = metrics["pp_mpmd_tokens_per_s_per_chip"]
    chaos = metrics["pp_mpmd-chaos_tokens_per_s_per_chip"]
    for rec in (clean, chaos):
        for k in ("bubble_fraction", "recovery_mttr_s",
                  "recompiles", "redispatched"):
            assert k in rec, (rec["metric"], k)
    assert clean["recompiles"] == 0 and chaos["recompiles"] == 0
    # The chaos family's whole point: a real recovery happened and
    # its cost is the banked baseline.
    assert chaos["faults"] == "stage_kill_at"
    assert chaos["recovery_mttr_s"] > 0
    assert chaos["redispatched"] > 0
    assert clean["recovery_mttr_s"] == 0
    rc = regress_main([hist, rows, "--bank"])
    assert rc == 0, capsys.readouterr().out


# ---------------------------------------------------------------------
# bench CLI guards (the misplaced-flag discipline)
# ---------------------------------------------------------------------
class TestBenchCli:
    @pytest.fixture(scope="class")
    def bench(self):
        # Import by path: bench.py is a repo-root script (the
        # test_bench_cli idiom).
        import importlib.util
        import pathlib

        path = pathlib.Path(
            __file__
        ).resolve().parent.parent / "bench.py"
        spec = importlib.util.spec_from_file_location(
            "bench_cli_mpmd", path
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_mpmd_needs_pp_workload(self, bench):
        with pytest.raises(SystemExit):
            bench.main(["--workload", "llama", "--pp-runtime", "mpmd"])

    def test_mpmd_rejects_foreign_schedule_and_backward(self, bench):
        # The default --pp-schedule is 1f1b: an mpmd row labeled
        # 1f1b would misdescribe the gpipe-ordered dispatch.
        with pytest.raises(SystemExit):
            bench.main(["--workload", "pp", "--pp-runtime", "mpmd"])
        with pytest.raises(SystemExit):
            bench.main([
                "--workload", "pp", "--pp-runtime", "mpmd",
                "--pp-schedule", "gpipe", "--pp-backward", "stash",
            ])
