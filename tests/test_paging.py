"""The paged KV cache (serve/paging.py): page accounting, prefix
reuse, chunked prefill, and the compile discipline.

Four invariant families:
  * **page accounting** -- a property suite over random
    admit/evict/CoW sequences: the allocator never double-frees or
    leaks (scratch + free + referenced == num_blocks after every
    operation);
  * **token exactness** -- greedy decode through the paged cache is
    token-exact against the no-cache forward (llama2.apply_llama),
    with and without prefix hits, with chunked prefill, and after the
    prefix's original owner was evicted;
  * **compile discipline** -- block tables are DATA: a warmed paged
    engine serves a mix with slot churn, hits, chunking and pool
    pressure with ZERO new executables;
  * **budget discipline** -- submit() hard-rejects only the truly
    unservable (typed error naming prompt+max_new vs the page
    budget); transient pool exhaustion queues (block stalls) and
    drains.

All on the 8-device simulated mesh (KV heads shard over ``model``;
the page pool stays whole), fp32 compute so "token-exact" means
exact.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hpc.models import llama2
from tpu_hpc.runtime import MeshSpec, build_mesh
from tpu_hpc.serve import (
    BlockAllocator,
    BlockBudgetError,
    ContinuousBatcher,
    Engine,
    PagedConfig,
    PagedEngine,
    PrefixTrie,
    Request,
    ServeConfig,
    UnservableRequestError,
)
from tpu_hpc.serve.paging import SCRATCH_BLOCK, paged_kv_cache_pspec


TINY = llama2.LlamaConfig(
    dim=64, n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=128,
    multiple_of=16, max_seq_len=64, dtype=jnp.float32,
)
SERVE = ServeConfig(slots=4, max_seq_len=48, prefill_buckets=(8, 16))


@pytest.fixture(scope="module")
def serve_mesh(devices):
    return build_mesh(MeshSpec(axes={"data": 4, "model": 2}))


@pytest.fixture(scope="module")
def tiny_params():
    return llama2.init_llama(jax.random.key(0), TINY)


@pytest.fixture(scope="module")
def warm_paged(tiny_params, serve_mesh):
    """One chunked paged engine serves the whole module: chunked
    prefill generalizes plain prefill (stride >= prompt is one
    chunk), so every parity case runs through it."""
    engine = PagedEngine(
        tiny_params, TINY, SERVE, serve_mesh,
        PagedConfig(block_size=4, num_blocks=48, prefill_chunk=8),
    )
    engine.warmup()
    return engine


_ORACLE_LEN = 48


@pytest.fixture(scope="module")
def greedy_oracle(tiny_params):
    """Greedy continuation via the full NO-CACHE forward pass (the
    training model) -- the same fixed-padded-length oracle
    tests/test_serve.py pins the slab engine against."""
    fwd = jax.jit(
        lambda toks: llama2.apply_llama(tiny_params, toks, TINY)
    )

    def oracle(prompt, steps):
        toks = list(prompt)
        out = []
        for _ in range(steps):
            assert len(toks) <= _ORACLE_LEN
            padded = np.zeros((1, _ORACLE_LEN), np.int32)
            padded[0, :len(toks)] = toks
            logits = fwd(jnp.asarray(padded))
            t = int(jnp.argmax(logits[0, len(toks) - 1]))
            out.append(t)
            toks.append(t)
        return out

    return oracle


def _drain(engine, reqs):
    batcher = ContinuousBatcher(engine)
    return batcher, batcher.run(reqs)


# ---------------------------------------------------------------------
# Page accounting: the property suite
# ---------------------------------------------------------------------


class TestBlockAllocator:
    def test_random_admit_evict_cow_never_leaks(self):
        """The allocator invariant under a random operation stream:
        scratch + free + referenced == num_blocks after EVERY op, with
        a shadow model cross-checking refcounts."""
        rng = np.random.default_rng(7)
        alloc = BlockAllocator(32)
        held = []          # (blocks, extra_refs) per live "request"
        for _ in range(600):
            op = rng.integers(0, 4)
            if op == 0 and alloc.free_blocks:       # admit
                n = int(rng.integers(1, alloc.free_blocks + 1))
                held.append((alloc.alloc(n), []))
            elif op == 1 and held:                  # share (retain)
                blocks, extra = held[
                    int(rng.integers(0, len(held)))
                ]
                b = blocks[int(rng.integers(0, len(blocks)))]
                alloc.retain([b])
                extra.append(b)
            elif op == 2 and held:                  # evict (release)
                i = int(rng.integers(0, len(held)))
                blocks, extra = held.pop(i)
                alloc.release(blocks)
                alloc.release(extra)
            elif op == 3 and held:                  # copy-on-write
                i = int(rng.integers(0, len(held)))
                blocks, extra = held[i]
                j = int(rng.integers(0, len(blocks)))
                try:
                    new, copied = alloc.cow(blocks[j])
                except BlockBudgetError:
                    continue  # pool full: legal, nothing changed
                if copied:
                    blocks[j] = new
            alloc.check_invariant()
        for blocks, extra in held:
            alloc.release(blocks)
            alloc.release(extra)
        alloc.check_invariant()
        assert alloc.free_blocks == 31  # everything returned

    def test_double_free_and_foreign_retain_raise(self):
        alloc = BlockAllocator(8)
        blocks = alloc.alloc(2)
        alloc.release(blocks)
        with pytest.raises(ValueError, match="double free"):
            alloc.release([blocks[0]])
        with pytest.raises(ValueError, match="unreferenced"):
            alloc.retain([blocks[0]])
        alloc.check_invariant()

    def test_overdraw_raises_budget_error(self):
        alloc = BlockAllocator(4)  # 3 usable
        with pytest.raises(BlockBudgetError, match="free pages"):
            alloc.alloc(4)
        alloc.check_invariant()

    def test_cow_exclusive_is_noop_shared_copies(self):
        alloc = BlockAllocator(8)
        (b,) = alloc.alloc(1)
        assert alloc.cow(b) == (b, False)
        alloc.retain([b])
        new, copied = alloc.cow(b)
        assert copied and new != b
        assert alloc.refcount(b) == 1  # the other owner's ref
        alloc.release([b])
        alloc.release([new])
        alloc.check_invariant()


class TestPrefixTrie:
    def _setup(self):
        alloc = BlockAllocator(16)
        trie = PrefixTrie(block_size=2)
        return alloc, trie

    def test_match_insert_roundtrip_full_blocks_only(self):
        alloc, trie = self._setup()
        blocks = alloc.alloc(2)
        prompt = [1, 2, 3, 4, 5]  # 2 full blocks + 1 partial token
        assert trie.insert(prompt, blocks, alloc) == 2
        assert trie.match(prompt) == blocks
        assert trie.match([1, 2, 3, 4, 9, 9]) == blocks
        assert trie.match([1, 2, 9, 9]) == blocks[:1]
        assert trie.match([9, 9]) == []
        alloc.check_invariant()

    def test_pages_survive_owner_release(self):
        """The trie's reference keeps a finished request's prompt
        pages allocated -- the host-side half of
        prefix-hit-after-eviction."""
        alloc, trie = self._setup()
        blocks = alloc.alloc(2)
        trie.insert([1, 2, 3, 4], blocks, alloc)
        freed = alloc.release(blocks)     # the request evicts
        assert freed == 0                 # trie still holds both
        assert trie.match([1, 2, 3, 4]) == blocks
        alloc.check_invariant()

    def test_evict_is_lru_leaf_first_and_respects_live_refs(self):
        alloc, trie = self._setup()
        b1 = alloc.alloc(2)               # chain a: two blocks
        trie.insert([1, 2, 3, 4], b1, alloc)
        b2 = alloc.alloc(1)               # chain b: one block
        trie.insert([5, 6], b2, alloc)
        alloc.release(b1)
        alloc.release(b2)
        trie.match([1, 2, 3, 4])          # chain a is now MRU
        free_before = alloc.free_blocks
        assert trie.evict(alloc, 1) == 1
        assert alloc.free_blocks == free_before + 1
        assert trie.match([5, 6]) == []   # LRU leaf went first
        assert trie.match([1, 2, 3, 4]) == b1
        # A leaf whose page a live request shares is PROTECTED:
        # releasing it would free nothing toward the shortage, and
        # deleting the node would throw away a hot prefix (review
        # finding). The inner block stays reachable only through it,
        # so nothing evicts.
        alloc.retain([b1[1]])
        assert trie.evict(alloc, 2) == 0
        assert trie.match([1, 2, 3, 4]) == b1  # chain survived
        # Once the live request releases, the chain evicts leaf-first.
        alloc.release([b1[1]])
        assert trie.evict(alloc, 2) == 2
        assert trie.match([1, 2, 3, 4]) == []
        alloc.check_invariant()


# ---------------------------------------------------------------------
# Token exactness
# ---------------------------------------------------------------------


class TestPagedParity:
    def test_single_request_token_exact(self, warm_paged, greedy_oracle):
        rng = np.random.default_rng(0)
        prompt = rng.integers(0, TINY.vocab_size, size=11).tolist()
        _, got = _drain(
            warm_paged,
            [Request(rid="a", prompt=prompt, max_new_tokens=4)],
        )
        assert got["a"] == greedy_oracle(prompt, 4)

    def test_prompt_of_one_token(self, warm_paged, greedy_oracle):
        _, got = _drain(
            warm_paged, [Request(rid="a", prompt=[5], max_new_tokens=4)]
        )
        assert got["a"] == greedy_oracle([5], 4)

    def test_mixed_stream_with_churn_matches_solo_oracles(
        self, warm_paged, greedy_oracle
    ):
        """More requests than slots, staggered lengths (one crossing
        the chunk stride): every request still generates exactly its
        solo greedy continuation -- pages are isolated."""
        rng = np.random.default_rng(2)
        shapes = [(5, 3), (11, 6), (7, 1), (13, 4), (4, 5), (9, 2)]
        reqs = [
            Request(
                rid=f"r{i}",
                prompt=rng.integers(
                    0, TINY.vocab_size, size=plen
                ).tolist(),
                max_new_tokens=mnew,
            )
            for i, (plen, mnew) in enumerate(shapes)
        ]
        batcher, got = _drain(warm_paged, reqs)
        for r in reqs:
            assert got[r.rid] == greedy_oracle(
                r.prompt, r.max_new_tokens
            ), r.rid
        assert batcher.stats["admitted"] == len(shapes)
        assert batcher.stats["admitted"] > SERVE.slots

    def test_prefix_hit_is_token_exact_and_counted(
        self, warm_paged, greedy_oracle
    ):
        rng = np.random.default_rng(3)
        prompt = rng.integers(0, TINY.vocab_size, size=13).tolist()
        _, first = _drain(
            warm_paged,
            [Request(rid="cold", prompt=prompt, max_new_tokens=3)],
        )
        hits_before = warm_paged.paged_stats["prefix_hits"]
        _, again = _drain(
            warm_paged,
            [Request(rid="warm", prompt=prompt, max_new_tokens=3)],
        )
        want = greedy_oracle(prompt, 3)
        assert first["cold"] == want
        assert again["warm"] == want
        assert warm_paged.paged_stats["prefix_hits"] == hits_before + 1
        # 13 tokens = 3 full pages of 4; all three resolve physically.
        assert warm_paged.paged_stats["prefix_hit_blocks"] >= 3

    def test_prefix_hit_after_owner_eviction(
        self, tiny_params, serve_mesh, greedy_oracle
    ):
        """The trie's reference outlives the original request: a
        fresh engine serves request A, fully drains (A's pages
        released), then a same-prompt request B hits the cached
        prefix and still decodes token-exact."""
        engine = PagedEngine(
            tiny_params, TINY, SERVE, serve_mesh,
            PagedConfig(block_size=4, num_blocks=32),
        )
        warmed = engine.warmup()
        rng = np.random.default_rng(4)
        prompt = rng.integers(0, TINY.vocab_size, size=12).tolist()
        _drain(
            engine, [Request(rid="a", prompt=prompt, max_new_tokens=2)]
        )
        assert engine.allocator.used_blocks > 0  # trie holds pages
        _, got = _drain(
            engine, [Request(rid="b", prompt=prompt, max_new_tokens=4)]
        )
        assert got["b"] == greedy_oracle(prompt, 4)
        assert engine.paged_stats["prefix_hits"] == 1
        assert engine.compile_count == warmed

    def test_fully_cached_prompt_still_reprefills_last_page(
        self, warm_paged, greedy_oracle
    ):
        """A prompt whose EVERY page is cached must still forward at
        least one token (the first greedy token needs the last
        position's logits): the hit caps at all-but-one page."""
        rng = np.random.default_rng(5)
        prompt = rng.integers(0, TINY.vocab_size, size=8).tolist()
        _drain(
            warm_paged,
            [Request(rid="c1", prompt=prompt, max_new_tokens=2)],
        )
        _, got = _drain(
            warm_paged,
            [Request(rid="c2", prompt=prompt, max_new_tokens=2)],
        )
        assert got["c2"] == greedy_oracle(prompt, 2)

    def test_chunked_prefill_interleaves_with_decode(
        self, warm_paged, greedy_oracle
    ):
        """A long admission must not stall in-flight decode: while a
        16-token prompt prefills in 8-token chunks, the short request
        already decoding keeps receiving tokens every tick."""
        rng = np.random.default_rng(6)
        short = rng.integers(0, TINY.vocab_size, size=3).tolist()
        long = rng.integers(0, TINY.vocab_size, size=16).tolist()
        batcher = ContinuousBatcher(warm_paged)
        batcher.submit(Request(rid="s", prompt=short,
                               max_new_tokens=8))
        batcher.step()  # admit + one-chunk prefill + first decode
        tokens_before = len(batcher.results["s"])
        batcher.submit(Request(rid="l", prompt=long, max_new_tokens=3))
        batcher.step()  # long: chunk 1 of 2 -- short still decodes
        assert len(batcher.results["s"]) == tokens_before + 1
        assert "l" not in batcher.results  # still prefilling
        # Chunk 2 completes -> first token, and the slot joins the
        # same tick's decode (the slab admission-tick behavior).
        batcher.step()
        assert len(batcher.results["s"]) == tokens_before + 2
        assert len(batcher.results["l"]) == 2
        got = batcher.run()
        assert got["s"] == greedy_oracle(short, 8)
        assert got["l"] == greedy_oracle(long, 3)

    def test_cow_guard_copies_and_stays_exact(
        self, warm_paged, greedy_oracle
    ):
        """Force the copy-on-write guard: another owner appears on the
        decode write-target page mid-request; the engine must copy the
        page (not corrupt the other owner) and stay token-exact."""
        rng = np.random.default_rng(8)
        prompt = rng.integers(0, TINY.vocab_size, size=6).tolist()
        batcher = ContinuousBatcher(warm_paged)
        batcher.submit(Request(rid="w", prompt=prompt,
                               max_new_tokens=5))
        batcher.step()
        slot = next(
            i for i, s in enumerate(batcher.slots) if s.rid == "w"
        )
        st = warm_paged.slot_state(slot)
        pos = batcher.slots[slot].pos
        target = st.blocks[pos // 4]
        warm_paged.allocator.retain([target])  # simulated second owner
        before = warm_paged.paged_stats["cow_copies"]
        batcher.step()
        assert warm_paged.paged_stats["cow_copies"] == before + 1
        got = batcher.run()
        assert got["w"] == greedy_oracle(prompt, 5)
        warm_paged.allocator.release([target])
        warm_paged.allocator.check_invariant()

    def test_paged_matches_slab_engine_exactly(
        self, tiny_params, serve_mesh, warm_paged
    ):
        """The seeded paged-vs-slab parity smoke: one request mix
        through both engines, identical token streams."""
        slab = Engine(tiny_params, TINY, SERVE, serve_mesh)
        slab.warmup()
        rng = np.random.default_rng(9)
        reqs = [
            Request(
                rid=f"p{i}",
                prompt=rng.integers(
                    0, TINY.vocab_size, size=3 + (7 * i) % 14
                ).tolist(),
                max_new_tokens=1 + i % 4,
            )
            for i in range(8)
        ]
        _, got_slab = _drain(slab, reqs)
        _, got_paged = _drain(warm_paged, reqs)
        assert got_slab == got_paged


# ---------------------------------------------------------------------
# Compile + budget discipline
# ---------------------------------------------------------------------


class TestPagedCompileDiscipline:
    def test_zero_recompiles_across_mix(self, warm_paged):
        """Block tables, positions and the active mask are data: a mix
        with churn, hits, chunked prompts and CoW adds NO executables
        after warmup (buckets + decode + copy_block)."""
        warmed = warm_paged.compile_count
        assert warmed == len(SERVE.prefill_buckets) + 2
        rng = np.random.default_rng(10)
        reqs = [
            Request(
                rid=f"z{i}",
                prompt=rng.integers(
                    0, TINY.vocab_size, size=2 + (5 * i) % 15
                ).tolist(),
                max_new_tokens=1 + i % 5,
            )
            for i in range(9)
        ]
        _drain(warm_paged, reqs)
        assert warm_paged.compile_count == warmed

    def test_pool_layout_on_mesh(self, warm_paged, serve_mesh):
        spec = paged_kv_cache_pspec(serve_mesh, TINY.kv_heads)
        assert spec == jax.sharding.PartitionSpec(
            None, None, None, "model", None
        )
        assert warm_paged.ks.sharding.spec == spec
        assert warm_paged.ks.shape == (
            TINY.n_layers, 48, 4, TINY.kv_heads, TINY.head_dim
        )
        assert warm_paged.cache_bytes == (
            2 * TINY.n_layers * 48 * 4 * TINY.kv_heads
            * TINY.head_dim * 4
        )

    def test_config_validation(self, tiny_params, serve_mesh):
        with pytest.raises(ValueError, match="multiple of block_size"):
            PagedConfig(block_size=4, num_blocks=8, prefill_chunk=6)
        with pytest.raises(ValueError, match="num_blocks"):
            PagedConfig(block_size=4, num_blocks=1)
        with pytest.raises(ValueError, match="multiple of "):
            PagedEngine(
                tiny_params, TINY,
                ServeConfig(slots=2, max_seq_len=50,
                            prefill_buckets=(8,)),
                serve_mesh, PagedConfig(block_size=4, num_blocks=16),
            )
        with pytest.raises(ValueError, match="not multiples"):
            PagedEngine(
                tiny_params, TINY,
                ServeConfig(slots=2, max_seq_len=48,
                            prefill_buckets=(6,)),
                serve_mesh, PagedConfig(block_size=4, num_blocks=16),
            )
        with pytest.raises(ValueError, match="exceeds the largest"):
            PagedEngine(
                tiny_params, TINY, SERVE, serve_mesh,
                PagedConfig(block_size=4, num_blocks=16,
                            prefill_chunk=32),
            )


class TestBlockBudget:
    def test_unservable_submit_is_typed_and_names_numbers(
        self, tiny_params, serve_mesh
    ):
        """The fail-at-submit discipline, paged edition: only a
        request the pool can NEVER hold is rejected, with both sides
        of the inequality in the message."""
        engine = PagedEngine(
            tiny_params, TINY, SERVE, serve_mesh,
            PagedConfig(block_size=4, num_blocks=10),  # 9 usable
        )
        batcher = ContinuousBatcher(engine)
        with pytest.raises(
            UnservableRequestError,
            match=r"prompt 16 \+ max_new 32 needs 12 pages",
        ) as ei:
            batcher.submit(
                Request(rid="huge", prompt=[1] * 16,
                        max_new_tokens=32)
            )
        assert "9 usable pages" in str(ei.value)
        # The slab-era cache-capacity check still guards first.
        with pytest.raises(ValueError, match="cache capacity"):
            batcher.submit(
                Request(rid="cap", prompt=[1] * 16,
                        max_new_tokens=40)
            )

    def test_pool_pressure_stalls_then_drains(
        self, tiny_params, serve_mesh, greedy_oracle
    ):
        """Admissions the pool cannot seat QUEUE (block stalls) and
        admit as in-flight requests free pages -- token streams stay
        exact throughout, and the accounting invariant holds after
        the drain."""
        engine = PagedEngine(
            tiny_params, TINY, SERVE, serve_mesh,
            PagedConfig(block_size=4, num_blocks=14),  # 13 usable
        )
        warmed = engine.warmup()
        rng = np.random.default_rng(11)
        reqs = [
            Request(
                rid=f"q{i}",
                prompt=rng.integers(
                    0, TINY.vocab_size, size=12
                ).tolist(),
                max_new_tokens=8,  # 5 pages each; 2 fit at once
            )
            for i in range(5)
        ]
        batcher, got = _drain(engine, reqs)
        for r in reqs:
            assert got[r.rid] == greedy_oracle(
                r.prompt, r.max_new_tokens
            ), r.rid
        assert batcher.stats["block_stalls"] > 0
        assert engine.compile_count == warmed
        engine.allocator.check_invariant()

    def test_trie_eviction_reclaims_pages_for_admission(
        self, tiny_params, serve_mesh, greedy_oracle
    ):
        """A pool whose free pages all sit in the prefix trie must
        reclaim them (LRU leaves first) rather than stall forever."""
        engine = PagedEngine(
            tiny_params, TINY, SERVE, serve_mesh,
            PagedConfig(block_size=4, num_blocks=12),  # 11 usable
        )
        engine.warmup()
        rng = np.random.default_rng(12)
        a = rng.integers(0, TINY.vocab_size, size=12).tolist()
        _drain(engine, [Request(rid="a", prompt=a, max_new_tokens=4)])
        assert engine.allocator.used_blocks == 3  # trie: a's 3 pages
        b = rng.integers(0, TINY.vocab_size, size=16).tolist()
        _, got = _drain(
            engine, [Request(rid="b", prompt=b, max_new_tokens=20)]
        )  # needs 9 pages; only 8 free -> must evict a trie page
        assert got["b"] == greedy_oracle(b, 20)
        assert engine.paged_stats["trie_evictions"] > 0
        engine.allocator.check_invariant()


class TestKvBlockTelemetry:
    def test_kv_block_events_ride_the_schema(self):
        from tpu_hpc.obs.schema import validate_record, stamp

        for action in ("alloc", "free", "cow", "prefix_hit"):
            validate_record(stamp({
                "event": "kv_block", "action": action, "n": 2,
                "slot": 1,
            }))

    def test_summary_fields_flow_to_report_and_gate(self):
        """paged_summary -> serve_summary -> report serve section ->
        regress namespace, with hit rate higher-is-better and
        block_stalls lower-is-better."""
        from tpu_hpc.obs.regress import lower_is_better
        from tpu_hpc.obs.report import _serve

        assert not lower_is_better("serve.prefix_hit_rate")
        assert lower_is_better("serve.block_stalls")
        rec = {
            "event": "serve_summary", "requests": 2, "tokens": 4,
            "tokens_per_s": 1.0, "kv_layout": "paged",
            "kv_block_size": 4, "kv_blocks": 16,
            "kv_blocks_free_min": 3, "prefix_hit_rate": 0.5,
            "prefix_hits": 1, "prefix_hit_blocks": 3,
            "prefill_chunks": 4,
            "batcher": {"block_stalls": 2},
        }
        out = _serve([rec])
        assert out["prefix_hit_rate"] == 0.5
        assert out["block_stalls"] == 2
        assert out["kv_layout"] == "paged"

    def test_block_occupancy_excludes_trie_parked_pages(
        self, tiny_params, serve_mesh
    ):
        """Occupancy is the admission policy's shed input: it must
        count pages held by LIVE requests only -- the trie's parked
        pages are a reclaimable cache, and counting them would read
        as permanent saturation once the trie warms (review
        finding)."""
        engine = PagedEngine(
            tiny_params, TINY, SERVE, serve_mesh,
            PagedConfig(block_size=4, num_blocks=16),
        )
        engine.warmup()
        rng = np.random.default_rng(14)
        prompt = rng.integers(0, TINY.vocab_size, size=12).tolist()
        batcher = ContinuousBatcher(engine)
        batcher.submit(
            Request(rid="a", prompt=prompt, max_new_tokens=6)
        )
        batcher.step()  # request still mid-flight: pages are live
        assert engine.block_occupancy > 0.0
        batcher.run()
        # Trie still holds the prompt's pages...
        assert engine.allocator.used_blocks > 0
        # ...but nothing live references the pool.
        assert engine.block_occupancy == 0.0
        assert batcher.occupancy == 0.0

    def test_scratch_block_reserved(self):
        alloc = BlockAllocator(8)
        taken = alloc.alloc(7)
        assert SCRATCH_BLOCK not in taken
        with pytest.raises(BlockBudgetError):
            alloc.alloc(1)
        alloc.release(taken)
        alloc.check_invariant()


class TestPagedDisagg:
    def test_paged_disagg_parity_hits_and_compile_pin(
        self, tiny_params, greedy_oracle
    ):
        """The cross-tier hop ships block tables + referenced pages:
        token parity (including a prompt LONGER than the largest
        bucket, which only chunked paged mode can serve), a
        prefill-tier prefix hit, and zero steady-state recompiles."""
        from tpu_hpc.serve.disagg import (
            DisaggEngine,
            split_serving_meshes,
        )

        pm, dm = split_serving_meshes(8, TINY)
        scfg = ServeConfig(
            slots=2, max_seq_len=48, prefill_buckets=(8, 16)
        )
        engine = DisaggEngine(
            tiny_params, TINY, scfg, pm, dm,
            paged=PagedConfig(block_size=4, num_blocks=32,
                              prefill_chunk=8),
        )
        warmed = engine.warmup()
        rng = np.random.default_rng(13)
        shapes = [(5, 3), (11, 4), (18, 2)]  # 18 > largest bucket
        reqs = [
            Request(
                rid=f"d{i}",
                prompt=rng.integers(
                    0, TINY.vocab_size, size=p
                ).tolist(),
                max_new_tokens=m,
            )
            for i, (p, m) in enumerate(shapes)
        ]
        batcher, got = _drain(engine, reqs)
        for r in reqs:
            assert got[r.rid] == greedy_oracle(
                r.prompt, r.max_new_tokens
            ), r.rid
        assert engine.transfer_stats["kv_transfers"] > 0
        assert engine.compile_count == warmed
        # Prefill-tier prefix hit on a repeat, still exact.
        _, again = _drain(
            engine,
            [Request(rid="hit", prompt=reqs[0].prompt,
                     max_new_tokens=3)],
        )
        assert again["hit"] == greedy_oracle(reqs[0].prompt, 3)
        assert engine.paged_summary()["prefix_hits"] >= 1
        assert engine.compile_count == warmed


class TestPagedReplayCLI:
    def test_paged_flags_end_to_end(self, capsys):
        from tpu_hpc.serve import server

        rc = server.main([
            "--requests", "4", "--max-new", "2", "--slots", "2",
            "--buckets", "8", "--prompt-lens", "3,6", "--vocab", "64",
            "--paged", "--kv-block-size", "4",
        ])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out.splitlines()[-1])
        assert summary["kv_layout"] == "paged"
        assert summary["recompiles"] == 0
        assert summary["kv_block_size"] == 4
        # bucket(8) + decode + copy_block
        assert summary["compiled_programs"] == 3

    def test_misplaced_paged_flags_are_cli_errors(self):
        from tpu_hpc.serve import server

        for flags in (
            ["--kv-block-size", "4"],
            ["--kv-blocks", "16"],
            ["--prefill-chunk", "8"],
        ):
            with pytest.raises(SystemExit):
                server.main(["--requests", "1", *flags])
        # Misaligned sizing fails at parse, not post-bring-up.
        with pytest.raises(SystemExit):
            server.main([
                "--paged", "--kv-block-size", "5", "--buckets", "8",
            ])
