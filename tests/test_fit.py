"""The 7B north-star must demonstrably shard and fit (VERDICT round-1
missing item #2): exact static accounting at the true 7B config, and the
real train step must AOT-lower + XLA-compile under the hybrid plan."""
import jax
import pytest

from tpu_hpc.checks import fit
from tpu_hpc.models import llama2
from tpu_hpc.parallel import hybrid, tp


GIB = 1024 ** 3


@pytest.fixture(scope="module")
def full_7b():
    cfg = llama2.LlamaConfig(max_seq_len=4096, remat=True)
    return fit.analyze(
        cfg=cfg, dp=4, tp_size=8, global_batch=8, seq_len=4096,
        do_compile=False,
    )


def test_7b_param_count(full_7b):
    # The true 7B defaults (reference llama2_model.py:13-16).
    assert 6.5e9 < full_7b.n_params < 7.0e9


def test_7b_static_accounting_exact(full_7b):
    # fp32 params + grads + 2x Adam moments = 16 bytes/param, sharded
    # over 32 chips; per-chip padding can only round up slightly.
    ideal = 16 * full_7b.n_params / 32
    assert ideal <= full_7b.static_bytes < ideal * 1.05


def test_7b_fits_v4_hbm(full_7b):
    assert full_7b.fits
    # And with real headroom, not by a whisker.
    assert full_7b.total_bytes < 0.5 * 32 * GIB


def test_7b_every_large_param_is_sharded():
    """No big tensor may stay replicated under the hybrid plan."""
    cfg = llama2.LlamaConfig(max_seq_len=4096, remat=True)
    abstract = jax.eval_shape(
        lambda: llama2.init_llama(jax.random.key(0), cfg)
    )
    specs = hybrid.hybrid_pspecs(abstract, tp.llama_rules(), data_size=4)
    import numpy as np

    for leaf, spec in zip(
        jax.tree.leaves(abstract),
        jax.tree.leaves(
            specs, is_leaf=lambda x: hasattr(x, "index")
        ),
    ):
        if int(np.prod(leaf.shape)) >= 100_000:
            assert any(e is not None for e in spec), (
                f"large param {leaf.shape} left replicated"
            )


def test_hybrid_step_compiles_on_mesh(mesh_2d):
    """The real Trainer step AOT-compiles under the hybrid plan on the
    (data=2, model=4) sim mesh at a reduced-depth 7B-wide config, and
    the partitioned module contains collectives (GSPMD accepted the
    plan end-to-end)."""
    cfg = llama2.LlamaConfig(
        n_layers=2, max_seq_len=512, remat=True
    )
    r = fit.analyze(
        cfg=cfg, dp=2, tp_size=4, global_batch=4, seq_len=512,
        do_compile=True,
    )
    assert r.compiled
    assert r.collectives["all-gather"] > 0
    assert (
        r.collectives["all-reduce"] + r.collectives["reduce-scatter"] > 0
    )
    # XLA's own per-chip argument accounting must agree with the
    # analytic static accounting (params + opt state; batch is noise).
    analytic = r.param_bytes + r.opt_bytes
    assert abs(r.xla_argument_bytes - analytic) / analytic < 0.05


def test_model_presets():
    """Llama-2 family shapes land on the public parameter counts."""
    import numpy as np

    def count(name):
        cfg = llama2.PRESETS[name]
        abstract = jax.eval_shape(
            lambda: llama2.init_llama(jax.random.key(0), cfg)
        )
        return sum(
            int(np.prod(l.shape)) for l in jax.tree.leaves(abstract)
        )

    assert abs(count("7b") / 6.74e9 - 1) < 0.01
    assert abs(count("13b") / 13.0e9 - 1) < 0.01
    # 70B: GQA shape (8 KV heads), ffn_hidden 28672.
    assert llama2.PRESETS["70b"].ffn_hidden == 28672
    assert abs(count("70b") / 69.0e9 - 1) < 0.01


def test_sizing_table_rows_fit():
    """Every published ladder row must actually fit -- the docs table
    is generated from this exact computation."""
    table = fit.sizing_table()
    assert "NO" not in table
    assert table.count("| yes |") == len(fit._TABLE_ROWS)


def test_sizing_table_catches_overflow():
    """The analyzer is not a rubber stamp: 70B on 8 chips must not fit."""
    import dataclasses as dc

    cfg = dc.replace(llama2.PRESETS["70b"], max_seq_len=4096)
    r = fit.analyze(
        cfg=cfg, dp=2, tp_size=4, global_batch=16, seq_len=4096,
        do_compile=False,
    )
    assert not r.fits


def test_count_collectives_backend_spellings():
    """The counter must see all three backend spellings: plain ops,
    the TPU async start/done pairs, and the v5e fused reduce-scatter
    (a kCustom fusion calls=%all-reduce-scatter) -- counting only
    'reduce-scatter(' reported 0 on real TPU lowerings."""
    hlo = "\n".join([
        '%ag = f32[8] all-gather(%x), dimensions={0}',
        '%ags = f32[8] all-gather-start(%x)',
        '%ar = f32[8] all-reduce(%x)',
        # Two fused reduce-scatters: computation def + body all-reduce
        # + kCustom call site each. The body all-reduces implement the
        # reduce-scatters and must not inflate the all-reduce row.
        '%all-reduce-scatter (input: f32[8]) -> f32[2] {',
        '  %body-ar = f32[8] all-reduce(%input)',
        '}',
        '%all-reduce-scatter.1 (input: f32[8]) -> f32[2] {',
        '  %body-ar.1 = f32[8] all-reduce(%input)',
        '}',
        '%rs = f32[2] reduce-scatter(%x)',
        '%f = f32[2] fusion(%x), kind=kCustom, calls=%all-reduce-scatter',
        '%f2 = f32[2] fusion(%y), kind=kCustom, calls=%all-reduce-scatter.1',
        '%cp = f32[8] collective-permute-start(%x)',
    ])
    c = fit._count_collectives(hlo)
    assert c["all-gather"] == 2          # plain + async start
    assert c["all-reduce"] == 1          # top-level only; bodies excluded
    assert c["reduce-scatter"] == 3      # plain + 2 fused
    assert c["collective-permute"] == 1  # async start
    assert c["all-to-all"] == 0


@pytest.mark.slow
def test_topology_compile_emits_reduce_scatter():
    """AOT compile of the real step against a virtual TPU topology
    (libtpu, no chips): the real lowering must evidence the
    reduce-scatter form the FSDP plan promises -- the CPU-sim
    backend legalizes it away, which is exactly why this path exists.
    Slow (~2 min: real TPU compiler on 1 core); skipped where libtpu
    or the topologies API is unavailable (e.g. bare CI runners)."""
    pytest.importorskip("libtpu")
    from jax.experimental import topologies

    try:
        topologies.get_topology_desc(
            platform="tpu", topology_name="v5e:2x4"
        )
    except Exception as e:  # pragma: no cover
        pytest.skip(f"topology descriptor unavailable: {e}")
    cfg = llama2.LlamaConfig(
        n_layers=2, max_seq_len=512, remat=True
    )
    r = fit.analyze(
        cfg=cfg, dp=4, tp_size=2, global_batch=8, seq_len=512,
        do_compile=True, tpu_topology="v5e:2x4",
    )
    assert r.compiled
    assert r.compile_backend == "tpu-topology:v5e:2x4"
    assert r.collectives["reduce-scatter"] > 0, r.collectives
    assert r.xla_temp_bytes > 0


class TestCPLayout:
    """--layout cp / --cp: the long-context fit model (FSDP over data
    x ring attention over context)."""

    def test_static_shards_over_data_only(self):
        cfg = llama2.LlamaConfig(n_layers=2, max_seq_len=8192, remat=True)
        r = fit.analyze(
            cfg=cfg, dp=2, tp_size=4, global_batch=4, seq_len=8192,
            do_compile=False, layout="cp",
        )
        assert r.layout == "cp"
        # Params shard over dp=2 only (no TP axis): per-chip statics
        # are half the fp32 totals, not an eighth.
        full = 16 * r.n_params  # params+grads+mu+nu fp32 bytes
        assert full / 2 * 0.95 < r.static_bytes < full / 2 * 1.10
        assert set(r.act_bytes) >= {
            "residual_checkpoints", "block_recompute_live",
            "lm_head_and_loss",
        }

    def test_activations_scale_inversely_with_ring(self):
        cfg = llama2.LlamaConfig(n_layers=2, max_seq_len=8192, remat=True)

        def act_total(cp):
            r = fit.analyze(
                cfg=cfg, dp=2, tp_size=cp, global_batch=4,
                seq_len=8192, do_compile=False, layout="cp",
            )
            return sum(r.act_bytes.values())

        # Doubling the ring roughly halves per-chip activations (the
        # whole point of context parallelism).
        assert act_total(8) < 0.6 * act_total(4)

    def test_indivisible_seq_rejected(self):
        cfg = llama2.LlamaConfig(n_layers=2, max_seq_len=100, remat=True)
        with pytest.raises(ValueError, match="divisible"):
            fit.analyze(
                cfg=cfg, dp=2, tp_size=3, global_batch=4, seq_len=100,
                do_compile=False, layout="cp",
            )

    def test_cp_step_compiles_on_sim_mesh(self, mesh_2d):
        """The real Trainer step under the CP layout compiles end-to-end
        on the sim mesh and shows the ring (collective-permute) +
        FSDP (all-gather) signature."""
        cfg = llama2.LlamaConfig(n_layers=2, max_seq_len=512, remat=True)
        r = fit.analyze(
            cfg=cfg, dp=2, tp_size=4, global_batch=4, seq_len=512,
            do_compile=True, layout="cp",
        )
        assert r.compiled
        assert r.collectives["collective-permute"] > 0, r.collectives
        assert r.collectives["all-gather"] > 0, r.collectives


class TestPPLayout:
    """Analytic pipeline fit: stage-sharded statics, 1F1B activations."""

    def test_statics_shard_over_stages_only(self):
        from tpu_hpc.models import llama2 as l2

        cfg = l2.PRESETS["7b"]
        r4 = fit.analyze(
            cfg, dp=2, tp_size=4, global_batch=64, seq_len=4096,
            do_compile=False, grad_accum=8, layout="pp",
        )
        r8 = fit.analyze(
            cfg, dp=2, tp_size=8, global_batch=64, seq_len=4096,
            do_compile=False, grad_accum=8, layout="pp",
        )
        # Twice the stages -> roughly half the per-chip layer params
        # (the worst stage keeps its embed/head share, so not exactly).
        assert r8.param_bytes < r4.param_bytes
        parts = l2.count_params_by_part(cfg)
        expect4 = (
            parts["per_layer"] * (cfg.n_layers // 4)
            + max(parts["embed"], parts["head"]) + parts["other"]
        ) * 4
        assert r4.param_bytes == expect4
        # dp does NOT shard pp statics (stage_pspecs replicates them).
        r_dp8 = fit.analyze(
            cfg, dp=8, tp_size=4, global_batch=64, seq_len=4096,
            do_compile=False, grad_accum=8, layout="pp",
        )
        assert r_dp8.param_bytes == r4.param_bytes

    def test_more_microbatches_shrink_activations(self):
        from tpu_hpc.models import llama2 as l2

        cfg = l2.PRESETS["7b"]
        r8 = fit.analyze(
            cfg, dp=1, tp_size=4, global_batch=64, seq_len=4096,
            do_compile=False, grad_accum=8, layout="pp",
        )
        r32 = fit.analyze(
            cfg, dp=1, tp_size=4, global_batch=64, seq_len=4096,
            do_compile=False, grad_accum=32, layout="pp",
        )
        # Past M >= S the in-flight count saturates at S while the
        # microbatch shrinks -> strictly less activation memory.
        assert sum(r32.act_bytes.values()) < sum(r8.act_bytes.values())

    def test_compile_pass_runs_real_stage_program(self):
        """layout='pp' + do_compile AOT-compiles the real stage-split
        Llama 1F1B step (models/llama_pp.py) -- the collective table
        must show the pipeline's ring ppermutes."""
        from tpu_hpc.models import llama2 as l2

        cfg = l2.LlamaConfig(
            dim=64, n_layers=4, n_heads=4, vocab_size=97,
            multiple_of=32, max_seq_len=32,
        )
        r = fit.analyze(
            cfg, dp=2, tp_size=4, global_batch=8,
            seq_len=32, do_compile=True, grad_accum=4,
            layout="pp",
        )
        assert r.compiled
        assert r.collectives.get("collective-permute", 0) >= 2
        # DP grad reduction across the data axis must appear too.
        assert r.collectives.get("all-reduce", 0) >= 1

    def test_layers_divisibility_enforced(self):
        from tpu_hpc.models import llama2 as l2

        with pytest.raises(ValueError, match="divisible by"):
            fit.analyze(
                l2.PRESETS["7b"], dp=1, tp_size=5, global_batch=10,
                seq_len=4096, do_compile=False, grad_accum=5,
                layout="pp",
            )

    def test_stash_backward_costs_memory(self):
        from tpu_hpc.models import llama2 as l2

        cfg = l2.PRESETS["7b"]
        remat = fit.analyze(
            cfg, dp=2, tp_size=4, global_batch=64, seq_len=4096,
            do_compile=False, grad_accum=8, layout="pp",
        )
        stash = fit.analyze(
            cfg, dp=2, tp_size=4, global_batch=64, seq_len=4096,
            do_compile=False, grad_accum=8, layout="pp",
            pp_backward="stash",
        )
        # Stash buffers full residuals (incl. a bf16 param copy per
        # in-flight microbatch) instead of input checkpoints only.
        assert sum(stash.act_bytes.values()) > \
            sum(remat.act_bytes.values())
        assert stash.static_bytes == remat.static_bytes


class TestKVCacheTerm:
    """Memory fit with a co-resident decode config: the serving
    engine's preallocated KV cache is real HBM the training-only
    analysis used to ignore."""

    def test_formula_exact(self):
        cfg = llama2.LlamaConfig(
            dim=64, n_layers=3, n_heads=4, n_kv_heads=2,
            vocab_size=128, multiple_of=16, max_seq_len=32,
        )
        # slots x seq x layers x kv_heads x head_dim x 2 (K,V) x bf16
        want = 8 * 32 * 3 * 2 * 16 * 2 * 2
        assert fit.kv_cache_bytes(cfg, 8) == want
        # explicit capacity overrides the model's max_seq_len
        assert fit.kv_cache_bytes(cfg, 8, max_seq_len=16) == want // 2
        # fp32 cache doubles it
        assert fit.kv_cache_bytes(
            cfg, 8, cache_dtype="float32"
        ) == 2 * want

    @pytest.fixture(scope="class")
    def with_kv(self, full_7b):
        # Same mesh/batch as the module's full_7b fixture, plus a
        # 64-slot decode config -- the pair the deltas below compare.
        return fit.analyze(
            cfg=full_7b.cfg, dp=4, tp_size=8, global_batch=8,
            seq_len=4096, do_compile=False, kv_slots=64,
        )

    def test_analyze_adds_sharded_term_to_total(
        self, full_7b, with_kv
    ):
        assert full_7b.kv_cache_bytes == 0
        full = fit.kv_cache_bytes(full_7b.cfg, 64)
        # 7B MHA: 32 kv heads shard over tp=8, 64 slots over dp=4.
        assert with_kv.kv_cache_bytes == full // (4 * 8)
        assert with_kv.total_bytes == \
            full_7b.total_bytes + with_kv.kv_cache_bytes
        assert with_kv.to_json()["kv_cache_bytes"] == \
            with_kv.kv_cache_bytes

    def test_indivisible_slots_stay_replicated(self):
        cfg = llama2.LlamaConfig(
            dim=64, n_layers=2, n_heads=8, n_kv_heads=8,
            vocab_size=256, multiple_of=16, max_seq_len=64,
        )
        r = fit.analyze(
            cfg, dp=4, tp_size=8, global_batch=8, seq_len=64,
            do_compile=False, kv_slots=6,  # 6 % dp(4) != 0
        )
        # slots don't divide dp -> only the kv-head split applies.
        assert r.kv_cache_bytes == fit.kv_cache_bytes(cfg, 6) // 8

    def test_markdown_reports_the_row(self, full_7b, with_kv):
        md = fit.to_markdown(with_kv)
        assert "KV cache (decode, 64 slots)" in md
        assert "KV cache" not in fit.to_markdown(full_7b)


class TestPagedKVTerm:
    """The paged-pool HBM model (--kv-blocks/--kv-block-size) and the
    slab-vs-paged fragmentation-headroom comparison."""

    def test_formula_exact(self):
        cfg = llama2.LlamaConfig(
            dim=64, n_layers=3, n_heads=4, n_kv_heads=2,
            vocab_size=128, multiple_of=16, max_seq_len=32,
        )
        # blocks x block_size x layers x kv_heads x head_dim x 2 x bf16
        want = 100 * 16 * 3 * 2 * 16 * 2 * 2
        assert fit.kv_paged_bytes(cfg, 100, 16) == want
        assert fit.kv_paged_bytes(
            cfg, 100, 16, cache_dtype="float32"
        ) == 2 * want

    @pytest.fixture(scope="class")
    def with_paged(self, full_7b):
        # Slab 64 slots x 4096 worst-case vs a pool provisioned for
        # the tokens the mix actually occupies (half the worst case).
        return fit.analyze(
            cfg=full_7b.cfg, dp=4, tp_size=8, global_batch=8,
            seq_len=4096, do_compile=False, kv_slots=64,
            kv_blocks=8192, kv_block_size=16,
        )

    def test_paged_term_replaces_slab_in_total(
        self, full_7b, with_paged
    ):
        full = fit.kv_paged_bytes(full_7b.cfg, 8192, 16)
        # KV heads shard over tp=8; the pool replicates over data.
        assert with_paged.kv_block_bytes == full // 8
        assert with_paged.total_bytes == \
            full_7b.total_bytes + with_paged.kv_block_bytes
        d = with_paged.to_json()
        assert d["kv_block_bytes"] == with_paged.kv_block_bytes
        assert d["kv_blocks"] == 8192
        assert d["kv_block_size"] == 16

    def test_markdown_headroom_line(self, with_paged):
        md = fit.to_markdown(with_paged)
        assert "KV cache (paged, 8192 pages x 16 tok)" in md
        assert "Fragmentation headroom (per data replica" in md
        # Per REPLICA (the only sharding-honest comparison): the
        # slab's share is 64/4 slots x 4096 = 65536 tokens; the pool
        # is 8192 x 16 = 131072 tokens -- over-provisioned 2x, and
        # the line must say so rather than flatter the config.
        assert "MORE** than the slab share" in md

    def test_cli_flags_reach_analyze(self, capsys):
        rc = fit.main([
            "--no-compile", "--kv-slots", "64",
            "--kv-blocks", "4096", "--kv-block-size", "16", "--json",
        ])
        import json as _json

        out = _json.loads(capsys.readouterr().out)
        assert out["kv_blocks"] == 4096
        assert out["kv_block_bytes"] > 0
        assert rc in (0, 1)


class TestQuantizedKVTerm:
    """The int8 page-storage budget (--kv-quant int8,
    tpu_hpc.kernels.paged_attention): 1-byte pages + per-page fp32
    scales, about half the bf16 pool -- and the report must print
    the capacity multiplier the flag exists for."""

    def test_formula_exact(self):
        cfg = llama2.LlamaConfig(
            dim=64, n_layers=3, n_heads=4, n_kv_heads=2,
            vocab_size=128, multiple_of=16, max_seq_len=32,
        )
        # pages at 1 byte/elem + one fp32 scale per page per layer
        # for K and V each.
        want = 100 * 16 * 3 * 2 * 16 * 2 * 1 + 100 * 3 * 2 * 4
        assert fit.kv_paged_bytes(cfg, 100, 16, kv_quant="int8") == want
        # Just under half the bf16 pool (the scale side array is the
        # difference from exactly half).
        bf16 = fit.kv_paged_bytes(cfg, 100, 16)
        assert want < bf16 * 0.51

    @pytest.fixture(scope="class")
    def with_quant(self, full_7b):
        return fit.analyze(
            cfg=full_7b.cfg, dp=4, tp_size=8, global_batch=8,
            seq_len=4096, do_compile=False,
            kv_blocks=8192, kv_block_size=16, kv_quant="int8",
        )

    def test_halves_the_pool_term(self, full_7b, with_quant):
        full = fit.kv_paged_bytes(
            full_7b.cfg, 8192, 16, kv_quant="int8"
        )
        assert with_quant.kv_block_bytes == -(-full // 8)
        bf16 = fit.analyze(
            cfg=full_7b.cfg, dp=4, tp_size=8, global_batch=8,
            seq_len=4096, do_compile=False,
            kv_blocks=8192, kv_block_size=16,
        )
        assert with_quant.kv_block_bytes < bf16.kv_block_bytes * 0.51
        assert with_quant.to_json()["kv_quant"] == "int8"

    def test_draft_mirror_quantizes_too(self, full_7b):
        from tpu_hpc.serve.spec import default_draft_config

        draft = default_draft_config(full_7b.cfg)
        r = fit.analyze(
            cfg=full_7b.cfg, dp=4, tp_size=8, global_batch=8,
            seq_len=4096, do_compile=False,
            kv_blocks=8192, kv_block_size=16, kv_quant="int8",
            draft_cfg=draft,
        )
        assert r.draft_kv_block_bytes == -(-fit.kv_paged_bytes(
            draft, 8192, 16, kv_quant="int8"
        ) // 8)

    def test_markdown_capacity_multiplier(self, with_quant):
        md = fit.to_markdown(with_quant)
        assert "int8 + fp32 scales" in md
        assert "Quantized KV capacity" in md
        assert "2.0x the resident context at equal HBM" in md

    def test_quant_requires_paged_pool(self, full_7b):
        with pytest.raises(ValueError, match="kv_blocks"):
            fit.analyze(
                cfg=full_7b.cfg, dp=4, tp_size=8, global_batch=8,
                seq_len=4096, do_compile=False, kv_quant="int8",
            )
        with pytest.raises(ValueError, match="kv_quant"):
            fit.analyze(
                cfg=full_7b.cfg, dp=4, tp_size=8, global_batch=8,
                seq_len=4096, do_compile=False,
                kv_blocks=64, kv_quant="fp8",
            )

    def test_cli_requires_kv_blocks(self, capsys):
        with pytest.raises(SystemExit):
            fit.main(["--no-compile", "--kv-quant", "int8"])
        assert "--kv-blocks" in capsys.readouterr().err

    def test_cli_flag_reaches_analyze(self, capsys):
        rc = fit.main([
            "--no-compile", "--kv-blocks", "4096",
            "--kv-quant", "int8", "--json",
        ])
        import json as _json

        out = _json.loads(capsys.readouterr().out)
        assert out["kv_quant"] == "int8"
        assert rc in (0, 1)


class TestSpecDraftTerm:
    """The speculative-draft HBM budget (serve/spec.py via
    --spec-draft): draft params + the mirrored paged pool must land
    in the total, and an oversized draft must flip the verdict --
    fail the fit report, not OOM at serving bring-up."""

    def test_draft_terms_add_to_total(self, full_7b):
        from tpu_hpc.serve.spec import default_draft_config

        draft = default_draft_config(full_7b.cfg)
        r = fit.analyze(
            cfg=full_7b.cfg, dp=4, tp_size=8, global_batch=8,
            seq_len=4096, do_compile=False,
            kv_blocks=8192, kv_block_size=16, draft_cfg=draft,
        )
        base = fit.analyze(
            cfg=full_7b.cfg, dp=4, tp_size=8, global_batch=8,
            seq_len=4096, do_compile=False,
            kv_blocks=8192, kv_block_size=16,
        )
        assert r.draft_n_params == llama2.count_params(draft)
        # fp32 serving params, TP-sharded over model=8.
        assert r.draft_param_bytes == -(-r.draft_n_params * 4 // 8)
        assert r.draft_kv_block_bytes == \
            fit.kv_paged_bytes(draft, 8192, 16) // 8
        assert r.total_bytes == (
            base.total_bytes + r.draft_param_bytes
            + r.draft_kv_block_bytes
        )
        md = fit.to_markdown(r)
        assert "spec draft params" in md
        assert "spec draft KV pool (mirrored 8192 pages)" in md

    def test_oversized_draft_fails_the_verdict(self, full_7b):
        # A "draft" as big as the target on an HBM budget that held
        # exactly the target: must flip to DOES NOT FIT.
        fits_alone = fit.analyze(
            cfg=full_7b.cfg, dp=4, tp_size=8, global_batch=8,
            seq_len=4096, do_compile=False,
            kv_blocks=4096, kv_block_size=16,
        )
        gib = fits_alone.total_bytes / (1 << 30) + 0.5
        r = fit.analyze(
            cfg=full_7b.cfg, dp=4, tp_size=8, global_batch=8,
            seq_len=4096, hbm_gib=gib, do_compile=False,
            kv_blocks=4096, kv_block_size=16,
            draft_cfg=full_7b.cfg,
        )
        assert not r.fits

    def test_draft_requires_paged_pool(self, full_7b):
        with pytest.raises(ValueError, match="kv_blocks"):
            fit.analyze(
                cfg=full_7b.cfg, dp=4, tp_size=8, global_batch=8,
                seq_len=4096, do_compile=False,
                draft_cfg=full_7b.cfg,
            )


class TestHostTierTerm:
    """The host-DRAM KV page-tier budget (serve/tier.py via
    --kv-host-tier): host bytes are DRAM, never HBM -- they must be
    reported for sizing without moving the fits verdict, and the
    markdown must carry the resident-sessions multiplier the tier
    exists to buy."""

    @pytest.fixture(scope="class")
    def with_tier(self, full_7b):
        return fit.analyze(
            cfg=full_7b.cfg, dp=4, tp_size=8, global_batch=8,
            seq_len=4096, do_compile=False,
            kv_blocks=1024, kv_block_size=16, kv_host_blocks=9216,
        )

    def test_host_bytes_never_in_hbm_total(self, full_7b, with_tier):
        base = fit.analyze(
            cfg=full_7b.cfg, dp=4, tp_size=8, global_batch=8,
            seq_len=4096, do_compile=False,
            kv_blocks=1024, kv_block_size=16,
        )
        # Full-width per host (device_get assembles the sharded rows
        # before the numpy store): no tp/dp division.
        assert with_tier.kv_host_bytes == \
            fit.kv_paged_bytes(full_7b.cfg, 9216, 16)
        # DRAM, not HBM: the total and the verdict must not move.
        assert with_tier.total_bytes == base.total_bytes
        assert with_tier.fits == base.fits
        d = with_tier.to_json()
        assert d["kv_host_blocks"] == 9216
        assert d["kv_host_bytes"] == with_tier.kv_host_bytes

    def test_markdown_resident_sessions_multiplier(self, with_tier):
        md = fit.to_markdown(with_tier)
        assert "Host KV tier (serve/tier.py)" in md
        assert "NOT in the HBM total" in md
        # 1023 device pages + 9215 host pages over 1023: the ~10x
        # headline resident-sessions claim, computed not asserted by
        # hand-wave.
        assert "**10.0x the resident sessions**" in md

    def test_tier_requires_paged_pool(self, full_7b):
        with pytest.raises(ValueError, match="kv_blocks"):
            fit.analyze(
                cfg=full_7b.cfg, dp=4, tp_size=8, global_batch=8,
                seq_len=4096, do_compile=False, kv_host_blocks=64,
            )

    def test_cli_requires_kv_blocks(self, capsys):
        with pytest.raises(SystemExit) as e:
            fit.main([
                "--no-compile", "--kv-host-tier", "64", "--json",
            ])
        assert e.value.code == 2
        assert "--kv-blocks" in capsys.readouterr().err

    def test_cli_flag_reaches_analyze(self, capsys):
        rc = fit.main([
            "--no-compile", "--kv-blocks", "1024",
            "--kv-host-tier", "9216", "--json",
        ])
        import json as _json

        out = _json.loads(capsys.readouterr().out)
        assert out["kv_host_blocks"] == 9216
        assert out["kv_host_bytes"] > 0
        assert rc in (0, 1)
