"""Topology-morphing coordinator (tpu_hpc.elastic): grow/shrink
mid-run with no restart.

The pinned contracts:

* THE acceptance: a preemption-storm chaos run (shrink at step 2,
  grow back at step 4) driven by the coordinator produces a loss
  stream AND final params bit-identical to a fixed-topology run on
  the final layout -- zero process restarts (one pid), zero
  steady-state recompiles (per-segment compile counters pinned), and
  the shrink moves ZERO wire bytes (the data-extent-preserving layout
  keeps every surviving device's shard resident).
* The morph-request channel (resilience.signals.MorphChannel): the
  scheduler-facing sibling of the SIGTERM contract -- post/pending/
  ack round-trips through the JSONL file, and a channel-driven morph
  acks with the transition's wire bytes and stall.
* Vacuous-pass guards, both directions: a Trainer OUTSIDE the
  coordinator hard-rejects armed slice faults; the coordinator
  hard-fails a run that ends with an armed slice fault that never
  fired; a no-op morph target is refused, not acked.
* The layout policy: the data-axis extent is preserved whenever
  legal (what makes bit-identity possible at all -- see
  elastic/layout.py for why a changed extent re-blocks the batch);
  when preservation is impossible the decision says so.
* Topology re-planning: the device-set fingerprint changes across a
  morph and a ``comm_mode="auto"`` trainer re-plans against the new
  fingerprint (one ``comm_plan`` event per topology segment).
* Supervisor accounting: completed morphs are booked as ZERO budget
  burned (``morphs_complete``), and the channel path is exported to
  every supervised child.
"""
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_hpc.config import TrainingConfig
from tpu_hpc.elastic import (
    TopologyCoordinator,
    choose_layout,
    legal_extents,
)
from tpu_hpc.resilience.signals import (
    ENV_MORPH_CHANNEL,
    MorphChannel,
)
from tpu_hpc.runtime import MeshSpec, build_mesh
from tpu_hpc.train.trainer import Trainer

N_DEV = 8  # conftest forces 8 sim devices


def _init_params():
    k1, k2 = jax.random.split(jax.random.key(7))
    return {
        "w1": jax.random.normal(k1, (16, 32), jnp.float32) * 0.1,
        "w2": jax.random.normal(k2, (32, 4), jnp.float32) * 0.1,
    }


def _forward(params, model_state, batch, rng):
    pred = jnp.tanh(batch["x"] @ params["w1"]) @ params["w2"]
    return jnp.mean((pred - batch["y"]) ** 2), model_state, {}


class _DS:
    def batch_at(self, step, gbs):
        k = jax.random.key(1000 + int(step))
        kx, ky = jax.random.split(k)
        return {
            "x": jax.random.normal(kx, (gbs, 16), jnp.float32),
            "y": jax.random.normal(ky, (gbs, 4), jnp.float32),
        }


def _cfg(path, steps=6, **kw):
    return TrainingConfig(
        epochs=steps, steps_per_epoch=1, global_batch_size=16,
        learning_rate=1e-2, weight_decay=0.01, metrics_path=path,
        **kw,
    )


def _factory(cfg):
    def factory(mesh):
        params = _init_params()
        return Trainer(
            cfg, mesh, _forward, params,
            param_pspecs=jax.tree.map(lambda _: P(), params),
            batch_pspec=P("data"),
        )
    return factory


def _losses(path):
    out = []
    with open(path) as f:
        for line in f:
            r = json.loads(line)
            if r.get("event") == "epoch":
                out.append((r["step"], r["loss"]))
    return out


def _records(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


# ---------------------------------------------------------------------
# THE acceptance: preemption storm, bit-identical, zero restarts
# ---------------------------------------------------------------------
@pytest.fixture(scope="module")
def storm(tmp_path_factory):
    """One fixed-topology reference plus one coordinator-driven storm
    (shrink@2 -> train -> grow@4 -> train) -- every acceptance pin
    reads from here."""
    tmp = tmp_path_factory.mktemp("storm")
    fixed_path = str(tmp / "fixed.jsonl")
    fixed_tr = _factory(_cfg(fixed_path))(build_mesh(
        MeshSpec(axes={"data": 4, "replica": 2}),
        devices=jax.devices(),
    ))
    fixed_res = fixed_tr.fit(_DS())

    morph_path = str(tmp / "morph.jsonl")
    ckpt_dir = str(tmp / "ck")
    prev = os.environ.get("TPU_HPC_FAULTS")
    os.environ["TPU_HPC_FAULTS"] = (
        "slice_down_at_step=2,slice_up_at_step=4"
    )
    try:
        coord = TopologyCoordinator(
            _factory(_cfg(morph_path)), global_batch=16,
            data_extent=4, checkpoint_dir=ckpt_dir,
        )
        summary = coord.run(_DS())
    finally:
        if prev is None:
            os.environ.pop("TPU_HPC_FAULTS", None)
        else:
            os.environ["TPU_HPC_FAULTS"] = prev
    return {
        "fixed_res": fixed_res,
        "fixed_params": jax.device_get(fixed_tr.state.params),
        "fixed_path": fixed_path,
        "coord": coord,
        "summary": summary,
        "morph_path": morph_path,
        "ckpt_dir": ckpt_dir,
    }


class TestPreemptionStorm:
    def test_loss_stream_bit_identical(self, storm):
        fixed = _losses(storm["fixed_path"])
        morph = _losses(storm["morph_path"])
        assert len(fixed) == 6
        assert fixed == morph  # bit-identical, not allclose

    def test_final_params_bit_identical(self, storm):
        got = jax.device_get(storm["coord"].trainer.state.params)
        for a, b in zip(
            jax.tree.leaves(storm["fixed_params"]),
            jax.tree.leaves(got),
        ):
            np.testing.assert_array_equal(a, b)

    def test_zero_process_restarts(self, storm):
        s = storm["summary"]
        assert s["restarts"] == 0
        assert s["pid"] == os.getpid()
        assert s["final_loss"] == storm["fixed_res"]["final_loss"]

    def test_storm_shape(self, storm):
        s = storm["summary"]
        assert s["morph_count"] == 2
        assert [m["kind"] for m in s["morphs"]] == ["shrink", "grow"]
        assert [m["step"] for m in s["morphs"]] == [2, 4]
        segs = [
            (seg["n_devices"], seg["axes"]) for seg in s["segments"]
        ]
        assert segs == [
            (8, {"data": 4, "replica": 2}),
            (4, {"data": 4}),
            (8, {"data": 4, "replica": 2}),
        ]

    def test_shrink_moves_zero_wire_bytes(self, storm):
        """Every surviving device already holds its shard: the
        data-extent-preserving shrink is a pure drop, not a move.
        The grow pays real wire bytes (new devices need replicas)."""
        shrink, grow = storm["summary"]["morphs"]
        assert shrink["wire_bytes"] == 0
        assert grow["wire_bytes"] > 0
        assert storm["summary"]["wire_bytes"] == grow["wire_bytes"]

    def test_extent_preserved_on_both_morphs(self, storm):
        assert all(
            m["preserved_data_extent"]
            for m in storm["summary"]["morphs"]
        )

    def test_zero_steady_state_recompiles(self, storm):
        """Compile accounting: each segment's only compiles are its
        own warmup (same count every segment -- nothing recompiles
        mid-segment), and each morph's reshard programs are counted
        on the morph record."""
        segs = storm["summary"]["segments"]
        counts = {seg["compiled_epoch_fns"] for seg in segs}
        assert len(counts) == 1
        for m in storm["summary"]["morphs"]:
            assert m["compiled_programs"] >= 0

    def test_topology_morph_events_schema_valid(self, storm):
        from tpu_hpc.obs.schema import validate_file

        validate_file(storm["morph_path"])
        recs = _records(storm["morph_path"])
        morphs = [
            r for r in recs if r.get("event") == "topology_morph"
        ]
        assert len(morphs) == 2
        for r in morphs:
            assert r["trace_id"]
            assert r["stall_s"] >= 0
            assert r["plan"]["axes"]
        assert morphs[0]["reason"] == "shrink"
        assert morphs[0]["src_mesh"] == {"data": 4, "replica": 2}
        assert morphs[0]["tgt_mesh"] == {"data": 4}
        assert morphs[1]["reason"] == "grow"
        # The injection announcements ride next to their effects.
        faults = [r for r in recs if r.get("event") == "fault"]
        assert [f["kind"] for f in faults] == [
            "slice_down", "slice_up",
        ]
        spans = [
            r for r in recs
            if r.get("event") == "span" and r.get("name") == "morph"
        ]
        assert len(spans) == 2

    def test_sidecar_topology_history_records_morphs(self, storm):
        from tpu_hpc.reshard.elastic import read_topology_history

        hist = read_topology_history(storm["ckpt_dir"])
        reasons = [e["reason"] for e in hist]
        assert reasons == ["morph-shrink", "morph-grow"]
        assert hist[0]["mesh"] == {"data": 4}
        assert hist[1]["mesh"] == {"data": 4, "replica": 2}
        assert [e["device_count"] for e in hist] == [4, 8]

    def test_report_renders_topology_morphs(self, storm):
        from tpu_hpc.obs.report import build_report, format_report

        rep = build_report(_records(storm["morph_path"]))
        el = rep["elastic"]
        assert el["morphs"] == 2
        assert el["wire_bytes"] == storm["summary"]["wire_bytes"]
        assert el["stall_s"] > 0
        text = format_report(rep)
        assert "## Topology morphs" in text
        assert "zero process restarts" in text

    def test_regress_flattens_elastic_namespace(self, storm):
        from tpu_hpc.obs.regress import report_metrics
        from tpu_hpc.obs.report import build_report

        flat = report_metrics(
            build_report(_records(storm["morph_path"]))
        )
        assert flat["elastic.morphs"] == 2.0
        assert flat["elastic.wire_bytes"] == float(
            storm["summary"]["wire_bytes"]
        )
        assert flat["elastic.stall_s"] > 0


# ---------------------------------------------------------------------
# layout policy
# ---------------------------------------------------------------------
class TestLayout:
    def test_legal_extents(self):
        assert legal_extents(8, 16) == [1, 2, 4, 8]
        assert legal_extents(6, 16) == [1, 2]  # 3, 6 don't divide 16
        assert legal_extents(4, 12) == [1, 2, 4]

    def test_preserves_current_extent_when_legal(self):
        d = choose_layout(
            jax.devices()[:4], global_batch=16,
            current_data_extent=4,
        )
        assert d.axes == {"data": 4}
        assert d.preserved_data_extent is True
        d2 = choose_layout(
            jax.devices(), global_batch=16, current_data_extent=4,
        )
        assert d2.axes == {"data": 4, "replica": 2}
        assert d2.preserved_data_extent is True

    def test_impossible_preservation_falls_back_and_says_so(self):
        # extent 8 cannot fit on 4 devices: the decision re-plans and
        # flags that bit-exact continuity was given up.
        d = choose_layout(
            jax.devices()[:4], global_batch=16,
            current_data_extent=8,
        )
        assert d.axes["data"] <= 4
        assert d.preserved_data_extent is False

    def test_empty_device_set_is_a_typed_error(self):
        with pytest.raises(ValueError, match="non-empty"):
            choose_layout([], global_batch=16)

    def test_awkward_device_count_still_has_extent_one(self):
        # 5 devices, batch 16: only extent 1 is legal -- the layout
        # degrades to replication rather than refusing to run.
        d = choose_layout(jax.devices()[:5], global_batch=16)
        assert d.axes == {"data": 1, "replica": 5}
        assert d.data_extent == 1

    def test_decision_summary_is_json_safe(self):
        d = choose_layout(
            jax.devices(), global_batch=16, current_data_extent=4,
        )
        s = json.dumps(d.summary())
        assert "axes" in s and "fingerprint" in s


# ---------------------------------------------------------------------
# the morph-request channel
# ---------------------------------------------------------------------
class TestMorphChannel:
    def test_post_pending_ack_round_trip(self, tmp_path):
        ch = MorphChannel(str(tmp_path / "chan.jsonl"))
        s0 = ch.post("shrink", 4, step=2)
        s1 = ch.post("grow", 8, step=5)
        pend = ch.pending()
        assert [(r.kind, r.n_devices, r.step) for r in pend] == [
            ("shrink", 4, 2), ("grow", 8, 5),
        ]
        ch.ack(s0, step=2, wire_bytes=0)
        assert [r.seq for r in ch.pending()] == [s1]
        ch.ack(s1, step=5, wire_bytes=123)
        assert ch.pending() == []
        acked = ch.acked()
        assert len(acked) == 2
        assert acked[1]["wire_bytes"] == 123

    def test_invalid_request_rejected(self, tmp_path):
        ch = MorphChannel(str(tmp_path / "chan.jsonl"))
        with pytest.raises(ValueError, match="kind"):
            ch.post("explode", 4)
        with pytest.raises(ValueError, match="n_devices"):
            ch.post("shrink", 0)

    def test_from_env(self, tmp_path, monkeypatch):
        monkeypatch.delenv(ENV_MORPH_CHANNEL, raising=False)
        assert MorphChannel.from_env() is None
        p = str(tmp_path / "c.jsonl")
        monkeypatch.setenv(ENV_MORPH_CHANNEL, p)
        ch = MorphChannel.from_env()
        assert ch is not None and ch.path == p

    def test_channel_driven_morph_acks_with_costs(self, tmp_path):
        """A scheduler-shaped request (no chaos env at all) drives
        the same live transition, and the ack carries the evidence."""
        ch = MorphChannel(str(tmp_path / "chan.jsonl"))
        ch.post("shrink", 4, step=2)
        coord = TopologyCoordinator(
            _factory(_cfg(str(tmp_path / "m.jsonl"), steps=4)),
            global_batch=16, data_extent=4, channel=ch,
        )
        summary = coord.run(_DS())
        assert summary["morph_count"] == 1
        assert summary["morphs"][0]["source"] == "channel"
        assert summary["restarts"] == 0
        acked = ch.acked()
        assert len(acked) == 1
        assert acked[0]["step"] == 2
        assert acked[0]["tgt_mesh"] == {"data": 4}
        assert "wire_bytes" in acked[0]
        assert ch.pending() == []

    def test_noop_morph_target_is_refused(self, tmp_path):
        ch = MorphChannel(str(tmp_path / "chan.jsonl"))
        ch.post("grow", N_DEV, step=1)  # already at the full pool
        coord = TopologyCoordinator(
            _factory(_cfg(str(tmp_path / "m.jsonl"), steps=3)),
            global_batch=16, data_extent=4, channel=ch,
        )
        with pytest.raises(RuntimeError, match="no-op"):
            coord.run(_DS())


# ---------------------------------------------------------------------
# vacuous-pass guards, both directions + parse discipline
# ---------------------------------------------------------------------
class TestSliceFaultDiscipline:
    def test_typed_parse(self):
        from tpu_hpc.resilience.faults import fault_plan_from_env

        plan = fault_plan_from_env({
            "TPU_HPC_FAULTS":
                "slice_down_at_step=2,slice_up_at_step=4",
        })
        assert plan.slice_down_at_step == 2
        assert plan.slice_up_at_step == 4
        assert plan.slice_fault_keys() == [
            "slice_down_at_step", "slice_up_at_step",
        ]

    def test_malformed_value_names_key_and_type(self):
        from tpu_hpc.resilience.faults import fault_plan_from_env

        with pytest.raises(
            ValueError, match=r"slice_down_at_step.*int"
        ):
            fault_plan_from_env(
                {"TPU_HPC_FAULTS": "slice_down_at_step=soon"}
            )

    def test_unmanaged_trainer_rejects_slice_faults(
        self, monkeypatch, tmp_path
    ):
        """Direction one: a Trainer outside the coordinator cannot
        morph, so an armed slice fault would silently never fire."""
        monkeypatch.setenv("TPU_HPC_FAULTS", "slice_down_at_step=2")
        with pytest.raises(ValueError, match="elastic coordinator"):
            _factory(_cfg(str(tmp_path / "m.jsonl")))(build_mesh(
                MeshSpec(axes={"data": 4, "replica": 2}),
                devices=jax.devices(),
            ))

    def test_unfired_slice_fault_fails_the_run(
        self, monkeypatch, tmp_path
    ):
        """Direction two: the coordinator refuses to let a chaos
        schedule pass when its armed fault never fired."""
        monkeypatch.setenv(
            "TPU_HPC_FAULTS", "slice_down_at_step=99"
        )
        coord = TopologyCoordinator(
            _factory(_cfg(str(tmp_path / "m.jsonl"), steps=3)),
            global_batch=16, data_extent=4,
        )
        with pytest.raises(RuntimeError, match="never fired"):
            coord.run(_DS())


# ---------------------------------------------------------------------
# topology re-plan: fingerprint changes, comm_mode="auto" follows
# ---------------------------------------------------------------------
class TestTopologyReplan:
    def test_fingerprint_digest_changes_across_morph(self):
        from tpu_hpc.comm.planner import fingerprint_devices

        full = fingerprint_devices(jax.devices())
        half = fingerprint_devices(jax.devices()[:4])
        assert full.digest != half.digest

    def test_comm_auto_replans_per_topology_segment(self, tmp_path):
        """Every segment's Trainer re-resolves comm_mode="auto"
        against ITS device set: one comm_plan event per segment, and
        the shrunken segment's fingerprint differs from the full
        pool's."""
        path = str(tmp_path / "m.jsonl")
        ch = MorphChannel(str(tmp_path / "chan.jsonl"))
        ch.post("shrink", 4, step=2)
        coord = TopologyCoordinator(
            _factory(_cfg(path, steps=4, comm_mode="auto")),
            global_batch=16, data_extent=4, channel=ch,
        )
        summary = coord.run(_DS())
        assert summary["morph_count"] == 1
        plans = [
            r for r in _records(path)
            if r.get("event") == "comm_plan"
        ]
        assert len(plans) == len(summary["segments"]) == 2
        fps = [p["fingerprint"] for p in plans]
        assert fps[0] != fps[1]


# ---------------------------------------------------------------------
# supervisor accounting: morphs burn zero budget
# ---------------------------------------------------------------------
class TestSupervisorMorphAccounting:
    def test_channel_exported_and_morphs_booked_as_zero_burn(
        self, tmp_path, monkeypatch
    ):
        from tpu_hpc.resilience.supervisor import Supervisor

        monkeypatch.delenv(ENV_MORPH_CHANNEL, raising=False)
        log_dir = str(tmp_path / "logs")
        # The child plays an elastic-managed run: it finds the
        # exported channel, completes two morphs (posts acks), exits
        # clean -- no restart machinery involved.
        child = (
            "import json, os\n"
            "p = os.environ['TPU_HPC_MORPH_CHANNEL']\n"
            "from tpu_hpc.resilience.signals import MorphChannel\n"
            "ch = MorphChannel(p)\n"
            "s0 = ch.post('shrink', 4, step=2)\n"
            "s1 = ch.post('grow', 8, step=4)\n"
            "ch.ack(s0, step=2, wire_bytes=0)\n"
            "ch.ack(s1, step=4, wire_bytes=123)\n"
        )
        sup = Supervisor(
            [sys.executable, "-c", child],
            max_restarts=0, log_dir=log_dir,
        )
        assert sup.run() == 0
        events = _records(os.path.join(log_dir, "supervisor.jsonl"))
        done = [
            e for e in events if e["event"] == "morphs_complete"
        ]
        assert len(done) == 1
        assert done[0]["count"] == 2
        assert done[0]["budget_burned"] == 0
        from tpu_hpc.obs.schema import validate_record

        validate_record(done[0])

    def test_no_channel_no_event(self, tmp_path, monkeypatch):
        from tpu_hpc.resilience.supervisor import Supervisor

        monkeypatch.delenv(ENV_MORPH_CHANNEL, raising=False)
        log_dir = str(tmp_path / "logs")
        sup = Supervisor(
            [sys.executable, "-c", "pass"],
            max_restarts=0, log_dir=log_dir,
        )
        assert sup.run() == 0
        events = _records(os.path.join(log_dir, "supervisor.jsonl"))
        assert not [
            e for e in events if e["event"] == "morphs_complete"
        ]
