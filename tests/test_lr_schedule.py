"""LR schedules: config -> optax schedule -> trainer integration.

The reference trains at a fixed LR everywhere (utils/config.py:27-35);
`lr_schedule`/`warmup_steps` extend that surface with the standard LLM
pretraining shape. The schedule is driven by the optimizer-update
count carried in the opt state, so it is grad-accum-agnostic and
survives checkpoint resume for free.
"""
import jax
import jax.numpy as jnp
import pytest

from tpu_hpc.config import TrainingConfig
from tpu_hpc.models import datasets, llama2
from tpu_hpc.runtime import MeshSpec, build_mesh
from tpu_hpc.train import Trainer
from tpu_hpc.train.trainer import make_lr_schedule


def test_constant_is_scalar():
    assert make_lr_schedule(TrainingConfig(learning_rate=3e-4)) == 3e-4


def test_constant_with_warmup():
    sched = make_lr_schedule(
        TrainingConfig(learning_rate=1.0, warmup_steps=10)
    )
    assert float(sched(0)) == 0.0
    assert float(sched(5)) == pytest.approx(0.5)
    assert float(sched(10)) == pytest.approx(1.0)
    assert float(sched(1000)) == pytest.approx(1.0)


def test_cosine_shape():
    cfg = TrainingConfig(
        learning_rate=1.0, lr_schedule="cosine", warmup_steps=10,
        epochs=2, steps_per_epoch=50,  # decay over 100 updates
    )
    sched = make_lr_schedule(cfg)
    assert float(sched(0)) == 0.0
    assert float(sched(10)) == pytest.approx(1.0)  # peak after warmup
    mid, near_end, end = (
        float(sched(55)), float(sched(99)), float(sched(100))
    )
    assert 0.0 < near_end < mid < 1.0
    assert end == pytest.approx(0.0)


def test_unknown_schedule_rejected():
    with pytest.raises(ValueError, match="lr_schedule"):
        make_lr_schedule(TrainingConfig(lr_schedule="polynomial"))


def test_trains_with_cosine(devices):
    model = llama2.LlamaConfig(
        dim=32, n_layers=1, n_heads=4, vocab_size=64, multiple_of=16,
        max_seq_len=16,
    )
    cfg = TrainingConfig(
        global_batch_size=8, steps_per_epoch=4, epochs=1,
        learning_rate=1e-2, lr_schedule="cosine", warmup_steps=2,
    )
    mesh = build_mesh(MeshSpec(axes={"data": 8}))
    params = llama2.init_llama(jax.random.key(0), model)
    t = Trainer(cfg, mesh, llama2.make_forward(model), params)
    ds = datasets.TokenStream(vocab_size=64, seq_len=16)
    out = t.fit(ds)
    assert jnp.isfinite(out["final_loss"])
    # The schedule count advanced with the optimizer updates.
    counts = [
        l for l in jax.tree.leaves(t.state.opt_state)
        if getattr(l, "dtype", None) == jnp.int32 and l.ndim == 0
    ]
    assert any(int(jax.device_get(c)) == 4 for c in counts)


def test_fit_epochs_override_conflicts_with_cosine(devices):
    """A fit(epochs=) override under cosine would silently clamp (longer
    run) or truncate decay (shorter) -- must raise, not drift."""
    model = llama2.LlamaConfig(
        dim=32, n_layers=1, n_heads=4, vocab_size=64, multiple_of=16,
        max_seq_len=16,
    )
    cfg = TrainingConfig(
        global_batch_size=8, steps_per_epoch=2, epochs=1,
        learning_rate=1e-2, lr_schedule="cosine", warmup_steps=1,
    )
    mesh = build_mesh(MeshSpec(axes={"data": 8}))
    params = llama2.init_llama(jax.random.key(0), model)
    t = Trainer(cfg, mesh, llama2.make_forward(model), params)
    ds = datasets.TokenStream(vocab_size=64, seq_len=16)
    with pytest.raises(ValueError, match="cosine"):
        t.fit(ds, epochs=3)


class TestBf16Moments:
    """adam_moments_dtype="bfloat16": both Adam moments stored in bf16
    (half the optimizer-state HBM -- the documented unlock for
    70B-class models on 16 GiB chips), update math still fp32."""

    def _trainer(self, moments):
        model = llama2.LlamaConfig(
            dim=32, n_layers=1, n_heads=4, vocab_size=64,
            multiple_of=16, max_seq_len=16,
        )
        cfg = TrainingConfig(
            global_batch_size=8, steps_per_epoch=4, epochs=1,
            learning_rate=1e-2, weight_decay=0.1,
            adam_moments_dtype=moments,
        )
        mesh = build_mesh(MeshSpec(axes={"data": 8}))
        params = llama2.init_llama(jax.random.key(0), model)
        return Trainer(cfg, mesh, llama2.make_forward(model), params)

    def test_moments_stored_bf16_and_training_descends(self):
        import optax

        t = self._trainer("bfloat16")
        adam_states = [
            s for s in jax.tree.leaves(
                t.state.opt_state,
                is_leaf=lambda x: isinstance(x, optax.ScaleByAdamState),
            )
            if isinstance(s, optax.ScaleByAdamState)
        ]
        assert adam_states
        for s in adam_states:
            for leaf in jax.tree.leaves(s.mu) + jax.tree.leaves(s.nu):
                assert leaf.dtype == jnp.bfloat16, leaf.dtype
        ds = datasets.TokenStream(vocab_size=64, seq_len=16)
        out = t.fit(ds)
        assert jnp.isfinite(out["final_loss"])
        # Moments stayed bf16 across real update steps.
        for s in jax.tree.leaves(
            t.state.opt_state,
            is_leaf=lambda x: isinstance(x, optax.ScaleByAdamState),
        ):
            if isinstance(s, optax.ScaleByAdamState):
                for leaf in jax.tree.leaves(s.nu):
                    assert leaf.dtype == jnp.bfloat16

    def test_close_to_fp32_trajectory(self):
        ds = datasets.TokenStream(vocab_size=64, seq_len=16)
        l32 = float(self._trainer("float32").fit(ds)["final_loss"])
        l16 = float(self._trainer("bfloat16").fit(ds)["final_loss"])
        assert abs(l32 - l16) < 0.05 * abs(l32), (l32, l16)

    def test_bogus_dtype_rejected(self):
        with pytest.raises(ValueError, match="adam_moments_dtype"):
            self._trainer("float16")

    def test_fit_accounting_halves_opt_bytes(self):
        from tpu_hpc.checks import fit as fitmod

        cfg = llama2.LlamaConfig(
            n_layers=2, max_seq_len=512, remat=True
        )
        r32 = fitmod.analyze(
            cfg=cfg, dp=2, tp_size=4, global_batch=4, seq_len=512,
            do_compile=False,
        )
        r16 = fitmod.analyze(
            cfg=cfg, dp=2, tp_size=4, global_batch=4, seq_len=512,
            do_compile=False, moments_dtype="bfloat16",
        )
        assert abs(r16.opt_bytes - r32.opt_bytes / 2) < 0.01 * r32.opt_bytes

    def test_rejected_on_sgd_path(self):
        """Silently ignoring the HBM-halving request on the default
        SGD optimizer would OOM the run the knob exists for."""
        model = llama2.LlamaConfig(
            dim=32, n_layers=1, n_heads=4, vocab_size=64,
            multiple_of=16, max_seq_len=16,
        )
        cfg = TrainingConfig(
            global_batch_size=8, weight_decay=0.0,
            adam_moments_dtype="bfloat16",
        )
        mesh = build_mesh(MeshSpec(axes={"data": 8}))
        params = llama2.init_llama(jax.random.key(0), model)
        with pytest.raises(ValueError, match="SGD path"):
            Trainer(cfg, mesh, llama2.make_forward(model), params)
