"""LR schedules: config -> optax schedule -> trainer integration.

The reference trains at a fixed LR everywhere (utils/config.py:27-35);
`lr_schedule`/`warmup_steps` extend that surface with the standard LLM
pretraining shape. The schedule is driven by the optimizer-update
count carried in the opt state, so it is grad-accum-agnostic and
survives checkpoint resume for free.
"""
import jax
import jax.numpy as jnp
import pytest

from tpu_hpc.config import TrainingConfig
from tpu_hpc.models import datasets, llama2
from tpu_hpc.runtime import MeshSpec, build_mesh
from tpu_hpc.train import Trainer
from tpu_hpc.train.trainer import make_lr_schedule


def test_constant_is_scalar():
    assert make_lr_schedule(TrainingConfig(learning_rate=3e-4)) == 3e-4


def test_constant_with_warmup():
    sched = make_lr_schedule(
        TrainingConfig(learning_rate=1.0, warmup_steps=10)
    )
    assert float(sched(0)) == 0.0
    assert float(sched(5)) == pytest.approx(0.5)
    assert float(sched(10)) == pytest.approx(1.0)
    assert float(sched(1000)) == pytest.approx(1.0)


def test_cosine_shape():
    cfg = TrainingConfig(
        learning_rate=1.0, lr_schedule="cosine", warmup_steps=10,
        epochs=2, steps_per_epoch=50,  # decay over 100 updates
    )
    sched = make_lr_schedule(cfg)
    assert float(sched(0)) == 0.0
    assert float(sched(10)) == pytest.approx(1.0)  # peak after warmup
    mid, near_end, end = (
        float(sched(55)), float(sched(99)), float(sched(100))
    )
    assert 0.0 < near_end < mid < 1.0
    assert end == pytest.approx(0.0)


def test_unknown_schedule_rejected():
    with pytest.raises(ValueError, match="lr_schedule"):
        make_lr_schedule(TrainingConfig(lr_schedule="polynomial"))


def test_trains_with_cosine(devices):
    model = llama2.LlamaConfig(
        dim=32, n_layers=1, n_heads=4, vocab_size=64, multiple_of=16,
        max_seq_len=16,
    )
    cfg = TrainingConfig(
        global_batch_size=8, steps_per_epoch=4, epochs=1,
        learning_rate=1e-2, lr_schedule="cosine", warmup_steps=2,
    )
    mesh = build_mesh(MeshSpec(axes={"data": 8}))
    params = llama2.init_llama(jax.random.key(0), model)
    t = Trainer(cfg, mesh, llama2.make_forward(model), params)
    ds = datasets.TokenStream(vocab_size=64, seq_len=16)
    out = t.fit(ds)
    assert jnp.isfinite(out["final_loss"])
    # The schedule count advanced with the optimizer updates.
    counts = [
        l for l in jax.tree.leaves(t.state.opt_state)
        if getattr(l, "dtype", None) == jnp.int32 and l.ndim == 0
    ]
    assert any(int(jax.device_get(c)) == 4 for c in counts)


def test_fit_epochs_override_conflicts_with_cosine(devices):
    """A fit(epochs=) override under cosine would silently clamp (longer
    run) or truncate decay (shorter) -- must raise, not drift."""
    model = llama2.LlamaConfig(
        dim=32, n_layers=1, n_heads=4, vocab_size=64, multiple_of=16,
        max_seq_len=16,
    )
    cfg = TrainingConfig(
        global_batch_size=8, steps_per_epoch=2, epochs=1,
        learning_rate=1e-2, lr_schedule="cosine", warmup_steps=1,
    )
    mesh = build_mesh(MeshSpec(axes={"data": 8}))
    params = llama2.init_llama(jax.random.key(0), model)
    t = Trainer(cfg, mesh, llama2.make_forward(model), params)
    ds = datasets.TokenStream(vocab_size=64, seq_len=16)
    with pytest.raises(ValueError, match="cosine"):
        t.fit(ds, epochs=3)
