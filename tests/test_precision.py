"""Mixed-precision plumbing: config -> model dtype pairs (round-1
VERDICT missing item #4). Parity: the reference's --use-amp/amp_dtype
switch (resnet_fsdp_training.py:198-204, utils/config.py:40-44) --
here param_dtype/compute_dtype flow from TrainingConfig into every
model config, and fp32-params/bf16-compute is the TPU-native default."""
import jax
import jax.numpy as jnp
import pytest

from tpu_hpc.config import TrainingConfig
from tpu_hpc.models import datasets, llama2, resnet, unet, vit
from tpu_hpc.runtime import MeshSpec, build_mesh
from tpu_hpc.train import Trainer


def test_jax_dtypes_defaults():
    param, compute = TrainingConfig().jax_dtypes()
    assert param == jnp.float32
    assert compute == jnp.bfloat16


def test_jax_dtypes_cli_switch():
    cfg = TrainingConfig.from_args(
        ["--compute-dtype", "float32", "--param-dtype", "bfloat16"]
    )
    param, compute = cfg.jax_dtypes()
    assert param == jnp.bfloat16
    assert compute == jnp.float32


def test_jax_dtypes_rejects_unknown():
    with pytest.raises(ValueError, match="unsupported dtype"):
        TrainingConfig(compute_dtype="int8").jax_dtypes()


def _param_dtypes(tree):
    return {str(leaf.dtype) for leaf in jax.tree.leaves(tree)}


def test_llama_param_dtype_follows_config():
    cfg = llama2.LlamaConfig(
        dim=64, n_layers=1, n_heads=4, vocab_size=64, multiple_of=16,
        max_seq_len=16,
    )
    assert _param_dtypes(
        llama2.init_llama(jax.random.key(0), cfg)
    ) == {"float32"}
    import dataclasses

    bf16 = dataclasses.replace(cfg, param_dtype=jnp.bfloat16)
    assert _param_dtypes(
        llama2.init_llama(jax.random.key(0), bf16)
    ) == {"bfloat16"}


def test_resnet_param_dtype_follows_config():
    cfg = resnet.ResNetConfig(
        depth=18, dtype=jnp.bfloat16, param_dtype=jnp.bfloat16
    )
    params, model_state = resnet.init_resnet(jax.random.key(0), cfg)
    assert _param_dtypes(params) == {"bfloat16"}


def test_unet_vit_param_dtype_follows_config():
    ucfg = unet.UNetConfig(
        in_channels=4, out_channels=4, base_features=8,
        dtype=jnp.bfloat16, param_dtype=jnp.bfloat16,
    )
    params, _ = unet.init_unet(jax.random.key(0), ucfg, (16, 16, 4))
    assert _param_dtypes(params) == {"bfloat16"}
    vcfg = vit.ViTConfig(
        in_channels=4, out_channels=4, patch_size=4, lat=16, lon=16,
        embed_dim=32, depth=1, n_heads=4, param_dtype=jnp.bfloat16,
    )
    assert _param_dtypes(vit.init_vit(jax.random.key(0), vcfg)) == {
        "bfloat16"
    }


def test_pipeline_param_dtype_follows_config():
    from tpu_hpc.models import pipeline_transformer as ptx

    cfg = ptx.PipeConfig(
        vocab_size=64, dim=32, n_heads=4, n_stages=2,
        layers_per_stage=1, max_seq_len=16, param_dtype=jnp.bfloat16,
    )
    params = ptx.init_pipeline_transformer(jax.random.key(0), cfg)
    assert _param_dtypes(params) == {"bfloat16"}


def test_compute_dtype_changes_the_math():
    """bf16 vs fp32 compute must produce (slightly) different logits --
    proof the flag reaches the matmuls, not just the param store."""
    kw = dict(
        dim=64, n_layers=2, n_heads=4, vocab_size=128, multiple_of=16,
        max_seq_len=32,
    )
    cfg32 = llama2.LlamaConfig(dtype=jnp.float32, **kw)
    cfg16 = llama2.LlamaConfig(dtype=jnp.bfloat16, **kw)
    params = llama2.init_llama(jax.random.key(0), cfg32)
    tokens = jax.random.randint(jax.random.key(1), (2, 32), 0, 128)
    out32 = llama2.apply_llama(params, tokens, cfg32)
    out16 = llama2.apply_llama(params, tokens, cfg16)
    # Logits come back in the compute dtype; the loss upcasts inside
    # its fused reductions (no [B, S, V] fp32 round-trip through HBM).
    assert out32.dtype == jnp.float32
    assert out16.dtype == jnp.bfloat16
    out16 = out16.astype(jnp.float32)
    assert not jnp.allclose(out32, out16, atol=1e-6)
    assert jnp.allclose(out32, out16, atol=0.5)  # same model, lower precision


def test_trainer_preserves_param_dtype_through_updates(devices):
    """fp32 masters must stay fp32 after optimizer updates even with
    bf16 compute (the AMP invariant the reference gets from
    MixedPrecision(param_dtype=...))."""
    mesh = build_mesh(MeshSpec(axes={"data": 8}))
    cfg = TrainingConfig(
        epochs=1, steps_per_epoch=2, global_batch_size=8,
    )
    model_cfg = resnet.ResNetConfig(
        depth=18, dtype=jnp.bfloat16, param_dtype=jnp.float32
    )
    params, model_state = resnet.init_resnet(jax.random.key(0), model_cfg)
    trainer = Trainer(
        cfg, mesh, resnet.make_forward(model_cfg), params, model_state,
    )
    trainer.fit(datasets.CIFARSynthetic())
    assert _param_dtypes(trainer.state.params) == {"float32"}
