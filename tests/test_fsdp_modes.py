"""FSDP sharding-strategy matrix: SHARD_GRAD_OP and HYBRID_SHARD.

The reference documents four FSDP modes
(docs/guide/05_fully_sharded_fsdp.md:114-156; HYBRID_SHARD recipe in
scripts/02_fully_sharded_fsdp/README.md:133-138):
  FULL_SHARD    -> fsdp.param_pspecs        (tests/test_train_dp.py)
  NO_SHARD      -> dp.param_pspecs          (tests/test_train_dp.py)
  SHARD_GRAD_OP -> fsdp.grad_op_pspecs      (this file)
  HYBRID_SHARD  -> fsdp.hybrid_shard_pspecs (this file)

The layout assertions here are the mode's *defining invariants* -- not
just "it runs": SHARD_GRAD_OP means params stay replicated across
optimizer steps while moments stay sharded; HYBRID_SHARD means params
shard only over the inner (intra-island) axis and every chip still
sees distinct data.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_hpc.config import TrainingConfig
from tpu_hpc.models import datasets, losses
from tpu_hpc.models.unet import UNetConfig, apply_unet, init_unet
from tpu_hpc.parallel import dp, fsdp
from tpu_hpc.runtime import MeshSpec, build_mesh
from tpu_hpc.train import Trainer


def _unet_forward(cfg_model):
    def forward(params, model_state, batch, step_rng):
        x, y = batch
        pred, new_ms = apply_unet(params, model_state, x, cfg_model, train=True)
        return losses.lat_weighted_mse(pred, y), new_ms, {}

    return forward


@pytest.fixture(scope="module")
def small_unet():
    cfg_model = UNetConfig(in_channels=4, out_channels=4, base_features=4)
    params, ms = init_unet(jax.random.key(0), cfg_model, (21, 24, 4))
    ds = datasets.ERA5Synthetic(n_vars=2, n_levels=2, lat=21, lon=24)
    return cfg_model, params, ms, ds


@pytest.fixture(scope="module")
def mesh_replica_fsdp(devices):
    """2D data mesh: 2 islands x 4 chips (the HYBRID_SHARD shape)."""
    return build_mesh(MeshSpec(axes={"replica": 2, "fsdp": 4}))


class TestShardGradOp:
    def test_layout_invariant_across_steps(self, mesh8, small_unet):
        """Params replicated, moments sharded -- and they STAY that way
        after optimizer.step (the updated params must not silently
        inherit the moments' sharded layout through apply_updates)."""
        cfg_model, params, ms, ds = small_unet
        p_specs, opt_specs = fsdp.grad_op_pspecs(
            params, axis_size=8, min_size=200
        )
        cfg = TrainingConfig(
            steps_per_epoch=2, global_batch_size=16, learning_rate=1e-2,
        )
        tr = Trainer(
            cfg, mesh8, _unet_forward(cfg_model), params, ms,
            param_pspecs=p_specs, opt_param_pspecs=opt_specs,
        )
        for step in range(2):
            tr.train_step(ds.batch_at(step, 16))
        for leaf in jax.tree.leaves(tr.state.params):
            assert leaf.sharding.is_fully_replicated, (
                "SHARD_GRAD_OP params must remain replicated after step"
            )
        moments = [
            leaf
            for leaf in jax.tree.leaves(tr.state.opt_state)
            if hasattr(leaf, "sharding") and leaf.size >= 200
        ]
        assert any(
            not m.sharding.is_fully_replicated for m in moments
        ), "SHARD_GRAD_OP optimizer moments must be sharded"

    def test_matches_full_shard_numerics(self, mesh8, small_unet):
        """Layout-only change: SHARD_GRAD_OP and FULL_SHARD are the
        same computation."""
        cfg_model, params, ms, ds = small_unet
        cfg = TrainingConfig(
            epochs=1, steps_per_epoch=3, global_batch_size=16,
            learning_rate=1e-2,
        )
        p_specs, opt_specs = fsdp.grad_op_pspecs(
            params, axis_size=8, min_size=200
        )
        tr_go = Trainer(
            cfg, mesh8, _unet_forward(cfg_model), params, ms,
            param_pspecs=p_specs, opt_param_pspecs=opt_specs,
        )
        tr_fs = Trainer(
            cfg, mesh8, _unet_forward(cfg_model), params, ms,
            param_pspecs=fsdp.param_pspecs(params, axis_size=8, min_size=200),
        )
        r1 = tr_go.fit(ds)
        r2 = tr_fs.fit(ds)
        np.testing.assert_allclose(
            r1["final_loss"], r2["final_loss"], rtol=1e-4
        )


class TestHybridShard:
    def test_size_must_be_explicit_or_from_mesh(
        self, mesh_replica_fsdp, small_unet
    ):
        """No whole-device-count default: on a 2-axis data mesh that
        would check divisibility against replica*fsdp and silently
        under-shard. mesh= derives the inner-axis size instead."""
        _, params, _, _ = small_unet
        with pytest.raises(ValueError, match="fsdp_size or mesh"):
            fsdp.hybrid_shard_pspecs(params, min_size=200)
        via_mesh = fsdp.hybrid_shard_pspecs(
            params, min_size=200, mesh=mesh_replica_fsdp
        )
        explicit = fsdp.hybrid_shard_pspecs(
            params, fsdp_size=4, min_size=200
        )
        assert jax.tree.map(
            lambda a, b: a == b, via_mesh, explicit,
            is_leaf=lambda x: isinstance(x, P),
        )

    def test_param_layout(self, mesh_replica_fsdp, small_unet):
        """Params shard on the inner fsdp axis only -- replicated
        across islands (param all-gathers never cross the slow link)."""
        cfg_model, params, ms, ds = small_unet
        specs = fsdp.hybrid_shard_pspecs(params, fsdp_size=4, min_size=200)
        for spec in jax.tree.leaves(
            specs, is_leaf=lambda x: isinstance(x, P)
        ):
            assert "replica" not in [a for a in spec if a is not None]
        cfg = TrainingConfig(
            steps_per_epoch=1, global_batch_size=16, learning_rate=1e-2,
        )
        tr = Trainer(
            cfg, mesh_replica_fsdp, _unet_forward(cfg_model), params, ms,
            param_pspecs=specs,
            batch_pspec=fsdp.hybrid_shard_batch_pspec(),
        )
        tr.train_step(ds.batch_at(0, 16))
        big = [
            leaf for leaf in jax.tree.leaves(tr.state.params)
            if leaf.size >= 200
        ]
        assert any(not b.sharding.is_fully_replicated for b in big)
        for leaf in big:
            spec = leaf.sharding.spec
            used = [a for a in spec if a is not None]
            assert "replica" not in used, (
                "HYBRID_SHARD params must not shard over the replica axis"
            )

    def test_matches_dp_numerics(self, mesh_replica_fsdp, mesh8, small_unet):
        """HYBRID_SHARD over (2 islands x 4 chips) is numerically plain
        8-way DP: same global batch -> same loss trajectory."""
        cfg_model, params, ms, ds = small_unet
        cfg = TrainingConfig(
            epochs=1, steps_per_epoch=3, global_batch_size=16,
            learning_rate=1e-2,
        )
        tr_hs = Trainer(
            cfg, mesh_replica_fsdp, _unet_forward(cfg_model), params, ms,
            param_pspecs=fsdp.hybrid_shard_pspecs(
                params, fsdp_size=4, min_size=200
            ),
            batch_pspec=fsdp.hybrid_shard_batch_pspec(),
        )
        tr_dp = Trainer(
            cfg, mesh8, _unet_forward(cfg_model), params, ms,
            param_pspecs=dp.param_pspecs(params),
        )
        r1 = tr_hs.fit(ds)
        r2 = tr_dp.fit(ds)
        np.testing.assert_allclose(
            r1["final_loss"], r2["final_loss"], rtol=1e-4
        )
