"""Trainer.evaluate: the reference trains *and tests* (Trainer.test
accuracy, resnet_fsdp_training.py:138-155; UNet test loss,
multinode_fsdp_unet.py) -- round-1 VERDICT missing item #3."""
import jax
import jax.numpy as jnp
import pytest

from tpu_hpc.config import TrainingConfig
from tpu_hpc.models import datasets, resnet
from tpu_hpc.parallel import fsdp
from tpu_hpc.runtime import MeshSpec, build_mesh
from tpu_hpc.train import Trainer


@pytest.fixture(scope="module")
def trained(devices):
    mesh = build_mesh(MeshSpec(axes={"data": 8}))
    cfg = TrainingConfig(
        epochs=1, steps_per_epoch=4, global_batch_size=16,
        learning_rate=1e-2,
    )
    model_cfg = resnet.ResNetConfig(depth=18)
    params, model_state = resnet.init_resnet(jax.random.key(0), model_cfg)
    trainer = Trainer(
        cfg, mesh, resnet.make_forward(model_cfg), params, model_state,
        param_pspecs=fsdp.param_pspecs(params, axis_size=8),
        eval_forward=resnet.make_eval_forward(model_cfg),
    )
    trainer.fit(datasets.CIFARSynthetic())
    return trainer


def test_evaluate_returns_loss_and_accuracy(trained):
    metrics = trained.evaluate(datasets.CIFARSynthetic(seed=1), n_steps=3)
    assert set(metrics) == {"loss", "accuracy"}
    # Random labels, 10 classes: loss near ln(10), accuracy near 10%.
    assert 0.0 <= metrics["accuracy"] <= 1.0
    assert 0.5 < metrics["loss"] < 10.0


def test_evaluate_deterministic(trained):
    ds = datasets.CIFARSynthetic(seed=2)
    a = trained.evaluate(ds, n_steps=2)
    b = trained.evaluate(ds, n_steps=2)
    assert a == b


def test_evaluate_matches_per_step_path(trained):
    """The scanned fast path and the host-loop fallback must agree."""
    ds = datasets.CIFARSynthetic(seed=3)
    scanned = trained.evaluate(ds, n_steps=2)

    class HostFed:
        def batch_at(self, step, bs):
            return ds.batch_at(step, bs)

    host = trained.evaluate(HostFed(), n_steps=2)
    for k in scanned:
        assert abs(scanned[k] - host[k]) < 1e-4


def test_evaluate_does_not_touch_state(trained):
    before = jax.tree.map(
        lambda a: jax.device_get(a).copy(), trained.state.model_state
    )
    trained.evaluate(datasets.CIFARSynthetic(seed=4), n_steps=1)
    after = jax.tree.map(lambda a: jax.device_get(a), trained.state.model_state)
    for x, y in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        assert jnp.array_equal(x, y)


def test_eval_forward_uses_inference_mode(trained):
    """BatchNorm must run on stored stats: a constant batch through the
    eval path must produce identical logits regardless of batch
    statistics (train mode would normalize by the batch itself)."""
    model_cfg = resnet.ResNetConfig(depth=18)
    x = jnp.ones((4, 32, 32, 3), jnp.float32)
    params = jax.device_get(trained.state.params)
    ms = jax.device_get(trained.state.model_state)
    train_logits, _ = resnet.apply_resnet(params, ms, x, model_cfg, train=True)
    eval_logits, _ = resnet.apply_resnet(params, ms, x, model_cfg, train=False)
    # A constant batch has zero variance: train-mode BN output differs
    # from stored-stats BN output unless the stats happen to match.
    assert not jnp.allclose(train_logits, eval_logits)


def test_fit_with_eval_dataset_records_curve(tmp_path):
    """fit(eval_dataset=...) runs a held-out pass after every epoch and
    appends 'eval' records to the metrics JSONL -- the convergence-run
    evidence format (train AND eval loss from one call)."""
    import json

    from tpu_hpc.config import TrainingConfig
    from tpu_hpc.parallel import dp
    from tpu_hpc.runtime import MeshSpec, build_mesh
    from tpu_hpc.train import Trainer

    metrics = tmp_path / "m.jsonl"
    cfg = TrainingConfig(
        epochs=2, steps_per_epoch=2, global_batch_size=8,
        metrics_path=str(metrics),
    )
    mesh = build_mesh(MeshSpec(axes={"data": -1}))
    model_cfg = resnet.ResNetConfig(depth=18)
    params, ms = resnet.init_resnet(jax.random.key(0), model_cfg)
    tr = Trainer(
        cfg, mesh, resnet.make_forward(model_cfg), params, ms,
        param_pspecs=dp.param_pspecs(params),
        eval_forward=resnet.make_eval_forward(model_cfg),
    )
    tr.fit(
        datasets.CIFARSynthetic(),
        eval_dataset=datasets.CIFARSynthetic(seed=1), eval_steps=1,
    )
    recs = [json.loads(l) for l in metrics.read_text().splitlines()]
    evals = [r for r in recs if r["event"] == "eval"]
    epochs = [r for r in recs if r["event"] == "epoch"]
    assert len(epochs) == 2
    assert len(evals) == 2  # one per epoch
    for r in evals:
        assert "loss" in r and "accuracy" in r
