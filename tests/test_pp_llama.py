"""Llama-2 through the pipeline engine: oracle correctness.

The sequential oracle for every pipelined Llama run is
``llama2.apply_llama`` on the SAME parameter values (merge_params is
the exact inverse of split_params), mirroring the role the reference's
full-model-on-every-rank construction plays for its schedules
(scripts/04_pipeline_parallel_pp/03_pipeline_training.py:166-171).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_hpc.models import llama2, llama_pp
from tpu_hpc.models.losses import cross_entropy
from tpu_hpc.parallel import pp
from tpu_hpc.runtime import MeshSpec, build_mesh

CFG = llama2.LlamaConfig(
    dim=64, n_layers=4, n_heads=4, vocab_size=97,
    multiple_of=32, max_seq_len=16, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return llama2.init_llama(jax.random.key(0), CFG)


@pytest.fixture(scope="module")
def tokens():
    k = jax.random.key(1)
    toks = jax.random.randint(
        k, (4, CFG.max_seq_len + 1), 0, CFG.vocab_size, dtype=jnp.int32
    )
    return toks[:, :-1], toks[:, 1:]


def test_split_merge_roundtrip(params):
    split = llama_pp.split_params(params, CFG, n_stages=4)
    merged = llama_pp.merge_params(split, CFG)
    assert jax.tree.structure(merged) == jax.tree.structure(params)
    for a, b in zip(jax.tree.leaves(merged), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_split_rejects_indivisible(params):
    with pytest.raises(ValueError, match="divisible"):
        llama_pp.split_params(params, CFG, n_stages=3)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_forward_matches_sequential_oracle(params, tokens, schedule):
    inputs, _ = tokens
    S, M = 4, 4
    mesh = build_mesh(
        MeshSpec(axes={"pipe": S}), devices=jax.devices()[:S]
    )
    split = llama_pp.split_params(params, CFG, n_stages=S)
    pipe = pp.pipelined(
        llama_pp.make_stage_fn(CFG, S), mesh, axis="pipe",
        schedule=schedule, batch_spec=P(),
    )

    def pipelined_logits(split_tree):
        xs = llama_pp.embed(
            split_tree["edges"], pp.microbatch(inputs, M), CFG
        )
        ys = pipe(split_tree["stages"], xs)
        return pp.unmicrobatch(
            llama_pp.head(split_tree["edges"], ys, CFG)
        )

    got = jax.jit(pipelined_logits)(split)
    want = llama2.apply_llama(params, inputs, CFG)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize(
    "schedule,backward",
    [("gpipe", "remat"), ("1f1b", "remat"), ("1f1b", "stash")],
)
def test_grads_match_sequential_oracle(params, tokens, schedule, backward):
    inputs, targets = tokens
    S, M = 4, 4
    mesh = build_mesh(
        MeshSpec(axes={"pipe": S}), devices=jax.devices()[:S]
    )
    split = llama_pp.split_params(params, CFG, n_stages=S)
    forward = llama_pp.make_forward(
        CFG, mesh, n_microbatches=M, schedule=schedule,
        backward=backward,
    )

    def pp_loss(split_tree):
        loss, _, _ = forward(split_tree, {}, (inputs, targets), None)
        return loss

    def oracle_loss(p):
        return cross_entropy(llama2.apply_llama(p, inputs, CFG), targets)

    loss_pp, grads_pp = jax.jit(jax.value_and_grad(pp_loss))(split)
    loss_or, grads_or = jax.jit(jax.value_and_grad(oracle_loss))(params)
    np.testing.assert_allclose(
        float(loss_pp), float(loss_or), rtol=1e-5, atol=1e-6
    )
    merged = llama_pp.merge_params(grads_pp, CFG)
    flat_pp = jax.tree.flatten_with_path(merged)[0]
    flat_or = dict(
        (jax.tree_util.keystr(k), v)
        for k, v in jax.tree.flatten_with_path(grads_or)[0]
    )
    assert len(flat_pp) == len(flat_or)
    for k, g in flat_pp:
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(flat_or[jax.tree_util.keystr(k)]),
            rtol=5e-4, atol=5e-5,
            err_msg=f"grad mismatch at {jax.tree_util.keystr(k)}",
        )


def test_pp_dp_composition_trains(params, tokens):
    """PP x DP: microbatch rows sharded over data, stages over pipe --
    one Trainer step runs and the loss matches the single-axis layout."""
    from tpu_hpc.config import TrainingConfig
    from tpu_hpc.models import datasets
    from tpu_hpc.train import Trainer

    S, M = 4, 4
    mesh = build_mesh(MeshSpec(axes={"data": 2, "pipe": S}))
    split = llama_pp.split_params(params, CFG, n_stages=S)
    forward = llama_pp.make_forward(
        CFG, mesh, n_microbatches=M, schedule="1f1b",
        batch_spec=P(None, "data"),
    )
    cfg = TrainingConfig(
        global_batch_size=8, steps_per_epoch=1, epochs=1,
        learning_rate=1e-3,
    )
    trainer = Trainer(
        cfg, mesh, forward, split,
        param_pspecs=llama_pp.pp_pspecs(split),
        batch_pspec=P(),
    )
    ds = datasets.TokenStream(
        vocab_size=CFG.vocab_size, seq_len=CFG.max_seq_len
    )
    metrics = trainer.train_step(ds.batch_at(0, 8))
    loss = float(jax.device_get(metrics["loss"]))
    assert np.isfinite(loss)


def test_flash_in_stage_matches_oracle(params, tokens):
    """The bench's flash-in-stage composition (blockwise_attention
    called batch-locally inside pp's shard_map; XLA fallback on CPU)
    must match the oracle like the plain path does."""
    from tpu_hpc.kernels.attention import blockwise_attention

    inputs, targets = tokens
    S, M = 4, 4
    mesh = build_mesh(
        MeshSpec(axes={"pipe": S}), devices=jax.devices()[:S]
    )
    split = llama_pp.split_params(params, CFG, n_stages=S)

    def attn_fn(q, k, v):
        out, _ = blockwise_attention(q, k, v, causal=True)
        return out

    forward = llama_pp.make_forward(
        CFG, mesh, n_microbatches=M, schedule="1f1b", attn_fn=attn_fn,
    )
    loss, _, _ = jax.jit(
        lambda t: forward(t, {}, (inputs, targets), None)
    )(split)
    want = cross_entropy(llama2.apply_llama(params, inputs, CFG), targets)
    np.testing.assert_allclose(float(loss), float(want), rtol=2e-4)


@pytest.mark.parametrize("schedule", ["interleaved", "interleaved-1f1b"])
def test_interleaved_matches_sequential_oracle(params, tokens, schedule):
    """Virtual-chunk Llama (v=2 chunks per device, round-robin global
    stages): the Megatron placement must still equal apply_llama on
    the merged values."""
    inputs, targets = tokens
    S, V, M = 2, 2, 4
    mesh = build_mesh(
        MeshSpec(axes={"pipe": S}), devices=jax.devices()[:S]
    )
    split = llama_pp.split_params_interleaved(params, CFG, S, V)
    # Round-trip sanity: the interleaved layout merges back exactly.
    merged = llama_pp.merge_params_interleaved(split, CFG, S, V)
    for a, b in zip(jax.tree.leaves(merged), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    forward = llama_pp.make_forward(
        CFG, mesh, n_microbatches=M, schedule=schedule, n_chunks=V,
    )

    def pp_loss(tree):
        loss, _, _ = forward(tree, {}, (inputs, targets), None)
        return loss

    def oracle_loss(p):
        return cross_entropy(llama2.apply_llama(p, inputs, CFG), targets)

    loss_pp, grads_pp = jax.jit(jax.value_and_grad(pp_loss))(split)
    loss_or = jax.jit(oracle_loss)(params)
    np.testing.assert_allclose(
        float(loss_pp), float(loss_or), rtol=1e-5, atol=1e-6
    )
    if schedule == "interleaved-1f1b":
        grads_or = jax.jit(jax.grad(oracle_loss))(params)
        gm = llama_pp.merge_params_interleaved(grads_pp, CFG, S, V)
        for (kp, g), (_, w) in zip(
            jax.tree.flatten_with_path(gm)[0],
            jax.tree.flatten_with_path(grads_or)[0],
        ):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), rtol=5e-4, atol=5e-5,
                err_msg=f"grad mismatch at {jax.tree_util.keystr(kp)}",
            )


def test_dp_checkpoint_restores_into_pp_layout(params, tokens, tmp_path):
    """The production retrain-under-PP scenario: a checkpoint saved
    from an unpipelined (DP) run restores bit-exact, re-splits into
    the stage-stacked layout, and the pipelined forward on it equals
    the original model's forward."""
    from tpu_hpc.ckpt import CheckpointManager

    inputs, _ = tokens
    mgr = CheckpointManager(str(tmp_path / "ck"), async_save=False)
    mgr.save({"params": params}, step=1, force=True)
    mgr.wait()
    restored = mgr.restore(1, {"params": params})["params"]

    S, M = 4, 4
    mesh = build_mesh(
        MeshSpec(axes={"pipe": S}), devices=jax.devices()[:S]
    )
    split = llama_pp.split_params(restored, CFG, n_stages=S)
    # Place on the pipe mesh (edges replicated, stages stage-sharded)
    # -- the restore-then-shard step a real PP retrain performs, now
    # through the general reshard engine (one planned move for the
    # whole tree instead of a device_put per leaf).
    from tpu_hpc import reshard
    from tpu_hpc.parallel.plans import shardings_for

    split = reshard.apply(
        split, shardings_for(mesh, llama_pp.pp_pspecs(split)),
        label="dp_ckpt_to_pp",
    )
    pipe = pp.pipelined(
        llama_pp.make_stage_fn(CFG, S), mesh, axis="pipe",
        schedule="1f1b", batch_spec=P(),
    )

    def logits_fn(tree):
        xs = llama_pp.embed(
            tree["edges"], pp.microbatch(inputs, M), CFG
        )
        return pp.unmicrobatch(
            llama_pp.head(tree["edges"], pipe(tree["stages"], xs), CFG)
        )

    got = jax.jit(logits_fn)(split)
    want = llama2.apply_llama(params, inputs, CFG)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )
